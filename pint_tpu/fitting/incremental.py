"""Fused incremental WLS refit: rank-k Gram updates over cached fit state.

Production traffic (ROADMAP item 3) is not cold fits — it is a session
appending a handful of TOAs to an already-converged solution. The
damped loop's judged object is the weighted Gauss-Newton system, and at
a converged point the old data is fully summarized by three cached
quantities: the (column-normalized) Cholesky factor ``L`` of the Gram
matrix, the converged chi2, and the absorbed weighted-mean phase
offset. An append of ``k`` TOAs then never has to touch the old table:

* the old rows' chi2 as a function of a parameter move ``u`` from the
  converged point is the quadratic ``chi2_0 + ||L^T D u||^2`` (``D`` =
  the cached column norms; the gradient is ~0 at convergence — what
  "converged" means);
* the k new rows are evaluated EXACTLY (phase + jacfwd over the tiny
  append bucket — :func:`pint_tpu.bucketing.append_bucket_size` pads
  them with standard zero-weight rows so every append size shares one
  compiled program);
* the combined Gauss-Newton factor is the **rank-k Cholesky update**
  ``L' L'^T = L L^T + A_k^T W A_k``, computed as the R factor of a QR
  over ``[L^T; sqrt(W) A_k]`` (the numerically-stable classic form —
  O((q+k) q^2) instead of the O(n q^2) full re-reduction);
* the whole accept/halve/converge walk runs through the SAME fused
  damped loop as a cold fit (``fitting.device_loop.dispatch_damped``):
  warm-started at ``u = 0`` (the cached solution), flight recorder
  riding the carry, ONE launch and ONE fetch per update.

The updated factor of the last *adopted* evaluation rides the loop's
``info`` carry, so the session layer (pint_tpu.serve.session) commits
the refreshed state from the same single fetch. Exactness: for a linear
model this is recursive least squares (exact); the nonlinear phase
model makes the quadratic summary drift as parameters move, which is
why the session layer pins correctness with a chi2-drift gate against
periodic full refits (see docs/ARCHITECTURE.md "Sessionful serving").

The state vector ``u`` is a flat (q,) array over [Offset?] + free
params: the implicit phase-offset column of the WLS step is an explicit
coordinate here (the old fit's mean subtraction profiled it out; the
incremental objective keeps its correlations through the cached Gram)
and its solved value folds back into the cached mean at commit time.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry

Array = jax.Array

#: state-dict leaves cached per session (device arrays; see snapshot_state)
STATE_FIELDS = ("L", "norm", "mu", "chi2")


def rank_k_chol_update(L: Array, Aw: Array) -> Array:
    """Lower Cholesky factor of ``L L^T + Aw^T Aw`` via QR.

    ``Aw`` is (k, q) — the k update rows already weighted (each row
    ``sqrt(w_i) a_i``). The R factor of ``qr([L^T; Aw])`` satisfies
    ``R^T R = L L^T + Aw^T Aw`` by construction; a sign fix makes the
    diagonal positive so the result is a true Cholesky factor. This is
    the standard stable rank-k update (no downdates here — appended
    rows only ever ADD information).
    """
    R = jnp.linalg.qr(jnp.concatenate([L.T, Aw], axis=0), mode="r")
    s = jnp.sign(jnp.diagonal(R))
    s = jnp.where(s == 0.0, 1.0, s)
    return (R * s[:, None]).T


def _state_names(model, params=None) -> tuple[list[str], int]:
    """(free-param order, offset-coordinate count) of the state vector."""
    names = list(params) if params is not None else list(model.free_params)
    off = 0 if model.has_component("PhaseOffset") else 1
    return names, off


def make_incr_rows(model, params=None):
    """Build ``rows(base, deltas_dict, toas) -> (M, resid_turns, w)``.

    The append-row evaluator shared by the incremental step, probe and
    gram snapshot: design matrix M (n, q) in the WLS step's exact
    column convention ([ones/f0?] + [-J/f0]), RAW anchored residual
    turns (no mean subtraction — the caller centers on the cached
    mean), and the EFAC/EQUAD weights. The model must carry a TZR
    anchor (the session layer routes anchorless models to full refits:
    a wrapped anchorless residual has an arbitrary per-evaluation
    offset that cannot be compared against a cached mean).
    """
    tzr = model.get_tzr_toas()
    if tzr is None:
        raise ValueError("incremental refit requires a TZR-anchored "
                         "model (no AbsPhase: use a full refit)")
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=True)
    names, off = _state_names(model, params)

    def rows(base, deltas, toas):
        f0 = base["F0"].hi + base["F0"].lo

        def total_phase(d):
            ph = phase_fn(base, d, toas)
            return (ph.int_part + (ph.frac.hi + ph.frac.lo),
                    ph.frac.hi + ph.frac.lo)

        err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)
        J, resid_turns = jax.jacfwd(total_phase, has_aux=True)(deltas)
        r = resid_turns
        cols = [] if not off else [jnp.ones_like(r)]
        for k in names:
            cols.append(-J[k])
        M = jnp.stack(cols, axis=1) / f0
        return M, r, w

    return rows


def make_incr_step(model, params=None):
    """Build the fused incremental full step ``full(u, operands)``.

    ``operands = (base, toas_k, state)`` with ``state`` the cached
    session dict (:data:`STATE_FIELDS`). One evaluation: append rows at
    the trial point, rank-k factor update, Gauss-Newton re-solve
    against [cached quadratic + exact new rows], same ``(new_u, info)``
    contract as the WLS step so :func:`pint_tpu.fitting.device_loop
    .build_damped_loop` drives it unchanged. ``info`` additionally
    carries ``L`` — the UPDATED factor at this evaluation's point —
    which the loop's adopt-select keeps at the last accepted point, so
    the refreshed session state arrives in the fit's single fetch.
    """
    rows = make_incr_rows(model, params)
    names, off = _state_names(model, params)

    def full(u, ops):
        base, toas_k, state = ops
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: u[off + i] for i, k in enumerate(names)}
        M, resid_turns, w = rows(base, d, toas_k)
        # center on the cached absorbed mean [turns]; the offset state
        # coordinate u[0] (turns) applies linearly — named params are
        # already exact in resid_turns via the phase evaluation
        rc = resid_turns - state["mu"]
        if off:
            rc = rc - u[0]
        r_eff = rc / f0
        norm = state["norm"]
        A = M / norm
        un = norm * u
        Lu = state["L"].T @ un
        quad = jnp.sum(jnp.square(Lu))
        chi2_new = jnp.sum(jnp.square(r_eff) * w)
        chi2_in = state["chi2"] + quad + chi2_new
        # rank-k update of the normalized Gram factor, then the GN
        # normal equations in normalized coordinates:
        #   (G + A^T W A) v = A^T W r_eff - G u      (all normalized)
        L_new = rank_k_chol_update(state["L"], A * jnp.sqrt(w)[:, None])
        g = A.T @ (r_eff * w) - state["L"] @ Lu
        vn = jax.scipy.linalg.cho_solve((L_new, True), g)
        cov = jax.scipy.linalg.cho_solve((L_new, True),
                                         jnp.eye(norm.shape[0]))
        new_u = u + vn / norm
        sig = jnp.sqrt(jnp.diagonal(cov)) / norm
        errors = {k: sig[off + i] for i, k in enumerate(names)}
        # the REPLACEMENT session state rides info (adopt-selected by
        # the loop, so the fetched value is the last accepted point's):
        # updated factor, folded-in offset, pass-through norms. It must
        # be computed IN-program — the input state buffers are donated
        # on accelerators, so nothing may touch them after dispatch.
        mu_new = state["mu"] + u[0] if off else state["mu"]
        return new_u, {"chi2": chi2_in - vn @ g, "errors": errors,
                       "chi2_at_input": chi2_in, "L": L_new,
                       "mu": mu_new, "norm": norm}

    return full


def make_incr_probe(model, params=None):
    """Residual-only judge ``probe(u, operands) -> chi2`` — one phase
    pass over the append bucket plus the cached quadratic; computes
    exactly the step's ``chi2_at_input`` expression (no jacfwd, no
    factor update), the fused loop's cheap halved-trial evaluator."""
    tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=True)
    names, off = _state_names(model, params)

    def probe(u, ops):
        base, toas_k, state = ops
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: u[off + i] for i, k in enumerate(names)}
        ph = phase_fn(base, d, toas_k)
        err = model.scaled_toa_uncertainty(toas_k)
        w = 1.0 / jnp.square(err)
        rc = (ph.frac.hi + ph.frac.lo) - state["mu"]
        if off:
            rc = rc - u[0]
        r_eff = rc / f0
        un = state["norm"] * u
        quad = jnp.sum(jnp.square(state["L"].T @ un))
        return state["chi2"] + quad + jnp.sum(jnp.square(r_eff) * w)

    return probe


def make_gram_snapshot(model, params=None):
    """Build ``snapshot(base, toas) -> state`` — the cached-state
    factory: one O(n q) pass over the FULL table at the model's current
    values (deltas = 0, i.e. immediately after a converged fit wrote
    back), producing the column norms, the normalized Gram's Cholesky
    factor (same Tikhonov floor as ``wls_solve_gram``), the absorbed
    weighted-mean offset [turns] and the converged chi2. Jitted per
    model structure via :func:`jitted_gram_snapshot`."""
    rows = make_incr_rows(model, params)
    names, off = _state_names(model, params)

    def snapshot(base, toas):
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: jnp.zeros((), jnp.float64) for k in names}
        M, resid_turns, w = rows(base, d, toas)
        if off:
            mu = jnp.sum(resid_turns * w) / jnp.sum(w)
        else:
            mu = jnp.zeros((), jnp.float64)
        r = (resid_turns - mu) / f0
        norm = jnp.sqrt(jnp.sum(jnp.square(M) * w[:, None], axis=0))
        norm = jnp.where(norm == 0.0, 1.0, norm)
        A = M / norm
        G = A.T @ (A * w[:, None])
        G = G + jnp.eye(G.shape[0]) * (jnp.finfo(jnp.float64).eps
                                       * jnp.trace(G))
        L = jnp.linalg.cholesky(G)
        chi2 = jnp.sum(jnp.square(r) * w)
        return {"L": L, "norm": norm, "mu": mu, "chi2": chi2}

    return snapshot


def jitted_incr_step(model, params: tuple):
    """Model-cache-shared :func:`make_incr_step` (the ``jitted_wls_step``
    convention: one traced program per structure, values through the
    traced ``base``; uncounted — traced into the fused loop)."""
    return model._cached_jit(("incr_step", tuple(params)),
                             lambda owner: make_incr_step(owner, params))


def jitted_incr_probe(model, params: tuple):
    """Model-cache-shared :func:`make_incr_probe`."""
    return model._cached_jit(("incr_probe", tuple(params)),
                             lambda owner: make_incr_probe(owner, params))


def jitted_gram_snapshot(model, params: tuple):
    """Model-cache-shared, jitted :func:`make_gram_snapshot`."""
    return model._cached_jit(
        ("incr_snapshot", tuple(params)),
        lambda owner: jax.jit(make_gram_snapshot(owner, params)))


def snapshot_state(model, toas) -> dict:
    """Compute + fetch-free cached state over the (bucketed) full table.

    Returns the device-array state dict (leaves stay on device — they
    are the session cache's donated working set) plus host metadata the
    session layer needs (``names``/``off``/``q``). One program launch;
    accounted as ``incr_snapshot`` in the program-reuse counters.
    """
    from pint_tpu import bucketing

    names, off = _state_names(model)
    toas_b = bucketing.bucket_toas(toas)
    snap = jitted_gram_snapshot(model, tuple(names))
    bucketing.note_program("incr_snapshot",
                           hash(model._fn_fingerprint()),
                           bucketing.toa_shape(toas_b))
    with telemetry.jit_span("incr.snapshot"):
        state = snap(model.base_dd(), toas_b)
    q = len(names) + off
    return {"state": state, "names": names, "off": off, "q": q,
            "bytes": state_bytes(state)}


def state_bytes(state: dict) -> int:
    """Device bytes of one session's cached state."""
    return int(sum(np.dtype(np.float64).itemsize * int(np.prod(np.shape(x)))
                   for x in jax.tree.leaves(state)))


class InFlightIncrUpdate:
    """A dispatched incremental update; one fetch, state kept on-device.

    Wraps the loop's :class:`pint_tpu.fitting.device_loop.InFlightFit`:
    before the host fetch, the replacement session state — the rank-k
    updated factor, folded mean, pass-through norms and the kept-point
    chi2, all adopt-selected inside the program — is captured as DEVICE
    arrays (:attr:`new_state`), so the session cache's working set
    never round-trips through the host between appends.
    """

    __slots__ = ("_inner", "_new_state", "_result")

    def __init__(self, inner):
        self._inner = inner
        self._new_state = None
        self._result = None

    def ready(self) -> bool:
        return self._inner.ready()

    def fetch(self):
        """The update's single device->host sync; idempotent."""
        if self._result is None:
            out = self._inner._out  # (deltas, info, chi2, conv, cnt, tr)
            if out is not None:
                info_dev = out[1]
                self._new_state = {
                    "L": info_dev["L"], "norm": info_dev["norm"],
                    "mu": info_dev["mu"],
                    "chi2": info_dev["chi2_at_input"]}
            self._result = self._inner.fetch()
        return self._result

    @property
    def new_state(self) -> dict:
        """Replacement cached state (device arrays); fetch() first."""
        if self._result is None:
            raise RuntimeError("fetch() the update before reading state")
        return self._new_state


class InFlightIncrBatch:
    """A dispatched MULTI-session incremental update: one vmapped launch.

    The fleet-scale flavor of :class:`InFlightIncrUpdate` (ISSUE 20): N
    same-structure sessions' append buckets ride a stacked member axis
    through ONE free-running batched damped loop
    (:func:`pint_tpu.fitting.device_loop.dispatch_damped_batched`), so N
    sessions cost one launch and one fetch instead of N. Per-member
    replacement states are captured as DEVICE-array slices of the
    batched info carry before the host fetch — each session's cache
    commit stays host-round-trip-free, exactly like the solo path.
    Unlike the solo path the stacked operands are FRESH buffers
    (``jnp.stack`` copies), so the member states are never donated and
    stay valid if the launch fails.
    """

    __slots__ = ("_inner", "_n_real", "_new_states", "_result")

    def __init__(self, inner, n_real: int):
        self._inner = inner
        self._n_real = n_real
        self._new_states = None
        self._result = None

    def ready(self) -> bool:
        return self._inner.ready()

    def fetch(self):
        """The batch's single device->host sync; idempotent."""
        if self._result is None:
            out = self._inner._inner._out
            if out is not None:
                info_dev = out[1]
                self._new_states = [
                    {"L": info_dev["L"][m], "norm": info_dev["norm"][m],
                     "mu": info_dev["mu"][m],
                     "chi2": info_dev["chi2_at_input"][m]}
                    for m in range(self._n_real)]
            self._result = self._inner.fetch()
        return self._result

    def new_state(self, m: int) -> dict:
        """Member ``m``'s replacement cached state; fetch() first."""
        if self._result is None:
            raise RuntimeError("fetch() the batch before reading state")
        return self._new_states[m]


def dispatch_incremental_batch(members, *, maxiter=20,
                               min_chi2_decrease=1e-3,
                               max_step_halvings=8):
    """Enqueue ONE vmapped rank-k launch over many sessions' appends.

    ``members`` is ``[(model, toas_append, state), ...]`` — every member
    must share one structure fingerprint (which pins the frozen and
    unfittable parameter values, TZR anchor included — see
    ``TimingModel._fn_fingerprint``), one free-parameter set and one
    append bucket; equal fingerprints are exactly what makes the plain
    ``jax.vmap`` of the scalar step/probe closures correct: every
    member evaluates the same compiled phase program, per-member values
    riding the stacked traced ``base``. The member axis pads to the
    pow-2 width (:func:`pint_tpu.bucketing.member_bucket_size`,
    replicating member 0 — inert: dummy results are never read) so
    nearby batch sizes share one compiled program. Returns an
    :class:`InFlightIncrBatch`.
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting import device_loop
    from pint_tpu.parallel.batch import stack_toas

    lead = members[0][0]
    names, off = _state_names(lead)
    names = tuple(names)
    step = jitted_incr_step(lead, names)
    probe = jitted_incr_probe(lead, names)
    k_target = bucketing.append_bucket_size(
        max(len(t) for _m, t, _s in members))
    n_real = len(members)
    b_target = bucketing.member_bucket_size(n_real)
    rows = list(members) + [members[0]] * (b_target - n_real)
    base = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]),
                        *[m.base_dd() for m, _t, _s in rows])
    toas_k = stack_toas([t for _m, t, _s in rows], k_target)
    state = jax.tree.map(lambda *xs: jnp.stack(xs),
                         *[s for _m, _t, s in rows])
    u0 = jnp.zeros((b_target, len(names) + off), jnp.float64)
    telemetry.inc("fit.incremental.batch_dispatched")
    telemetry.inc("fit.incremental.batch_members", n_real)
    return InFlightIncrBatch(device_loop.dispatch_damped_batched(
        jax.vmap(lambda u, ops: step(u, ops), in_axes=(0, 0)), u0,
        (base, toas_k, state),
        probe=jax.vmap(lambda u, ops: probe(u, ops), in_axes=(0, 0)),
        key=("incr_batch", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings,
        kind="device_loop_incr_batch",
        fingerprint=(hash(lead._fn_fingerprint()), names, b_target),
        shape=(b_target, k_target, len(names) + off)), n_real)


def dispatch_incremental(model, toas_append, state, *, names, maxiter=20,
                         min_chi2_decrease=1e-3, max_step_halvings=8):
    """Enqueue one fused incremental update; returns the
    :class:`pint_tpu.fitting.device_loop.InFlightFit` handle.

    ONE launch: append-bucket padding is host-side numpy-free
    (``bucketing.pad_toas``), the loop program is the same damped state
    machine every cold fit runs (flight recorder and counters
    included), and ``handle.fetch()`` is the update's single
    device->host sync carrying the solution, uncertainties, the
    rank-k-updated factor and the trace. The cached-state operand is
    DONATED on accelerator backends (the update replaces it; XLA:CPU
    has no input aliasing and skips donation — the PR-2 rule).
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting import device_loop

    names = tuple(names)
    _names, off = _state_names(model, names)
    step = jitted_incr_step(model, names)
    probe = jitted_incr_probe(model, names)
    k_target = bucketing.append_bucket_size(len(toas_append))
    toas_k = bucketing.pad_toas(toas_append, k_target) \
        if k_target != len(toas_append) else toas_append
    if device_loop._donate_operands():
        # donation consumes EVERY operand buffer. The cached state is
        # replaced (that is the point) and base_dd is rebuilt per call,
        # but an exact-bucket append passes the caller's own table —
        # whose buffers the session keeps alive in entry.pending for
        # the next full refit — so donate a private copy instead
        # (O(append bucket) bytes; accelerator backends only)
        toas_k = jax.tree.map(jnp.array, toas_k)
    u0 = jnp.zeros(len(names) + off, jnp.float64)
    telemetry.inc("fit.incremental.dispatched")
    return InFlightIncrUpdate(device_loop.dispatch_damped(
        lambda u, ops: step(u, ops), u0,
        (model.base_dd(), toas_k, state),
        probe=lambda u, ops: probe(u, ops),
        key=("incr", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind="device_loop_incr",
        fingerprint=(hash(model._fn_fingerprint()), names),
        shape=(k_target, len(names) + off), donate_state=True))
