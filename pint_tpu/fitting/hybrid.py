"""Hybrid GLS fit: CPU-exact DD phase -> accelerator linear algebra.

Why this exists (measured, not assumed): ``dd.self_check`` is **False**
on the TPU backend (BENCH record) — the error-free transforms
(TwoSum/TwoProd) underlying double-double arithmetic do not hold under
the TPU's emulated float64, so the phase/residual pipeline computed
there is garbage (NaN chi2). The split promised by ``pint_tpu.ops.dd``:

* **stage 1 (CPU)** — everything DD-graded: the composed phase
  function, residual wrap, weighted-mean subtraction, and the jacfwd
  design matrix. Output is plain float64 ``(M, r, sigma, t_s)`` —
  nanosecond information now lives in *residuals* (small numbers), so
  f64 suffices downstream.
* **stage 2 (accelerator)** — the O(n (p+k)^2) extended-normal-equation
  GLS solve with in-jit Fourier bases and segment-sum ECORR
  (:func:`pint_tpu.fitting.gls_step.gls_solve_seg`) — where the FLOPs
  are, and plain f64 linear algebra the TPU executes correctly.

Transfer cost is O(n (p + 2)) floats per iteration (the Fourier basis
is rebuilt on-device from ``t_s``, never shipped).

Reference: src/pint/fitter.py :: GLSFitter (SURVEY §3.3) — upstream has
no split because longdouble numpy only ever runs on the host CPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import SECS_PER_DAY
from pint_tpu.fitting.fitter import Fitter
from pint_tpu.fitting.gls_step import (NoiseStatics, PLSpec,
                                       build_noise_statics, fourier_design,
                                       gls_finalize_seg, gls_gram_whitened,
                                       powerlaw_phi)

Array = jax.Array


def cpu_device():
    """The IEEE-exact float64 device DD arithmetic requires.

    (pint_tpu.ops.dd docstring contract; the round-1 review flagged this
    helper as promised-but-missing.)
    """
    return jax.devices("cpu")[0]


def accelerator_device():
    """First non-CPU device, or the CPU if none is attached."""
    for d in jax.devices():
        if d.platform != "cpu":
            return d
    return cpu_device()


def _accel_pl_bases(t_s, inv_f2, specs: tuple[PLSpec, ...], pl_params):
    """pl_bases rebuilt from plain arrays (accelerator side)."""
    if not specs:
        return None, None
    blocks, phis = [], []
    for i, spec in enumerate(specs):
        F, f, df = fourier_design(t_s, spec.nharm)
        if spec.scale == "dm":
            F = F * inv_f2[:, None]
        blocks.append(F)
        phis.append(jnp.repeat(
            powerlaw_phi(f, pl_params[i, 0], pl_params[i, 1], df), 2))
    return jnp.concatenate(blocks, axis=1), jnp.concatenate(phis)


class HybridGLSFitter(Fitter):
    """GLSFitter semantics with the CPU/accelerator split.

    On an all-CPU host both stages land on the CPU and results match
    ``GLSFitter``/``ShardedGLSFitter`` to float64 round-off (tested);
    on a TPU host stage 2 runs on the chip while every DD operation
    stays on the (exact) CPU backend.
    """

    def __init__(self, toas, model, *, accel=None):
        super().__init__(toas, model)
        self.cpu = cpu_device()
        self.accel = accel if accel is not None else accelerator_device()
        self.noise, self.pl_specs = build_noise_statics(model, toas)

        names = model.free_params
        self._names = names
        tzr = model.get_tzr_toas()
        phase_fn = model.phase_fn_toas(tzr=tzr)
        toas_cpu = jax.device_put(toas, self.cpu)

        def stage1(base, deltas):
            f0 = base["F0"].hi + base["F0"].lo

            def total_phase(d):
                ph = phase_fn(base, d, toas_cpu)
                return ph.int_part + (ph.frac.hi + ph.frac.lo)

            err = model.scaled_toa_uncertainty(toas_cpu)
            w = 1.0 / jnp.square(err)
            sw = jnp.sqrt(w)
            ph = phase_fn(base, deltas, toas_cpu)
            resid = ph.frac.hi + ph.frac.lo
            resid = resid - jnp.sum(resid * w) / jnp.sum(w)
            r = resid / f0
            J = jax.jacfwd(total_phase)(deltas)
            cols = [jnp.ones_like(r) / f0] + [-J[k] / f0 for k in names]
            M = jnp.stack(cols, axis=1)
            # whiten + unit-normalize columns HERE: the accelerator's
            # emulated f64 has f32 dynamic range, and sum(M^2 w) on raw
            # spin-derivative columns overflows it (see gls_gram_whitened)
            Mw = M * sw[:, None]
            norm_M = jnp.sqrt(jnp.sum(jnp.square(Mw), axis=0))
            norm_M = jnp.where(norm_M == 0.0, 1.0, norm_M)
            A_M = Mw / norm_M
            rw = r * sw
            t_s = (toas_cpu.tdb.hi + toas_cpu.tdb.lo) * SECS_PER_DAY
            from pint_tpu.models.noise import DM_FREF_MHZ

            inv_f2 = jnp.square(DM_FREF_MHZ / toas_cpu.freq_mhz)
            return A_M, rw, sw, norm_M, t_s, inv_f2

        pl_specs = self.pl_specs
        n_params = len(names) + 1  # + offset column

        # on a real accelerator the O(n q^2) matmuls run as double-single
        # f32 on the MXU (emulated f64 matmul measured ~100x slower than
        # host CPU); the gradient and segment sums stay exact f64
        use_mxu = self.accel.platform != "cpu"

        def stage2_gram(A_M, rw, sw, norm_M, t_s, inv_f2, epoch_idx,
                        ecorr_phi, pl_params):
            F, phi_F = _accel_pl_bases(t_s, inv_f2, pl_specs, pl_params)
            return gls_gram_whitened(A_M, rw, sw, norm_M, F, phi_F,
                                     epoch_idx, ecorr_phi, mxu=use_mxu)

        self._stage1 = jax.jit(stage1)
        self._stage2_gram = jax.jit(stage2_gram)
        self._finalize = jax.jit(lambda parts: gls_finalize_seg(parts,
                                                                n_params))
        # the (q, q) Cholesky finalize runs on the CPU whenever the
        # accelerator is not one: beyond the chip's f64 emulation having
        # f32 *range*, the un-normalized covariance entries themselves
        # (e.g. var(F1) ~ 1e-40 s^-2 Hz^2) sit below the f32 floor, so
        # the finalize output cannot even be represented there. It is
        # O(q^3) — microseconds — next to the O(n q^2) on-chip Gram.
        self.finalize_device = (self.cpu if self.accel.platform != "cpu"
                                else self.accel)

    def _iterate(self, base, deltas) -> tuple[dict, dict]:
        s1 = self._stage1(base, deltas)
        noise = self.noise
        moved = [jax.device_put(x, self.accel) for x in s1] + [
            jax.device_put(noise.epoch_idx, self.accel),
            jax.device_put(noise.ecorr_phi, self.accel),
            jax.device_put(noise.pl_params, self.accel),
        ]
        parts = self._stage2_gram(*moved)
        if self.finalize_device is not self.accel:
            parts = {k: jax.device_put(v, self.finalize_device)
                     for k, v in parts.items()}
        sol = self._finalize(parts)
        x = np.asarray(sol["x"])
        new_deltas = {k: deltas[k] + x[i + 1]
                      for i, k in enumerate(self._names)}
        return new_deltas, sol

    def fit_toas(self, maxiter: int = 2, **kw) -> float:
        base = jax.device_put(self.model.base_dd(), self.cpu)
        deltas = {k: jnp.zeros((), jnp.float64) for k in self._names}
        sol = None
        for _ in range(max(1, maxiter)):
            deltas, sol = self._iterate(base, deltas)
        cov = np.asarray(sol["cov"])
        errors = np.sqrt(np.diagonal(cov))
        for i, k in enumerate(self._names):
            p = self.model[k]
            p.add_delta(float(np.asarray(deltas[k])))
            p.uncertainty = float(errors[i + 1])
        self.fit_params = list(self._names)
        self.parameter_covariance_matrix = cov
        self.resids = self._new_resids()
        self.converged = True
        return float(np.asarray(sol["chi2"]))
