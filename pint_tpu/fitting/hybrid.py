"""Hybrid GLS fit: CPU-exact DD phase -> accelerator linear algebra.

Why this exists (observed on hardware, not assumed): ``dd.self_check``
came back **False** on the TPU v5e backend in a round-2 session,
re-confirmed on hardware in round 4's brief live-tunnel window
(committed artifact pending — see ops/dd.py) — the error-free transforms
(TwoSum/TwoProd) underlying double-double arithmetic do not hold under
the TPU's emulated float64, so the phase/residual pipeline computed
there is garbage (NaN chi2). The split promised by ``pint_tpu.ops.dd``:

* **stage 1 (CPU)** — everything DD-graded: the composed phase
  function, residual wrap, weighted-mean subtraction, and the jacfwd
  design matrix. Output is plain float64 ``(M, r, sigma, t_s)`` —
  nanosecond information now lives in *residuals* (small numbers), so
  f64 suffices downstream.
* **stage 2 (accelerator)** — the O(n (p+k)^2) extended-normal-equation
  GLS solve with in-jit Fourier bases and segment-sum ECORR
  (:func:`pint_tpu.fitting.gls_step.gls_solve_seg`) — where the FLOPs
  are, and plain f64 linear algebra the TPU executes correctly.

Transfer cost is O(n (p + 2)) floats per iteration (the Fourier basis
is rebuilt on-device from ``t_s``, never shipped).

Reference: src/pint/fitter.py :: GLSFitter (SURVEY §3.3) — upstream has
no split because longdouble numpy only ever runs on the host CPU.
"""

from __future__ import annotations

import os

from pint_tpu import config

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import SECS_PER_DAY
from pint_tpu.fitting.fitter import Fitter
from pint_tpu.fitting.gls_step import (PLSpec, build_noise_statics,
                                       fourier_design, gls_gram_whitened,
                                       gls_solve_normalized,
                                       noise_marginal_chi2, powerlaw_phi)

Array = jax.Array


def cpu_device():
    """The IEEE-exact float64 device DD arithmetic requires.

    (pint_tpu.ops.dd docstring contract; the round-1 review flagged this
    helper as promised-but-missing.)
    """
    return jax.devices("cpu")[0]


def accelerator_device():
    """First non-CPU device, or the CPU if none is attached."""
    for d in jax.devices():
        if d.platform != "cpu":
            return d
    return cpu_device()


def accel_mxu_mode(dev):
    """Gram-arithmetic policy for an accelerator device (one place):
    ``False`` = exact f64 (CPU devices — fastest there, and the
    split-plumbing tests want exactness), ``"pallas"`` = hand-tiled
    double-single kernel (real TPUs), ``True`` = XLA ds32 (any other
    accelerator). Shared by ``HybridGLSFitter`` and ``PTAGLSFitter``.
    """
    if dev is None or dev.platform == "cpu":
        return False
    return "pallas" if dev.platform == "tpu" else True


def run_stage2_with_fallback(owner, key, run):
    """Shared pallas->ds32 fallback contract for the hybrid fitters.

    ``run(mode)`` executes the stage-2 program under gram mode ``mode``;
    ``owner`` holds the current mode in ``_mxu_mode`` and per-program
    success keys in ``_stage2_ok_keys`` (a set). A failure under
    ``"pallas"`` *before the first success of this program key* is
    treated as a Mosaic lowering/compile failure: the owner is switched
    to XLA ds32 (re-keying every later stage-2 build) and the call
    retried. A failure after that key has succeeded is a real runtime
    error and propagates. Keys give per-structure granularity: one
    pulsar's successful pallas compile must not disable the fallback
    for a differently-shaped pulsar (PTA heterogeneous structures).
    """
    mode = owner._mxu_mode
    try:
        out = run(mode)
    except Exception:  # noqa: BLE001 — lowering failure only (see above)
        if mode != "pallas" or key in owner._stage2_ok_keys:
            raise
        import logging

        logging.getLogger(__name__).warning(
            "pallas gram kernel failed to compile; "
            "falling back to XLA ds32")
        owner._mxu_mode = True
        out = run(True)
    owner._stage2_ok_keys.add(key)
    return out


def stage2_donate_argnums(dev) -> tuple:
    """``donate_argnums`` for a stage-2 program running on ``dev``.

    The packed stage-1 buffer (argument 0) is rebuilt and re-shipped
    every iteration and dead after the stage-2 call, so donating it lets
    XLA reuse its HBM for the Gram intermediates — O(n (p+2)) bytes off
    the per-iteration peak at no cost. Accelerators only: this jaxlib's
    XLA:CPU has no input-output aliasing (donation there just warns and
    no-ops), and a *compile*-time failure under the pallas->ds32
    fallback retries with the same buffer, which is safe because
    donation consumes the buffer only at execution.
    """
    return (0,) if dev is not None and dev.platform != "cpu" else ()


def ship_stage2_statics(toas, noise, dev):
    """Device-resident iteration-independent stage-2 inputs, shipped
    once: ``(epoch_idx, ecorr_phi, pl_params, t_s, inv_f2)`` — the
    positional argument contract of both hybrid stage-2 programs
    (``HybridGLSFitter`` and :func:`pint_tpu.parallel.pta
    .make_pta_stage2`). One definition so the argument order and the
    ``inv_f2`` convention cannot drift between the two consumers.
    """
    from pint_tpu.models.noise import DM_FREF_MHZ

    t_s = np.asarray(toas.tdb.hi + toas.tdb.lo) * SECS_PER_DAY
    inv_f2 = np.square(DM_FREF_MHZ / np.asarray(toas.freq_mhz))
    return tuple(jax.device_put(x, dev) for x in (
        noise.epoch_idx, noise.ecorr_phi, noise.pl_params,
        jnp.asarray(t_s), jnp.asarray(inv_f2)))


def make_whiten_stage1(model, tzr=None):
    """CPU stage-1 builder shared by the hybrid fitters: DD phase ->
    whitened, column-normalized design, packed flat.

    Everything DD-graded for one dataset — composed phase, residual
    wrap, weighted-mean subtraction, jacfwd design matrix (one primal
    pass serves both via ``has_aux``), whitening and unit column
    normalization — packed into a single flat f64 buffer
    ``[A_M.ravel() | rw | sw | norm_M]`` for one host->device transfer.
    ``toas`` is a traced argument, so all same-structure datasets (the
    68 PTA pulsars; repeated fitter constructions) share one compiled
    program via ``TimingModel._cached_jit``. Consumed by
    ``HybridGLSFitter`` and ``PTAGLSFitter``'s stage 2 — the packing
    offsets are a contract between the two stages.
    """
    if tzr is None:
        tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=tzr is not None)
    names = model.free_params
    has_phoff = model.has_component("PhaseOffset")

    def stage1(base, deltas, toas):
        f0 = base["F0"].hi + base["F0"].lo

        def total_phase(d):
            ph = phase_fn(base, d, toas)
            # aux carries the wrapped fractional phase from the SAME
            # primal evaluation — one DD pipeline pass serves both
            # the residual and the jacobian
            return (ph.int_part + (ph.frac.hi + ph.frac.lo),
                    ph.frac.hi + ph.frac.lo)

        err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)
        sw = jnp.sqrt(w)
        J, resid = jax.jacfwd(total_phase, has_aux=True)(deltas)
        if not has_phoff:
            resid = resid - jnp.sum(resid * w) / jnp.sum(w)
        r = resid / f0
        cols = ([] if has_phoff else [jnp.ones_like(r) / f0]) \
            + [-J[k] / f0 for k in names]
        M = jnp.stack(cols, axis=1)
        # whiten + unit-normalize columns HERE: the accelerator's
        # emulated f64 has f32 dynamic range, and sum(M^2 w) on raw
        # spin-derivative columns overflows it (see gls_gram_whitened)
        Mw = M * sw[:, None]
        norm_M = jnp.sqrt(jnp.sum(jnp.square(Mw), axis=0))
        norm_M = jnp.where(norm_M == 0.0, 1.0, norm_M)
        return jnp.concatenate([(Mw / norm_M).ravel(), r * sw, sw, norm_M])

    return stage1


def make_resid_stage1(model, tzr=None):
    """CPU residual-only stage 1 for damped-loop probe steps.

    The DD phase pipeline without the jacfwd tangents — whitened
    residuals ``r * sqrt(w)`` only (residual convention shared with
    every probe path via :func:`pint_tpu.fitting.step.make_resid_fn`).
    A halved/rejected trial point in the damped outer loop needs just
    the noise-marginal chi2 at its input (``downhill_iterate``'s
    ``chi2_at``), for which the design matrix is never consulted; this
    program costs one phase evaluation instead of 1 + n_params tangent
    passes. Cached per model structure alongside
    :func:`make_whiten_stage1` (key ``("resid_stage1",)``).
    """
    from pint_tpu.fitting.step import make_resid_fn

    resid = make_resid_fn(model, tzr)

    def stage1r(base, deltas, toas):
        r, _err, w = resid(base, deltas, toas)
        return r * jnp.sqrt(w)

    return stage1r


def _accel_pl_basis_arrays(t_s, inv_f2, specs: tuple[PLSpec, ...]):
    """The iteration-INDEPENDENT part of the noise bases: the stacked
    Fourier block (n, k_F) with chromatic scaling applied, plus the
    per-spec frequency grids. Depends only on the TOA table, so the
    hybrid fitter builds it ONCE on-device at construction instead of
    re-evaluating O(n·k) transcendentals inside every iteration's
    stage-2 program (round-5 clawback; the per-iteration part is only
    :func:`_accel_pl_phi`, O(k) work)."""
    blocks, fs = [], []
    for spec in specs:
        F, f, _df = fourier_design(t_s, spec.nharm)
        if spec.scale != "none":
            s = inv_f2[:, None]
            F = F * (s if spec.alpha == 2.0 else s ** (spec.alpha / 2.0))
        blocks.append(F)
        fs.append(f)
    return jnp.concatenate(blocks, axis=1), tuple(fs)


def _accel_pl_phi(fs, specs: tuple[PLSpec, ...], pl_params):
    """Per-bin prior variances from traced hyperparameters (O(k)).

    ``f[0] == 1/tspan == df`` by construction (harmonics j/T_span), so
    the bin width needs no separate plumbing."""
    return jnp.concatenate([
        jnp.repeat(powerlaw_phi(fs[i], pl_params[i, 0], pl_params[i, 1],
                                fs[i][0]), 2)
        for i in range(len(specs))])


class HybridGLSFitter(Fitter):
    """GLSFitter semantics with the CPU/accelerator split.

    On an all-CPU host both stages land on the CPU and results match
    ``GLSFitter``/``ShardedGLSFitter`` to float64 round-off (tested);
    on a TPU host stage 2 runs on the chip while every DD operation
    stays on the (exact) CPU backend.
    """

    def __init__(self, toas, model, *, accel=None,
                 force_mxu: bool | None = None):
        super().__init__(toas, model)
        self._force_mxu = force_mxu
        self.cpu = cpu_device()
        self.accel = accel if accel is not None else accelerator_device()
        self._n_orig = len(toas)
        self.noise, self.pl_specs = build_noise_statics(model, toas)
        # bucket the fit table (zero-weight pad; pint_tpu.bucketing):
        # same-structure fitters over different TOA counts share ONE
        # compiled stage-1/stage-2 program pair. self.toas stays the
        # original (residual reporting); padded epoch rows point at the
        # dummy segment so every epoch estimate is untouched.
        from pint_tpu import bucketing
        from pint_tpu.fitting.gls_step import pad_noise_statics

        n_fit = bucketing.bucket_size(self._n_orig)
        if n_fit != self._n_orig:
            toas = bucketing.pad_toas(toas, n_fit)
            self.noise = pad_noise_statics(self.noise, n_fit)

        names = model.free_params
        self._names = names
        # explicit PHOFF replaces the implicit offset column + mean
        # subtraction (see TimingModel.designmatrix)
        has_phoff = model.has_component("PhaseOffset")
        self._off = 0 if has_phoff else 1
        toas_cpu = jax.device_put(toas, self.cpu)
        # ONE flat stage-1 output buffer: the accelerator sits behind a
        # transfer link whose per-transfer latency dominates at these
        # sizes (observed in a round-2 TPU session: ~17 round trips cost
        # ~0.7 s/iter, the on-chip compute <1 ms; committed artifact
        # pending), so stage 1 packs everything iteration-dependent into
        # a single array for a single host->device put (t_s/inv_f2 are
        # TOA-only: shipped once). The builder is shared with the PTA
        # hybrid and cached per model structure (make_whiten_stage1).
        # build under the CPU pin: the EFT backend gate inside
        # _cached_jit must validate the device this DD program actually
        # runs on (self.cpu), not the process-default accelerator
        with jax.default_device(self.cpu):
            stage1_fn = model._cached_jit(
                ("whiten_stage1",), lambda owner: make_whiten_stage1(owner))

        def stage1(base, deltas):
            with jax.default_device(self.cpu):
                return stage1_fn(base, jax.device_put(deltas, self.cpu),
                                 toas_cpu)

        pl_specs = self.pl_specs
        n_params = len(names) + (0 if has_phoff else 1)  # + offset column
        self._n_params = n_params
        n = len(toas)
        k_f = int(sum(2 * s.nharm for s in pl_specs))
        q = n_params + k_f
        ne = int(np.asarray(self.noise.ecorr_phi).shape[0])
        self._q, self._ne = q, ne

        # noise statics and TOA-only arrays never change across
        # iterations: ship them once (shared argument contract —
        # see ship_stage2_statics)
        self._noise_dev = ship_stage2_statics(toas, self.noise,
                                              self.accel)
        # the (n, k_F) Fourier block is TOA-only too: build it once on
        # the accelerator (the operands are device-resident, so the jit
        # executes there) and keep it resident — each iteration's
        # stage-2 program then does only the O(k) phi evaluation
        # instead of O(n·k) transcendentals (_accel_pl_basis_arrays)
        if pl_specs:
            F_dev, fs = jax.jit(
                lambda t, i: _accel_pl_basis_arrays(t, i, pl_specs))(
                    self._noise_dev[3], self._noise_dev[4])
            self._pl_static = (F_dev,) + tuple(fs)
        else:
            self._pl_static = ()

        # on a real accelerator the O(n q^2) matmuls run as double-single
        # f32 on the MXU (emulated f64 matmul observed ~100x slower than
        # host CPU in a round-2 TPU session; artifact pending); on a TPU
        # the square Grams additionally go through
        # the hand-tiled pallas kernel. The gradient and segment sums
        # stay exact f64. force_mxu overrides (tests exercise the ds32
        # path on CPU).
        use_mxu = (self._force_mxu if self._force_mxu is not None
                   else accel_mxu_mode(self.accel))

        def make_stage2(mxu_mode):
            def stage2(packed, epoch_idx, ecorr_phi, pl_params,
                       t_s, inv_f2, *pl_static):
                # unpack stage 1's flat buffer (static slicing)
                o = n * n_params
                A_M = packed[:o].reshape(n, n_params)
                rw = packed[o:o + n]; o += n
                sw = packed[o:o + n]; o += n
                norm_M = packed[o:o + n_params]
                if pl_specs:
                    F = pl_static[0]
                    phi_F = _accel_pl_phi(pl_static[1:], pl_specs,
                                          pl_params)
                else:
                    F, phi_F = None, None
                parts = gls_gram_whitened(A_M, rw, sw, norm_M, F, phi_F,
                                          epoch_idx, ecorr_phi,
                                          mxu=mxu_mode)
                # the full solve stays on-chip: in the normalized domain
                # every quantity is range-safe for the chip's f32-range
                # f64 (gls_solve_normalized docstring); only the
                # un-normalization happens back on the host. ONE packed
                # result buffer.
                sol = gls_solve_normalized(parts)
                return jnp.concatenate([
                    sol["xB"], sol["Sigma"].ravel(), parts["norm"],
                    jnp.reshape(sol["chi2"], (1,)),
                    jnp.reshape(noise_marginal_chi2(parts, n_params),
                                (1,)),
                    sol["x_e"],
                ])
            return stage2

        self._stage1 = stage1  # stage1_fn already jitted via _cached_jit
        self._make_stage2 = make_stage2
        self._mxu_mode = use_mxu
        self._donate = stage2_donate_argnums(self.accel)
        self._stage2 = jax.jit(make_stage2(use_mxu),
                               donate_argnums=self._donate)
        self._stage2_mode = use_mxu
        self._stage2_ok_keys: set = set()
        self._toas_cpu = toas_cpu
        self._n_toas = n
        self._prog_fp = (hash(model._fn_fingerprint()), pl_specs)
        self._chi2_probe = None       # lazily built (see _chi2_at)

    def _run_stage2(self, packed_dev):
        def run(mode):
            if mode != self._stage2_mode:
                self._stage2 = jax.jit(self._make_stage2(mode),
                                       donate_argnums=self._donate)
                self._stage2_mode = mode
            return self._stage2(packed_dev, *self._noise_dev,
                                *self._pl_static)

        # single model structure -> one program key
        return run_stage2_with_fallback(self, "stage2", run)

    def _stage1_packed(self, base, deltas, *, instrument: bool = False):
        """Run stage 1; ``instrument`` wraps it in its telemetry span
        with an honest completion sync (the plain driver's accounting).
        The pipelined driver leaves instrumentation off so the dispatch
        stays non-blocking (overlap is the point there)."""
        from pint_tpu import bucketing, telemetry

        bucketing.note_program("hybrid_step", self._prog_fp,
                               (self._n_toas,))
        if not instrument:
            return self._stage1(base, deltas)
        with telemetry.jit_span("hybrid.stage1_cpu"):
            packed = self._stage1(base, deltas)
            if telemetry.enabled():
                # close the span at stage-1 completion (dispatch is
                # async); disabled, keep the uninstrumented overlap
                jax.block_until_ready(packed)  # jaxlint: disable=host-sync-in-hot-path -- telemetry-gated honest span close; the uninstrumented path above keeps the async overlap
        return packed

    def _iterate_dispatch(self, base, deltas):
        """Start one full hybrid step WITHOUT blocking on its result.

        Stage 1 (CPU) and stage 2 (accelerator) are both asynchronous
        dispatches; the returned handle is the un-fetched stage-2 output
        buffer. While it executes on the chip, the pipelined damped
        driver runs the NEXT halved candidate's CPU probe under it
        (fitting.damped.downhill_iterate_pipelined).
        """
        packed = self._stage1_packed(base, deltas)
        return (self._run_stage2(jax.device_put(packed, self.accel)),
                deltas)

    def _iterate_finish(self, out, deltas) -> tuple[dict, dict]:
        """Fetch + unpack a dispatched step (the one device->host sync)."""
        out = np.asarray(out)
        q, ne, p = self._q, self._ne, self._n_params
        o = 0
        xB = out[:q]; o = q
        Sigma = out[o:o + q * q].reshape(q, q); o += q * q
        norm = out[o:o + q]; o += q
        chi2 = out[o]; o += 1
        chi2_in = out[o]; o += 1
        x_e = out[o:o + ne]
        x = xB / norm
        cov = Sigma / np.outer(norm, norm)
        sol = {"x": x[:p], "cov": cov[:p, :p], "chi2": chi2,
               "chi2_at_input": chi2_in,
               "fourier_coeffs": x[p:], "ecorr_coeffs": x_e}
        new_deltas = {k: deltas[k] + sol["x"][i + self._off]
                      for i, k in enumerate(self._names)}
        return new_deltas, sol

    def _iterate(self, base, deltas) -> tuple[dict, dict]:
        from pint_tpu import telemetry

        packed = self._stage1_packed(base, deltas, instrument=True)
        # the span wraps DISPATCH + fetch: the first call's synchronous
        # jit compile must land inside it, or the rollup's stage-2
        # compile wall would be a fetch-sized lie (PR-1 honesty rule)
        with telemetry.jit_span("hybrid.stage2_accel"):
            out = self._run_stage2(jax.device_put(packed, self.accel))
            # one device->host fetch; un-normalize on the full-range
            # host (covariance entries reach ~1e-42 — below f32-range
            # f64); the fetch also closes the span honestly
            return self._iterate_finish(out, deltas)

    def _iterate_fetch(self, handle) -> tuple[dict, dict]:
        """Blocking half of :meth:`_iterate_dispatch`."""
        out, deltas = handle
        return self._iterate_finish(out, deltas)

    def _build_chi2_probe(self):
        """Constants + program for the O(n·k) noise-marginal chi2 probe.

        ``sw`` never changes across iterations (scaled_toa_uncertainty
        is a function of the TOA table only), so the whitened noise
        block ``A_F``, its ECORR cross/diagonal blocks and the Cholesky
        factor of the noise-only Schur system are all
        iteration-independent — built once here on the CPU device and
        reused by every probe. The algebra mirrors
        :func:`pint_tpu.fitting.gls_step.gls_gram_whitened` restricted
        to the noise columns + :func:`noise_marginal_chi2` (which is
        independent of the timing columns), so probe values track the
        full program's ``chi2_at_input`` to XLA-reordering roundoff.
        """
        # The probe runs ENTIRELY on the CPU device: it is O(n·k) exact
        # f64 linear algebra — the op class measured ~100x slower as the
        # accelerator's emulated f64, and unlike stage 2 it is not
        # normalized/double-single, so routing it through the chip would
        # need the whole mxu/fallback machinery for no win. rw is
        # already CPU-resident (residual-only stage 1).
        # sw is a pure function of the TOA table (same expression as
        # make_whiten_stage1) — computed directly so the probe has no
        # ordering dependency on a prior full _iterate.
        with jax.default_device(self.cpu):
            err = self.model.scaled_toa_uncertainty(self._toas_cpu)
            sw = 1.0 / jnp.asarray(err)
        ne, pl_specs = self._ne, self.pl_specs
        # CPU copies of the shipped statics + the Fourier block (the
        # one-time O(n·k) build mirrors _pl_static, on the host)
        noise_cpu = tuple(jax.device_put(x, self.cpu)
                          for x in self._noise_dev)
        if pl_specs:
            with jax.default_device(self.cpu):
                F_cpu, fs_cpu = jax.jit(
                    lambda t, i: _accel_pl_basis_arrays(t, i, pl_specs))(
                        noise_cpu[3], noise_cpu[4])
            pl_static = (F_cpu,) + tuple(fs_cpu)
        else:
            pl_static = ()
        self._probe_epoch_idx_cpu = noise_cpu[0]

        def build(sw, epoch_idx, ecorr_phi, pl_params, t_s, inv_f2,
                  *pl_static):
            if pl_specs:
                F = pl_static[0]
                phi_F = _accel_pl_phi(pl_static[1:], pl_specs, pl_params)
                Fw = F * sw[:, None]
                norm_F = jnp.sqrt(jnp.sum(jnp.square(Fw), axis=0))
                norm_F = jnp.where(norm_F == 0.0, 1.0, norm_F)
                A_F = Fw / norm_F
                phiinv = 1.0 / jnp.maximum(phi_F, 1e-36)
                G = A_F.T @ A_F + jnp.diag(phiinv / norm_F / norm_F)
            else:
                A_F = jnp.zeros((sw.shape[0], 0))
                G = jnp.zeros((0, 0))
            if ne > 0:
                def seg(x):
                    return jax.ops.segment_sum(
                        x, epoch_idx, num_segments=ne + 1)[:ne]

                d = seg(jnp.square(sw)) + 1.0 / ecorr_phi
                C = seg(A_F * sw[:, None])
                Cs = C * jax.lax.rsqrt(d)[:, None]
                S = G - Cs.T @ Cs
            else:
                d = jnp.ones(0)
                C = jnp.zeros((0, A_F.shape[1]))
                S = G
            k = A_F.shape[1]
            if k > 0:
                S = S + jnp.eye(k) * (jnp.finfo(jnp.float64).eps
                                      * jnp.trace(S))
                cho = jax.scipy.linalg.cho_factor(S, lower=True)[0]
            else:
                cho = jnp.zeros((0, 0))
            return A_F, C, d, cho, sw

        with jax.default_device(self.cpu):
            consts = jax.jit(build)(sw, *noise_cpu, *pl_static)
        k = int(consts[0].shape[1])

        def chi2_fn(rw, epoch_idx, A_F, C, d, cho, sw):
            chi2 = jnp.sum(jnp.square(rw))
            if ne > 0:
                c_e = jax.ops.segment_sum(
                    rw * sw, epoch_idx, num_segments=ne + 1)[:ne]
            if k > 0:
                c_F = A_F.T @ rw
                rhs = c_F - C.T @ (c_e / d) if ne > 0 else c_F
                xn = jax.scipy.linalg.cho_solve((cho, True), rhs)
                chi2 = chi2 - c_F @ xn
                if ne > 0:
                    x_e = (c_e - C @ xn) / d
                    chi2 = chi2 - c_e @ x_e
            elif ne > 0:
                chi2 = chi2 - c_e @ (c_e / d)
            return chi2

        return consts, jax.jit(chi2_fn)

    def _chi2_at_dispatch(self, base, deltas):
        """Start the noise-marginal chi2 probe WITHOUT blocking.

        One residual-only CPU phase pass (no jacfwd tangents) + the
        O(n·k) CPU probe program; both dispatches are asynchronous, so
        the pipelined driver can run this under an in-flight stage-2.
        """
        with jax.default_device(self.cpu):
            stage1r = self.model._cached_jit(
                ("resid_stage1",), lambda owner: make_resid_stage1(owner))
            rw = stage1r(base, jax.device_put(deltas, self.cpu),
                         self._toas_cpu)
        if self._chi2_probe is None:
            self._chi2_probe = self._build_chi2_probe()
        consts, prog = self._chi2_probe
        with jax.default_device(self.cpu):
            return prog(rw, self._probe_epoch_idx_cpu, *consts)

    def _chi2_at(self, base, deltas) -> float:
        """Noise-marginal chi2 at ``deltas`` without a design matrix
        (the damped loop's cheap trial-point judge,
        ``downhill_iterate(chi2_at=...)``)."""
        return float(np.asarray(self._chi2_at_dispatch(base, deltas)))

    def _pipeline_enabled(self) -> bool:
        """Speculative probe pipelining gate.

        Auto-on only when stage 2 runs on a REAL accelerator: the
        speculation spends host CPU inside the chip's execution window,
        which is free there but pure overhead on an all-CPU host (both
        stages contend for the same cores). ``PINT_TPU_HYBRID_PIPELINE``
        forces it on (1 — how the CPU-only parity tests exercise the
        path) or off (0).
        """
        env = config.env_raw("PINT_TPU_HYBRID_PIPELINE") or ""
        if env == "0":
            return False
        if env == "1":
            return True
        return self.accel is not None and self.accel.platform != "cpu"

    def fit_toas(self, maxiter: int = 20,
                 min_chi2_decrease: float = 1e-3, **kw) -> float:
        from pint_tpu import telemetry
        from pint_tpu.fitting.damped import (downhill_iterate,
                                             downhill_iterate_pipelined)

        telemetry.set_gauge("fit.ntoas", self._n_orig)
        base = jax.device_put(self.model.base_dd(), self.cpu)
        deltas0 = {k: jnp.zeros((), jnp.float64) for k in self._names}
        with telemetry.profile_span("fit.hybrid_gls", ntoas=self._n_orig,
                            accel=str(self.accel),
                            pipelined=self._pipeline_enabled()):
            if self._pipeline_enabled():
                # the hybrid split cannot fuse its CPU stage 1 into a
                # device loop; it pipelines instead — stage 2 for the
                # current trial executes on the chip while the CPU
                # probe of the next halved candidate runs speculatively
                deltas, sol, chi2, converged = downhill_iterate_pipelined(
                    lambda d: self._iterate_dispatch(base, d),
                    self._iterate_fetch,
                    lambda d: self._chi2_at_dispatch(base, d),
                    lambda h: float(np.asarray(h)),
                    deltas0, maxiter=maxiter,
                    min_chi2_decrease=min_chi2_decrease)
            else:
                deltas, sol, chi2, converged = downhill_iterate(
                    lambda d: self._iterate(base, d), deltas0,
                    maxiter=maxiter,
                    min_chi2_decrease=min_chi2_decrease,
                    chi2_at=lambda d: self._chi2_at(base, d))
        # a diverged fit (non-finite chi2, flagged in-loop) must never
        # write NaN parameters/uncertainties back into the model
        self.diverged = bool(np.asarray(sol.get("diverged", False)))
        if self.diverged:
            self.diverged_reason = f"non-finite chi2 ({chi2})"
            self.converged = False
            return chi2
        cov = np.asarray(sol["cov"])
        errors = np.sqrt(np.diagonal(cov))
        for i, k in enumerate(self._names):
            p = self.model[k]
            p.add_delta(float(np.asarray(deltas[k])))
            p.uncertainty = float(errors[i + self._off])
        self.fit_params = list(self._names)
        self.parameter_covariance_matrix = cov
        self.resids = self._new_resids()
        self.converged = converged
        return chi2
