"""One-shot jitted fit step: residuals + jacfwd design matrix + solve.

This is the whole of the reference's WLS iteration (SURVEY.md §3.3) as a
single pure function suitable for jit / vmap / sharding: the TOA table is
a traced argument, so its leaves can carry `NamedSharding` over the TOA
axis (pint_tpu.parallel) or a leading pulsar-batch axis under `vmap`.

Used by the benchmark harness, the multichip dry run, and the batched
multi-pulsar fitter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pint_tpu.fitting.fitter import wls_solve_gram

Array = jax.Array


def make_wls_step(model, tzr=None):
    """Build ``step(base, deltas, toas) -> (new_deltas, chi2)``.

    `base` is the DD linearization point (model.base_dd()); `deltas` the
    current float64 corrections per free parameter. One call performs a
    full damped-free Gauss-Newton iteration: residuals, design matrix by
    ``jacfwd``, Gram-matrix WLS solve, parameter update, post-fit chi2.
    """
    if tzr is None:
        tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr)
    names = model.free_params
    f0 = model.f0_f64

    def step(base, deltas, toas):
        def total_phase(d):
            ph = phase_fn(base, d, toas)
            return ph.int_part + (ph.frac.hi + ph.frac.lo)

        def frac_phase(d):
            ph = phase_fn(base, d, toas)
            return ph.frac.hi + ph.frac.lo

        err = toas.error_us * 1e-6
        w = 1.0 / jnp.square(err)

        resid_turns = frac_phase(deltas)
        resid_turns = resid_turns - jnp.sum(resid_turns * w) / jnp.sum(w)
        r = resid_turns / f0

        J = jax.jacfwd(total_phase)(deltas)
        cols = [jnp.ones_like(r) / f0] + [-J[k] / f0 for k in names]
        M = jnp.stack(cols, axis=1)

        sol = wls_solve_gram(M, r, err)
        new_deltas = {k: deltas[k] + sol["x"][i + 1] for i, k in enumerate(names)}

        post = frac_phase(new_deltas)
        post = post - jnp.sum(post * w) / jnp.sum(w)
        chi2 = jnp.sum(jnp.square(post / f0) * w)
        return new_deltas, chi2

    return step
