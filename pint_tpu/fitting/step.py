"""One-shot jitted fit step: residuals + jacfwd design matrix + solve.

This is the whole of the reference's WLS iteration (SURVEY.md §3.3) as a
single pure function suitable for jit / vmap / sharding: the TOA table is
a traced argument, so its leaves can carry `NamedSharding` over the TOA
axis of a device mesh (pint_tpu.parallel) or a leading pulsar-batch axis
under `vmap` (independent pulsars — the "expert" axis).

Used by the benchmark harness, the multichip dry run, and the sharded /
batched fitters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pint_tpu.fitting.fitter import wls_solve_gram

Array = jax.Array


def _circular_recenter(resid_turns, w):
    """Rotate wrapped phase residuals by their weighted circular mean.

    Anchorless (``abs_phase=False``) wrapped residuals carry an
    arbitrary constant offset; when it lands near ±0.5 turns the
    per-TOA wrap straddles the boundary (half the residuals come out
    +0.5, half −0.5) and the weighted-mean subtraction destroys phase
    coherence — chi2 jumps to wrap scale and the damped loop "converges"
    to garbage. The circular mean is offset-equivariant, so subtracting
    it and re-wrapping re-centers the cluster at 0 whatever the offset;
    the linear mean subtraction / PHOFF column then sees coherent
    residuals. A pure re-anchoring: no effect on the jacobian, and the
    post-rotation residuals equal the un-rotated ones minus a constant
    whenever no TOA actually wraps.
    """
    ang = 2.0 * jnp.pi * resid_turns
    circ = jnp.arctan2(jnp.sum(jnp.sin(ang) * w),
                       jnp.sum(jnp.cos(ang) * w)) / (2.0 * jnp.pi)
    shifted = resid_turns - circ
    return shifted - jnp.round(shifted)


def make_wls_step(model, tzr=None, *, abs_phase: bool = True,
                  masked: bool = False, params: list[str] | None = None,
                  traced_tzr: bool = False):
    """Build ``step(base, deltas, toas[, mask][, tzr]) -> (new_deltas, info)``.

    `base` is the DD linearization point (model.base_dd()); `deltas` the
    current float64 corrections per free parameter. One call performs a
    full Gauss-Newton iteration: residuals, design matrix by ``jacfwd``,
    Gram-matrix WLS solve, parameter update, post-fit chi2. ``info``
    carries {"chi2", "errors": {name: sigma}}.

    F0 is read from the traced `base`, so the same compiled step serves a
    ``vmap``-ed batch of pulsars with different spin frequencies.
    ``abs_phase=False`` skips the TZR anchor (the anchorless batched
    fallback; the wrapped residuals are re-centered on their circular
    mean first — see :func:`_circular_recenter`). ``traced_tzr=True``
    instead takes the TZR anchor table as a trailing *traced* argument:
    the batched fitter stacks one-row per-member TZR tables so every
    batch member computes the exact dense anchored convention.

    ``masked=True`` adds a ``mask: {name: 0/1 scalar}`` argument
    that zeroes design-matrix columns — the parameter-superset mechanism
    letting one compiled step serve heterogeneous pulsars (a masked
    column solves to a zero delta; the batched fitter skips its update).
    """
    if tzr is None and abs_phase and not traced_tzr:
        tzr = model.get_tzr_toas()
    anchorless = tzr is None and not traced_tzr
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase,
                                   traced_tzr=traced_tzr)
    names = params if params is not None else model.free_params
    # explicit PHOFF replaces the implicit offset column + mean
    # subtraction (see TimingModel.designmatrix)
    has_phoff = model.has_component("PhaseOffset")
    off = 0 if has_phoff else 1

    def step(base, deltas, toas, mask=None, tzr_toas=None):
        f0 = base["F0"].hi + base["F0"].lo

        def total_phase(d):
            ph = (phase_fn(base, d, toas, tzr_toas) if traced_tzr
                  else phase_fn(base, d, toas))
            # aux carries the wrapped fractional phase from the SAME
            # primal evaluation: one DD pipeline trace serves both the
            # residual and the jacobian (the guarded primal keeps the
            # residual bitwise — see make_whiten_stage1), instead of
            # tracing the phase program once per use (measured ~12 s
            # fused-step compile per model structure, dominating suite
            # wall clock)
            return (ph.int_part + (ph.frac.hi + ph.frac.lo),
                    ph.frac.hi + ph.frac.lo)

        # EFAC/EQUAD-scaled sigmas, matching WLSFitter's weighting
        # (scale_sigma and toa_mask are trace-safe)
        err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)

        J, resid_turns = jax.jacfwd(total_phase, has_aux=True)(deltas)
        if anchorless:
            resid_turns = _circular_recenter(resid_turns, w)
        if not has_phoff:
            resid_turns = resid_turns - jnp.sum(resid_turns * w) / jnp.sum(w)
        r = resid_turns / f0

        cols = [] if has_phoff else [jnp.ones_like(r) / f0]
        for k in names:
            col = -J[k] / f0
            if mask is not None:
                col = col * mask[k]
            cols.append(col)
        M = jnp.stack(cols, axis=1)

        sol = wls_solve_gram(M, r, err)
        new_deltas = {k: deltas[k] + sol["x"][i + off]
                      for i, k in enumerate(names)}
        sig = jnp.sqrt(jnp.diagonal(sol["cov"]))
        errors = {k: sig[i + off] for i, k in enumerate(names)}

        # chi2 of the residuals at the INPUT deltas — what a damped
        # (Downhill) outer loop compares against when judging the step
        chi2_in = jnp.sum(jnp.square(r) * w)
        # linearized post chi2 (the GLS-step convention, gls_step.py):
        # at the Gauss-Newton solution chi2_post = chi2_in - x·g with
        # g = M^T W r. Evaluating the TRUE post chi2 cost a third trace
        # of the whole phase program (~4 s compile per model structure);
        # the two agree to linearization error, and the damped drivers
        # judge every step by the exact chi2_at_input regardless.
        chi2 = chi2_in - sol["x"] @ (M.T @ (r * w))
        return new_deltas, {"chi2": chi2, "errors": errors,
                            "chi2_at_input": chi2_in}

    if not masked:
        if traced_tzr:
            def step_unmasked_tzr(base, deltas, toas, tzr_toas):
                return step(base, deltas, toas, None, tzr_toas)

            return step_unmasked_tzr

        def step_unmasked(base, deltas, toas):
            return step(base, deltas, toas)

        return step_unmasked
    return step


def jitted_wls_step(model, *, abs_phase: bool = True, masked: bool = False,
                    params: list[str] | None = None, vmapped: bool = False,
                    counted: bool = True, traced_tzr: bool = False):
    """Jitted :func:`make_wls_step`, shared across fitter instances.

    ``jax.jit(make_wls_step(model))`` compiles a fresh program per
    *closure object*, so two fitters over the same model structure —
    or repeated fits in a pintk/gridutils session — each pay the full
    XLA compile. This routes the step through the same model-level
    program cache as the host API (`TimingModel._cached_jit`): one
    compiled step per (structure fingerprint, step config), with free
    values flowing through the traced ``base``. ``vmapped`` builds the
    batched (pulsar-axis) masked variant used by BatchedPulsarFitter.

    ``counted=False`` skips the per-execution program-reuse counter
    wrapper — for callers that trace the step INTO a larger program
    (the fused device loop), where a host-side counter per call would
    fire once at trace time and never again.
    """
    key = ("wls_step", abs_phase, masked,
           tuple(params) if params is not None else None, vmapped,
           traced_tzr)

    def build(owner):
        fn = make_wls_step(owner, abs_phase=abs_phase, masked=masked,
                           params=params, traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        n_args = 3 + (1 if masked else 0) + (1 if traced_tzr else 0)
        return jax.vmap(fn, in_axes=(0,) * n_args)

    cached = model._cached_jit(key, build)
    if not counted:
        return cached
    return _counted_step(cached, key, model)


def make_resid_fn(model, tzr=None, *, abs_phase: bool = True,
                  traced_tzr: bool = False):
    """Build ``resid(base, deltas, toas) -> (r, err, w)`` — the shared
    residual-only evaluator: one phase pass (no jacfwd tangents),
    wrapped fractional residual in seconds with the step functions'
    exact weighted-mean convention, plus the scaled uncertainties and
    weights. The ONE home of the residual-prep block for every probe
    path (WLS/GLS device-loop probes, the hybrid CPU probe stage) so
    the convention cannot drift from the full steps' ``chi2_at_input``.
    """
    if tzr is None and abs_phase and not traced_tzr:
        tzr = model.get_tzr_toas()
    anchorless = tzr is None and not traced_tzr
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase,
                                   traced_tzr=traced_tzr)
    has_phoff = model.has_component("PhaseOffset")

    def resid(base, deltas, toas, tzr_toas=None, err=None):
        f0 = base["F0"].hi + base["F0"].lo
        ph = (phase_fn(base, deltas, toas, tzr_toas) if traced_tzr
              else phase_fn(base, deltas, toas))
        res = ph.frac.hi + ph.frac.lo
        # ``err`` (trace-time override): the GLS/wideband probes pass
        # the statics-carried scaled sigmas so the probe's weights —
        # the mean subtraction included — match the full step's traced
        # EFAC/EQUAD path exactly (ISSUE 10 satellite)
        if err is None:
            err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)
        if anchorless:
            # same circular re-centering as make_wls_step, so the probe
            # chi2 stays the step's exact chi2_at_input expression
            res = _circular_recenter(res, w)
        if not has_phoff:
            res = res - jnp.sum(res * w) / jnp.sum(w)
        return res / f0, err, w

    return resid


def make_wls_probe(model, tzr=None, *, abs_phase: bool = True,
                   traced_tzr: bool = False):
    """Build ``probe(base, deltas, toas) -> chi2`` — residual-only WLS chi2.

    The device-loop analogue of the hybrid fitter's cheap trial judge:
    one phase evaluation, no jacfwd tangents and no solve, computing
    exactly the ``chi2_at_input`` expression of :func:`make_wls_step`.
    A halved trial in the fused loop costs this instead of a full step;
    the accepted point is still re-judged by the full step's
    authoritative value (see fitting.device_loop). ``traced_tzr=True``
    takes the TZR anchor table as a trailing traced argument (the
    batched fitter's per-member stacked anchors, as in
    :func:`make_wls_step`).
    """
    resid = make_resid_fn(model, tzr, abs_phase=abs_phase,
                          traced_tzr=traced_tzr)

    if traced_tzr:
        def probe_tzr(base, deltas, toas, tzr_toas):
            r, _err, w = resid(base, deltas, toas, tzr_toas)
            return jnp.sum(jnp.square(r) * w)

        return probe_tzr

    def probe(base, deltas, toas):
        r, _err, w = resid(base, deltas, toas)
        return jnp.sum(jnp.square(r) * w)

    return probe


def jitted_wls_probe(model, *, abs_phase: bool = True,
                     traced_tzr: bool = False, vmapped: bool = False):
    """Model-cache-shared :func:`make_wls_probe` (same rationale as
    :func:`jitted_wls_step`; uncounted — it is traced into the fused
    loop program, never dispatched on its own)."""
    key = ("wls_probe", abs_phase, traced_tzr, vmapped)

    def build(owner):
        fn = make_wls_probe(owner, abs_phase=abs_phase,
                            traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        return jax.vmap(fn, in_axes=(0,) * (3 + (1 if traced_tzr else 0)))

    return model._cached_jit(key, build)


def _counted_step(fn, key, model):
    """Wrap a shared jitted step with per-shape program-reuse counters.

    The cached callable is one object per model structure, but jax.jit
    re-specializes per TOA shape — exactly what bucketing
    (pint_tpu.bucketing) canonicalizes. Counting (kind, fingerprint,
    shape) executions here makes the reuse auditable: a
    ``cache.fit_program.miss`` is an XLA compile, a ``.hit`` a
    warm-program execution.
    """
    from pint_tpu.bucketing import note_program, toa_shape

    fp = hash(model._fn_fingerprint())
    kind = key[0]

    def counted(base, deltas, toas, *rest):
        note_program(kind, (fp,) + tuple(key[1:]), toa_shape(toas))
        return fn(base, deltas, toas, *rest)

    return counted
