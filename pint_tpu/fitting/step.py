"""One-shot jitted fit step: residuals + jacfwd design matrix + solve.

This is the whole of the reference's WLS iteration (SURVEY.md §3.3) as a
single pure function suitable for jit / vmap / sharding: the TOA table is
a traced argument, so its leaves can carry `NamedSharding` over the TOA
axis of a device mesh (pint_tpu.parallel) or a leading pulsar-batch axis
under `vmap` (independent pulsars — the "expert" axis).

Used by the benchmark harness, the multichip dry run, and the sharded /
batched fitters.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from pint_tpu.fitting.fitter import wls_solve_gram

Array = jax.Array


def make_wls_step(model, tzr=None, *, abs_phase: bool = True,
                  masked: bool = False, params: list[str] | None = None):
    """Build ``step(base, deltas, toas[, mask]) -> (new_deltas, info)``.

    `base` is the DD linearization point (model.base_dd()); `deltas` the
    current float64 corrections per free parameter. One call performs a
    full Gauss-Newton iteration: residuals, design matrix by ``jacfwd``,
    Gram-matrix WLS solve, parameter update, post-fit chi2. ``info``
    carries {"chi2", "errors": {name: sigma}}.

    F0 is read from the traced `base`, so the same compiled step serves a
    ``vmap``-ed batch of pulsars with different spin frequencies.
    ``abs_phase=False`` skips the TZR anchor (the batched path, where the
    weighted-mean subtraction absorbs the absolute phase anyway).

    ``masked=True`` adds a 4th argument ``mask: {name: 0/1 scalar}``
    that zeroes design-matrix columns — the parameter-superset mechanism
    letting one compiled step serve heterogeneous pulsars (a masked
    column solves to a zero delta; the batched fitter skips its update).
    """
    if tzr is None and abs_phase:
        tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase)
    names = params if params is not None else model.free_params
    # explicit PHOFF replaces the implicit offset column + mean
    # subtraction (see TimingModel.designmatrix)
    has_phoff = model.has_component("PhaseOffset")
    off = 0 if has_phoff else 1

    def step(base, deltas, toas, mask=None):
        f0 = base["F0"].hi + base["F0"].lo

        def total_phase(d):
            ph = phase_fn(base, d, toas)
            # aux carries the wrapped fractional phase from the SAME
            # primal evaluation: one DD pipeline trace serves both the
            # residual and the jacobian (the guarded primal keeps the
            # residual bitwise — see make_whiten_stage1), instead of
            # tracing the phase program once per use (measured ~12 s
            # fused-step compile per model structure, dominating suite
            # wall clock)
            return (ph.int_part + (ph.frac.hi + ph.frac.lo),
                    ph.frac.hi + ph.frac.lo)

        # EFAC/EQUAD-scaled sigmas, matching WLSFitter's weighting
        # (scale_sigma and toa_mask are trace-safe)
        err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)

        J, resid_turns = jax.jacfwd(total_phase, has_aux=True)(deltas)
        if not has_phoff:
            resid_turns = resid_turns - jnp.sum(resid_turns * w) / jnp.sum(w)
        r = resid_turns / f0

        cols = [] if has_phoff else [jnp.ones_like(r) / f0]
        for k in names:
            col = -J[k] / f0
            if mask is not None:
                col = col * mask[k]
            cols.append(col)
        M = jnp.stack(cols, axis=1)

        sol = wls_solve_gram(M, r, err)
        new_deltas = {k: deltas[k] + sol["x"][i + off]
                      for i, k in enumerate(names)}
        sig = jnp.sqrt(jnp.diagonal(sol["cov"]))
        errors = {k: sig[i + off] for i, k in enumerate(names)}

        # chi2 of the residuals at the INPUT deltas — what a damped
        # (Downhill) outer loop compares against when judging the step
        chi2_in = jnp.sum(jnp.square(r) * w)
        # linearized post chi2 (the GLS-step convention, gls_step.py):
        # at the Gauss-Newton solution chi2_post = chi2_in - x·g with
        # g = M^T W r. Evaluating the TRUE post chi2 cost a third trace
        # of the whole phase program (~4 s compile per model structure);
        # the two agree to linearization error, and the damped drivers
        # judge every step by the exact chi2_at_input regardless.
        chi2 = chi2_in - sol["x"] @ (M.T @ (r * w))
        return new_deltas, {"chi2": chi2, "errors": errors,
                            "chi2_at_input": chi2_in}

    if not masked:
        def step_unmasked(base, deltas, toas):
            return step(base, deltas, toas)

        return step_unmasked
    return step


def jitted_wls_step(model, *, abs_phase: bool = True, masked: bool = False,
                    params: list[str] | None = None, vmapped: bool = False,
                    counted: bool = True):
    """Jitted :func:`make_wls_step`, shared across fitter instances.

    ``jax.jit(make_wls_step(model))`` compiles a fresh program per
    *closure object*, so two fitters over the same model structure —
    or repeated fits in a pintk/gridutils session — each pay the full
    XLA compile. This routes the step through the same model-level
    program cache as the host API (`TimingModel._cached_jit`): one
    compiled step per (structure fingerprint, step config), with free
    values flowing through the traced ``base``. ``vmapped`` builds the
    batched (pulsar-axis) masked variant used by BatchedPulsarFitter.

    ``counted=False`` skips the per-execution program-reuse counter
    wrapper — for callers that trace the step INTO a larger program
    (the fused device loop), where a host-side counter per call would
    fire once at trace time and never again.
    """
    key = ("wls_step", abs_phase, masked,
           tuple(params) if params is not None else None, vmapped)

    def build(owner):
        fn = make_wls_step(owner, abs_phase=abs_phase, masked=masked,
                           params=params)
        return jax.vmap(fn, in_axes=(0, 0, 0, 0)) if vmapped else fn

    cached = model._cached_jit(key, build)
    if not counted:
        return cached
    return _counted_step(cached, key, model)


def make_resid_fn(model, tzr=None, *, abs_phase: bool = True):
    """Build ``resid(base, deltas, toas) -> (r, err, w)`` — the shared
    residual-only evaluator: one phase pass (no jacfwd tangents),
    wrapped fractional residual in seconds with the step functions'
    exact weighted-mean convention, plus the scaled uncertainties and
    weights. The ONE home of the residual-prep block for every probe
    path (WLS/GLS device-loop probes, the hybrid CPU probe stage) so
    the convention cannot drift from the full steps' ``chi2_at_input``.
    """
    if tzr is None and abs_phase:
        tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase)
    has_phoff = model.has_component("PhaseOffset")

    def resid(base, deltas, toas):
        f0 = base["F0"].hi + base["F0"].lo
        ph = phase_fn(base, deltas, toas)
        res = ph.frac.hi + ph.frac.lo
        err = model.scaled_toa_uncertainty(toas)
        w = 1.0 / jnp.square(err)
        if not has_phoff:
            res = res - jnp.sum(res * w) / jnp.sum(w)
        return res / f0, err, w

    return resid


def make_wls_probe(model, tzr=None, *, abs_phase: bool = True):
    """Build ``probe(base, deltas, toas) -> chi2`` — residual-only WLS chi2.

    The device-loop analogue of the hybrid fitter's cheap trial judge:
    one phase evaluation, no jacfwd tangents and no solve, computing
    exactly the ``chi2_at_input`` expression of :func:`make_wls_step`.
    A halved trial in the fused loop costs this instead of a full step;
    the accepted point is still re-judged by the full step's
    authoritative value (see fitting.device_loop).
    """
    resid = make_resid_fn(model, tzr, abs_phase=abs_phase)

    def probe(base, deltas, toas):
        r, _err, w = resid(base, deltas, toas)
        return jnp.sum(jnp.square(r) * w)

    return probe


def jitted_wls_probe(model, *, abs_phase: bool = True):
    """Model-cache-shared :func:`make_wls_probe` (same rationale as
    :func:`jitted_wls_step`; uncounted — it is traced into the fused
    loop program, never dispatched on its own)."""
    key = ("wls_probe", abs_phase)
    return model._cached_jit(
        key, lambda owner: make_wls_probe(owner, abs_phase=abs_phase))


def _counted_step(fn, key, model):
    """Wrap a shared jitted step with per-shape program-reuse counters.

    The cached callable is one object per model structure, but jax.jit
    re-specializes per TOA shape — exactly what bucketing
    (pint_tpu.bucketing) canonicalizes. Counting (kind, fingerprint,
    shape) executions here makes the reuse auditable: a
    ``cache.fit_program.miss`` is an XLA compile, a ``.hit`` a
    warm-program execution.
    """
    from pint_tpu.bucketing import note_program, toa_shape

    fp = hash(model._fn_fingerprint())
    kind = key[0]

    def counted(base, deltas, toas, *rest):
        note_program(kind, (fp,) + tuple(key[1:]), toa_shape(toas))
        return fn(base, deltas, toas, *rest)

    return counted
