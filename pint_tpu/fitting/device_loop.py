"""Fused damped Gauss-Newton: the whole accept/halve/converge loop on-device.

Motivation (ISSUE 3): with shape bucketing making compiles rare, the
dominant non-FLOP cost of a fit became the per-iteration dispatch+sync
pattern of the host driver (:func:`pint_tpu.fitting.damped
.downhill_iterate`) — one program launch and one blocking ``float(chi2)``
device->host fetch per iteration *and per halving trial*. This module
moves the entire loop inside XLA: a ``lax.while_loop`` whose carry is
``(deltas, proposal, chi2, lam, halving/iteration counters, flags)``
drives the SAME accept / halve / converge semantics, so a complete fit
is ONE program launch and ONE host fetch regardless of iteration count.

Semantics are the host driver's, preserved exactly (and pinned by
tests/test_device_loop.py parity assertions):

* the first (lam=1) trial of each iteration runs the FULL fused step
  (its proposal is needed on acceptance, the common case);
* halved trials are judged by the cheap residual-only chi2 *probe* when
  one is provided — and a probe-accepted point is re-evaluated once with
  the full step, whose chi2 is AUTHORITATIVE (the probe is a different
  arithmetic path; when the full value contradicts the acceptance the
  loop keeps halving instead of applying an uphill step);
* ``min_chi2_decrease`` convergence floor, ``max_step_halvings`` cap,
  and the ``fit.*`` telemetry counters (iterations / accepts / halvings
  / probe_evals / probe_rejects / converged / maxiter_exhausted) — now
  read from the returned carry in the single fetch instead of being
  incremented per dispatch.

Divergence (ISSUE 6): a fit whose FULL evaluation produces a non-finite
chi2 (NaN-poisoned table, overflowing step) terminates immediately with
a ``diverged`` flag riding the while-loop carry, returned as
``info["diverged"]`` in the SAME single fetch, never an extra sync. The
batched loops carry a per-member (B,) flag: a diverging member is
finished (its deltas stay at the last kept point) while co-members
proceed untouched — vmapped evaluation is member-diagonal, so their
trajectories stay bit-identical to an undiverged batch (pinned by
tests/test_faults.py). ``converged`` is never True for a diverged fit.

The loop body executes exactly ONE step evaluation per ``while``
iteration (a small state machine with an ``is_init`` first pass and an
``is_recheck`` pass for probe-accepted trials), so the compiled program
contains a single instance of the fused step — compile cost stays at
~one step trace, not one per loop phase.

``maxiter`` / ``min_chi2_decrease`` / ``max_step_halvings`` are traced
operands: one compiled loop serves every hyperparameter setting.

Kill switch: ``PINT_TPU_DEVICE_LOOP=0`` restores the host driver
everywhere (the reference oracle; parity tests run both).
"""

from __future__ import annotations

from pint_tpu import config

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry
from pint_tpu.telemetry import recorder
from pint_tpu.utils.cache import LRUCache

# accept tolerance of the host driver (damped.downhill_iterate)
_EPS = 1e-12

# compiled loop programs keyed by the caller's (kind, step-identity)
# tuple; the captured step closures are the model-level cached jitted
# steps, so entries stay valid for the life of those programs
_LOOP_CACHE = LRUCache(32, name="device_loop")


def enabled() -> bool:
    """Device-loop gate (read per call so tests can flip the env var)."""
    return config.env_on("PINT_TPU_DEVICE_LOOP")


def _sel(pred, a, b):
    return jnp.where(pred, a, b)


def _tree_sel(pred, ta, tb):
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b), ta, tb)


def _zeros_like_shapes(tree_shapes):
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), tree_shapes)


_COUNTERS = ("iterations", "accepts", "halvings", "probe_evals",
             "probe_rejects")


def build_damped_loop(full, probe=None, record=False):
    """Build ``loop(deltas0, operands, maxiter, min_dec, max_halvings)``.

    ``full(deltas, operands) -> (new_deltas, info)`` is the fused step
    (``info["chi2_at_input"]`` judges the trial); ``probe(deltas,
    operands) -> chi2`` is the optional residual-only evaluator for
    halved trials. Both are traced INTO the loop program (cached jitted
    steps inline under the outer jit). Returns a plain function suitable
    for ``jax.jit``; the loop result is ``(deltas, info, chi2,
    converged, counters, trace)`` — ``trace`` is the flight-recorder
    ring (``telemetry.recorder``; one entry per body = per full-step
    evaluation, returned in the same fetch) when ``record``, else None.

    Structure: a TWO-LEVEL while — full steps in the outer body, the
    probe in an inner while over halved candidates — with no
    ``lax.cond`` anywhere. XLA:CPU compiles elementwise fusion loops
    inside ``cond`` branches ~1.5x slower than the same loops in a
    plain computation (measured on this host; ``while`` bodies carry no
    such penalty), and the full step's phase/jacfwd pipeline is exactly
    that op class — a cond-based probe/full dispatch taxed every
    ACCEPTED step to keep a rarely-taken probe branch. Here each outer
    body runs exactly one full evaluation: the init pass, a first
    (lam=1) trial, or the authoritative re-check of a probe-accepted
    candidate; a rejected full drops into the inner probe loop, which
    halves until a candidate looks downhill (next outer body re-checks
    it) or halvings are exhausted (converged at the numerical optimum).
    """
    has_probe = probe is not None
    trace_cap = recorder.trace_len() if record else 0

    def loop(deltas0, operands, maxiter, min_dec, max_halvings):
        maxiter = jnp.maximum(jnp.asarray(maxiter, jnp.int32), 1)
        max_halvings = jnp.maximum(jnp.asarray(max_halvings, jnp.int32), 1)
        min_dec = jnp.asarray(min_dec, jnp.float64)

        # info carry needs the right structure before the first full
        # eval: abstract-eval the step (no ops emitted) and start from
        # zeros — overwritten by the is_init pass before any read
        info_shapes = jax.eval_shape(lambda d: full(d, operands)[1],
                                     deltas0)
        c0 = {
            "deltas": deltas0,
            "new_deltas": deltas0,
            "dx": jax.tree.map(jnp.zeros_like, deltas0),
            "info": _zeros_like_shapes(info_shapes),
            "chi2": jnp.zeros((), jnp.float64),
            "lam": jnp.ones((), jnp.float64),
            "h": jnp.zeros((), jnp.int32),
            "it": jnp.zeros((), jnp.int32),
            "is_init": jnp.bool_(True),
            "done": jnp.bool_(False),
            "converged": jnp.bool_(False),
            "diverged": jnp.bool_(False),
            **{k: jnp.zeros((), jnp.int32) for k in _COUNTERS},
        }
        if record:
            # flight-recorder ring: one entry per body (= per full-step
            # evaluation), written in place, fetched with the result
            c0["trace"] = {
                "chi2": jnp.zeros(trace_cap, jnp.float64),
                "lam": jnp.zeros(trace_cap, jnp.float64),
                "accepted": jnp.zeros(trace_cap, bool),
                "halvings": jnp.zeros(trace_cap, jnp.int32),
                "probe_evals": jnp.zeros(trace_cap, jnp.int32),
            }
            c0["tn"] = jnp.zeros((), jnp.int32)

        def body(c):
            # this body's full evaluation: the init point (dx == 0), a
            # first (lam=1, h=0) trial, or a probe-accepted candidate
            # being authoritatively re-checked (h > 0)
            trial = jax.tree.map(lambda d, x: d + c["lam"] * x,
                                 c["deltas"], c["dx"])
            t_new, t_info = full(trial, operands)
            t_chi2 = t_info["chi2_at_input"]

            # a non-finite full evaluation is divergence: terminate the
            # fit at the last kept point instead of probing NaN ladders
            # (all the new predicates are False for finite fits, so a
            # healthy fit's trajectory is bit-identical to pre-flag)
            bad = ~jnp.isfinite(t_chi2)
            accept_test = (t_chi2 <= c["chi2"] + _EPS) & (~bad)
            p_init = c["is_init"]
            p_acc = (~p_init) & accept_test
            p_rej = (~p_init) & (~accept_test) & (~bad)
            adopt = p_init | p_acc

            deltas_n = _tree_sel(p_acc, trial, c["deltas"])
            chi2_n = _sel(adopt, t_chi2, c["chi2"])
            new_n = _tree_sel(adopt, t_new, c["new_deltas"])
            info_n = _tree_sel(adopt, t_info, c["info"])
            dx_n = _tree_sel(
                adopt,
                jax.tree.map(lambda a, b: a - b, new_n, deltas_n),
                c["dx"])

            decrease = c["chi2"] - t_chi2
            conv_now = p_acc & (decrease < min_dec)
            exhausted = p_acc & (c["it"] >= maxiter)

            if has_probe:
                # rejected full -> probe halved candidates until one
                # looks downhill (the NEXT outer body re-checks it with
                # the authoritative full value) or halvings run out.
                # Counter parity with the host driver: halvings and
                # probe_evals at probe-trial start; the re-check shares
                # its candidate's h (no extra halving count).
                def inner_cond(s):
                    return s["run"] & (~s["found"]) \
                        & (s["hp"] < max_halvings)

                def inner_body(s):
                    cand = jax.tree.map(lambda d, x: d + s["lam_p"] * x,
                                        c["deltas"], c["dx"])
                    pc = probe(cand, operands)
                    found = pc <= c["chi2"] + _EPS
                    return {
                        "run": s["run"],
                        "found": found,
                        "hp": _sel(found, s["hp"], s["hp"] + 1),
                        "lam_p": _sel(found, s["lam_p"],
                                      s["lam_p"] * 0.5),
                        "halv": s["halv"] + 1,
                        "pev": s["pev"] + 1,
                    }

                s = jax.lax.while_loop(inner_cond, inner_body, {
                    "run": p_rej,
                    "found": jnp.bool_(False),
                    "hp": c["h"] + 1,
                    "lam_p": c["lam"] * 0.5,
                    "halv": jnp.zeros((), jnp.int32),
                    "pev": jnp.zeros((), jnp.int32),
                })
                probe_found = p_rej & s["found"]
                rej_exh = p_rej & (~s["found"])
                lam_r, h_r = s["lam_p"], s["hp"]
                halv_inc, pev_inc = s["halv"], s["pev"]
                # a rejecting full at h>0 is the re-check contradicting
                # its probe's acceptance
                prej_inc = (p_rej & (c["h"] > 0)).astype(jnp.int32)
            else:
                # no probe: halved trials are full evaluations — the
                # next outer body simply runs at lam/2
                rej_exh = p_rej & (c["h"] + 1 >= max_halvings)
                probe_found = p_rej & (~rej_exh)
                lam_r, h_r = c["lam"] * 0.5, c["h"] + 1
                halv_inc = probe_found.astype(jnp.int32)
                pev_inc = jnp.zeros((), jnp.int32)
                prej_inc = jnp.zeros((), jnp.int32)

            done_n = conv_now | exhausted | rej_exh | bad
            converged_n = conv_now | rej_exh

            out = {
                "deltas": deltas_n,
                "new_deltas": new_n,
                "dx": dx_n,
                "info": info_n,
                "chi2": chi2_n,
                "lam": _sel(adopt, 1.0, _sel(probe_found, lam_r,
                                             c["lam"])),
                "h": _sel(adopt, 0, _sel(probe_found, h_r, c["h"])),
                "it": _sel(p_init, 1, _sel(p_acc, c["it"] + 1, c["it"])),
                "is_init": jnp.bool_(False),
                "done": done_n,
                "converged": converged_n,
                "diverged": c["diverged"] | bad,
                "iterations": c["iterations"]
                + p_init.astype(jnp.int32)
                + (p_acc & (~done_n)).astype(jnp.int32),
                "accepts": c["accepts"] + p_acc.astype(jnp.int32),
                "halvings": c["halvings"] + halv_inc,
                "probe_evals": c["probe_evals"] + pev_inc,
                "probe_rejects": c["probe_rejects"] + prej_inc,
            }
            if record:
                # entry for THIS body's full evaluation; halvings /
                # probe evals of the inner loop attach to its window
                idx = jnp.mod(c["tn"], trace_cap)
                tr = c["trace"]
                out["trace"] = {
                    "chi2": tr["chi2"].at[idx].set(t_chi2),
                    "lam": tr["lam"].at[idx].set(c["lam"]),
                    "accepted": tr["accepted"].at[idx].set(p_acc),
                    "halvings": tr["halvings"].at[idx].set(halv_inc),
                    "probe_evals": tr["probe_evals"].at[idx].set(pev_inc),
                }
                out["tn"] = c["tn"] + 1
            return out

        out = jax.lax.while_loop(lambda c: ~c["done"], body, c0)
        counters = {k: out[k] for k in _COUNTERS}
        trace = {"n": out["tn"], **out["trace"]} if record else None
        return (out["deltas"], dict(out["info"], diverged=out["diverged"]),
                out["chi2"], out["converged"], counters, trace)

    return loop


def _args_sig(args):
    """Hashable abstract signature of the loop-call arguments.

    Tree structure + per-leaf (shape, dtype, sharding) — the same
    specialization key ``jax.jit`` uses, computed up front so the AOT-
    compiled executable can be reused explicitly (and its XLA cost /
    memory analysis captured exactly once, at the compile).
    """
    leaves, treedef = jax.tree_util.tree_flatten(args)
    sig = [treedef]
    for leaf in leaves:
        sig.append((np.shape(leaf), str(np.result_type(leaf)),
                    getattr(leaf, "sharding", None)))
    return tuple(sig)


def _store_load(entry, sig):
    """Supply-chain rung: a persisted/adopted executable for this sig.

    Consults the persistent program store (:mod:`pint_tpu.programs`)
    for an AOT artifact saved by a prior process or shipped by the
    fleet. None on any miss, skew, or failure — the caller's next rung
    is a normal compile (which itself round-trips the persistent XLA
    cache when the store is wired)."""
    base = entry.get("pkey_base")
    if not base:
        return None
    try:
        from pint_tpu.programs import key as _pk
        # NOTE: the package re-exports the store() FUNCTION, which
        # shadows the submodule — import from the module path
        from pint_tpu.programs.store import store as _store

        st = _store()
        if st is None:
            return None
        from pint_tpu.serve.fingerprint import canonical_repr

        return st.load(_pk.artifact_key(base, sig),
                       sig=canonical_repr(sig))
    except Exception:  # noqa: BLE001 — persistence must never break a fit
        return None


def _store_save(entry, sig, compiled) -> None:
    """Persist one freshly-compiled executable (best-effort)."""
    base = entry.get("pkey_base")
    if not base:
        return
    try:
        from pint_tpu.programs import key as _pk
        from pint_tpu.programs.store import store as _store

        st = _store()
        if st is None:
            return
        from pint_tpu.serve.fingerprint import canonical_repr

        st.save(_pk.artifact_key(base, sig), compiled,
                sig=canonical_repr(sig), kind=entry.get("kind", ""),
                fp8=entry.get("fp8", ""), base=base)
    except Exception:  # noqa: BLE001
        pass


def _resolve_program(entry, deltas0, operands, hyper):
    """(program, freshly_compiled, sig): the AOT executable for this
    call signature, compiling (and caching) it on first sight.

    AOT (``jit(...).lower(...).compile()``) instead of plain jit
    dispatch so the compiled object is in hand for program accounting
    (``recorder.capture_program``); the compile itself happens exactly
    when jit would have compiled anyway. With a persistent program
    store configured, a disk/shipped artifact is tried FIRST (zero
    recompile), and a fresh compile is serialized back (the supply
    chain; see :mod:`pint_tpu.programs`). Any failure in the AOT path —
    building OR hashing the signature, lowering, compiling — falls back
    to the jitted callable (sig None when it cannot be cached):
    accounting must never break a fit."""
    try:
        sig = _args_sig((deltas0, operands, hyper))
        prog = entry["aot"].get(sig)  # hashes sig — inside the guard
    except Exception:  # noqa: BLE001 — unhashable sharding etc.
        return entry["jit"], None, None
    if prog is not None:
        return prog, None, sig
    prog = _store_load(entry, sig)
    if prog is not None:
        entry["aot"][sig] = prog
        return prog, None, sig
    import time as _time

    t0 = _time.perf_counter()
    try:
        prog = entry["jit"].lower(deltas0, operands, *hyper).compile()
    except Exception:  # noqa: BLE001
        prog = entry["jit"]
    else:
        # per-structure compile accounting (bench splits compile cost
        # by kind instead of one aggregate loop_compile_s)
        telemetry.inc(
            "programs.compile_s." + (entry.get("kind") or "unknown"),
            _time.perf_counter() - t0)
        _store_save(entry, sig, prog)
    entry["aot"][sig] = prog
    return prog, (prog if prog is not entry["jit"] else None), sig


class InFlightFit:
    """A dispatched fused fit whose single host fetch has not happened.

    The handle the throughput scheduler's double-buffered pipeline holds
    while the device executes: :func:`_dispatch` enqueued the whole loop
    program (JAX async dispatch — the call returns as soon as the work
    is queued) and the host is free to pack/whiten/pad the NEXT batch.
    :meth:`fetch` performs the fit's ONE device->host sync, re-emits the
    carried counters and flight-recorder trace, and returns the same
    tuple the synchronous runners return.
    """

    __slots__ = ("_out", "_kind", "_result")

    def __init__(self, out, kind):
        self._out = out
        self._kind = kind
        self._result = None

    def ready(self) -> bool:
        """Is the dispatched program's result already complete?

        A pure runtime-queue peek (``jax.Array.is_ready``) — never
        blocks, never syncs — so the serve pipeline's work-stealing
        drain can fetch finished shards ahead of FIFO order.
        """
        if self._result is not None:
            return True
        try:
            return all(x.is_ready() for x in jax.tree.leaves(self._out)
                       if hasattr(x, "is_ready"))
        except Exception:  # noqa: BLE001 — readiness is advisory only
            return True

    def fetch(self):
        """Block on the single device->host sync; idempotent."""
        if self._result is None:
            with telemetry.span(f"{self._kind}.fetch", kind="execute"):
                # the ONE device->host sync of the whole fit
                deltas, info, chi2, converged, counters, trace = \
                    jax.device_get(self._out)
            self._out = None  # free the device buffers' host references
            telemetry.inc("fit.device_loop.fetches")
            counters = {k: int(v) for k, v in counters.items()}
            for k, v in counters.items():
                if v:
                    telemetry.inc(f"fit.{k}", v)
            if trace is not None:
                recorder.emit_device_trace(self._kind, trace)
            self._result = (deltas, info, chi2, converged, counters)
        return self._result


def _donate_operands() -> bool:
    """Donate the operand pytree to the loop program? Accelerators only:
    this jaxlib's XLA:CPU has no input-output aliasing (donation there
    warns and no-ops — the PR-2 / hybrid ``stage2_donate_argnums``
    rule)."""
    try:
        return jax.default_backend() != "cpu"
    except Exception:  # noqa: BLE001 — donation is an optimization only
        return False


def _dispatch(builder, key, deltas0, operands, hyper, *, kind, fingerprint,
              shape, donate_state=False) -> InFlightFit:
    """Shared launch head of the runners: one cached-program lookup, one
    launch, NO host sync — the returned handle's :meth:`InFlightFit
    .fetch` is the fit's single device->host sync.

    ``donate_state=True`` donates the operand pytree (argument 1) to
    the compiled program on accelerator backends — the sessionful
    incremental update's cached buffers are replaced by the update, so
    XLA may alias their memory for the new factor (ISSUE 10)."""
    from pint_tpu.bucketing import note_program

    # the recorder changes the carry (hence the compiled program), so
    # it is part of the cache key; ditto the ring capacity and the
    # donation flag (donated programs have a different buffer contract)
    rec_on = recorder.active()
    donate = bool(donate_state) and _donate_operands()
    trace_len = recorder.trace_len() if rec_on else 0
    cache_key = (key, rec_on, trace_len, donate)
    entry = _LOOP_CACHE.get_lru(cache_key)
    if entry is None:
        # the entry's stable identity for the persistent store: the
        # accounting triple + the dispatch-variant facts that select a
        # distinct executable. None (unkeyable fingerprint) simply
        # disables persistence for this entry.
        try:
            from pint_tpu.programs import key as _pk

            pkey_base = _pk.program_key(
                kind, fingerprint, tuple(shape),
                extra=(rec_on, trace_len, donate))
            fp8 = _pk.current_fp8() or ""
        except Exception:  # noqa: BLE001 — identity is optional
            pkey_base, fp8 = None, ""
        entry = _LOOP_CACHE.put_lru(
            cache_key,
            {"jit": jax.jit(builder(rec_on),
                            donate_argnums=(1,) if donate else ()),
             "aot": {}, "pkey_base": pkey_base, "kind": kind,
             "fp8": fp8})
    prog, fresh, sig = _resolve_program(entry, deltas0, operands, hyper)
    note_program(kind, fingerprint, tuple(shape), compiled=fresh)
    telemetry.inc("fit.device_loop.launches")
    with telemetry.jit_span(f"{kind}.program"):
        try:
            out = prog(deltas0, operands, *hyper)
        except Exception:
            # an AOT executable is stricter than jit dispatch (exact
            # avals); on any mismatch re-dispatch through jit — and
            # unpoison the cache so later same-sig launches skip the
            # known-bad executable
            if prog is entry["jit"]:
                raise
            if sig is not None:
                entry["aot"][sig] = entry["jit"]
            out = entry["jit"](deltas0, operands, *hyper)
    return InFlightFit(out, kind)


def dispatch_damped(full, deltas0, operands, *, key, probe=None,
                    maxiter=20, min_chi2_decrease=1e-3,
                    max_step_halvings=8, kind="device_loop",
                    fingerprint=None, shape=(),
                    donate_state=False) -> InFlightFit:
    """Asynchronous :func:`run_damped`: enqueue the fused scalar loop
    and return its :class:`InFlightFit` handle without blocking.

    The TOA-sharded serving route's building block (ISSUE 7,
    pint_tpu.parallel.sharded_fit.ShardedServeFitter): a big single fit
    dispatches as one mesh-partitioned program and the scheduler's
    pipeline overlaps the next batch's host prep with it, exactly as
    :func:`dispatch_damped_batched` does for member batches.
    ``handle.fetch()`` is the fit's single device->host sync.
    """
    return _dispatch(
        lambda rec: build_damped_loop(full, probe, record=rec), key,
        deltas0, operands,
        (maxiter, min_chi2_decrease, max_step_halvings), kind=kind,
        fingerprint=fingerprint, shape=shape, donate_state=donate_state)


def run_damped(full, deltas0, operands, *, key, probe=None, maxiter=20,
               min_chi2_decrease=1e-3, max_step_halvings=8,
               kind="device_loop", fingerprint=None, shape=()):
    """Execute a fused damped fit: one launch, one fetch.

    Same return contract as :func:`pint_tpu.fitting.damped
    .downhill_iterate` plus the counters dict: ``(deltas, info, chi2,
    converged, counters)`` with every array already fetched to host
    numpy. ``key`` identifies the (step, probe) pair for the compiled-
    loop cache; ``kind``/``fingerprint``/``shape`` feed the bucketing
    program-reuse accounting (a ``cache.fit_program.miss`` under this
    kind is an XLA compile of the whole loop program).
    """
    deltas, info, chi2, converged, counters = dispatch_damped(
        full, deltas0, operands, key=key, probe=probe, maxiter=maxiter,
        min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind=kind,
        fingerprint=fingerprint, shape=shape).fetch()
    converged = bool(converged)
    if bool(np.asarray(info.get("diverged", False))):
        telemetry.inc("fit.diverged")
    else:
        telemetry.inc("fit.converged" if converged
                      else "fit.maxiter_exhausted")
    return deltas, info, float(chi2), converged, counters


# ----------------------------------------------------------------------
# batched (per-member lam carry) variant
# ----------------------------------------------------------------------

def _bwhere(mask, a, b):
    """Member-wise where over leaves with a leading (B,) axis."""
    m = jnp.reshape(mask, mask.shape + (1,) * (jnp.ndim(a) - 1))
    return jnp.where(m, a, b)


_BATCH_COUNTERS = ("iterations", "accepts", "halvings", "step_evals")


def build_batched_loop(run, probe=None, record=False):
    """Batched analogue of :func:`build_damped_loop`.

    ``run(deltas, operands) -> (new_deltas, info)`` is the vmapped step
    over a leading pulsar axis; every judged quantity is a (B,) vector
    and each member carries its own damping ``lam`` and convergence
    flag — members halve independently on-device, with none of the host
    masking rounds of the pre-fusion ``BatchedPulsarFitter`` loop. The
    semantics mirror that host loop exactly (tests pin parity): one
    batch-wide trial per body, member-wise acceptance via a zeroed
    ``lam`` for already-settled members, and a final refresh evaluation
    only when the last trial left some member away from its kept point.

    ``probe(deltas, operands) -> (B,) chi2`` is the optional residual-
    only evaluator for halved trials, the batched form of the scalar
    loop's two-level structure: a full body whose trial a member
    rejects drops that member into an inner member-wise probe ``while``
    (synchronized halving depths, found members freeze their lam) and
    the NEXT full body re-checks every probe-accepted candidate with
    the authoritative step value. Without it every halving level is a
    full vmapped step evaluation — the ISSUE-5 throughput A/B measured
    ~84% of batched device time in those halving bodies (a damped fit
    near its optimum burns its halving budget on the final iteration,
    and a full body costs ~(P+1)x a probe body).

    The flight recorder (``record=True``) traces the per-member
    judgment: each body appends ``(chi2, lam, accepted)`` (B,)-vectors
    — ``lam`` is the member-wise damping actually applied (0 for
    settled members and the init/final passes) — so a non-converging
    member of a batched fit is diagnosable from the single fetch.
    """
    if probe is not None:
        return _build_batched_probe_loop(run, probe, record=record)
    trace_cap = recorder.trace_len() if record else 0

    def loop(deltas0, operands, maxiter, min_dec, max_halvings):
        maxiter = jnp.maximum(jnp.asarray(maxiter, jnp.int32), 1)
        max_halvings = jnp.maximum(jnp.asarray(max_halvings, jnp.int32), 1)
        min_dec = jnp.asarray(min_dec, jnp.float64)

        B = int(np.shape(jax.tree.leaves(deltas0)[0])[0])
        info_shapes = jax.eval_shape(lambda d: run(d, operands)[1],
                                     deltas0)
        c0 = {
            "deltas": deltas0,
            "new_deltas": deltas0,
            "dx": jax.tree.map(jnp.zeros_like, deltas0),
            "info": _zeros_like_shapes(info_shapes),
            "chi2": jnp.zeros(B, jnp.float64),
            "lam": jnp.ones(B, jnp.float64),
            "active": jnp.ones(B, bool),
            "accepted": jnp.zeros(B, bool),
            "converged": jnp.zeros(B, bool),
            "diverged": jnp.zeros(B, bool),
            "h": jnp.zeros((), jnp.int32),
            "it": jnp.zeros((), jnp.int32),
            "is_init": jnp.bool_(True),
            "is_final": jnp.bool_(False),
            "done": jnp.bool_(False),
            **{k: jnp.zeros((), jnp.int32) for k in _BATCH_COUNTERS},
        }
        if record:
            c0["trace"] = {
                "chi2": jnp.zeros((trace_cap, B), jnp.float64),
                "lam": jnp.zeros((trace_cap, B), jnp.float64),
                "accepted": jnp.zeros((trace_cap, B), bool),
            }
            c0["tn"] = jnp.zeros((), jnp.int32)

        def body(c):
            live = c["active"] & (~c["accepted"]) & (~c["diverged"])
            # init: dx == 0 so the trial is deltas0 regardless of lam;
            # final: a zero lam pins the trial at the kept points
            lam_j = jnp.where(c["is_init"] | c["is_final"], 0.0,
                              jnp.where(live, c["lam"], 0.0))
            trial = jax.tree.map(
                lambda d, x: d + jnp.reshape(
                    lam_j, lam_j.shape + (1,) * (jnp.ndim(x) - 1)) * x,
                c["deltas"], c["dx"])
            t_new, t_info = run(trial, operands)
            t_chi2 = t_info["chi2_at_input"]

            p_init = c["is_init"]
            p_final = c["is_final"]
            p_norm = (~p_init) & (~p_final)

            # ---- normal trial judgment (member-wise) ----
            # a member whose full evaluation is non-finite diverges:
            # finished at its last kept point, never adopted, never
            # counted converged (every new predicate is False for
            # finite members — co-member trajectories are bit-exact)
            bad = ~jnp.isfinite(t_chi2)
            better = (t_chi2 <= c["chi2"] + _EPS) & (~bad)
            newly = p_norm & live & better
            div_n = c["diverged"] | (bad & (p_init | (p_norm & live)))
            deltas_n = jax.tree.map(lambda t, d: _bwhere(newly, t, d),
                                    trial, c["deltas"])
            new_n = jax.tree.map(lambda t, d: _bwhere(newly, t, d),
                                 t_new, c["new_deltas"])
            decrease = c["chi2"] - t_chi2
            chi2_n = _sel(p_init, t_chi2,
                          jnp.where(newly, t_chi2, c["chi2"]))
            conv_n = c["converged"] | (newly & (decrease < min_dec))
            acc_n = c["accepted"] | newly

            inner_done = jnp.all(acc_n | (~c["active"]) | div_n)
            inner_exh = p_norm & (~inner_done) & (c["h"] + 1 >= max_halvings)
            end_iter = p_norm & (inner_done | inner_exh)
            # members with no downhill step left are at their optimum
            conv_n = jnp.where(end_iter & c["active"] & (~acc_n)
                               & (~div_n), True, conv_n)
            all_conv = jnp.all(conv_n | div_n)
            stop_outer = end_iter & (all_conv | (c["it"] >= maxiter))
            # the host driver re-evaluates at the kept points only when
            # the last trial left an active member at a rejected lam
            need_final = stop_outer & (~inner_done)
            next_iter = end_iter & (~stop_outer)

            # adopt the init evaluation / start the next iteration
            start = p_init | next_iter
            new_n = _tree_sel(p_init, t_new, new_n)
            dx_n = _tree_sel(
                start,
                jax.tree.map(lambda a, b: a - b, new_n, deltas_n),
                c["dx"])

            lam_n = jnp.where(start, 1.0,
                              jnp.where(p_norm & (~end_iter) & c["active"]
                                        & (~acc_n), c["lam"] * 0.5,
                                        c["lam"]))

            out = {
                "deltas": deltas_n,
                "new_deltas": new_n,
                "dx": dx_n,
                # every body IS an evaluation; its info is the freshest
                # (init / final included — host parity for both)
                "info": t_info,
                "chi2": chi2_n,
                "lam": lam_n,
                "active": jnp.where(start, ~(conv_n | div_n),
                                    c["active"]),
                "accepted": jnp.where(start, False, acc_n),
                "converged": conv_n,
                "diverged": div_n,
                "h": _sel(start | end_iter, 0,
                          _sel(p_norm, c["h"] + 1, c["h"])),
                "it": _sel(p_init, 1, _sel(next_iter, c["it"] + 1,
                                           c["it"])),
                "is_init": jnp.bool_(False),
                "is_final": need_final,
                "done": _sel(p_final, True, stop_outer & (~need_final)),
                "iterations": c["iterations"]
                + (p_init | next_iter).astype(jnp.int32),
                "accepts": c["accepts"]
                + jnp.sum(newly).astype(jnp.int32),
                "halvings": c["halvings"]
                + (p_norm & (c["h"] > 0)).astype(jnp.int32),
                "step_evals": c["step_evals"] + 1,
            }
            if record:
                idx = jnp.mod(c["tn"], trace_cap)
                tr = c["trace"]
                out["trace"] = {
                    "chi2": tr["chi2"].at[idx].set(t_chi2),
                    "lam": tr["lam"].at[idx].set(lam_j),
                    "accepted": tr["accepted"].at[idx].set(newly),
                }
                out["tn"] = c["tn"] + 1
            return out

        out = jax.lax.while_loop(lambda c: ~c["done"], body, c0)
        counters = {k: out[k] for k in _BATCH_COUNTERS}
        trace = {"n": out["tn"], **out["trace"]} if record else None
        return (out["deltas"], dict(out["info"], diverged=out["diverged"]),
                out["chi2"], out["converged"], counters, trace)

    return loop


_BATCH_PROBE_COUNTERS = _BATCH_COUNTERS + ("probe_evals", "probe_rejects")


def _build_batched_probe_loop(run, probe, record=False):
    """Probe flavor of :func:`build_batched_loop` (see there).

    Each member's judged events are its standalone damped loop's
    exactly — the vmapped step is member-diagonal, so member m's
    accept/halve/converge walk depends only on its own state — but the
    batch executes them CONTINUOUSLY: every full body advances every
    unfinished member's own state machine by one authoritative
    evaluation (its iteration-opening lam=1 trial or the re-check of a
    probe-found candidate), and members halving at the same time share
    member-wise probe rounds. There is no batch-wide iteration barrier:
    the lockstep no-probe loop makes a member that accepted early ride
    (at lam 0, but at full vmapped-body cost) while slower members
    drain their halving ladders, which measured ~2.4x the per-member
    full evaluations of the sequential fused loop on the ISSUE-5
    throughput A/B — the barrier, not the vectorization, was the cost.
    Finished members still occupy their vmap lane (static shapes) but
    add no extra bodies.

    Probe semantics match the scalar loop: halved candidates are judged
    by the residual-only ``probe(deltas, operands) -> (B,) chi2``
    (computing the step's own ``chi2_at_input`` expression), a found
    candidate is re-checked by the authoritative full step, and a
    contradicting re-check counts a ``probe_reject`` and resumes that
    member's ladder one level deeper. ``info`` is carried member-wise
    at each member's last ADOPTED point, so no final refresh pass is
    needed.
    """
    trace_cap = recorder.trace_len() if record else 0

    def loop(deltas0, operands, maxiter, min_dec, max_halvings):
        maxiter = jnp.maximum(jnp.asarray(maxiter, jnp.int32), 1)
        max_halvings = jnp.maximum(jnp.asarray(max_halvings, jnp.int32), 1)
        min_dec = jnp.asarray(min_dec, jnp.float64)

        B = int(np.shape(jax.tree.leaves(deltas0)[0])[0])
        info_shapes = jax.eval_shape(lambda d: run(d, operands)[1],
                                     deltas0)
        c0 = {
            "deltas": deltas0,
            "new_deltas": deltas0,
            "dx": jax.tree.map(jnp.zeros_like, deltas0),
            "info": _zeros_like_shapes(info_shapes),
            "chi2": jnp.zeros(B, jnp.float64),
            "lam": jnp.ones(B, jnp.float64),
            "h": jnp.zeros(B, jnp.int32),
            "it": jnp.zeros(B, jnp.int32),
            "init": jnp.ones(B, bool),    # member awaits its init eval
            "pend": jnp.ones(B, bool),    # candidate awaits a full eval
            "fin": jnp.zeros(B, bool),    # member's fit is finished
            "converged": jnp.zeros(B, bool),
            "diverged": jnp.zeros(B, bool),
            "done": jnp.bool_(False),
            **{k: jnp.zeros((), jnp.int32)
               for k in _BATCH_PROBE_COUNTERS},
        }
        if record:
            c0["trace"] = {
                "chi2": jnp.zeros((trace_cap, B), jnp.float64),
                "lam": jnp.zeros((trace_cap, B), jnp.float64),
                "accepted": jnp.zeros((trace_cap, B), bool),
            }
            c0["tn"] = jnp.zeros((), jnp.int32)

        def body(c):
            # one full evaluation: every pending member at its own
            # candidate (init members at their start point — dx is 0),
            # finished/probe-idle members riding at their kept point
            act = c["pend"] & (~c["fin"])
            lam_j = jnp.where(c["init"], 0.0,
                              jnp.where(act, c["lam"], 0.0))
            trial = jax.tree.map(
                lambda d, x: d + jnp.reshape(
                    lam_j, lam_j.shape + (1,) * (jnp.ndim(x) - 1)) * x,
                c["deltas"], c["dx"])
            t_new, t_info = run(trial, operands)
            t_chi2 = t_info["chi2_at_input"]

            # a live member whose full evaluation is non-finite diverges:
            # finished at its last kept point, out of the probe ladder,
            # never adopted or counted converged. Every new predicate is
            # False for finite members, so co-member trajectories stay
            # bit-identical to an undiverged batch (member-diagonal)
            bad = ~jnp.isfinite(t_chi2)
            norm = act & (~c["init"])
            better = (t_chi2 <= c["chi2"] + _EPS) & (~bad)
            newly = norm & better
            rej = norm & (~better) & (~bad)
            div_now = bad & (c["init"] | norm)
            adopt = c["init"] | newly

            deltas_n = jax.tree.map(lambda t, d: _bwhere(newly, t, d),
                                    trial, c["deltas"])
            new_n = jax.tree.map(lambda t, d: _bwhere(adopt, t, d),
                                 t_new, c["new_deltas"])
            info_n = jax.tree.map(lambda t, d: _bwhere(adopt, t, d),
                                  t_info, c["info"])
            decrease = c["chi2"] - t_chi2
            chi2_n = jnp.where(adopt, t_chi2, c["chi2"])
            conv_now = newly & (decrease < min_dec)
            maxed = newly & (c["it"] >= maxiter)
            fin_acc = conv_now | maxed

            # accepting members open their next iteration immediately
            # (member-wise dx from THIS body's proposal); nobody waits
            # for a batch-wide iteration boundary. A diverging init
            # member must NOT open an iteration (its proposal is NaN)
            startm = adopt & (~fin_acc) & (~div_now)
            dx_n = jax.tree.map(
                lambda a, b, d: _bwhere(startm, a - b, d),
                new_n, deltas_n, c["dx"])

            # rejected members walk their probe ladder (member-wise
            # depths; found members freeze their candidate)
            def inner_cond(s):
                return jnp.any(s["seek"] & (s["hp"] < max_halvings))

            def inner_body(s):
                sk = s["seek"] & (s["hp"] < max_halvings)
                lam_pj = jnp.where(sk, s["lam_p"], 0.0)
                cand = jax.tree.map(
                    lambda d, x: d + jnp.reshape(
                        lam_pj,
                        lam_pj.shape + (1,) * (jnp.ndim(x) - 1)) * x,
                    deltas_n, dx_n)
                pc = probe(cand, operands)
                fnd = sk & (pc <= chi2_n + _EPS)
                cont = sk & (~fnd)
                n_sk = jnp.sum(sk).astype(jnp.int32)
                return {
                    "seek": s["seek"] & (~fnd),
                    "found": s["found"] | fnd,
                    "hp": jnp.where(cont, s["hp"] + 1, s["hp"]),
                    "lam_p": jnp.where(cont, s["lam_p"] * 0.5,
                                       s["lam_p"]),
                    "halv": s["halv"] + n_sk,
                    "pev": s["pev"] + n_sk,
                }

            s = jax.lax.while_loop(inner_cond, inner_body, {
                "seek": rej,
                "found": jnp.zeros(B, bool),
                "hp": c["h"] + 1,
                "lam_p": c["lam"] * 0.5,
                "halv": jnp.zeros((), jnp.int32),
                "pev": jnp.zeros((), jnp.int32),
            })
            probe_found = s["found"]
            # no downhill step left: at the numerical optimum
            exhausted = rej & (~s["found"])

            conv_n = c["converged"] | conv_now | exhausted
            div_n = c["diverged"] | div_now
            fin_n = c["fin"] | fin_acc | exhausted | div_now
            pend_n = startm | probe_found

            out = {
                "deltas": deltas_n,
                "new_deltas": new_n,
                "dx": dx_n,
                "info": info_n,
                "chi2": chi2_n,
                "lam": jnp.where(startm, 1.0,
                                 jnp.where(probe_found, s["lam_p"],
                                           c["lam"])),
                "h": jnp.where(startm, 0,
                               jnp.where(probe_found, s["hp"], c["h"])),
                "it": jnp.where(c["init"], 1,
                                jnp.where(newly & (~fin_acc),
                                          c["it"] + 1, c["it"])),
                "init": jnp.zeros(B, bool),
                "pend": pend_n,
                "fin": fin_n,
                "converged": conv_n,
                "diverged": div_n,
                "done": jnp.all(fin_n),
                "iterations": c["iterations"]
                + jnp.sum(c["init"] | (newly & (~fin_acc)))
                .astype(jnp.int32),
                "accepts": c["accepts"]
                + jnp.sum(newly).astype(jnp.int32),
                "halvings": c["halvings"] + s["halv"],
                "probe_evals": c["probe_evals"] + s["pev"],
                # a rejecting full at h>0 is the re-check contradicting
                # its member's probe acceptance
                "probe_rejects": c["probe_rejects"]
                + jnp.sum(rej & (c["h"] > 0)).astype(jnp.int32),
                "step_evals": c["step_evals"] + 1,
            }
            if record:
                idx = jnp.mod(c["tn"], trace_cap)
                tr = c["trace"]
                out["trace"] = {
                    "chi2": tr["chi2"].at[idx].set(t_chi2),
                    "lam": tr["lam"].at[idx].set(lam_j),
                    "accepted": tr["accepted"].at[idx].set(newly),
                }
                out["tn"] = c["tn"] + 1
            return out

        out = jax.lax.while_loop(lambda c: ~c["done"], body, c0)
        counters = {k: out[k] for k in _BATCH_PROBE_COUNTERS}
        trace = {"n": out["tn"], **out["trace"]} if record else None
        return (out["deltas"], dict(out["info"], diverged=out["diverged"]),
                out["chi2"], out["converged"], counters, trace)

    return loop


def run_damped_batched(run, deltas0, operands, *, key, probe=None,
                       maxiter=20, min_chi2_decrease=1e-3,
                       max_step_halvings=8, kind="device_loop_batched",
                       fingerprint=None, shape=()):
    """Batched :func:`run_damped`: one launch + one fetch for the array.

    Returns ``(deltas, info, chi2, converged, counters)`` with per-
    member (B,) chi2 and converged arrays, fetched to host numpy.
    """
    return dispatch_damped_batched(
        run, deltas0, operands, key=key, probe=probe, maxiter=maxiter,
        min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind=kind,
        fingerprint=fingerprint, shape=shape).fetch()


class InFlightBatchedFit:
    """A dispatched batched fit; :meth:`fetch` is its one host sync.

    Thin per-member-typed wrapper over :class:`InFlightFit` so the
    serve pipeline gets numpy (B,) chi2/converged vectors — the batched
    loop's per-member convergence early-exit mask arrives in the same
    single fetch as the fit result.
    """

    __slots__ = ("_inner",)

    def __init__(self, inner: InFlightFit):
        self._inner = inner

    def ready(self) -> bool:
        return self._inner.ready()

    def fetch(self):
        deltas, info, chi2, converged, counters = self._inner.fetch()
        return (deltas, info, np.asarray(chi2), np.asarray(converged),
                counters)


def dispatch_damped_batched(run, deltas0, operands, *, key, probe=None,
                            maxiter=20, min_chi2_decrease=1e-3,
                            max_step_halvings=8,
                            kind="device_loop_batched", fingerprint=None,
                            shape=()) -> InFlightBatchedFit:
    """Asynchronous :func:`run_damped_batched`: launch without blocking.

    The throughput scheduler's building block (pint_tpu.serve): the
    whole fused batched loop is enqueued and the call returns
    immediately, so the host can prepare the next batch while the
    device executes this one. ``.fetch()`` on the returned handle
    performs the fit's single device->host sync.
    """
    return InFlightBatchedFit(_dispatch(
        lambda rec: build_batched_loop(run, probe, record=rec), key,
        deltas0, operands,
        (maxiter, min_chi2_decrease, max_step_halvings), kind=kind,
        fingerprint=fingerprint, shape=shape))


# ----------------------------------------------------------------------
# dense (single-device, bucketed) convenience entry points
# ----------------------------------------------------------------------

def _maybe_trace_sigma(noise, model, toas, n_target):
    """Attach the traced scaled-sigma vector to dense-fit statics when
    the EFAC-tracing frontier is on (ISSUE 10 satellite) — the
    standalone oracles then run the exact arithmetic the batched traced
    path runs, and one compiled dense program serves every white-noise
    value set of a structure."""
    from pint_tpu.fitting.gls_step import (scaled_sigma_np,
                                           sigma_traceable,
                                           trace_efac_enabled)

    if not (trace_efac_enabled() and sigma_traceable(model)):
        return noise
    return noise._replace(
        sigma=jnp.asarray(scaled_sigma_np(model, toas, n_target)))


def fingerprint_id(model) -> str:
    """Stable content id of the model structure for the dense paths'
    program fingerprints — process-independent (unlike the salted
    ``hash(model._fn_fingerprint())`` it replaced), so the persistent
    program store and the fleet shipping protocol derive identical
    keys in every worker (:mod:`pint_tpu.programs.key`)."""
    from pint_tpu.programs.key import fingerprint_id as _fid

    return _fid(model)


def dense_wls_fit(toas, model, *, maxiter=20, min_chi2_decrease=1e-3,
                  max_step_halvings=8):
    """Fused dense WLS fit: bucketed table, one program, one fetch.

    The no-mesh flavor of :func:`pint_tpu.parallel.sharded_fit
    .sharded_fit`; returns ``(deltas, info, chi2, converged, counters)``.
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting.step import jitted_wls_probe, jitted_wls_step

    toas_b = bucketing.bucket_toas(toas)
    step = jitted_wls_step(model, counted=False)
    probe = jitted_wls_probe(model)
    telemetry.set_gauge("fit.ntoas", len(toas))
    return run_damped(
        lambda d, ops: step(ops[0], d, *ops[1:]),
        model.zero_deltas(), (model.base_dd(), toas_b),
        probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
        key=("dense_wls", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind="device_loop_wls",
        fingerprint=(fingerprint_id(model),),
        shape=bucketing.toa_shape(toas_b))


def dense_wideband_fit(toas, model, *, maxiter=20, min_chi2_decrease=1e-3,
                       max_step_halvings=8):
    """Fused dense wideband fit: joint TOA+DM loop, one program/fetch.

    The standalone oracle for wideband batch members (ISSUE 8): the
    same fused-wideband step a union batch runs, at B=1 without vmap —
    with or without correlated-noise bases. Returns ``(deltas, info,
    chi2, converged, counters)``.
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting.gls_step import (build_noise_statics,
                                           pad_noise_statics)
    from pint_tpu.fitting.wideband import (build_wb_data, jitted_wb_probe,
                                           jitted_wb_step)

    noise, pl_specs = build_noise_statics(model, toas)
    n_target = bucketing.bucket_size(len(toas))
    noise = pad_noise_statics(noise, n_target)
    noise = _maybe_trace_sigma(noise, model, toas, n_target)
    dm = build_wb_data(toas, n_target)
    toas_b = bucketing.bucket_toas(toas)
    step = jitted_wb_step(model, pl_specs=pl_specs, counted=False)
    probe = jitted_wb_probe(model, pl_specs=pl_specs)
    telemetry.set_gauge("fit.ntoas", len(toas))
    return run_damped(
        lambda d, ops: step(ops[0], d, *ops[1:]),
        model.zero_deltas(), (model.base_dd(), toas_b, noise, dm),
        probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
        key=("dense_wb", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind="device_loop_wb",
        fingerprint=(fingerprint_id(model), tuple(pl_specs)),
        shape=bucketing.toa_shape(toas_b))


def dense_gls_fit(toas, model, *, maxiter=20, min_chi2_decrease=1e-3,
                  max_step_halvings=8):
    """Fused dense GLS fit (device-side noise bases): one program/fetch."""
    from pint_tpu import bucketing
    from pint_tpu.fitting.gls_step import (build_noise_statics,
                                           jitted_gls_probe,
                                           jitted_gls_step,
                                           pad_noise_statics)

    noise, pl_specs = build_noise_statics(model, toas)
    n_target = bucketing.bucket_size(len(toas))
    noise = pad_noise_statics(noise, n_target)
    noise = _maybe_trace_sigma(noise, model, toas, n_target)
    toas_b = bucketing.bucket_toas(toas)
    step = jitted_gls_step(model, pl_specs=pl_specs, counted=False)
    probe = jitted_gls_probe(model, pl_specs=pl_specs)
    telemetry.set_gauge("fit.ntoas", len(toas))
    return run_damped(
        lambda d, ops: step(ops[0], d, *ops[1:]),
        model.zero_deltas(), (model.base_dd(), toas_b, noise),
        probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
        key=("dense_gls", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings, kind="device_loop_gls",
        fingerprint=(fingerprint_id(model), pl_specs),
        shape=bucketing.toa_shape(toas_b))
