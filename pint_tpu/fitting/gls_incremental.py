"""Incremental GLS refit: rank-k updates of the noise-marginalized Schur system.

ISSUE 20 tentpole (b): correlated-noise sessions used to bypass the
incremental path entirely — every append was a full warm refit
(``serve.session.stateless``). This module extends the WLS rank-k
machinery (:mod:`pint_tpu.fitting.incremental`) to the seg-GLS system
built by :func:`pint_tpu.fitting.gls_step.gls_gram_seg`: at a converged
GLS solution the old table is fully summarized by the Cholesky factor
of the **noise-marginalized Schur complement** ``S`` over the extended
coordinates ``[offset?] + free params + Fourier coefficients`` (ECORR
epoch amplitudes eliminated, red-noise prior folded into the diagonal),
and an append of ``k`` TOAs updates it as

    S' = S + A_k^T W A_k - C_k^T d_k^-1 C_k

— the new rows' whitened Gram minus the Schur elimination of the
append's NEW ECORR epochs. The downdate term forbids the QR update
form the WLS path uses, so the step refactorizes the (small, q_B x q_B)
updated system with one fresh Cholesky per evaluation — still O(q_B^3)
against the stateless path's O(n q_B^2 + n k_F) over the whole table.

State vector ``u`` (q_B,) = [offset? (turns)] + free-param deltas +
Fourier-coefficient displacements. Three GLS-specific facts ride the
cached state beyond the WLS quartet:

* ``a`` — the Fourier coefficients solved (conditioned on the written-
  back timing solution) at snapshot time. They are never written into
  the model, so the state must carry the expansion point explicitly;
  the rank-k step updates them exactly (they are linear coordinates).
* ``t_ref`` / ``tspan`` — the Fourier basis is FROZEN at the snapshot's
  time span (:func:`pint_tpu.fitting.gls_step.fourier_design` with
  explicit reference/span): the cached ``S`` was built against that
  basis and appended rows must be evaluated in the same one. Appends
  extending the span make the frozen basis (and its prior grid)
  slightly stale — bounded by the session layer's append-count gate,
  which re-freezes the basis at every full refit.

Approximations (the session drift gates + tests/test_session.py GLS
parity pin them): the timing-coordinate gradient at the snapshot point
is dropped (the WLS incremental's documented "converged means ~zero
gradient" assumption — the offset and Fourier coordinates are solved
exactly at snapshot, so their gradient is zero by construction), and an
append's ECORR epochs are assumed NEW (an appended observation never
extends an old epoch's average — the observatory-pipeline reality the
session layer serves).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import telemetry
from pint_tpu.constants import SECS_PER_DAY
from pint_tpu.fitting.incremental import (_state_names, make_incr_rows,
                                          state_bytes)

Array = jax.Array

#: state-dict leaves cached per GLS session (superset of the WLS
#: incremental's STATE_FIELDS; see module docstring)
STATE_FIELDS = ("L", "norm", "mu", "chi2", "a", "t_ref", "tspan")


def _k_fourier(pl_specs: tuple) -> int:
    """Fourier-coefficient count of the stacked red-noise blocks."""
    return 2 * sum(int(s.nharm) for s in pl_specs)


def frozen_pl_bases(toas, pl_specs: tuple, pl_params, t_ref, tspan):
    """:func:`pint_tpu.fitting.gls_step.pl_bases` against an EXPLICIT
    (traced) reference epoch and span — the frozen-basis hook: appended
    rows must be expanded in the snapshot's basis, not one re-derived
    from their own (later) times."""
    from pint_tpu.fitting.gls_step import fourier_design, powerlaw_phi

    if not pl_specs:
        return None, None
    t_s = (toas.tdb.hi + toas.tdb.lo) * SECS_PER_DAY
    blocks, phis = [], []
    for i, spec in enumerate(pl_specs):
        F, f, df = fourier_design(t_s, spec.nharm, t_ref=t_ref,
                                  tspan=tspan)
        if spec.scale != "none":
            from pint_tpu.models.noise import DM_FREF_MHZ

            ratio = (DM_FREF_MHZ / toas.freq_mhz)[:, None]
            F = F * (jnp.square(ratio) if spec.alpha == 2.0
                     else ratio ** spec.alpha)
        blocks.append(F)
        phis.append(jnp.repeat(
            powerlaw_phi(f, pl_params[i, 0], pl_params[i, 1], df), 2))
    return jnp.concatenate(blocks, axis=1), jnp.concatenate(phis)


def make_gls_snapshot(model, params=None, pl_specs: tuple = ()):
    """Build ``snapshot(base, toas, noise) -> state`` over the FULL table.

    One :func:`gls_gram_seg` reduction at the model's current values
    (deltas = 0, immediately after a converged GLS fit wrote back),
    then: jittered Cholesky of the full Schur system ``S`` (red-noise
    prior inside, ECORR epochs eliminated), and ONE conditional solve
    of the non-timing coordinates (offset + Fourier block, timing
    pinned at the written-back solution) whose result folds into the
    absorbed mean and seeds the cached Fourier coefficients — making
    the snapshot point an exact stationary point of those coordinates.
    """
    from pint_tpu.fitting.gls_step import gls_gram_seg

    rows = make_incr_rows(model, params)
    names, off = _state_names(model, params)
    p = off + len(names)
    k_f = _k_fourier(pl_specs)

    def snapshot(base, toas, noise):
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: jnp.zeros((), jnp.float64) for k in names}
        M, resid_turns, w = rows(base, d, toas)
        sigma = 1.0 / jnp.sqrt(w)
        if off:
            mu = jnp.sum(resid_turns * w) / jnp.sum(w)
        else:
            mu = jnp.zeros((), jnp.float64)
        r = (resid_turns - mu) / f0
        t_s = (toas.tdb.hi + toas.tdb.lo) * SECS_PER_DAY
        # zero-weight padding rows replicate real TOAs (pad_toas), so
        # the frozen span is the real table's span
        t_ref = jnp.min(t_s)
        tspan = jnp.maximum(jnp.max(t_s) - t_ref, SECS_PER_DAY)
        F, phi_F = frozen_pl_bases(toas, pl_specs, noise.pl_params,
                                   t_ref, tspan)
        parts = gls_gram_seg(M, r, sigma, F, phi_F,
                             noise.epoch_idx, noise.ecorr_phi)
        S, rhs, norm = parts["S"], parts["rhs"], parts["norm"]
        qb = S.shape[0]
        S = S + jnp.eye(qb) * (jnp.finfo(jnp.float64).eps
                               * jnp.trace(S))
        L = jnp.linalg.cholesky(S)
        chi2 = parts["quad0"]
        if parts["d"].shape[0] > 0:
            chi2 = chi2 - parts["c_e"] @ (parts["c_e"] / parts["d"])
        # conditional solve of the offset + Fourier block (timing rows/
        # cols excluded: those values were written back by the fit and
        # are the expansion point by definition)
        idx = ([0] if off else []) + list(range(p, qb))
        a = jnp.zeros(k_f, jnp.float64)
        if idx:
            ix = np.asarray(idx)
            Si = S[np.ix_(ix, ix)]
            ri = rhs[ix]
            cf = jax.scipy.linalg.cho_factor(Si, lower=True)
            z = jax.scipy.linalg.cho_solve(cf, ri)
            chi2 = chi2 - z @ ri
            if off:
                mu = mu + z[0] / norm[0]
            if k_f:
                a = z[1 if off else 0:] / norm[p:]
        return {"L": L, "norm": norm, "mu": mu, "chi2": chi2, "a": a,
                "t_ref": t_ref, "tspan": tspan}

    return snapshot


def make_gls_incr_step(model, params=None, pl_specs: tuple = ()):
    """Build the fused GLS incremental full step ``full(u, operands)``.

    ``operands = (base, toas_k, state, noise_k)`` — the cached state
    plus the append bucket's own :class:`~pint_tpu.fitting.gls_step
    .NoiseStatics` (its NEW ECORR epochs and padded-dummy rows). One
    evaluation: append rows + frozen Fourier columns at the trial
    point, Schur elimination of the new epochs, rank-k refactorization
    of the marginalized system, Gauss-Newton re-solve. Same ``(new_u,
    info)`` contract as the WLS incremental step; ``info`` carries the
    full replacement state (adopt-selected to the kept point).
    """
    rows = make_incr_rows(model, params)
    names, off = _state_names(model, params)
    p = off + len(names)
    k_f = _k_fourier(pl_specs)

    def full(u, ops):
        base, toas_k, state, noise = ops
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: u[off + i] for i, k in enumerate(names)}
        M, resid_turns, w = rows(base, d, toas_k)
        rc = resid_turns - state["mu"]
        if off:
            rc = rc - u[0]
        rho = rc / f0
        if k_f:
            F, _phi = frozen_pl_bases(toas_k, pl_specs, noise.pl_params,
                                      state["t_ref"], state["tspan"])
            rho = rho - F @ (state["a"] + u[p:])
            Bt = jnp.concatenate([M, F], axis=1)
        else:
            Bt = M
        norm = state["norm"]
        A = Bt / norm
        un = norm * u
        Lu = state["L"].T @ un
        G_new = A.T @ (A * w[:, None])
        g = A.T @ (rho * w) - state["L"] @ Lu
        chi2_new = jnp.sum(jnp.square(rho) * w)
        ne = noise.ecorr_phi.shape[0]
        if ne > 0:
            def seg(x):
                return jax.ops.segment_sum(x, noise.epoch_idx,
                                           num_segments=ne + 1)[:ne]

            d_e = seg(w) + 1.0 / noise.ecorr_phi
            C = seg(A * w[:, None])
            c_e = seg(rho * w)
            G_new = G_new - C.T @ (C / d_e[:, None])
            g = g - C.T @ (c_e / d_e)
            chi2_new = chi2_new - c_e @ (c_e / d_e)
        chi2_in = state["chi2"] + jnp.sum(jnp.square(Lu)) + chi2_new
        H = state["L"] @ state["L"].T + G_new
        H = H + jnp.eye(H.shape[0]) * (jnp.finfo(jnp.float64).eps
                                       * jnp.trace(H))
        Lh = jnp.linalg.cholesky(H)
        vn = jax.scipy.linalg.cho_solve((Lh, True), g)
        cov = jax.scipy.linalg.cho_solve((Lh, True),
                                         jnp.eye(norm.shape[0]))
        new_u = u + vn / norm
        sig = jnp.sqrt(jnp.diagonal(cov)) / norm
        errors = {k: sig[off + i] for i, k in enumerate(names)}
        mu_new = state["mu"] + u[0] if off else state["mu"]
        a_new = state["a"] + u[p:] if k_f else state["a"]
        return new_u, {"chi2": chi2_in - vn @ g, "errors": errors,
                       "chi2_at_input": chi2_in, "L": Lh,
                       "mu": mu_new, "norm": norm, "a": a_new,
                       "t_ref": state["t_ref"], "tspan": state["tspan"]}

    return full


def make_gls_incr_probe(model, params=None, pl_specs: tuple = ()):
    """Residual-only judge: the step's ``chi2_at_input`` expression
    (cached quadratic + new rows' NEW-epoch-marginalized chi2) with no
    jacfwd and no factorization — the fused loop's halved-trial
    evaluator."""
    tzr = model.get_tzr_toas()
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=True)
    names, off = _state_names(model, params)
    p = off + len(names)
    k_f = _k_fourier(pl_specs)

    def probe(u, ops):
        base, toas_k, state, noise = ops
        f0 = base["F0"].hi + base["F0"].lo
        d = {k: u[off + i] for i, k in enumerate(names)}
        ph = phase_fn(base, d, toas_k)
        err = model.scaled_toa_uncertainty(toas_k)
        w = 1.0 / jnp.square(err)
        rc = (ph.frac.hi + ph.frac.lo) - state["mu"]
        if off:
            rc = rc - u[0]
        rho = rc / f0
        if k_f:
            F, _phi = frozen_pl_bases(toas_k, pl_specs, noise.pl_params,
                                      state["t_ref"], state["tspan"])
            rho = rho - F @ (state["a"] + u[p:])
        un = state["norm"] * u
        quad = jnp.sum(jnp.square(state["L"].T @ un))
        chi2_new = jnp.sum(jnp.square(rho) * w)
        ne = noise.ecorr_phi.shape[0]
        if ne > 0:
            def seg(x):
                return jax.ops.segment_sum(x, noise.epoch_idx,
                                           num_segments=ne + 1)[:ne]

            d_e = seg(w) + 1.0 / noise.ecorr_phi
            c_e = seg(rho * w)
            chi2_new = chi2_new - c_e @ (c_e / d_e)
        return state["chi2"] + quad + chi2_new

    return probe


def jitted_gls_incr_step(model, params: tuple, pl_specs: tuple):
    """Model-cache-shared :func:`make_gls_incr_step` (uncounted —
    traced into the fused loop)."""
    return model._cached_jit(
        ("gls_incr_step", tuple(params), tuple(pl_specs)),
        lambda owner: make_gls_incr_step(owner, params, pl_specs))


def jitted_gls_incr_probe(model, params: tuple, pl_specs: tuple):
    """Model-cache-shared :func:`make_gls_incr_probe`."""
    return model._cached_jit(
        ("gls_incr_probe", tuple(params), tuple(pl_specs)),
        lambda owner: make_gls_incr_probe(owner, params, pl_specs))


def jitted_gls_snapshot(model, params: tuple, pl_specs: tuple):
    """Model-cache-shared, jitted :func:`make_gls_snapshot`."""
    return model._cached_jit(
        ("gls_incr_snapshot", tuple(params), tuple(pl_specs)),
        lambda owner: jax.jit(make_gls_snapshot(owner, params, pl_specs)))


def snapshot_state(model, toas) -> dict:
    """Compute + fetch-free cached GLS state over the bucketed table.

    The GLS analogue of :func:`pint_tpu.fitting.incremental
    .snapshot_state`: one program launch, device-array state leaves,
    host metadata (``names``/``off``/``q``/``pl_specs``) riding along.
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting.gls_step import (build_noise_statics,
                                           pad_noise_statics)

    names, off = _state_names(model)
    noise, pl_specs = build_noise_statics(model, toas)
    n_target = bucketing.bucket_size(len(toas))
    noise = pad_noise_statics(noise, n_target)
    toas_b = bucketing.bucket_toas(toas)
    snap = jitted_gls_snapshot(model, tuple(names), pl_specs)
    bucketing.note_program("gls_incr_snapshot",
                           hash(model._fn_fingerprint()),
                           bucketing.toa_shape(toas_b))
    with telemetry.jit_span("incr.gls_snapshot"):
        state = snap(model.base_dd(), toas_b, noise)
    q = len(names) + off
    return {"state": state, "names": names, "off": off, "q": q,
            "pl_specs": pl_specs, "bytes": state_bytes(state)}


class InFlightGlsIncrUpdate:
    """A dispatched GLS incremental update; one fetch, state on-device.

    The :class:`pint_tpu.fitting.incremental.InFlightIncrUpdate`
    contract over the extended GLS state (:data:`STATE_FIELDS`)."""

    __slots__ = ("_inner", "_new_state", "_result")

    def __init__(self, inner):
        self._inner = inner
        self._new_state = None
        self._result = None

    def ready(self) -> bool:
        return self._inner.ready()

    def fetch(self):
        """The update's single device->host sync; idempotent."""
        if self._result is None:
            out = self._inner._out
            if out is not None:
                info_dev = out[1]
                self._new_state = {
                    "L": info_dev["L"], "norm": info_dev["norm"],
                    "mu": info_dev["mu"],
                    "chi2": info_dev["chi2_at_input"],
                    "a": info_dev["a"], "t_ref": info_dev["t_ref"],
                    "tspan": info_dev["tspan"]}
            self._result = self._inner.fetch()
        return self._result

    @property
    def new_state(self) -> dict:
        """Replacement cached state (device arrays); fetch() first."""
        if self._result is None:
            raise RuntimeError("fetch() the update before reading state")
        return self._new_state


def dispatch_gls_incremental(model, toas_append, state, *, names,
                             maxiter=20, min_chi2_decrease=1e-3,
                             max_step_halvings=8):
    """Enqueue one fused GLS rank-k update; returns an
    :class:`InFlightGlsIncrUpdate`.

    The append bucket's noise statics are built fresh (its ECORR
    epochs are NEW segments by assumption) and padded: rows to the
    append bucket, the epoch axis to the basis bucket
    (:func:`pint_tpu.bucketing.basis_bucket_size` — inert 1 s^2 dummy
    priors with zero TOA support), so every append size and epoch
    count of a structure shares one compiled program. Operand donation
    follows the WLS incremental's rule exactly (the cached state is
    replaced; accelerator backends only).
    """
    from pint_tpu import bucketing
    from pint_tpu.fitting import device_loop
    from pint_tpu.fitting.gls_step import (build_noise_statics,
                                           pad_noise_statics)

    names = tuple(names)
    _names, off = _state_names(model, names)
    noise_k, pl_specs = build_noise_statics(model, toas_append)
    has_ecorr = any(hasattr(c, "epoch_indices")
                    for c in model.components)
    k_target = bucketing.append_bucket_size(len(toas_append))
    # an ECORR structure always pads the epoch axis (floor included even
    # when this append happens to select zero epochs) so every append of
    # the structure shares one compiled program shape
    ne_target = (bucketing.basis_bucket_size(
        max(int(noise_k.ecorr_phi.shape[0]), 1)) if has_ecorr else None)
    noise_k = pad_noise_statics(noise_k, k_target, ne_target)
    toas_k = bucketing.pad_toas(toas_append, k_target) \
        if k_target != len(toas_append) else toas_append
    if device_loop._donate_operands():
        # same rule as dispatch_incremental: an exact-bucket append
        # passes the caller's own table whose buffers the session
        # keeps alive in entry.pending — donate a private copy
        toas_k = jax.tree.map(jnp.array, toas_k)
    step = jitted_gls_incr_step(model, names, pl_specs)
    probe = jitted_gls_incr_probe(model, names, pl_specs)
    qb = len(names) + off + _k_fourier(pl_specs)
    u0 = jnp.zeros(qb, jnp.float64)
    telemetry.inc("fit.incremental.gls_dispatched")
    return InFlightGlsIncrUpdate(device_loop.dispatch_damped(
        lambda u, ops: step(u, ops), u0,
        (model.base_dd(), toas_k, state, noise_k),
        probe=lambda u, ops: probe(u, ops),
        key=("gls_incr", id(step), id(probe)),
        maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
        max_step_halvings=max_step_halvings,
        kind="device_loop_gls_incr",
        fingerprint=(hash(model._fn_fingerprint()), names, pl_specs),
        shape=(k_target, qb), donate_state=True))
