"""Generalized least squares with correlated noise, and Downhill variants.

Reference equivalent: ``pint.fitter.GLSFitter`` / ``DownhillGLSFitter`` /
``DownhillWLSFitter`` (src/pint/fitter.py). The noise covariance is

    C = N + T diag(phi) T^T

with N = diag(scaled sigma^2) and T the stacked noise basis
(ECORR epochs, red-noise Fourier modes — pint_tpu.models.noise). Two
solve paths, both single jitted XLA programs:

* ``full_cov=False`` (default): extended normal equations a la the
  reference — augment the design matrix with the noise basis, put the
  prior 1/phi on the noise coefficients, solve the small
  (p+k, p+k) system by Cholesky. O(n (p+k)^2): the TPU-friendly path,
  and the one the sharded fitter reuses (Gram matrix = psum over the
  TOA axis).
* ``full_cov=True``: dense Cholesky of C (n, n) — O(n^3) reference
  path for validation.

The Downhill fitters wrap either step in the reference's damped
Gauss-Newton loop: take the step, and while chi2 got worse, halve the
step (host loop; ~few iterations).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import bucketing
from pint_tpu.fitting.fitter import Fitter, WLSFitter, wls_solve

Array = jax.Array


def _pad_gls_rows(n: int, r, sigma, M, T=None, owner=None):
    """Bucket the dense solvers' row dimension with exact zero rows.

    One compiled ``wls_solve``/``gls_solve`` per (bucket, columns)
    instead of per TOA count; zero rows contribute exactly nothing to
    any Gram/norm/chi2 term (pint_tpu.bucketing.pad_solve_rows). The
    accounting kind follows the solver actually run (``wls_solve`` when
    there is no noise basis) so the two call paths of one program share
    one key. ``owner`` (a fitter) memoizes the padded noise basis: T is
    fixed for the fitter's lifetime and O(n·k), so re-concatenating it
    on every step/probe evaluation was measurable copy traffic.
    """
    nb = bucketing.bucket_size(n)
    r, sigma, M = bucketing.pad_solve_rows(nb, r, sigma, M)
    if T is None:
        bucketing.note_program("wls_solve", None, (nb, M.shape[1]))
        return r, sigma, M, None
    bucketing.note_program("gls_solve", None, (nb, M.shape[1], T.shape[1]))
    if int(T.shape[0]) != nb:
        memo = getattr(owner, "_padded_T_memo", None) if owner else None
        if memo is not None and memo[0] is T and memo[1] == nb:
            T = memo[2]
        else:
            Tb = jnp.concatenate(
                [jnp.asarray(T),
                 jnp.zeros((nb - int(T.shape[0]), T.shape[1]))], axis=0)
            if owner is not None:
                owner._padded_T_memo = (T, nb, Tb)
            T = Tb
    return r, sigma, M, T


@jax.jit
def gls_solve(M: Array, T: Array, phi: Array, r: Array, sigma: Array) -> dict:
    """Extended-normal-equation GLS solve (Woodbury form).

    M: (n, p) timing design matrix; T: (n, k) noise basis; phi: (k,) prior
    variances; r: (n,) residuals [s]; sigma: (n,) scaled white sigmas [s].
    Returns timing deltas x (p,), their covariance, noise-coefficient
    realization, and the GLS chi2  r^T C^-1 r  at the solution.
    """
    p = M.shape[1]
    F = jnp.concatenate([M, T], axis=1)
    phiinv = jnp.concatenate([jnp.zeros(p), 1.0 / phi])

    w = 1.0 / jnp.square(sigma)
    norm = jnp.sqrt(jnp.sum(jnp.square(F) * w[:, None], axis=0))
    norm = jnp.where(norm == 0.0, 1.0, norm)
    A = F / norm
    G = A.T @ (A * w[:, None]) + jnp.diag(phiinv / jnp.square(norm))
    c = A.T @ (r * w)
    # Tikhonov floor: low red-noise harmonics are near-degenerate with the
    # spindown columns (condition ~1/eps); keeps Cholesky PD like the
    # reference's SVD threshold does for its extended-lstsq path
    G = G + jnp.eye(G.shape[0]) * (jnp.finfo(jnp.float64).eps * jnp.trace(G))
    cf = jax.scipy.linalg.cho_factor(G, lower=True)
    xn = jax.scipy.linalg.cho_solve(cf, c)
    Sigma = jax.scipy.linalg.cho_solve(cf, jnp.eye(G.shape[0]))

    x = xn / norm
    cov = Sigma / jnp.outer(norm, norm)
    # chi2 = r^T C^-1 r at the solution (Woodbury identity: the minimized
    # penalized quadratic equals r^T N^-1 r - c^T xhat)
    chi2 = jnp.sum(jnp.square(r) * w) - c @ xn
    return {"x": x[:p], "cov": cov[:p, :p], "noise_coeffs": x[p:],
            "chi2": chi2, "cov_full": cov}


@jax.jit
def gls_solve_full_cov(M: Array, T: Array, phi: Array, r: Array,
                       sigma: Array) -> dict:
    """Dense-covariance GLS: Cholesky of C = N + T phi T^T (O(n^3))."""
    p = M.shape[1]
    C = jnp.diag(jnp.square(sigma)) + (T * phi[None, :]) @ T.T
    cf = jax.scipy.linalg.cho_factor(C, lower=True)
    Cinv_M = jax.scipy.linalg.cho_solve(cf, M)
    Cinv_r = jax.scipy.linalg.cho_solve(cf, r)
    G = M.T @ Cinv_M
    c = M.T @ Cinv_r
    gf = jax.scipy.linalg.cho_factor(G, lower=True)
    x = jax.scipy.linalg.cho_solve(gf, c)
    cov = jax.scipy.linalg.cho_solve(gf, jnp.eye(p))
    chi2 = r @ Cinv_r - c @ x
    # conditional mean of the noise coefficients given the post-fit
    # residuals: a_hat = phi T^T C^-1 (r - M x)
    Cinv_post = jax.scipy.linalg.cho_solve(cf, r - M @ x)
    coeffs = phi * (T.T @ Cinv_post)
    return {"x": x, "cov": cov, "noise_coeffs": coeffs,
            "chi2": chi2, "cov_full": cov}


class GLSFitter(Fitter):
    """GLS fit with correlated noise (reference: GLSFitter.fit_toas).

    ``solve_device`` optionally places the collapsed-float64 linear
    algebra (design matrix, noise basis, solve) on a different device
    than the DD phase evaluation — the CPU/accelerator split documented
    in pint_tpu.ops.dd for backends whose float64 emulation fails
    ``dd.self_check()``.
    """

    def __init__(self, toas, model, residuals=None, track_mode=None,
                 solve_device=None):
        super().__init__(toas, model, residuals, track_mode)
        self.resids_noise: np.ndarray | None = None
        self.noise_coeffs: np.ndarray | None = None
        self.solve_device = solve_device

    def _to_solve_device(self, *arrays):
        if self.solve_device is None:
            return arrays
        return tuple(None if a is None else jax.device_put(a, self.solve_device)
                     for a in arrays)

    def _noise_arrays(self):
        # basis depends only on (model noise params, toas) — both fixed for
        # a fitter's lifetime; build once, reuse across iterations/halvings
        cache = getattr(self, "_noise_cache", None)
        if cache is not None:
            return cache
        T = self.model.noise_model_designmatrix(self.toas)
        if T is None:
            self._noise_cache = (None, None)
        else:
            phi = self.model.noise_model_basis_weight(self.toas)
            self._noise_cache = (jnp.asarray(T), jnp.asarray(phi))
        return self._noise_cache

    def fit_toas(self, maxiter: int = 1, full_cov: bool = False, **kw) -> float:
        T, phi = self._noise_arrays()
        for it in range(max(1, maxiter)):
            if it > 0:
                self.resids = self._new_resids()
            M, names = self.get_designmatrix()
            sigma = self.resids.get_errors_s()
            r = self.resids.time_resids
            # pad into LOCAL names: T persists across iterations and
            # must stay unpadded (padding it twice would grow it)
            if not full_cov:  # dense-C path stays exact-shape (O(n^2))
                r, sigma, M, Tb = _pad_gls_rows(len(self.toas), r, sigma,
                                                M, T, owner=self)
            else:
                Tb = T
            M, r, sigma, Tb, phi = self._to_solve_device(M, r, sigma, Tb, phi)
            if Tb is None:
                sol = wls_solve(M, r, sigma)
                sol = {"x": sol["x"], "cov": sol["cov"], "chi2": sol["chi2"],
                       "noise_coeffs": np.zeros(0)}
                T_np = None
            else:
                solve = gls_solve_full_cov if full_cov else gls_solve
                sol = solve(M, Tb, phi, r, sigma)
                T_np = np.asarray(Tb)
            x = np.asarray(sol["x"])
            cov = np.asarray(sol["cov"])
            self.update_model(names, x, np.sqrt(np.diag(cov)))
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
            self.noise_coeffs = np.asarray(sol["noise_coeffs"])
            if T_np is not None and self.noise_coeffs.size:
                # slice off the bucketing pad rows (user-visible waveform)
                self.resids_noise = (T_np @ self.noise_coeffs)[:len(self.toas)]
        self.resids = self._new_resids()
        final = float(np.asarray(sol["chi2"]))
        self.diverged = not np.isfinite(final)
        if self.diverged:
            from pint_tpu import telemetry

            self.diverged_reason = f"non-finite chi2 ({final})"
            telemetry.inc("fit.diverged")
        return final

    def get_noise_residuals(self) -> np.ndarray | None:
        """Realized correlated-noise waveform [s] at each TOA."""
        return self.resids_noise


class _DownhillMixin:
    """Damped Gauss-Newton loop (reference: DownhillFitter).

    Take the proposed step; while chi2 increases, halve the step. Stop
    when the chi2 decrease falls below `min_chi2_decrease`.
    """

    max_step_halvings = 8
    min_chi2_decrease = 1e-3

    def _snapshot(self) -> dict:
        return {name: (p.value, p.uncertainty)
                for name, p in self.model.params.items()}

    def _restore(self, snap: dict) -> None:
        for name, (value, unc) in snap.items():
            p = self.model[name]
            p.value = value
            p.uncertainty = unc

    def _chi2_now(self) -> float:
        self.resids = self._new_resids()
        return self._fit_chi2()

    def _fit_chi2(self) -> float:
        """chi2 of current residuals under this fitter's noise treatment."""
        raise NotImplementedError

    def fit_toas(self, maxiter: int = 20,
                 min_chi2_decrease: float | None = None, **kw) -> float:
        # same convergence-floor knob as the hybrid/sharded fitters
        # (None = the class attribute), so callers can tighten any
        # north-star fitter uniformly
        from pint_tpu import telemetry
        from pint_tpu.telemetry import recorder

        if min_chi2_decrease is not None:
            self.min_chi2_decrease = min_chi2_decrease
        self.converged = False
        self.diverged = False
        self.diverged_reason = None
        telemetry.set_gauge("fit.ntoas", len(self.toas))
        # degenerate-table guard (ISSUE 6): a table with no usable
        # weight (every TOA error non-finite or non-positive) has no
        # objective — running the solver would manufacture a chi2-0
        # "perfect fit" with zero/NaN uncertainties. Flag and return
        # without touching the model (a structured failure, never a
        # silent one).
        errs = np.asarray(self.resids.get_errors_s())
        if not np.any(np.isfinite(errs) & (errs > 0)):
            self.diverged = True
            self.diverged_reason = "all-zero-weight table (no finite " \
                                   "positive TOA uncertainty)"
            telemetry.inc("fit.diverged")
            return float("nan")
        # flight recorder: in this driver every trial IS a full chi2
        # evaluation (no residual-only probe), so each trial appends an
        # entry and halvings attach to the rejected predecessor — the
        # no-probe flavor of the damped.py/device-loop trace contract
        rec = recorder.host_trace()
        chi2 = self._chi2_now()
        if rec:
            rec.eval(chi2, 1.0)
        if not np.isfinite(chi2):
            # divergence at entry (NaN-poisoned table): flagged, model
            # untouched — mirrors the fused device loop's diverged flag
            self.diverged = True
            self.diverged_reason = f"non-finite chi2 at entry ({chi2})"
            telemetry.inc("fit.diverged")
            if rec:
                rec.emit("dense_downhill")
            return float(chi2)
        for _ in range(max(1, maxiter)):
            telemetry.inc("fit.iterations")
            snap = self._snapshot()
            with telemetry.jit_span("fit.step"):
                x, names, errors, cov = self._step(**kw)
            lam = 1.0
            best_chi2 = chi2
            applied = False
            saw_finite = False
            for _h in range(self.max_step_halvings):
                if _h > 0:
                    telemetry.inc("fit.halvings")
                    if rec:
                        rec.halving()
                self._restore(snap)
                self.update_model(names, lam * x, errors)
                new_chi2 = self._chi2_now()
                saw_finite = saw_finite or bool(np.isfinite(new_chi2))
                if rec:
                    rec.eval(new_chi2, lam)
                if new_chi2 <= best_chi2 + 1e-12:
                    applied = True
                    telemetry.inc("fit.accepts")
                    if rec:
                        rec.accept()
                    break
                lam *= 0.5
            if not applied:
                # no downhill step found: restore and stop. When every
                # trial chi2 was non-finite the solver produced garbage
                # (NaN step from a degenerate solve), not an optimum —
                # that is divergence, not convergence
                self._restore(snap)
                self._chi2_now()
                if not saw_finite:
                    self.diverged = True
                    self.diverged_reason = ("step produced non-finite "
                                            "chi2 at every damping level")
                    break
                self.converged = True
                break
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
            if chi2 - new_chi2 < self.min_chi2_decrease:
                chi2 = new_chi2
                self.converged = True
                break
            chi2 = new_chi2
        if self.diverged:
            telemetry.inc("fit.diverged")
        else:
            telemetry.inc("fit.converged" if self.converged
                          else "fit.maxiter_exhausted")
        if rec:
            rec.emit("dense_downhill")
        return chi2

    def _step(self, **kw):
        raise NotImplementedError


class DownhillWLSFitter(_DownhillMixin, WLSFitter):
    """Reference: DownhillWLSFitter."""

    def _fit_chi2(self) -> float:
        return self.resids.chi2

    def _step(self, threshold: float | None = None, **kw):
        M, names = self.get_designmatrix()
        r, sigma, M, _ = _pad_gls_rows(len(self.toas),
                                       self.resids.time_resids,
                                       self.resids.get_errors_s(), M)
        sol = wls_solve(M, r, sigma, threshold)
        cov = np.asarray(sol["cov"])
        return np.asarray(sol["x"]), names, np.sqrt(np.diag(cov)), cov


class DownhillGLSFitter(_DownhillMixin, GLSFitter):
    """Reference: DownhillGLSFitter."""

    def _fit_chi2(self) -> float:
        T, phi = self._noise_arrays()
        if T is None:
            return self.resids.chi2
        # GLS chi2 of current residuals: r^T C^-1 r via the Woodbury
        # identity with a zero-column design matrix
        r, sigma, M0, T = _pad_gls_rows(
            len(self.toas), self.resids.time_resids,
            self.resids.get_errors_s(), jnp.zeros((len(self.toas), 0)), T,
            owner=self)
        sol = gls_solve(M0, T, phi, r, sigma)
        return float(np.asarray(sol["chi2"]))

    def _step(self, full_cov: bool = False, **kw):
        T, phi = self._noise_arrays()
        M, names = self.get_designmatrix()
        sigma = self.resids.get_errors_s()
        r = self.resids.time_resids
        if not full_cov:
            r, sigma, M, T = _pad_gls_rows(len(self.toas), r, sigma, M, T,
                                            owner=self)
        if T is None:
            sol = wls_solve(M, r, sigma)
        else:
            solve = gls_solve_full_cov if full_cov else gls_solve
            sol = solve(M, T, phi, r, sigma)
            self.noise_coeffs = np.asarray(sol["noise_coeffs"])
            if self.noise_coeffs.size:
                self.resids_noise = (np.asarray(T)
                                     @ self.noise_coeffs)[:len(self.toas)]
        cov = np.asarray(sol["cov"])
        return np.asarray(sol["x"]), names, np.sqrt(np.diag(cov)), cov
