"""Fitting layer: weighted/generalized least squares on device.

Reference equivalent: ``pint.fitter`` (src/pint/fitter.py).
"""

from pint_tpu.fitting import device_loop  # noqa: F401
from pint_tpu.fitting.fitter import Fitter, WLSFitter  # noqa: F401
from pint_tpu.fitting.gls import (  # noqa: F401
    DownhillGLSFitter, DownhillWLSFitter, GLSFitter)
from pint_tpu.fitting.gls_step import (  # noqa: F401
    NoiseStatics, build_noise_statics, gls_solve_seg, make_gls_step)
from pint_tpu.fitting.wideband import (  # noqa: F401
    WidebandDownhillFitter, WidebandTOAFitter, WidebandTOAResiduals)
