"""Fitting layer: weighted/generalized least squares on device.

Reference equivalent: ``pint.fitter`` (src/pint/fitter.py).
"""

from pint_tpu.fitting.fitter import Fitter, WLSFitter  # noqa: F401
