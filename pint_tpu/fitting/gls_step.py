"""One-shot jitted GLS fit step with device-side noise bases.

This is the north-star path (SURVEY.md §5, §3.3: the reference's
``GLSFitter.fit_toas`` recast for a TOA-sharded device mesh). The
correlated-noise covariance is

    C = N + T diag(phi) T^T,    T = [F_red | F_dm | U_ecorr]

and the solve is the extended normal equations — but unlike
``pint_tpu.fitting.gls``, nothing of size (n, k) is built on the host:

* **Fourier bases** (PLRedNoise / PLDMNoise) are computed inside the
  jitted step from the traced TOA table — an outer product of the
  (sharded) TDB times with the harmonic frequencies. Only the
  per-device shard of each (n, 2*nharm) block ever exists.
* **ECORR** is never materialized at all. Its quantization-basis columns
  are disjoint 0/1 indicators, so the epoch block of the extended Gram
  matrix is *diagonal* and every cross term is a
  ``jax.ops.segment_sum`` over the (sharded) TOA axis — XLA partitions
  the scatter-adds and inserts the psum, exactly like the dense Gram
  products.
* The epoch block is then eliminated analytically (Schur complement on
  a diagonal block), leaving a small (p + 2*sum(nharm))^2 system solved
  by replicated Cholesky.

Cost per iteration: O(n (p + k_F)^2 / n_devices) flops + one
psum of a (p + k_F)^2 matrix — independent of the number of ECORR
epochs. At 6e5 TOAs this removes the ~20 GB host basis the dense path
would need (VERDICT.md weakness 5).

Reference: src/pint/fitter.py :: GLSFitter (upstream pointer — see
SURVEY.md provenance warning); src/pint/models/noise_model.py for the
basis conventions.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.constants import SECS_PER_DAY
from pint_tpu.models.noise import FYR_HZ

Array = jax.Array


class PLSpec(NamedTuple):
    """Static (shape-determining) description of one power-law component.

    The amplitude/index live in ``NoiseStatics.pl_params`` as *traced*
    values, so one compiled step serves every pulsar sharing a model
    structure (the PTA path batches dozens of pulsars through it).
    """

    scale: str        # "none" (achromatic) | "dm" (nu^-2) | "chrom" (nu^-alpha)
    nharm: int
    alpha: float = 2.0  # chromatic index (used when scale != "none")


class NoiseStatics(NamedTuple):
    """Per-dataset noise data passed through jit alongside the TOA table.

    ``epoch_idx`` rides the TOA axis (shard it with the table);
    ``ecorr_phi``/``pl_params`` are tiny and replicated. A pulsar-batched
    (B, n) / (B, ne) version works under ``vmap`` unchanged.

    ``sigma`` (ISSUE 10 satellite, the PR-8 residue) optionally carries
    the EFAC/EQUAD-scaled per-TOA uncertainties [s] as a TRACED (n,)
    operand: when present, the GLS/wideband steps read it instead of
    ``model.scaled_toa_uncertainty`` — whose EFAC/EQUAD values are
    host-side trace constants that would otherwise split compiled
    programs per white-noise value set. ``None`` (the default, and the
    only value under the ``PINT_TPU_TRACE_EFAC=0`` kill switch) keeps
    the pinned-constant behavior bit-for-bit.
    """

    epoch_idx: Array  # (n,) int32 in [0, ne]; ne = "no epoch" dummy
    ecorr_phi: Array  # (ne,) prior variances [s^2]
    pl_params: Array  # (n_pl, 2) [log10_amp, gamma] per PLSpec entry
    sigma: Array | None = None  # (n,) scaled uncertainties [s], or None
    #: (ISSUE 14 satellite, the PR-10 residue) optionally carries the
    #: DMEFAC/DMEQUAD-scaled wideband DM uncertainties [pc/cm^3] as a
    #: TRACED (n,) operand: when present, the wideband step/probe read
    #: it instead of applying ``ScaleDmError.scale_dm_sigma`` — whose
    #: DMEFAC/DMEQUAD values are host-side trace constants that split
    #: compiled programs per value set. ``None`` (the default, and the
    #: only value under ``PINT_TPU_TRACE_DMEFAC=0``) keeps the
    #: pinned-constant behavior bit-for-bit. Ignored by narrowband
    #: steps (they never read DM errors).
    dm_sigma: Array | None = None  # (n,) scaled DM sigmas, or None


def trace_efac_enabled() -> bool:
    """EFAC/EQUAD-tracing gate (read per call so tests can flip it):
    ``PINT_TPU_TRACE_EFAC=0`` pins white-noise values as trace
    constants again (the PR-8 behavior, in which mixed-EFAC traffic
    splits compiled programs and serve batches)."""
    from pint_tpu import config

    return config.env_on("PINT_TPU_TRACE_EFAC")


def trace_dmefac_enabled() -> bool:
    """DMEFAC/DMEQUAD-tracing gate (ISSUE 14 satellite; mirrors
    ``trace_efac_enabled``): ``PINT_TPU_TRACE_DMEFAC=0`` pins wideband
    DM-error scaling values as trace constants again, in which
    mixed-DMEFAC wideband traffic splits compiled programs and serve
    batches."""
    from pint_tpu import config

    return config.env_on("PINT_TPU_TRACE_DMEFAC")


def scaled_sigma_np(model, toas, n_target: int | None = None) -> np.ndarray:
    """Numpy mirror of ``model.scaled_toa_uncertainty`` (+ padding).

    The batch-prep path computes one (n,) scaled-uncertainty vector per
    member on the host — eager jnp ops here would cost an XLA dispatch
    per selector per member (the ``stack_toas`` lesson), so the
    EFAC/EQUAD formula (``scale * sqrt(sigma^2 + equad^2)``, the
    reference convention) is applied in numpy. ``n_target`` extends the
    result the way ``bucketing.pad_toas`` + in-trace scaling would:
    padding rows replicate the LAST row's selector masks with
    ``PAD_ERROR_US`` uncertainty, so the traced vector is elementwise
    what the pinned path computes on the padded table.
    """
    from pint_tpu.bucketing import PAD_ERROR_US
    from pint_tpu.models.parameter import toa_mask

    sigma = np.asarray(toas.error_us, dtype=np.float64) * 1e-6
    k = 0 if n_target is None else n_target - len(sigma)
    if k < 0:
        raise ValueError(f"n_target {n_target} < ntoas {len(sigma)}")
    if k:
        sigma = np.concatenate([sigma, np.full(k, PAD_ERROR_US * 1e-6)])

    def mask_of(selector):
        m = np.asarray(toa_mask(selector, toas), dtype=np.float64)
        if k:
            m = np.concatenate([m, np.full(k, m[-1])])
        return m

    var = np.square(sigma)
    scale = np.ones_like(sigma)
    for c in model.components:
        if not getattr(c, "is_noise_scale", False):
            continue
        for name in getattr(c, "equad_names", ()):
            p = c.param(name)
            var = var + mask_of(p.selector) * (p.value_f64 * 1e-6) ** 2
        for name in getattr(c, "tneq_names", ()):
            p = c.param(name)
            var = var + mask_of(p.selector) * 10.0 ** (2.0 * p.value_f64)
        for name in getattr(c, "efac_names", ()):
            p = c.param(name)
            scale = np.where(mask_of(p.selector) != 0.0, p.value_f64,
                             scale)
    return scale * np.sqrt(var)


def sigma_traceable(model) -> bool:
    """Can this model's white-noise scaling ride the traced ``sigma``?

    Exactly one noise-scale component: with several, the reference
    applies them SEQUENTIALLY (each rescales the previous output) and
    the one-shot numpy mirror above would reassociate the chain. Zero
    components need no tracing at all (the raw errors are already a
    traced table leaf)."""
    return sum(1 for c in model.components
               if getattr(c, "is_noise_scale", False)) == 1


def scaled_dm_sigma_np(model, toas, n_target: int | None = None
                       ) -> np.ndarray:
    """Numpy mirror of ``model.scaled_dm_uncertainty`` (+ padding).

    The DMEFAC/DMEQUAD analogue of :func:`scaled_sigma_np` (ISSUE 14
    satellite): one (n,) scaled wideband DM-uncertainty vector per
    member on the host, reproducing ``ScaleDmError.scale_dm_sigma``
    applied to the raw ``-pp_dme`` errors. ``n_target`` extends the
    result the way ``wideband.build_wb_data`` pads: appended rows carry
    ``DM_PAD_ERROR`` uncertainty with the LAST row's selector masks, so
    the traced vector is elementwise what the pinned path computes on
    the padded DM block.
    """
    from pint_tpu.fitting.wideband import DM_PAD_ERROR
    from pint_tpu.models.parameter import toa_mask

    sigma = np.asarray(toas.get_dm_errors(), dtype=np.float64)
    k = 0 if n_target is None else n_target - len(sigma)
    if k < 0:
        raise ValueError(f"n_target {n_target} < ntoas {len(sigma)}")
    if k:
        sigma = np.concatenate([sigma, np.full(k, DM_PAD_ERROR)])

    def mask_of(selector):
        m = np.asarray(toa_mask(selector, toas), dtype=np.float64)
        if k:
            m = np.concatenate([m, np.full(k, m[-1])])
        return m

    var = np.square(sigma)
    scale = np.ones_like(sigma)
    for c in model.components:
        if not hasattr(c, "scale_dm_sigma"):
            continue
        for name in getattr(c, "dmequad_names", ()):
            p = c.param(name)
            var = var + mask_of(p.selector) * p.value_f64 ** 2
        for name in getattr(c, "dmefac_names", ()):
            p = c.param(name)
            scale = np.where(mask_of(p.selector) != 0.0, p.value_f64,
                             scale)
    return scale * np.sqrt(var)


def dm_sigma_traceable(model) -> bool:
    """Can this model's DM-error scaling ride the traced ``dm_sigma``?
    Exactly one ``ScaleDmError``-shaped component (the
    :func:`sigma_traceable` rule: a chain would be reassociated by the
    one-shot mirror); zero needs no tracing — the raw ``-pp_dme``
    errors already ride the traced ``dm`` block."""
    return sum(1 for c in model.components
               if hasattr(c, "scale_dm_sigma")) == 1


def build_noise_statics(model, toas, *, as_numpy: bool = False
                        ) -> tuple[NoiseStatics, tuple[PLSpec, ...]]:
    """Host-side scan of the model's noise components.

    Returns the (device-array) ECORR epoch assignment + power-law
    hyperparameters, plus the static specs the jitted step closes over.
    O(n) host work — no (n, k) basis is formed. ``as_numpy=True`` keeps
    the leaves numpy (the batch-prep path stacks per-member statics on
    the host and device-places the stack ONCE; materializing jnp arrays
    here would transfer every member's epoch vector twice — the
    ``stack_toas`` lesson).
    """
    n = len(toas)
    epoch_idx = None
    phi_e = np.zeros(0)
    specs: list[PLSpec] = []
    pl_params: list[tuple[float, float]] = []
    for c in model.components:
        if hasattr(c, "epoch_indices"):
            if epoch_idx is not None:
                raise ValueError("multiple ECORR components in one model")
            epoch_idx, phi_e = c.epoch_indices(toas)
        elif hasattr(c, "pl_spec"):
            if hasattr(c, "refresh_from_model"):
                c.refresh_from_model(model)
            scale, log10_amp, gamma, nharm, alpha = c.pl_spec()
            specs.append(PLSpec(scale, nharm, alpha))
            pl_params.append((log10_amp, gamma))
    if epoch_idx is None:
        epoch_idx = np.zeros(n, dtype=np.int32)  # ne=0: everything is dummy
    from pint_tpu import telemetry

    telemetry.set_gauge("noise.ecorr_epochs", len(phi_e))
    telemetry.set_gauge("noise.pl_components", len(specs))
    if as_numpy:
        return (NoiseStatics(
            np.asarray(epoch_idx, dtype=np.int32),
            np.asarray(phi_e, dtype=np.float64),
            np.asarray(pl_params,
                       dtype=np.float64).reshape(len(specs), 2)),
            tuple(specs))
    return (NoiseStatics(jnp.asarray(epoch_idx), jnp.asarray(phi_e),
                         jnp.asarray(pl_params).reshape(len(specs), 2)),
            tuple(specs))


def pad_noise_statics(noise: NoiseStatics, n_target: int,
                      ne_target: int | None = None) -> NoiseStatics:
    """Extend epoch_idx to `n_target` rows pointing at the dummy segment.

    ``ne_target`` (the batchable-frontier basis bucket,
    :func:`pint_tpu.bucketing.basis_bucket_size`) additionally pads the
    ECORR epoch axis: the dummy segment index moves from ``ne`` to
    ``ne_target`` and the appended prior entries are 1.0 s^2 with zero
    TOA support — exactly inert in the segment-sum Schur solve (see
    :func:`pint_tpu.bucketing.pad_basis_cols`), so batches over
    different epoch counts share one compiled program.
    """
    # array-namespace-agnostic: numpy statics (the batch-prep path —
    # one device transfer at shard time) pad in numpy, device statics
    # pad on-device
    xp = np if isinstance(noise.epoch_idx, np.ndarray) else jnp
    n = int(np.shape(noise.epoch_idx)[0])
    ne = int(np.shape(noise.ecorr_phi)[0])
    epoch_idx, phi = noise.epoch_idx, noise.ecorr_phi
    if ne_target is not None and ne_target != ne:
        from pint_tpu.bucketing import pad_basis_cols

        # remap the dummy segment (== ne) to the padded dummy slot;
        # real epochs 0..ne-1 are unchanged
        epoch_idx = xp.where(xp.asarray(epoch_idx) == ne,
                             xp.int32(ne_target),
                             xp.asarray(epoch_idx, xp.int32))
        (phi,) = pad_basis_cols(ne_target, phi)
        phi = xp.asarray(phi)
        ne = ne_target
    sigma = noise.sigma
    dm_sigma = noise.dm_sigma
    if n_target != n:
        pad = xp.full(n_target - n, ne, dtype=xp.int32)
        epoch_idx = xp.concatenate([xp.asarray(epoch_idx, xp.int32),
                                    pad])
        if sigma is not None and int(np.shape(sigma)[0]) == n:
            # zero-weight padding rows: the pinned path would scale the
            # PAD sigma by the last row's EFAC, a 1e-24-relative weight
            # detail already inside the padding contract's round-off
            from pint_tpu.bucketing import PAD_ERROR_US

            sigma = xp.concatenate([
                xp.asarray(sigma),
                xp.full(n_target - n, PAD_ERROR_US * 1e-6)])
        if dm_sigma is not None and int(np.shape(dm_sigma)[0]) == n:
            # same rule for the DM block: pad rows at DM_PAD_ERROR
            # weight (the build_wb_data convention; the last row's
            # DMEFAC on a 1e12 sigma is round-off below the contract)
            from pint_tpu.fitting.wideband import DM_PAD_ERROR

            dm_sigma = xp.concatenate([
                xp.asarray(dm_sigma),
                xp.full(n_target - n, DM_PAD_ERROR)])
    if (epoch_idx is noise.epoch_idx and phi is noise.ecorr_phi
            and sigma is noise.sigma and dm_sigma is noise.dm_sigma):
        return noise
    return NoiseStatics(epoch_idx, phi, noise.pl_params, sigma, dm_sigma)


def stack_noise_statics(statics: list[NoiseStatics], n_target: int,
                        ne_target: int) -> NoiseStatics:
    """Stack per-member statics along a leading batch axis.

    Every member is padded to (``n_target`` rows, ``ne_target`` epoch
    columns) first — the batched GLS/wideband steps vmap over the
    result: epoch_idx (B, n), ecorr_phi (B, ne), pl_params (B, n_pl, 2).
    Numpy leaves (the caller device-places them with the batch mesh).
    """
    padded = [pad_noise_statics(s, n_target, ne_target) for s in statics]
    for leaf in ("sigma", "dm_sigma"):
        if any(getattr(s, leaf) is not None for s in padded) \
                and not all(getattr(s, leaf) is not None for s in padded):
            raise ValueError(f"mixed traced/pinned {leaf} across a "
                             "batch; attach it to every member or none")
    return NoiseStatics(
        np.stack([np.asarray(s.epoch_idx) for s in padded]),
        np.stack([np.asarray(s.ecorr_phi) for s in padded]),
        np.stack([np.asarray(s.pl_params) for s in padded]),
        (np.stack([np.asarray(s.sigma) for s in padded])
         if padded and padded[0].sigma is not None else None),
        (np.stack([np.asarray(s.dm_sigma) for s in padded])
         if padded and padded[0].dm_sigma is not None else None))


def fourier_design(t_s: Array, nharm: int, t_ref=None, tspan=None
                   ) -> tuple[Array, Array, Array]:
    """In-jit Fourier basis: (F (n, 2*nharm), f (nharm,) Hz, df Hz).

    Columns interleave sin/cos per harmonic (matching
    pint_tpu.models.noise._PLNoiseBase._fourier). f_j = j / T_span with
    T_span from the traced times — under TOA-axis sharding the min/max
    are XLA collectives; zero-weight padding rows replicate real TOAs so
    they cannot perturb the span. Pass explicit ``t_ref``/``tspan``
    [s] for a basis coherent *across* datasets (the PTA GW basis must
    share one reference epoch and frequency grid for every pulsar).
    """
    if t_ref is None:
        t_ref = jnp.min(t_s)
    if tspan is None:
        tspan = jnp.maximum(jnp.max(t_s) - t_ref, SECS_PER_DAY)
    f = jnp.arange(1, nharm + 1, dtype=jnp.float64) / tspan
    # direct trig: an angle-addition scan (2 transcendentals/TOA) was
    # measured NOT faster at 600k TOAs — the (n, 2k) basis build is
    # memory-bound, and the scan's transpose traffic eats the savings
    arg = 2.0 * jnp.pi * (t_s - t_ref)[:, None] * f[None, :]
    F = jnp.stack([jnp.sin(arg), jnp.cos(arg)], axis=-1)
    return F.reshape(t_s.shape[0], 2 * nharm), f, 1.0 / tspan


def powerlaw_phi(f: Array, log10_amp, gamma, df) -> Array:
    """Per-bin variances [s^2] of a power-law PSD (GWB convention)."""
    amp = 10.0 ** log10_amp
    return (amp * amp / (12.0 * jnp.pi ** 2) * FYR_HZ ** (-3.0)
            * (f / FYR_HZ) ** (-gamma) * df)


def pl_bases(toas, specs: tuple[PLSpec, ...], pl_params: Array
             ) -> tuple[Array | None, Array | None]:
    """Stacked Fourier blocks (n, k_F) and prior variances (k_F,), in-jit.

    ``pl_params[i] = [log10_amp, gamma]`` (traced) pairs with specs[i].
    """
    if not specs:
        return None, None
    t_s = (toas.tdb.hi + toas.tdb.lo) * SECS_PER_DAY
    blocks, phis = [], []
    for i, spec in enumerate(specs):
        F, f, df = fourier_design(t_s, spec.nharm)
        if spec.scale != "none":
            from pint_tpu.models.noise import DM_FREF_MHZ

            ratio = (DM_FREF_MHZ / toas.freq_mhz)[:, None]
            F = F * (jnp.square(ratio) if spec.alpha == 2.0
                     else ratio ** spec.alpha)
        blocks.append(F)
        phis.append(jnp.repeat(
            powerlaw_phi(f, pl_params[i, 0], pl_params[i, 1], df), 2))
    return jnp.concatenate(blocks, axis=1), jnp.concatenate(phis)


def gls_gram_seg(M: Array, r: Array, sigma: Array,
                 F: Array | None, phi_F: Array | None,
                 epoch_idx: Array, phi_e: Array) -> dict:
    """The O(n)/O(ne) reduction of the seg-GLS solve.

    Everything that touches the (sharded) TOA axis: whitened Gram
    matrix, ECORR segment sums, Schur elimination of the diagonal epoch
    block. Returns the small Schur system plus the pieces
    :func:`gls_finalize_seg` needs — S/rhs are (q, q)/(q,), C is
    (ne, q). Split out so the hybrid fitter can run this part on the
    accelerator and the (tiny) Cholesky finalize wherever it is
    numerically safe.
    """
    p = M.shape[1]
    if F is not None:
        B = jnp.concatenate([M, F], axis=1)
        phiinv_B = jnp.concatenate([jnp.zeros(p), 1.0 / phi_F])
    else:
        B = M
        phiinv_B = jnp.zeros(p)
    q = B.shape[1]
    w = 1.0 / jnp.square(sigma)

    norm = jnp.sqrt(jnp.sum(jnp.square(B) * w[:, None], axis=0))
    norm = jnp.where(norm == 0.0, 1.0, norm)
    A = B / norm
    G_BB = A.T @ (A * w[:, None]) + jnp.diag(phiinv_B / jnp.square(norm))
    c_B = A.T @ (r * w)

    ne = phi_e.shape[0]
    if ne > 0:
        def seg(x):
            return jax.ops.segment_sum(x, epoch_idx, num_segments=ne + 1)[:ne]

        d = seg(w) + 1.0 / phi_e          # diagonal epoch block of the Gram
        C = seg(A * w[:, None])           # (ne, q) cross block U^T W A
        c_e = seg(r * w)
        S = G_BB - C.T @ (C / d[:, None])
        rhs = c_B - C.T @ (c_e / d)
    else:
        d = jnp.ones(0)
        C = jnp.zeros((0, q))
        c_e = jnp.zeros(0)
        S, rhs = G_BB, c_B
    return {"S": S, "rhs": rhs, "c_B": c_B, "norm": norm,
            "quad0": jnp.sum(jnp.square(r) * w), "C": C, "c_e": c_e, "d": d}


def gls_solve_normalized(parts: dict) -> dict:
    """Cholesky solve of the Schur system, entirely in normalized units.

    Every input and output here is O(1)-to-O(chi2)-scaled — the design
    block arrives whitened with unit columns (see
    :func:`gls_gram_whitened`), so S, rhs, xB, Sigma and chi2 all sit
    comfortably inside float32 dynamic *range*. That makes this function
    safe to run on an accelerator whose emulated f64 carries f32 range
    (the TPU): only the un-normalization (x = xB/norm,
    cov = Sigma/norm·normᵀ — entries down to ~1e-42) must happen on a
    full-range device, and it is O(q²) host work.
    """
    S, rhs = parts["S"], parts["rhs"]
    q = S.shape[0]
    S = S + jnp.eye(q) * (jnp.finfo(jnp.float64).eps * jnp.trace(S))
    cf = jax.scipy.linalg.cho_factor(S, lower=True)
    xB = jax.scipy.linalg.cho_solve(cf, rhs)
    Sigma = jax.scipy.linalg.cho_solve(cf, jnp.eye(q))
    chi2 = parts["quad0"] - parts["c_B"] @ xB
    if parts["d"].shape[0] > 0:
        x_e = (parts["c_e"] - parts["C"] @ xB) / parts["d"]
        chi2 = chi2 - parts["c_e"] @ x_e
    else:
        x_e = jnp.zeros(0)
    return {"xB": xB, "Sigma": Sigma, "chi2": chi2, "x_e": x_e}


def noise_marginal_chi2(parts: dict, p: int) -> Array:
    """GLS chi2 of the *input* residuals: r^T C^-1 r, timing params fixed.

    The dense fitters get this via a zero-column design matrix
    (``DownhillGLSFitter._fit_chi2``); here it falls out of the Schur
    system already built by :func:`gls_gram_seg`: restricting the
    quadratic form to the noise columns (p:) commutes with the ECORR
    elimination (the epoch block's Schur complement is formed
    column-by-column), so the noise-only system is exactly
    ``S[p:, p:] x = rhs[p:]``. One tiny extra Cholesky — this is what a
    damped (Downhill) outer loop needs to judge a proposed step, fused
    into the same XLA program as the step itself.
    """
    S, rhs = parts["S"], parts["rhs"]
    q = S.shape[0]
    k = q - p
    chi2 = parts["quad0"]
    if k > 0:
        Sn = S[p:, p:]
        Sn = Sn + jnp.eye(k) * (jnp.finfo(jnp.float64).eps * jnp.trace(Sn))
        cf = jax.scipy.linalg.cho_factor(Sn, lower=True)
        xn = jax.scipy.linalg.cho_solve(cf, rhs[p:])
        chi2 = chi2 - parts["c_B"][p:] @ xn
        if parts["d"].shape[0] > 0:
            x_e = (parts["c_e"] - parts["C"][:, p:] @ xn) / parts["d"]
            chi2 = chi2 - parts["c_e"] @ x_e
    elif parts["d"].shape[0] > 0:
        chi2 = chi2 - parts["c_e"] @ (parts["c_e"] / parts["d"])
    return chi2


def gls_finalize_seg(parts: dict, p: int) -> dict:
    """Normalized solve + un-normalization to physical parameter units.

    ``p`` (static) is the timing-parameter count — the first p columns
    of the extended system. Jittable; O(q^3) + O(ne q) — negligible next
    to the Gram reduction, so it can run on whichever device has
    trustworthy f64 Cholesky.
    """
    sol = gls_solve_normalized(parts)
    norm = parts["norm"]
    x = sol["xB"] / norm
    cov = sol["Sigma"] / jnp.outer(norm, norm)
    return {"x": x[:p], "cov": cov[:p, :p], "chi2": sol["chi2"],
            "fourier_coeffs": x[p:], "ecorr_coeffs": sol["x_e"]}


def gls_gram_whitened(A_M: Array, rw: Array, sw: Array, norm_M: Array,
                      F: Array | None, phi_F: Array | None,
                      epoch_idx: Array, phi_e: Array,
                      *, mxu: bool = False) -> dict:
    """Gram reduction from pre-whitened inputs, range-safe for TPU f64.

    The TPU's emulated float64 carries float32 *dynamic range* (observed
    on TPU v5e round 2, artifact pending: ``sum(M^2 w)`` at
    ~1e40 overflows to inf/NaN for spin-derivative
    design columns). This variant therefore takes the whitening done on
    the CPU — ``A_M = M sqrt(w) / ||M sqrt(w)||`` (unit columns),
    ``rw = r sqrt(w)``, ``sw = sqrt(w)`` — and keeps every on-chip
    intermediate below ~1e17. Algebraically identical to
    :func:`gls_gram_seg`; composed with the same
    :func:`gls_finalize_seg`.

    ``mxu=True`` computes the two O(n q^2)/O(ne q^2) matmuls (the Gram
    and the ECORR Schur term) as double-single f32 MXU products
    (:func:`pint_tpu.ops.mxu.ds32_gram`, ~1e-7 relative) while the
    gradient c_B, the segment sums and everything O(n q) stay exact f64
    — the Gauss-Newton fixed point is unchanged, only the step operator
    is approximate. ``mxu="pallas"`` additionally routes the square
    Grams through the hand-tiled TPU kernel
    (:mod:`pint_tpu.ops.pallas_gram`).
    """
    if mxu:
        from pint_tpu.ops.mxu import ds32_gram
    use_pallas = mxu == "pallas"
    p = A_M.shape[1]
    if F is not None:
        Fw = F * sw[:, None]
        norm_F = jnp.sqrt(jnp.sum(jnp.square(Fw), axis=0))
        norm_F = jnp.where(norm_F == 0.0, 1.0, norm_F)
        A = jnp.concatenate([A_M, Fw / norm_F], axis=1)
        norm = jnp.concatenate([norm_M, norm_F])
        # floor keeps 1/phi inside the f32 exponent range; 1e-36 s^2 is
        # 1e-18 s rms — physically nothing. The prior diagonal is built
        # from norm_F ONLY and by sequential division: norm_M can be
        # ~1e21+ (spin-derivative columns) and squaring it overflows the
        # chip's f32-range f64 (timing columns carry no prior anyway).
        phiinv = 1.0 / jnp.maximum(phi_F, 1e-36)
        diag_prior = jnp.concatenate(
            [jnp.zeros(p), phiinv / norm_F / norm_F])
    else:
        A = A_M
        norm = norm_M
        diag_prior = jnp.zeros(p)
    q = A.shape[1]

    gram = ((lambda X: ds32_gram(X, use_pallas=use_pallas)) if mxu
            else (lambda X: X.T @ X))
    G_BB = gram(A) + jnp.diag(diag_prior)
    c_B = A.T @ rw

    ne = phi_e.shape[0]
    if ne > 0:
        def seg(x):
            return jax.ops.segment_sum(x, epoch_idx, num_segments=ne + 1)[:ne]

        d = seg(jnp.square(sw)) + 1.0 / phi_e
        C = seg(A * sw[:, None])
        c_e = seg(rw * sw)
        Cs = C * jax.lax.rsqrt(d)[:, None]
        S = G_BB - gram(Cs)
        rhs = c_B - C.T @ (c_e / d)
    else:
        d = jnp.ones(0)
        C = jnp.zeros((0, q))
        c_e = jnp.zeros(0)
        S, rhs = G_BB, c_B
    return {"S": S, "rhs": rhs, "c_B": c_B, "norm": norm,
            "quad0": jnp.sum(jnp.square(rw)), "C": C, "c_e": c_e, "d": d}


def gls_solve_seg(M: Array, r: Array, sigma: Array,
                  F: Array | None, phi_F: Array | None,
                  epoch_idx: Array, phi_e: Array) -> dict:
    """Extended-normal-equation GLS with the ECORR block eliminated.

    M: (n, p) timing design matrix; F/phi_F: stacked Fourier noise block
    and its priors (or None); epoch_idx/phi_e: ECORR epoch assignment
    (idx == ne means "no epoch"). All n-axis inputs may be sharded; the
    output is replicated. Matches ``pint_tpu.fitting.gls.gls_solve`` to
    float64 roundoff (tests/test_sharded_gls.py). Composed from
    :func:`gls_gram_seg` + :func:`gls_finalize_seg` (XLA fuses them
    when jitted together).
    """
    return gls_finalize_seg(gls_gram_seg(M, r, sigma, F, phi_F,
                                         epoch_idx, phi_e), M.shape[1])


def make_gls_step(model, tzr=None, *, abs_phase: bool = True,
                  pl_specs: tuple[PLSpec, ...] = (),
                  masked: bool = False, params: list[str] | None = None,
                  traced_tzr: bool = False):
    """Build ``step(base, deltas, toas, noise[, mask][, tzr]) ->
    (new_deltas, info)``.

    The GLS analogue of ``pint_tpu.fitting.step.make_wls_step``: one call
    is a full Gauss-Newton GLS iteration — residuals, jacfwd design
    matrix, in-jit noise bases, extended-normal-equation solve with
    segment-sum ECORR — as a single pure function of the (shardable) TOA
    table and noise statics. ``info`` carries the GLS chi2 at the
    solution (the linearized post-fit value, the reference GLSFitter's
    convention) and per-parameter uncertainties.

    ``masked`` / ``params`` / ``traced_tzr`` mirror
    :func:`pint_tpu.fitting.step.make_wls_step` exactly — they are what
    lets the throughput scheduler's union batches carry GLS members
    (ISSUE 8): ``mask`` zeroes design-matrix columns of parameters a
    member does not fit (a zero column is exactly inert: its normalized
    Gram row reduces to the diagonal jitter and its gradient entry is
    0, so it solves to a zero delta), and ``traced_tzr`` anchors each
    vmapped member at its own stacked one-row TZR table.
    """
    from pint_tpu.fitting.step import _circular_recenter

    if tzr is None and abs_phase and not traced_tzr:
        tzr = model.get_tzr_toas()
    anchorless = tzr is None and not traced_tzr
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase,
                                   traced_tzr=traced_tzr)
    names = params if params is not None else model.free_params
    # explicit PHOFF replaces the implicit offset column + mean
    # subtraction (see TimingModel.designmatrix)
    has_phoff = model.has_component("PhaseOffset")
    off = 0 if has_phoff else 1

    def step(base, deltas, toas, noise: NoiseStatics, mask=None,
             tzr_toas=None):
        f0 = base["F0"].hi + base["F0"].lo

        def total_phase(d):
            ph = (phase_fn(base, d, toas, tzr_toas) if traced_tzr
                  else phase_fn(base, d, toas))
            # one DD pipeline trace serves residual + jacobian via
            # has_aux (guarded primal keeps the residual bitwise — see
            # make_whiten_stage1); a separate residual evaluation
            # doubled the program's op count and compile time
            return (ph.int_part + (ph.frac.hi + ph.frac.lo),
                    ph.frac.hi + ph.frac.lo)

        # traced white-noise scaling (ISSUE 10 satellite): when the
        # statics carry per-TOA scaled sigmas, EFAC/EQUAD values never
        # enter the trace — mixed-value traffic shares one program
        err = (noise.sigma if noise.sigma is not None
               else model.scaled_toa_uncertainty(toas))
        w = 1.0 / jnp.square(err)

        J, resid_turns = jax.jacfwd(total_phase, has_aux=True)(deltas)
        if anchorless:
            resid_turns = _circular_recenter(resid_turns, w)
        if not has_phoff:
            resid_turns = resid_turns - jnp.sum(resid_turns * w) / jnp.sum(w)
        r = resid_turns / f0

        cols = [] if has_phoff else [jnp.ones_like(r) / f0]
        for k in names:
            col = -J[k] / f0
            if mask is not None:
                col = col * mask[k]
            cols.append(col)
        M = jnp.stack(cols, axis=1)

        F, phi_F = pl_bases(toas, pl_specs, noise.pl_params)
        parts = gls_gram_seg(M, r, err, F, phi_F,
                             noise.epoch_idx, noise.ecorr_phi)
        sol = gls_finalize_seg(parts, M.shape[1])
        new_deltas = {k: deltas[k] + sol["x"][i + off]
                      for i, k in enumerate(names)}
        sig = jnp.sqrt(jnp.diagonal(sol["cov"]))
        errors = {k: sig[i + off] for i, k in enumerate(names)}
        return new_deltas, {"chi2": sol["chi2"], "errors": errors,
                            "chi2_at_input":
                                noise_marginal_chi2(parts, M.shape[1]),
                            "fourier_coeffs": sol["fourier_coeffs"],
                            "ecorr_coeffs": sol["ecorr_coeffs"]}

    # fixed positional signatures per config (vmap in_axes need exact
    # arity; mirrors make_wls_step's wrapper convention)
    if not masked:
        if traced_tzr:
            def step_unmasked_tzr(base, deltas, toas, noise, tzr_toas):
                return step(base, deltas, toas, noise, None, tzr_toas)

            return step_unmasked_tzr

        def step_unmasked(base, deltas, toas, noise):
            return step(base, deltas, toas, noise)

        return step_unmasked
    return step


def jitted_gls_step(model, *, pl_specs: tuple[PLSpec, ...] = (),
                    abs_phase: bool = True, masked: bool = False,
                    params: list[str] | None = None,
                    vmapped: bool = False, traced_tzr: bool = False,
                    counted: bool = True):
    """Jitted :func:`make_gls_step`, shared across fitter instances.

    Same rationale as :func:`pint_tpu.fitting.step.jitted_wls_step`:
    ``jax.jit(make_gls_step(model, ...))`` compiles per closure object,
    so every new sharded/hybrid fitter over the same model structure
    repays the full XLA compile. Routed through
    ``TimingModel._cached_jit`` instead — one program per (structure
    fingerprint, pl_specs, step config); values flow through the traced
    ``base`` and the traced ``NoiseStatics``. ``vmapped`` builds the
    batched (pulsar-axis) variant the union batches run — every
    argument, the noise statics included, gains a leading (B,) axis.
    ``counted=False`` skips the execution-counter wrapper (device-loop
    callers trace the step into a larger program).
    """
    from pint_tpu.fitting.step import _counted_step

    key = ("gls_step", pl_specs, abs_phase, masked,
           tuple(params) if params is not None else None, vmapped,
           traced_tzr)

    def build(owner):
        fn = make_gls_step(owner, pl_specs=pl_specs, abs_phase=abs_phase,
                           masked=masked, params=params,
                           traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        n_args = 4 + (1 if masked else 0) + (1 if traced_tzr else 0)
        return jax.vmap(fn, in_axes=(0,) * n_args)

    cached = model._cached_jit(key, build)
    if not counted:
        return cached
    return _counted_step(cached, key, model)


def make_gls_probe(model, tzr=None, *, abs_phase: bool = True,
                   pl_specs: tuple[PLSpec, ...] = (),
                   traced_tzr: bool = False):
    """Build ``probe(base, deltas, toas, noise) -> chi2`` — the
    noise-marginal GLS chi2 at ``deltas`` WITHOUT a design matrix.

    One residual-only phase pass (no jacfwd tangents; the shared
    :func:`pint_tpu.fitting.step.make_resid_fn` convention) + the Schur
    noise-column system of :func:`gls_gram_seg` restricted to zero
    timing columns — algebraically the same value
    :func:`noise_marginal_chi2` extracts from the full step's parts
    (restriction to the noise block commutes with the ECORR
    elimination), to XLA-reordering round-off. The fused device loop
    judges halved trials with this; a probe-accepted point is re-judged
    by the full step's authoritative value.
    """
    from pint_tpu.fitting.step import make_resid_fn

    resid = make_resid_fn(model, tzr, abs_phase=abs_phase,
                          traced_tzr=traced_tzr)

    if traced_tzr:
        def probe_tzr(base, deltas, toas, noise, tzr_toas):
            r, err, _w = resid(base, deltas, toas, tzr_toas,
                               err=noise.sigma)
            F, phi_F = pl_bases(toas, pl_specs, noise.pl_params)
            parts = gls_gram_seg(jnp.zeros((r.shape[0], 0)), r, err, F,
                                 phi_F, noise.epoch_idx, noise.ecorr_phi)
            return noise_marginal_chi2(parts, 0)

        return probe_tzr

    def probe(base, deltas, toas, noise: NoiseStatics):
        r, err, _w = resid(base, deltas, toas, err=noise.sigma)
        F, phi_F = pl_bases(toas, pl_specs, noise.pl_params)
        parts = gls_gram_seg(jnp.zeros((r.shape[0], 0)), r, err, F, phi_F,
                             noise.epoch_idx, noise.ecorr_phi)
        return noise_marginal_chi2(parts, 0)

    return probe


def jitted_gls_probe(model, *, pl_specs: tuple[PLSpec, ...] = (),
                     abs_phase: bool = True, traced_tzr: bool = False,
                     vmapped: bool = False):
    """Model-cache-shared :func:`make_gls_probe` (uncounted; traced into
    the fused device loop, never dispatched on its own)."""
    key = ("gls_probe", pl_specs, abs_phase, traced_tzr, vmapped)

    def build(owner):
        fn = make_gls_probe(owner, pl_specs=pl_specs,
                            abs_phase=abs_phase, traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        return jax.vmap(fn, in_axes=(0,) * (4 + (1 if traced_tzr else 0)))

    return model._cached_jit(key, build)
