"""Least-squares fitters: parameter estimation from timing residuals.

Reference equivalent: ``pint.fitter`` (src/pint/fitter.py :: Fitter,
WLSFitter; GLS and Downhill variants arrive with the noise layer). The
fit loop is the reference's (SURVEY.md §3.3) recast for TPU:

1. residual + design-matrix evaluation is one jitted function of the
   base parameter dict (toas closed over as XLA constants; double-double
   phase, float64 Jacobian via ``jacfwd``);
2. the whitened least-squares solve (column-normalized SVD with singular
   value thresholding, exactly the reference's scheme) runs on device;
3. the host applies the solved deltas to the DD base values *exactly*
   and re-iterates — so float64 linear algebra never erodes longdouble-
   grade parameter state.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.residuals import Residuals

Array = jax.Array


@partial(jax.jit, static_argnames=("threshold",))
def wls_solve(M: Array, r: Array, werr: Array,
              threshold: float | None = None) -> dict:
    """Whitened, column-normalized SVD least squares.

    M: (n, p) design matrix [s/unit]; r: (n,) residuals [s]; werr: (n,)
    per-TOA uncertainties [s]; `threshold` is the relative singular-value
    cutoff (default eps*n, the reference WLSFitter's SVD conditioning).
    Returns deltas, covariance, post-fit chi2.
    """
    sw = 1.0 / werr
    A = M * sw[:, None]
    b = r * sw
    norm = jnp.linalg.norm(A, axis=0)
    norm = jnp.where(norm == 0.0, 1.0, norm)
    A = A / norm
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    rel = threshold if threshold is not None else jnp.finfo(jnp.float64).eps * A.shape[0]
    tol = rel * jnp.max(s)
    sinv = jnp.where(s > tol, 1.0 / jnp.where(s > tol, s, 1.0), 0.0)
    x = (Vt.T * sinv) @ (U.T @ b)
    x = x / norm
    cov = (Vt.T * sinv**2) @ Vt / jnp.outer(norm, norm)
    post = b - (A * norm) @ (x)
    return {"x": x, "cov": cov, "chi2": jnp.sum(jnp.square(post)),
            "singular_values": s}


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas / summaries."""

    def __init__(self, toas, model, residuals: Residuals | None = None,
                 track_mode: str | None = None):
        self.toas = toas
        self.model = model
        self.track_mode = track_mode
        self.resids_init = residuals or Residuals(toas, model, track_mode=track_mode)
        self.resids: Residuals = self.resids_init
        self.parameter_covariance_matrix: np.ndarray | None = None
        self.fit_params: list[str] = []
        self.converged = False

    # -- reference: pint.fitter.Fitter.auto ----------------------------
    @staticmethod
    def auto(toas, model, downhill: bool = True):
        """Pick the appropriate fitter subclass for the model (reference:
        Fitter.auto chooses WLS/GLS/Wideband x Downhill by model content)."""
        has_noise_basis = any(
            getattr(c, "is_noise_basis", False) for c in model.components
        )
        if has_noise_basis:
            from pint_tpu.fitting import gls as _gls

            return _gls.GLSFitter(toas, model)
        return WLSFitter(toas, model)

    def update_model(self, names: list[str], deltas: np.ndarray,
                     errors: np.ndarray) -> None:
        for name, d, e in zip(names, deltas, errors):
            if name == "Offset":
                continue
            p = self.model[name]
            p.add_delta(float(d))
            p.uncertainty = float(e)

    def get_designmatrix(self):
        return self.model.designmatrix(self.toas)

    def fit_toas(self, maxiter: int = 1, **kw) -> float:  # pragma: no cover
        raise NotImplementedError

    # -- reference: pint.fitter.Fitter.get_summary ----------------------
    def get_summary(self, nodmx: bool = True) -> str:
        out = [f"Fitted model using {type(self).__name__}",
               f"  pulsar: {self.model.name}",
               f"  TOAs:   {len(self.toas)}",
               f"  chi2:   {self.resids.chi2:.4f} / dof {self.resids.dof} "
               f"= {self.resids.reduced_chi2:.4f}",
               f"  wrms:   {self.resids.rms_weighted_s() * 1e6:.4f} us", ""]
        out.append(f"{'PAR':<12}{'value':>24}{'uncertainty':>16}  units")
        for name, p in self.model.params.items():
            if not p.is_numeric:
                continue
            if nodmx and name.startswith("DMX"):
                continue
            flag = "" if p.frozen else "*"
            out.append(
                f"{name + flag:<12}{p.format_value():>24}"
                f"{p.format_uncertainty() if p.uncertainty else '':>16}  {p.units}"
            )
        return "\n".join(out)


class WLSFitter(Fitter):
    """Weighted least squares, no correlated noise (reference: WLSFitter)."""

    def fit_toas(self, maxiter: int = 1, threshold: float | None = None) -> float:
        """Iterate (residuals -> design matrix -> solve -> update); returns chi2."""
        chi2 = self.resids.chi2
        for it in range(max(1, maxiter)):
            if it > 0:  # self.resids is already current on entry
                self.resids = Residuals(self.toas, self.model,
                                        track_mode=self.track_mode)
            M, names = self.get_designmatrix()
            err = self.resids.get_errors_s()
            sol = wls_solve(M, self.resids.time_resids, err, threshold)
            x = np.asarray(sol["x"])
            cov = np.asarray(sol["cov"])
            errors = np.sqrt(np.diag(cov))
            self.update_model(names, x, errors)
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
        self.resids = Residuals(self.toas, self.model, track_mode=self.track_mode)
        self.converged = abs(self.resids.chi2 - chi2) < 1e-8 * max(1.0, chi2)
        return self.resids.chi2
