"""Least-squares fitters: parameter estimation from timing residuals.

Reference equivalent: ``pint.fitter`` (src/pint/fitter.py :: Fitter,
WLSFitter; GLS and Downhill variants arrive with the noise layer). The
fit loop is the reference's (SURVEY.md §3.3) recast for TPU:

1. residual + design-matrix evaluation is one jitted function of the
   base parameter dict (toas closed over as XLA constants; double-double
   phase, float64 Jacobian via ``jacfwd``);
2. the whitened least-squares solve (column-normalized SVD with singular
   value thresholding, exactly the reference's scheme) runs on device;
3. the host applies the solved deltas to the DD base values *exactly*
   and re-iterates — so float64 linear algebra never erodes longdouble-
   grade parameter state.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.residuals import Residuals

Array = jax.Array


@partial(jax.jit, static_argnames=("threshold",))
def wls_solve(M: Array, r: Array, werr: Array,
              threshold: float | None = None) -> dict:
    """Whitened, column-normalized SVD least squares.

    M: (n, p) design matrix [s/unit]; r: (n,) residuals [s]; werr: (n,)
    per-TOA uncertainties [s]; `threshold` is the relative singular-value
    cutoff (default eps*n, the reference WLSFitter's SVD conditioning).
    Returns deltas, covariance, post-fit chi2.
    """
    sw = 1.0 / werr
    A = M * sw[:, None]
    b = r * sw
    norm = jnp.linalg.norm(A, axis=0)
    norm = jnp.where(norm == 0.0, 1.0, norm)
    A = A / norm
    U, s, Vt = jnp.linalg.svd(A, full_matrices=False)
    rel = threshold if threshold is not None else jnp.finfo(jnp.float64).eps * A.shape[0]
    tol = rel * jnp.max(s)
    sinv = jnp.where(s > tol, 1.0 / jnp.where(s > tol, s, 1.0), 0.0)
    x = (Vt.T * sinv) @ (U.T @ b)
    x = x / norm
    cov = (Vt.T * sinv**2) @ Vt / jnp.outer(norm, norm)
    post = b - (A * norm) @ (x)
    return {"x": x, "cov": cov, "chi2": jnp.sum(jnp.square(post)),
            "singular_values": s}


@jax.jit
def wls_solve_gram(M: Array, r: Array, werr: Array) -> dict:
    """Normal-equation WLS via the (p, p) Gram matrix.

    The sharding-friendly form (SURVEY.md §5): with the TOA axis of M and
    r sharded over a device mesh, ``M^T W M`` and ``M^T W r`` are sharded
    matmuls whose tiny (p, p)/(p,) outputs XLA reduces with a ``psum``
    over ICI; the Cholesky solve then runs replicated on every device.
    Column normalization keeps the Gram matrix conditioned (the dense-SVD
    path `wls_solve` remains the single-device reference).
    """
    w = 1.0 / jnp.square(werr)
    norm = jnp.sqrt(jnp.sum(jnp.square(M) * w[:, None], axis=0))
    norm = jnp.where(norm == 0.0, 1.0, norm)
    A = M / norm
    G = A.T @ (A * w[:, None])
    c = A.T @ (r * w)
    # Tikhonov floor keeps Cholesky PD under degenerate columns
    G = G + jnp.eye(G.shape[0]) * (jnp.finfo(jnp.float64).eps * jnp.trace(G))
    L, low = jax.scipy.linalg.cho_factor(G, lower=True)
    x = jax.scipy.linalg.cho_solve((L, low), c)
    cov = jax.scipy.linalg.cho_solve((L, low), jnp.eye(G.shape[0]))
    post = r - A @ x
    chi2 = jnp.sum(jnp.square(post) * w)
    return {"x": x / norm, "cov": cov / jnp.outer(norm, norm), "chi2": chi2}


class Fitter:
    """Base fitter: holds (toas, model), exposes fit_toas / summaries."""

    resid_cls = Residuals  # subclass hook (wideband overrides)

    def __init__(self, toas, model, residuals: Residuals | None = None,
                 track_mode: str | None = None):
        self.toas = toas
        self.model = model
        self.track_mode = track_mode
        self.resids_init = residuals or self._new_resids()
        self.resids = self.resids_init
        self.parameter_covariance_matrix: np.ndarray | None = None
        self.fit_params: list[str] = []
        self.converged = False
        # structured-failure flags (ISSUE 6): a fit that produced a
        # non-finite chi2 or ran on a degenerate table is FLAGGED, never
        # silently "converged" — the serve layer maps this to its
        # diverged/quarantined statuses
        self.diverged = False
        self.diverged_reason: str | None = None

    def _new_resids(self):
        return self.resid_cls(self.toas, self.model, track_mode=self.track_mode)

    # -- reference: pint.fitter.Fitter.auto ----------------------------
    @staticmethod
    def auto(toas, model, downhill: bool = True):
        """Pick the appropriate fitter subclass for the model (reference:
        Fitter.auto chooses WLS/GLS/Wideband x Downhill by model content)."""
        from pint_tpu.fitting import gls as _gls

        wideband = getattr(toas, "is_wideband", lambda: False)()
        if wideband:
            from pint_tpu.fitting import wideband as _wb

            return (_wb.WidebandDownhillFitter(toas, model) if downhill
                    else _wb.WidebandTOAFitter(toas, model))
        has_noise_basis = any(
            getattr(c, "is_noise_basis", False) for c in model.components
        )
        if has_noise_basis:
            return (_gls.DownhillGLSFitter(toas, model) if downhill
                    else _gls.GLSFitter(toas, model))
        return (_gls.DownhillWLSFitter(toas, model) if downhill
                else WLSFitter(toas, model))

    def update_model(self, names: list[str], deltas: np.ndarray,
                     errors: np.ndarray) -> None:
        for name, d, e in zip(names, deltas, errors):
            if name == "Offset":
                continue
            p = self.model[name]
            p.add_delta(float(d))
            p.uncertainty = float(e)

    def get_designmatrix(self):
        return self.model.designmatrix(self.toas)

    # -- labeled-matrix reporting (reference: pint.pint_matrix /
    #    Fitter.parameter_correlation_matrix) ---------------------------
    def get_covariance_matrix(self):
        """Labeled parameter covariance (after fit_toas)."""
        from pint_tpu.matrix import CovarianceMatrix

        return CovarianceMatrix.from_fitter(self)

    def get_parameter_correlation_matrix(self, pretty_print: bool = False):
        """Labeled correlation matrix; optionally print the lower triangle."""
        corr = self.get_covariance_matrix().to_correlation_matrix()
        if pretty_print:
            print(corr.prettyprint())
        return corr

    def get_fit_report(self) -> dict:
        """Machine-readable fit summary (json-able).

        The structured counterpart of :meth:`get_summary` the round-1
        review asked for (reference exposes only the text summary):
        pipelines log/compare this dict instead of parsing the table.
        """
        r = self.resids
        params = {}
        for name, p in self.model.params.items():
            if not p.is_numeric:
                continue
            params[name] = {
                "value": p.value_f64,
                "uncertainty": p.uncertainty or 0.0,
                "units": p.units,
                "frozen": p.frozen,
                "fitted": name in self.fit_params,
            }
        return {
            "pulsar": self.model.name,
            "fitter": type(self).__name__,
            "ntoas": len(self.toas),
            "chi2": float(r.chi2),
            "dof": int(r.dof),
            "reduced_chi2": float(r.reduced_chi2),
            "wrms_us": float(r.rms_weighted_s() * 1e6),
            "converged": bool(self.converged),
            "fit_params": list(self.fit_params),
            "params": params,
        }

    def get_derived_params(self) -> dict:
        """Derived quantities with first-order propagated uncertainties.

        Reference: pint.fitter.Fitter.get_derived_params — spin-derived
        (period, age, B field, Edot) plus binary mass function when a
        binary model is present. Uncertainties propagate linearly from
        the fitted parameter uncertainties (jacfwd of each scalar
        derived function would be equivalent; these are simple enough
        for closed forms).
        """
        from pint_tpu import derived_quantities as dq

        out: dict[str, tuple[float, float]] = {}
        p = self.model.params
        f0 = p["F0"].value_f64
        s0 = p["F0"].uncertainty or 0.0
        out["P0_s"] = (dq.pulsar_period_s(f0), s0 / f0 ** 2)
        if "F1" in p and p["F1"].is_numeric:
            f1 = p["F1"].value_f64
            s1 = p["F1"].uncertainty or 0.0
            # P1 = -F1/F0^2: absolute partials (valid at F1 == 0 too)
            p1 = dq.period_derivative(f0, f1)
            out["P1"] = (p1, np.hypot(s1 / f0 ** 2,
                                      2.0 * f1 * s0 / f0 ** 3))
            if f1 < 0:
                # age = -F0/(2 F1): d ln age = d ln F0 - d ln F1
                age = dq.pulsar_age_yr(f0, f1)
                out["age_yr"] = (age, age * np.hypot(s0 / f0, s1 / f1))
                # B ~ sqrt(-F1) * F0^(-3/2):
                # d ln B = 0.5 d ln(-F1) - 1.5 d ln F0
                B = dq.pulsar_B_gauss(f0, f1)
                out["B_surface_G"] = (B, B * np.hypot(
                    0.5 * s1 / f1, 1.5 * s0 / f0))
                # Edot ~ F0 * F1: d ln E = d ln F0 + d ln F1
                E = dq.pulsar_edot_erg_s(f0, f1)
                out["Edot_erg_s"] = (E, E * np.hypot(
                    s0 / f0, s1 / f1))
        if "PB" in p and "A1" in p:
            pb, a1 = p["PB"].value_f64, p["A1"].value_f64
            spb = p["PB"].uncertainty or 0.0
            sa1 = p["A1"].uncertainty or 0.0
            fm = dq.mass_funct_msun(pb, a1)
            out["mass_function_Msun"] = (fm, fm * np.hypot(
                3.0 * sa1 / a1 if a1 else 0.0,
                2.0 * spb / pb if pb else 0.0))
            out["companion_mass_min_Msun"] = (
                dq.companion_mass_msun(pb, a1, inc_rad=np.pi / 2), 0.0)
        return out

    def fit_toas(self, maxiter: int = 1, **kw) -> float:  # pragma: no cover
        raise NotImplementedError

    # -- reference: pint.fitter.Fitter.get_summary ----------------------
    def get_summary(self, nodmx: bool = True) -> str:
        out = [f"Fitted model using {type(self).__name__}",
               f"  pulsar: {self.model.name}",
               f"  TOAs:   {len(self.toas)}",
               f"  chi2:   {self.resids.chi2:.4f} / dof {self.resids.dof} "
               f"= {self.resids.reduced_chi2:.4f}",
               f"  wrms:   {self.resids.rms_weighted_s() * 1e6:.4f} us", ""]
        out.append(f"{'PAR':<12}{'value':>24}{'uncertainty':>16}  units")
        for name, p in self.model.params.items():
            if not p.is_numeric:
                continue
            if nodmx and name.startswith("DMX"):
                continue
            if p.frozen and p.kind == "float" and not np.isfinite(p.value_f64):
                # unset alternate-convention params (e.g. RNAMP when the
                # model uses TNRED*): as_parfile skips them; so does the
                # summary table
                continue
            flag = "" if p.frozen else "*"
            out.append(
                f"{name + flag:<12}{p.format_value():>24}"
                f"{p.format_uncertainty() if p.uncertainty else '':>16}  {p.units}"
            )
        return "\n".join(out)


class WLSFitter(Fitter):
    """Weighted least squares, no correlated noise (reference: WLSFitter)."""

    def fit_toas(self, maxiter: int = 1, threshold: float | None = None) -> float:
        """Iterate (residuals -> design matrix -> solve -> update); returns chi2."""
        from pint_tpu import telemetry

        telemetry.set_gauge("fit.ntoas", len(self.toas))
        chi2 = self.resids.chi2
        for it in range(max(1, maxiter)):
            telemetry.inc("fit.iterations")
            if it > 0:  # self.resids is already current on entry
                self.resids = self._new_resids()
            with telemetry.jit_span("fit.wls_iter"):
                M, names = self.get_designmatrix()
                err = self.resids.get_errors_s()
                # bucketed solve shape (exact zero rows — bucketing doc)
                from pint_tpu import bucketing

                nb = bucketing.bucket_size(len(self.toas))
                r, err, M = bucketing.pad_solve_rows(
                    nb, self.resids.time_resids, err, M)
                bucketing.note_program("wls_solve", None, (nb, M.shape[1]))
                sol = wls_solve(M, r, err, threshold)
                x = np.asarray(sol["x"])
            cov = np.asarray(sol["cov"])
            errors = np.sqrt(np.diag(cov))
            self.update_model(names, x, errors)
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
        self.resids = self._new_resids()
        final = self.resids.chi2
        self.diverged = not np.isfinite(final)
        if self.diverged:
            self.diverged_reason = f"non-finite chi2 ({final})"
            telemetry.inc("fit.diverged")
        self.converged = (not self.diverged
                          and abs(final - chi2) < 1e-8 * max(1.0, chi2))
        return final
