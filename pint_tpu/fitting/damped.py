"""Damped Gauss-Newton outer loop over a fused fit step.

Host-side driver shared by the north-star fitters
(:class:`pint_tpu.parallel.sharded_fit.ShardedWLSFitter` /
``ShardedGLSFitter`` and :class:`pint_tpu.fitting.hybrid.HybridGLSFitter`):
the same accept / halve / converge semantics as the dense
``_DownhillMixin`` (reference: src/pint/fitter.py :: DownhillFitter,
SURVEY §2.3), but expressed over a *fused step function* — one call
evaluates the chi2 at the input parameters AND proposes a Gauss-Newton
step, so judging a trial point costs exactly one device program instead
of a separate residual pass.

The step contract: ``iterate(deltas) -> (new_deltas, info)`` where
``info["chi2_at_input"]`` is the (noise-marginalized, for GLS) chi2 of
the residuals at ``deltas`` and ``new_deltas`` is the proposed full
step from there.  The driver never needs residuals on the host.

``chi2_at(deltas) -> float`` is an optional cheap probe evaluating ONLY
``chi2_at_input`` (no design matrix, no solve). When provided, halved
trial points are judged with it instead of the full fused step — the
round-4 verdict's clawback: a rejected trial used to pay a full jacfwd
design-matrix build whose output was discarded.  The first (lam=1)
trial still uses the full step, because acceptance there is the common
case and its proposal is needed anyway, so a convergent fit that never
halves pays zero extra programs; a probe-accepted point is then
re-evaluated once with the full step to obtain the next proposal.
"""

from __future__ import annotations

import math

from pint_tpu import telemetry
from pint_tpu.telemetry import recorder


def downhill_iterate(iterate, deltas0: dict, *, maxiter: int = 20,
                     min_chi2_decrease: float = 1e-3,
                     max_step_halvings: int = 8, chi2_at=None):
    """Run a damped Gauss-Newton loop; returns (deltas, info, chi2, converged).

    Take the proposed step; while chi2 increases, halve it.  Stop when
    no downhill step exists (converged at a minimum of the linearized
    model) or the decrease falls below ``min_chi2_decrease``.  ``info``
    is the step output evaluated *at the returned deltas* (so its
    errors / covariance / noise coefficients are current); ``chi2`` is
    the actual chi2 there, not the linearized prediction.

    Telemetry: every full-step evaluation runs under a ``fit.step``
    span (first-in-process call = the compile span — every step
    function blocks on its outputs, so span walls are honest) and every
    probe under ``fit.probe``; the loop events feed the ``fit.*``
    counters (iterations / accepts / halvings / probe_evals /
    probe_rejects / converged / maxiter_exhausted) that make damping
    behavior auditable from the rollup.

    Flight recorder (``telemetry.recorder``): the driver records one
    trace entry per FULL evaluation — the same entry semantics as the
    fused device loop's on-device ring, so the oracle and the fused
    program emit identical traces for the same fit (pinned by
    tests/test_device_loop.py).
    """
    rec = recorder.host_trace()
    with telemetry.jit_span("fit.step"):
        new_deltas, info = iterate(deltas0)
    chi2 = float(info["chi2_at_input"])
    if rec:
        rec.eval(chi2, 1.0)
    deltas = deltas0
    converged = False
    # divergence mirror of the fused device loop (ISSUE 6): the first
    # non-finite FULL evaluation terminates the fit at the last kept
    # point with ``diverged`` flagged in info, converged False
    diverged = not math.isfinite(chi2)
    for _ in (() if diverged else range(max(1, maxiter))):
        telemetry.inc("fit.iterations")
        dx = {k: new_deltas[k] - deltas[k] for k in deltas}
        lam, applied = 1.0, False
        trial = trial_new = trial_info = None
        for _h in range(max_step_halvings):
            if _h > 0:
                telemetry.inc("fit.halvings")
                if rec:
                    rec.halving()
            trial = {k: deltas[k] + lam * dx[k] for k in deltas}
            if _h == 0 or chi2_at is None:
                with telemetry.jit_span("fit.step"):
                    trial_new, trial_info = iterate(trial)
                trial_chi2 = float(trial_info["chi2_at_input"])
                if rec:
                    rec.eval(trial_chi2, lam)
                if not math.isfinite(trial_chi2):
                    diverged = True
                    break
            else:
                telemetry.inc("fit.probe_evals")
                trial_new = trial_info = None
                with telemetry.jit_span("fit.probe"):
                    trial_chi2 = float(chi2_at(trial))
                if rec:
                    rec.probe_eval()
            if trial_chi2 <= chi2 + 1e-12:
                if trial_info is None:
                    # accepted via the cheap probe: one full evaluation
                    # at the accepted point supplies the next proposal
                    # and current info. Its chi2 is AUTHORITATIVE — the
                    # probe is a different XLA program (and under the
                    # mxu path the full program's Gram is double-single
                    # while the probe's is f64), so when the full value
                    # contradicts the acceptance, keep halving instead
                    # of applying an uphill step.
                    with telemetry.jit_span("fit.step"):
                        trial_new, trial_info = iterate(trial)
                    trial_chi2 = float(trial_info["chi2_at_input"])
                    if rec:
                        rec.eval(trial_chi2, lam)
                    if not math.isfinite(trial_chi2):
                        diverged = True
                        break
                    if trial_chi2 > chi2 + 1e-12:
                        telemetry.inc("fit.probe_rejects")
                        lam *= 0.5
                        continue
                applied = True
                telemetry.inc("fit.accepts")
                if rec:
                    rec.accept()
                break
            lam *= 0.5
        if diverged:
            break
        if not applied:
            # no downhill direction left: we are at (numerical) optimum
            converged = True
            break
        decrease = chi2 - trial_chi2
        deltas, chi2 = trial, trial_chi2
        new_deltas, info = trial_new, trial_info
        if decrease < min_chi2_decrease:
            converged = True
            break
    if diverged:
        telemetry.inc("fit.diverged")
    else:
        telemetry.inc("fit.converged" if converged
                      else "fit.maxiter_exhausted")
    if rec:
        rec.emit()
    return deltas, dict(info, diverged=diverged), chi2, converged


def downhill_iterate_pipelined(step_dispatch, step_fetch, probe_dispatch,
                               probe_fetch, deltas0: dict, *,
                               maxiter: int = 20,
                               min_chi2_decrease: float = 1e-3,
                               max_step_halvings: int = 8):
    """:func:`downhill_iterate` with speculative probe pipelining.

    For split fitters whose full step is (host stage) -> (asynchronous
    accelerator stage) -> (blocking fetch) — the hybrid CPU-DD fitter —
    the loop cannot be fused on-device (stage 1 must run on the host),
    but the sync structure still leaves the host idle while the chip
    executes stage 2. This driver overlaps that window: when a full
    step for trial ``lam`` is dispatched, the CPU probe of the NEXT
    halved candidate (``lam/2`` — known before the full result, since
    it depends only on the current proposal) is dispatched speculatively
    while the accelerator works. A rejected trial then finds its probe
    already evaluated (the halving path pays zero probe latency); an
    accepted one discards it (counted ``fit.probe_spec_wasted`` — CPU
    cycles spent inside the accelerator's execution window).

    The accept/halve/converge semantics and the judged-event counters
    (``fit.iterations/accepts/halvings/probe_evals/probe_rejects``) are
    IDENTICAL to :func:`downhill_iterate` — speculation changes when
    work is dispatched, never what is judged (parity pinned by
    tests/test_device_loop.py).

    Contract: ``step_dispatch(deltas) -> handle`` starts a full step
    without blocking, ``step_fetch(handle) -> (new_deltas, info)``
    blocks; same for ``probe_dispatch``/``probe_fetch`` (probe value is
    the scalar chi2 at the input).
    """
    rec = recorder.host_trace()
    with telemetry.jit_span("fit.step"):
        new_deltas, info = step_fetch(step_dispatch(deltas0))
    chi2 = float(info["chi2_at_input"])
    if rec:
        rec.eval(chi2, 1.0)
    deltas = deltas0
    converged = False
    diverged = not math.isfinite(chi2)
    for _ in (() if diverged else range(max(1, maxiter))):
        telemetry.inc("fit.iterations")
        dx = {k: new_deltas[k] - deltas[k] for k in deltas}
        lam, applied = 1.0, False
        trial = trial_new = trial_info = None
        spec = None  # (lam of the speculated candidate, probe handle)

        def _speculate(lam_now, h_now, dx=dx, deltas=deltas):
            if h_now + 1 >= max_step_halvings:
                return None  # that halving would never be tried
            telemetry.inc("fit.probe_speculated")
            cand = {k: deltas[k] + (lam_now * 0.5) * dx[k] for k in deltas}
            return (lam_now * 0.5, probe_dispatch(cand))

        for _h in range(max_step_halvings):
            if _h > 0:
                telemetry.inc("fit.halvings")
                if rec:
                    rec.halving()
            trial = {k: deltas[k] + lam * dx[k] for k in deltas}
            if _h == 0:
                handle = step_dispatch(trial)
                spec = _speculate(lam, _h)
                with telemetry.jit_span("fit.step"):
                    trial_new, trial_info = step_fetch(handle)
                trial_chi2 = float(trial_info["chi2_at_input"])
                if rec:
                    rec.eval(trial_chi2, lam)
                if not math.isfinite(trial_chi2):
                    diverged = True
                    break
            else:
                telemetry.inc("fit.probe_evals")
                trial_new = trial_info = None
                with telemetry.jit_span("fit.probe"):
                    if spec is not None and spec[0] == lam:
                        trial_chi2 = float(probe_fetch(spec[1]))
                    else:
                        if spec is not None:
                            telemetry.inc("fit.probe_spec_wasted")
                        trial_chi2 = float(probe_fetch(
                            probe_dispatch(trial)))
                spec = None
                if rec:
                    rec.probe_eval()
            if trial_chi2 <= chi2 + 1e-12:
                if trial_info is None:
                    # probe-accepted: authoritative full re-check, with
                    # the next halving's probe speculated under it
                    handle = step_dispatch(trial)
                    spec = _speculate(lam, _h)
                    with telemetry.jit_span("fit.step"):
                        trial_new, trial_info = step_fetch(handle)
                    trial_chi2 = float(trial_info["chi2_at_input"])
                    if rec:
                        rec.eval(trial_chi2, lam)
                    if not math.isfinite(trial_chi2):
                        diverged = True
                        break
                    if trial_chi2 > chi2 + 1e-12:
                        telemetry.inc("fit.probe_rejects")
                        lam *= 0.5
                        continue
                applied = True
                telemetry.inc("fit.accepts")
                if rec:
                    rec.accept()
                break
            lam *= 0.5
        if spec is not None:
            telemetry.inc("fit.probe_spec_wasted")
            spec = None
        if diverged:
            break
        if not applied:
            converged = True
            break
        decrease = chi2 - trial_chi2
        deltas, chi2 = trial, trial_chi2
        new_deltas, info = trial_new, trial_info
        if decrease < min_chi2_decrease:
            converged = True
            break
    if diverged:
        telemetry.inc("fit.diverged")
    else:
        telemetry.inc("fit.converged" if converged
                      else "fit.maxiter_exhausted")
    if rec:
        rec.emit()
    return deltas, dict(info, diverged=diverged), chi2, converged
