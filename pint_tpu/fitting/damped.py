"""Damped Gauss-Newton outer loop over a fused fit step.

Host-side driver shared by the north-star fitters
(:class:`pint_tpu.parallel.sharded_fit.ShardedWLSFitter` /
``ShardedGLSFitter`` and :class:`pint_tpu.fitting.hybrid.HybridGLSFitter`):
the same accept / halve / converge semantics as the dense
``_DownhillMixin`` (reference: src/pint/fitter.py :: DownhillFitter,
SURVEY §2.3), but expressed over a *fused step function* — one call
evaluates the chi2 at the input parameters AND proposes a Gauss-Newton
step, so judging a trial point costs exactly one device program instead
of a separate residual pass.

The step contract: ``iterate(deltas) -> (new_deltas, info)`` where
``info["chi2_at_input"]`` is the (noise-marginalized, for GLS) chi2 of
the residuals at ``deltas`` and ``new_deltas`` is the proposed full
step from there.  The driver never needs residuals on the host.
"""

from __future__ import annotations


def downhill_iterate(iterate, deltas0: dict, *, maxiter: int = 20,
                     min_chi2_decrease: float = 1e-3,
                     max_step_halvings: int = 8):
    """Run a damped Gauss-Newton loop; returns (deltas, info, chi2, converged).

    Take the proposed step; while chi2 increases, halve it.  Stop when
    no downhill step exists (converged at a minimum of the linearized
    model) or the decrease falls below ``min_chi2_decrease``.  ``info``
    is the step output evaluated *at the returned deltas* (so its
    errors / covariance / noise coefficients are current); ``chi2`` is
    the actual chi2 there, not the linearized prediction.
    """
    new_deltas, info = iterate(deltas0)
    chi2 = float(info["chi2_at_input"])
    deltas = deltas0
    converged = False
    for _ in range(max(1, maxiter)):
        dx = {k: new_deltas[k] - deltas[k] for k in deltas}
        lam, applied = 1.0, False
        trial = trial_new = trial_info = None
        for _h in range(max_step_halvings):
            trial = {k: deltas[k] + lam * dx[k] for k in deltas}
            trial_new, trial_info = iterate(trial)
            trial_chi2 = float(trial_info["chi2_at_input"])
            if trial_chi2 <= chi2 + 1e-12:
                applied = True
                break
            lam *= 0.5
        if not applied:
            # no downhill direction left: we are at (numerical) optimum
            converged = True
            break
        decrease = chi2 - trial_chi2
        deltas, chi2 = trial, trial_chi2
        new_deltas, info = trial_new, trial_info
        if decrease < min_chi2_decrease:
            converged = True
            break
    return deltas, info, chi2, converged
