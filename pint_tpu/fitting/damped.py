"""Damped Gauss-Newton outer loop over a fused fit step.

Host-side driver shared by the north-star fitters
(:class:`pint_tpu.parallel.sharded_fit.ShardedWLSFitter` /
``ShardedGLSFitter`` and :class:`pint_tpu.fitting.hybrid.HybridGLSFitter`):
the same accept / halve / converge semantics as the dense
``_DownhillMixin`` (reference: src/pint/fitter.py :: DownhillFitter,
SURVEY §2.3), but expressed over a *fused step function* — one call
evaluates the chi2 at the input parameters AND proposes a Gauss-Newton
step, so judging a trial point costs exactly one device program instead
of a separate residual pass.

The step contract: ``iterate(deltas) -> (new_deltas, info)`` where
``info["chi2_at_input"]`` is the (noise-marginalized, for GLS) chi2 of
the residuals at ``deltas`` and ``new_deltas`` is the proposed full
step from there.  The driver never needs residuals on the host.

``chi2_at(deltas) -> float`` is an optional cheap probe evaluating ONLY
``chi2_at_input`` (no design matrix, no solve). When provided, halved
trial points are judged with it instead of the full fused step — the
round-4 verdict's clawback: a rejected trial used to pay a full jacfwd
design-matrix build whose output was discarded.  The first (lam=1)
trial still uses the full step, because acceptance there is the common
case and its proposal is needed anyway, so a convergent fit that never
halves pays zero extra programs; a probe-accepted point is then
re-evaluated once with the full step to obtain the next proposal.
"""

from __future__ import annotations

from pint_tpu import telemetry


def downhill_iterate(iterate, deltas0: dict, *, maxiter: int = 20,
                     min_chi2_decrease: float = 1e-3,
                     max_step_halvings: int = 8, chi2_at=None):
    """Run a damped Gauss-Newton loop; returns (deltas, info, chi2, converged).

    Take the proposed step; while chi2 increases, halve it.  Stop when
    no downhill step exists (converged at a minimum of the linearized
    model) or the decrease falls below ``min_chi2_decrease``.  ``info``
    is the step output evaluated *at the returned deltas* (so its
    errors / covariance / noise coefficients are current); ``chi2`` is
    the actual chi2 there, not the linearized prediction.

    Telemetry: every full-step evaluation runs under a ``fit.step``
    span (first-in-process call = the compile span — every step
    function blocks on its outputs, so span walls are honest) and every
    probe under ``fit.probe``; the loop events feed the ``fit.*``
    counters (iterations / accepts / halvings / probe_evals /
    probe_rejects / converged / maxiter_exhausted) that make damping
    behavior auditable from the rollup.
    """
    with telemetry.jit_span("fit.step"):
        new_deltas, info = iterate(deltas0)
    chi2 = float(info["chi2_at_input"])
    deltas = deltas0
    converged = False
    for _ in range(max(1, maxiter)):
        telemetry.inc("fit.iterations")
        dx = {k: new_deltas[k] - deltas[k] for k in deltas}
        lam, applied = 1.0, False
        trial = trial_new = trial_info = None
        for _h in range(max_step_halvings):
            if _h > 0:
                telemetry.inc("fit.halvings")
            trial = {k: deltas[k] + lam * dx[k] for k in deltas}
            if _h == 0 or chi2_at is None:
                with telemetry.jit_span("fit.step"):
                    trial_new, trial_info = iterate(trial)
                trial_chi2 = float(trial_info["chi2_at_input"])
            else:
                telemetry.inc("fit.probe_evals")
                trial_new = trial_info = None
                with telemetry.jit_span("fit.probe"):
                    trial_chi2 = float(chi2_at(trial))
            if trial_chi2 <= chi2 + 1e-12:
                if trial_info is None:
                    # accepted via the cheap probe: one full evaluation
                    # at the accepted point supplies the next proposal
                    # and current info. Its chi2 is AUTHORITATIVE — the
                    # probe is a different XLA program (and under the
                    # mxu path the full program's Gram is double-single
                    # while the probe's is f64), so when the full value
                    # contradicts the acceptance, keep halving instead
                    # of applying an uphill step.
                    with telemetry.jit_span("fit.step"):
                        trial_new, trial_info = iterate(trial)
                    trial_chi2 = float(trial_info["chi2_at_input"])
                    if trial_chi2 > chi2 + 1e-12:
                        telemetry.inc("fit.probe_rejects")
                        lam *= 0.5
                        continue
                applied = True
                telemetry.inc("fit.accepts")
                break
            lam *= 0.5
        if not applied:
            # no downhill direction left: we are at (numerical) optimum
            converged = True
            break
        decrease = chi2 - trial_chi2
        deltas, chi2 = trial, trial_chi2
        new_deltas, info = trial_new, trial_info
        if decrease < min_chi2_decrease:
            converged = True
            break
    telemetry.inc("fit.converged" if converged else "fit.maxiter_exhausted")
    return deltas, info, chi2, converged
