"""Wideband fitting: joint TOA + DM least squares.

Reference equivalent: ``pint.residuals.WidebandTOAResiduals`` and
``pint.fitter.WidebandTOAFitter`` / ``WidebandDownhillFitter``
(src/pint/residuals.py, src/pint/fitter.py). Wideband TOAs carry a
per-TOA DM measurement (``-pp_dm`` / ``-pp_dme`` flags); the fit
minimizes both blocks jointly:

    [ r_toa / sig_toa ]     [ M_toa / sig_toa ]
    [ r_dm  / sig_dm  ]  ~  [ M_dm  / sig_dm  ] x

with M_dm = d(model DM)/d(param) (TimingModel.dm_designmatrix). The
stacked system reuses the whitened SVD solve — one XLA program, rows =
2n. Correlated noise bases (ECORR etc.) extend the TOA block only,
zero-padded over the DM block.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.fitter import Fitter, wls_solve
from pint_tpu.fitting.gls import _DownhillMixin, gls_solve
from pint_tpu.residuals import Residuals

__all__ = ["WidebandTOAResiduals", "WidebandTOAFitter", "WidebandDownhillFitter"]


class WidebandTOAResiduals:
    """TOA + DM residual blocks (reference: WidebandTOAResiduals)."""

    def __init__(self, toas, model, *, track_mode: str | None = None):
        self.toas = toas
        self.model = model
        self.toa = Residuals(toas, model, track_mode=track_mode)
        dm_data = jnp.asarray(toas.get_dm_values())
        self.dm_model = model.total_dm(toas)
        self.dm_resids = dm_data - self.dm_model
        self.dm_errors = model.scaled_dm_uncertainty(toas)

    @property
    def chi2(self) -> float:
        dm_chi2 = float(jnp.sum(jnp.square(self.dm_resids / self.dm_errors)))
        return self.toa.chi2 + dm_chi2

    @property
    def dof(self) -> int:
        return 2 * len(self.toas) - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    # Fitter API compatibility (mirrors Residuals)
    @property
    def time_resids(self):
        return self.toa.time_resids

    def get_errors_s(self):
        return self.toa.get_errors_s()

    def rms_weighted_s(self) -> float:
        return self.toa.rms_weighted_s()


class WidebandTOAFitter(Fitter):
    """Joint TOA+DM WLS/GLS fit (reference: WidebandTOAFitter)."""

    resid_cls = WidebandTOAResiduals

    def __init__(self, toas, model, residuals=None, track_mode=None):
        if not toas.is_wideband():
            raise ValueError("WidebandTOAFitter requires TOAs with -pp_dm flags"
                             " on every TOA")
        dm_err = toas.get_dm_errors()
        if not np.all(np.isfinite(dm_err) & (dm_err > 0)):
            bad = int(np.sum(~(np.isfinite(dm_err) & (dm_err > 0))))
            raise ValueError(
                f"{bad} TOA(s) have missing or non-positive -pp_dme DM "
                f"uncertainties; the whitened wideband solve would be NaN")
        super().__init__(toas, model, residuals, track_mode)
        self._noise_cache = None

    def _stacked_system(self):
        """(M, r, err) with TOA rows on top of DM rows, plus param names."""
        M_t, names = self.model.designmatrix(self.toas)
        M_dm, _ = self.model.dm_designmatrix(self.toas)
        r = jnp.concatenate([self.resids.toa.time_resids, self.resids.dm_resids])
        err = jnp.concatenate([self.resids.toa.get_errors_s(),
                               self.resids.dm_errors])
        return jnp.concatenate([M_t, M_dm], axis=0), r, err, names

    def _noise_arrays_stacked(self):
        """Correlated-noise basis zero-padded over the DM rows."""
        if self._noise_cache is not None:
            return self._noise_cache
        T = self.model.noise_model_designmatrix(self.toas)
        if T is None:
            self._noise_cache = (None, None)
        else:
            phi = self.model.noise_model_basis_weight(self.toas)
            Tz = np.concatenate([T, np.zeros_like(T)], axis=0)
            self._noise_cache = (jnp.asarray(Tz), jnp.asarray(phi))
        return self._noise_cache

    def _solve(self):
        from pint_tpu.fitting.gls import _pad_gls_rows

        M, r, err, names = self._stacked_system()
        T, phi = self._noise_arrays_stacked()
        # bucket the stacked 2n row dimension (exact zero rows; the
        # cached T is padded into a LOCAL only — see GLSFitter.fit_toas)
        r, err, M, Tb = _pad_gls_rows(int(r.shape[0]), r, err, M, T,
                                      owner=self)
        if Tb is None:
            sol = wls_solve(M, r, err)
        else:
            sol = gls_solve(M, Tb, phi, r, err)
        return sol, names

    def fit_toas(self, maxiter: int = 1, **kw) -> float:
        for it in range(max(1, maxiter)):
            if it > 0:
                self.resids = self._new_resids()
            sol, names = self._solve()
            x = np.asarray(sol["x"])
            cov = np.asarray(sol["cov"])
            self.update_model(names, x, np.sqrt(np.diag(cov)))
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
        self.resids = self._new_resids()
        return self.resids.chi2

    def get_summary(self, nodmx: bool = True) -> str:
        base = super().get_summary(nodmx=nodmx)
        dm_rms = float(jnp.sqrt(jnp.mean(jnp.square(self.resids.dm_resids))))
        return base + f"\n  DM rms: {dm_rms:.3e} pc/cm3"


class WidebandDownhillFitter(_DownhillMixin, WidebandTOAFitter):
    """Reference: WidebandDownhillFitter."""

    def _fit_chi2(self) -> float:
        # the accept/halve/converge objective must be the same one _solve
        # minimizes: with a correlated-noise basis that is the GLS
        # chi2 r^T C^-1 r (zero-column design matrix), not the white chi2
        T, phi = self._noise_arrays_stacked()
        if T is None:
            return self.resids.chi2
        from pint_tpu.fitting.gls import _pad_gls_rows

        r = jnp.concatenate([self.resids.toa.time_resids, self.resids.dm_resids])
        err = jnp.concatenate([self.resids.toa.get_errors_s(),
                               self.resids.dm_errors])
        M0 = jnp.zeros((r.shape[0], 0))
        # the memo key is (T identity, bucket), so the probe shares the
        # step's padded T
        r, err, M0, Tb = _pad_gls_rows(int(r.shape[0]), r, err, M0, T,
                                       owner=self)
        sol = gls_solve(M0, Tb, phi, r, err)
        return float(np.asarray(sol["chi2"]))

    def _step(self, **kw):
        sol, names = self._solve()
        cov = np.asarray(sol["cov"])
        return np.asarray(sol["x"]), names, np.sqrt(np.diag(cov)), cov
