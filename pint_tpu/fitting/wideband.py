"""Wideband fitting: joint TOA + DM least squares.

Reference equivalent: ``pint.residuals.WidebandTOAResiduals`` and
``pint.fitter.WidebandTOAFitter`` / ``WidebandDownhillFitter``
(src/pint/residuals.py, src/pint/fitter.py). Wideband TOAs carry a
per-TOA DM measurement (``-pp_dm`` / ``-pp_dme`` flags); the fit
minimizes both blocks jointly:

    [ r_toa / sig_toa ]     [ M_toa / sig_toa ]
    [ r_dm  / sig_dm  ]  ~  [ M_dm  / sig_dm  ] x

with M_dm = d(model DM)/d(param) (TimingModel.dm_designmatrix). The
stacked system reuses the whitened SVD solve — one XLA program, rows =
2n. Correlated noise bases (ECORR etc.) extend the TOA block only,
zero-padded over the DM block.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.fitter import Fitter, wls_solve
from pint_tpu.fitting.gls import _DownhillMixin, gls_solve
from pint_tpu.residuals import Residuals

__all__ = ["WidebandTOAResiduals", "WidebandTOAFitter",
           "WidebandDownhillFitter", "build_wb_data", "make_wb_step",
           "jitted_wb_step", "make_wb_probe", "jitted_wb_probe"]

# padded wideband DM rows carry this uncertainty [pc/cm^3] -> weight
# ~1e-32 of a real DM measurement (the DM-block analogue of
# bucketing.PAD_ERROR_US)
DM_PAD_ERROR = 1e12


class WidebandTOAResiduals:
    """TOA + DM residual blocks (reference: WidebandTOAResiduals)."""

    def __init__(self, toas, model, *, track_mode: str | None = None):
        self.toas = toas
        self.model = model
        self.toa = Residuals(toas, model, track_mode=track_mode)
        dm_data = jnp.asarray(toas.get_dm_values())
        self.dm_model = model.total_dm(toas)
        self.dm_resids = dm_data - self.dm_model
        self.dm_errors = model.scaled_dm_uncertainty(toas)

    @property
    def chi2(self) -> float:
        dm_chi2 = float(jnp.sum(jnp.square(self.dm_resids / self.dm_errors)))
        return self.toa.chi2 + dm_chi2

    @property
    def dof(self) -> int:
        return 2 * len(self.toas) - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    # Fitter API compatibility (mirrors Residuals)
    @property
    def time_resids(self):
        return self.toa.time_resids

    def get_errors_s(self):
        return self.toa.get_errors_s()

    def rms_weighted_s(self) -> float:
        return self.toa.rms_weighted_s()


class WidebandTOAFitter(Fitter):
    """Joint TOA+DM WLS/GLS fit (reference: WidebandTOAFitter)."""

    resid_cls = WidebandTOAResiduals

    def __init__(self, toas, model, residuals=None, track_mode=None):
        if not toas.is_wideband():
            raise ValueError("WidebandTOAFitter requires TOAs with -pp_dm flags"
                             " on every TOA")
        dm_err = toas.get_dm_errors()
        if not np.all(np.isfinite(dm_err) & (dm_err > 0)):
            bad = int(np.sum(~(np.isfinite(dm_err) & (dm_err > 0))))
            raise ValueError(
                f"{bad} TOA(s) have missing or non-positive -pp_dme DM "
                f"uncertainties; the whitened wideband solve would be NaN")
        super().__init__(toas, model, residuals, track_mode)
        self._noise_cache = None

    def _stacked_system(self):
        """(M, r, err) with TOA rows on top of DM rows, plus param names."""
        M_t, names = self.model.designmatrix(self.toas)
        M_dm, _ = self.model.dm_designmatrix(self.toas)
        r = jnp.concatenate([self.resids.toa.time_resids, self.resids.dm_resids])
        err = jnp.concatenate([self.resids.toa.get_errors_s(),
                               self.resids.dm_errors])
        return jnp.concatenate([M_t, M_dm], axis=0), r, err, names

    def _noise_arrays_stacked(self):
        """Correlated-noise basis zero-padded over the DM rows."""
        if self._noise_cache is not None:
            return self._noise_cache
        T = self.model.noise_model_designmatrix(self.toas)
        if T is None:
            self._noise_cache = (None, None)
        else:
            phi = self.model.noise_model_basis_weight(self.toas)
            Tz = np.concatenate([T, np.zeros_like(T)], axis=0)
            self._noise_cache = (jnp.asarray(Tz), jnp.asarray(phi))
        return self._noise_cache

    def _solve(self):
        from pint_tpu.fitting.gls import _pad_gls_rows

        M, r, err, names = self._stacked_system()
        T, phi = self._noise_arrays_stacked()
        # bucket the stacked 2n row dimension (exact zero rows; the
        # cached T is padded into a LOCAL only — see GLSFitter.fit_toas)
        r, err, M, Tb = _pad_gls_rows(int(r.shape[0]), r, err, M, T,
                                      owner=self)
        if Tb is None:
            sol = wls_solve(M, r, err)
        else:
            sol = gls_solve(M, Tb, phi, r, err)
        return sol, names

    def fit_toas(self, maxiter: int = 1, **kw) -> float:
        for it in range(max(1, maxiter)):
            if it > 0:
                self.resids = self._new_resids()
            sol, names = self._solve()
            x = np.asarray(sol["x"])
            cov = np.asarray(sol["cov"])
            self.update_model(names, x, np.sqrt(np.diag(cov)))
            self.fit_params = [n for n in names if n != "Offset"]
            self.parameter_covariance_matrix = cov
        self.resids = self._new_resids()
        return self.resids.chi2

    def get_summary(self, nodmx: bool = True) -> str:
        base = super().get_summary(nodmx=nodmx)
        dm_rms = float(jnp.sqrt(jnp.mean(jnp.square(self.resids.dm_resids))))
        return base + f"\n  DM rms: {dm_rms:.3e} pc/cm3"


# ----------------------------------------------------------------------
# fused wideband step (ISSUE 8): the joint TOA+DM iteration as one pure
# traced function — vmappable, so wideband fits are first-class members
# of the throughput scheduler's union batches
# ----------------------------------------------------------------------

def build_wb_data(toas, n_target: int | None = None) -> dict:
    """Materialize the wideband DM block as TRACED arrays.

    The ``-pp_dm`` / ``-pp_dme`` measurements live on the static flag
    dicts, which batch stacking strips (``parallel.batch._strip_static``)
    — so the fused step takes them as a data operand ``{"vals": (n,),
    "errs": (n,)}`` instead. ``n_target`` pads with inert rows: values
    replicate the last measurement, uncertainties are ``DM_PAD_ERROR``
    (zero weight), the exact policy of ``bucketing.pad_toas``.
    """
    vals = np.asarray(toas.get_dm_values(), dtype=np.float64)
    errs = np.asarray(toas.get_dm_errors(), dtype=np.float64)
    if not np.all(np.isfinite(vals)):
        raise ValueError("wideband fit requires -pp_dm on every TOA")
    if not np.all(np.isfinite(errs) & (errs > 0)):
        bad = int(np.sum(~(np.isfinite(errs) & (errs > 0))))
        raise ValueError(
            f"{bad} TOA(s) have missing or non-positive -pp_dme DM "
            f"uncertainties; the whitened wideband solve would be NaN")
    if n_target is not None and n_target != len(vals):
        if n_target < len(vals):
            raise ValueError(f"n_target {n_target} < n {len(vals)}")
        k = n_target - len(vals)
        vals = np.concatenate([vals, np.repeat(vals[-1:], k)])
        errs = np.concatenate([errs, np.full(k, DM_PAD_ERROR)])
    return {"vals": vals, "errs": errs}


def make_wb_step(model, tzr=None, *, abs_phase: bool = True,
                 pl_specs=(), masked: bool = False,
                 params: list[str] | None = None,
                 traced_tzr: bool = False):
    """Build ``step(base, deltas, toas, noise, dm[, mask][, tzr]) ->
    (new_deltas, info)`` — one fused wideband Gauss-Newton iteration.

    The stacked system of :class:`WidebandTOAFitter` as a single pure
    function: TOA rows (phase residuals, jacfwd design matrix) on top
    of DM rows (``dm_data - model DM``, d(DM)/d(param) columns), solved
    through the segment-sum GLS machinery of
    :mod:`pint_tpu.fitting.gls_step` — correlated-noise bases extend
    the TOA block only (Fourier blocks zero-padded over the DM rows,
    ECORR epoch indices pointing every DM row at the dummy segment),
    exactly the dense fitter's convention. With no noise basis the
    solve degenerates to the joint WLS. ``info["chi2_at_input"]`` is
    the stacked r^T C^-1 r the damped loop judges trials by (=
    ``WidebandDownhillFitter._fit_chi2``'s objective).

    ``masked`` / ``params`` / ``traced_tzr`` mirror ``make_wls_step``
    (the union-batch machinery); ``dm`` is :func:`build_wb_data`'s
    traced block.
    """
    from pint_tpu.fitting.gls_step import (gls_finalize_seg, gls_gram_seg,
                                           noise_marginal_chi2, pl_bases)
    from pint_tpu.fitting.step import _circular_recenter

    if tzr is None and abs_phase and not traced_tzr:
        tzr = model.get_tzr_toas()
    anchorless = tzr is None and not traced_tzr
    phase_fn = model.phase_fn_toas(tzr=tzr, abs_phase=abs_phase,
                                   traced_tzr=traced_tzr)
    names = params if params is not None else model.free_params
    has_phoff = model.has_component("PhaseOffset")
    off = 0 if has_phoff else 1
    dm_comps = [c for c in model.components if hasattr(c, "dm_value")]
    dm_scale_comps = [c for c in model.components
                      if hasattr(c, "scale_dm_sigma")]

    def step(base, deltas, toas, noise, dm, mask=None, tzr_toas=None):
        f0 = base["F0"].hi + base["F0"].lo

        def joint(d):
            ph = (phase_fn(base, d, toas, tzr_toas) if traced_tzr
                  else phase_fn(base, d, toas))
            p = model.resolve(base, d)
            dm_m = jnp.zeros(np.shape(toas.freq_mhz)[-1])
            for c in dm_comps:
                dm_m = dm_m + c.dm_value(p, toas)
            # aux carries the wrapped fractional phase AND the DM primal
            # from the SAME evaluation (one DD pipeline trace serves
            # residual + jacobian; see make_wls_step)
            return ((ph.int_part + (ph.frac.hi + ph.frac.lo), dm_m),
                    (ph.frac.hi + ph.frac.lo, dm_m))

        # traced white-noise scaling (ISSUE 10 satellite): statics-
        # carried scaled sigmas keep EFAC/EQUAD values out of the trace;
        # DMEFAC/DMEQUAD ride ``noise.dm_sigma`` the same way (ISSUE 14
        # satellite — the PR-10 residue), so one compiled program serves
        # every wideband DM-error value mix
        err_t = (noise.sigma if noise.sigma is not None
                 else model.scaled_toa_uncertainty(toas))
        w_t = 1.0 / jnp.square(err_t)

        (J_ph, J_dm), (resid_turns, dm_m) = \
            jax.jacfwd(joint, has_aux=True)(deltas)
        if anchorless:
            resid_turns = _circular_recenter(resid_turns, w_t)
        if not has_phoff:
            resid_turns = resid_turns \
                - jnp.sum(resid_turns * w_t) / jnp.sum(w_t)
        r_t = resid_turns / f0
        r_dm = dm["vals"] - dm_m
        if noise.dm_sigma is not None:
            err_dm = noise.dm_sigma
        else:
            err_dm = dm["errs"]
            for c in dm_scale_comps:
                err_dm = c.scale_dm_sigma(err_dm, toas)

        # stacked design matrix: the Offset column moves no DM
        # measurement (zeros over the DM rows), parameter columns are
        # [-dphase/dp / f0 ; -d(resid_dm)/dp] = [-J_ph/f0 ; +J_dm]
        zeros = jnp.zeros_like(r_t)
        cols_t = [] if has_phoff else [jnp.ones_like(r_t) / f0]
        cols_dm = [] if has_phoff else [zeros]
        for k in names:
            col_t = -J_ph[k] / f0
            col_dm = J_dm[k]
            if mask is not None:
                col_t = col_t * mask[k]
                col_dm = col_dm * mask[k]
            cols_t.append(col_t)
            cols_dm.append(col_dm)
        M = jnp.concatenate([jnp.stack(cols_t, axis=1),
                             jnp.stack(cols_dm, axis=1)], axis=0)
        r = jnp.concatenate([r_t, r_dm])
        err = jnp.concatenate([err_t, err_dm])

        # noise bases cover the TOA rows only: Fourier blocks zero over
        # the DM rows, every DM row in the ECORR dummy segment
        F, phi_F = pl_bases(toas, pl_specs, noise.pl_params)
        if F is not None:
            F = jnp.concatenate([F, jnp.zeros_like(F)], axis=0)
        ne = noise.ecorr_phi.shape[-1]
        epoch_idx = jnp.concatenate(
            [noise.epoch_idx,
             jnp.full(r_t.shape[0], ne, dtype=jnp.int32)])

        parts = gls_gram_seg(M, r, err, F, phi_F, epoch_idx,
                             noise.ecorr_phi)
        sol = gls_finalize_seg(parts, M.shape[1])
        new_deltas = {k: deltas[k] + sol["x"][i + off]
                      for i, k in enumerate(names)}
        sig = jnp.sqrt(jnp.diagonal(sol["cov"]))
        errors = {k: sig[i + off] for i, k in enumerate(names)}
        return new_deltas, {"chi2": sol["chi2"], "errors": errors,
                            "chi2_at_input":
                                noise_marginal_chi2(parts, M.shape[1]),
                            "fourier_coeffs": sol["fourier_coeffs"],
                            "ecorr_coeffs": sol["ecorr_coeffs"]}

    if not masked:
        if traced_tzr:
            def step_unmasked_tzr(base, deltas, toas, noise, dm,
                                  tzr_toas):
                return step(base, deltas, toas, noise, dm, None, tzr_toas)

            return step_unmasked_tzr

        def step_unmasked(base, deltas, toas, noise, dm):
            return step(base, deltas, toas, noise, dm)

        return step_unmasked
    return step


def jitted_wb_step(model, *, pl_specs=(), abs_phase: bool = True,
                   masked: bool = False,
                   params: list[str] | None = None,
                   vmapped: bool = False, traced_tzr: bool = False,
                   counted: bool = True):
    """Model-cache-shared :func:`make_wb_step` (the ``jitted_wls_step``
    convention: one compiled program per structure + step config, free
    values through the traced ``base``, noise values through the traced
    ``NoiseStatics``, DM data through the traced ``dm`` block)."""
    from pint_tpu.fitting.step import _counted_step

    key = ("wb_step", tuple(pl_specs), abs_phase, masked,
           tuple(params) if params is not None else None, vmapped,
           traced_tzr)

    def build(owner):
        fn = make_wb_step(owner, pl_specs=pl_specs, abs_phase=abs_phase,
                          masked=masked, params=params,
                          traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        n_args = 5 + (1 if masked else 0) + (1 if traced_tzr else 0)
        return jax.vmap(fn, in_axes=(0,) * n_args)

    cached = model._cached_jit(key, build)
    if not counted:
        return cached
    return _counted_step(cached, key, model)


def make_wb_probe(model, tzr=None, *, abs_phase: bool = True,
                  pl_specs=(), traced_tzr: bool = False):
    """Build ``probe(base, deltas, toas, noise, dm[, tzr]) -> chi2`` —
    the stacked wideband chi2 at ``deltas`` without a design matrix
    (one phase pass + one DM pass; the residual-only trial judge of the
    fused damped loop, computing exactly the step's ``chi2_at_input``
    expression through the zero-column Schur system)."""
    from pint_tpu.fitting.gls_step import (gls_gram_seg,
                                           noise_marginal_chi2, pl_bases)
    from pint_tpu.fitting.step import make_resid_fn

    resid = make_resid_fn(model, tzr, abs_phase=abs_phase,
                          traced_tzr=traced_tzr)
    dm_comps = [c for c in model.components if hasattr(c, "dm_value")]
    dm_scale_comps = [c for c in model.components
                      if hasattr(c, "scale_dm_sigma")]

    def probe(base, deltas, toas, noise, dm, tzr_toas=None):
        r_t, err_t, _w = (resid(base, deltas, toas, tzr_toas,
                                err=noise.sigma) if traced_tzr
                          else resid(base, deltas, toas,
                                     err=noise.sigma))
        p = model.resolve(base, deltas)
        dm_m = jnp.zeros(np.shape(toas.freq_mhz)[-1])
        for c in dm_comps:
            dm_m = dm_m + c.dm_value(p, toas)
        if noise.dm_sigma is not None:
            err_dm = noise.dm_sigma
        else:
            err_dm = dm["errs"]
            for c in dm_scale_comps:
                err_dm = c.scale_dm_sigma(err_dm, toas)
        r = jnp.concatenate([r_t, dm["vals"] - dm_m])
        err = jnp.concatenate([err_t, err_dm])
        F, phi_F = pl_bases(toas, pl_specs, noise.pl_params)
        if F is not None:
            F = jnp.concatenate([F, jnp.zeros_like(F)], axis=0)
        ne = noise.ecorr_phi.shape[-1]
        epoch_idx = jnp.concatenate(
            [noise.epoch_idx,
             jnp.full(r_t.shape[0], ne, dtype=jnp.int32)])
        parts = gls_gram_seg(jnp.zeros((r.shape[0], 0)), r, err, F,
                             phi_F, epoch_idx, noise.ecorr_phi)
        return noise_marginal_chi2(parts, 0)

    if traced_tzr:
        def probe_tzr(base, deltas, toas, noise, dm, tzr_toas):
            return probe(base, deltas, toas, noise, dm, tzr_toas)

        return probe_tzr

    def probe_plain(base, deltas, toas, noise, dm):
        return probe(base, deltas, toas, noise, dm)

    return probe_plain


def jitted_wb_probe(model, *, pl_specs=(), abs_phase: bool = True,
                    traced_tzr: bool = False, vmapped: bool = False):
    """Model-cache-shared :func:`make_wb_probe` (uncounted; traced into
    the fused device loop, never dispatched on its own)."""
    key = ("wb_probe", tuple(pl_specs), abs_phase, traced_tzr, vmapped)

    def build(owner):
        fn = make_wb_probe(owner, pl_specs=pl_specs, abs_phase=abs_phase,
                           traced_tzr=traced_tzr)
        if not vmapped:
            return fn
        return jax.vmap(fn, in_axes=(0,) * (5 + (1 if traced_tzr else 0)))

    return model._cached_jit(key, build)


class WidebandDownhillFitter(_DownhillMixin, WidebandTOAFitter):
    """Reference: WidebandDownhillFitter."""

    def _fit_chi2(self) -> float:
        # the accept/halve/converge objective must be the same one _solve
        # minimizes: with a correlated-noise basis that is the GLS
        # chi2 r^T C^-1 r (zero-column design matrix), not the white chi2
        T, phi = self._noise_arrays_stacked()
        if T is None:
            return self.resids.chi2
        from pint_tpu.fitting.gls import _pad_gls_rows

        r = jnp.concatenate([self.resids.toa.time_resids, self.resids.dm_resids])
        err = jnp.concatenate([self.resids.toa.get_errors_s(),
                               self.resids.dm_errors])
        M0 = jnp.zeros((r.shape[0], 0))
        # the memo key is (T identity, bucket), so the probe shares the
        # step's padded T
        r, err, M0, Tb = _pad_gls_rows(int(r.shape[0]), r, err, M0, T,
                                       owner=self)
        sol = gls_solve(M0, Tb, phi, r, err)
        return float(np.asarray(sol["chi2"]))

    def _step(self, **kw):
        sol, names = self._solve()
        cov = np.asarray(sol["cov"])
        return np.asarray(sol["x"]), names, np.sqrt(np.diag(cov)), cov
