"""pint_tpu — a TPU-native pulsar-timing framework.

A from-scratch reimplementation of the capabilities of PINT
(reference: ktzhao/PINT, a fork of nanograv/PINT; see SURVEY.md) designed
for JAX/XLA on TPU rather than ported from the numpy/astropy original:

* PINT's ``numpy.longdouble`` time arithmetic -> double-double (hi/lo
  float64 pairs, :mod:`pint_tpu.ops.dd`) evaluated on IEEE-exact CPU
  backends, with the heavy linear algebra (design matrices, GLS solves)
  linearized into plain float64 on the TPU's MXU.
* PINT's hand-coded analytic parameter derivatives
  (``TimingModel.d_phase_d_param``; reference src/pint/models/timing_model.py)
  -> ``jax.jacfwd`` over pure phase functions.
* PINT's single-core per-component Python loops -> pure functions composed
  once, ``vmap``-ed over the TOA axis, and ``pjit``-ed with the TOA axis
  sharded over a device mesh (:mod:`pint_tpu.parallel`).

Numerical precision contract: every time-like quantity that must hold
nanosecond precision over multi-decade baselines (~1e-18 relative) is a
double-double; everything else (delays < ~1e4 s, design-matrix entries,
covariances) is float64.
"""

import jax as _jax

# The whole framework assumes 64-bit floats; enable before anything traces.
_jax.config.update("jax_enable_x64", True)


def setup_platform(platform: str | None = None) -> None:
    """Make an explicit JAX platform request actually stick.

    Some accelerator plugins (the sandbox's axon tunnel among them)
    force-select their platform via ``jax.config`` in ``sitecustomize``,
    which silently overrides a user's ``JAX_PLATFORMS`` environment
    variable — a plain script run with ``JAX_PLATFORMS=cpu`` then hangs
    at backend initialization when the accelerator is unreachable.

    This is the ONE place that workaround lives (round-3 weak #4):
    ``import pint_tpu`` calls it with no argument, re-applying the
    ``JAX_PLATFORMS`` env var to ``jax.config`` when the var is set and
    the config disagrees; entry points that must run on a specific
    backend call it explicitly, e.g. ``pint_tpu.setup_platform("cpu")``,
    before any jax computation. With no argument and no env var it does
    nothing (an auto-detected accelerator stays selected). No-op with a
    warning if the backend is already initialized (too late to switch).
    """
    import os

    want = platform or os.environ.get("JAX_PLATFORMS", "")
    if not want:
        return
    # the env var is the pin accelerator-plugin stacks actually honor:
    # a config-only update can still be raced by a plugin's lazy
    # backend hook (observed round 4: jax.config.update("jax_platforms",
    # "cpu") before any jax use still initialized the tunnel client at
    # the first device_put, while JAX_PLATFORMS=cpu did not) — so an
    # EXPLICIT platform request sets both.
    if platform:
        os.environ["JAX_PLATFORMS"] = platform
    try:
        if str(_jax.config.jax_platforms or "") != want:
            _jax.config.update("jax_platforms", want)
    except RuntimeError as exc:  # backends already initialized
        import logging

        logging.getLogger(__name__).warning(
            "setup_platform(%r) too late — jax backends already "
            "initialized (%s)", want, exc)


setup_platform()

__version__ = "0.1.0"

from pint_tpu.ops import dd  # noqa: E402,F401
