"""pint_tpu — a TPU-native pulsar-timing framework.

A from-scratch reimplementation of the capabilities of PINT
(reference: ktzhao/PINT, a fork of nanograv/PINT; see SURVEY.md) designed
for JAX/XLA on TPU rather than ported from the numpy/astropy original:

* PINT's ``numpy.longdouble`` time arithmetic -> double-double (hi/lo
  float64 pairs, :mod:`pint_tpu.ops.dd`) evaluated on IEEE-exact CPU
  backends, with the heavy linear algebra (design matrices, GLS solves)
  linearized into plain float64 on the TPU's MXU.
* PINT's hand-coded analytic parameter derivatives
  (``TimingModel.d_phase_d_param``; reference src/pint/models/timing_model.py)
  -> ``jax.jacfwd`` over pure phase functions.
* PINT's single-core per-component Python loops -> pure functions composed
  once, ``vmap``-ed over the TOA axis, and ``pjit``-ed with the TOA axis
  sharded over a device mesh (:mod:`pint_tpu.parallel`).

Numerical precision contract: every time-like quantity that must hold
nanosecond precision over multi-decade baselines (~1e-18 relative) is a
double-double; everything else (delays < ~1e4 s, design-matrix entries,
covariances) is float64.
"""

import jax as _jax

# The whole framework assumes 64-bit floats; enable before anything traces.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from pint_tpu.ops import dd  # noqa: E402,F401
