"""Logging setup: level filtering and duplicate suppression.

Reference equivalent: ``pint.logging`` (src/pint/logging.py), which wraps
loguru with a ``setup()`` entry point and de-duplication filters so the
per-TOA warning storms of big datasets don't flood the console. Here the
same surface is built on stdlib logging (no loguru offline): ``setup()``
configures the ``pint_tpu`` logger tree, and ``DedupFilter`` collapses
repeated messages past a threshold.
"""

from __future__ import annotations

import logging
import sys

LOG_FORMAT = "%(levelname)-9s %(name)s: %(message)s"

# Telemetry-aware debug level: span begin/end mirroring
# (pint_tpu.telemetry.spans, enabled via PINT_TPU_TELEMETRY_LOG) logs
# between DEBUG and INFO — visible with setup(level="TELEMETRY") without
# drowning in full DEBUG output, invisible at the INFO default.
TELEMETRY = 15
logging.addLevelName(TELEMETRY, "TELEMETRY")


def get_logger(name: str = "pint_tpu") -> logging.Logger:
    """The shared ``pint_tpu`` logger tree (one config via setup()).

    Every module — telemetry mirroring included — logs through children
    of the ``pint_tpu`` root logger, so a single :func:`setup` call
    controls level, format and dedup for the whole package.
    """
    if name != "pint_tpu" and not name.startswith("pint_tpu."):
        name = f"pint_tpu.{name}"
    return logging.getLogger(name)


class DedupFilter(logging.Filter):
    """Suppress the Nth+ repetition of an identical (level, message) pair."""

    def __init__(self, max_repeats: int = 3):
        super().__init__()
        self.max_repeats = max_repeats
        self._counts: dict[tuple[int, str], int] = {}

    def filter(self, record: logging.LogRecord) -> bool:
        key = (record.levelno, record.getMessage())
        count = self._counts.get(key, 0) + 1
        self._counts[key] = count
        if count == self.max_repeats:
            record.msg = f"{record.getMessage()} [repeated messages suppressed]"
            record.args = ()
        return count <= self.max_repeats


def setup(level: str = "INFO", *, dedup: bool = True,
          max_repeats: int = 3, stream=None) -> logging.Logger:
    """Configure the ``pint_tpu`` logger (reference: pint.logging.setup).

    Returns the package root logger. Repeated calls reconfigure (old
    handlers are removed), so scripts can call it unconditionally.
    ``level`` accepts the stdlib names plus ``"TELEMETRY"`` (between
    DEBUG and INFO — shows mirrored span begin/end lines).
    """
    logger = logging.getLogger("pint_tpu")
    lvl = (TELEMETRY if level.upper() == "TELEMETRY"
           else getattr(logging, level.upper(), logging.INFO))
    logger.setLevel(lvl)
    for h in list(logger.handlers):
        logger.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    if dedup:
        handler.addFilter(DedupFilter(max_repeats))
    logger.addHandler(handler)
    logger.propagate = False
    return logger
