"""Logging setup: level filtering and duplicate suppression.

Reference equivalent: ``pint.logging`` (src/pint/logging.py), which wraps
loguru with a ``setup()`` entry point and de-duplication filters so the
per-TOA warning storms of big datasets don't flood the console. Here the
same surface is built on stdlib logging (no loguru offline): ``setup()``
configures the ``pint_tpu`` logger tree, and ``DedupFilter`` collapses
repeated messages past a threshold.
"""

from __future__ import annotations

import logging
import sys

LOG_FORMAT = "%(levelname)-7s %(name)s: %(message)s"


class DedupFilter(logging.Filter):
    """Suppress the Nth+ repetition of an identical (level, message) pair."""

    def __init__(self, max_repeats: int = 3):
        super().__init__()
        self.max_repeats = max_repeats
        self._counts: dict[tuple[int, str], int] = {}

    def filter(self, record: logging.LogRecord) -> bool:
        key = (record.levelno, record.getMessage())
        count = self._counts.get(key, 0) + 1
        self._counts[key] = count
        if count == self.max_repeats:
            record.msg = f"{record.getMessage()} [repeated messages suppressed]"
            record.args = ()
        return count <= self.max_repeats


def setup(level: str = "INFO", *, dedup: bool = True,
          max_repeats: int = 3, stream=None) -> logging.Logger:
    """Configure the ``pint_tpu`` logger (reference: pint.logging.setup).

    Returns the package root logger. Repeated calls reconfigure (old
    handlers are removed), so scripts can call it unconditionally.
    """
    logger = logging.getLogger("pint_tpu")
    logger.setLevel(getattr(logging, level.upper(), logging.INFO))
    for h in list(logger.handlers):
        logger.removeHandler(h)
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(LOG_FORMAT))
    if dedup:
        handler.addFilter(DedupFilter(max_repeats))
    logger.addHandler(handler)
    logger.propagate = False
    return logger
