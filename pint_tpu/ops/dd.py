"""Double-double (hi/lo float64 pair) arithmetic for JAX.

This module replaces ``numpy.longdouble`` in the reference design
(PINT keeps all TOA MJDs and pulse phases in 80-bit extended precision;
reference src/pint/pulsar_mjd.py and src/pint/phase.py). TPUs have no
long double, and x86 extended precision does not exist on any accelerator,
so the framework represents every precision-critical scalar as an
unevaluated sum ``hi + lo`` of two float64 with ``|lo| <= ulp(hi)/2``.
That gives ~106 bits of significand (~1e-32 relative), comfortably beyond
the ~1e-18 needed for 1 ns over 30 years.

Correctness rests on *error-free transforms* (Knuth TwoSum, Dekker split /
TwoProd), which require IEEE-754 correctly-rounded float64 add/sub/mul.

.. note::
   Backend validity is established by **evidence, not assumption**:
   :func:`self_check` verifies the TwoSum/TwoProd invariants under ``jit``
   on whichever backend it runs, and the benchmark harness (``bench.py``)
   records its result (``dd_self_check``) next to every timing number so
   the precision claim is auditable per hardware target.

   * XLA **CPU** passes for all *normal-range* float64: identical to
     numpy IEEE arithmetic except that XLA flushes **subnormal** results
     to zero (FTZ) where numpy keeps them (found by hypothesis in round
     2: TwoSum(1.152e-294, 3.956e-305) has exact error term -2.14e-311,
     which XLA returns as 0.0).  The DD contract is therefore bounded:
     **TwoSum** is exact for inputs ``|x| > ~1e-280`` (its error term is
     an integer multiple of ``ulp(min|x|) >= ulp(2^-930) = 2^-982 >
     2^-1022`` and can never be subnormal); **TwoProd** additionally
     needs the *product* in range, ``~1e-150 < |a*b| < ~1e150`` (its
     error term lives at ``ulp(a*b)``, and the Dekker split halves at
     ``~|x| * 2^-27`` must also stay normal — the bounds
     ``tests/test_dd_properties.py::test_two_prod_exact_property``
     enforces).  Scale
     argument for why timing never leaves this domain: the smallest
     hi-words in the pipeline are delays of ~1e-12 s and parameter
     derivatives of ~1e-20; lo-words are bounded below (when nonzero and
     material) by ulps of those, ~1e-36 — more than 240 orders of
     magnitude above the subnormal threshold.  Even a worst-case flush
     loses < 2.2e-308 absolute, ~1e250x below the 1 ns / 30 yr target.
     (Verified in ``tests/test_dd.py``; the FTZ divergence is pinned in
     ``tests/test_dd_properties.py::test_two_sum_subnormal_flush_documented``.)
   * XLA **TPU** emulates float64 and **failed the check on TPU v5e**
     (observed in a round-2 session; **re-confirmed round 4** in a
     ~2-minute live-tunnel window: ``self_check()`` returned False on
     "TPU v5 lite" moments before the tunnel died again — the same
     window also exposed the MXU bf16 demotion fixed in ops/mxu.py.
     DD phase evaluated on-chip yields NaN chi2. A committed
     TPU-backend bench JSON is still pending — every BENCH_r* so far
     is a CPU fallback; tpu_evidence.py captures the full bundle the
     next live window). Consequence: the DD phase pipeline must stay
     on the CPU backend, with only the collapsed-float64 linear algebra
     (design matrix / GLS solve — errors there multiply small parameter
     deltas) offloaded to the chip. Two implementations of that split:
     ``pint_tpu.fitting.hybrid.HybridGLSFitter`` (CPU stage-1 phase/
     design -> accelerator stage-2 seg-GLS solve; used by bench.py) and
     ``GLSFitter(..., solve_device=jax.devices('tpu')[0])`` (dense-basis
     variant).

All functions are shape-polymorphic, jit-safe, and vmap-safe; ``DD`` is a
NamedTuple and hence a pytree.
"""

from __future__ import annotations

import operator
from decimal import Decimal
from fractions import Fraction
from typing import NamedTuple, Union

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

# Dekker splitter for binary64: 2^27 + 1.
_SPLITTER = 134217729.0


class DD(NamedTuple):
    """Unevaluated sum hi + lo of two float64; |lo| <= ulp(hi)/2 when normalized."""

    hi: Array
    lo: Array

    # -- convenience operator sugar (pure functions below do the work) --
    def __add__(self, other):
        return add(self, other)

    def __radd__(self, other):
        return add(self, other)

    def __sub__(self, other):
        return sub(self, other)

    def __rsub__(self, other):
        return sub(_coerce(other), self)

    def __mul__(self, other):
        return mul(self, other)

    def __rmul__(self, other):
        return mul(self, other)

    def __truediv__(self, other):
        return div(self, other)

    def __rtruediv__(self, other):
        return div(_coerce(other), self)

    def __neg__(self):
        return DD(-self.hi, -self.lo)

    @property
    def shape(self):
        return jnp.shape(self.hi)

    @property
    def dtype(self):
        return jnp.asarray(self.hi).dtype

    def __getitem__(self, idx):
        return DD(self.hi[idx], self.lo[idx])

    def astype_f64(self) -> Array:
        """Collapse to a single float64 (loses the low word)."""
        return self.hi + self.lo


DDLike = Union[DD, Array, float, int, np.ndarray]


def _coerce(x: DDLike) -> DD:
    if isinstance(x, DD):
        return x
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


# ---------------------------------------------------------------------------
# Error-free transforms
# ---------------------------------------------------------------------------


def two_sum(a: Array, b: Array) -> tuple[Array, Array]:
    """Knuth TwoSum: s + err == a + b exactly (6 flops, branch-free).

    The pivot sum is guarded too: backend FMA only fuses a MULTIPLY
    into an add, but the observed breakage also reached sums through
    rematerialized products in sibling fusions — guarding the pivot
    keeps every consumer on one rounded value (see _exact).
    """
    s = _exact(a + b)
    bb = s - a
    err = (a - (s - bb)) + (b - bb)
    return s, err


def quick_two_sum(a: Array, b: Array) -> tuple[Array, Array]:
    """Fast TwoSum requiring |a| >= |b| (or a == 0)."""
    s = _exact(a + b)  # see two_sum
    err = b - (s - a)
    return s, err


@jax.custom_jvp
def _exact(x: Array) -> Array:
    """Pin a product's IEEE rounding against backend FMA contraction.

    XLA:CPU's JIT builds its TargetMachine with FP-op fusion enabled,
    so LLVM instruction selection contracts an ``fmul`` feeding an
    ``fadd`` into one fma EVEN THOUGH the emitted IR carries no
    fast-math flags (round-4 find: the dumped optimized HLO/LLVM-IR of
    a jitted ``dd.mul`` is faithful Dekker arithmetic, yet the dumped
    OBJECT CODE contains ``vfmadd213pd`` and the executed result is
    off by ~1 ulp of the product — ~1e-6 relative on the pair, vs the
    ~1e-32 DD contract; eager per-op execution is exact, which is why
    ``self_check`` and the unit tests never caught it). HLO
    ``optimization_barrier`` does NOT survive to codegen on CPU and
    cannot prevent this.

    The guard: a select whose condition is runtime data (``x == x`` —
    true except NaN, where the DD pipeline is already meaningless).
    ISel cannot pattern-match fmul->fadd THROUGH a select, and no
    compiler pass can fold a data-dependent one.
    Applied where the EFT proofs need an intermediate rounding pinned:
    the Dekker splitter product, TwoProd's high product, and the
    TwoSum pivot sums. With the guards, a spindown-scale jitted
    ``dd.mul`` is BITWISE identical to eager (tests/test_dd.py) and
    the fully composed phase program agrees with eager to < 1e-9
    turns (~1 ulp of the plain-f64 Roemer delay — harmless;
    tests/test_model_core.py pins it). An ``optimization_barrier``
    variant achieves bitwise parity for the composed program too, but
    fragments every DD kernel (+5 min suite compile, +8% runtime) for
    precision 5 orders below the ns contract — not worth it. Cost of
    the select guard, measured on the 2e4-TOA CPU GLS bench:
    iteration 0.078 -> 0.107 s and design-matrix build ~2.3x — all in
    the DD phase stage. Accepted deliberately: the alternative is a
    timing code whose compiled phase silently differs from IEEE
    evaluation by tens of ns for fast pulsars on decade baselines.

    **Tangents pass through unguarded** (custom_jvp below): the guard
    exists to pin the *value* chain — the DD residual that must agree
    with IEEE evaluation to the lo word. Derivative columns only ever
    need plain-f64 accuracy (they are collapsed via ``astype_f64`` and
    multiply small parameter deltas in Gauss-Newton; a contracted fma
    in a tangent product shifts a design-matrix entry by ~1 ulp
    relative, ~1e-16), so threading selects through the jacfwd tangent
    graph costs the design-matrix build ~2.3x for nothing. The primal
    inside ``jacfwd(..., has_aux=True)`` keeps its selects, so the
    residual extracted from the same evaluation keeps bitwise parity
    (round-5 clawback of the round-4 regression; pinned by
    tests/test_dd.py::test_jacfwd_primal_keeps_guard).

    NaN handling: the else-branch is NaN (not 0.0), so a NaN entering
    an EFT poisons the hi word too — a consumer reading only hi
    (int_part extraction, masks) sees NaN, not finite garbage
    (round-4 advisor finding). Still a data-dependent select: neither
    branch is foldable and ISel cannot contract through it.
    """
    return jnp.where(x == x, x, jnp.full_like(x, jnp.nan))


@_exact.defjvp
def _exact_jvp(primals, tangents):
    (x,), (dx,) = primals, tangents
    return _exact(x), dx


def split(a: Array) -> tuple[Array, Array]:
    """Dekker split: a == hi + lo with hi, lo having <= 26/27-bit significands."""
    # the guard stops `t - a` contracting into fma(SPLITTER, a, -a),
    # which skips t's rounding and breaks the split (see _exact)
    t = _exact(_SPLITTER * a)
    hi = t - (t - a)
    lo = a - hi
    return hi, lo


def two_prod(a: Array, b: Array) -> tuple[Array, Array]:
    """Dekker TwoProd: p + err == a * b exactly (IEEE multiply required)."""
    # the guard keeps every consumer of p (the err expansion here,
    # two_sum chains in callers) reading the SAME rounded product —
    # without it LLVM contracts one use into an fma and the pair no
    # longer sums to a*b (see _exact)
    p = _exact(a * b)
    ahi, alo = split(a)
    bhi, blo = split(b)
    err = ((ahi * bhi - p) + ahi * blo + alo * bhi) + alo * blo
    return p, err


# ---------------------------------------------------------------------------
# Construction / conversion
# ---------------------------------------------------------------------------


def from_f64(x) -> DD:
    """Lift float64 array (exact) into DD."""
    x = jnp.asarray(x, dtype=jnp.float64)
    return DD(x, jnp.zeros_like(x))


def from_sum(a, b) -> DD:
    """DD representing a + b exactly, for float64 a, b."""
    a = jnp.asarray(a, dtype=jnp.float64)
    b = jnp.asarray(b, dtype=jnp.float64)
    return DD(*two_sum(a, b))


def normalize(x: DD) -> DD:
    """Renormalize so |lo| <= ulp(hi)/2."""
    return DD(*quick_two_sum(*two_sum(x.hi, x.lo)))


def from_string(s: str) -> DD:
    """Parse a decimal string into DD *exactly* (host-side, not jittable).

    This is how par/tim files feed the framework: PINT reads MJDs and F0
    with up to ~20 significant digits into longdouble (reference
    src/pint/pulsar_mjd.py :: str2longdouble); we split the exact decimal
    value into hi = round(x), lo = round(x - hi) via Fraction arithmetic.
    """
    hi, lo = _split_decimal(s)
    # numpy scalars, not device arrays: parsing is host bookkeeping and
    # must not dispatch XLA ops (jit boundaries convert on entry)
    return DD(np.float64(hi), np.float64(lo))


def _split_decimal(s: str) -> tuple[float, float]:
    s = str(s).strip().replace("D", "e").replace("d", "e")
    try:
        frac = Fraction(Decimal(s))
        hi = float(frac)
        lo = float(frac - Fraction(hi))
    except Exception as exc:  # ConversionSyntax, OverflowError, ...
        raise ValueError(f"not a float64-representable decimal: {s!r}") from exc
    return hi, lo


def from_strings(strings) -> DD:
    """Vector version of :func:`from_string` -> DD of shape (n,)."""
    his = np.empty(len(strings), dtype=np.float64)
    los = np.empty(len(strings), dtype=np.float64)
    for i, s in enumerate(strings):
        his[i], los[i] = _split_decimal(s)
    return DD(his, los)


def to_string(x: DD, ndigits: int = 25) -> str:
    """Render a scalar DD to a decimal string with `ndigits` significant digits."""
    from decimal import localcontext

    with localcontext() as ctx:
        ctx.prec = max(ndigits, 40)
        val = Decimal(float(np.asarray(x.hi))) + Decimal(float(np.asarray(x.lo)))
        ctx.prec = ndigits
        return str(+val)


def to_longdouble(x: DD) -> np.ndarray:
    """Host-side conversion to numpy longdouble (for tests/interop)."""
    return np.asarray(jax.device_get(x.hi), np.longdouble) + np.asarray(
        jax.device_get(x.lo), np.longdouble
    )


def from_longdouble(x) -> DD:
    """Host-side conversion from numpy longdouble (exact for 80-bit x86)."""
    x = np.asarray(x, np.longdouble)
    hi = np.asarray(x, np.float64)
    lo = np.asarray(x - np.asarray(hi, np.longdouble), np.float64)
    return DD(jnp.asarray(hi), jnp.asarray(lo))


# ---------------------------------------------------------------------------
# Arithmetic
# ---------------------------------------------------------------------------


def add(x: DDLike, y: DDLike) -> DD:
    """Full-precision DD addition (IEEE TwoSum cascade)."""
    x, y = _coerce(x), _coerce(y)
    s, e = two_sum(x.hi, y.hi)
    t, f = two_sum(x.lo, y.lo)
    e = e + t
    s, e = quick_two_sum(s, e)
    e = e + f
    return DD(*quick_two_sum(s, e))


def sub(x: DDLike, y: DDLike) -> DD:
    y = _coerce(y)
    return add(x, DD(-y.hi, -y.lo))


def mul(x: DDLike, y: DDLike) -> DD:
    x, y = _coerce(x), _coerce(y)
    p, e = two_prod(x.hi, y.hi)
    e = e + (x.hi * y.lo + x.lo * y.hi)
    return DD(*quick_two_sum(p, e))


def div(x: DDLike, y: DDLike) -> DD:
    x, y = _coerce(x), _coerce(y)
    q1 = x.hi / y.hi
    r = sub(x, mul(y, q1))
    q2 = r.hi / y.hi
    r = sub(r, mul(y, q2))
    q3 = r.hi / y.hi
    q, e = quick_two_sum(q1, q2)
    return DD(*quick_two_sum(q, e + q3))


def scale_pow2(x: DD, k: float) -> DD:
    """Multiply by an exact power of two (error-free)."""
    return DD(x.hi * k, x.lo * k)


def neg(x: DD) -> DD:
    return DD(-x.hi, -x.lo)


def abs_(x: DD) -> DD:
    sgn = jnp.where(x.hi < 0, -1.0, 1.0)
    return DD(x.hi * sgn, x.lo * sgn)


def sqr(x: DD) -> DD:
    return mul(x, x)


# ---------------------------------------------------------------------------
# Rounding / modular ops (the phase-wrapping workhorses)
# ---------------------------------------------------------------------------


def floor(x: DD) -> DD:
    """floor(hi+lo) as DD (exact)."""
    fh = jnp.floor(x.hi)
    # if hi is integral the low word decides whether we've already passed floor
    fl = jnp.where(fh == x.hi, jnp.floor(x.lo), 0.0)
    return DD(*quick_two_sum(fh, fl))


def round_half_even_int(x: DD) -> Array:
    """Round to nearest integer (ties arbitrary at DD precision), as float64.

    Only valid when |x| < 2^52 so the result fits a float64 exactly.
    """
    r = jnp.round(x.hi)
    d = (x.hi - r) + x.lo  # exact when |x.hi - r| <= 0.5
    r = r + jnp.round(d)
    # one correction pass for |d| straddling 0.5
    rem = (x.hi - r) + x.lo
    r = r + jnp.where(rem > 0.5, 1.0, 0.0) - jnp.where(rem < -0.5, 1.0, 0.0)
    return r


def split_int_frac(x: DD) -> tuple[Array, DD]:
    """Split into (nearest integer as float64, fractional DD in [-0.5, 0.5])."""
    n = round_half_even_int(x)
    f = add(DD(x.hi - n, jnp.zeros_like(x.hi)), DD(x.lo, jnp.zeros_like(x.lo)))
    # x.hi - n is exact (both near each other), so f = (x.hi-n) + x.lo exactly
    return n, f


def sum_(x: DD) -> DD:
    """Compensated sum of a DD array -> scalar DD (Kahan-style over pairs)."""

    def body(carry, xi):
        return add(carry, DD(xi[0], xi[1])), None

    stacked = jnp.stack([x.hi.ravel(), x.lo.ravel()], axis=-1)
    init = DD(jnp.asarray(0.0, x.hi.dtype), jnp.asarray(0.0, x.hi.dtype))
    out, _ = jax.lax.scan(body, init, stacked)
    return out


def dot_f64(a: Array, x: DD) -> DD:
    """Precise dot product of float64 vector with DD vector."""
    prods = mul(from_f64(a), x)
    return sum_(prods)


# comparisons (on normalized inputs)
def _cmp(x: DDLike, y: DDLike, op) -> Array:
    x, y = _coerce(x), _coerce(y)
    d = sub(x, y)
    z = d.hi + d.lo
    return op(z, 0.0) if op is not operator.eq else (d.hi == 0.0) & (d.lo == 0.0)


def lt(x, y):
    return _cmp(x, y, operator.lt)


def le(x, y):
    return _cmp(x, y, operator.le)


def gt(x, y):
    return _cmp(x, y, operator.gt)


def ge(x, y):
    return _cmp(x, y, operator.ge)


def eq(x, y):
    return _cmp(x, y, operator.eq)


# ---------------------------------------------------------------------------
# Elementary functions (DD-accurate where the framework needs them)
# ---------------------------------------------------------------------------


def polyval(coeffs: list[DD], x: DD) -> DD:
    """Horner evaluation with DD coefficients and DD argument."""
    acc = coeffs[0]
    for c in coeffs[1:]:
        acc = add(mul(acc, x), c)
    return acc


def sin2pi(x: DD) -> Array:
    """sin(2*pi*x) with argument reduction done in DD (result float64).

    For oscillatory terms (WAVE components, binary phases) the *argument*
    is the precision-critical part: x may be ~1e4 revolutions, and float64
    reduction would lose ~1e-12 of a turn. We reduce mod 1 in DD then
    evaluate in float64 (result precision ~1e-16 is ample for delays).
    """
    _, frac = split_int_frac(x)
    ang = frac.hi * (2.0 * np.pi) + frac.lo * (2.0 * np.pi)
    return jnp.sin(ang)


def cos2pi(x: DD) -> Array:
    _, frac = split_int_frac(x)
    ang = frac.hi * (2.0 * np.pi) + frac.lo * (2.0 * np.pi)
    return jnp.cos(ang)


# ---------------------------------------------------------------------------
# Backend validation
# ---------------------------------------------------------------------------

_BACKEND_GUARD_OK: dict = {}


def ensure_backend_guard(device=None) -> bool:
    """Once-per-process EFT gate for plain library use (cached per backend).

    The round-4 FMA-contraction find means the select guard's validity
    is a property of the *toolchain*, not the source: a jaxlib/LLVM
    upgrade whose instruction selection learns to pattern-match through
    a data-dependent select would silently reintroduce ulp-scale phase
    errors in ordinary ``Fitter``/``Residuals`` use, with only
    bench-time ``self_check`` calls standing guard. This runs the full
    :func:`self_check` (per-op EFTs + the whole-program fusion probe)
    the first time a DD phase program is built on each backend
    (``TimingModel._cached_jit`` calls it) and warns loudly on failure
    instead of relying on bench/CI toolchain parity. It deliberately
    warns rather than raises: a failing backend is exactly what the
    hybrid CPU-DD/accelerator-solve split exists to work around, and
    the TPU backend is *expected* to fail (TPU_OBSERVATIONS.json).
    """
    # accept a Device, a platform string (jax.default_device allows
    # 'cpu'/'gpu'/'tpu'), or None (process default backend)
    if device is None:
        key, dev = jax.default_backend(), None
    elif isinstance(device, str):
        key, dev = device, jax.devices(device)[0]
    else:
        key, dev = device.platform, device
    ok = _BACKEND_GUARD_OK.get(key)
    if ok is None:
        ok = self_check(dev)
        _BACKEND_GUARD_OK[key] = ok
        if not ok:
            import warnings

            warnings.warn(
                f"double-double error-free transforms do NOT hold on "
                f"backend {key!r} (per-op or whole-program fusion probe "
                f"failed): DD phase/residual results computed there are "
                f"untrustworthy. Keep DD work on an IEEE float64 CPU "
                f"backend (pint_tpu.fitting.hybrid) — see "
                f"pint_tpu.ops.dd docstring and TPU_OBSERVATIONS.json.",
                RuntimeWarning, stacklevel=2)
    return ok


def self_check(device=None) -> bool:
    """Verify error-free-transform invariants hold on `device`.

    Returns True iff (a) TwoSum and TwoProd are exact under jit on the
    target backend (compared against numpy IEEE float64) AND (b) a
    whole-program fusion probe — a spindown-scale ``dd.mul`` returning
    both words — gives the same results jitted as op-by-op (the round-4
    FMA-contraction class, invisible to per-op checks). This is the
    evidence gate for running the DD phase pipeline on an accelerator —
    bench.py records it per run; see the module docstring for the
    fallback split when a backend fails either way.
    """
    rng = np.random.default_rng(1234)
    a = rng.uniform(-1e9, 1e9, 4096)
    b = rng.uniform(-1e-6, 1e-6, 4096)

    def probe(a, b):
        s, e = two_sum(a, b)
        p, f = two_prod(a, b * 1e6)
        return s, e, p, f

    if device is not None:
        a_d = jax.device_put(a, device)
        b_d = jax.device_put(b, device)
    else:
        a_d, b_d = a, b
    s, e, p, f = jax.jit(probe)(a_d, b_d)
    s, e, p, f = map(np.asarray, (s, e, p, f))

    # reference with numpy (IEEE): same transforms must match bit-for-bit
    s0 = a + b
    bb = s0 - a
    e0 = (a - (s0 - bb)) + (b - bb)
    ok_sum = np.array_equal(s, s0) and np.array_equal(e, e0)

    ld = np.longdouble
    exact = ld(a) * ld(b * 1e6) - ld(p)
    ok_prod = bool(np.max(np.abs(ld(f) - exact)) < 1e-18 * np.max(np.abs(p)))

    # fusion probe: the round-4 FMA-contraction bug was INVISIBLE to
    # the per-op checks above — small programs compile exactly, large
    # fusions contract fmul+fadd at instruction selection (see _exact).
    # A composite spindown-scale chain must give the SAME hi words
    # under whole-program jit as op-by-op (eager) execution on the
    # same device; divergence means compilation-dependent rounding.
    def chain(h, l):
        # exactly the shape that reproduced the contraction: one DD
        # multiply of a spindown-scale pair by a DD scalar, BOTH words
        # out (the two-output program is what splits the computation
        # across fusions and exposes the rematerialized-product
        # inconsistency)
        x = mul(DD(h, l), DD(jnp.float64(478.41687741),
                             jnp.float64(1.3e-15)))
        return x.hi, x.lo

    h = rng.uniform(1e7, 2.6e8, 4096)
    low = rng.uniform(-1e-9, 1e-9, 4096)
    if device is not None:
        h = jax.device_put(h, device)
        low = jax.device_put(low, device)
    hi_jit, lo_jit = jax.jit(chain)(h, low)
    hi_eager, lo_eager = chain(jnp.asarray(h), jnp.asarray(low))
    # hi bitwise; lo words directly (a float64 collapse would round the
    # lo contribution away entirely at these magnitudes) — divergence
    # below 1e-20 absolute is the harmless error-term cross-product
    # contraction, anything larger is compilation-dependent rounding
    ok_fused = (np.array_equal(np.asarray(hi_jit), np.asarray(hi_eager))
                and bool(np.max(np.abs(np.asarray(lo_jit)
                                       - np.asarray(lo_eager))) < 1e-20))
    return bool(ok_sum and ok_prod and ok_fused)
