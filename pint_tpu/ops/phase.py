"""Pulse-phase container with exact integer part.

Reference equivalent: ``pint.phase.Phase`` (src/pint/phase.py), a
(longdouble int, longdouble frac) 2-tuple. Here the integer part is a
float64 holding an exact integer (|n| < 2^53 covers any realistic pulse
count; a 30-yr, 700 Hz pulsar accumulates ~7e11 turns) and the fractional
part is a double-double in [-0.5, 0.5].
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from pint_tpu.ops import dd
from pint_tpu.ops.dd import DD

Array = jax.Array


class Phase(NamedTuple):
    """Pulse phase = int_part + frac, with frac a DD in [-0.5, 0.5]."""

    int_part: Array  # exact integers stored as float64
    frac: DD

    def __add__(self, other: "Phase") -> "Phase":
        return add(self, other)

    def __sub__(self, other: "Phase") -> "Phase":
        return add(self, neg(other))

    def __neg__(self) -> "Phase":
        return neg(self)

    def total(self) -> DD:
        """Full phase as DD turns (int + frac)."""
        return dd.add(dd.from_f64(self.int_part), self.frac)

    def total_f64(self) -> Array:
        return self.int_part + self.frac.hi + self.frac.lo


def from_dd(x: DD) -> Phase:
    """Wrap a DD turn count into (int, frac in [-0.5, 0.5])."""
    n, f = dd.split_int_frac(x)
    return Phase(n, f)


def from_f64(x: Array) -> Phase:
    return from_dd(dd.from_f64(x))


def zero_like(x: Array) -> Phase:
    z = jnp.zeros_like(jnp.asarray(x, jnp.float64))
    return Phase(z, DD(z, z))


def add(a: Phase, b: Phase) -> Phase:
    """Exact phase addition with re-wrapping of the fractional part."""
    n = a.int_part + b.int_part
    f = dd.add(a.frac, b.frac)  # |f| <= 1
    k, f = dd.split_int_frac(f)
    return Phase(n + k, f)


def neg(a: Phase) -> Phase:
    return Phase(-a.int_part, dd.neg(a.frac))
