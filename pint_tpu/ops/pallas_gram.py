"""Pallas TPU kernel: double-single f32 Gram matrix with compensated
accumulation.

The hot op of the north-star GLS iteration (SURVEY §5: the
``(p+k)² `` Gram of the whitened design+noise block over 6×10⁵ TOAs) as
a hand-tiled TPU kernel. Rationale over the XLA formulation in
:mod:`pint_tpu.ops.mxu`:

* Pallas on TPU has **no float64** — but it doesn't need it. The three
  double-single products A1ᵀA1 + A1ᵀA2 + A2ᵀA1 run on the MXU in f32,
  and the cross-block reduction is carried in a **compensated (hi, lo)
  f32 pair** via the TwoSum error-free transform, which *is* exact in
  hardware f32 (unlike the chip's emulated f64, whose error-free
  transforms fail — observed on TPU v5e round 2, re-confirmed on
  hardware round 4; committed artifact pending, see tpu_evidence.py;
  the fact behind the whole hybrid design, see
  ``pint_tpu.ops.dd``). Net precision matches
  :func:`pint_tpu.ops.mxu.ds32_gram`'s f64 block accumulation
  (~2⁻⁴⁸ representation + ~√B·2⁻²⁴ per-block MXU floor).
* One kernel = one pass over A in VMEM: the split products and the
  reduction fuse, with no (nb, q, q) f64 intermediates in HBM and no
  emulated-f64 adds at all.

Reference equivalent: none — upstream PINT runs LAPACK dgemm on the
host (SURVEY §2.5); this kernel is the TPU-native replacement for the
same linear-algebra step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def _round_up(x: int, m: int) -> int:
    return -(-x // m) * m


_I32_ZERO = np.int32(0)


def _gram_kernel(a1_ref, a2_ref, hi_ref, lo_ref):
    """One n-block: ds32 partial product + compensated accumulation."""
    import jax.experimental.pallas as pl

    a1 = a1_ref[:]
    a2 = a2_ref[:]

    def xtx(x, y):  # x^T y on the MXU, f32 accumulate
        # HIGHEST is load-bearing: at default precision the TPU MXU
        # demotes f32 operands to bf16 (~2^-11 per product — observed
        # on TPU v5e, round 4), which swamps the double-single split.
        return jax.lax.dot_general(
            x, y, (((0,), (0,)), ((), ())),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    p = xtx(a1, a1) + (xtx(a1, a2) + xtx(a2, a1))

    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        hi_ref[:] = p
        lo_ref[:] = jnp.zeros_like(p)

    @pl.when(i > 0)
    def _accumulate():
        # TwoSum(hi, p): exact in hardware f32 (IEEE round-to-nearest)
        a = hi_ref[:]
        s = a + p
        bv = s - a
        err = (a - (s - bv)) + (p - bv)
        hi_ref[:] = s
        lo_ref[:] = lo_ref[:] + err


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def ds32_gram_pallas(A: Array, *, block: int = 1024,
                     interpret: bool = False) -> Array:
    """AᵀA (f64 in/out) via the pallas double-single kernel.

    A: (n, q) float64, columns pre-whitened/normalized to O(1) (the
    GLS callers guarantee this — see gls_gram_whitened). ``interpret``
    runs the kernel in the pallas interpreter (CPU tests).
    """
    import jax.experimental.pallas as pl

    n, q = A.shape
    qp = _round_up(max(q, 1), 128)
    bn = min(block, _round_up(max(n, 1), 8))
    nb = -(-n // bn)

    a1 = A.astype(jnp.float32)
    a2 = (A - a1.astype(jnp.float64)).astype(jnp.float32)
    # zero-pad: extra rows/cols contribute exact zeros to the Gram
    a1 = jnp.pad(a1, ((0, nb * bn - n), (0, qp - q)))
    a2 = jnp.pad(a2, ((0, nb * bn - n), (0, qp - q)))

    out_shape = jax.ShapeDtypeStruct((qp, qp), jnp.float32)
    hi, lo = pl.pallas_call(
        _gram_kernel,
        grid=(nb,),
        in_specs=[
            # index maps avoid python-int literals: under enable_x64 a
            # literal 0 traces as i64 next to the i32 program id, and
            # Mosaic rejects the (i32, i64) index tuple (observed on
            # TPU v5e, round 4)
            pl.BlockSpec((bn, qp), lambda i: (i, _I32_ZERO)),
            pl.BlockSpec((bn, qp), lambda i: (i, _I32_ZERO)),
        ],
        out_specs=[
            pl.BlockSpec((qp, qp), lambda i: (_I32_ZERO, _I32_ZERO)),
            pl.BlockSpec((qp, qp), lambda i: (_I32_ZERO, _I32_ZERO)),
        ],
        out_shape=[out_shape, out_shape],
        interpret=interpret,
    )(a1, a2)
    return (hi[:q, :q].astype(jnp.float64)
            + lo[:q, :q].astype(jnp.float64))


def gram_error_bound(n: int, block: int = 1024) -> float:
    """Loose relative error estimate (mirrors mxu.ds32_gram_error_bound)."""
    per_block = np.sqrt(min(n, block)) * 2.0 ** -24
    return float(per_block * 3.0 + 2.0 ** -48)
