"""Time-scale conversions: UTC -> TAI -> TT -> TDB, in double-double MJD.

Replaces the reference's reliance on ``astropy.time`` + the ERFA C library
(reference: src/pint/toa.py :: TOAs.compute_TDBs, src/pint/pulsar_mjd.py).
Neither astropy nor erfa exists on this machine (SURVEY.md §2.4), so the
chain is built from first principles:

* **Leap seconds** (TAI-UTC): step table shipped in
  :data:`pint_tpu.data.leapseconds.LEAP_MJD` / ``LEAP_TAI_MINUS_UTC``,
  current through 2017-01-01 (TAI-UTC = 37 s; no leap second has been
  scheduled since, as of 2026). Pluggable for updates.
* **TT = TAI + 32.184 s** (exact by definition).
* **TDB - TT**: truncated Fairhead & Bretagnon (1990) harmonic series
  (the same family ERFA's ``dtdb.c`` implements with 787 terms). We ship
  the principal terms in :mod:`pint_tpu.data.fb1990`; truncation +
  offline-recalled coefficients bound the absolute accuracy at the
  ~0.1-1 us level. This is documented, acceptable for self-consistent
  simulate->fit workflows, and the table is data (swap in the full ERFA
  table for exact parity when available).
* **Topocentric Einstein term** ``v_earth . r_obs / c^2`` (diurnal,
  ~2 us amplitude) is applied by the data layer when observatory position
  vectors are available.

Conventions
-----------
All epochs are double-double MJD *days* in a named scale. A day is always
86400 s of its scale ("pulsar MJD" convention for UTC: the day fraction is
interpreted against 86400 even across leap seconds, matching PINT's
``pulsar_mjd`` format; reference src/pint/pulsar_mjd.py).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.data.fb1990 import FB1990_T0, FB1990_T1, FB1990_T2
from pint_tpu.data.leapseconds import LEAP_MJD, LEAP_TAI_MINUS_UTC
from pint_tpu.ops import dd
from pint_tpu.ops.dd import DD

from pint_tpu.constants import (  # noqa: F401  (re-exported)
    C_M_S, JULIAN_MILLENNIUM_DAYS, MJD_J2000, SECS_PER_DAY, TT_MINUS_TAI_S,
)

# numpy at module scope: a jnp array here would initialize the default
# backend at import time (observed to hang on the flaky axon tunnel);
# jnp ops convert these to on-device constants at trace time anyway
_LEAP_MJD = np.asarray(LEAP_MJD, np.float64)
_LEAP_OFF = np.asarray(LEAP_TAI_MINUS_UTC, np.float64)


def tai_minus_utc(mjd_utc_day: jax.Array) -> jax.Array:
    """TAI-UTC in seconds at the given UTC MJD (float64 day is ample)."""
    idx = jnp.clip(jnp.searchsorted(_LEAP_MJD, mjd_utc_day, side="right") - 1, 0, None)
    return jnp.asarray(_LEAP_OFF)[idx]


def utc_to_tai(mjd_utc: DD) -> DD:
    off_days = tai_minus_utc(mjd_utc.hi) / SECS_PER_DAY
    return dd.add(mjd_utc, off_days)


def tai_to_tt(mjd_tai: DD) -> DD:
    return dd.add(mjd_tai, TT_MINUS_TAI_S / SECS_PER_DAY)


def utc_to_tt(mjd_utc: DD) -> DD:
    return tai_to_tt(utc_to_tai(mjd_utc))


def _fb_eval(t_millennia: jax.Array) -> jax.Array:
    """Fairhead-Bretagnon harmonic series: TDB-TT in seconds (float64).

    Sum over groups g of T^g * sum_i A_i sin(w_i T + phi_i), amplitudes in
    microseconds. Evaluated in float64: the result is ~1.7e-3 s with
    required absolute accuracy ~1e-9 s, i.e. ~1e-6 relative — far above
    float64 noise, so no DD needed *inside* the series. Shape-polymorphic.
    """
    T = t_millennia[..., None]  # broadcast against the term axis
    total = jnp.zeros(jnp.shape(t_millennia))
    for power, table in enumerate((FB1990_T0, FB1990_T1, FB1990_T2)):
        amp, freq, phase = (jnp.asarray(col, jnp.float64) for col in table)
        terms = amp * jnp.sin(freq * T + phase)
        total = total + (t_millennia**power) * jnp.sum(terms, axis=-1)
    return total * 1e-6


def tdb_minus_tt(mjd_tt: DD) -> jax.Array:
    """TDB-TT in seconds at geocenter (float64)."""
    t = (mjd_tt.hi - MJD_J2000 + mjd_tt.lo) / JULIAN_MILLENNIUM_DAYS
    return _fb_eval(jnp.atleast_1d(t))


def tt_to_tdb(mjd_tt: DD, topo_correction_s: jax.Array | None = None) -> DD:
    """TT -> TDB. `topo_correction_s` adds the observatory Einstein term."""
    corr = tdb_minus_tt(mjd_tt)
    corr = corr.reshape(jnp.shape(mjd_tt.hi)) if jnp.ndim(mjd_tt.hi) else corr[0]
    if topo_correction_s is not None:
        corr = corr + topo_correction_s
    return dd.add(mjd_tt, corr / SECS_PER_DAY)


def utc_to_tdb(mjd_utc: DD, topo_correction_s: jax.Array | None = None) -> DD:
    return tt_to_tdb(utc_to_tt(mjd_utc), topo_correction_s)


def dt_seconds(t: DD, epoch: DD) -> DD:
    """(t - epoch) in seconds, both DD MJD days — the fundamental Δt."""
    return dd.mul(dd.sub(t, epoch), SECS_PER_DAY)


def mjd_string_to_dd(s: str) -> DD:
    """Exact decimal MJD string -> DD days (host-side)."""
    return dd.from_string(s)


def topocentric_einstein_s(v_earth_m_s: jax.Array, r_obs_m: jax.Array) -> jax.Array:
    """v_E . r_obs / c^2 — diurnal topocentric piece of TDB-TT (seconds).

    v_earth: (..., 3) SSB velocity of geocenter [m/s]; r_obs: (..., 3)
    geocentric observatory position in the same frame [m].
    """
    return jnp.sum(v_earth_m_s * r_obs_m, axis=-1) / (C_M_S * C_M_S)
