"""Numeric primitives: double-double arithmetic, phase containers, time scales."""

from pint_tpu.ops import dd
from pint_tpu.ops.dd import DD

__all__ = ["dd", "DD"]
