"""MXU-friendly high-precision matmul: double-single float32 Gram.

Why: the TPU executes float64 by software emulation at ~1/100 of host
CPU throughput (observed in a round-2 session on TPU v5e — the 1e5-TOA
Gram took ~1.1 s emulated vs ~10 ms of CPU f64; committed artifact
pending, to be recorded in a TPU-backend bench JSON the first session
the tunnel revives), while its MXU runs float32 matmuls at full speed.
For the GLS Gram matrix G = A^T A of a *whitened, column-normalized*
design block (entries O(1) — see gls_gram_whitened), the right TPU
program is the classic double-single split:

    A = A1 + A2,  A1 = f32(A),  A2 = f32(A - A1)
    G ~= A1^T A1 + A1^T A2 + A2^T A1      (A2^T A2 ~ 2^-48: dropped)

— three MXU matmuls. Representation error is ~2^-48 relative;
*accumulation* error of the f32 MXU (which accumulates in f32) is the
floor: ~sqrt(B) 2^-24 per block, so the contraction axis is chunked
(`block` rows) with the per-block (q, q) products accumulated in f64.
Net relative error ~1e-6..1e-7 on G — used ONLY for the Gauss-Newton
step operator and the covariance, never for the gradient c_B = A^T r,
which stays in exact f64 (it is O(n q), cheap even emulated): the
iterated solve therefore converges to the f64 answer, an approximate
Hessian only perturbs the path, not the fixed point.

This trades nothing on CPU (where plain f64 is fastest); callers gate
it on the accelerator platform.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def ds32_gram(A: Array, B: Array | None = None, *, block: int = 32768,
              use_pallas: bool = False) -> Array:
    """A^T B (f64 in/out) via double-single f32 MXU matmuls.

    A: (n, p); B: (n, q) (defaults to A -> the Gram A^T A). The n axis
    is chunked into `block`-row slabs whose f32 partial products are
    accumulated in f64. ``use_pallas`` routes the square Gram through
    the hand-tiled kernel (:mod:`pint_tpu.ops.pallas_gram`), which
    carries the cross-block reduction in compensated hardware-f32 pairs
    instead of emulated f64 — same precision band, zero emulated ops.
    """
    if B is None:
        if use_pallas:
            from pint_tpu.ops.pallas_gram import ds32_gram_pallas

            return ds32_gram_pallas(A)
        B = A
    n, p = A.shape
    q = B.shape[1]
    block = min(block, max(n, 1))  # small inputs (ECORR Schur term) must
    nb = -(-n // block)            # not pad to a full-size slab
    pad = nb * block - n
    if pad:
        A = jnp.concatenate([A, jnp.zeros((pad, p), A.dtype)])
        B = jnp.concatenate([B, jnp.zeros((pad, q), B.dtype)])

    a1 = A.astype(jnp.float32)
    a2 = (A - a1.astype(jnp.float64)).astype(jnp.float32)
    b1 = B.astype(jnp.float32)
    b2 = (B - b1.astype(jnp.float64)).astype(jnp.float32)

    a1 = a1.reshape(nb, block, p)
    a2 = a2.reshape(nb, block, p)
    b1 = b1.reshape(nb, block, q)
    b2 = b2.reshape(nb, block, q)

    def mm(x, y):  # (nb, B, p) x (nb, B, q) -> (nb, p, q), f32 on the MXU
        # HIGHEST is load-bearing: at default precision the TPU MXU
        # demotes f32 operands to bf16 (~2^-11 per product — observed
        # on TPU v5e, round 4), which swamps the double-single split.
        return jax.lax.dot_general(
            x, y, (((1,), (1,)), ((0,), (0,))),
            precision=jax.lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32)

    g = (mm(a1, b1).astype(jnp.float64)
         + mm(a1, b2).astype(jnp.float64)
         + mm(a2, b1).astype(jnp.float64))
    return jnp.sum(g, axis=0)


def ds32_gram_error_bound(n: int, block: int = 32768) -> float:
    """Loose relative error estimate for documentation/tests."""
    nb = -(-n // block)
    per_block = np.sqrt(min(n, block)) * 2.0 ** -24
    return float(per_block / max(np.sqrt(nb), 1.0) * 3.0 + 2.0 ** -48)
