"""Sessionful serving: cached on-device fit state + incremental refits.

ROADMAP item 3 / ISSUE 10: the throughput engine's missing piece
between "fast cold fits" and "fast service". A *session* is one user's
evolving dataset: per-``(session_id, structure fingerprint)`` the cache
holds the live fitted model, the accumulated TOA table (host side,
append-only) and — for models the incremental path can express — the
on-device state the fused rank-k update consumes
(:mod:`pint_tpu.fitting.incremental`: normalized Gram Cholesky factor,
column norms, absorbed mean, converged chi2; donated buffers on
accelerators).

Request routing (see :class:`SessionJob`):

* first request for a key -> **populate**: a normal full fused fit,
  committed as session state (the device snapshot is taken only for
  TZR-anchored batchable WLS models — exactly the fused incremental
  step's domain);
* append with live device state, inside the gates -> **incremental**:
  ONE fused launch folds the new TOAs in via the rank-k Cholesky
  update with warm-started damped iterations (flight recorder riding
  the carry), one fetch returns solution + uncertainties + the
  replacement state;
* anything else -> **full refit** over the accumulated table, warm-
  started from the session model's current (converged) values — GLS /
  wideband / anchorless / non-batchable models are therefore fully
  sessionable, they just pay the full-fit price; a refit REPOPULATES
  the device state through the same code path a cold populate uses, so
  the gated path is bitwise the cold path (pinned in
  tests/test_session.py).

**Drift gate.** The incremental update is recursive least squares: for
a linear model it is exact; the pulsar phase model is locally linear,
so the cached quadratic summary of old rows drifts as parameters move.
Two gates force a full refit: an append-count cap
(``PINT_TPU_SESSION_MAX_APPENDS``, default 16) and a cumulative
parameter-motion gate (``PINT_TPU_SESSION_DRIFT_SIGMA``, default 1.0 —
the sum over appends of the largest parameter move measured in its own
posterior sigma). Inside the gates the observed chi2 drift against a
full refit is bounded by :data:`DRIFT_CHI2_REL` (the documented
acceptance; measured by the BENCH_r13 A/B and the CI smoke).

**Eviction / backpressure.** Device state is LRU-evicted under the byte
budget (``PINT_TPU_SESSION_BYTES``, default 64 MiB). Eviction drops
ONLY the device buffers — the committed solution (model values,
uncertainties, accumulated table) stays host-side, so a later append
full-refits and repopulates: nothing is ever lost silently. When a new
state cannot be admitted even after evicting every unpinned entry
(entries referenced by still-queued requests are pinned),
:meth:`SessionCache.check_admission` raises :class:`SessionCacheFull`
— the ``ServeQueueFull``-style contract with a ``retry_after_s`` hint
— at *submit* time, before any work is queued.
"""

from __future__ import annotations

import collections
import dataclasses
from pint_tpu import config
import time
from typing import Any

import numpy as np

from pint_tpu import telemetry
from pint_tpu.serve import fingerprint as _fp

#: documented chi2-drift acceptance of the incremental path, relative
#: to a full refit over the same accumulated table, while inside the
#: append/motion gates (asserted by bench --smoke and BENCH_r13)
DRIFT_CHI2_REL = 1e-3



def byte_budget() -> int:
    """Session-cache device-byte budget (read per call for tests)."""
    return config.env_int("PINT_TPU_SESSION_BYTES")


def max_appends() -> int:
    """Append-count gate: full refit after this many rank-k updates."""
    return config.env_int("PINT_TPU_SESSION_MAX_APPENDS")


def drift_limit_sigma() -> float:
    """Cumulative parameter-motion gate [posterior sigmas]."""
    return config.env_float("PINT_TPU_SESSION_DRIFT_SIGMA")


def _session_family(model, toas) -> str | None:
    """Incremental family a (model, toas) structure snapshots under.

    ``"wls"`` -> the rank-k QR update; ``"gls"`` -> the Schur rank-k
    update (ISSUE 20, gated by ``PINT_TPU_SESSION_GLS``); ``None`` ->
    stateless (full refit per append): non-batchable structures,
    anchorless models, wideband (joint TOA+DM rows fit neither update's
    row convention), and gated-off GLS.
    """
    ok, _ = _fp.batchable(model, toas)
    if not ok or model.get_tzr_toas() is None:
        return None
    fam = _fp.family(model, toas)
    if fam == "wls":
        return "wls"
    if fam == "gls" and config.env_on("PINT_TPU_SESSION_GLS"):
        return "gls"
    return None


class SessionCacheFull(RuntimeError):
    """Session-state admission failed: every evictable entry is pinned
    by queued requests and the budget has no room. The ``ServeQueueFull``
    contract: carries ``bytes_requested`` / ``bytes_in_use`` /
    ``budget`` and a ``retry_after_s`` hint (drain the scheduler, then
    retry)."""

    def __init__(self, bytes_requested: int = 0, bytes_in_use: int = 0,
                 budget: int = 0, retry_after_s: float | None = None):
        self.bytes_requested = bytes_requested
        self.bytes_in_use = bytes_in_use
        self.budget = budget
        self.retry_after_s = retry_after_s
        msg = (f"session cache at capacity ({bytes_in_use}/{budget} B in "
               f"use, {bytes_requested} B requested, every resident "
               "state pinned by queued requests); drain() first")
        if retry_after_s is not None:
            msg += f" and retry after ~{retry_after_s:g}s"
        super().__init__(msg)


@dataclasses.dataclass
class SessionEntry:
    """One (session_id, fingerprint)'s committed solution + state."""

    session_id: Any
    fp: tuple                  # structure fingerprint
    fp8: str                   # short id (telemetry label)
    model: Any = None          # live fitted model (host)
    toas: Any = None           # merged accumulated table (host)
    #: appended-but-unmerged tables. ``merge_TOAs`` over a 1e5-row
    #: table costs ~150 ms of host concatenates — measured as ~ALL of
    #: the incremental update's p50 when done eagerly per append — so
    #: accumulation is LAZY: appends stack here and merge only when a
    #: full refit actually needs the whole table
    pending: list = dataclasses.field(default_factory=list)
    state: dict | None = None  # on-device incremental state, or None
    names: list | None = None  # state-vector param order
    off: int = 0               # offset-coordinate count
    #: incremental family of the committed state: "wls" (rank-k QR
    #: update) or "gls" (Schur rank-k update, ISSUE 20); None while
    #: stateless
    family: str | None = None
    state_bytes: int = 0
    chi2: float = float("nan")
    n_toas: int = 0
    appends: int = 0           # rank-k updates since last full refit
    drift: float = 0.0         # cumulative motion [sigma] since refit
    pins: int = 0              # queued requests referencing this entry
    #: commit version (ISSUE 11): bumped on every committed populate/
    #: refit/incremental update; read artifacts record the version they
    #: were built from and the segment cache refuses a mismatch
    version: int = 0

    def accumulated(self):
        """The full committed table, merging any pending appends."""
        if self.pending:
            from pint_tpu.toas import merge_TOAs

            self.toas = merge_TOAs([self.toas] + self.pending)
            self.pending = []
        return self.toas


class SessionCache:
    """LRU session store under a device-byte budget.

    One instance per :class:`~pint_tpu.serve.scheduler
    .ThroughputScheduler` by default; shareable across schedulers. All
    mutation happens on the scheduler's thread (the serve layer is
    deliberately thread-free).
    """

    def __init__(self, budget_bytes: int | None = None):
        self._budget = budget_bytes
        self.entries: "collections.OrderedDict[tuple, SessionEntry]" = \
            collections.OrderedDict()
        self._by_sid: dict[Any, tuple] = {}  # sid -> most recent key
        self.bytes_in_use = 0
        self.evictions = 0
        # read-path invalidation hooks (ISSUE 11): segment caches whose
        # artifacts derive from this cache's committed models
        self._read_caches: list = []

    @property
    def budget(self) -> int:
        return self._budget if self._budget is not None else byte_budget()

    # ------------------------------------------------------------------
    # lookup / routing
    # ------------------------------------------------------------------
    def resolve(self, request) -> tuple[tuple, SessionEntry | None, tuple]:
        """(cache key, entry or None, fingerprint) for one request.

        An append may omit ``model`` — the session's own model is
        authoritative; when a model IS passed, its fingerprint keys the
        lookup, so a same-sid request with a different structure opens
        a separate session entry (the cache key is (sid, fingerprint)).
        """
        sid = request.session_id
        if request.model is None:
            key = self._by_sid.get(sid)
            if key is None:
                raise ValueError(
                    f"session {sid!r} has no committed state and the "
                    "request carries no model; the first request of a "
                    "session must include one")
            return key, self.entries[key], self.entries[key].fp
        fp = _fp.structure_fingerprint(request.model, request.toas)
        key = (sid, _fp.short_id(fp))
        return key, self.entries.get(key), fp

    def lookup_for_read(self, session_id) -> tuple[tuple, SessionEntry]:
        """(key, entry) of a session's committed solution for the read
        path (ISSUE 11). Reads are served from the HOST model — device
        fit-state eviction never affects them — and never pin."""
        key = self._by_sid.get(session_id)
        if key is None or self.entries[key].model is None:
            raise ValueError(
                f"session {session_id!r} has no committed solution to "
                "read from; fit (populate) it first")
        self.entries.move_to_end(key)
        return key, self.entries[key]

    def attach_read_cache(self, cache) -> None:
        """Register a segment cache for commit invalidation (anything
        with ``invalidate_session(key)``)."""
        if cache not in self._read_caches:
            self._read_caches.append(cache)

    def notify_commit(self, key: tuple) -> None:
        """A populate/refit/incremental update committed new parameter
        values for ``key``: bump the entry's version and drop every
        read artifact derived from the old one, so a refit is
        immediately visible to readers (the invalidation-on-commit
        rule, docs/ARCHITECTURE.md "The read path")."""
        e = self.entries.get(key)
        if e is not None:
            e.version += 1
        for c in self._read_caches:
            c.invalidate_session(key)

    def touch(self, key: tuple) -> None:
        if key in self.entries:
            self.entries.move_to_end(key)

    def pin(self, key: tuple) -> None:
        e = self.entries.get(key)
        if e is not None:
            e.pins += 1

    def unpin(self, key: tuple) -> None:
        e = self.entries.get(key)
        if e is not None and e.pins > 0:
            e.pins -= 1

    # ------------------------------------------------------------------
    # admission / eviction (the backpressure contract)
    # ------------------------------------------------------------------
    def estimate_bytes(self, model) -> int:
        """Device bytes a session state for ``model`` will occupy."""
        q = len(model.free_params) \
            + (0 if model.has_component("PhaseOffset") else 1)
        return 8 * (q * q + q + 2)

    def check_admission(self, nbytes: int,
                        retry_after_s: float | None = None) -> None:
        """Raise :class:`SessionCacheFull` when ``nbytes`` of NEW state
        could not be admitted even after evicting every unpinned
        resident state. Called on the submit path — backpressure fires
        before work is queued, never silently mid-drain."""
        if nbytes > self.budget:
            # a single state larger than the whole budget is not
            # backpressure (no amount of draining helps): it is served
            # stateless (full refit per append) and counted
            return
        free = self.budget - self.bytes_in_use
        evictable = sum(e.state_bytes for e in self.entries.values()
                        if e.state is not None and e.pins == 0)
        if nbytes > free + evictable:
            telemetry.inc("serve.session.admission_rejected")
            raise SessionCacheFull(
                bytes_requested=nbytes, bytes_in_use=self.bytes_in_use,
                budget=self.budget, retry_after_s=retry_after_s)

    def _evict_for(self, nbytes: int, keep: tuple) -> bool:
        """Evict LRU unpinned device states until ``nbytes`` fit.

        Eviction order is strict LRU over entries *with* device state
        (insertion order refreshed by :meth:`touch`). Only the device
        buffers are dropped — the committed solution survives."""
        if nbytes > self.budget:
            return False
        for key in list(self.entries):
            if self.bytes_in_use + nbytes <= self.budget:
                break
            e = self.entries[key]
            if key == keep or e.state is None or e.pins > 0:
                continue
            self.evict(key)
        return self.bytes_in_use + nbytes <= self.budget

    def evict(self, key: tuple) -> None:
        """Drop one entry's device state (the solution is kept)."""
        e = self.entries[key]
        if e.state is None:
            return
        self.bytes_in_use -= e.state_bytes
        e.state = None
        e.state_bytes = 0
        self.evictions += 1
        telemetry.inc("serve.session.evictions")

    def invalidate(self, key: tuple) -> None:
        """Drop a key's device state after a dispatched-but-uncommitted
        update (failed dispatch/fetch): on accelerators the buffers
        were DONATED to the failed program and must never be read
        again — the committed host solution stays; the next append
        full-refits and repopulates."""
        e = self.entries.get(key)
        if e is not None and e.state is not None:
            self.evict(key)

    def drop(self, session_id) -> None:
        """Forget a session entirely (host solution included) — the
        caller-driven lifecycle end; never done implicitly. Read
        artifacts derived from the dropped solution go with it (they
        would otherwise sit orphaned in the segment-cache budget)."""
        for key in [k for k in self.entries if k[0] == session_id]:
            self.evict(key)
            del self.entries[key]
            for c in self._read_caches:
                c.invalidate_session(key)
        self._by_sid.pop(session_id, None)

    # ------------------------------------------------------------------
    # commit
    # ------------------------------------------------------------------
    def entry_for(self, key: tuple, fp: tuple) -> SessionEntry:
        e = self.entries.get(key)
        if e is None:
            e = SessionEntry(session_id=key[0], fp=fp, fp8=key[1])
            self.entries[key] = e
        self._by_sid[key[0]] = key
        self.entries.move_to_end(key)
        return e

    def commit_state(self, key: tuple, state: dict | None,
                     nbytes: int) -> bool:
        """Install (or clear) an entry's device state under the budget;
        returns False when the state was not admitted (entry stays
        stateless; appends full-refit)."""
        e = self.entries[key]
        if e.state is not None:
            self.bytes_in_use -= e.state_bytes
            e.state, e.state_bytes = None, 0
        if state is None:
            return True
        if not self._evict_for(nbytes, key):
            telemetry.inc("serve.session.uncacheable")
            return False
        e.state = state
        e.state_bytes = nbytes
        self.bytes_in_use += nbytes
        telemetry.set_gauge("serve.session.bytes", self.bytes_in_use)
        return True

    def adopt(self, key: tuple, fp: tuple, model, toas,
              chi2: float) -> SessionEntry:
        """Install a REPLICATED committed solution as this cache's own
        state (ISSUE 13 warm failover): the ring successor receives the
        dead host's small summary (fitted model, chi2, append count)
        plus the journal's accumulated table and adopts it exactly as
        if its own populate had committed it — including the device
        snapshot when the model is inside the incremental step's
        domain, so the very next append takes the rank-k path. Gates
        reset: the adopted point is a converged solution, the same
        fresh start a populate commit gives."""
        e = self.entry_for(key, fp)
        e.model = model
        e.toas = toas
        e.pending = []
        e.n_toas = len(toas)
        e.appends = 0
        e.drift = 0.0
        e.chi2 = float(chi2)
        try:
            family = _session_family(model, toas)
        except Exception:  # noqa: BLE001 — snapshot is an optimization
            family = None
        if family is not None:
            if family == "gls":
                from pint_tpu.fitting import gls_incremental as _mod
            else:
                from pint_tpu.fitting import incremental as _mod

            snap = _mod.snapshot_state(model, toas)
            e.names, e.off = snap["names"], snap["off"]
            e.family = family
            self.commit_state(key, snap["state"], snap["bytes"])
        else:
            self.commit_state(key, None, 0)
            e.names, e.off, e.family = None, 0, None
        self.notify_commit(key)
        telemetry.inc("serve.session.adopted")
        return e

    def stats(self) -> dict:
        with_state = sum(1 for e in self.entries.values()
                         if e.state is not None)
        return {"entries": len(self.entries), "with_state": with_state,
                "bytes": self.bytes_in_use, "budget": self.budget,
                "evictions": self.evictions}


# ----------------------------------------------------------------------
# per-request execution (driven by the scheduler's drain stages)
# ----------------------------------------------------------------------

#: route tokens (drain records / counters / batch_detail)
ROUTES = ("populate", "incremental", "full_refit")


class SessionJob:
    """One session request walked through prep -> dispatch -> finish.

    Mirrors the scheduler's other batch-state objects: ``prep`` decides
    the route (gates read HERE, once per request), ``dispatch``
    enqueues the fused incremental program asynchronously (or runs the
    host-synchronous full refit, stamping its completion time), and
    ``finish`` performs the single fetch, writes fitted values back
    into the session model, commits the replacement state and returns
    the envelope fields. An incremental update that diverges falls back
    to a full refit (attempts=2) — correctness is always pinned against
    the cold path.
    """

    def __init__(self, cache: SessionCache, key: tuple, fp: tuple,
                 request, mode: str):
        self.cache = cache
        self.key = key
        self.fp = fp
        self.request = request
        self.mode = mode          # "create" | "append"
        self.route = None         # set at prep
        self.reason = ""
        self.attempts = 1
        self._handle = None
        self._result = None
        self._t0 = None
        self.t_done = None
        self.wall_s = None
        #: set by :class:`SessionBatch` when this job rides a vmapped
        #: multi-session launch: the batch handle + this job's member
        #: index on the stacked axis
        self._batch = None
        self._member = None
        self.launch = None        # "solo" | "batched" | None (full path)

    # -- helpers -------------------------------------------------------
    def _hyper(self) -> dict:
        r = self.request
        return dict(maxiter=r.maxiter,
                    min_chi2_decrease=r.min_chi2_decrease,
                    max_step_halvings=r.max_step_halvings)

    @staticmethod
    def _snapshot_family(model, toas) -> str | None:
        """Incremental family of this fit, or None (stateless).

        TZR-anchored batchable WLS takes the rank-k QR update
        (:mod:`pint_tpu.fitting.incremental`); TZR-anchored batchable
        GLS takes the Schur rank-k update (:mod:`pint_tpu.fitting
        .gls_incremental`, gated by ``PINT_TPU_SESSION_GLS``). Wideband
        stays stateless: its joint TOA+DM rows do not fit either
        update's row convention.
        """
        return _session_family(model, toas)

    def prep(self) -> None:
        """Stage-entry stamp. Routing happens at DISPATCH time
        (:meth:`route_now`): a same-key append earlier in the same
        drain commits its replacement state between this job's prep and
        dispatch, and the gates must read the committed state."""
        self._t0 = time.perf_counter()

    def route_now(self) -> None:
        """Decide the route against the CURRENT cache state."""
        entry = self.cache.entries.get(self.key)
        if self.mode == "create" or entry is None or entry.model is None:
            self.route = "populate"
            telemetry.inc("serve.session.miss")
            return
        telemetry.inc("serve.session.hit")
        if entry.state is None:
            self.route, self.reason = "full_refit", "no_state"
        elif entry.appends + 1 > max_appends():
            self.route, self.reason = "full_refit", "append_gate"
            telemetry.inc("serve.session.drift_trips")
        elif entry.drift >= drift_limit_sigma():
            self.route, self.reason = "full_refit", "drift_gate"
            telemetry.inc("serve.session.drift_trips")
        else:
            self.route = "incremental"

    def dispatch(self) -> None:
        """Enqueue (incremental) or run (full) the fit."""
        from pint_tpu.fitting import incremental as _incr

        if self.route is None:
            self.route_now()
        if self.route == "incremental":
            entry = self.cache.entries[self.key]
            self.launch = "solo"
            telemetry.inc("serve.session.launch.solo")
            with telemetry.span("serve.session.dispatch",
                                route=self.route):
                if entry.family == "gls":
                    from pint_tpu.fitting import gls_incremental as _gls

                    self._handle = _gls.dispatch_gls_incremental(
                        entry.model, self.request.toas, entry.state,
                        names=entry.names, **self._hyper())
                else:
                    self._handle = _incr.dispatch_incremental(
                        entry.model, self.request.toas, entry.state,
                        names=entry.names, **self._hyper())
            return
        # populate / full refit: host-driven, resolved synchronously
        # (like the scheduler's passthrough plans); completion stamped
        # NOW so deferred fetches cannot inflate latency
        self._result = self._run_full()
        self.t_done = time.perf_counter()

    def ready(self) -> bool:
        if self._result is not None:
            return True
        try:
            if self._batch is not None:
                return self._batch.ready()
            return self._handle is not None and self._handle.ready()
        except Exception:  # noqa: BLE001 — readiness is advisory
            return True

    # -- full-fit path -------------------------------------------------
    def _run_full(self) -> dict:
        """Full fused (or host) fit over the accumulated table; commits
        model + table + (when eligible) a fresh device snapshot. The
        ONE populate/refit code path: a gate-tripped refit is bitwise a
        cold populate over the same table by construction."""
        from pint_tpu.fitting import incremental as _incr
        from pint_tpu.toas import merge_TOAs

        telemetry.inc(f"serve.session.{self.route}")
        if self.reason:
            telemetry.inc(f"serve.session.refit.{self.reason}")
        entry = self.cache.entry_for(self.key, self.fp)
        if self.route == "populate":
            model, toas_full = self.request.model, self.request.toas
        else:
            model = entry.model
            toas_full = merge_TOAs([entry.accumulated(),
                                    self.request.toas])
            self.attempts = max(self.attempts, 1)
        hyper = self._hyper()
        family = self._snapshot_family(model, toas_full)
        if family is not None:
            from pint_tpu.fitting import device_loop

            dense = (device_loop.dense_gls_fit if family == "gls"
                     else device_loop.dense_wls_fit)
            d, info, chi2, conv, _cnt = dense(toas_full, model, **hyper)
            div = bool(np.asarray(info.get("diverged", False)))
            if not div:
                errors = info["errors"]
                for k in model.free_params:
                    model[k].add_delta(float(np.asarray(d[k])))
                    model[k].uncertainty = float(np.asarray(errors[k]))
            conv = bool(conv)
        else:
            from pint_tpu.fitting.fitter import Fitter

            f = Fitter.auto(toas_full, model)
            f.max_step_halvings = hyper["max_step_halvings"]
            chi2 = f.fit_toas(
                maxiter=hyper["maxiter"],
                min_chi2_decrease=hyper["min_chi2_decrease"])
            chi2 = float(np.atleast_1d(np.asarray(chi2, float))[0])
            div = bool(getattr(f, "diverged", False)) \
                or not np.isfinite(chi2)
            conv = bool(np.all(np.asarray(f.converged)))
        if div:
            # never commit a poisoned solution: the entry keeps its
            # last good model/table/chi2 untouched. The device state is
            # dropped — on an incremental-diverged fallback its buffers
            # were donated to the failed update, and a stale-but-alive
            # factor buys nothing a refit will not rebuild
            self.cache.commit_state(self.key, None, 0)
            return {"chi2": float(chi2), "converged": False,
                    "diverged": True, "route": self.route}
        entry.model = model
        entry.toas = toas_full
        entry.pending = []
        entry.n_toas = len(toas_full)
        entry.appends = 0
        entry.drift = 0.0
        entry.chi2 = float(chi2)
        if family is None:
            self.cache.commit_state(self.key, None, 0)
            entry.names, entry.off, entry.family = None, 0, None
            telemetry.inc("serve.session.stateless")
        else:
            if family == "gls":
                from pint_tpu.fitting import gls_incremental as _gls

                snap = _gls.snapshot_state(model, toas_full)
            else:
                snap = _incr.snapshot_state(model, toas_full)
            entry.names, entry.off = snap["names"], snap["off"]
            entry.family = family
            self.cache.commit_state(self.key, snap["state"],
                                    snap["bytes"])
        # the committed values changed: readers must see THIS solution
        self.cache.notify_commit(self.key)
        return {"chi2": float(chi2), "converged": conv, "diverged": div,
                "route": self.route}

    # -- fetch / commit ------------------------------------------------
    def finish(self) -> dict:
        """Resolve the request: fetch, write back, commit state.

        Returns ``{chi2, converged, diverged, route}`` for the
        scheduler's envelope. Idempotent via ``self._result``.
        """
        if self._result is not None:
            self.wall_s = (self.t_done or time.perf_counter()) - self._t0
            return self._result
        entry = self.cache.entries[self.key]
        if self._batch is not None:
            # one member of a vmapped multi-session launch (ISSUE 20):
            # the batch's single fetch is shared; this job commits its
            # own member slice through the identical code path below
            m = self._member
            u, info, chi2, conv, _cnt = self._batch.fetch()

            def pick(x):
                return np.asarray(x)[m]

            new_state = self._batch.handle.new_state(m)
        else:
            u, info, chi2, conv, _cnt = self._handle.fetch()
            pick = np.asarray
            new_state = self._handle.new_state
        div = bool(pick(info.get("diverged", False))) \
            if "diverged" in info else False
        if div:
            # a poisoned append (or a stale-state pathology): never
            # commit — fall back to the cold path, which repopulates
            telemetry.inc("serve.session.incremental_diverged")
            self.route, self.reason = "full_refit", "incremental_diverged"
            self.attempts = 2
            self._result = self._run_full()
            self.t_done = time.perf_counter()
            self.wall_s = self.t_done - self._t0
            return self._result
        telemetry.inc("serve.session.incremental")
        u = np.asarray(pick(u))
        off, names = entry.off, entry.names
        sig = np.zeros(len(names))
        for i, k in enumerate(names):
            e = float(np.asarray(pick(info["errors"][k])))
            sig[i] = e
            entry.model[k].add_delta(float(u[off + i]))
            entry.model[k].uncertainty = e
        # cumulative drift: the largest parameter move of this update in
        # its own posterior sigma (zero-sigma params cannot gate). Slice
        # the TIMING coordinates only — a GLS state vector carries the
        # Fourier-coefficient displacements after them, and those are
        # exact linear updates that cannot stale the cached quadratic
        moves = np.abs(u[off:off + len(names)])
        with np.errstate(divide="ignore", invalid="ignore"):
            rel = np.where(sig > 0, moves / np.where(sig > 0, sig, 1.0),
                           0.0)
        # lazy accumulation: merging the (possibly 1e5-row) table here
        # would dominate the update wall — a full refit merges instead
        entry.pending.append(self.request.toas)
        entry.n_toas += len(self.request.toas)
        entry.appends += 1
        entry.drift += float(np.max(rel)) if rel.size else 0.0
        entry.chi2 = float(pick(chi2))
        committed = self.cache.commit_state(
            self.key, new_state, _incr_state_bytes(new_state))
        if not committed:
            telemetry.inc("serve.session.state_dropped")
        # incremental commit moved the parameter values too (ISSUE 11)
        self.cache.notify_commit(self.key)
        self.cache.touch(self.key)
        self.t_done = time.perf_counter()
        self.wall_s = self.t_done - self._t0
        self._result = {"chi2": float(pick(chi2)),
                        "converged": bool(pick(conv)), "diverged": False,
                        "route": "incremental"}
        return self._result


class SessionBatch:
    """N same-structure session jobs drained as ONE vmapped launch.

    The scheduler's ``"session_batch"`` plan state (ISSUE 20): the
    grouped jobs' routes are decided at dispatch time (same rule as a
    solo job — a refit earlier in the drain may have changed any
    member's gates), members still on the incremental WLS route ride
    one :func:`pint_tpu.fitting.incremental.dispatch_incremental_batch`
    launch, and everyone else — populates, gate-tripped refits, GLS
    sessions (whose Schur update stays solo: its state shapes depend on
    the noise structure) — peels out to its ordinary solo path inside
    the same plan. ``finish`` stays per member (each
    :class:`SessionJob` commits its own slice of the shared fetch), so
    durability journaling, read invalidation and trace hop fan-out
    compose per member with no batch-aware code anywhere downstream.
    """

    def __init__(self, jobs: list):
        self.jobs = list(jobs)
        self.members: list = []   # jobs riding the vmapped launch
        self.handle = None
        self._fetched = None

    def prep(self) -> None:
        for j in self.jobs:
            j.prep()

    def dispatch(self) -> None:
        from pint_tpu.fitting import incremental as _incr

        riders = []
        for j in self.jobs:
            if j.route is None:
                j.route_now()
            entry = j.cache.entries.get(j.key)
            if (j.route == "incremental" and entry is not None
                    and entry.family == "wls"):
                riders.append(j)
            else:
                j.dispatch()  # peel out: populate / refit / GLS solo
        if len(riders) < 2:
            for j in riders:
                j.dispatch()
            return
        lead = riders[0]
        telemetry.inc("serve.session.launch.batched")
        telemetry.inc("serve.session.launch.batched_members",
                      len(riders))
        with telemetry.span("serve.session.dispatch",
                            route="incremental_batch"):
            self.handle = _incr.dispatch_incremental_batch(
                [(j.cache.entries[j.key].model, j.request.toas,
                  j.cache.entries[j.key].state) for j in riders],
                **lead._hyper())
        self.members = riders
        for m, j in enumerate(riders):
            j._batch = self
            j._member = m
            j.launch = "batched"

    def ready(self) -> bool:
        try:
            if self.handle is not None and not self.handle.ready():
                return False
        except Exception:  # noqa: BLE001 — readiness is advisory
            return True
        return all(j.ready() for j in self.jobs if j._batch is not self)

    def fetch(self):
        """The batch's single device->host sync; idempotent (every
        member's :meth:`SessionJob.finish` goes through here)."""
        if self._fetched is None:
            self._fetched = self.handle.fetch()
        return self._fetched


def _incr_state_bytes(state: dict) -> int:
    from pint_tpu.fitting.incremental import state_bytes

    return state_bytes(state)
