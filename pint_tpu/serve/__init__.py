"""pint_tpu.serve — the throughput engine for many-fit workloads.

One fit is one fused XLA program (fitting.device_loop); this package
makes a *stream* of fits cheap: a bounded request queue, fingerprint-
bucketed continuous batching into the fused batched loop (B compatible
fits = ONE launch + ONE fetch), pow-2 member padding with bit-inert
dummies, and a double-buffered dispatch pipeline that overlaps host
packing with device execution. Every request resolves to a structured
status (never an exception tearing down a drain): per-request
isolation, deadlines, transient-error retries, quarantine and a
degradation ladder, with seed-driven chaos in
:mod:`pint_tpu.serve.faults`. Sessionful requests
(``FitRequest.session_id``; :mod:`pint_tpu.serve.session`) append TOAs
to a cached converged solution via fused rank-k incremental updates
instead of paying a cold fit. Reads (:class:`PredictRequest`;
:mod:`pint_tpu.predict`) are the second tier: phase/TOA predictions
served from cached fit state through a fast lane that never queues
behind fit drains. Catalog-scale joint PTA fits
(:class:`pint_tpu.catalog.job.CatalogFitRequest`) are the third tier:
long-running checkpointing jobs advanced one bounded device-budget
slice per drain, so they coexist with (and never starve) the fit and
read lanes. Scale-OUT over many hosts lives one tier up in
:mod:`pint_tpu.fleet` (fingerprint-sticky rendezvous routing over N
per-host schedulers; this scheduler's ``host_id`` / ``report()`` are
its per-host surface). See docs/ARCHITECTURE.md "Throughput engine",
"Failure domains & degradation ladder", "Sessionful serving",
"The read path", "Catalog workloads" and "Fleet tier".
"""

from pint_tpu.serve import faults  # noqa: F401
from pint_tpu.serve.session import (  # noqa: F401
    DRIFT_CHI2_REL, SessionCache, SessionCacheFull)
from pint_tpu.serve.fingerprint import (  # noqa: F401
    basis_bucket, batchable, family, noise_batch_enabled, plan_key,
    short_id, structure_fingerprint)
from pint_tpu.serve.pipeline import run_pipeline  # noqa: F401
from pint_tpu.serve.scheduler import (  # noqa: F401
    READ_STATUSES, STATUSES, BatchPlan, FitHandle, FitRequest, FitResult,
    PredictHandle, PredictRequest, PredictResult, ServeQueueFull,
    ThroughputScheduler, transient_error)

__all__ = [
    "BatchPlan", "DRIFT_CHI2_REL", "FitHandle", "FitRequest",
    "FitResult", "PredictHandle", "PredictRequest", "PredictResult",
    "READ_STATUSES", "STATUSES", "ServeQueueFull", "SessionCache",
    "SessionCacheFull", "ThroughputScheduler", "basis_bucket",
    "batchable", "faults", "family", "noise_batch_enabled", "plan_key",
    "run_pipeline", "short_id", "structure_fingerprint",
    "transient_error",
]
