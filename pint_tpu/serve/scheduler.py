"""Throughput scheduler: fingerprint-bucketed continuous batching.

The north star is a *service*: many independent fit requests, not one
fast fit. After PR 3 a single fit is one XLA launch, but a stream of
fits still executed strictly one-after-another, each paying its own
launch + fetch + host-prep serialization. This module closes that gap
with the standard serving-system moves (continuous batching a la Orca,
double-buffered dispatch):

1. **Bounded queue** — :meth:`ThroughputScheduler.submit` enqueues a
   :class:`FitRequest` and returns a :class:`FitHandle`; a full queue
   raises :class:`ServeQueueFull` (backpressure is the caller's signal
   to drain, never silent dropping) carrying the queue depth and a
   retry-after hint derived from the recent drain rate.
2. **Batch formation** (:meth:`ThroughputScheduler.plan`) — queued
   requests group by (structure fingerprint, TOA-count bucket, fit
   hyperparameters); each group chunks to ``max_batch_members`` and
   pads to the pow-2 member bucket
   (:func:`pint_tpu.bucketing.member_bucket_size`) with bit-inert dummy
   members, so B structurally-compatible fits cost ONE fused program
   launch and ONE fetch — and same-group batches across drains reuse
   one compiled program (the fit-program cache).
3. **Double-buffered dispatch** (:mod:`pint_tpu.serve.pipeline`) —
   while batch k executes on-device, the host packs/whitens/pads batch
   k+1; a bounded in-flight window keeps device memory bounded.

**Batchable frontier (ISSUE 8).** Correlated-noise (GLS) and wideband
fits are first-class batch members: noise-basis stacks and wideband-
ness split the structure fingerprint (and the ECORR epoch-column
bucket joins the plan key next to the TOA bucket) instead of forcing a
passthrough, so the heaviest production models batch through the same
fused union loop. The residue the union still cannot express
(delay-side jumps, multiple ECORR components, free noise
hyperparameters — or everything noise/wideband under the
``PINT_TPU_BATCH_NOISE=0`` kill switch) is served through the
**passthrough** path — a per-request ``Fitter.auto`` fit in its own
singleton batch — so the scheduler accepts any model the library can
fit; every passthrough records WHY via
``serve.passthrough.reason.<token>`` counters and the drain record's
``passthrough`` breakdown.

**Failure domains (ISSUE 6).** Every submitted request resolves to a
:class:`FitResult` with a ``status`` — one of :data:`STATUSES` — and an
exception in one batch can never tear down a drain:

* a batch member whose on-device fit produces non-finite chi2 (the
  device loop's ``diverged`` carry, read in the same single fetch) is
  retried ONCE as a standalone passthrough fit, then **quarantined**
  with its flight-recorder trace attached to the failure record;
* a failed prep/dispatch/fetch stage salvages its members through
  per-request passthrough fits (``failed`` only when that also raises);
* transient ``XlaRuntimeError``-class dispatch/fetch errors retry with
  exponential backoff (``max_dispatch_retries`` x ``retry_backoff_s``,
  the tools/tpu_retry.sh probe-then-retry idea in-library);
* ``deadline_s`` is checked at formation (expired requests resolve
  ``timed_out`` without running) and again after ``finish()``;
* under sustained batch failure the scheduler walks a **degradation
  ladder**: first every plan becomes an isolated passthrough (blast
  radius one request), then load sheds predictably — submit rejects at
  half capacity and the drain resolves the NEWEST queued requests
  beyond it as ``rejected`` with a retry-after hint — rather than
  collapsing. A clean drain heals the ladder.

Fault injection for all of the above lives in
:mod:`pint_tpu.serve.faults` (seed-driven, zero-cost when off).

**Mesh-sharded serving (ISSUE 7).** Formed batches no longer all run
on one device set: the planner places every plan on a slice of the
device pool (``mesh_devices`` of ``jax.devices()``):

* a **batched** plan's member axis is sharded over an aligned power-of-
  two block of devices (width = the largest pow-2 dividing its member
  bucket, capped at the pool) — many small fits spread across the mesh;
* a batchable **singleton at or above ``toa_shard_min``** routes
  through the TOA-axis-sharded path instead (one fit, every O(n) leaf
  partitioned over the whole pool —
  :class:`pint_tpu.parallel.sharded_fit.ShardedServeFitter`);
* blocks are packed least-loaded-first, deterministically, so repeated
  drains of the same plan sequence reuse their compiled (partitioned)
  programs; the device count is part of the PLAN key
  (:func:`pint_tpu.serve.fingerprint.plan_key`), never the structure
  fingerprint;
* the in-flight ``window`` applies PER DEVICE (pipeline slot pool):
  disjoint blocks pipeline independently, with a work-stealing drain
  order that fetches already-complete shards ahead of FIFO;
* the PR-6 fault machinery is **shard-local**: per-device fail streaks
  isolate a failing block (its plans become passthrough and placement
  routes around it) without tripping the global ladder — the global
  streak only grows on drains where EVERY batch failed, and one clean
  drain heals everything;
* per-device member/occupancy/bytes vectors land in the drain record's
  ``mesh`` block plus ``serve.mesh.*`` counters, rendered by the
  report CLI's "mesh" section (with an occupancy-skew warning).

Telemetry: ``serve.*`` counters/gauges (now including ``serve.fault.*``
/ ``serve.retry.*`` / ``serve.quarantine.*`` / ``serve.status.*`` /
``serve.mesh.*`` / ``serve.pad.dummy_members``), one ``type="serve"``
record per drain and one ``type="fault"`` record per failure event —
rendered by ``python -m pint_tpu.telemetry.report`` under "throughput
engine", "failure domains" and "mesh".
"""

from __future__ import annotations

import dataclasses
import os
import time
from typing import Any

import numpy as np

from pint_tpu import bucketing, config, telemetry
from pint_tpu.serve import fingerprint as _fp
from pint_tpu.serve import faults as _faults
from pint_tpu.serve.pipeline import run_pipeline

#: the request-status taxonomy (docs/ARCHITECTURE.md "Failure domains")
STATUSES = ("ok", "nonconverged", "diverged", "failed", "timed_out",
            "quarantined", "rejected")


class ServeQueueFull(RuntimeError):
    """submit() on a full queue: drain (or widen max_queue) and retry.

    Carries the actionable context: ``depth`` / ``max_queue`` at the
    rejection, a ``retry_after_s`` hint (queue depth over the recent
    drain rate), and whether the scheduler was in its ``degraded``
    shedding state (capacity halved).
    """

    def __init__(self, depth: int = 0, max_queue: int = 0,
                 retry_after_s: float | None = None,
                 degraded: bool = False):
        self.depth = depth
        self.max_queue = max_queue
        self.retry_after_s = retry_after_s
        self.degraded = degraded
        msg = f"queue at capacity ({depth}/{max_queue}"
        if degraded:
            msg += ", degraded: shedding at half capacity"
        msg += "); drain() first"
        if retry_after_s is not None:
            msg += f" and retry after ~{retry_after_s:g}s"
        super().__init__(msg)


# transient = worth re-dispatching the SAME work: the jaxlib runtime
# error classes a flaky device/tunnel surfaces, plus the grpc-ish status
# strings they carry (the probe-then-retry policy of tools/tpu_retry.sh)
_TRANSIENT_TYPES = ("XlaRuntimeError", "JaxRuntimeError")
_TRANSIENT_MARKERS = ("UNAVAILABLE", "RESOURCE_EXHAUSTED",
                      "DEADLINE_EXCEEDED", "ABORTED", "INTERNAL",
                      "connection", "socket closed")


def transient_error(exc: BaseException) -> bool:
    """Is this a retry-worthy device/runtime failure (vs a model bug)?"""
    if isinstance(exc, _faults.InjectedDeviceError):
        return True
    if isinstance(exc, _faults.InjectedFault):
        return False
    if type(exc).__name__ in _TRANSIENT_TYPES:
        return True
    if isinstance(exc, (RuntimeError, OSError)):
        return any(m in str(exc) for m in _TRANSIENT_MARKERS)
    return False


@dataclasses.dataclass
class FitRequest:
    """One fit: a TOA table + a (perturbed) model to fit in place.

    ``deadline_s`` (optional) is a per-request latency budget counted
    from submit: expired before formation -> resolved ``timed_out``
    without running; expired when the result lands -> the fit is
    attached but the status reports the SLA miss.

    ``session_id`` (ISSUE 10) opts the request into the sessionful
    layer (:mod:`pint_tpu.serve.session`): the FIRST request of a
    ``(session_id, model structure)`` pair is a normal full fit whose
    state is committed to the session cache; every LATER request is an
    **append** — ``toas`` then carries ONLY the new TOAs (``model``
    may be None: the session's own fitted model is authoritative) and
    is folded in via the fused rank-k incremental update, falling back
    to a warm-started full refit outside the incremental path's domain
    or when a drift gate trips.
    """

    toas: Any
    model: Any
    maxiter: int = 20
    min_chi2_decrease: float = 1e-3
    max_step_halvings: int = 8
    tag: Any = None
    deadline_s: float | None = None
    session_id: Any = None
    trace_ctx: Any = None         # distributed-trace chain head (or None)


@dataclasses.dataclass
class FitResult:
    """Per-request outcome envelope.

    ``status`` is one of :data:`STATUSES`; ``request.model`` holds the
    fitted values only for ``ok`` / ``nonconverged`` / ``timed_out``
    (a diverged/quarantined fit never writes back NaN parameters).
    ``trace`` carries the member's flight-recorder record on
    quarantine; ``retry_after_s`` the shed hint on ``rejected``;
    ``injected`` names the fault pint_tpu.serve.faults planted (chaos
    runs only — diagnostics, never behavior).
    """

    tag: Any
    request: FitRequest
    chi2: float
    converged: bool
    batch: int
    group: str
    n_members: int
    occupancy: float
    queue_latency_s: float
    passthrough: bool = False
    status: str = "ok"
    error: str | None = None
    attempts: int = 1
    trace: dict | None = None
    retry_after_s: float | None = None
    injected: str | None = None
    session: str | None = None  # session route token (ISSUE 10)
    host: str | None = None     # serving host id (ISSUE 12 fleet tier)
    trace_ctx: Any = None       # dispatch-hop context (router commit parent)

    @property
    def fitted(self) -> bool:
        """Did a fit complete and write back (status-taxonomy helper)?

        A ``timed_out`` request counts only when the fit actually ran
        (deadline missed after finish — finite chi2 attached); one that
        expired before formation never ran and holds stale parameters.
        """
        if self.status in ("ok", "nonconverged"):
            return True
        return self.status == "timed_out" and bool(np.isfinite(self.chi2))


class FitHandle:
    """Future-like handle returned by :meth:`ThroughputScheduler.submit`."""

    __slots__ = ("_result",)

    def __init__(self):
        self._result: FitResult | None = None

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> FitResult:
        if self._result is None:
            raise RuntimeError("request not drained yet; call "
                               "ThroughputScheduler.drain() first")
        return self._result


# ----------------------------------------------------------------------
# the read path (ISSUE 11): predictions served from cached fit state
# ----------------------------------------------------------------------

@dataclasses.dataclass
class PredictRequest:
    """One read: pulse phase / apparent spin frequency at query times.

    Reads NEVER touch the fit loop: they are served from the committed
    session solution (``session_id``) or an explicit fitted ``model``
    through :mod:`pint_tpu.predict` — segment-cache hit -> on-device
    Chebyshev evaluation; miss -> direct dense model-phase evaluation
    while the artifact warms asynchronously; ``PINT_TPU_READ_PATH=0``
    -> the host ``Polycos`` reference path. ``deadline_s`` is the read
    SLA, counted from submit exactly like a fit deadline.
    """

    mjds: Any                     # (n,) site-local MJD query times
    session_id: Any = None        # serve from this session's solution
    model: Any = None             # sessionless: an explicit fitted model
    obs: str = "@"                # tempo site code of the queries
    freq_mhz: float = 1400.0      # observing frequency of the queries
    tag: Any = None
    deadline_s: float | None = None
    trace_ctx: Any = None         # distributed-trace chain head (or None)


#: read-result status taxonomy (a strict subset of :data:`STATUSES`)
READ_STATUSES = ("ok", "failed", "timed_out")


@dataclasses.dataclass
class PredictResult:
    """Per-read outcome envelope (the fast lane's ``FitResult``).

    ``phase_int``/``phase_frac``/``freq_hz`` are host arrays aligned
    with the request's ``mjds`` (``None`` on ``failed``); ``source``
    names the ladder rung that served it (``cheb`` / ``dense`` /
    ``mixed`` / ``host_polycos``); ``latency_s`` counts from submit —
    for the synchronous fast lane that is the service time itself.
    """

    tag: Any
    request: PredictRequest
    status: str
    phase_int: Any = None
    phase_frac: Any = None
    freq_hz: Any = None
    source: str = ""
    cache_hit: bool = False
    n_queries: int = 0
    latency_s: float = 0.0
    error: str | None = None
    host: str | None = None     # serving host id (ISSUE 12 fleet tier)
    trace_ctx: Any = None       # read-hop context (router commit parent)


class PredictHandle:
    """Future-like handle for queued reads (:meth:`ThroughputScheduler
    .submit` with a :class:`PredictRequest`)."""

    __slots__ = ("_result",)

    def __init__(self):
        self._result: PredictResult | None = None

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> PredictResult:
        if self._result is None:
            raise RuntimeError("read not drained yet; call "
                               "ThroughputScheduler.drain_reads() first")
        return self._result


@dataclasses.dataclass
class BatchPlan:
    """One planned program launch (inspectable, pure — no device work).

    ``devices``/``slot`` are the planner's placement: the plan's
    buffers and program span devices ``slot .. slot + devices - 1`` of
    the scheduler's pool (``devices == 0`` for passthrough plans, which
    are host-synchronous and hold no windowed device buffers). A
    ``"batched"`` plan shards its MEMBER axis over the block; a
    ``"sharded"`` plan is one big fit with its TOA axis sharded over
    the whole pool.
    """

    kind: str                 # "batched" | "sharded" | "passthrough"
    #                           | "session" (ISSUE 10: sessionful fits —
    #                           host-routed singletons like passthrough,
    #                           but the incremental route dispatches one
    #                           fused async program)
    #                           | "session_batch" (ISSUE 20: many same-
    #                           structure session appends riding ONE
    #                           vmapped rank-k launch; indices are the
    #                           member requests in queue order)
    group: str                # fingerprint short id
    indices: list[int]        # queue positions of the member requests
    toa_bucket: int
    n_members: int            # padded member count (1 for passthrough)
    devices: int = 1          # device-block width (0 = host/passthrough)
    slot: int = 0             # first device index of the block
    basis_bucket: int = 0     # padded ECORR epoch columns (ISSUE 8)
    reason: str = ""          # passthrough reason token (ISSUE 8)
    #: member x TOA grid depth (ISSUE 12, the PR-7 residue): a batched
    #: plan whose member axis is narrower than its device block also
    #: shards each member's TOA axis over ``toa_devices`` devices —
    #: the block is a (devices/toa_devices, toa_devices) ("psr","toa")
    #: grid instead of idling the spare devices
    toa_devices: int = 1

    @property
    def occupancy(self) -> float:
        return len(self.indices) / max(1, self.n_members)

    @property
    def device_ids(self) -> tuple[int, ...]:
        """Pool indices this plan's buffers/program span."""
        return tuple(range(self.slot, self.slot + self.devices))


def _program_store_stats() -> dict | None:
    """Persistent-program-store health for :meth:`report` (never
    raises; None = no store configured — the bitwise-today default)."""
    try:
        from pint_tpu.programs import store_stats

        return store_stats()
    except Exception:  # noqa: BLE001 — health surface must not fail
        return None


class _FailedBatch:
    """Pipeline-stage failure marker: the batch's members get salvaged
    through per-request passthrough fits at the fetch stage."""

    __slots__ = ("plan", "error", "stage", "attempts")

    def __init__(self, plan, error, stage, attempts=1):
        self.plan = plan
        self.error = error
        self.stage = stage
        self.attempts = attempts


class _BatchState:
    """In-flight state threaded through prep -> dispatch -> fetch."""

    __slots__ = ("plan", "fitter", "handle", "resolved", "trace",
                 "attempts", "hyper", "device_bytes", "t_done")

    def __init__(self, plan, fitter=None):
        self.plan = plan
        self.fitter = fitter
        self.handle = None
        self.resolved = None  # passthrough: (chi2, conv, div, reason)
        self.trace = None     # passthrough: trace captured at fit time
        self.attempts = 1
        self.hyper = None
        self.device_bytes = None  # per-device bytes of placed tables
        self.t_done = None    # passthrough: completion stamped at dispatch


def _member_trace(trace: dict | None, m: int) -> dict | None:
    """Member ``m``'s slice of a batched flight-recorder record."""
    from pint_tpu.telemetry.recorder import BATCH_FIELDS

    if trace is None:
        return None
    out = {k: trace[k] for k in ("type", "loop", "kind", "n", "recorded",
                                 "dropped") if k in trace}
    out["member"] = m
    for f in BATCH_FIELDS:  # the authoritative per-member field list
        rows = trace.get(f)
        if rows:
            out[f] = [row[m] if isinstance(row, (list, tuple)) else row
                      for row in rows]
    return out


class ThroughputScheduler:
    """Bounded-queue continuous batching over the fused batched loop.

    Parameters: ``max_queue`` bounds :meth:`submit` (backpressure);
    ``max_batch_members`` caps one program's member count;
    ``member_floor`` floors the pow-2 member bucket (tests use it to
    force dummy padding); ``window`` is the in-flight depth PER DEVICE
    (the pipeline's per-slot window pool).

    Mesh placement (ISSUE 7): the device pool is ``jax.devices()`` —
    or the devices of an explicit ``mesh``, kept for compatibility —
    truncated to ``mesh_devices`` when given (tools/soak.py randomizes
    it). Batched plans shard their member axis over aligned pow-2
    device blocks; a batchable singleton whose TOA bucket reaches
    ``toa_shard_min`` routes through the TOA-axis-sharded path over
    the whole pool instead (default = the bucketing ceiling, above
    which fits carry exact shapes and a single fit is mesh-scale
    work). With one device every rule degenerates to the PR-5
    single-set behavior.

    Fault-domain knobs: ``max_dispatch_retries`` transient re-dispatches
    per batch, ``retry_backoff_s`` the exponential backoff base (0 in
    tests), ``degrade_after`` the consecutive-failing-drain count that
    trips the degradation ladder — globally when whole drains fail,
    per device block when only some shards do (see :meth:`degraded` /
    :meth:`degraded_devices`).
    """

    def __init__(self, *, max_queue: int = 256,
                 max_batch_members: int = 64, member_floor: int = 1,
                 window: int = 2, mesh=None, mesh_devices: int | None = None,
                 devices=None, toa_shard_min: int = 16384,
                 toa_grid_min: int = 1024,
                 max_dispatch_retries: int = 2,
                 retry_backoff_s: float = 0.05,
                 degrade_after: int = 2, session_cache=None,
                 host_id: str = ""):
        import jax

        if max_queue < 1 or max_batch_members < 1:
            raise ValueError("max_queue and max_batch_members must be >= 1")
        self.max_queue = max_queue
        self.max_batch_members = max_batch_members
        self.member_floor = max(1, member_floor)
        # same contract as pipeline.run_pipeline, enforced HERE so a
        # bad window rejects at construction instead of failing every
        # drain: non-int raises, < 1 clamps to the documented floor
        if isinstance(window, bool) or not isinstance(window, int):
            raise TypeError(f"window must be an int >= 1, got {window!r}")
        self.window = max(1, window)
        # fleet identity (ISSUE 12): stamped on every result envelope,
        # drain record and read record so a multi-host rollup can
        # attribute work; empty for plain single-host use
        self.host_id = host_id
        if devices is not None:
            # explicit pool (the fleet worker passes its PROCESS-LOCAL
            # devices: in a jax.distributed fleet jax.devices() spans
            # processes and must not be this host's placement pool)
            devs = list(devices)
        elif mesh is not None:
            devs = list(np.asarray(mesh.devices).ravel())
        else:
            devs = list(jax.devices())
        if mesh_devices is not None:
            devs = devs[:max(1, int(mesh_devices))]
        self.devices = devs
        self.n_devices = len(devs)
        self._dev_index = {d.id: i for i, d in enumerate(devs)}
        self.toa_shard_min = max(1, int(toa_shard_min))
        # member x TOA grid floor (ISSUE 12 / PR-7 residue): batched
        # plans only grid their TOA axis over spare devices when the
        # bucket reaches this (sharding a tiny table buys nothing)
        self.toa_grid_min = max(1, int(toa_grid_min))
        self._meshes: dict = {}  # (kind-is-sharded, slot, psr, toa) -> Mesh
        self.max_dispatch_retries = max(0, max_dispatch_retries)
        self.retry_backoff_s = max(0.0, retry_backoff_s)
        self.degrade_after = max(1, degrade_after)
        # (request, handle, t_submit, fingerprint, meta) — meta carries
        # the submit sequence number and any injected-fault label
        self._queue: list[tuple[FitRequest, FitHandle, float, tuple,
                                dict]] = []
        self._seq = 0          # submit sequence (fault-injection key)
        self._drain_seq = 0
        self._fail_streak = 0  # consecutive ALL-batches-failed drains
        self._dev_streak: dict[int, int] = {}  # device -> fail streak
        self._drain_rate: float | None = None  # EWMA fits/s
        self.last_drain: dict | None = None
        # sessionful layer (ISSUE 10): per-(session, fingerprint) fit
        # state; shareable across schedulers via the ctor kwarg
        from pint_tpu.serve.session import SessionCache

        self.sessions = (session_cache if session_cache is not None
                         else SessionCache())
        # the read path (ISSUE 11): predictions from cached fit state.
        # Artifacts (and their evaluations) live on the LAST device of
        # the pool — with > 1 device, reads never share a dispatch
        # stream with fit programs; the session cache invalidates the
        # segment cache on every commit
        from pint_tpu.predict import ReadService

        self.reads = ReadService(
            device=self.devices[-1 if self.n_devices > 1 else 0])
        self.sessions.attach_read_cache(self.reads.cache)
        self._read_queue: list[tuple[PredictRequest, PredictHandle,
                                     float]] = []
        self._read_stats: list[dict] = []  # per-read, since last record
        self.last_read: dict | None = None
        # durable fleet sessions (ISSUE 13): replicas stashed HERE by
        # the router make this host the warm-failover successor for
        # sessions owned elsewhere — small committed summaries only
        # (model blob + DD values + chi2), bounded FIFO
        self.replicas: dict[tuple, dict] = {}
        self.max_replicas = 64
        # catalog workloads (ISSUE 14): long-running joint-fit jobs
        # advanced one bounded device-budget slice per drain — reads
        # and small fits interleave between slices by construction
        self.catalog_jobs: dict[str, Any] = {}
        self._catalog_seq = 0

    # ------------------------------------------------------------------
    # catalog workloads: the long-job surface (ISSUE 14)
    # ------------------------------------------------------------------
    def submit_catalog(self, request):
        """Accept one long-running catalog joint fit; returns a
        :class:`pint_tpu.catalog.job.CatalogHandle`.

        Nothing runs here — the job advances in bounded slices
        (``PINT_TPU_CATALOG_SLICE_S``) at the END of every
        :meth:`drain` (and via :meth:`advance_catalog` standalone), so
        reads (which drain FIRST) and small-fit batches keep flowing
        while the catalog fit is in progress: long jobs never starve
        the fast lanes."""
        from pint_tpu.catalog.job import CatalogHandle, CatalogJob

        self._catalog_seq += 1
        job_id = (f"cat-{self.host_id or 'local'}-"
                  f"{self._catalog_seq}")
        job = CatalogJob(request, job_id, host_id=self.host_id,
                         devices=self.devices)
        self.catalog_jobs[job_id] = job
        telemetry.inc("catalog.jobs")
        return CatalogHandle(job)

    def adopt_catalog(self, checkpoint: dict):
        """Resume a checkpointed catalog job as this host's own (the
        fleet failover path): the catalog regenerates from the spec,
        pre-checkpoint iterations are accounted (never re-run), and
        the job keeps advancing under this host's slices."""
        from pint_tpu.catalog.job import CatalogHandle, CatalogJob

        job = CatalogJob.from_checkpoint(
            checkpoint, host_id=self.host_id, devices=self.devices)
        self.catalog_jobs[job.job_id] = job
        telemetry.inc("catalog.adopted")
        return CatalogHandle(job)

    def advance_catalog(self, budget_s: float | None = None
                        ) -> list[dict]:
        """Advance every live catalog job by at most one device-budget
        slice each; returns their progress dicts. Called by every
        :meth:`drain` after the fit pipeline resolves; callable
        standalone for a dedicated long-job pump loop."""
        out = []
        for job in list(self.catalog_jobs.values()):
            if job.state not in ("done", "failed"):
                with telemetry.span("catalog.slice", job=job.job_id):
                    job.advance(budget_s)
            out.append(job.progress())
        return out

    def catalog_progress(self, job_id: str) -> dict | None:
        job = self.catalog_jobs.get(job_id)
        return None if job is None else job.progress()

    def catalog_checkpoint(self, job_id: str) -> dict | None:
        """The job's latest checkpoint (the router stashes it after
        every slice so a host death resumes instead of restarting)."""
        job = self.catalog_jobs.get(job_id)
        if job is None:
            return None
        return job._last_checkpoint or job.checkpoint()

    # ------------------------------------------------------------------
    # durable sessions: the replication/adoption surface (ISSUE 13)
    # ------------------------------------------------------------------
    def session_summary(self, key: tuple) -> dict | None:
        """This host's committed summary for one session key — the
        replica payload the router ships to the ring successor after a
        commit: the fitted model (pickled with its exact (hi, lo)
        double-double values + uncertainties), chi2, append count.
        Small by design: the accumulated table stays in the router's
        journal. None when the key holds no committed solution."""
        import pickle

        e = self.sessions.entries.get(tuple(key))
        if e is None or e.model is None:
            return None
        return {
            "skey": tuple(key),
            "model_blob": pickle.dumps(
                e.model, protocol=pickle.HIGHEST_PROTOCOL),
            "params": {k: (e.model[k].hi, e.model[k].lo,
                           e.model[k].uncertainty)
                       for k in e.model.free_params},
            "chi2": e.chi2, "appends": e.appends,
            "n_toas": e.n_toas, "version": e.version,
        }

    def stash_replica(self, key: tuple, blob: dict) -> None:
        """Store a replica for a session another host owns (FIFO-capped
        — replicas are a warm-failover accelerant, never the only copy:
        the router's journal can always cold-rebuild)."""
        key = tuple(key)
        self.replicas.pop(key, None)
        while len(self.replicas) >= self.max_replicas:
            self.replicas.pop(next(iter(self.replicas)))
            telemetry.inc("serve.session.replica_evicted")
        self.replicas[key] = blob
        telemetry.inc("serve.session.replica_stashed")

    def adopt_session(self, key: tuple, toas,
                      replica: dict | None = None) -> dict:
        """Warm failover (ISSUE 13): adopt a replicated session as this
        host's own committed state. The replica comes from the local
        stash (shipped by the router after each commit) unless passed
        explicitly; ``toas`` is the journal's accumulated table the
        replica's solution was fitted to. Returns ``{"adopted": bool,
        "chi2": float|None, "epoch": int|None}`` — not adopted when no
        replica is held (the router then cold-replays the journal)."""
        import pickle

        from pint_tpu.serve import fingerprint as _fpm

        key = tuple(key)
        blob = replica if replica is not None \
            else self.replicas.pop(key, None)
        if blob is None:
            return {"adopted": False, "chi2": None, "epoch": None}
        model = pickle.loads(blob["model_blob"])
        fp = _fpm.structure_fingerprint(model, toas)
        entry = self.sessions.adopt(key, fp, model, toas,
                                    chi2=blob["chi2"])
        return {"adopted": True, "chi2": entry.chi2,
                "epoch": blob.get("epoch"),
                "with_state": entry.state is not None}

    # ------------------------------------------------------------------
    # degradation ladder
    # ------------------------------------------------------------------
    def degraded(self) -> bool:
        """GLOBAL ladder tripped: ``degrade_after`` consecutive drains
        in which every batch that ran exhausted its retries (the whole
        pool failing, not one shard — see :meth:`degraded_devices`).
        While degraded, plans are isolated passthroughs and capacity
        halves (shedding)."""
        return self._fail_streak >= self.degrade_after

    def degraded_devices(self) -> set[int]:
        """Pool indices whose per-device fail streak has tripped.

        Shard-local degradation (ISSUE 7): a device accumulates one
        streak point per drain in which a batch placed on it failed,
        heals on a drain where it completed a batch cleanly (or on any
        fully clean drain). The planner routes batches around degraded
        devices; when no clean block exists for a plan's width, that
        plan falls back to isolated passthroughs — one poisoned shard
        degrades alone instead of tripping the global ladder."""
        return {d for d, s in self._dev_streak.items()
                if s >= self.degrade_after}

    def _retry_after_hint(self, depth: int) -> float:
        """Seconds until the queue plausibly has room: depth over the
        EWMA drain rate (bounded); depth-scaled default with no
        history."""
        rate = self._drain_rate or 0.0
        if rate <= 0.0:
            return round(max(1.0, 0.02 * depth), 3)
        return round(min(60.0, max(0.05, depth / rate)), 3)

    def report(self) -> dict:
        """The host health surface (ISSUE 12): everything the fleet
        router's per-host health state is fed from — queue depths, the
        PR-6 ladder state, the EWMA drain rate, and the process's
        program-cache miss total (the cross-host-recompile measurement
        of the FLEET A/B). Cheap, side-effect-free, callable between
        drains; the fleet worker serves it as its own protocol op."""
        from pint_tpu.telemetry.counters import counter_value

        return {
            "host": self.host_id,
            "queue_depth": len(self._queue),
            "read_depth": len(self._read_queue),
            "fail_streak": self._fail_streak,
            "degraded": self.degraded(),
            "degraded_devices": sorted(self.degraded_devices()),
            "drain_rate": self._drain_rate,
            "devices": self.n_devices,
            "sessions": len(self.sessions.entries),
            "replicas": len(self.replicas),
            "catalog_jobs": sum(
                1 for j in self.catalog_jobs.values()
                if j.state not in ("done", "failed")),
            "last_drain_wall_s": (self.last_drain or {}).get("wall_s"),
            "program_misses": int(
                counter_value("cache.fit_program.miss") or 0),
            # persistent program store health (None = no store): the
            # router's join prewarm and tools/soak read adopt/save/skew
            # totals from here
            "programs": _program_store_stats(),
        }

    def metrics_snapshot(self) -> dict:
        """The live-plane snapshot (ISSUE 19): one versioned dict with
        everything ``telemetry.top`` renders — :meth:`report`'s health
        surface plus the full counter/gauge registries, the SLO ledger,
        and the trace ids currently in flight on this host. Served by
        the fleet ``metrics`` op; must stay cheap and side-effect-free
        (no drain, no device work) so the plane answers while busy."""
        from pint_tpu import telemetry as _t
        from pint_tpu.telemetry.top import METRICS_SNAPSHOT_VERSION

        inflight = sorted(
            {req.trace_ctx.trace_id
             for req, *_rest in self._queue
             if req.trace_ctx is not None and req.trace_ctx.trace_id}
            | {req.trace_ctx.trace_id
               for req, _h, _t_sub in self._read_queue
               if req.trace_ctx is not None
               and req.trace_ctx.trace_id})[:64]
        return {
            "version": METRICS_SNAPSHOT_VERSION,
            "t": time.time(),
            "pid": os.getpid(),
            "enabled": _t.enabled(),
            **self.report(),
            "counters": _t.counters_snapshot(),
            "gauges": _t.gauges_snapshot(),
            "session_cache": self.sessions.stats(),
            "read_cache": self.reads.cache.stats(),
            "slo": _t.slo.snapshot(),
            "inflight_traces": inflight,
        }

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, request: FitRequest) -> FitHandle:
        """Enqueue one request; raises :class:`ServeQueueFull` when the
        bounded queue is at capacity (the backpressure contract) — at
        HALF capacity while the degradation ladder is shedding.

        The structure fingerprint is canonicalized HERE, once per
        request on the enqueue path (it is ~1 ms of model hashing — in
        the drain it would serialize with every batch), so an
        unfingerprintable model fails fast at submission and
        :meth:`plan`/:meth:`drain` only group precomputed keys.

        A :class:`PredictRequest` routes to the READ lane instead: its
        own bounded queue, drained by :meth:`drain_reads` ahead of any
        fit batch — reads never queue behind fit drains. A
        :class:`~pint_tpu.catalog.job.CatalogFitRequest` routes to the
        LONG-JOB lane (:meth:`submit_catalog`)."""
        from pint_tpu.catalog.job import CatalogFitRequest

        if isinstance(request, PredictRequest):
            return self._submit_read(request)
        if isinstance(request, CatalogFitRequest):
            return self.submit_catalog(request)
        degraded = self.degraded()
        cap = self.max_queue if not degraded else max(1, self.max_queue // 2)
        if len(self._queue) >= cap:
            depth = len(self._queue)
            telemetry.inc("serve.rejected")
            raise ServeQueueFull(depth=depth, max_queue=self.max_queue,
                                 retry_after_s=self._retry_after_hint(depth),
                                 degraded=degraded)
        seq = self._seq
        self._seq += 1
        injected = None
        plan_f = _faults.active()
        if plan_f is not None and request.model is not None:
            toas, model, injected = plan_f.corrupt_request(
                seq, request.toas, request.model)
            if injected is not None:
                request = dataclasses.replace(request, toas=toas,
                                              model=model)
                telemetry.inc(f"serve.fault.injected.{injected}")
        if request.trace_ctx is None:
            # single-host use: the trace is born HERE (fleet requests
            # arrive with the router's root already attached)
            request.trace_ctx = telemetry.trace.begin(
                "submit", host=self.host_id or None, lane="fit")
        else:
            # fleet intake: the accept hop pins THIS process into the
            # request's trace at admission — flushed per worker op, it
            # survives even a SIGKILL before the fit dispatches
            request.trace_ctx = telemetry.trace.hop(
                request.trace_ctx, "accept",
                host=self.host_id or None) or request.trace_ctx
        if request.session_id is not None:
            # sessionful request (ISSUE 10): resolve the cache key once
            # on the enqueue path; admission backpressure for NEW
            # sessions fires HERE (SessionCacheFull), before any work
            # is queued; the entry is pinned until its drain resolves
            key, entry, fp = self.sessions.resolve(request)
            mode = ("append" if entry is not None
                    and entry.model is not None else "create")
            if mode == "create":
                if request.model is None:
                    # the entry exists but holds no committed solution
                    # (its populate failed/diverged): this is still a
                    # first contact and needs a model — a structured
                    # error, not an AttributeError mid-admission
                    raise ValueError(
                        f"session {request.session_id!r} has no "
                        "committed solution (its populate did not "
                        "complete); resubmit with a model")
                self.sessions.check_admission(
                    self.sessions.estimate_bytes(request.model),
                    self._retry_after_hint(len(self._queue) + 1))
            self.sessions.pin(key)
            handle = FitHandle()
            self._queue.append((request, handle, time.perf_counter(),
                                fp, {"seq": seq, "injected": injected,
                                     "basis_bucket": 0, "pt_reason": "",
                                     "session": {"key": key, "fp": fp,
                                                 "mode": mode}}))
            telemetry.inc("serve.requests")
            telemetry.inc("serve.session.requests")
            return handle
        handle = FitHandle()
        ok, reason = _fp.batchable(request.model, request.toas)
        fp = _fp.structure_fingerprint(request.model, request.toas)
        # the ECORR basis bucket is a member SHAPE (like the TOA
        # bucket): computed once on the enqueue path, it joins the plan
        # key so equal groups share one padded-epoch-column program
        bb = (_fp.basis_bucket(request.model, request.toas)
              if ok and fp[1] != "wls" else 0)
        self._queue.append((request, handle, time.perf_counter(), fp,
                            {"seq": seq, "injected": injected,
                             "basis_bucket": bb,
                             "pt_reason": reason if not ok else ""}))
        telemetry.inc("serve.requests")
        return handle

    def pending(self) -> int:
        return len(self._queue)

    def pending_reads(self) -> int:
        return len(self._read_queue)

    # ------------------------------------------------------------------
    # the read lane (ISSUE 11)
    # ------------------------------------------------------------------
    def predict(self, request: PredictRequest) -> PredictResult:
        """The fast lane: serve one read NOW, synchronously.

        Never enqueued, never behind the fit queue — the µs-class
        request/response shape observatories and folding pipelines use.
        Its stats ride the same rolling window as queued reads and land
        in the next ``type="read"`` record."""
        return self._serve_read(request, time.perf_counter())

    def _submit_read(self, request: PredictRequest) -> PredictHandle:
        """Enqueue one read; the read queue is bounded like the fit
        queue (at 4x — reads are orders of magnitude cheaper) and
        rejects with the same :class:`ServeQueueFull` contract."""
        cap = 4 * self.max_queue
        if len(self._read_queue) >= cap:
            telemetry.inc("serve.rejected")
            raise ServeQueueFull(
                depth=len(self._read_queue), max_queue=cap,
                retry_after_s=0.05)
        if request.trace_ctx is None:
            request.trace_ctx = telemetry.trace.begin(
                "submit", host=self.host_id or None, lane="read")
        else:
            request.trace_ctx = telemetry.trace.hop(
                request.trace_ctx, "accept",
                host=self.host_id or None) or request.trace_ctx
        handle = PredictHandle()
        self._read_queue.append((request, handle, time.perf_counter()))
        telemetry.inc("serve.requests")
        return handle

    def drain_reads(self) -> list[PredictResult]:
        """Serve every queued read and emit one ``type="read"`` record.

        Called by :meth:`drain` BEFORE any fit batch forms (the
        two-tier contract) and callable standalone — a read drain never
        launches, waits on, or fetches fit work."""
        if not self._read_queue:
            return []
        queue, self._read_queue = self._read_queue, []
        out = []
        for req, handle, t_sub in queue:
            res = self._serve_read(req, t_sub)
            handle._result = res
            out.append(res)
        self._emit_read_record()
        return out

    def read_stats(self) -> dict | None:
        """Flush fast-lane stats into a ``type="read"`` record and
        return the latest record (None when no reads ran)."""
        self._emit_read_record()
        return self.last_read

    def _serve_read(self, request: PredictRequest,
                    t_submit: float) -> PredictResult:
        """Resolve + serve one read through the predict ladder."""
        from pint_tpu.serve import fingerprint as _fpm

        telemetry.inc("serve.read.requests")
        if request.trace_ctx is None:
            # the synchronous fast lane never passed through submit
            request.trace_ctx = telemetry.trace.begin(
                "submit", host=self.host_id or None, lane="read")
        t0 = time.perf_counter()
        try:
            n = int(np.atleast_1d(np.asarray(request.mjds)).size)
        except Exception:  # noqa: BLE001 — ragged input: predict()
            n = 0          # below raises the structured error
        status, error, out = "ok", None, None
        with telemetry.trace.use(request.trace_ctx), \
                telemetry.span("serve.read"):
            try:
                if request.session_id is not None:
                    skey, entry = self.sessions.lookup_for_read(
                        request.session_id)
                    model, version = entry.model, entry.version
                elif request.model is not None:
                    model, version = request.model, 0
                    # sessionless keys carry a value digest: the cache
                    # has no commit hook into a caller-owned model, so
                    # changed values must MISS (stale entries LRU out)
                    fp8 = _fpm.short_id(
                        _fpm.structure_fingerprint(model, None))
                    values = tuple(
                        p.value_f64 for p in model.params.values()
                        if p.is_numeric)
                    skey = ("model", fp8, hash(values))
                else:
                    raise ValueError(
                        "PredictRequest needs a session_id or a model")
                out = self.reads.predict(
                    model, request.mjds, obs=request.obs,
                    freq_mhz=request.freq_mhz, skey=skey,
                    version=version)
            except Exception as e:  # noqa: BLE001 — isolation boundary
                status = "failed"
                error = f"{type(e).__name__}: {e}"
                telemetry.inc("serve.read.failed")
        t_done = time.perf_counter()
        latency = t_done - t_submit       # queue-inclusive (the SLA)
        service_s = t_done - t0           # this read's own work
        if (status == "ok" and request.deadline_s is not None
                and latency > request.deadline_s):
            telemetry.inc("serve.read.deadline_timeouts")
            status = "timed_out"
            error = (f"deadline_s={request.deadline_s:g} exceeded "
                     f"(latency {latency:.6f}s); the completed "
                     "prediction is attached")
        telemetry.inc(f"serve.read.status.{status}")
        res = PredictResult(
            tag=request.tag, request=request, status=status,
            phase_int=None if out is None else out.phase_int,
            phase_frac=None if out is None else out.phase_frac,
            freq_hz=None if out is None else out.freq_hz,
            source="" if out is None else out.source,
            cache_hit=bool(out is not None and out.cache_hit),
            n_queries=n, latency_s=round(latency, 9), error=error,
            host=self.host_id or None)
        res.trace_ctx = telemetry.trace.hop(
            request.trace_ctx, "read", host=self.host_id or None,
            status=status, latency_s=round(latency, 6))
        telemetry.slo.observe("read", latency, missed=status != "ok")
        self._read_stats.append({
            "latency_s": latency, "service_s": service_s,
            "queries": n, "status": status,
            "hit": res.cache_hit,
            "trace_id": (None if request.trace_ctx is None
                         else request.trace_ctx.trace_id),
            "source": res.source or "error",
            "misses": 0 if out is None else out.window_misses,
            "fallback_queries": (0 if out is None
                                 else out.fallback_queries)})
        if status == "failed":
            telemetry.add_record(telemetry.trace.stamp({
                "type": "fault", "status": "read_failed",
                "tag": repr(request.tag), "error": error,
                "queue_latency_s": round(latency, 6)},
                request.trace_ctx))
        return res

    def _emit_read_record(self) -> None:
        """One ``type="read"`` record per window of served reads: the
        drain-record analogue for the read tier (hit rate, fallbacks,
        latency percentiles, throughput) — rendered by the report CLI's
        "read path" section; absent on read-free runs so old artifacts
        degrade gracefully."""
        window, self._read_stats = self._read_stats, []
        if not window:
            return
        lats = sorted(r["latency_s"] for r in window)

        def pct(p):
            i = min(len(lats) - 1, max(0, round(p / 100 * (len(lats) - 1))))
            return round(lats[i], 9)

        sources: dict[str, int] = {}
        statuses: dict[str, int] = {}
        for r in window:
            sources[r["source"]] = sources.get(r["source"], 0) + 1
            statuses[r["status"]] = statuses.get(r["status"], 0) + 1
        queries = sum(r["queries"] for r in window)
        # throughput over SERVICE time, not queue-inclusive latency:
        # queued reads all share the same queue wait, so summing their
        # latencies would overcount the wall by the queue depth
        busy = sum(r["service_s"] for r in window)
        self.last_read = {
            "type": "read",
            **({"host": self.host_id} if self.host_id else {}),
            "requests": len(window),
            "queries": queries,
            "cache_hit_rate": round(
                sum(1 for r in window if r["hit"]) / len(window), 4),
            "window_misses": sum(r["misses"] for r in window),
            "fallback_queries": sum(r["fallback_queries"]
                                    for r in window),
            "sources": sources,
            "statuses": statuses,
            "p50_s": pct(50), "p95_s": pct(95), "p99_s": pct(99),
            "predictions_per_s": (round(queries / busy, 1)
                                  if busy > 0 else None),
            "latencies_s": [round(v, 9) for v in lats[:64]],
            "trace_ids": sorted({r["trace_id"] for r in window
                                 if r.get("trace_id")})[:64],
            "cache": self.reads.cache.stats(),
        }
        telemetry.set_gauge("serve.read.p50_s", self.last_read["p50_s"])
        telemetry.set_gauge("serve.read.p95_s", self.last_read["p95_s"])
        telemetry.add_record(dict(self.last_read))

    # ------------------------------------------------------------------
    # batch formation
    # ------------------------------------------------------------------
    def plan(self) -> list[BatchPlan]:
        """Group the queue into placed program launches (pure; queue
        untouched).

        Group key = :func:`pint_tpu.serve.fingerprint.plan_key`
        (structure fingerprint, TOA bucket, fit hyperparameters, device
        count): equal keys guarantee one union program partitioned for
        this pool; the TOA bucket uses the fit-path policy
        (``bucketing.bucket_size``) so unequal-length tables sharing a
        bucket share a batch via the existing zero-weight ``pad_toas``
        rows. Groups keep submission order; each chunks at
        ``max_batch_members`` and pads to the pow-2 member bucket.

        Placement (the shard planner, ISSUE 7): a batchable singleton
        whose TOA bucket reaches ``toa_shard_min`` becomes a
        ``"sharded"`` plan — its TOA axis partitioned over the WHOLE
        pool (one such fit is mesh-scale work by itself). Every other
        batchable chunk becomes a ``"batched"`` plan whose MEMBER axis
        shards over an aligned device block of width = min(largest
        pow-2 dividing the member bucket, largest pow-2 <= pool size);
        blocks are chosen least-loaded-first (by member-slots already
        placed this pass, ties to the lowest slot — deterministic, so a
        repeated plan sequence lands on the same devices and reuses its
        compiled programs).

        Degradation: while globally :meth:`degraded`, EVERY plan is an
        isolated passthrough (blast radius one request). Shard-locally,
        placement avoids blocks containing :meth:`degraded_devices`;
        a plan whose every candidate block is poisoned falls back to
        isolated passthroughs while healthy blocks keep batching.
        """
        from pint_tpu.parallel.mesh import (largest_pow2_divisor,
                                            largest_pow2_leq)

        degraded = self.degraded()
        bad_devs = self.degraded_devices()
        groups: dict[tuple, list[int]] = {}
        order: list[tuple] = []
        plans: list[BatchPlan] = []
        # session-append grouping (ISSUE 20): same-structure appends
        # from MANY sessions share one vmapped rank-k launch. The group
        # key is (fingerprint short-id, pow-2 APPEND bucket, fit
        # hyperparameters) — exactly what makes one compiled batched
        # program correct for every member. Only the FIRST append per
        # session key may join a group: a second same-key append in one
        # drain must observe the first's committed state, so it stays a
        # solo singleton behind the drain's sess_prev serialization.
        sess_solo: list[tuple[int, BatchPlan]] = []
        sess_groups: dict[tuple, list[int]] = {}
        sess_keys_batched: set = set()
        sb_on = config.env_on("PINT_TPU_SESSION_BATCH")
        for i, (req, _h, _t, fp, m) in enumerate(self._queue):
            if m.get("session") is not None:
                # sessionful plans (ISSUE 10): never mixed into fit
                # batches — the incremental route holds per-session
                # state and the full-refit route runs over the
                # ACCUMULATED table, not the request's append payload.
                # Emitted first so the async incremental dispatch
                # overlaps later batch prep; blast radius stays
                # per-request (member faults resolve individually), so
                # the degradation ladder needs no special-casing.
                sm = m["session"]
                if (sb_on and sm["mode"] == "append"
                        and sm["key"] not in sess_keys_batched):
                    sess_keys_batched.add(sm["key"])
                    gkey = (_fp.short_id(fp),
                            bucketing.append_bucket_size(len(req.toas)),
                            (req.maxiter, req.min_chi2_decrease,
                             req.max_step_halvings))
                    sess_groups.setdefault(gkey, []).append(i)
                else:
                    sess_solo.append((i, BatchPlan(
                        "session", _fp.short_id(fp), [i],
                        bucketing.bucket_size(len(req.toas)), 1,
                        devices=0, reason=sm["mode"])))
                continue
            key = _fp.plan_key(fp, bucketing.bucket_size(len(req.toas)),
                               (req.maxiter, req.min_chi2_decrease,
                                req.max_step_halvings), self.n_devices,
                               m.get("basis_bucket", 0))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(i)
        # emit session plans (grouped chunks + solos) in queue order of
        # their first member, ahead of every fit batch — same overlap
        # rationale as the ISSUE-10 singletons. A group chunks at the
        # max member width and a 1-member chunk degenerates to the solo
        # plan (the batched machinery never sees width-1 work).
        sb_max = max(1, config.env_int("PINT_TPU_SESSION_BATCH_MAX"))
        for (fp8, kb, _hyp), idxs in sess_groups.items():
            for c in range(0, len(idxs), sb_max):
                chunk = idxs[c:c + sb_max]
                if len(chunk) < 2:
                    sess_solo.extend((i, BatchPlan(
                        "session", fp8, [i],
                        bucketing.bucket_size(len(self._queue[i][0].toas)),
                        1, devices=0, reason="append")) for i in chunk)
                else:
                    sess_solo.append((chunk[0], BatchPlan(
                        "session_batch", fp8, chunk, kb, len(chunk),
                        devices=0, reason="append")))
        plans.extend(p for _i, p in sorted(sess_solo, key=lambda t: t[0]))
        load = [0] * self.n_devices  # member-slots placed this pass
        width_cap = largest_pow2_leq(self.n_devices)

        def _passthrough(fp, idxs, bucket, reason):
            """One singleton passthrough plan per request; ``reason`` is
            the token the drain counts (per-request batchable reasons
            take precedence over the group-level cause)."""
            plans.extend(BatchPlan(
                "passthrough", _fp.short_id(fp), [i], bucket, 1,
                devices=0,
                reason=self._queue[i][4].get("pt_reason") or reason)
                for i in idxs)

        def _place(width: int) -> tuple[int, bool]:
            """(slot, clean): least-loaded aligned block of ``width``;
            ``clean`` False when every candidate contains a degraded
            device (placement preference keys sort degraded last)."""
            best = None
            for a in range(0, self.n_devices - width + 1, width):
                blk = range(a, a + width)
                k = (any(d in bad_devs for d in blk),
                     max(load[d] for d in blk), a)
                if best is None or k < best[0]:
                    best = (k, a)
            return best[1], not best[0][0]

        # pass 1: chunk every group; batched chunks are DEFERRED (an
        # ordered placeholder) so the member x TOA grid rule below can
        # see the whole pass's demand before widths are fixed
        batched_specs: list[tuple] = []  # (plans pos, fp, chunk, ...)
        for key in order:
            fp, bucket, bb = key[0], key[1], key[4]
            idxs = groups[key]
            if not fp[0] or degraded:  # unbatchable OR isolation mode
                _passthrough(fp, idxs, bucket,
                             "unbatchable" if not fp[0] else "degraded")
                continue
            if (self.n_devices > 1 and bucket >= self.toa_shard_min
                    and fp[1] == "wls"):
                # big-fit route: TOA axis over the whole pool, one fit
                # per program (it saturates the mesh alone; WLS only —
                # ShardedServeFitter has no noise/wideband step, so
                # big GLS/wideband singletons stay batched plans). The
                # block is every device, so any degraded device
                # isolates it.
                if bad_devs:
                    _passthrough(fp, idxs, bucket, "degraded_devices")
                    continue
                for i in idxs:
                    for d in range(self.n_devices):
                        load[d] += 1
                    plans.append(BatchPlan(
                        "sharded", _fp.short_id(fp), [i], bucket, 1,
                        devices=self.n_devices, slot=0))
                continue
            for j in range(0, len(idxs), self.max_batch_members):
                chunk = idxs[j:j + self.max_batch_members]
                # the pow-2 member bucket must not round past the
                # caller's hard cap (a 48-cap chunk padded to 64 would
                # break the device-memory bound the cap exists for)
                n_members = min(bucketing.member_bucket_size(
                                    len(chunk), floor=self.member_floor),
                                self.max_batch_members)
                plans.append(None)  # placeholder: filled in pass 2
                batched_specs.append((len(plans) - 1, fp, chunk,
                                      bucket, n_members, bb))

        # pass 2 (ISSUE 12, the PR-7 residue): when the pass's batched
        # chunks demand fewer device slots than the pool holds, the
        # spare capacity grids each plan's TOA axis instead of idling —
        # a 2-member batch on an 8-device pool becomes a (2, 4)
        # ("psr", "toa") grid, each member's TOA axis sharded over 4
        # devices. Demand >= pool (a busy drain) degenerates to the
        # pure member-sharded PR-7 rule; tiny tables (< toa_grid_min)
        # never grid (partition overhead would exceed the work).
        demand = sum(min(largest_pow2_divisor(nm), width_cap)
                     for _pos, _fp_, _c, _b, nm, _bb in batched_specs)
        spare = (largest_pow2_leq(max(1, self.n_devices // demand))
                 if demand else 1)
        filled: dict[int, BatchPlan] = {}
        for pos, fp, chunk, bucket, n_members, bb in batched_specs:
            m_width = min(largest_pow2_divisor(n_members), width_cap)
            toa_w = 1
            if bucket >= self.toa_grid_min and self.n_devices > 1:
                toa_w = min(spare, max(1, width_cap // m_width),
                            largest_pow2_leq(bucket))
            width = m_width * toa_w
            slot, clean = _place(width)
            if not clean:
                _passthrough(fp, chunk, bucket, "degraded_devices")
                continue
            for d in range(slot, slot + width):
                load[d] += n_members // m_width
            filled[pos] = BatchPlan(
                "batched", _fp.short_id(fp), chunk, bucket,
                n_members, devices=width, slot=slot,
                basis_bucket=bb, toa_devices=toa_w)
        return [filled.get(i, p) for i, p in enumerate(plans)
                if p is not None or i in filled]

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _mesh_for(self, plan: BatchPlan):
        """The plan's placement mesh over its device block (cached per
        (kind, slot, width) — jax Mesh equality is structural, so even
        fresh instances would hit the program caches; the dict just
        skips rebuilding). ``"batched"`` plans get a (width, 1)
        psr-major mesh (member axis sharded, TOA axis whole);
        ``"sharded"`` plans a (1, width) toa-major mesh; a gridded
        batched plan (``toa_devices > 1``, ISSUE 12) a
        (width/toa_devices, toa_devices) psr x toa grid."""
        from pint_tpu.parallel.mesh import make_mesh

        sharded = plan.kind == "sharded"
        psr = 1 if sharded else plan.devices // plan.toa_devices
        key = (sharded, plan.slot, plan.devices, psr)
        m = self._meshes.get(key)
        if m is None:
            devs = self.devices[plan.slot:plan.slot + plan.devices]
            m = make_mesh(devices=devs, psr_axis=psr)
            self._meshes[key] = m
        return m

    def _passthrough_fit(self, req: FitRequest):
        """One standalone ``Fitter.auto`` fit; returns
        ``(chi2, converged, diverged, reason)``. Raises on hard errors
        (the caller maps that to ``failed``)."""
        from pint_tpu.fitting.fitter import Fitter

        f = Fitter.auto(req.toas, req.model)
        # every Fitter.auto target is a _DownhillMixin, whose loop reads
        # the halving cap off the instance
        f.max_step_halvings = req.max_step_halvings
        chi2 = f.fit_toas(maxiter=req.maxiter,
                          min_chi2_decrease=req.min_chi2_decrease)
        chi2 = float(np.atleast_1d(np.asarray(chi2, dtype=float))[0])
        diverged = bool(getattr(f, "diverged", False)) \
            or not np.isfinite(chi2)
        reason = getattr(f, "diverged_reason", None) \
            or (f"non-finite chi2 ({chi2})" if diverged else None)
        return chi2, bool(np.all(np.asarray(f.converged))), diverged, reason

    def _envelope(self, entry, *, status, plan=None, chi2=float("nan"),
                  converged=False, error=None, attempts=1, trace=None,
                  retry_after_s=None, passthrough=False,
                  t_done=None, session=None) -> FitResult:
        """Build + resolve one request's result envelope (counters,
        deadline override, fault record)."""
        req, handle, t_sub, _fp_i, meta = entry
        if t_done is None:
            t_done = time.perf_counter()
        if (status in ("ok", "nonconverged") and req.deadline_s is not None
                and (t_done - t_sub) > req.deadline_s):
            telemetry.inc("serve.deadline.timeouts")
            status = "timed_out"
            error = (f"deadline_s={req.deadline_s:g} exceeded "
                     f"(latency {t_done - t_sub:.3f}s); the completed "
                     "fit is attached")
        # the dispatch hop: this host served the request — the result
        # carries the hop back so the router's commit parents under it
        hop_ctx = telemetry.trace.hop(
            req.trace_ctx, "dispatch", host=self.host_id or None,
            status=status, queue_latency_s=round(t_done - t_sub, 6))
        res = FitResult(
            tag=req.tag, request=req, chi2=float(chi2),
            converged=bool(converged),
            batch=getattr(plan, "_seq", -1) if plan is not None else -1,
            group=plan.group if plan is not None else "",
            n_members=plan.n_members if plan is not None else 0,
            occupancy=plan.occupancy if plan is not None else 0.0,
            queue_latency_s=round(t_done - t_sub, 6),
            passthrough=passthrough, status=status, error=error,
            attempts=attempts, trace=trace, retry_after_s=retry_after_s,
            injected=meta.get("injected"), session=session,
            host=self.host_id or None, trace_ctx=hop_ctx)
        handle._result = res
        telemetry.inc(f"serve.status.{status}")
        telemetry.slo.observe(
            "session" if session is not None else "fit", t_done - t_sub,
            missed=status not in ("ok", "nonconverged"))
        if status not in ("ok", "nonconverged"):
            rec = {"type": "fault", "status": status,
                   "tag": repr(req.tag), "group": res.group,
                   "error": error, "attempts": attempts,
                   "injected": res.injected,
                   "queue_latency_s": res.queue_latency_s}
            if trace is not None:
                rec["trace"] = trace
            telemetry.add_record(
                telemetry.trace.stamp(rec, hop_ctx or req.trace_ctx))
        return res

    def _salvage(self, live, plan, failure: _FailedBatch):
        """A batch stage failed: fit every member standalone instead.

        Success -> ``ok``/``nonconverged``/``diverged`` on the member's
        own merits; a second failure -> ``failed`` with both errors.
        A passthrough plan whose DISPATCH stage failed already WAS the
        standalone fit — re-running the identical deterministic fit
        would just double the cost of the same exception, so it maps
        straight to ``failed``."""
        telemetry.add_record({
            "type": "fault", "status": "batch_" + failure.stage,
            "group": plan.group, "kind": plan.kind,
            "members": len(plan.indices), "attempts": failure.attempts,
            "error": f"{type(failure.error).__name__}: {failure.error}"})
        if plan.kind in ("session", "session_batch"):
            # a session stage failure must NOT salvage via a standalone
            # fit of the request payload: an append's toas are only the
            # new rows, and the session's committed HOST solution is
            # intact (the cache only updates on success) — resolve
            # ``failed`` and let the caller retry the append. The
            # DEVICE state, however, may have been donated to the
            # failed program (accelerators): invalidate it so the
            # retry full-refits and repopulates instead of reading
            # deleted buffers forever.
            telemetry.inc("serve.fault.request")
            for i in plan.indices:
                sm = live[i][4].get("session")
                if sm is not None:
                    self.sessions.invalidate(sm["key"])
            return [self._envelope(
                live[i], status="failed", plan=plan,
                error=f"session {failure.stage} stage raised "
                      f"{type(failure.error).__name__}: {failure.error}",
                attempts=failure.attempts)
                for i in plan.indices]
        if plan.kind == "passthrough" and failure.stage == "dispatch":
            telemetry.inc("serve.fault.request")
            return [self._envelope(
                live[i], status="failed", plan=plan,
                error=f"standalone fit raised "
                      f"{type(failure.error).__name__}: {failure.error}",
                attempts=failure.attempts, passthrough=True)
                for i in plan.indices]
        out = []
        for i in plan.indices:
            entry = live[i]
            telemetry.inc("serve.retry.passthrough")
            try:
                chi2, conv, div, reason = self._passthrough_fit(entry[0])
                if div:
                    telemetry.inc("serve.fault.diverged")
                    out.append(self._envelope(
                        entry, status="diverged", plan=plan, chi2=chi2,
                        error=f"batch {failure.stage} failed "
                              f"({failure.error}); standalone retry "
                              f"diverged: {reason}",
                        attempts=failure.attempts + 1, passthrough=True))
                else:
                    telemetry.inc("serve.retry.success")
                    out.append(self._envelope(
                        entry, status="ok" if conv else "nonconverged",
                        plan=plan, chi2=chi2, converged=conv,
                        attempts=failure.attempts + 1, passthrough=True))
            except Exception as e:  # noqa: BLE001 — isolation boundary
                telemetry.inc("serve.fault.request")
                out.append(self._envelope(
                    entry, status="failed", plan=plan,
                    error=f"batch {failure.stage} stage: "
                          f"{type(failure.error).__name__}: "
                          f"{failure.error}; passthrough retry: "
                          f"{type(e).__name__}: {e}",
                    attempts=failure.attempts + 1, passthrough=True))
        return out

    def _retry_diverged(self, entry, plan, trace, m):
        """Batch member diverged on-device: ONE standalone retry, then
        quarantine with the member's flight-recorder trace attached."""
        telemetry.inc("serve.fault.diverged")
        telemetry.inc("serve.retry.passthrough")
        mtrace = _member_trace(trace, m)
        try:
            chi2, conv, div, reason = self._passthrough_fit(entry[0])
        except Exception as e:  # noqa: BLE001 — isolation boundary
            telemetry.inc("serve.quarantine.count")
            return self._envelope(
                entry, status="quarantined", plan=plan, trace=mtrace,
                error="diverged in batch (non-finite chi2); standalone "
                      f"retry raised {type(e).__name__}: {e}",
                attempts=2, passthrough=True)
        if div:
            telemetry.inc("serve.quarantine.count")
            return self._envelope(
                entry, status="quarantined", plan=plan, chi2=chi2,
                trace=mtrace,
                error="diverged in batch (non-finite chi2); standalone "
                      f"retry also diverged: {reason}",
                attempts=2, passthrough=True)
        telemetry.inc("serve.retry.success")
        return self._envelope(
            entry, status="ok" if conv else "nonconverged", plan=plan,
            chi2=chi2, converged=conv, attempts=2, passthrough=True)

    def drain(self, *, advance_catalog: bool = True) -> list[FitResult]:
        """Fit every queued request; resolve handles; empty the queue.

        Batches flow through the double-buffered pipeline: host prep of
        batch k+1 overlaps device execution of batch k, with at most
        ``window`` batches in flight. Returns results in submission
        order (batch execution order is a scheduling detail). Every
        request resolves to a structured status — a fault in one batch
        salvages its own members and never strands the rest.

        ``advance_catalog=False`` (the fleet transports' drain path)
        skips the end-of-drain catalog slice: the router advances long
        jobs through its OWN ``advance_catalog`` op under the generous
        slow-path deadline — embedding a slice (minutes of joint-fit
        work at catalog scale) inside the fit-drain RPC would blow the
        fit-sized wire deadline and falsely suspect a working host,
        and the job would advance twice per router drain.
        """
        from pint_tpu.telemetry import recorder

        # two-tier scheduling (ISSUE 11): the read lane drains FIRST —
        # queued reads are served (and any fast-lane stats recorded)
        # before a single fit batch forms, so a read can never wait on
        # a fit launch, fetch or salvage
        if self._read_queue:
            self.drain_reads()
        else:
            self._emit_read_record()
        if not self._queue:
            # no fit batches this drain: the catalog jobs still get
            # their slice (a drain loop with only long-job traffic
            # must make progress)
            if advance_catalog and self.catalog_jobs:
                self.advance_catalog()
            return []
        queue, self._queue = self._queue, []
        self._drain_seq += 1
        drain_id = self._drain_seq
        plan_f = _faults.active()
        t_form = time.perf_counter()
        results: list[FitResult | None] = [None] * len(queue)

        # ladder level 2 (shedding): while degraded, the NEWEST requests
        # beyond half capacity are rejected with a retry-after hint —
        # predictable load shedding instead of a collapsing backlog
        live_idx = list(range(len(queue)))
        if self.degraded():
            cap = max(1, self.max_queue // 2)
            if len(live_idx) > cap:
                hint = self._retry_after_hint(len(queue))
                for i in live_idx[cap:]:
                    telemetry.inc("serve.shed")
                    results[i] = self._envelope(
                        queue[i], status="rejected", retry_after_s=hint,
                        error=f"shed: degraded after {self._fail_streak} "
                              f"failing drains, queue {len(queue)} > "
                              f"degraded capacity {cap}; retry after "
                              f"~{hint:g}s", t_done=t_form)
                live_idx = live_idx[:cap]

        # deadline check at formation: an already-expired request must
        # not consume a batch slot just to miss harder
        kept = []
        for i in live_idx:
            req = queue[i][0]
            if (req.deadline_s is not None
                    and t_form - queue[i][2] > req.deadline_s):
                telemetry.inc("serve.deadline.timeouts")
                results[i] = self._envelope(
                    queue[i], status="timed_out", t_done=t_form,
                    error=f"deadline_s={req.deadline_s:g} expired before "
                          "batch formation")
            else:
                kept.append(i)

        live = [queue[i] for i in kept]
        plans = self._plans_for(live)
        fail_batches = 0
        sess_jobs: list = []  # resolved SessionJobs (drain record)
        sess_prev: dict = {}  # cache key -> last dispatched SessionJob
        # per-plan outcome/placement for shard-local ladder accounting
        # and the drain record's mesh block (keyed by plan sequence)
        failed_plans: set[int] = set()
        clean_plans: set[int] = set()
        plan_bytes: dict[int, dict] = {}

        def _hyper(plan):
            req0 = live[plan.indices[0]][0]
            return dict(maxiter=req0.maxiter,
                        min_chi2_decrease=req0.min_chi2_decrease,
                        max_step_halvings=req0.max_step_halvings)

        def _prep(plan: BatchPlan):
            state = _BatchState(plan)
            state.hyper = _hyper(plan)
            try:
                if plan_f is not None:
                    plan_f.maybe_prep_fault((drain_id, plan._seq))
                if plan.kind == "session":
                    from pint_tpu.serve.session import SessionJob

                    sm = live[plan.indices[0]][4]["session"]
                    job = SessionJob(self.sessions, sm["key"], sm["fp"],
                                     live[plan.indices[0]][0],
                                     sm["mode"])
                    job.prep()  # gates read here, once per request
                    state.fitter = job
                    return state
                if plan.kind == "session_batch":
                    from pint_tpu.serve.session import (SessionBatch,
                                                        SessionJob)

                    jobs = []
                    for i in plan.indices:
                        sm = live[i][4]["session"]
                        jobs.append(SessionJob(
                            self.sessions, sm["key"], sm["fp"],
                            live[i][0], sm["mode"]))
                    batch = SessionBatch(jobs)
                    batch.prep()
                    state.fitter = batch
                    return state
                if plan.kind == "passthrough":
                    return state  # Fitter.auto built at dispatch time
                if plan.kind == "sharded":
                    from pint_tpu.parallel.sharded_fit import \
                        ShardedServeFitter

                    req0 = live[plan.indices[0]][0]
                    with telemetry.span("serve.prep",
                                        sharded=plan.devices):
                        state.fitter = ShardedServeFitter(
                            req0.toas, req0.model, self._mesh_for(plan))
                else:
                    from pint_tpu.parallel.batch import BatchedPulsarFitter

                    problems = [(live[i][0].toas, live[i][0].model)
                                for i in plan.indices]
                    with telemetry.span("serve.prep",
                                        members=plan.n_members):
                        state.fitter = BatchedPulsarFitter(
                            problems, mesh=self._mesh_for(plan),
                            pad_members=plan.n_members,
                            basis_bucket=plan.basis_bucket)
                state.device_bytes = state.fitter.device_bytes()
                return state
            except Exception as e:  # noqa: BLE001 — isolation boundary
                telemetry.inc("serve.fault.prep")
                return _FailedBatch(plan, e, "prep")

        def _dispatch(state):
            if isinstance(state, _FailedBatch):
                return state
            # tag every program compiled under this launch with the
            # plan's fingerprint short-id: the persistent store's
            # artifacts then carry the SAME fp8 the fleet router's
            # warm-set/popularity stats use, which is what the join
            # prewarm protocol filters shipments on (pint_tpu.programs)
            from pint_tpu.programs.key import serve_fp8

            with serve_fp8(state.plan.group):
                return _dispatch_inner(state)

        def _dispatch_inner(state):
            plan = state.plan
            while True:
                try:
                    if plan_f is not None and plan.kind != "passthrough":
                        plan_f.maybe_device_error(
                            (drain_id, plan._seq), state.attempts - 1)
                    if plan.kind == "session":
                        # a same-key job dispatched earlier in THIS
                        # drain must commit its replacement state
                        # before this one routes/dispatches — two
                        # appends to one session in one drain would
                        # otherwise both read the pre-update state
                        # (stale math on CPU; deleted donated buffers
                        # on accelerators). finish() is idempotent, so
                        # the pipeline's later fetch just reads it.
                        prev = sess_prev.get(state.fitter.key)
                        if prev is not None and prev is not state.fitter:
                            try:
                                prev.finish()
                            except Exception:  # noqa: BLE001
                                pass  # surfaced at prev's own fetch
                        # incremental route: async fused dispatch (the
                        # handle's fetch is deferred to the fetch
                        # stage); populate/full-refit route: host-
                        # driven, resolved here like a passthrough
                        state.fitter.dispatch()
                        sess_prev[state.fitter.key] = state.fitter
                        return state
                    if plan.kind == "session_batch":
                        # per-member serialization against earlier
                        # same-key jobs in this drain (the grouped plan
                        # holds at most one job per key, but a create
                        # or a duplicate-append solo plan may have
                        # dispatched before this one)
                        for job in state.fitter.jobs:
                            prev = sess_prev.get(job.key)
                            if prev is not None and prev is not job:
                                try:
                                    prev.finish()
                                except Exception:  # noqa: BLE001
                                    pass  # surfaced at prev's own fetch
                        state.fitter.dispatch()
                        for job in state.fitter.jobs:
                            sess_prev[job.key] = job
                        return state
                    if plan.kind == "passthrough":
                        # host-driven fitters cannot be suspended
                        # mid-loop: the fit runs here, already resolved
                        # at fetch time. The trace is captured NOW —
                        # by fetch time a later batch's dispatch may
                        # have overwritten last_trace() — and so is the
                        # completion time: the work-stealing pipeline
                        # may defer this state's fetch past later
                        # batches, which must not inflate the request's
                        # queue latency or trip its deadline
                        req0 = live[plan.indices[0]][0]
                        state.resolved = self._passthrough_fit(req0)
                        state.trace = recorder.last_trace()
                        state.t_done = time.perf_counter()
                    else:
                        state.handle = state.fitter.dispatch_fit(
                            **state.hyper)
                    return state
                except Exception as e:  # noqa: BLE001
                    if (state.attempts <= self.max_dispatch_retries
                            and transient_error(e)):
                        telemetry.inc("serve.retry.dispatch")
                        if self.retry_backoff_s > 0:
                            time.sleep(self.retry_backoff_s
                                       * 2 ** (state.attempts - 1))
                        state.attempts += 1
                        continue
                    telemetry.inc("serve.fault.dispatch")
                    return _FailedBatch(plan, e, "dispatch",
                                        state.attempts)

        def _fetch(state, plan: BatchPlan):
            nonlocal fail_batches
            if isinstance(state, _FailedBatch):
                fail_batches += 1
                failed_plans.add(plan._seq)
                return self._salvage(live, plan, state)
            if state.device_bytes:
                plan_bytes[plan._seq] = state.device_bytes
            if plan.kind == "session":
                entry = live[plan.indices[0]]
                job = state.fitter
                try:
                    res = job.finish()
                except Exception as e:  # noqa: BLE001 — isolation
                    fail_batches += 1
                    failed_plans.add(plan._seq)
                    return self._salvage(live, plan,
                                         _FailedBatch(plan, e, "fetch",
                                                      state.attempts))
                clean_plans.add(plan._seq)
                sess_jobs.append(job)
                if res["diverged"]:
                    telemetry.inc("serve.fault.diverged")
                    return [self._envelope(
                        entry, status="diverged", plan=plan,
                        chi2=res["chi2"], t_done=job.t_done,
                        attempts=job.attempts, session=res["route"],
                        error="session fit diverged (incremental "
                              "fallback included)" if job.attempts > 1
                              else "session fit diverged")]
                return [self._envelope(
                    entry,
                    status="ok" if res["converged"] else "nonconverged",
                    plan=plan, chi2=res["chi2"],
                    converged=res["converged"], t_done=job.t_done,
                    attempts=job.attempts, session=res["route"])]
            if plan.kind == "session_batch":
                # per-member resolution: one member's fetch failure
                # resolves THAT member ``failed`` (device state
                # invalidated, committed host solution intact — the
                # ISSUE-10 salvage contract) while the rest commit on
                # their own merits
                out = []
                any_fail = False
                for m_i, i in enumerate(plan.indices):
                    entry = live[i]
                    job = state.fitter.jobs[m_i]
                    try:
                        res = job.finish()
                    except Exception as e:  # noqa: BLE001 — isolation
                        any_fail = True
                        telemetry.inc("serve.fault.request")
                        sm = entry[4].get("session")
                        if sm is not None:
                            self.sessions.invalidate(sm["key"])
                        out.append(self._envelope(
                            entry, status="failed", plan=plan,
                            error=f"session batch member raised "
                                  f"{type(e).__name__}: {e}",
                            attempts=state.attempts))
                        continue
                    sess_jobs.append(job)
                    if res["diverged"]:
                        telemetry.inc("serve.fault.diverged")
                        out.append(self._envelope(
                            entry, status="diverged", plan=plan,
                            chi2=res["chi2"], t_done=job.t_done,
                            attempts=job.attempts,
                            session=res["route"],
                            error="session fit diverged (incremental "
                                  "fallback included)"
                                  if job.attempts > 1
                                  else "session fit diverged"))
                    else:
                        out.append(self._envelope(
                            entry,
                            status="ok" if res["converged"]
                            else "nonconverged",
                            plan=plan, chi2=res["chi2"],
                            converged=res["converged"],
                            t_done=job.t_done, attempts=job.attempts,
                            session=res["route"]))
                if any_fail:
                    fail_batches += 1
                    failed_plans.add(plan._seq)
                else:
                    clean_plans.add(plan._seq)
                return out
            if plan.kind == "passthrough":
                clean_plans.add(plan._seq)
                entry = live[plan.indices[0]]
                chi2, conv, div, reason = state.resolved
                if div:
                    telemetry.inc("serve.fault.diverged")
                    return [self._envelope(
                        entry, status="diverged", plan=plan, chi2=chi2,
                        error=f"standalone fit diverged: {reason}",
                        trace=state.trace, t_done=state.t_done,
                        attempts=state.attempts, passthrough=True)]
                return [self._envelope(
                    entry, status="ok" if conv else "nonconverged",
                    plan=plan, chi2=chi2, converged=conv,
                    t_done=state.t_done,
                    attempts=state.attempts, passthrough=True)]
            while True:
                try:
                    # the deferred async-dispatch error surfaces at this
                    # sync; one retry "attempt" = fresh dispatch + fetch
                    if state.handle is None:
                        from pint_tpu.programs.key import serve_fp8

                        with serve_fp8(plan.group):
                            state.handle = state.fitter.dispatch_fit(
                                **state.hyper)
                    chi2 = np.asarray(state.handle.finish(), dtype=float)
                    break
                except Exception as e:  # noqa: BLE001
                    state.handle = None  # never refetch a failed handle
                    if (state.attempts <= self.max_dispatch_retries
                            and transient_error(e)):
                        telemetry.inc("serve.retry.dispatch")
                        if self.retry_backoff_s > 0:
                            time.sleep(self.retry_backoff_s
                                       * 2 ** (state.attempts - 1))
                        state.attempts += 1
                        continue
                    telemetry.inc("serve.fault.fetch")
                    fail_batches += 1
                    failed_plans.add(plan._seq)
                    return self._salvage(live, plan,
                                         _FailedBatch(plan, e, "fetch",
                                                      state.attempts))
            clean_plans.add(plan._seq)
            fitter = state.fitter
            conv = np.asarray(fitter.converged)
            div = np.asarray(fitter.diverged)
            # the batch's device trace (per-member vectors), captured
            # before any passthrough retry overwrites last_trace()
            trace = recorder.last_trace() if bool(div.any()) else None
            # stamped AFTER finish(): queue latency must include the
            # device wait, not just the time to reach the fetch stage
            t_done = time.perf_counter()
            out = []
            for m, i in enumerate(plan.indices):
                entry = live[i]
                if bool(div[m]):
                    out.append(self._retry_diverged(entry, plan,
                                                    trace, m))
                else:
                    out.append(self._envelope(
                        entry,
                        status="ok" if bool(np.all(conv[m]))
                        else "nonconverged",
                        plan=plan, chi2=float(chi2[m]),
                        converged=bool(np.all(conv[m])),
                        attempts=state.attempts, t_done=t_done))
            return out

        def _ready(state) -> bool:
            """Non-blocking completion peek for the work-stealing drain
            (advisory: a wrong True only reorders one fetch)."""
            if isinstance(state, _FailedBatch):
                return True
            if state.plan.kind == "passthrough":
                return True  # resolved synchronously at dispatch
            if state.plan.kind in ("session", "session_batch"):
                return state.fitter.ready()
            try:
                return bool(state.handle is not None
                            and state.handle.ready())
            except Exception:  # noqa: BLE001
                return True

        for seq, plan in enumerate(plans):
            plan._seq = seq
        try:
            per_batch, stats = run_pipeline(
                plans, prep=_prep, dispatch=_dispatch,
                fetch=_fetch, window=self.window,
                slots_of=lambda p: p.device_ids, ready=_ready)
        except BaseException:
            # the stages above are isolation boundaries, so this fires
            # only on a scheduler bug: every request whose handle is
            # still unresolved goes back on the queue (ahead of anything
            # submitted meanwhile) so the caller can retry — nothing is
            # ever silently dropped
            self._queue[:0] = [e for e in queue if e[1]._result is None]
            raise
        finally:
            # release session pins for every RESOLVED request (requeued
            # ones keep theirs — their entry must stay evict-protected)
            for e in queue:
                sm = e[4].get("session")
                if sm is not None and e[1]._result is not None:
                    self.sessions.unpin(sm["key"])

        for plan, batch_results in zip(plans, per_batch):
            for i, res in zip(plan.indices, batch_results):
                results[kept[i]] = res

        # ladder bookkeeping (shard-local, ISSUE 7): the GLOBAL streak
        # grows only when every batch that ran failed (the whole pool
        # in trouble) and heals on a failure-free drain; a MIXED drain
        # — some shards failing while others complete — leaves the
        # global ladder alone and charges the failing shards' devices
        # instead, so one poisoned shard degrades (and is routed
        # around) without collapsing the service to passthroughs
        if not fail_batches:
            self._fail_streak = 0
            self._dev_streak.clear()  # a clean drain heals every shard
        elif not clean_plans:
            self._fail_streak += 1
        if fail_batches:
            by_plan = {p._seq: p for p in plans}
            fail_devs = {d for s in failed_plans
                         for d in by_plan[s].device_ids}
            clean_devs = {d for s in clean_plans
                          for d in by_plan[s].device_ids}
            for d in fail_devs:
                self._dev_streak[d] = self._dev_streak.get(d, 0) + 1
            for d in clean_devs - fail_devs:
                self._dev_streak.pop(d, None)
        telemetry.set_gauge("serve.fail_streak", self._fail_streak)

        n_real = sum(len(p.indices) for p in plans)
        n_members = sum(p.n_members for p in plans)
        occupancy = n_real / max(1, n_members)

        # passthrough accounting (ISSUE 8 satellite): WHY a request
        # skipped the batched path, as stable reason tokens — counters
        # plus a per-drain breakdown so frontier regressions (a model
        # class silently falling off the batchable set) are visible
        # from committed artifacts via the report CLI
        pt_reasons: dict[str, int] = {}
        n_pt_req = 0
        for p in plans:
            if p.kind != "passthrough":
                continue
            n_pt_req += len(p.indices)
            token = p.reason or "unbatchable"
            pt_reasons[token] = pt_reasons.get(token, 0) + len(p.indices)
            telemetry.inc(f"serve.passthrough.reason.{token}",
                          len(p.indices))
        pt_rate = n_pt_req / max(1, n_real)
        # pow-2 member-padding waste, visible BEFORE sharding multiplies
        # it (ISSUE-7 satellite): dummy members replicate a real fit's
        # work on every device their batch spans
        dummies = n_members - n_real
        if dummies:
            telemetry.inc("serve.pad.dummy_members", dummies)

        # per-device placement accounting for the drain record's mesh
        # block: member-slots assigned vs real members per device (the
        # occupancy vector) and placed table bytes, summed over the
        # drain's plans (not a simultaneous peak — the per-device
        # window bounds concurrency)
        D = self.n_devices
        dev_members = [0] * D
        dev_slots = [0] * D
        dev_bytes = [0] * D
        member_sharded = toa_sharded = 0
        for p in plans:
            if p.kind == "batched":
                member_sharded += p.devices > 1
                # a gridded plan (ISSUE 12) spans a (m_width,
                # toa_devices) block: each member row occupies
                # toa_devices consecutive devices, every one holding a
                # TOA shard of that row's members
                m_width = p.devices // p.toa_devices
                per = p.n_members // m_width
                for o, d in enumerate(p.device_ids):
                    j = o // p.toa_devices  # this device's member row
                    dev_slots[d] += per
                    dev_members[d] += max(
                        0, min(per, len(p.indices) - j * per))
            elif p.kind == "sharded":
                toa_sharded += 1
                for d in p.device_ids:
                    dev_slots[d] += 1
                    dev_members[d] += 1
        for s, by_dev in plan_bytes.items():
            for did, nb in by_dev.items():
                idx = self._dev_index.get(did)
                if idx is not None:
                    dev_bytes[idx] += nb
        occ_vec = [round(dev_members[d] / dev_slots[d], 4)
                   if dev_slots[d] else 0.0 for d in range(D)]
        gridded = sum(p.kind == "batched" and p.toa_devices > 1
                      for p in plans)
        telemetry.set_gauge("serve.mesh.devices", D)
        if member_sharded:
            telemetry.inc("serve.mesh.member_sharded", member_sharded)
        if toa_sharded:
            telemetry.inc("serve.mesh.toa_sharded", toa_sharded)
        if gridded:
            telemetry.inc("serve.mesh.gridded", gridded)
        if stats.get("stolen_fetches"):
            telemetry.inc("serve.mesh.stolen_fetches",
                          stats["stolen_fetches"])
        fits_per_s = n_real / max(stats["wall_s"], 1e-12)
        if n_real:
            self._drain_rate = (fits_per_s if self._drain_rate is None
                                else 0.5 * self._drain_rate
                                + 0.5 * fits_per_s)
        # sessionful rollup (ISSUE 10): per-drain route split, update-
        # latency percentiles of the incremental path, cache health —
        # the report CLI's "sessions" section reads this block (absent
        # on session-free drains; old records degrade gracefully)
        sessions_block = None
        if sess_jobs:
            routes: dict[str, int] = {}
            trips = 0
            for j in sess_jobs:
                routes[j.route] = routes.get(j.route, 0) + 1
                trips += j.reason in ("append_gate", "drift_gate")
            incr_walls = sorted(
                j.wall_s for j in sess_jobs
                if j.route == "incremental" and j.wall_s is not None)
            # launch accounting (ISSUE 20): N batched members riding M
            # vmapped launches + S solo rank-k launches -> the drain's
            # incremental work cost M + S device launches, and
            # launches-per-update is the headline batching win
            solo = sum(j.launch == "solo" for j in sess_jobs)
            batched_members = sum(j.launch == "batched"
                                  for j in sess_jobs)
            batched = len({id(j._batch) for j in sess_jobs
                           if j.launch == "batched"})
            sessions_block = {
                "requests": len(sess_jobs),
                "routes": routes,
                "drift_trips": trips,
                "launches": {"solo": solo, "batched": batched,
                             "batched_members": batched_members,
                             "per_update": round(
                                 (solo + batched)
                                 / max(1, solo + batched_members), 4)},
                "update_latencies_s": [round(w, 6)
                                       for w in incr_walls[:64]],
                "p50_update_s": (round(float(np.percentile(
                    incr_walls, 50)), 6) if incr_walls else None),
                "p95_update_s": (round(float(np.percentile(
                    incr_walls, 95)), 6) if incr_walls else None),
                "cache": self.sessions.stats(),
            }
            telemetry.inc("serve.session.drains")

        # catalog slice (ISSUE 14): long jobs advance AFTER this
        # drain's reads and fit batches resolved — bounded by the
        # device-budget slice, so a drain's wall is small-fit work
        # plus at most one slice, never the whole joint fit
        catalog_block = None
        if advance_catalog and self.catalog_jobs:
            prog = self.advance_catalog()
            catalog_block = {
                "jobs": len(prog),
                "running": sum(p["state"] == "running" for p in prog),
                "done": sum(p["state"] == "done" for p in prog),
                "failed": sum(p["state"] == "failed" for p in prog),
                "iterations": sum(p["iterations"] for p in prog),
                "checkpoints": sum(p["checkpoints"] for p in prog),
                "resumes": sum(p["resumes"] for p in prog),
            }

        statuses: dict[str, int] = {}
        for r in results:
            statuses[r.status] = statuses.get(r.status, 0) + 1
        telemetry.inc("serve.batches", len(plans))
        telemetry.inc("serve.batches.passthrough",
                      sum(p.kind == "passthrough" for p in plans))
        telemetry.set_gauge("serve.occupancy", occupancy)
        telemetry.set_gauge("serve.fits_per_s", round(fits_per_s, 3))
        telemetry.set_gauge("serve.overlap_efficiency",
                            stats["overlap_efficiency"])
        self.last_drain = {
            "type": "serve",
            **({"host": self.host_id} if self.host_id else {}),
            "fits": n_real, "batches": len(plans),
            "occupancy": round(occupancy, 4),
            "fits_per_s": round(fits_per_s, 3),
            "queue_latency_s_mean": round(
                float(np.mean([r.queue_latency_s for r in results])), 6),
            "window": self.window,
            "statuses": statuses,
            "failed_batches": fail_batches,
            "degraded": self.degraded(),
            "fail_streak": self._fail_streak,
            "dummy_members": dummies,
            "dummy_fraction": round(dummies / max(1, n_members), 4),
            "passthrough": {
                "requests": n_pt_req,
                "rate": round(pt_rate, 4),
                "reasons": dict(sorted(pt_reasons.items(),
                                       key=lambda kv: -kv[1])),
            },
            "mesh": {
                "devices": D,
                "per_device_members": dev_members,
                "per_device_slots": dev_slots,
                "per_device_occupancy": occ_vec,
                "per_device_bytes": dev_bytes,
                "member_sharded": member_sharded,
                "toa_sharded": toa_sharded,
                "gridded": gridded,
                "shard_fail_streaks": {
                    str(d): s
                    for d, s in sorted(self._dev_streak.items())},
            },
            **({"sessions": sessions_block} if sessions_block else {}),
            **({"catalog": catalog_block} if catalog_block else {}),
            # distributed-trace cross-reference (capped): which request
            # traces this drain served — report --trace joins on these
            "trace_ids": sorted({
                r.trace_ctx.trace_id for r in results
                if r.trace_ctx is not None})[:64],
            "batch_detail": [
                {"kind": p.kind, "group": p.group,
                 "toa_bucket": p.toa_bucket, "real": len(p.indices),
                 "members": p.n_members, "devices": p.devices,
                 "slot": p.slot,
                 "occupancy": round(p.occupancy, 4),
                 **({"basis_bucket": p.basis_bucket}
                    if p.basis_bucket else {}),
                 **({"toa_devices": p.toa_devices}
                    if p.toa_devices > 1 else {}),
                 **({"reason": p.reason} if p.reason else {})}
                for p in plans],
            **stats,
        }
        telemetry.add_record(dict(self.last_drain))
        return results

    def _plans_for(self, queue) -> list[BatchPlan]:
        """plan() against an already-dequeued snapshot."""
        saved, self._queue = self._queue, queue
        try:
            return self.plan()
        finally:
            self._queue = saved
