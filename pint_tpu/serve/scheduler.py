"""Throughput scheduler: fingerprint-bucketed continuous batching.

The north star is a *service*: many independent fit requests, not one
fast fit. After PR 3 a single fit is one XLA launch, but a stream of
fits still executed strictly one-after-another, each paying its own
launch + fetch + host-prep serialization. This module closes that gap
with the standard serving-system moves (continuous batching a la Orca,
double-buffered dispatch):

1. **Bounded queue** — :meth:`ThroughputScheduler.submit` enqueues a
   :class:`FitRequest` and returns a :class:`FitHandle`; a full queue
   raises :class:`ServeQueueFull` (backpressure is the caller's signal
   to drain, never silent dropping).
2. **Batch formation** (:meth:`ThroughputScheduler.plan`) — queued
   requests group by (structure fingerprint, TOA-count bucket, fit
   hyperparameters); each group chunks to ``max_batch_members`` and
   pads to the pow-2 member bucket
   (:func:`pint_tpu.bucketing.member_bucket_size`) with bit-inert dummy
   members, so B structurally-compatible fits cost ONE fused program
   launch and ONE fetch — and same-group batches across drains reuse
   one compiled program (the fit-program cache).
3. **Double-buffered dispatch** (:mod:`pint_tpu.serve.pipeline`) —
   while batch k executes on-device, the host packs/whitens/pads batch
   k+1; a bounded in-flight window keeps device memory bounded.

Models the vmapped WLS union cannot express (correlated-noise bases,
delay-side jumps, wideband) are served through a **passthrough** path —
a per-request ``Fitter.auto`` fit in its own singleton batch — so the
scheduler accepts any model the library can fit.

Telemetry: ``serve.*`` counters/gauges plus one ``type="serve"``
JSON-lines record per drain (per-batch occupancy, queue latency,
overlap efficiency, fits/s) — rendered by ``python -m
pint_tpu.telemetry.report`` under "throughput engine".
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any

import numpy as np

from pint_tpu import bucketing, telemetry
from pint_tpu.serve import fingerprint as _fp
from pint_tpu.serve.pipeline import run_pipeline


class ServeQueueFull(RuntimeError):
    """submit() on a full queue: drain (or widen max_queue) and retry."""


@dataclasses.dataclass
class FitRequest:
    """One fit: a TOA table + a (perturbed) model to fit in place."""

    toas: Any
    model: Any
    maxiter: int = 20
    min_chi2_decrease: float = 1e-3
    max_step_halvings: int = 8
    tag: Any = None


@dataclasses.dataclass
class FitResult:
    """Per-request outcome; ``request.model`` holds the fitted values."""

    tag: Any
    request: FitRequest
    chi2: float
    converged: bool
    batch: int
    group: str
    n_members: int
    occupancy: float
    queue_latency_s: float
    passthrough: bool = False


class FitHandle:
    """Future-like handle returned by :meth:`ThroughputScheduler.submit`."""

    __slots__ = ("_result",)

    def __init__(self):
        self._result: FitResult | None = None

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> FitResult:
        if self._result is None:
            raise RuntimeError("request not drained yet; call "
                               "ThroughputScheduler.drain() first")
        return self._result


@dataclasses.dataclass
class BatchPlan:
    """One planned program launch (inspectable, pure — no device work)."""

    kind: str                 # "batched" | "passthrough"
    group: str                # fingerprint short id
    indices: list[int]        # queue positions of the member requests
    toa_bucket: int
    n_members: int            # padded member count (1 for passthrough)

    @property
    def occupancy(self) -> float:
        return len(self.indices) / max(1, self.n_members)


class ThroughputScheduler:
    """Bounded-queue continuous batching over the fused batched loop.

    Parameters: ``max_queue`` bounds :meth:`submit` (backpressure);
    ``max_batch_members`` caps one program's member count;
    ``member_floor`` floors the pow-2 member bucket (tests use it to
    force dummy padding); ``window`` is the double-buffer depth
    (in-flight batches); ``mesh`` is forwarded to the batched fitter.
    """

    def __init__(self, *, max_queue: int = 256,
                 max_batch_members: int = 64, member_floor: int = 1,
                 window: int = 2, mesh=None):
        if max_queue < 1 or max_batch_members < 1:
            raise ValueError("max_queue and max_batch_members must be >= 1")
        self.max_queue = max_queue
        self.max_batch_members = max_batch_members
        self.member_floor = max(1, member_floor)
        self.window = max(1, window)
        self.mesh = mesh
        self._queue: list[tuple[FitRequest, FitHandle, float, tuple]] = []
        self.last_drain: dict | None = None

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, request: FitRequest) -> FitHandle:
        """Enqueue one request; raises :class:`ServeQueueFull` when the
        bounded queue is at capacity (the backpressure contract).

        The structure fingerprint is canonicalized HERE, once per
        request on the enqueue path (it is ~1 ms of model hashing — in
        the drain it would serialize with every batch), so an
        unfingerprintable model fails fast at submission and
        :meth:`plan`/:meth:`drain` only group precomputed keys."""
        if len(self._queue) >= self.max_queue:
            telemetry.inc("serve.rejected")
            raise ServeQueueFull(
                f"queue at capacity ({self.max_queue}); drain() first")
        handle = FitHandle()
        fp = _fp.structure_fingerprint(request.model, request.toas)
        self._queue.append((request, handle, time.perf_counter(), fp))
        telemetry.inc("serve.requests")
        return handle

    def pending(self) -> int:
        return len(self._queue)

    # ------------------------------------------------------------------
    # batch formation
    # ------------------------------------------------------------------
    def plan(self) -> list[BatchPlan]:
        """Group the queue into program launches (pure; queue untouched).

        Group key = (structure fingerprint, TOA bucket, fit
        hyperparameters): equal keys guarantee one union program; the
        TOA bucket uses the fit-path policy (``bucketing.bucket_size``)
        so unequal-length tables sharing a bucket share a batch via the
        existing zero-weight ``pad_toas`` rows. Groups keep submission
        order; each chunks at ``max_batch_members`` and pads to the
        pow-2 member bucket.
        """
        groups: dict[tuple, list[int]] = {}
        order: list[tuple] = []
        for i, (req, _h, _t, fp) in enumerate(self._queue):
            key = (fp, bucketing.bucket_size(len(req.toas)),
                   req.maxiter, req.min_chi2_decrease,
                   req.max_step_halvings)
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append(i)
        plans: list[BatchPlan] = []
        for key in order:
            fp, bucket = key[0], key[1]
            idxs = groups[key]
            if not fp[0]:          # the fingerprint's batchable bit
                plans.extend(
                    BatchPlan("passthrough", _fp.short_id(fp), [i],
                              bucket, 1) for i in idxs)
                continue
            for j in range(0, len(idxs), self.max_batch_members):
                chunk = idxs[j:j + self.max_batch_members]
                # the pow-2 member bucket must not round past the
                # caller's hard cap (a 48-cap chunk padded to 64 would
                # break the device-memory bound the cap exists for)
                plans.append(BatchPlan(
                    "batched", _fp.short_id(fp), chunk, bucket,
                    min(bucketing.member_bucket_size(
                            len(chunk), floor=self.member_floor),
                        self.max_batch_members)))
        return plans

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def drain(self) -> list[FitResult]:
        """Fit every queued request; resolve handles; empty the queue.

        Batches flow through the double-buffered pipeline: host prep of
        batch k+1 overlaps device execution of batch k, with at most
        ``window`` batches in flight. Returns results in submission
        order (batch execution order is a scheduling detail).
        """
        if not self._queue:
            return []
        queue, self._queue = self._queue, []
        plans = self._plans_for(queue)

        def _prep(plan: BatchPlan):
            if plan.kind == "passthrough":
                from pint_tpu.fitting.fitter import Fitter

                req = queue[plan.indices[0]][0]
                return Fitter.auto(req.toas, req.model)
            from pint_tpu.parallel.batch import BatchedPulsarFitter

            problems = [(queue[i][0].toas, queue[i][0].model)
                        for i in plan.indices]
            with telemetry.span("serve.prep", members=plan.n_members):
                return BatchedPulsarFitter(problems, mesh=self.mesh,
                                           pad_members=plan.n_members)

        def _dispatch(prepped):
            plan, fitter = prepped._serve_plan, prepped
            req0 = queue[plan.indices[0]][0]
            if plan.kind == "passthrough":
                # host-driven fitters cannot be suspended mid-loop: the
                # fit runs here, already resolved at fetch time. Every
                # Fitter.auto target is a _DownhillMixin, whose loop
                # reads the halving cap off the instance
                fitter.max_step_halvings = req0.max_step_halvings
                chi2 = fitter.fit_toas(
                    maxiter=req0.maxiter,
                    min_chi2_decrease=req0.min_chi2_decrease)
                return (chi2, fitter)
            return fitter.dispatch_fit(
                maxiter=req0.maxiter,
                min_chi2_decrease=req0.min_chi2_decrease,
                max_step_halvings=req0.max_step_halvings)

        def _fetch(handle, plan: BatchPlan):
            out: list[FitResult] = []
            if plan.kind == "passthrough":
                chi2, fitter = handle
                chi2 = np.atleast_1d(np.asarray(chi2, dtype=float))
                conv = np.atleast_1d(np.asarray(fitter.converged))
            else:
                chi2 = np.asarray(handle.finish(), dtype=float)
                conv = np.asarray(handle.fitter.converged)
            # stamped AFTER finish(): queue latency must include the
            # device wait, not just the time to reach the fetch stage
            t_done = time.perf_counter()
            for m, i in enumerate(plan.indices):
                req, rh, t_sub, _fp_i = queue[i]
                res = FitResult(
                    tag=req.tag, request=req, chi2=float(chi2[m]),
                    converged=bool(np.all(conv[m])), batch=plan._seq,
                    group=plan.group, n_members=plan.n_members,
                    occupancy=plan.occupancy,
                    queue_latency_s=round(t_done - t_sub, 6),
                    passthrough=plan.kind == "passthrough")
                rh._result = res
                out.append(res)
            return out

        # thread each plan through prep so dispatch/fetch see it
        def prep_with_plan(plan):
            prepped = _prep(plan)
            prepped._serve_plan = plan
            return prepped

        for seq, plan in enumerate(plans):
            plan._seq = seq
        try:
            per_batch, stats = run_pipeline(
                plans, prep=prep_with_plan,
                dispatch=_dispatch,
                fetch=lambda h, plan: _fetch(h, plan), window=self.window)
        except BaseException:
            # one bad batch must not strand the rest of the drain:
            # every request whose handle is still unresolved goes back
            # on the queue (ahead of anything submitted meanwhile) so
            # the caller can retry — nothing is ever silently dropped
            self._queue[:0] = [e for e in queue if e[1]._result is None]
            raise

        results: list[FitResult] = [None] * len(queue)
        for plan, batch_results in zip(plans, per_batch):
            for i, res in zip(plan.indices, batch_results):
                results[i] = res

        n_real = sum(len(p.indices) for p in plans)
        n_members = sum(p.n_members for p in plans)
        occupancy = n_real / max(1, n_members)
        fits_per_s = n_real / max(stats["wall_s"], 1e-12)
        telemetry.inc("serve.batches", len(plans))
        telemetry.inc("serve.batches.passthrough",
                      sum(p.kind == "passthrough" for p in plans))
        telemetry.set_gauge("serve.occupancy", occupancy)
        telemetry.set_gauge("serve.fits_per_s", round(fits_per_s, 3))
        telemetry.set_gauge("serve.overlap_efficiency",
                            stats["overlap_efficiency"])
        self.last_drain = {
            "type": "serve", "fits": n_real, "batches": len(plans),
            "occupancy": round(occupancy, 4),
            "fits_per_s": round(fits_per_s, 3),
            "queue_latency_s_mean": round(
                float(np.mean([r.queue_latency_s for r in results])), 6),
            "window": self.window,
            "batch_detail": [
                {"kind": p.kind, "group": p.group,
                 "toa_bucket": p.toa_bucket, "real": len(p.indices),
                 "members": p.n_members,
                 "occupancy": round(p.occupancy, 4)} for p in plans],
            **stats,
        }
        telemetry.add_record(dict(self.last_drain))
        return results

    def _plans_for(self, queue) -> list[BatchPlan]:
        """plan() against an already-dequeued snapshot."""
        saved, self._queue = self._queue, queue
        try:
            return self.plan()
        finally:
            self._queue = saved
