"""Double-buffered host/device dispatch pipeline.

The fused batched loop (fitting.device_loop) made a whole batch of fits
ONE program launch and ONE fetch — but a naive driver still serializes
host packing (union build, mask materialization, stacking, padding,
device placement) with device execution: the device idles while the
host prepares batch k+1, and the host idles while the device runs
batch k. JAX dispatch is asynchronous (a jitted call returns as soon
as the work is enqueued), so the two stages overlap whenever the fetch
is deferred:

    host   : prep(0) dispatch(0) prep(1) dispatch(1) fetch(0) prep(2) ...
    device :         [==== batch 0 ====][==== batch 1 ====][== batch 2 ...

:func:`run_pipeline` drives that schedule with a bounded in-flight
window (default 2 = classic double buffering): the window drains to
``window - 1`` BEFORE batch k's prep runs — prep itself device-places
the stacked tables, so batch k's fresh buffers plus the in-flight
batches never exceed ``window`` sets of live device buffers, the
backpressure contract that keeps device memory bounded no matter how
many batches a drain covers. Batch k's prep still overlaps the
``window - 1`` batches left executing (with the default window of 2
that is exactly prep-k+1-over-execute-k double buffering).

The pipeline is deliberately thread-free: overlap comes from the JAX
runtime's async dispatch, not host threading, so every user-model
callback (prep's union building mutates no shared state, but models
are not thread-safe in general) runs on the caller's thread.
"""

from __future__ import annotations

import time


def run_pipeline(items, *, prep, dispatch, fetch, window: int = 2):
    """Run each item through prep -> dispatch -> fetch with overlap.

    ``prep(item)`` is the host stage (pack/whiten/pad); ``dispatch
    (prepped)`` enqueues device work and must NOT block on it,
    returning a handle; ``fetch(handle, item)`` blocks on the result.
    Returns ``(results, stats)`` with results in item order and
    ``stats = {"prep_s", "dispatch_s", "wait_s", "wall_s",
    "overlap_efficiency"}`` — ``wait_s`` is the time the host spent
    blocked in fetch; ``overlap_efficiency`` the fraction of the drain
    wall during which the host was doing useful (non-blocked) work,
    i.e. ``1 - wait_s / wall_s``.
    """
    window = max(1, int(window))
    items = list(items)
    results = [None] * len(items)
    inflight: list[tuple[int, object]] = []
    prep_s = dispatch_s = wait_s = 0.0
    t_start = time.perf_counter()

    def _fetch_oldest():
        nonlocal wait_s
        i, handle = inflight.pop(0)
        t0 = time.perf_counter()
        results[i] = fetch(handle, items[i])
        wait_s += time.perf_counter() - t0

    for i, item in enumerate(items):
        # drain to window - 1 BEFORE prep: prep device-places batch i's
        # stacked tables, so draining any later would let window + 1
        # batches hold live device buffers (the documented bound is
        # ``window``); prep still overlaps the remaining in-flight work
        while len(inflight) >= window:
            _fetch_oldest()
        t0 = time.perf_counter()
        prepped = prep(item)
        prep_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        inflight.append((i, dispatch(prepped)))
        dispatch_s += time.perf_counter() - t0
    while inflight:
        _fetch_oldest()
    wall_s = time.perf_counter() - t_start
    return results, {
        "prep_s": round(prep_s, 6),
        "dispatch_s": round(dispatch_s, 6),
        "wait_s": round(wait_s, 6),
        "wall_s": round(wall_s, 6),
        "overlap_efficiency": round(1.0 - wait_s / max(wall_s, 1e-12), 4),
    }
