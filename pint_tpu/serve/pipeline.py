"""Windowed host/device dispatch pipeline over a device-slot pool.

The fused batched loop (fitting.device_loop) made a whole batch of fits
ONE program launch and ONE fetch — but a naive driver still serializes
host packing (union build, mask materialization, stacking, padding,
device placement) with device execution: the device idles while the
host prepares batch k+1, and the host idles while the device runs
batch k. JAX dispatch is asynchronous (a jitted call returns as soon
as the work is enqueued), so the two stages overlap whenever the fetch
is deferred:

    host   : prep(0) dispatch(0) prep(1) dispatch(1) fetch(0) prep(2) ...
    device :         [==== batch 0 ====][==== batch 1 ====][== batch 2 ...

:func:`run_pipeline` drives that schedule with a bounded in-flight
window (default 2 = classic double buffering) — now generalized from
one global window to a **per-slot window pool** (ISSUE 7): each item
occupies a set of device slots (``slots_of``; the mesh scheduler maps
these to the devices a plan's shard spans), and the window bound
applies PER SLOT. Items on disjoint slots pipeline independently —
batch k for devices 0-3 never blocks behind batch j in flight on
devices 4-7 — while the memory contract is unchanged per device: the
window drains to ``window - 1`` on every one of an item's slots BEFORE
its prep runs (prep device-places the stacked tables), so each
device's fresh buffers plus its in-flight batches never exceed
``window`` sets of live buffers, no matter how many batches a drain
covers or how the planner packed them.

**Work-stealing drain order**: blocking fetches follow the oldest
in-flight item on a *contended* slot, but whenever the runtime reports
some OTHER in-flight item already complete (``ready``; jax.Array
``is_ready`` — a pure queue peek, no sync), its fetch is stolen first:
result write-back for finished shards proceeds while the contended
shard still executes, instead of head-of-line blocking in global FIFO
order. Items with an empty slot set (host-synchronous passthrough
fits) are never windowed — they hold no device buffers beyond their
own synchronous dispatch.

``window`` must be an int; values below 1 CLAMP to 1 (the documented
floor — a window of 1 is strict ping-pong: at most one batch's buffers
live per slot, pinned by tests/test_serve.py), and a non-int raises
``TypeError`` rather than silently truncating a fractional window.

The pipeline is deliberately thread-free: overlap comes from the JAX
runtime's async dispatch, not host threading, so every user-model
callback (prep's union building mutates no shared state, but models
are not thread-safe in general) runs on the caller's thread.
"""

from __future__ import annotations

import time


def run_pipeline(items, *, prep, dispatch, fetch, window: int = 2,
                 slots_of=None, ready=None):
    """Run each item through prep -> dispatch -> fetch with overlap.

    ``prep(item)`` is the host stage (pack/whiten/pad); ``dispatch
    (prepped)`` enqueues device work and must NOT block on it,
    returning a handle; ``fetch(handle, item)`` blocks on the result.

    ``slots_of(item) -> iterable of hashable slot ids`` declares which
    device slots the item's buffers live on (default: one shared slot,
    the classic single-window behavior); the ``window`` bound applies
    per slot, and an empty slot set opts the item out of windowing
    (host-synchronous work holding no device buffers). ``ready(handle)
    -> bool`` (optional) reports whether a dispatched handle's result
    is already complete without blocking; when provided, fetches steal
    completed handles ahead of the oldest-blocking order.

    Returns ``(results, stats)`` with results in item order and
    ``stats = {"prep_s", "dispatch_s", "wait_s", "wall_s",
    "overlap_efficiency", "stolen_fetches"}`` — ``wait_s`` is the time
    the host spent inside fetch; ``overlap_efficiency`` the fraction
    of the drain wall during which the host was doing useful
    (non-fetch) work, i.e. ``1 - wait_s / wall_s``;
    ``stolen_fetches`` the number of fetches taken out of oldest-first
    order because their result was already complete.
    """
    if isinstance(window, bool) or not isinstance(window, int):
        raise TypeError(f"window must be an int >= 1, got {window!r}")
    window = max(1, window)  # documented clamp: floor at strict ping-pong
    items = list(items)
    results = [None] * len(items)
    # (item index, handle, slots) in dispatch order
    inflight: list[tuple[int, object, tuple]] = []
    load: dict = {}  # slot -> in-flight item count
    prep_s = dispatch_s = wait_s = 0.0
    stolen = 0
    t_start = time.perf_counter()

    def _resolve(j: int) -> None:
        nonlocal wait_s
        i, handle, slots = inflight.pop(j)
        t0 = time.perf_counter()
        results[i] = fetch(handle, items[i])
        wait_s += time.perf_counter() - t0
        for s in slots:
            load[s] -= 1

    def _ready_index():
        if ready is None:
            return None
        return next((j for j, (_i, h, _s) in enumerate(inflight)
                     if ready(h)), None)

    for i, item in enumerate(items):
        slots = tuple(slots_of(item)) if slots_of is not None else (0,)
        # drain this item's slots to window - 1 BEFORE prep: prep
        # device-places the item's stacked tables, so draining any
        # later would let window + 1 batches hold live buffers on a
        # device (the documented bound is ``window``); prep still
        # overlaps every other slot's in-flight work
        while any(load.get(s, 0) >= window for s in slots):
            j = _ready_index()
            if j is None:
                # oldest in-flight item sharing a contended slot
                j = next(k for k, (_i, _h, s2) in enumerate(inflight)
                         if set(s2) & set(slots))
            elif not (set(inflight[j][2]) & set(slots)):
                stolen += 1
            _resolve(j)
        t0 = time.perf_counter()
        prepped = prep(item)
        prep_s += time.perf_counter() - t0
        t0 = time.perf_counter()
        inflight.append((i, dispatch(prepped), slots))
        dispatch_s += time.perf_counter() - t0
        for s in slots:
            load[s] = load.get(s, 0) + 1
    while inflight:
        j = _ready_index()
        if j is not None and j > 0:
            stolen += 1
        _resolve(j if j is not None else 0)
    wall_s = time.perf_counter() - t_start
    return results, {
        "prep_s": round(prep_s, 6),
        "dispatch_s": round(dispatch_s, 6),
        "wait_s": round(wait_s, 6),
        "wall_s": round(wall_s, 6),
        "overlap_efficiency": round(1.0 - wait_s / max(wall_s, 1e-12), 4),
        "stolen_fetches": stolen,
    }
