"""Seed-driven fault injection for the throughput engine (chaos harness).

The serve layer's failure paths (pint_tpu.serve.scheduler: structured
result envelopes, per-request isolation, dispatch retries, quarantine,
the degradation ladder) are worthless untested — and real faults (a
NaN-poisoned table, a dead tunnel mid-dispatch) are rare and
unreproducible. This module makes them cheap and deterministic:

* **Data faults** (chosen per request at submit, before the fingerprint
  is computed): ``nan_toas`` poisons one TOA uncertainty with NaN (the
  whitened chi2 goes non-finite on the very first evaluation — the
  device loop's ``diverged`` carry path), ``zero_weight`` sets every
  uncertainty to +inf (the all-zero-weight degenerate table), and
  ``singular`` duplicates a free JUMP column covering every TOA (an
  exactly singular normal matrix, also collinear with the offset).
* **Infrastructure faults** (chosen per batch in the drain):
  ``prep_exc`` raises :class:`InjectedFault` from the host-prep stage,
  ``device_err`` raises :class:`InjectedDeviceError` from dispatch — the
  scheduler classifies it transient (the ``XlaRuntimeError`` class) and
  retries with backoff; ``device_persistent=True`` makes it survive
  every retry so the passthrough-salvage path runs instead. ``slow``
  sleeps ``slow_s`` inside prep (deadline pressure).

**Determinism**: every decision is a pure function of ``(seed, kind,
key)`` — the key is the scheduler's own submit/batch sequence number —
so a chaos run is reproducible from its seed alone (tools/soak.py
``faults`` axis / ``--chaos``).

**Gating and cost**: off by default. Arm with
:func:`configure`(:class:`FaultPlan`) or the ``PINT_TPU_FAULTS`` env
var (``"nan_toas=0.2,device_err=0.1,seed=7"``). When off — or armed
with an all-zero plan — every hook is a global read (or one float
compare) and returns; the serve hot path stays instrumented
unconditionally, pinned by the fault-idle A/B in BENCH_DETAIL_r10.
"""

from __future__ import annotations

import dataclasses
from pint_tpu import config
import time
import zlib

import numpy as np


class InjectedFault(RuntimeError):
    """Injected host-prep failure (NOT transient: fails the batch)."""


class InjectedDeviceError(RuntimeError):
    """Injected device/tunnel failure (transient XlaRuntimeError class)."""


_RATE_FIELDS = ("nan_toas", "zero_weight", "singular", "prep_exc",
                "device_err", "slow")


@dataclasses.dataclass
class FaultPlan:
    """Injection probabilities (all default 0 = armed but inert)."""

    seed: int = 0
    nan_toas: float = 0.0       # P(one NaN TOA uncertainty) per request
    zero_weight: float = 0.0    # P(all-inf uncertainties) per request
    singular: float = 0.0       # P(duplicate free JUMP column) per request
    prep_exc: float = 0.0       # P(InjectedFault in host prep) per batch
    device_err: float = 0.0     # P(InjectedDeviceError at dispatch) per batch
    device_persistent: bool = False  # device errors survive retries
    slow: float = 0.0           # P(slow prep) per batch
    slow_s: float = 0.01        # injected prep delay [s]

    def __post_init__(self):
        self._inert = all(getattr(self, f) <= 0.0 for f in _RATE_FIELDS)

    # ------------------------------------------------------------------
    def _draw(self, kind: str, key) -> float:
        """Uniform [0,1) draw, a pure function of (seed, kind, key)."""
        h = zlib.crc32(f"{kind}:{key!r}".encode())
        return float(np.random.default_rng((self.seed, h)).random())

    # ------------------------------------------------------------------
    # request-level data/model faults (scheduler submit path)
    # ------------------------------------------------------------------
    def corrupt_request(self, seq: int, toas, model):
        """(toas, model, kind|None): at most ONE fault per request.

        One uniform draw walks the stacked ``nan_toas`` / ``zero_weight``
        / ``singular`` thresholds, so raising one probability never
        reshuffles which requests the others hit.
        """
        if self._inert:
            return toas, model, None
        r = self._draw("request", seq)
        t = self.nan_toas
        if r < t:
            return self._poison_nan(seq, toas), model, "nan_toas"
        t += self.zero_weight
        if r < t:
            err = np.full(len(toas), np.inf)
            return dataclasses.replace(toas, error_us=err), model, \
                "zero_weight"
        t += self.singular
        if r < t:
            return toas, self._singular_model(model), "singular"
        return toas, model, None

    def _poison_nan(self, seq: int, toas):
        err = np.array(toas.error_us, dtype=np.float64)
        idx = int(self._draw("nan_idx", seq) * len(err)) % len(err)
        err[idx] = np.nan
        return dataclasses.replace(toas, error_us=err)

    def _singular_model(self, model):
        """Deep copy with TWO identical free all-TOA JUMP columns."""
        import copy

        from pint_tpu.models.jump import PhaseJump
        from pint_tpu.models.timing_model import TimingModel

        m = copy.deepcopy(model)
        pj = next((c for c in m.components if type(c) is PhaseJump), None)
        if pj is None:
            pj = PhaseJump()
            m = TimingModel(list(m.components) + [pj], name=m.name,
                            header=dict(m.header))
        for _ in range(2):
            pj.add_jump(("mjd", "0", "1000000"), frozen=False)
        return m

    # ------------------------------------------------------------------
    # batch-level infrastructure faults (scheduler drain path)
    # ------------------------------------------------------------------
    def maybe_prep_fault(self, key) -> None:
        """Slow and/or fail the host-prep stage of one batch."""
        if self._inert:
            return
        if self.slow > 0.0 and self._draw("slow", key) < self.slow:
            time.sleep(self.slow_s)
        if self.prep_exc > 0.0 and self._draw("prep", key) < self.prep_exc:
            raise InjectedFault(
                f"injected host-prep failure (batch key {key!r})")

    def maybe_device_error(self, key, attempt: int) -> None:
        """Fail a dispatch; transient unless ``device_persistent``."""
        if self._inert or self.device_err <= 0.0:
            return
        if self._draw("device", key) < self.device_err:
            if attempt == 0 or self.device_persistent:
                raise InjectedDeviceError(
                    "injected UNAVAILABLE: simulated device/tunnel "
                    f"failure (batch key {key!r}, attempt {attempt})")


# ----------------------------------------------------------------------
# process-global gate
# ----------------------------------------------------------------------

_PLAN: FaultPlan | None = None
_ENV_READ = False


def configure(plan: FaultPlan | None) -> None:
    """Arm (or, with None, disarm) fault injection process-wide."""
    global _PLAN, _ENV_READ
    _PLAN = plan
    _ENV_READ = True  # explicit config wins over the env var


def active() -> FaultPlan | None:
    """The armed plan, or None. Reads ``PINT_TPU_FAULTS`` once."""
    global _PLAN, _ENV_READ
    if _PLAN is None and not _ENV_READ:
        _ENV_READ = True
        spec = config.env_str("PINT_TPU_FAULTS")
        if spec:
            _PLAN = plan_from_spec(spec)
    return _PLAN


def plan_from_spec(spec: str) -> FaultPlan:
    """Parse ``"nan_toas=0.2,device_err=0.1,seed=7"`` into a FaultPlan.

    Unknown keys raise (a silently ignored typo would un-arm a chaos
    run); bool fields accept 0/1.
    """
    kw: dict = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        key, _, val = part.partition("=")
        key = key.strip()
        fields = {f.name: f.type for f in dataclasses.fields(FaultPlan)}
        if key not in fields:
            raise ValueError(f"PINT_TPU_FAULTS: unknown key {key!r} "
                             f"(known: {sorted(fields)})")
        if key == "seed":
            kw[key] = int(val)
        elif key == "device_persistent":
            kw[key] = val.strip() not in ("0", "", "false", "False")
        else:
            kw[key] = float(val)
    return FaultPlan(**kw)


def _reset() -> None:
    """Test hook: back to the unarmed, env-unread state."""
    global _PLAN, _ENV_READ
    _PLAN = None
    _ENV_READ = False
