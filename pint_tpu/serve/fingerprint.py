"""Model-structure fingerprints for throughput-scheduler batch grouping.

The scheduler (pint_tpu.serve.scheduler) may place two requests in one
batch — and reuse one compiled program across batches — only when their
traced fit programs are identical up to values that flow through traced
arguments. The canonical key for that is the model's own
``_fn_fingerprint()`` (the audited identity of everything the jitted
entry points close over: component stack + trace facts, frozen /
unfittable parameter values, selectors, backend-relevant header keys —
FREE fittable values are excluded because they ride the traced
``base_dd``). "Same structure, different values" therefore hashes equal
by construction, which is exactly the reuse the issue asks to extend
beyond hand-built homogeneous batches.

Additions on top of ``_fn_fingerprint``:

* **structural state** (DMX MJD windows, IFunc node epochs, glitch
  indices) is pinned explicitly — ``build_union_model`` refuses to
  merge components whose non-parameter state differs, so the group key
  must split them even if a component's ``trace_facts`` hook happens
  not to cover some attribute (belt and braces: equal fingerprint must
  imply the union build succeeds);
* **family** ("wls" | "gls" | "wb"): which fused step a batch of this
  structure runs. Wideband-ness lives on the TOAs and noise bases on
  the model; both are *fingerprint splits* now (ISSUE 8), not
  passthrough routes — a GLS+ECORR group runs the vmapped GLS union
  step, a wideband group the joint TOA+DM step;
* **noise-value invariance**: the batched GLS/wideband steps feed the
  noise hyperparameter VALUES (ECORR weights, power-law amp/gamma)
  through the traced ``NoiseStatics`` operand, so the fingerprint
  treats them like free fittable values (``_fn_fingerprint(
  value_traced=...)``) — "same noise structure, different noise
  values" batches. Shape-static noise facts (harmonic counts,
  chromatic index, selectors, component classes) stay pinned;
* **batchability**: the residue of models the union still cannot
  express (delay-side jumps, multiple ECORR components, free noise
  hyperparameters — or ANY noise/wideband structure under the
  ``PINT_TPU_BATCH_NOISE=0`` kill switch, which restores the PR-5
  passthrough routing) gets ``batchable=False`` with a stable
  snake_case reason token, routed through the per-request passthrough
  path and counted under ``serve.passthrough.reason.<token>``.
"""

from __future__ import annotations

from pint_tpu import config

def noise_batch_enabled() -> bool:
    """Batchable-frontier gate (read per call so tests can flip it):
    ``PINT_TPU_BATCH_NOISE=0`` restores the PR-5 routing in which every
    correlated-noise / wideband request is a per-request passthrough."""
    return config.env_on("PINT_TPU_BATCH_NOISE")


def _structural_state(model) -> tuple:
    """Non-parameter component state that must match across a batch —
    ``parallel.batch._structural_state`` per component, so the group key
    and the union builder can never disagree about what "structural"
    means (a new DMX-like attribute added there splits groups here)."""
    from pint_tpu.parallel.batch import _structural_state as _component

    return tuple((type(c).__name__, _component(c))
                 for c in model.components)


def family(model, toas=None) -> str:
    """Which fused step serves this structure: ``"wb"`` (wideband TOAs
    — the joint TOA+DM step, with or without noise bases), ``"gls"``
    (correlated-noise bases on a narrowband table), ``"wls"``."""
    if toas is not None and getattr(toas, "is_wideband", lambda: False)():
        return "wb"
    if any(getattr(c, "is_noise_basis", False) for c in model.components):
        return "gls"
    return "wls"


def _noise_value_params(model, wideband: bool = False) -> frozenset:
    """Names of noise hyperparameters whose VALUES ride the traced
    ``NoiseStatics`` operand of the batched GLS/wideband steps — the
    harmonic-count parameter (shape-static) stays pinned.

    With EFAC/EQUAD tracing on (``pint_tpu.fitting.gls_step
    .trace_efac_enabled``, ISSUE 10 satellite), the white-noise scale
    values join too: the steps read the per-TOA scaled sigmas from the
    statics, so "same selectors, different EFAC/EQUAD values" must
    hash equal — mixed-EFAC traffic then shares batches AND compiled
    programs. Selectors stay pinned (they are structure), and models
    whose scaling cannot ride the traced vector (several chained
    noise-scale components — see ``sigma_traceable``) keep their
    values pinned.

    With DMEFAC/DMEQUAD tracing on (``trace_dmefac_enabled``, ISSUE 14
    satellite — the PR-10 residue), wideband DM-error scaling values
    join the traced set the same way: the wideband step reads per-TOA
    scaled DM sigmas from ``NoiseStatics.dm_sigma``, so mixed-DMEFAC
    wideband catalog members hash equal and share one compiled
    program. ``wideband=False`` (narrowband families) keeps them out:
    a narrowband step never reads DM errors, and an inert component's
    values may as well stay pinned."""
    from pint_tpu.fitting.gls_step import (dm_sigma_traceable,
                                           sigma_traceable,
                                           trace_dmefac_enabled,
                                           trace_efac_enabled)

    out = set()
    trace_scale = trace_efac_enabled() and sigma_traceable(model)
    trace_dm = (wideband and trace_dmefac_enabled()
                and dm_sigma_traceable(model))
    for c in model.components:
        if getattr(c, "is_noise_basis", False):
            keep = getattr(c, "_c_name", None)
            out.update(p.name for p in c.params
                       if p.is_numeric and p.name != keep)
        elif trace_scale and getattr(c, "is_noise_scale", False):
            out.update(p.name for p in c.params if p.is_numeric)
        elif trace_dm and hasattr(c, "scale_dm_sigma"):
            out.update(p.name for p in c.params if p.is_numeric)
    return frozenset(out)


def batchable(model, toas=None) -> tuple[bool, str]:
    """(ok, reason): can this fit be a vmapped union batch member?

    ``reason`` is a stable snake_case token (it becomes the
    ``serve.passthrough.reason.<token>`` counter suffix and the drain
    record's breakdown key). The rejections mirror
    ``parallel.batch.build_union_model``; wideband-ness lives on the
    TOAs (``toas.is_wideband()`` — the same dispatch ``Fitter.auto``
    uses), so pass the request's table. A fit failing here is served
    through the scheduler's passthrough path (a normal per-request
    fit), never an error.
    """
    from pint_tpu.models.jump import PhaseJump

    fam = family(model, toas)
    for c in model.components:
        if isinstance(c, PhaseJump) and type(c) is not PhaseJump:
            return False, "delay_side_jump"
    if fam == "wls":
        return True, ""
    if not noise_batch_enabled():
        return False, ("wideband_kill_switch" if fam == "wb"
                       else "noise_kill_switch")
    if fam == "wb":
        import numpy as np

        errs = np.asarray(toas.get_dm_errors())
        if not np.all(np.isfinite(errs) & (errs > 0)):
            # the joint solve would be NaN; the passthrough fitter's
            # constructor raises the actionable error FAIL-FAST
            # (attempts=1), instead of a batch prep failure + salvage
            return False, "invalid_dm_errors"
    n_ecorr = sum(hasattr(c, "epoch_indices") for c in model.components)
    if n_ecorr > 1:
        return False, "multiple_ecorr"
    for c in model.components:
        if getattr(c, "is_noise_basis", False):
            if any(not p.frozen for p in c.params if p.is_numeric):
                # an unfrozen hyperparameter is read host-side by the
                # standalone fitters' basis builders mid-fit; the union
                # statics are built once at batch prep
                return False, "free_noise_param"
    return True, ""


def structure_fingerprint(model, toas=None) -> tuple:
    """Hashable batch-group identity of a fit's structure.

    ``(batchable, family, fn_fingerprint, structural_state)`` — equal
    fingerprints guarantee (a) ``build_union_model`` accepts the set,
    and (b) same-shape batches trace to one compiled loop program (the
    union's own ``_fn_fingerprint`` is determined by the members', with
    noise values normalized on both sides). Pass ``toas`` so wideband
    tables split into their own ("wb") groups.

    The structure key deliberately carries NO placement state — device
    count, mesh layout, shard width are properties of where a plan
    runs, not of what a model is (a request's fingerprint must not
    change because the device pool resized between submit and drain) —
    and no data-dependent shapes: the TOA bucket and the ECORR basis
    bucket join at the PLAN key instead (:func:`plan_key`).
    """
    ok, _reason = batchable(model, toas)
    fam = family(model, toas)
    traced = (_noise_value_params(model, wideband=fam == "wb")
              if fam != "wls" else frozenset())
    return (ok, fam, model._fn_fingerprint(value_traced=traced),
            _structural_state(model))


def basis_bucket(model, toas) -> int:
    """The request's pow-2 ECORR basis bucket (0 = no ECORR epochs).

    Data-dependent like the TOA bucket — the epoch count comes from
    quantizing THIS table — so it joins the plan key, not the structure
    fingerprint. Batch prep pads every member's epoch columns to this
    bucket with exactly-inert columns
    (:func:`pint_tpu.bucketing.pad_basis_cols`).
    """
    from pint_tpu.bucketing import basis_bucket_size

    for c in model.components:
        if hasattr(c, "epoch_indices"):
            _idx, phi = c.epoch_indices(toas)
            return basis_bucket_size(len(phi))
    return 0


def plan_key(fp: tuple, toa_bucket: int, hyper: tuple,
             devices: int, basis_bucket: int = 0) -> tuple:
    """Batch-PLAN grouping key: structure + shapes + placement.

    Two requests may share one program launch iff their plan keys are
    equal: same :func:`structure_fingerprint`, same TOA bucket (the
    padded shape), same ECORR basis bucket (the padded epoch-column
    shape, ISSUE 8 — new member shape next to the TOA bucket), same fit
    hyperparameters (traced but part of the request contract), and —
    with mesh-sharded serving (ISSUE 7) — the same device count,
    because a formed batch's compiled program is partitioned for a
    specific mesh. Placement and shapes live HERE and not in
    :func:`structure_fingerprint` (see there).
    """
    return (fp, toa_bucket, hyper, int(devices), int(basis_bucket))


def canonical_repr(obj) -> str:
    """Process-independent textual form of a fingerprint-shaped value.

    ``repr()`` alone is NOT stable across processes for sets and dicts:
    string hash randomization (PYTHONHASHSEED) permutes their iteration
    order, so two workers would digest the same fingerprint to
    different program keys. Sets/frozensets are rendered sorted by
    their elements' canonical forms, dicts sorted by key; everything
    else falls through to ``repr`` (tuples of strings/numbers — the
    shape ``_fn_fingerprint`` actually produces — are already stable).
    The program supply chain (:mod:`pint_tpu.programs.key`) digests
    this form, so it is part of the on-disk artifact contract: changing
    it invalidates every persisted program key.
    """
    if isinstance(obj, (set, frozenset)):
        return "{" + ",".join(sorted(canonical_repr(x) for x in obj)) + "}"
    if isinstance(obj, dict):
        return "{" + ",".join(
            f"{canonical_repr(k)}:{canonical_repr(v)}"
            for k, v in sorted(obj.items(),
                               key=lambda kv: canonical_repr(kv[0]))) + "}"
    if isinstance(obj, tuple):
        return "(" + ",".join(canonical_repr(x) for x in obj) + ",)"
    if isinstance(obj, list):
        return "[" + ",".join(canonical_repr(x) for x in obj) + "]"
    return repr(obj)


def short_id(fp: tuple) -> str:
    """Stable 8-hex-digit label of a fingerprint for telemetry/records
    (content digest over :func:`canonical_repr`, not ``hash()`` — that
    is salted per process, and plain ``repr`` is set-order unstable)."""
    import hashlib

    return hashlib.sha1(canonical_repr(fp).encode()).hexdigest()[:8]
