"""Model-structure fingerprints for throughput-scheduler batch grouping.

The scheduler (pint_tpu.serve.scheduler) may place two requests in one
batch — and reuse one compiled program across batches — only when their
traced fit programs are identical up to values that flow through traced
arguments. The canonical key for that is the model's own
``_fn_fingerprint()`` (the audited identity of everything the jitted
entry points close over: component stack + trace facts, frozen /
unfittable parameter values, selectors, backend-relevant header keys —
FREE fittable values are excluded because they ride the traced
``base_dd``). "Same structure, different parameter values" therefore
hashes equal by construction, which is exactly the reuse the issue
asks to extend beyond hand-built homogeneous batches.

Two additions on top of ``_fn_fingerprint``:

* **structural state** (DMX MJD windows, IFunc node epochs, glitch
  indices) is pinned explicitly — ``build_union_model`` refuses to
  merge components whose non-parameter state differs, so the group key
  must split them even if a component's ``trace_facts`` hook happens
  not to cover some attribute (belt and braces: equal fingerprint must
  imply the union build succeeds);
* **batchability**: models the vmapped WLS union cannot express at all
  (correlated-noise bases, delay-side jumps, wideband tables) get
  ``batchable=False`` and are routed through the per-request
  passthrough path instead of a batch.
"""

from __future__ import annotations


def _structural_state(model) -> tuple:
    """Non-parameter component state that must match across a batch —
    ``parallel.batch._structural_state`` per component, so the group key
    and the union builder can never disagree about what "structural"
    means (a new DMX-like attribute added there splits groups here)."""
    from pint_tpu.parallel.batch import _structural_state as _component

    return tuple((type(c).__name__, _component(c))
                 for c in model.components)


def batchable(model, toas=None) -> tuple[bool, str]:
    """(ok, reason): can this fit be a vmapped WLS batch member?

    The model rejections mirror ``parallel.batch.build_union_model``;
    wideband-ness lives on the TOAs (``toas.is_wideband()`` — the same
    dispatch ``Fitter.auto`` uses), so pass the request's table to
    route wideband fits too. A fit failing here is served through the
    scheduler's passthrough path (a normal per-request fit), never an
    error.
    """
    from pint_tpu.models.jump import PhaseJump

    if toas is not None and getattr(toas, "is_wideband", lambda: False)():
        return False, "wideband TOAs"
    for c in model.components:
        if getattr(c, "is_noise_basis", False):
            return False, f"correlated-noise basis {type(c).__name__}"
        if isinstance(c, PhaseJump) and type(c) is not PhaseJump:
            return False, f"delay-side jump {type(c).__name__}"
    return True, ""


def structure_fingerprint(model, toas=None) -> tuple:
    """Hashable batch-group identity of a fit's structure.

    Equal fingerprints guarantee (a) ``build_union_model`` accepts the
    set, and (b) same-shape batches trace to one compiled loop program
    (the union's own ``_fn_fingerprint`` is determined by the members').
    Pass ``toas`` so wideband tables get a passthrough fingerprint.

    The structure key deliberately carries NO placement state — device
    count, mesh layout, shard width are properties of where a plan
    runs, not of what a model is (a request's fingerprint must not
    change because the device pool resized between submit and drain).
    Placement joins at the PLAN key instead (:func:`plan_key`).
    """
    ok, _reason = batchable(model, toas)
    return (ok, model._fn_fingerprint(), _structural_state(model))


def plan_key(fp: tuple, toa_bucket: int, hyper: tuple,
             devices: int) -> tuple:
    """Batch-PLAN grouping key: structure + shapes + placement.

    Two requests may share one program launch iff their plan keys are
    equal: same :func:`structure_fingerprint`, same TOA bucket (the
    padded shape), same fit hyperparameters (traced but part of the
    request contract), and — new with mesh-sharded serving (ISSUE 7) —
    the same device count, because a formed batch's compiled program is
    partitioned for a specific mesh: a batch planned for 8 devices and
    one planned for 1 are different programs even at identical
    structure and shapes. Device count lives HERE and not in
    :func:`structure_fingerprint` (see there).
    """
    return (fp, toa_bucket, hyper, int(devices))


def short_id(fp: tuple) -> str:
    """Stable 8-hex-digit label of a fingerprint for telemetry/records
    (content digest, not ``hash()`` — that is salted per process)."""
    import hashlib

    return hashlib.sha1(repr(fp).encode()).hexdigest()[:8]
