"""Clock-correction files: tempo and tempo2 formats.

Reference equivalent: ``pint.observatory.clock_file.ClockFile``
(src/pint/observatory/clock_file.py). A clock file is an irregular table
(MJD, correction) mapping a site clock toward UTC/TT; chains compose, e.g.
ao2gps -> gps2utc -> utc2tai -> tai2tt(BIPM). Parsing and evaluation are
host-side numpy (done once at TOA load; results live on the TOA table).

No clock data ships with the framework (offline); these parsers exist so
users can drop in the IPTA pulsar-clock-corrections repository files.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field

import numpy as np

log = logging.getLogger(__name__)


@dataclass
class ClockFile:
    """(mjd, clock_s) table; linear interpolation, configurable edge policy."""

    mjd: np.ndarray
    clock_s: np.ndarray
    name: str = ""
    header: str = ""

    def evaluate(self, mjd: np.ndarray, *, limits: str = "warn") -> np.ndarray:
        mjd = np.asarray(mjd, np.float64)
        if self.mjd.size == 0:
            return np.zeros_like(mjd)
        below = mjd < self.mjd[0]
        above = mjd > self.mjd[-1]
        if (below.any() or above.any()):
            msg = (
                f"clock file {self.name or '<unnamed>'} spans "
                f"[{self.mjd[0]:.1f}, {self.mjd[-1]:.1f}] but TOAs reach "
                f"[{mjd.min():.1f}, {mjd.max():.1f}]"
            )
            if limits == "error":
                raise ValueError(msg)
            log.warning("%s; extrapolating with edge values", msg)
        return np.interp(mjd, self.mjd, self.clock_s)

    @classmethod
    def read_tempo2(cls, path: str) -> "ClockFile":
        """tempo2 .clk: '# <from> <to> ...' header then 'mjd clock[ flags]' rows."""
        mjds, corrs = [], []
        header = ""
        with open(path) as f:
            for line in f:
                line = line.rstrip()
                if not line:
                    continue
                if line.startswith("#"):
                    if not header:
                        header = line.lstrip("# ")
                    continue
                parts = line.split()
                if len(parts) >= 2:
                    try:
                        mjds.append(float(parts[0]))
                        corrs.append(float(parts[1]))
                    except ValueError:
                        continue
        return cls(np.asarray(mjds), np.asarray(corrs), name=path, header=header)

    @classmethod
    def read_tempo(cls, path: str, obscode: str | None = None) -> "ClockFile":
        """tempo time.dat: fixed-ish columns 'mjd offset1 offset2 obscode ...'.

        Corrections are in microseconds (tempo convention); the applied
        correction is (offset2 - offset1) us, filtered by site code when
        obscode is given.
        """
        mjds, corrs = [], []
        with open(path) as f:
            for line in f:
                ls = line.strip()
                if not ls or ls.startswith(("#", "MJD", "=")):
                    continue
                parts = ls.split()
                try:
                    mjd = float(parts[0])
                    off1 = float(parts[1]) if len(parts) > 1 else 0.0
                    off2 = float(parts[2]) if len(parts) > 2 else 0.0
                except (ValueError, IndexError):
                    continue
                code = parts[3] if len(parts) > 3 else ""
                if obscode is not None and code and code.lower() != obscode.lower():
                    continue
                mjds.append(mjd)
                corrs.append((off2 - off1) * 1e-6)
        return cls(np.asarray(mjds), np.asarray(corrs), name=path)

    def write_tempo2(self, path: str, hdrline: str | None = None) -> None:
        with open(path, "w") as f:
            f.write(f"# {hdrline or self.header or 'UTC UTC(pint_tpu)'}\n")
            for m, c in zip(self.mjd, self.clock_s):
                f.write(f"{m:.6f} {c:.12e}\n")


def merge_clock_files(files: list[ClockFile]) -> ClockFile:
    """Sum a chain onto the union grid (for export/inspection)."""
    grid = np.unique(np.concatenate([f.mjd for f in files if f.mjd.size]))
    total = np.zeros_like(grid)
    for f in files:
        total = total + f.evaluate(grid, limits="warn")
    return ClockFile(grid, total, name="+".join(f.name for f in files))
