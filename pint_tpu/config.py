"""Runtime configuration: the central ``PINT_TPU_*`` knob registry.

Reference equivalents: ``pint.config`` (runtimefile locator for
src/pint/data/runtime) and the reference's scattered environment
switches (clock-file policies etc.).

Every environment knob the tree reads is DECLARED here — name, default,
kind, one-line doc — and read through the typed helpers below
(:func:`env_str` / :func:`env_int` / :func:`env_float` / :func:`env_on`
/ :func:`env_raw`). The static-analysis pass (``python -m
tools.analyze``, rule ``env-knob-registry``) enforces both directions:
a direct ``os.environ`` read of a ``PINT_TPU_*`` name outside this
module is a finding, and so is a helper read (or an ``os.environ``
write) naming a knob that is not declared. ``python -m tools.analyze
--knobs`` prints the full table; ``docs/KNOBS.md`` is generated from it
(never hand-edited — tests pin the regeneration).

Declarations are PURE LITERALS on purpose: the analyzer extracts the
registry by parsing this file's AST (it must run without importing jax,
which ``import pint_tpu`` pulls in), so ``declare(...)`` calls may not
use computed names, defaults or docs.

Knob kinds:

* ``str``      — string value; empty/unset resolves to the default.
* ``int``/``float`` — parsed number; empty/unset or unparseable
  resolves to the default (a typo'd knob must not crash a service).
* ``bool``     — :func:`env_on` semantics: unset/empty -> default,
  the literal string ``"0"`` -> False, anything else -> True. This is
  the tree's kill-switch convention (``PINT_TPU_X=0`` disables).
* ``tristate`` — raw string compared at the call site (e.g.
  ``PINT_TPU_TELEMETRY``: "0" hard-off, "1" on-at-import, unset
  defers); read through :func:`env_raw`.

``scope`` marks where a knob is read: ``lib`` (pint_tpu), ``bench``
(bench.py / scale_proof.py / tpu_evidence.py), ``tools``
(tools/soak.py), ``tests`` (tests/ only — outside the analyzer's scan,
declared for the generated docs), ``reserved`` (named by ROADMAP /
CHANGES for a future subsystem; declared so the kill-switch inventory
check closes before the code lands).
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass(frozen=True)
class Knob:
    name: str
    default: object
    kind: str  # "str" | "int" | "float" | "bool" | "tristate"
    doc: str
    scope: str = "lib"


#: name -> Knob; populated by the literal declare() calls below.
KNOBS: dict[str, Knob] = {}


def declare(name: str, default, kind: str, doc: str,
            scope: str = "lib") -> None:
    """Register one knob. Arguments must be literals (see module doc)."""
    if name in KNOBS:
        raise ValueError(f"duplicate knob declaration {name}")
    if kind not in ("str", "int", "float", "bool", "tristate"):
        raise ValueError(f"unknown knob kind {kind!r} for {name}")
    KNOBS[name] = Knob(name, default, kind, doc, scope)


def knob(name: str) -> Knob:
    """The declaration of ``name``; KeyError names the registry rule so
    an undeclared read fails loudly at runtime too, not only in CI."""
    try:
        return KNOBS[name]
    except KeyError:
        raise KeyError(
            f"{name} is not declared in the pint_tpu.config knob "
            "registry (jaxlint rule env-knob-registry)") from None


def env_raw(name: str) -> str | None:
    """The raw environment value of a DECLARED knob (None when unset).

    For ``tristate`` knobs whose call sites compare literal strings;
    every other kind has a typed helper below.
    """
    knob(name)
    return os.environ.get(name)


def env_str(name: str) -> str | None:
    """String knob: the env value, or the declared default when unset
    or empty (the tree's ``os.environ.get(X) or None`` convention)."""
    k = knob(name)
    raw = os.environ.get(name)
    if raw:
        return raw
    return k.default


def env_int(name: str) -> int:
    """Integer knob; unset/empty/unparseable -> declared default."""
    k = knob(name)
    raw = os.environ.get(name)
    if raw:
        try:
            return int(raw)
        except ValueError:
            pass
    return int(k.default)


def env_float(name: str) -> float:
    """Float knob; unset/empty/unparseable -> declared default."""
    k = knob(name)
    raw = os.environ.get(name)
    if raw:
        try:
            return float(raw)
        except ValueError:
            pass
    return float(k.default)


def env_on(name: str) -> bool:
    """Boolean knob, kill-switch convention: unset or empty -> the
    declared default; the literal ``"0"`` -> False; any other value ->
    True. (``PINT_TPU_FLEET=0`` disables, ``PINT_TPU_FLEET=`` defers.)
    """
    k = knob(name)
    raw = os.environ.get(name)
    if raw is None or raw == "":
        return bool(k.default)
    return raw != "0"


# --- library knobs (pint_tpu/) --------------------------------------
declare("PINT_TPU_EPHEM_DIR", None, "str",
        "Directory searched for deNNN.bsp solar-system ephemeris "
        "kernels before the bundled/analytic fallbacks.")
declare("PINT_TPU_STRICT_EPHEM", False, "bool",
        "Refuse the analytic-ephemeris fallback: a missing .bsp kernel "
        "raises instead of degrading precision silently.")
declare("PINT_TPU_CLOCK_DIR", None, "str",
        "Directory of tempo/tempo2 clock files auto-registered at "
        "first use.")
declare("PINT_TPU_CACHE_DIR", None, "str",
        "TOA pickle-cache location (defaults beside the .tim file).")
declare("PINT_TPU_DEVICE_LOOP", True, "bool",
        "Kill switch for the fused one-launch/one-fetch device fit "
        "loop; 0 restores the host-driven downhill loop (the parity "
        "oracle).")
declare("PINT_TPU_FIT_BUCKETING", True, "bool",
        "Kill switch for pow-2 TOA-count bucketing (compiled-program "
        "reuse across nearby sizes); 0 compiles per exact shape.")
declare("PINT_TPU_BUCKET_MAX", 16384, "int",
        "Bucketing ceiling: TOA counts above it pad to multiples "
        "instead of the next power of two.")
declare("PINT_TPU_HYBRID_PIPELINE", "", "tristate",
        "Hybrid CPU->accelerator fitter stage-overlap: 1 forces the "
        "pipelined driver on (how CPU-only parity tests exercise it), "
        "0 forces it off, unset auto-enables on real accelerators.")
declare("PINT_TPU_TRACE_EFAC", True, "bool",
        "Kill switch for EFAC/EQUAD values riding the traced "
        "NoiseStatics.sigma (mixed-EFAC traffic sharing one compiled "
        "program); 0 restores the PR-8 pinned-constant routing.")
declare("PINT_TPU_TRACE_DMEFAC", True, "bool",
        "Kill switch for DMEFAC/DMEQUAD values riding the traced "
        "NoiseStatics.dm_sigma (wideband analogue of "
        "PINT_TPU_TRACE_EFAC); 0 restores the pinned-constant path.")
declare("PINT_TPU_BATCH_NOISE", True, "bool",
        "Kill switch for the batchable noise/wideband frontier; 0 "
        "restores the PR-5 routing (every correlated-noise/wideband "
        "request a per-request passthrough).")
declare("PINT_TPU_F64", True, "bool",
        "Reserved (ROADMAP item 5): force-f64 kill switch for the "
        "mixed-precision fit kernels; every kernel is f64 today.",
        scope="reserved")
declare("PINT_TPU_SESSION_BYTES", 67108864, "int",
        "Session-cache device-byte budget; admission beyond it evicts "
        "LRU unpinned states, then raises SessionCacheFull.")
declare("PINT_TPU_SESSION_MAX_APPENDS", 16, "int",
        "Append-count drift gate: a session full-refits (through the "
        "one populate code path) after this many rank-k updates.")
declare("PINT_TPU_SESSION_DRIFT_SIGMA", 1.0, "float",
        "Cumulative parameter-motion drift gate in posterior sigmas "
        "before a session's incremental state forces a full refit.")
declare("PINT_TPU_SESSION_BATCH", True, "bool",
        "Kill switch for vmapped multi-session append batching; 0 "
        "restores one rank-k launch per session (the bitwise solo "
        "path).")
declare("PINT_TPU_SESSION_BATCH_MAX", 64, "int",
        "Max member width of one batched session launch; a drain's "
        "same-structure append group chunks beyond it.")
declare("PINT_TPU_SESSION_GLS", True, "bool",
        "Gate for the GLS Schur rank-k incremental session path; 0 "
        "restores the stateless full-refit-per-append behavior for "
        "correlated-noise sessions.")
declare("PINT_TPU_FAULTS", None, "str",
        "Seed-driven fault-injection plan, e.g. "
        "'nan_toas=0.2,seed=7' (tools/soak.py chaos gates); unset = "
        "injector inert.")
declare("PINT_TPU_READ_PATH", True, "bool",
        "Kill switch for the on-device Chebyshev read path; 0 serves "
        "predictions through host Polycos (the parity oracle).")
declare("PINT_TPU_READ_SEGMENT_MIN", 60.0, "float",
        "Chebyshev segment span in minutes for the read path's "
        "generated windows.")
declare("PINT_TPU_READ_WINDOW_SEGMENTS", 24, "int",
        "Segments per generated read-path cache window.")
declare("PINT_TPU_READ_NCOEFF", 12, "int",
        "Chebyshev coefficients per read-path segment.")
declare("PINT_TPU_READ_CACHE_BYTES", 33554432, "int",
        "Read-path segment-cache byte budget (LRU beyond it).")
declare("PINT_TPU_READ_MAX_WINDOWS", 16, "int",
        "Cap on fresh cache windows one predict request may generate; "
        "rows beyond it are served dense (counted, never truncated).")
declare("PINT_TPU_FLEET", True, "bool",
        "Kill switch for the fleet tier; 0 (or one host) degenerates "
        "to the bitwise single-host scheduler path.")
declare("PINT_TPU_FLEET_PROCESSES", 1, "int",
        "Fleet process count; >1 arms jax.distributed.initialize in "
        "workers.")
declare("PINT_TPU_FLEET_PROCESS_ID", 0, "int",
        "This worker's process index for jax.distributed.initialize.")
declare("PINT_TPU_FLEET_COORD", "127.0.0.1:9733", "str",
        "jax.distributed coordinator address for fleet workers.")
declare("PINT_TPU_FLEET_JOURNAL_BYTES", 67108864, "int",
        "Fleet append-journal byte budget; over it, committed appends "
        "snapshot-truncate into the base table (replay cost only).")
declare("PINT_TPU_FLEET_OP_DEADLINE_S", 60.0, "float",
        "Default per-operation fleet transport wire deadline [s]; a "
        "miss raises HostSuspect into the suspicion ladder.")
declare("PINT_TPU_FLEET_HEARTBEAT_S", 5.0, "float",
        "Fleet heartbeat ping deadline [s] (suspicion-ladder cadence).")
declare("PINT_TPU_CATALOG_SLICE_S", 5.0, "float",
        "Device-budget per catalog long-job slice [s] between which "
        "reads and small fits drain; always >= 1 iteration.")
declare("PINT_TPU_SCRIPT_INIT_TIMEOUT", 60, "int",
        "CLI scripts' backend-init watchdog [s] (tunnel-hang guard).")
declare("PINT_TPU_TELEMETRY", "", "tristate",
        "Telemetry master gate: 0 hard kill switch (overrides entry "
        "points), 1 on at import for plain library use, unset defers "
        "to telemetry.configure().")
declare("PINT_TPU_TELEMETRY_PATH", None, "str",
        "Telemetry JSON-lines artifact path (appended to); unset "
        "keeps records in-memory only (rollup still works).")
declare("PINT_TPU_TELEMETRY_LOAD1", 1.5, "float",
        "1-min load-average threshold above which a host sample is "
        "flagged polluted.")
declare("PINT_TPU_TELEMETRY_LOG", False, "bool",
        "Mirror span begin/end to the pint_tpu.telemetry logger.")
declare("PINT_TPU_TELEMETRY_MAX_MB", 16.0, "float",
        "Telemetry artifact rotation threshold [MB].")
declare("PINT_TPU_TRACE_SAMPLE", 1.0, "float",
        "Distributed-trace root sampling rate in [0,1]; thinned "
        "deterministically (error accumulator, no RNG). An unsampled "
        "request is traceless for its whole life.")
declare("PINT_TPU_FLEET_METRICS_DEADLINE_S", 5.0, "float",
        "Wire deadline [s] for the fleet 'metrics' snapshot op (the "
        "live plane must answer fast even when the host is busy).")
declare("PINT_TPU_SLO_READ_S", 0.05, "float",
        "Latency objective [s] for read-class (predict) requests; "
        "served latency above it burns the read SLO counter.")
declare("PINT_TPU_SLO_FIT_S", 30.0, "float",
        "Latency objective [s] for sessionless fit requests "
        "(submit-to-envelope wall).")
declare("PINT_TPU_SLO_SESSION_S", 30.0, "float",
        "Latency objective [s] for sessionful fit requests "
        "(resolve/pin + fit wall).")
declare("PINT_TPU_SLO_LONGJOB_S", 3600.0, "float",
        "Latency objective [s] for catalog long jobs, submit to "
        "terminal state.")
declare("PINT_TPU_PROFILE_DIR", None, "str",
        "XLA-profiler output directory; unset = profiling off.")
declare("PINT_TPU_FLIGHT_RECORDER", True, "bool",
        "Kill switch for the in-carry flight-recorder trace ring; 0 "
        "removes the ring from the loop carry (different program).")
declare("PINT_TPU_TRACE_LEN", 64, "int",
        "Flight-recorder ring capacity in entries (floor 4).")
declare("PINT_TPU_PROGRAM_CACHE_DIR", None, "str",
        "Root of the per-host persistent program store (XLA compile "
        "cache + AOT fit-program artifacts + manifest); unset = supply "
        "chain off, bitwise today's in-process compile behavior.")
declare("PINT_TPU_PROGRAM_AOT", True, "bool",
        "Kill switch for the AOT executable serialize/adopt rung of "
        "the program store; 0 keeps only the persistent XLA compile "
        "cache (for hosts where executable reload misbehaves — see "
        "docs/COMPILE_CACHE.md round-3 history).")
declare("PINT_TPU_PROGRAM_SHIP", True, "bool",
        "Fleet join prewarm gate: ship popularity-ranked warm programs "
        "and replica summaries to a joining host before it takes "
        "traffic; 0 restores the instant-routable join.")
declare("PINT_TPU_PREWARM_TOP_K", 8, "int",
        "Adopt-set size cap for the fleet join prewarm: the top-K "
        "most-popular warm structures assigned to the joining host.")

# --- bench.py / scale_proof.py / tpu_evidence.py knobs ---------------
declare("PINT_TPU_BENCH_MODE", "gls", "str",
        "bench.py mode: gls | fit_throughput | throughput_mixed | "
        "throughput_mesh | throughput_incremental | session_fleet | "
        "read_mixed | fleet | pta | catalog.", scope="bench")
declare("PINT_TPU_BENCH_N", 100000, "int",
        "bench.py TOA count for the headline fit.", scope="bench")
declare("PINT_TPU_BENCH_REPS", 5, "int",
        "bench.py repetitions (mode-specific floors apply).",
        scope="bench")
declare("PINT_TPU_BENCH_FITS", 64, "int",
        "Request count for the throughput bench modes.", scope="bench")
declare("PINT_TPU_BENCH_PSRS", 16, "int",
        "Pulsar count for the PTA bench mode.", scope="bench")
declare("PINT_TPU_BENCH_PTA_N", 40000, "int",
        "TOA count for the rider PTA record in default-mode runs.",
        scope="bench")
declare("PINT_TPU_BENCH_MESH_DEVICES", 8, "int",
        "Virtual device count armed for the throughput_mesh mode.",
        scope="bench")
declare("PINT_TPU_BENCH_READ_N", 100000, "int",
        "TOA count of the contending fit in the read_mixed mode.",
        scope="bench")
declare("PINT_TPU_BENCH_READ_Q", 256, "int",
        "Queries per predict request in the read_mixed mode.",
        scope="bench")
declare("PINT_TPU_BENCH_READ_DEVICES", 2, "int",
        "Virtual device count armed for the read_mixed mode.",
        scope="bench")
declare("PINT_TPU_BENCH_INIT_TIMEOUT", 300, "int",
        "bench.py backend-init watchdog [s].", scope="bench")
declare("PINT_TPU_BENCH_TOTAL_TIMEOUT", 1200, "int",
        "bench.py whole-run watchdog [s], CPU fallback included.",
        scope="bench")
declare("PINT_TPU_BENCH_CHILD", False, "bool",
        "Internal: set in bench.py children so the driver/child split "
        "recurses exactly once.", scope="bench")
declare("PINT_TPU_BENCH_SMOKE", False, "bool",
        "Internal: set by bench --smoke children (tiny CI workload).",
        scope="bench")
declare("PINT_TPU_BENCH_COLDSTART", False, "bool",
        "Internal: set by bench --cold-start children (process-start -> "
        "first-fit measurement against a shared program store).",
        scope="bench")
declare("PINT_TPU_BENCH_DETAIL", None, "str",
        "Path for the full bench record (stdout carries only the "
        "short line).", scope="bench")
declare("PINT_TPU_BENCH_PROFILE", None, "str",
        "Legacy alias of PINT_TPU_PROFILE_DIR for bench runs.",
        scope="bench")
declare("PINT_TPU_MESH_DETAIL", None, "str",
        "Path for the full throughput_mesh record.", scope="bench")
declare("PINT_TPU_FLEET_DETAIL", None, "str",
        "Path for the full fleet-mode record.", scope="bench")
declare("PINT_TPU_SCALE_PSRS", 68, "int",
        "scale_proof.py catalog pulsar count.", scope="bench")
declare("PINT_TPU_SCALE_N_PER_PSR", 8824, "int",
        "scale_proof.py TOAs per catalog pulsar.", scope="bench")
declare("PINT_TPU_SCALE_N", 600000, "int",
        "scale_proof.py single-fit TOA count (gls600k/sharded8).",
        scope="bench")
declare("PINT_TPU_SCALE_BATCH_N", 20000, "int",
        "scale_proof.py per-member TOA count for batched_het.",
        scope="bench")
declare("PINT_TPU_EVIDENCE_OUT", "TPU_EVIDENCE_r05.json", "str",
        "tpu_evidence.py output artifact path.", scope="bench")
declare("PINT_TPU_EVIDENCE_N", 100000, "int",
        "tpu_evidence.py hybrid-fit TOA count.", scope="bench")

# --- tools/soak.py knobs ---------------------------------------------
declare("PINT_TPU_SOAK_REPRO_DIR", ".", "str",
        "Directory for per-trial soak repro artifacts on failure.",
        scope="tools")

declare("PINT_TPU_JAX_CACHE", True, "bool",
        "Persistent XLA compile cache for the test suite and the bench "
        "--smoke child (pint_tpu.compile_cache); 0 opts out on hosts "
        "where the cache itself misbehaves.")
declare("PINT_TPU_JAX_CACHE_DIR", None, "str",
        "Override location of the persistent XLA compile cache "
        "(default: <repo>/.jax_cache/<host-tag>).")

# --- tests-only knobs (declared for the generated docs; tests/ is
# outside the analyzer's scan scope) ---------------------------------
declare("PINT_TPU_RUN_TPU_TESTS", False, "bool",
        "Keep the accelerator platform visible to the test suite "
        "(tier-1 pins JAX_PLATFORMS=cpu otherwise).", scope="tests")
declare("PINT_TPU_GOLDEN_DIR", None, "str",
        "Directory of external golden datasets; unset skips those "
        "tests with an explanation.", scope="tests")


@dataclasses.dataclass
class Config:
    ephem_dir: str | None = None
    strict_ephem: bool = False
    clock_dir: str | None = None
    cache_dir: str | None = None

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            ephem_dir=env_str("PINT_TPU_EPHEM_DIR"),
            strict_ephem=env_on("PINT_TPU_STRICT_EPHEM"),
            clock_dir=env_str("PINT_TPU_CLOCK_DIR"),
            cache_dir=env_str("PINT_TPU_CACHE_DIR"),
        )


_override: Config | None = None


def set_config(cfg: Config | None) -> None:
    """Install a programmatic override (None restores env-driven config)."""
    global _override
    _override = cfg


def get_config(refresh: bool = False) -> Config:
    """Current config: the programmatic override if set, else the env.

    Env reads are cheap, so without an override every call reflects the
    live environment (tests monkeypatch env vars freely). ``refresh`` is
    accepted for API compatibility; it additionally clears an override.
    """
    global _override
    if refresh:
        _override = None
    return _override if _override is not None else Config.from_env()


def runtimefile(name: str) -> str:
    """Absolute path of a bundled runtime data file.

    Reference: pint.config.runtimefile — locates files shipped inside
    the package (here ``pint_tpu/data``). Raises FileNotFoundError with
    the searched path if absent.
    """
    base = os.path.join(os.path.dirname(__file__), "data")
    path = os.path.join(base, name)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no bundled runtime file {name!r} in {base}")
    return path
