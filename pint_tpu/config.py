"""Runtime configuration + bundled-data locator.

Reference equivalents: ``pint.config`` (runtimefile locator for
src/pint/data/runtime) and the reference's scattered environment
switches (clock-file policies etc.). All knobs live in one dataclass
read from the environment once, overridable programmatically:

* ``PINT_TPU_EPHEM_DIR``     — directory searched for ``deNNN.bsp`` kernels
* ``PINT_TPU_STRICT_EPHEM``  — refuse the analytic-ephemeris fallback
* ``PINT_TPU_CLOCK_DIR``     — directory of tempo/tempo2 clock files to
  auto-register at first use
* ``PINT_TPU_CACHE_DIR``     — TOA pickle-cache location (defaults beside
  the tim file)
"""

from __future__ import annotations

import dataclasses
import os


@dataclasses.dataclass
class Config:
    ephem_dir: str | None = None
    strict_ephem: bool = False
    clock_dir: str | None = None
    cache_dir: str | None = None

    @classmethod
    def from_env(cls) -> "Config":
        return cls(
            ephem_dir=os.environ.get("PINT_TPU_EPHEM_DIR") or None,
            strict_ephem=bool(os.environ.get("PINT_TPU_STRICT_EPHEM")),
            clock_dir=os.environ.get("PINT_TPU_CLOCK_DIR") or None,
            cache_dir=os.environ.get("PINT_TPU_CACHE_DIR") or None,
        )


_override: Config | None = None


def set_config(cfg: Config | None) -> None:
    """Install a programmatic override (None restores env-driven config)."""
    global _override
    _override = cfg


def get_config(refresh: bool = False) -> Config:
    """Current config: the programmatic override if set, else the env.

    Env reads are cheap, so without an override every call reflects the
    live environment (tests monkeypatch env vars freely). ``refresh`` is
    accepted for API compatibility; it additionally clears an override.
    """
    global _override
    if refresh:
        _override = None
    return _override if _override is not None else Config.from_env()


def runtimefile(name: str) -> str:
    """Absolute path of a bundled runtime data file.

    Reference: pint.config.runtimefile — locates files shipped inside
    the package (here ``pint_tpu/data``). Raises FileNotFoundError with
    the searched path if absent.
    """
    base = os.path.join(os.path.dirname(__file__), "data")
    path = os.path.join(base, name)
    if not os.path.isfile(path):
        raise FileNotFoundError(f"no bundled runtime file {name!r} in {base}")
    return path
