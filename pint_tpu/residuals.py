"""Residuals: model-predicted phase vs observed arrival, in turns and seconds.

Reference equivalent: ``pint.residuals.Residuals`` (src/pint/residuals.py).
Conventions matched to the reference (SURVEY.md hard-part #5):

* ``track_mode="nearest"``: the fractional part of the model phase (in
  [-0.5, 0.5]) is the residual — each TOA is compared to its nearest
  integer pulse.
* ``track_mode="use_pulse_numbers"``: residual = full phase minus the
  per-TOA pulse number (from ``-pn`` flags), keeping integer-turn slips.
* PHASE-command offsets from the tim file enter as added turns.
* Optional (default on) subtraction of the (weighted) mean phase.
* ``time_resids = phase_resids / F0``.

Residual magnitudes are < 1 turn, so float64 carries them losslessly once
the DD phase has been wrapped; chi-square and all downstream linear
algebra are float64 (TPU-friendly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.ops import phase as phase_mod

Array = jax.Array


class Residuals:
    """Computed once at construction; arrays are device-resident float64."""

    def __init__(self, toas, model, *, subtract_mean: bool = True,
                 use_weighted_mean: bool = True, track_mode: str | None = None):
        self.toas = toas
        self.model = model
        # an explicit PHOFF parameter replaces the implicit mean
        # subtraction (reference: Residuals disables subtract_mean when
        # a PhaseOffset component is present)
        if model.has_component("PhaseOffset"):
            subtract_mean = False
        self.subtract_mean = subtract_mean
        self.use_weighted_mean = use_weighted_mean
        if track_mode is None:
            has_pn = bool(np.any(np.isfinite(np.asarray(toas.pulse_number))))
            track_mode = "use_pulse_numbers" if has_pn else "nearest"
        self.track_mode = track_mode
        self.phase = model.phase(toas, abs_phase=True)
        self.phase_resids = self._calc_phase_resids()
        self.time_resids = self.phase_resids / model.f0_f64

    # ------------------------------------------------------------------
    def _calc_phase_resids(self) -> Array:
        # PHASE-command offsets enter in phase space *before* wrapping, so
        # integer PHASE commands are no-ops under nearest tracking
        # (reference: delta_pulse_number handling in Residuals).
        ph = phase_mod.add(self.phase, phase_mod.from_f64(self.toas.phase_offset))
        if self.track_mode == "use_pulse_numbers":
            pn = self.toas.pulse_number
            pn_safe = jnp.where(jnp.isfinite(pn), pn, ph.int_part)
            resid = (ph.int_part - pn_safe) + (ph.frac.hi + ph.frac.lo)
        elif self.track_mode == "nearest":
            resid = ph.frac.hi + ph.frac.lo
        else:
            raise ValueError(f"unknown track_mode {self.track_mode!r}")
        if self.subtract_mean:
            if self.use_weighted_mean:
                # NOISE-SCALED uncertainties (get_errors_s), not the raw
                # per-TOA errors: the reference weights the mean by
                # get_data_error (EFAC/EQUAD applied), and every fitter
                # subtracts the mean with the same scaled weights — raw
                # weights left a constant offset in the residuals of any
                # model with heterogeneous EFAC/EQUAD groups (~36 ns on
                # soak seed 20021), skewing r^T C^-1 r merit values
                # between fitters by ~0.1%
                err = self.get_errors_s()
                w = jnp.where(err > 0, 1.0 / jnp.square(err), 0.0)
                mean = jnp.sum(resid * w) / jnp.sum(w)
            else:
                mean = jnp.mean(resid)
            resid = resid - mean
        return resid

    # ------------------------------------------------------------------
    def get_errors_s(self) -> Array:
        """Per-TOA uncertainty [s], noise-model-scaled when present.

        Reference: Residuals.get_data_error -> model.scaled_toa_uncertainty.
        """
        scaler = getattr(self.model, "scaled_toa_uncertainty", None)
        if scaler is not None:
            return scaler(self.toas)
        return self.toas.get_errors_s()

    @property
    def chi2(self) -> float:
        err = self.get_errors_s()
        return float(jnp.sum(jnp.square(self.time_resids / err)))

    @property
    def dof(self) -> int:
        # free params + 1 for the implicit phase offset (reference convention)
        return len(self.toas) - len(self.model.free_params) - 1

    @property
    def reduced_chi2(self) -> float:
        return self.chi2 / self.dof

    def rms_weighted_s(self) -> float:
        err = self.get_errors_s()
        w = 1.0 / jnp.square(err)
        mean = jnp.sum(self.time_resids * w) / jnp.sum(w)
        var = jnp.sum(jnp.square(self.time_resids - mean) * w) / jnp.sum(w)
        return float(jnp.sqrt(var))

    def calc_time_resids(self) -> Array:
        return self.time_resids

    def calc_phase_resids(self) -> Array:
        return self.phase_resids

    # ------------------------------------------------------------------
    def ecorr_average(self, *, use_noise_model: bool = True,
                      dt_s: float | None = None) -> dict[str, np.ndarray]:
        """Epoch-averaged residuals (reference: Residuals.ecorr_average).

        Epochs are the model's own ECORR grouping when an ``EcorrNoise``
        component is present (``EcorrNoise.epoch_indices`` — per
        selector, the component's ``dt_s``/``nmin``); TOAs outside any
        ECORR epoch, or the whole set when no ECORR exists, are grouped
        by time adjacency (``dt_s`` seconds, default the component's or
        1.0). Residuals are weighted-averaged within each epoch; with
        ``use_noise_model`` the weights use the scaled (EFAC/EQUAD)
        errors and the per-epoch uncertainty adds the epoch's ECORR in
        quadrature — the plk-style "averaged residuals" view.

        Returns a dict of per-epoch arrays sorted by time: ``mjds``,
        ``freqs``, ``time_resids`` [s], ``errors`` [s] (NaN for an
        all-zero-error epoch), ``indices`` (list of member-index
        arrays).
        """
        from pint_tpu.constants import SECS_PER_DAY
        from pint_tpu.models.noise import quantize_epochs

        mjds = np.asarray(self.toas.tdb.hi) + np.asarray(self.toas.tdb.lo)
        n = len(self.toas)
        ec = self.model.get_component("EcorrNoise") if use_noise_model else None
        groups: list[np.ndarray] = []
        group_var: list[float] = []  # per-epoch ECORR variance [s^2]
        ungrouped = np.ones(n, dtype=bool)
        if ec is not None:
            idx, phi = ec.epoch_indices(self.toas)
            ne = len(phi)
            # one argsort over idx instead of an O(ne * n) per-epoch scan
            order_i = np.argsort(idx, kind="stable")
            sorted_idx = idx[order_i]
            starts = np.searchsorted(sorted_idx, np.arange(ne + 1))
            for e in range(ne):
                g = order_i[starts[e]:starts[e + 1]]
                groups.append(g)
                group_var.append(float(phi[e]))
                ungrouped[g] = False
        if dt_s is None:
            dt_s = ec.dt_s if ec is not None else 1.0
        rest = np.nonzero(ungrouped)[0]
        if rest.size:
            for g in quantize_epochs(mjds[rest] * SECS_PER_DAY,
                                     dt_s=dt_s, nmin=1):
                groups.append(rest[g])
                group_var.append(0.0)
        err = np.asarray(self.get_errors_s() if use_noise_model
                         else self.toas.get_errors_s())
        r = np.asarray(self.time_resids)
        freqs = np.asarray(self.toas.freq_mhz)
        out = {"mjds": [], "freqs": [], "time_resids": [], "errors": [],
               "indices": []}
        for g, var in zip(groups, group_var):
            w = np.where(err[g] > 0, 1.0 / np.square(err[g]), 0.0)
            sw = np.sum(w)
            if sw == 0.0:  # all-zero-error epoch: unweighted, unknown sigma
                w, sw, white_var = np.ones(len(g)), float(len(g)), np.nan
            else:
                white_var = 1.0 / sw
            out["mjds"].append(np.sum(mjds[g] * w) / sw)
            out["freqs"].append(np.sum(freqs[g] * w) / sw)
            out["time_resids"].append(np.sum(r[g] * w) / sw)
            out["errors"].append(np.sqrt(white_var + var))
            out["indices"].append(g)
        order = np.argsort(np.asarray(out["mjds"]))
        return {k: (np.asarray(v)[order] if k != "indices"
                    else [v[i] for i in order]) for k, v in out.items()}
