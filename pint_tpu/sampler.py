"""Affine-invariant ensemble MCMC in pure JAX (Goodman & Weare 2010).

Reference equivalent: the ``emcee`` dependency behind
``pint.mcmc_fitter`` (src/pint/mcmc_fitter.py). Rather than shelling
out to a CPU sampler, the stretch-move ensemble runs as a
``lax.scan`` over steps with the walker axis vectorized — the whole
chain is one XLA program, and the log-posterior is the same jitted
phase-function evaluation the fitters use. Walkers split into two
half-ensembles updated alternately (the standard parallel stretch
move, Foreman-Mackey et al. 2013 §3).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array


def run_ensemble(log_prob: Callable[[Array], Array], p0: np.ndarray,
                 n_steps: int, *, a: float = 2.0, seed: int = 0,
                 thin: int = 1) -> dict:
    """Run the stretch-move ensemble sampler.

    log_prob: maps a (ndim,) parameter vector to a scalar log posterior
    (will be vmapped); p0: (nwalkers, ndim) initial ensemble, nwalkers
    even and >= 2*ndim recommended. Returns {"chain": (nsteps//thin,
    nwalkers, ndim), "log_prob": ..., "acceptance": (nwalkers,)}.
    """
    p0 = jnp.asarray(p0, jnp.float64)
    nw, nd = p0.shape
    if nw % 2:
        raise ValueError("nwalkers must be even")
    half = nw // 2
    lp_fn = jax.vmap(log_prob)

    def half_step(key, movers, movers_lp, others):
        k1, k2, k3 = jax.random.split(key, 3)
        # stretch factor z ~ g(z) = 1/sqrt(z) on [1/a, a]
        u = jax.random.uniform(k1, (half,))
        z = jnp.square((a - 1.0) * u + 1.0) / a
        idx = jax.random.randint(k2, (half,), 0, half)
        partners = others[idx]
        prop = partners + z[:, None] * (movers - partners)
        prop_lp = lp_fn(prop)
        log_ratio = (nd - 1.0) * jnp.log(z) + prop_lp - movers_lp
        accept = jnp.log(jax.random.uniform(k3, (half,))) < log_ratio
        new = jnp.where(accept[:, None], prop, movers)
        new_lp = jnp.where(accept, prop_lp, movers_lp)
        return new, new_lp, accept

    def step(carry, key):
        p, lp, acc = carry
        ka, kb = jax.random.split(key)
        first, first_lp, acc_a = half_step(ka, p[:half], lp[:half], p[half:])
        second, second_lp, acc_b = half_step(kb, p[half:], lp[half:], first)
        p = jnp.concatenate([first, second])
        lp = jnp.concatenate([first_lp, second_lp])
        acc = acc + jnp.concatenate([acc_a, acc_b])
        return (p, lp, acc), (p, lp)

    keys = jax.random.split(jax.random.PRNGKey(seed), n_steps)
    init = (p0, lp_fn(p0), jnp.zeros(nw))
    (pf, lpf, acc), (chain, chain_lp) = jax.lax.scan(step, init, keys)
    return {
        "chain": np.asarray(chain[::thin]),
        "log_prob": np.asarray(chain_lp[::thin]),
        "acceptance": np.asarray(acc) / n_steps,
        "final": (np.asarray(pf), np.asarray(lpf)),
    }


def initialize_walkers(center: np.ndarray, scale: np.ndarray, nwalkers: int,
                       seed: int = 0) -> np.ndarray:
    """Gaussian ball of walkers around `center` with per-dim `scale`."""
    rng = np.random.default_rng(seed)
    return center[None, :] + scale[None, :] * rng.standard_normal(
        (nwalkers, center.size))
