"""Bayesian timing: priors, log-posterior builder, ensemble MCMC fitter.

Reference equivalents: ``pint.bayesian.BayesianTiming`` (prior plumbing +
lnlikelihood/lnposterior over free parameters, src/pint/bayesian.py) and
``pint.mcmc_fitter.MCMCFitter`` (emcee-driven fitting,
src/pint/mcmc_fitter.py). TPU-first differences:

* the log-posterior is one pure jitted function of a flat parameter
  vector — the same composed phase function the fitters use, with the
  DD linearization point closed over (samples are float64 *offsets*
  resolved against the double-double base, so nothing loses precision);
* the sampler is the in-package pure-JAX stretch move
  (``pint_tpu.sampler.run_ensemble``): walkers are vmapped, steps are a
  ``lax.scan`` — the whole chain is a single XLA program, no emcee;
* white-noise parameters (EFAC/EQUAD) may be sampled: their scaling is
  rebuilt inside the traced likelihood from materialized selector
  masks, not read from host parameter objects;
* correlated noise (ECORR / red noise) with fixed hyperparameters is
  marginalized analytically via the Woodbury quadratic form + log-det.
"""

from __future__ import annotations

import dataclasses
from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.models.parameter import toa_mask
from pint_tpu.sampler import initialize_walkers, run_ensemble

Array = jax.Array
LOG2PI = float(np.log(2.0 * np.pi))


@dataclasses.dataclass(frozen=True)
class UniformPrior:
    lo: float
    hi: float

    def log_pdf(self, x: Array) -> Array:
        inside = (x >= self.lo) & (x <= self.hi)
        return jnp.where(inside, -jnp.log(self.hi - self.lo), -jnp.inf)

    def width(self) -> float:
        return (self.hi - self.lo) / np.sqrt(12.0)


@dataclasses.dataclass(frozen=True)
class NormalPrior:
    mu: float
    sigma: float

    def log_pdf(self, x: Array) -> Array:
        z = (x - self.mu) / self.sigma
        return -0.5 * (z * z + LOG2PI) - jnp.log(self.sigma)

    def width(self) -> float:
        return self.sigma


def default_priors(model, *, sigma_factor: float = 10.0) -> dict:
    """Uniform priors ±sigma_factor x uncertainty around each free value.

    Reference: pint.bayesian's default uniform priors from par-file
    uncertainties. Parameters without an uncertainty get a broad uniform
    from a per-kind heuristic scale (documented weakness shared with the
    reference: you should set real priors).
    """
    priors = {}
    for name in model.free_params:
        p = model.params[name]
        v = p.value_f64
        unc = p.uncertainty or 0.0
        if unc <= 0.0:
            unc = max(abs(v) * 1e-6, 1e-12)
        w = sigma_factor * unc
        priors[name] = UniformPrior(v - w, v + w)
    return priors


class BayesianTiming:
    """Log-prior / log-likelihood / log-posterior over free parameters.

    ``param_vector()`` orders the free parameters; every log-density
    takes a flat (ndim,) float64 vector of *parameter values* in par
    units. Internally values become offsets from the DD base with the
    exact two-step subtraction (x - hi) - lo, so F0-scale magnitudes
    lose nothing.

    Reference: pint.bayesian.BayesianTiming (lnprior/lnlikelihood/
    lnposterior); correlated noise is marginalized instead of sampled.
    """

    def __init__(self, toas, model, priors: dict | None = None):
        self.toas = toas
        self.model = model
        self.fit_params = list(model.free_params)
        # a prior on a frozen EFAC/EQUAD/TNEQ opts that white-noise
        # parameter into sampling (the reference's pint.bayesian
        # use_pulse_numbers/white-noise choice); anything else frozen is
        # an error — freeze/unfreeze is the user's sampling switch.
        if priors:
            for k in priors:
                if k in self.fit_params:
                    continue
                p = model.params.get(k)
                kind = k.rstrip("0123456789")
                if p is not None and kind in ("EFAC", "EQUAD", "TNEQ"):
                    self.fit_params.append(k)
                else:
                    raise ValueError(
                        f"prior for non-free parameter {k!r} (only frozen "
                        "EFAC/EQUAD/TNEQ may be opted into sampling)")
        self.nparams = len(self.fit_params)
        self.priors = dict(default_priors(model))
        if priors:
            self.priors.update(priors)

        # white-noise scaling terms, in scale_sigma's application order
        # (EQUAD/TNEQ variances first, then EFAC replace-where): sampled
        # terms read the traced vector, fixed ones are constants.
        sampled_noise = {k for k in self.fit_params
                         if k.rstrip("0123456789") in ("EFAC", "EQUAD", "TNEQ")}
        self._noise_terms: list[tuple[str, str, Array, float | None]] = []
        for p in model.params.values():
            kind = p.name.rstrip("0123456789")
            if kind not in ("EFAC", "EQUAD", "TNEQ"):
                continue
            mask = jnp.asarray(np.asarray(toa_mask(p.selector, toas)),
                               jnp.float64)
            fixed = None if p.name in sampled_noise else p.value_f64
            self._noise_terms.append((p.name, kind, mask, fixed))
        self._has_sampled_noise = bool(sampled_noise)
        self._timing_params = [k for k in self.fit_params
                               if k not in sampled_noise]

        base = model.base_dd()
        self._base_hi = {k: float(base[k].hi) for k in self.fit_params}
        self._base_lo = {k: float(base[k].lo) for k in self.fit_params}
        self._phase_fn = model.phase_fn(toas)
        self._base = base
        self._f0 = model.f0_f64
        self._sigma0 = jnp.asarray(toas.get_errors_s()) \
            if self._has_sampled_noise \
            else jnp.asarray(model.scaled_toa_uncertainty(toas))

        # fixed-hyperparameter correlated noise: marginalize analytically
        pairs = model._noise_basis_pairs(toas) if model.has_correlated_errors \
            else []
        if pairs:
            U = np.concatenate([u for _, u, _ in pairs], axis=1)
            phi = np.concatenate([w for _, _, w in pairs])
            self._U = jnp.asarray(U)
            self._log_phi = jnp.asarray(np.log(phi))
            self._inv_phi = jnp.asarray(1.0 / phi)
        else:
            self._U = None

        self._lnpost = jax.jit(self._build_lnpost())

    # ------------------------------------------------------------------
    def param_vector(self) -> np.ndarray:
        return np.asarray([self.model.params[k].value_f64
                           for k in self.fit_params])

    def param_uncertainties(self) -> np.ndarray:
        out = []
        for k in self.fit_params:
            unc = self.model.params[k].uncertainty or 0.0
            out.append(unc if unc > 0 else self.priors[k].width())
        return np.asarray(out)

    def _deltas(self, x: Array) -> dict[str, Array]:
        """Offsets from the DD base; exact for x near the base value."""
        out = {}
        for j, k in enumerate(self.fit_params):
            out[k] = (x[j] - self._base_hi[k]) - self._base_lo[k]
        return out

    def _build_lnpost(self) -> Callable[[Array], Array]:
        prior_fns = [(j, self.priors[k])
                     for j, k in enumerate(self.fit_params)]
        timing = self._timing_params
        noise_terms = self._noise_terms
        has_sampled = self._has_sampled_noise
        name_to_idx = {k: j for j, k in enumerate(self.fit_params)}

        def lnprior(x: Array) -> Array:
            lp = jnp.zeros(())
            for j, pr in prior_fns:
                lp = lp + pr.log_pdf(x[j])
            return lp

        def sigma_of(x: Array) -> Array:
            sigma = self._sigma0
            if not has_sampled:
                return sigma  # already host-scaled
            var = jnp.square(sigma)
            for name, kind, mask, fixed in noise_terms:
                v = fixed if fixed is not None else x[name_to_idx[name]]
                if kind == "EQUAD":
                    var = var + mask * jnp.square(v * 1e-6)
                elif kind == "TNEQ":
                    var = var + mask * 10.0 ** (2.0 * v)
            scale = jnp.ones_like(sigma)
            for name, kind, mask, fixed in noise_terms:
                if kind == "EFAC":  # replace-where, matching scale_sigma
                    v = fixed if fixed is not None else x[name_to_idx[name]]
                    scale = jnp.where(mask > 0, v, scale)
            return scale * jnp.sqrt(var)

        def lnlike(x: Array) -> Array:
            deltas = self._deltas(x)
            d_timing = {k: deltas[k] for k in timing}
            ph = self._phase_fn(self._base, d_timing)
            frac = ph.frac.hi + ph.frac.lo
            sigma = sigma_of(x)
            w = 1.0 / jnp.square(sigma)
            mean = jnp.sum(frac * w) / jnp.sum(w)
            r = (frac - mean) / self._f0
            rw = r / sigma
            lnl = -0.5 * jnp.sum(jnp.square(rw)) \
                - jnp.sum(jnp.log(sigma)) - 0.5 * r.size * LOG2PI
            if self._U is not None:
                A = self._U / sigma[:, None]
                S = jnp.diag(self._inv_phi) + A.T @ A
                L, low = jax.scipy.linalg.cho_factor(S, lower=True)
                b = A.T @ rw
                lnl = lnl + 0.5 * b @ jax.scipy.linalg.cho_solve((L, low), b) \
                    - jnp.sum(jnp.log(jnp.diag(L))) \
                    - 0.5 * jnp.sum(self._log_phi)
            return lnl

        def lnpost(x: Array) -> Array:
            lp = lnprior(x)
            ll = jnp.where(jnp.isfinite(lp), lnlike(x), 0.0)
            return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        return lnpost

    # public names mirroring the reference API
    def lnposterior(self, x) -> float:
        return float(np.asarray(self._lnpost(jnp.asarray(x, jnp.float64))))

    def lnprior(self, x) -> float:
        x = jnp.asarray(x, jnp.float64)
        lp = jnp.zeros(())
        for j, k in enumerate(self.fit_params):
            lp = lp + self.priors[k].log_pdf(x[j])
        return float(np.asarray(lp))

    def lnlikelihood(self, x) -> float:
        return self.lnposterior(x) - self.lnprior(x)


class MCMCFitter:
    """Posterior sampling fitter (reference: pint.mcmc_fitter.MCMCFitter).

    ``fit_toas`` runs the stretch-move ensemble on the jitted
    log-posterior and writes the posterior mean / standard deviation
    into the model's free parameters. The chain (post burn-in) is kept
    on ``self.chain`` for corner plots / diagnostics.
    """

    def __init__(self, toas, model, priors: dict | None = None, *,
                 nwalkers: int | None = None, nsteps: int = 500,
                 burn_frac: float = 0.25, seed: int = 0):
        self.bt = BayesianTiming(toas, model, priors)
        self.toas = toas
        self.model = model
        self.nwalkers = nwalkers or max(2 * self.bt.nparams + 2, 16)
        if self.nwalkers % 2:
            self.nwalkers += 1
        self.nsteps = nsteps
        self.burn_frac = burn_frac
        self.seed = seed
        self.chain: np.ndarray | None = None
        self.acceptance: np.ndarray | None = None

    def fit_toas(self, maxiter: int | None = None) -> float:
        """Sample; returns the best log-posterior found. maxiter = nsteps."""
        nsteps = maxiter or self.nsteps
        center = self.bt.param_vector()
        scale = self.bt.param_uncertainties()
        p0 = initialize_walkers(center, scale, self.nwalkers, seed=self.seed)
        out = run_ensemble(self.bt._lnpost, p0, nsteps, seed=self.seed)
        burn = int(nsteps * self.burn_frac)
        chain = out["chain"][burn:]
        self.chain = chain.reshape(-1, self.bt.nparams)
        self.acceptance = out["acceptance"]
        mean = self.chain.mean(axis=0)
        std = self.chain.std(axis=0)
        for j, k in enumerate(self.bt.fit_params):
            p = self.model.params[k]
            p.add_delta(float(mean[j]) - p.value_f64)
            p.uncertainty = float(std[j])
        return float(out["log_prob"][burn:].max())
