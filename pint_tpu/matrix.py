"""Labeled design / covariance / correlation matrices.

Reference equivalent: ``pint.pint_matrix`` (src/pint/pint_matrix.py ::
DesignMatrix, CovarianceMatrix, CorrelationMatrix,
combine_design_matrices_by_quantity). The reference carries astropy
units through a generic axis-label machine; here labels are
``(param name, unit string)`` pairs on plain float64 arrays — the jitted
fit path keeps using raw arrays (units at the API boundary only, per
SURVEY.md §2.4), and these wrappers are the host-side reporting /
combination layer on top.
"""

from __future__ import annotations

import dataclasses

import numpy as np


def _param_units(model, names: list[str]) -> list[str]:
    out = []
    for n in names:
        if n == "Offset":
            out.append("s")
        elif n in model.params:
            out.append(model.params[n].units or "")
        else:
            out.append("")
    return out


@dataclasses.dataclass
class DesignMatrix:
    """(n, p) derivative matrix with labeled parameter columns.

    ``quantity`` is what the rows differentiate ("toa" residuals in
    seconds, or "dm" in pc/cm^3) — the key wideband combination merges
    on. Reference: pint.pint_matrix.DesignMatrix.
    """

    matrix: np.ndarray
    params: list[str]
    units: list[str]
    quantity: str = "toa"
    quantity_unit: str = "s"

    @classmethod
    def from_model(cls, model, toas, params: list[str] | None = None,
                   quantity: str = "toa") -> "DesignMatrix":
        if quantity == "toa":
            M, names = model.designmatrix(toas, params)
            qunit = "s"
        elif quantity == "dm":
            M, names = model.dm_designmatrix(toas, params)
            qunit = "pc cm^-3"
        else:
            raise ValueError(f"unknown design-matrix quantity {quantity!r}")
        return cls(np.asarray(M), list(names), _param_units(model, list(names)),
                   quantity, qunit)

    @property
    def shape(self) -> tuple[int, int]:
        return self.matrix.shape

    def derivative_params(self) -> list[str]:
        return list(self.params)

    def get_unit(self, param: str) -> str:
        return self.units[self.params.index(param)]

    def labels(self) -> list[tuple[str, str]]:
        return list(zip(self.params, self.units))


def combine_design_matrices_by_quantity(matrices: list[DesignMatrix]
                                        ) -> DesignMatrix:
    """Stack row blocks of different quantities over one parameter set.

    The wideband joint fit stacks the TOA block on top of the DM block;
    all blocks must share the same parameter columns (order included).
    Reference: pint.pint_matrix.combine_design_matrices_by_quantity.
    """
    if not matrices:
        raise ValueError("no design matrices given")
    first = matrices[0]
    for m in matrices[1:]:
        if m.params != first.params:
            raise ValueError(
                f"parameter columns differ: {m.params} vs {first.params}")
    return DesignMatrix(
        np.concatenate([m.matrix for m in matrices], axis=0),
        list(first.params), list(first.units),
        quantity="+".join(m.quantity for m in matrices),
        quantity_unit="+".join(m.quantity_unit for m in matrices))


def combine_design_matrices_by_param(matrices: list[DesignMatrix]
                                     ) -> DesignMatrix:
    """Concatenate parameter-column blocks over one quantity/row axis.

    Shared columns must be bitwise identical (they come from the same
    model/toas); new columns append. Reference:
    pint.pint_matrix.combine_design_matrices_by_param.
    """
    if not matrices:
        raise ValueError("no design matrices given")
    out = matrices[0]
    for m in matrices[1:]:
        if m.matrix.shape[0] != out.matrix.shape[0]:
            raise ValueError("row (quantity) axes differ")
        new_cols, new_params, new_units = [], [], []
        for j, p in enumerate(m.params):
            if p in out.params:
                if not np.array_equal(m.matrix[:, j],
                                      out.matrix[:, out.params.index(p)]):
                    raise ValueError(f"conflicting columns for {p}")
                continue
            new_cols.append(m.matrix[:, j])
            new_params.append(p)
            new_units.append(m.units[j])
        if new_cols:
            out = DesignMatrix(
                np.concatenate([out.matrix, np.stack(new_cols, 1)], axis=1),
                out.params + new_params, out.units + new_units,
                out.quantity, out.quantity_unit)
    return out


@dataclasses.dataclass
class CovarianceMatrix:
    """(p, p) parameter covariance with labels; prettyprint + correlation.

    Reference: pint.pint_matrix.CovarianceMatrix / CorrelationMatrix
    (and pint.utils' covariance-to-correlation helpers).
    """

    matrix: np.ndarray
    params: list[str]
    units: list[str]

    @classmethod
    def from_fitter(cls, fitter) -> "CovarianceMatrix":
        if fitter.parameter_covariance_matrix is None:
            raise ValueError("fit_toas() has not been run")
        names = ["Offset"] + list(fitter.fit_params)
        cov = np.asarray(fitter.parameter_covariance_matrix)
        if cov.shape[0] == len(names) - 1:  # fitter dropped the offset row
            names = list(fitter.fit_params)
        return cls(cov, names, _param_units(fitter.model, names))

    @property
    def shape(self) -> tuple[int, int]:
        return self.matrix.shape

    def get_label_names(self) -> list[str]:
        return list(self.params)

    def get_uncertainties(self) -> np.ndarray:
        return np.sqrt(np.diag(self.matrix))

    def to_correlation_matrix(self) -> "CorrelationMatrix":
        sig = self.get_uncertainties()
        denom = np.outer(sig, sig)
        corr = np.divide(self.matrix, denom,
                         out=np.zeros_like(self.matrix), where=denom != 0)
        return CorrelationMatrix(corr, list(self.params),
                                 [""] * len(self.params))

    def prettyprint(self, prec: int = 3) -> str:
        return _pretty(self.matrix, self.params, prec, sci=True)


@dataclasses.dataclass
class CorrelationMatrix(CovarianceMatrix):
    def prettyprint(self, prec: int = 3) -> str:
        return _pretty(self.matrix, self.params, prec, sci=False)


def _pretty(mat: np.ndarray, names: list[str], prec: int, *, sci: bool) -> str:
    """Lower-triangle table like the reference's correlation printout."""
    w = max(max((len(n) for n in names), default=4), prec + (8 if sci else 4))
    fmt = f"{{:>{w}.{prec}e}}" if sci else f"{{:>{w}.{prec}f}}"
    lines = []
    for i, n in enumerate(names):
        cells = [fmt.format(mat[i, j]) for j in range(i + 1)]
        lines.append(f"{n:<12}" + " ".join(cells))
    lines.append(" " * 12 + " ".join(f"{n:>{w}}" for n in names))
    return "\n".join(lines)
