"""TOA table: parsed arrival times + precomputed astrometric context.

Reference equivalent: ``pint.toa.TOAs`` / ``get_TOAs()`` (src/pint/toa.py),
which stores an astropy Table and computes clock corrections, TDB times and
observatory solar-system positions. Here the table is a *pytree of device
arrays* (registered dataclass) so the whole object flows through jit /
vmap / shard_map, with host-only metadata (flags, site names) held as
static aux data.

Load pipeline (host, once per dataset — mirrors reference call stack
SURVEY.md §3.1):

1. parse `.tim` (strings; exact-precision MJDs)
2. site clock chain -> UTC        (observatory.clock_corrections_s)
3. UTC -> TT -> TDB in DD         (ops.timescales; topocentric Einstein term)
4. observatory GCRS offset        (earth.itrf_to_gcrs_posvel)
5. Earth/Sun/planet posvels       (ephemeris provider)

Everything downstream (delays, phases, fits) consumes only this object.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu import earth, observatory as obs_mod
from pint_tpu.ephemeris import AnalyticEphemeris, Ephemeris, get_ephemeris
from pint_tpu.io.timfile import RawTOA, TimFile, parse_timfile
from pint_tpu.ops import dd, timescales as ts
from pint_tpu.ops.dd import DD

Array = jax.Array

from pint_tpu.constants import C_M_S, SECS_PER_DAY
PLANET_NAMES = ("sun", "venus", "jupiter", "saturn", "uranus", "neptune")


class Flags(tuple):
    """Tuple of per-TOA flag dicts, hashable by content.

    TOAs cross jit boundaries as pytrees (the sharded fit path passes the
    table as a traced argument), so static aux data must be hashable —
    plain tuples of dicts are not. The content hash is computed once and
    cached; flag dicts are treated as immutable after construction.
    """

    def __hash__(self) -> int:  # noqa: D105
        h = getattr(self, "_hash", None)
        if h is None:
            h = hash(tuple(tuple(sorted(d.items())) for d in self))
            self._hash = h
        return h


@jax.tree_util.register_dataclass
@dataclass
class TOAs:
    """Pytree TOA table. Shapes: (n,) unless noted; positions (n, 3) lt-s."""

    # --- data fields (traced leaves) ---
    tdb: DD  # TDB MJD at the observatory
    utc: DD  # site-clock-corrected UTC MJD (for rotation/evaluation)
    freq_mhz: Array  # topocentric observing frequency
    error_us: Array  # TOA uncertainty
    obs_pos_ls: Array  # observatory wrt SSB [lt-s], (n, 3)
    obs_vel_c: Array  # observatory velocity / c, (n, 3)
    phase_offset: Array  # accumulated tim-file PHASE commands
    planet_pos_ls: dict  # name -> (n,3) body position wrt *observatory* [lt-s]
    pulse_number: Array  # tracked pulse numbers (nan = absent)
    obs_index: Array  # site index per TOA (int32)
    jump_group: Array  # tim-file JUMP block id per TOA (int32; 0 = none)

    # --- metadata (static aux; must be hashable) ---
    obs_names: tuple = field(metadata=dict(static=True))  # index -> site name
    flags: Flags = field(metadata=dict(static=True))  # per-TOA flag dicts
    ephem_name: str = field(default="builtin_analytic", metadata=dict(static=True))
    clock_applied: bool = field(default=True, metadata=dict(static=True))
    # selector masks materialized as data (traced): key "-flag value" ->
    # (n,) float mask. Lets flag-based maskParameters (EFAC/JUMP/...) ride
    # vmap/stacking where the static flags must be stripped
    # (pint_tpu.models.parameter.materialize_selector_masks).
    aux_masks: dict = field(default_factory=dict)

    def __len__(self) -> int:
        return int(np.shape(self.tdb.hi)[0])

    @property
    def ntoas(self) -> int:
        return len(self)

    def get_mjds(self) -> np.ndarray:
        """TDB MJDs as float64 (display/selection precision)."""
        return np.asarray(self.tdb.hi + self.tdb.lo)

    def get_errors_s(self) -> Array:
        return self.error_us * 1e-6

    def get_freqs_hz(self) -> Array:
        return self.freq_mhz * 1e6

    def get_flag_value(self, flag: str, default: str = "") -> list[str]:
        return [f.get(flag, default) for f in self.flags]

    # -- wideband DM data (reference: pint.toa wideband "-pp_dm"/"-pp_dme"
    # flags consumed by WidebandTOAResiduals) --------------------------
    def _dm_flag_memo(self, flag: str) -> np.ndarray:
        """Per-instance memo of an O(n) per-flag float parse. The serve
        submit path consults the wideband data several times per request
        (routing, fingerprint family, the traced DM block); flags are
        treated as immutable after construction (mutation goes through
        ``dataclasses.replace``, which drops the memo), so the cache
        cannot go stale — same contract as ``_bucket_pad_memo``."""
        cache = getattr(self, "_dm_flag_cache", None)
        if cache is None:
            cache = {}
            object.__setattr__(self, "_dm_flag_cache", cache)
        out = cache.get(flag)
        if out is None:
            out = cache[flag] = np.asarray(
                [float(f.get(flag, "nan")) for f in self.flags])
        return out

    def get_dm_values(self) -> np.ndarray:
        """Wideband DM measurements [pc/cm^3] from -pp_dm flags (nan absent)."""
        return self._dm_flag_memo("pp_dm")

    def get_dm_errors(self) -> np.ndarray:
        """Wideband DM uncertainties [pc/cm^3] from -pp_dme flags."""
        return self._dm_flag_memo("pp_dme")

    def is_wideband(self) -> bool:
        """True when every TOA carries a wideband DM measurement."""
        vals = self.get_dm_values()
        return len(vals) > 0 and bool(np.all(np.isfinite(vals)))

    def select(self, mask) -> "TOAs":
        """Boolean-mask subset (host-side; returns a new TOAs)."""
        mask = np.asarray(mask)
        idx = np.nonzero(mask)[0]
        take = lambda a: jnp.asarray(np.asarray(a)[idx])
        return TOAs(
            tdb=DD(take(self.tdb.hi), take(self.tdb.lo)),
            utc=DD(take(self.utc.hi), take(self.utc.lo)),
            freq_mhz=take(self.freq_mhz),
            error_us=take(self.error_us),
            obs_pos_ls=take(self.obs_pos_ls),
            obs_vel_c=take(self.obs_vel_c),
            phase_offset=take(self.phase_offset),
            planet_pos_ls={k: take(v) for k, v in self.planet_pos_ls.items()},
            pulse_number=take(self.pulse_number),
            obs_index=take(self.obs_index),
            jump_group=take(self.jump_group),
            obs_names=self.obs_names,
            flags=Flags(self.flags[i] for i in idx),
            ephem_name=self.ephem_name,
            clock_applied=self.clock_applied,
            aux_masks={k: take(v) for k, v in self.aux_masks.items()},
        )

    def first_mjd(self) -> float:
        return float(np.min(self.get_mjds()))

    def last_mjd(self) -> float:
        return float(np.max(self.get_mjds()))

    def get_summary(self) -> str:
        """Human-readable table description (reference: TOAs.get_summary)."""
        mjds = self.get_mjds()
        err = np.asarray(self.error_us)
        freq = np.asarray(self.freq_mhz)
        obs_idx = np.asarray(self.obs_index)
        lines = [
            f"Number of TOAs: {len(self)}",
            f"MJD span: {mjds.min():.4f} to {mjds.max():.4f} "
            f"({(mjds.max() - mjds.min()) / 365.25:.2f} yr)",
            f"Frequency range: {freq.min():.1f} to {freq.max():.1f} MHz",
            f"TOA errors: median {np.median(err):.3g} us "
            f"(min {err.min():.3g}, max {err.max():.3g})",
            f"Ephemeris: {self.ephem_name}; clock corrections "
            f"{'applied' if self.clock_applied else 'NOT applied'}",
            "Observatories:",
        ]
        for i, name in enumerate(self.obs_names):
            n = int(np.sum(obs_idx == i))
            if n:
                lines.append(f"  {name}: {n} TOAs")
        return "\n".join(lines)

    def print_summary(self) -> None:
        print(self.get_summary())


def merge_TOAs(toas_list: list[TOAs]) -> TOAs:
    """Concatenate TOA tables (reference: pint.toa.merge_TOAs)."""
    keys = set(toas_list[0].aux_masks)
    for t in toas_list[1:]:
        if set(t.aux_masks) != keys:
            raise ValueError(
                "cannot merge TOAs with different aux_masks keys "
                f"({sorted(keys)} vs {sorted(t.aux_masks)}): materialize "
                "selector masks consistently before merging")
    cat = lambda getter: jnp.concatenate([np.asarray(getter(t)) for t in toas_list])
    planets = {}
    for name in toas_list[0].planet_pos_ls:
        planets[name] = jnp.concatenate([t.planet_pos_ls[name] for t in toas_list])
    # site indices need remapping onto the merged name table
    names: list[str] = []
    for t in toas_list:
        for n in t.obs_names:
            if n not in names:
                names.append(n)
    obs_index = np.concatenate(
        [np.asarray([names.index(t.obs_names[i]) for i in np.asarray(t.obs_index)])
         for t in toas_list]
    )
    return TOAs(
        tdb=DD(cat(lambda t: t.tdb.hi), cat(lambda t: t.tdb.lo)),
        utc=DD(cat(lambda t: t.utc.hi), cat(lambda t: t.utc.lo)),
        freq_mhz=cat(lambda t: t.freq_mhz),
        error_us=cat(lambda t: t.error_us),
        obs_pos_ls=cat(lambda t: t.obs_pos_ls),
        obs_vel_c=cat(lambda t: t.obs_vel_c),
        phase_offset=cat(lambda t: t.phase_offset),
        planet_pos_ls=planets,
        pulse_number=cat(lambda t: t.pulse_number),
        obs_index=jnp.asarray(obs_index, jnp.int32),
        jump_group=jnp.concatenate([jnp.asarray(t.jump_group) for t in toas_list]),
        obs_names=tuple(names),
        flags=Flags(f for t in toas_list for f in t.flags),
        ephem_name=toas_list[0].ephem_name,
        clock_applied=all(t.clock_applied for t in toas_list),
        aux_masks={k: jnp.concatenate([t.aux_masks[k] for t in toas_list])
                   for k in toas_list[0].aux_masks},
    )


def get_TOAs(
    timfile: str | TimFile,
    *,
    ephem: str | Ephemeris = "builtin_analytic",
    planets: bool = True,
    include_clock: bool = True,
    clock_limits: str = "warn",
    usepickle: bool = False,
) -> TOAs:
    """Load a `.tim` file into a fully-corrected TOAs table.

    Mirrors reference ``pint.toa.get_TOAs(timfile, ...)`` including the
    clock chain, posvel computation, and the ``usepickle`` load cache
    (src/pint/toa.py): with ``usepickle`` the built table is cached as
    ``<tim>.<ephem>.npz`` (in PINT_TPU_CACHE_DIR if set, else beside the
    tim file) and reused while newer than the tim file.
    """
    import os

    cache_path = None
    if usepickle and isinstance(timfile, str) and os.path.isfile(timfile):
        from pint_tpu.config import get_config

        ename = ephem if isinstance(ephem, str) else getattr(ephem, "name", "eph")
        cdir = get_config().cache_dir or os.path.dirname(os.path.abspath(timfile))
        os.makedirs(cdir, exist_ok=True)
        # every value-affecting option is part of the key: a cache built
        # with clock corrections must not serve an include_clock=False
        # call; a path hash keeps same-basename tim files in a shared
        # cache dir from colliding
        import hashlib

        tag = hashlib.sha1(
            os.path.abspath(timfile).encode()).hexdigest()[:8]
        cache_path = os.path.join(
            cdir, f"{os.path.basename(timfile)}.{tag}.{ename}"
                  f".p{int(planets)}c{int(include_clock)}.npz")
        if (os.path.isfile(cache_path)
                and os.path.getmtime(cache_path) > os.path.getmtime(timfile)):
            return load_pickle(cache_path)

    tf = parse_timfile(timfile) if isinstance(timfile, str) else timfile
    if not tf.toas:
        raise ValueError("tim file contains no TOAs")
    eph = get_ephemeris(ephem) if isinstance(ephem, str) else ephem
    toas = build_TOAs_from_raw(tf, eph, planets=planets,
                               include_clock=include_clock,
                               clock_limits=clock_limits)
    if cache_path is not None:
        save_pickle(toas, cache_path)
    return toas


def build_TOAs_from_raw(
    tf: TimFile,
    eph: Ephemeris,
    *,
    planets: bool = True,
    include_clock: bool = True,
    clock_limits: str = "warn",
) -> TOAs:
    raw = tf.toas
    n = len(raw)

    # exact-precision MJD parse (site-local time scale, usually UTC)
    mjd_local = dd.from_strings([t.mjd_str for t in raw])
    # TIME command offsets (seconds) — applied before clock corrections
    time_off = np.asarray([t.time_offset_s for t in raw])
    if np.any(time_off):
        mjd_local = dd.add(mjd_local, jnp.asarray(time_off) / ts.SECS_PER_DAY)

    site_names: list[str] = []
    obs_index = np.empty(n, dtype=np.int32)
    for i, t in enumerate(raw):
        name = obs_mod.get_observatory(t.obs).name
        if name not in site_names:
            site_names.append(name)
        obs_index[i] = site_names.index(name)

    return build_TOAs_from_arrays(
        mjd_local,
        freq_mhz=np.asarray([t.freq_mhz for t in raw]),
        error_us=np.asarray([t.error_us for t in raw]),
        obs_index=obs_index,
        obs_names=tuple(site_names),
        flags=tuple(dict(t.flags) for t in raw),
        phase_offset=np.asarray([t.phase_offset for t in raw]),
        jump_group=np.asarray([t.jump_group for t in raw]),
        eph=eph,
        planets=planets,
        include_clock=include_clock,
        clock_limits=clock_limits,
    )


# jitted TT->TDB->posvel pipelines, keyed by (ephemeris instance,
# planets flag, explicit-GCRS flag); the value holds a strong ref to the
# ephemeris so the id() key can never be recycled. LRU-bounded:
# id()-keyed providers (SPK/tabulated) would otherwise pin ephemerides +
# executables forever in long sessions.
from pint_tpu.utils.cache import LRUCache

_PIPELINE_JIT_CACHE = LRUCache(32, name="toa_pipeline")


def _astrometric_pipeline(eph: Ephemeris, planets: bool,
                          explicit_gcrs: bool):
    """One fused XLA program for the array compute of a TOA build.

    utc -> TT -> (earth posvel, topocentric Einstein) -> TDB ->
    observatory SSB posvel -> planet positions, as a single jitted
    function instead of hundreds of op-by-op dispatches (each eager op
    is its own tiny XLA program below the persistent-cache threshold;
    fused, the whole build compiles once per input shape and is cached
    on disk).  This is also the TPU-first shape of the pipeline: one
    program the compiler can fuse and shard.
    """
    # AnalyticEphemeris is a frozen value type: key by value so every
    # instance (and every get_TOAs call) shares one compiled pipeline;
    # array-backed providers (SPK/tabulated) key by identity
    from pint_tpu.ephemeris import AnalyticEphemeris

    if isinstance(eph, AnalyticEphemeris):
        key = (eph, planets, explicit_gcrs)
    else:
        key = (id(eph), planets, explicit_gcrs)
    ent = _PIPELINE_JIT_CACHE.get_lru(key)
    if ent is not None and (ent[0] is eph or isinstance(eph, AnalyticEphemeris)):
        return ent[1]

    body_names = tuple(PLANET_NAMES) if planets else ("sun",)
    bodies_fn = getattr(eph, "bodies_posvel_ssb", None)

    def pipeline(utc, itrf, is_bary, is_geo, gcrs_pos_m, gcrs_vel_m_s):
        tt = ts.utc_to_tt(utc)
        tt_f64 = tt.hi + tt.lo
        if explicit_gcrs:
            obs_gcrs_pos, obs_gcrs_vel = gcrs_pos_m, gcrs_vel_m_s
        else:
            obs_gcrs_pos, obs_gcrs_vel = earth.itrf_to_gcrs_posvel(
                itrf, utc.hi + utc.lo)

        if bodies_fn is not None:
            # ONE shared-subexpression posvel evaluation at TT for every
            # body INCLUDING the geocenter (the transcendental-heavy
            # Kepler/wobble chains dominated the whole TOA build when
            # run once for the Einstein term, again at TDB, and again
            # for the planets). Positions are then advanced to TDB to
            # first order, pos + v*(TDB-TT): |TDB-TT| < 2 ms and the
            # largest acceleration (geocenter, 6e-3 m/s^2) makes the
            # quadratic remainder < 1e-8 m — twelve decades below the
            # ~0.3 m that matters for ns timing.
            pv = bodies_fn(tt_f64, ("earth",) + body_names)
            earth_pos_tt, earth_vel = pv["earth"]
            topo_corr = ts.topocentric_einstein_s(earth_vel * C_M_S,
                                                  obs_gcrs_pos)
            topo_corr = jnp.where(is_bary | is_geo, 0.0, topo_corr)
            corr_s = ts.tdb_minus_tt(tt) + topo_corr
            tdb = dd.add(tt, corr_s / SECS_PER_DAY)
            tdb = DD(jnp.where(is_bary, utc.hi, tdb.hi),
                     jnp.where(is_bary, utc.lo, tdb.lo))
            earth_pos = earth_pos_tt + earth_vel * corr_s[:, None]
            planet_pv = {nm: (pv[nm][0] + pv[nm][1] * corr_s[:, None])
                         for nm in body_names}
        else:
            # generic provider without the batched hook: evaluate the
            # protocol methods at each timescale (reference structure)
            _earth_pos, earth_vel = eph.earth_posvel_ssb(tt_f64)
            topo_corr = ts.topocentric_einstein_s(earth_vel * C_M_S,
                                                  obs_gcrs_pos)
            topo_corr = jnp.where(is_bary | is_geo, 0.0, topo_corr)
            tdb = ts.tt_to_tdb(tt, topo_corr)
            tdb = DD(jnp.where(is_bary, utc.hi, tdb.hi),
                     jnp.where(is_bary, utc.lo, tdb.lo))
            tdb_f64 = tdb.hi + tdb.lo
            earth_pos, earth_vel = eph.earth_posvel_ssb(tdb_f64)
            planet_pv = {}
            for nm in body_names:
                p, _ = (eph.sun_posvel_ssb(tdb_f64) if nm == "sun"
                        else eph.planet_posvel_ssb(nm, tdb_f64))
                planet_pv[nm] = p

        obs_pos = earth_pos + obs_gcrs_pos / C_M_S  # GCRS m -> lt-s
        obs_vel = earth_vel + obs_gcrs_vel / C_M_S
        zero3 = jnp.zeros_like(obs_pos)
        bm, gm = is_bary[:, None], is_geo[:, None]
        obs_pos = jnp.where(bm, zero3, jnp.where(gm, earth_pos, obs_pos))
        obs_vel = jnp.where(bm, zero3, jnp.where(gm, earth_vel, obs_vel))
        planet_pos = {nm: p - obs_pos for nm, p in planet_pv.items()}
        return tdb, obs_pos, obs_vel, planet_pos

    fn = jax.jit(pipeline)
    _PIPELINE_JIT_CACHE.put_lru(key, (eph, fn))
    return fn


def build_TOAs_from_arrays(
    mjd_local: DD,
    *,
    freq_mhz,
    error_us,
    obs_index=None,
    obs_names: tuple = ("@",),
    flags: tuple | None = None,
    phase_offset=None,
    jump_group=None,
    eph: Ephemeris | str = "builtin_analytic",
    planets: bool = True,
    include_clock: bool = True,
    clock_limits: str = "warn",
    gcrs_pos_m=None,
    gcrs_vel_m_s=None,
) -> TOAs:
    """Array-based TOA construction (no per-TOA string parsing).

    The fast path for simulation and benchmarking at large N; the
    reference's equivalent is building ``pint.toa.TOA`` objects from
    arrays and running the same clock/TDB/posvel pipeline.
    """
    eph = get_ephemeris(eph) if isinstance(eph, str) else eph
    n = int(np.shape(np.asarray(mjd_local.hi))[0])
    if n == 0:
        # the power-of-two padding below repeats the LAST row, which
        # does not exist: x[-1:] on an empty array stays empty, so the
        # pipeline would silently compile a shape-0 program instead of
        # the intended bucket (and array-backed providers would see
        # empty inputs)
        raise ValueError("cannot build an empty TOA table (0 TOAs)")
    site_names = list(obs_names)
    obs_index = (np.zeros(n, dtype=np.int32) if obs_index is None
                 else np.asarray(obs_index, dtype=np.int32))
    flags = Flags({} for _ in range(n)) if flags is None else Flags(flags)
    if phase_offset is None:
        phase_offset = np.zeros(n)
    if jump_group is None:
        jump_group = np.zeros(n, dtype=np.int64)

    # clock chain to UTC (host-side numpy; per-site vectorized)
    clock_s = np.zeros(n)
    if include_clock:
        mjd_f64 = np.asarray(mjd_local.hi + mjd_local.lo)
        for si, sname in enumerate(site_names):
            sel = obs_index == si
            if not np.any(sel):
                continue
            ob = obs_mod.get_observatory(sname)
            if ob.is_special:
                continue
            clock_s[sel] = obs_mod.clock_corrections_s(sname, mjd_f64[sel], limits=clock_limits)
    utc = dd.add(mjd_local, jnp.asarray(clock_s) / ts.SECS_PER_DAY)

    # special-site handling
    is_bary = np.asarray(
        [obs_mod.get_observatory(s).is_barycenter for s in site_names]
    )[obs_index]
    is_geo = np.asarray(
        [obs_mod.get_observatory(s).is_geocenter for s in site_names]
    )[obs_index]

    # observatory ITRF -> GCRS (zeros for special sites)
    itrf = np.zeros((n, 3))
    for si, sname in enumerate(site_names):
        ob = obs_mod.get_observatory(sname)
        if ob.itrf_xyz_m is not None:
            itrf[obs_index == si] = np.asarray(ob.itrf_xyz_m)

    is_spacecraft = [obs_mod.get_observatory(s).is_special
                     and not obs_mod.get_observatory(s).is_barycenter
                     and not obs_mod.get_observatory(s).is_geocenter
                     for s in site_names]
    if any(is_spacecraft) and gcrs_pos_m is None:
        raise ValueError(
            "spacecraft observatory needs per-TOA GCRS positions: pass "
            "gcrs_pos_m (from pint_tpu.event_toas.load_orbit_file) — "
            "refusing to silently treat orbit TOAs as geocentric")

    if gcrs_pos_m is not None:
        # explicit GCRS offsets (spacecraft orbit data) replace the
        # ITRF-rotation path wholesale; they feed the topocentric
        # Einstein term exactly like a ground site's position
        if not all(is_spacecraft):
            raise ValueError(
                "gcrs_pos_m overrides every TOA's observatory position; "
                f"mixed sites {site_names} would be silently wrong — "
                "build spacecraft and ground TOAs separately and merge")
        gcrs_pos_m = np.asarray(gcrs_pos_m, dtype=np.float64)
        if gcrs_pos_m.shape != (n, 3):
            raise ValueError(
                f"gcrs_pos_m shape {gcrs_pos_m.shape} != ({n}, 3)")
        gp = jnp.asarray(gcrs_pos_m)
        gv = (jnp.zeros_like(gp) if gcrs_vel_m_s is None
              else jnp.asarray(gcrs_vel_m_s, jnp.float64))
    else:
        gp = jnp.zeros((n, 3))
        gv = jnp.zeros((n, 3))

    # coverage must be validated on CONCRETE times: inside the jitted
    # pipeline the ephemeris sees tracers and cannot raise (SPK kernels
    # would silently evaluate a divergent Chebyshev series out of span).
    # UTC -> TDB differs by ~minutes; 0.01 day of margin covers it.
    check_cov = getattr(eph, "check_coverage", None)
    if check_cov is not None and n:
        utc_f64 = np.asarray(utc.hi + utc.lo)
        check_cov(np.array([utc_f64.min() - 0.01, utc_f64.max() + 0.01]))

    # bucket the TOA axis (pad by repeating the last row): the pipeline
    # is elementwise over n, so padding is exact, and the whole suite /
    # a whole session compiles a bounded number of fused programs
    # instead of one per distinct TOA count. The size policy lives with
    # the fit-path bucketing in pint_tpu.bucketing (one home).
    from pint_tpu.bucketing import pipeline_bucket_size

    n_pad = pipeline_bucket_size(n)

    def _pad(x, fill=None):
        x = jnp.asarray(x)
        if n_pad == n:
            return x
        reps = jnp.repeat(x[-1:] if fill is None else fill, n_pad - n,
                          axis=0)
        return jnp.concatenate([x, reps], axis=0)

    pipeline = _astrometric_pipeline(eph, planets, gcrs_pos_m is not None)
    tdb, obs_pos, obs_vel, planet_pos = pipeline(
        DD(_pad(utc.hi), _pad(utc.lo)), _pad(itrf), _pad(is_bary),
        _pad(is_geo), _pad(gp), _pad(gv))
    tdb = DD(tdb.hi[:n], tdb.lo[:n])
    obs_pos, obs_vel = obs_pos[:n], obs_vel[:n]
    planet_pos = {k: v[:n] for k, v in planet_pos.items()}

    pulse_number = jnp.asarray(
        [float(f.get("pn", "nan")) for f in flags], jnp.float64
    )

    return TOAs(
        tdb=tdb,
        utc=utc,
        freq_mhz=jnp.asarray(freq_mhz, jnp.float64),
        error_us=jnp.asarray(error_us, jnp.float64),
        obs_pos_ls=obs_pos,
        obs_vel_c=obs_vel,
        phase_offset=jnp.asarray(phase_offset, jnp.float64),
        planet_pos_ls=planet_pos,
        pulse_number=pulse_number,
        obs_index=jnp.asarray(obs_index, jnp.int32),
        jump_group=jnp.asarray(np.asarray(jump_group), jnp.int32),
        obs_names=tuple(site_names),
        flags=flags,
        ephem_name=getattr(eph, "name", "custom"),
        clock_applied=include_clock,
    )


def write_TOA_file(toas: TOAs, path: str | None = None) -> str:
    """Serialize a TOAs table as a tempo2-format ``.tim`` file.

    Reference: ``pint.toa.TOAs.write_TOA_file`` (src/pint/toa.py). The
    site-local MJD is reconstructed by undoing the clock chain (evaluated
    at the corrected time — the clock rate is ~us/day, so the inversion
    error is femtoseconds); sites with no registered clock files round-trip
    exactly. Returns the text; writes it to `path` when given.
    """
    n = len(toas)
    utc_f64 = np.asarray(toas.utc.hi + toas.utc.lo)
    clock_s = np.zeros(n)
    if toas.clock_applied:
        obs_idx = np.asarray(toas.obs_index)
        for si, sname in enumerate(toas.obs_names):
            sel = obs_idx == si
            if not np.any(sel):
                continue
            ob = obs_mod.get_observatory(sname)
            if ob.is_special:
                continue
            clock_s[sel] = obs_mod.clock_corrections_s(sname, utc_f64[sel],
                                                       limits="warn")
    local = dd.sub(toas.utc, jnp.asarray(clock_s) / ts.SECS_PER_DAY)
    local = DD(np.asarray(local.hi), np.asarray(local.lo))  # host once, not per TOA

    freqs = np.asarray(toas.freq_mhz)
    errs = np.asarray(toas.error_us)
    obs_idx = np.asarray(toas.obs_index)
    lines = ["FORMAT 1"]
    for i in range(n):
        flags = dict(toas.flags[i])
        name = flags.pop("name", f"toa_{i}")
        mjd_str = dd.to_string(local[i], ndigits=20)
        entry = f"{name} {freqs[i]:.6f} {mjd_str} {errs[i]:.3f} {toas.obs_names[int(obs_idx[i])]}"
        for k, v in sorted(flags.items()):
            entry += f" -{k} {v}"
        lines.append(entry)
    text = "\n".join(lines) + "\n"
    if path is not None:
        with open(path, "w") as f:
            f.write(text)
    return text


def save_pickle(toas: TOAs, path: str) -> None:
    """Cache a TOAs table (reference: get_TOAs(..., usepickle=True))."""
    np.savez_compressed(
        path,
        tdb_hi=np.asarray(toas.tdb.hi), tdb_lo=np.asarray(toas.tdb.lo),
        utc_hi=np.asarray(toas.utc.hi), utc_lo=np.asarray(toas.utc.lo),
        freq_mhz=np.asarray(toas.freq_mhz), error_us=np.asarray(toas.error_us),
        obs_pos=np.asarray(toas.obs_pos_ls), obs_vel=np.asarray(toas.obs_vel_c),
        phase_offset=np.asarray(toas.phase_offset),
        pulse_number=np.asarray(toas.pulse_number),
        obs_index=np.asarray(toas.obs_index),
        obs_names=np.asarray(toas.obs_names, dtype=object),
        flags=np.asarray([repr(f) for f in toas.flags], dtype=object),
        jump_group=np.asarray(toas.jump_group),
        planet_names=np.asarray(list(toas.planet_pos_ls), dtype=object),
        **{f"planet_{k}": np.asarray(v) for k, v in toas.planet_pos_ls.items()},
        ephem_name=np.asarray(toas.ephem_name, dtype=object),
        clock_applied=np.asarray(toas.clock_applied),
    )


def load_pickle(path: str) -> TOAs:
    import ast

    z = np.load(path, allow_pickle=True)
    return TOAs(
        tdb=DD(jnp.asarray(z["tdb_hi"]), jnp.asarray(z["tdb_lo"])),
        utc=DD(jnp.asarray(z["utc_hi"]), jnp.asarray(z["utc_lo"])),
        freq_mhz=jnp.asarray(z["freq_mhz"]),
        error_us=jnp.asarray(z["error_us"]),
        obs_pos_ls=jnp.asarray(z["obs_pos"]),
        obs_vel_c=jnp.asarray(z["obs_vel"]),
        phase_offset=jnp.asarray(z["phase_offset"]),
        planet_pos_ls={str(k): jnp.asarray(z[f"planet_{k}"]) for k in z["planet_names"]},
        pulse_number=jnp.asarray(z["pulse_number"]),
        obs_index=jnp.asarray(z["obs_index"], jnp.int32),
        jump_group=jnp.asarray(z["jump_group"], jnp.int32),
        obs_names=tuple(str(s) for s in z["obs_names"]),
        flags=Flags(ast.literal_eval(str(f)) for f in z["flags"]),
        ephem_name=str(z["ephem_name"]),
        clock_applied=bool(z["clock_applied"]),
    )
