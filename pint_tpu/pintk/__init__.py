"""pintk: interactive timing GUI (reference: src/pint/pintk/).

The reference's Tk application splits into plk (residual plot panel),
paredit/timedit (model/TOA editors) and a Tk shell. Here the same
surface is two layers:

* :mod:`pint_tpu.pintk.controller` — a headless state machine holding
  (TOAs, model, fits, selection, random-model draws). Every GUI action
  is a plain method, unit-testable without a display, and all numerics
  go through the same jitted fitters the CLI uses.
* :mod:`pint_tpu.pintk.app` — the thin Tk + matplotlib view binding
  buttons/clicks to controller calls.

Run via the ``pintk`` console script.
"""

from pint_tpu.pintk.controller import PintkController  # noqa: F401


def main(argv=None) -> int:
    """Console entry point: ``pintk par tim``."""
    import argparse

    from pint_tpu.scripts import script_init

    parser = argparse.ArgumentParser(
        prog="pintk", description="Interactive pulsar-timing GUI")
    parser.add_argument("parfile")
    parser.add_argument("timfile")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    from pint_tpu.models import get_model_and_toas
    from pint_tpu.pintk.app import run_app

    model, toas = get_model_and_toas(args.parfile, args.timfile)
    return run_app(PintkController(toas, model))
