"""Headless pintk state machine (reference: pint.pintk.pulsar.Pulsar).

The reference wraps (par, tim) in a ``Pulsar`` object that the plk
widget drives; every GUI capability there is a method here:
prefit/postfit residuals, TOA selection and deletion, fit-flag toggles,
fitting the selection, random-model envelopes, orbital-phase x-axes,
and writing par/tim files. All numerics run through the package's
jitted fitters — the view layer (pint_tpu.pintk.app) only draws.
"""

from __future__ import annotations

import copy

import numpy as np

from pint_tpu.fitting import Fitter
from pint_tpu.models import get_model
from pint_tpu.residuals import Residuals
from pint_tpu.simulation import calculate_random_models
from pint_tpu.toas import write_TOA_file

X_AXES = ("mjd", "orbital phase", "serial", "day of year", "frequency")
Y_AXES = ("prefit", "postfit")


class PintkController:
    """Model/TOAs/fit state behind the pintk GUI."""

    def __init__(self, toas, model):
        self.all_toas = toas
        self.base_model = model
        self.model = copy.deepcopy(model)
        self.postfit_model = None
        self.fitter = None
        self.selected = np.ones(len(toas), dtype=bool)
        self.deleted = np.zeros(len(toas), dtype=bool)
        self.random_dphase = None
        self._prefit_cache = None
        self._postfit_cache = None
        self._avg_cache = {}

    # ---------------------------------------------------------------- state
    @property
    def n_active(self) -> int:
        return int((~self.deleted).sum())

    def active_toas(self):
        return self.all_toas.select(~self.deleted)

    def prefit_resids(self) -> Residuals:
        if self._prefit_cache is None:
            self._prefit_cache = Residuals(self.active_toas(), self.model)
        return self._prefit_cache

    def postfit_resids(self) -> Residuals | None:
        if self.postfit_model is None:
            return None
        if self._postfit_cache is None:
            self._postfit_cache = Residuals(self.active_toas(),
                                            self.postfit_model)
        return self._postfit_cache

    def _invalidate(self):
        self._prefit_cache = None
        self._postfit_cache = None
        self._avg_cache = {}

    # ------------------------------------------------------------ selection
    def select_range(self, mjd_lo: float, mjd_hi: float, *,
                     extend: bool = False) -> int:
        """Select active TOAs in [mjd_lo, mjd_hi]; returns count selected."""
        mjds = self.all_toas.get_mjds()
        box = (mjds >= mjd_lo) & (mjds <= mjd_hi) & (~self.deleted)
        self.selected = (self.selected | box) if extend else box
        return int(self.selected.sum())

    def select_all(self):
        self.selected = ~self.deleted

    def delete_selected(self) -> int:
        """Mark the selected TOAs deleted; returns how many remain."""
        self.deleted |= self.selected
        self.selected = np.zeros_like(self.selected)
        self.random_dphase = None  # envelope shape no longer matches
        self._invalidate()
        return self.n_active

    def undelete_all(self):
        self.deleted[:] = False
        self._invalidate()

    # ------------------------------------------------------------- fit flags
    def fit_flags(self) -> dict[str, bool]:
        """{param: free?} for every fittable numeric parameter."""
        return {p.name: not p.frozen for p in self.model.params.values()
                if p.is_numeric and p.fittable}

    def set_fit_flag(self, name: str, free: bool):
        self.model.params[name].frozen = not free
        if self.postfit_model is not None and name in self.postfit_model.params:
            self.postfit_model.params[name].frozen = not free

    # ------------------------------------------------------------------ fit
    def fit(self, maxiter: int = 4) -> dict:
        """Fit the active TOAs; the postfit model becomes the new prefit
        on the next call (like hitting Fit twice in the reference)."""
        start = self.postfit_model or self.model
        fit_model = copy.deepcopy(start)
        toas = self.active_toas()
        self.fitter = Fitter.auto(toas, fit_model)
        chi2 = self.fitter.fit_toas(maxiter=maxiter)
        self.postfit_model = fit_model
        self.random_dphase = None
        self._postfit_cache = None
        self._avg_cache.pop("postfit", None)
        return {"chi2": float(chi2), "dof": self.fitter.resids.dof,
                "wrms_us": self.fitter.resids.rms_weighted_s() * 1e6,
                "fitter": type(self.fitter).__name__}

    def reset(self):
        """Back to the as-loaded model; clears fits/deletions/selection."""
        self.model = copy.deepcopy(self.base_model)
        self.postfit_model = None
        self.fitter = None
        self.random_dphase = None
        self.undelete_all()
        self.select_all()

    # ---------------------------------------------------------- random models
    def random_models(self, n: int = 30, seed: int | None = 0) -> np.ndarray:
        """(n, n_active) time-envelope draws from the fit covariance [s]."""
        if self.fitter is None:
            raise ValueError("fit first: random models need a covariance")
        self.random_dphase = calculate_random_models(
            self.fitter, self.active_toas(), Nmodels=n, seed=seed,
            return_time=True)
        return self.random_dphase

    # ------------------------------------------------------------- plot data
    def x_data(self, axis: str = "mjd") -> tuple[np.ndarray, str]:
        """X values for the active TOAs + axis label."""
        toas = self.active_toas()
        mjds = toas.get_mjds()
        if axis == "mjd":
            return mjds, "MJD"
        if axis == "serial":
            return np.arange(mjds.size, dtype=float), "TOA number"
        if axis == "day of year":
            # true calendar day-of-year (the reference's seasonal view),
            # not a fold over the MJD epoch
            days = np.floor(mjds).astype(np.int64)
            dates = np.datetime64("1858-11-17") + days.astype("timedelta64[D]")
            year_start = dates.astype("datetime64[Y]").astype("datetime64[D]")
            doy = (dates - year_start).astype(np.float64) + 1.0 + (mjds - days)
            return doy, "Day of year"
        if axis == "frequency":
            return np.asarray(toas.freq_mhz), "Frequency (MHz)"
        if axis == "orbital phase":
            model = self.postfit_model or self.model
            comp = next((c for c in model.components
                         if getattr(c, "binary_model_name", None)), None)
            if comp is None:
                raise ValueError("model has no binary component")
            p = model.base_dd()
            name = "TASC" if "TASC" in model.params else "T0"
            epoch = p[name].hi + p[name].lo
            pb = p["PB"].hi + p["PB"].lo
            return ((mjds - epoch) / pb) % 1.0, "Orbital phase"
        raise ValueError(f"unknown x axis {axis!r}; have {X_AXES}")

    def _resids_for(self, which: str) -> Residuals:
        if which == "prefit":
            return self.prefit_resids()
        if which == "postfit":
            r = self.postfit_resids()
            if r is None:
                raise ValueError("no postfit model yet: fit first")
            return r
        raise ValueError(f"unknown y axis {which!r}; have {Y_AXES}")

    def y_data(self, which: str = "prefit") -> tuple[np.ndarray, np.ndarray, str]:
        """(residuals_us, errors_us, label) for the active TOAs."""
        r = self._resids_for(which)
        return (np.asarray(r.time_resids) * 1e6,
                np.asarray(r.get_errors_s()) * 1e6,
                f"{which} residual (us)")

    def averaged_y_data(self, which: str = "prefit"
                        ) -> tuple[np.ndarray, np.ndarray, np.ndarray, str]:
        """Epoch-averaged residuals (plk 'avg' view; Residuals.ecorr_average).

        Returns (mjds, residuals_us, errors_us, label).
        """
        r = self._resids_for(which)
        if which not in self._avg_cache:  # invalidated with the resids
            self._avg_cache[which] = r.ecorr_average()
        avg = self._avg_cache[which]
        return (avg["mjds"], avg["time_resids"] * 1e6,
                avg["errors"] * 1e6, f"avg {which} residual (us)")

    # ------------------------------------------------------------ text panes
    # (reference: pint.pintk.paredit / timedit — in-GUI par/tim text
    # editing round-tripping through the normal load paths)
    def get_par_text(self) -> str:
        """Editable par text of the current (pre-fit) model."""
        return self.model.as_parfile()

    def apply_par_text(self, text: str):
        """Replace the working model with one parsed from edited text.

        Round-trips through :func:`pint_tpu.models.get_model` — exactly
        what loading the file would do — so invalid edits raise before
        any state is touched.  Clears fit state (the old postfit model
        belongs to the old parameterization) but keeps TOA selection /
        deletion, like the reference's paredit Apply.
        """
        model = get_model(text)
        self.model = model
        self.base_model = copy.deepcopy(model)
        self.postfit_model = None
        self.fitter = None
        self.random_dphase = None
        self._invalidate()

    def get_tim_text(self) -> str:
        """Editable tempo2-format text of ALL loaded TOAs (incl. deleted)."""
        return write_TOA_file(self.all_toas)

    def apply_tim_text(self, text: str):
        """Replace the TOA table with one parsed from edited text.

        Round-trips through the normal tim pipeline (clock chain, TDB,
        posvels via :func:`pint_tpu.toas.get_TOAs`, with the model's
        ephemeris).  Selection and deletion reset — row identity is not
        preserved across an arbitrary text edit.
        """
        import os
        import tempfile

        from pint_tpu.toas import get_TOAs

        fd, path = tempfile.mkstemp(suffix=".tim")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(text)
            toas = get_TOAs(path, ephem=self.model.ephem)
        finally:
            os.unlink(path)
        self.all_toas = toas
        self.selected = np.ones(len(toas), dtype=bool)
        self.deleted = np.zeros(len(toas), dtype=bool)
        self.fitter = None
        self.postfit_model = None
        self.random_dphase = None
        self._invalidate()

    # ---------------------------------------------------------------- output
    def write_par(self, path: str) -> str:
        model = self.postfit_model or self.model
        text = model.as_parfile()
        with open(path, "w") as f:
            f.write(text)
        return text

    def write_tim(self, path: str):
        write_TOA_file(self.active_toas(), path)

    def summary(self) -> str:
        if self.fitter is not None:
            return self.fitter.get_summary()
        r = self.prefit_resids()
        return (f"{self.model.name}: {self.n_active} TOAs, prefit "
                f"wrms {r.rms_weighted_s() * 1e6:.3f} us, "
                f"chi2 {r.chi2:.2f} / dof {r.dof}")
