"""Tk + matplotlib view for pintk (reference: pint.pintk.plk/paredit).

Thin layer: every callback delegates to
:class:`pint_tpu.pintk.controller.PintkController`; no numerics live
here. Layout mirrors the reference's plk screen: residual plot with
error bars (prefit grey / postfit color), rubber-band box selection,
an x-axis selector, a parameter panel with fit checkboxes, and the
Fit / Reset / Random models / Write par / Write tim button row.
"""

from __future__ import annotations

import numpy as np


def run_app(ctrl) -> int:
    import tkinter as tk
    from tkinter import filedialog, messagebox, ttk

    import matplotlib
    matplotlib.use("TkAgg")
    from matplotlib.backends.backend_tkagg import FigureCanvasTkAgg
    from matplotlib.figure import Figure
    from matplotlib.widgets import RectangleSelector

    from pint_tpu.pintk.controller import X_AXES

    root = tk.Tk()
    root.title(f"pintk — {ctrl.model.name}")
    root.geometry("1100x700")

    fig = Figure(figsize=(8, 5), dpi=100)
    ax = fig.add_subplot(111)
    canvas = FigureCanvasTkAgg(fig, master=root)

    status = tk.StringVar(value=ctrl.summary())
    xaxis = tk.StringVar(value="mjd")
    show_random = tk.BooleanVar(value=False)
    show_avg = tk.BooleanVar(value=False)

    # ---------------------------------------------------------------- params
    side = ttk.Frame(root)
    ttk.Label(side, text="Fit parameters").pack(anchor="w")
    flags_frame = ttk.Frame(side)  # rebuilt wholesale after paredit Apply
    flags_frame.pack(anchor="w", fill="y")
    flag_vars: dict[str, tk.BooleanVar] = {}

    def on_flag(name):
        def cb():
            ctrl.set_fit_flag(name, flag_vars[name].get())
        return cb

    def _refresh_flags():
        for w in flags_frame.winfo_children():
            w.destroy()
        flag_vars.clear()
        for name, free in ctrl.fit_flags().items():
            v = tk.BooleanVar(value=free)
            flag_vars[name] = v
            ttk.Checkbutton(flags_frame, text=name, variable=v,
                            command=on_flag(name)).pack(anchor="w")

    _refresh_flags()

    # ------------------------------------------------------------------ plot
    def redraw():
        ax.clear()
        x, xlabel = ctrl.x_data(xaxis.get())
        y, e, ylabel = ctrl.y_data("prefit")
        ydisp = y  # whichever residuals are front-most for overlays
        ax.errorbar(x, y, yerr=e, fmt=".", color="0.6", label="prefit",
                    alpha=0.7)
        if ctrl.postfit_model is not None:
            yp, ep, _ = ctrl.y_data("postfit")
            ax.errorbar(x, yp, yerr=ep, fmt=".", color="C0", label="postfit")
            ylabel = "residual (us)"
            ydisp = yp
            if show_random.get() and ctrl.random_dphase is not None:
                order = np.argsort(x)
                for row in ctrl.random_dphase * 1e6:
                    ax.plot(x[order], (yp + row)[order], color="C1",
                            alpha=0.15, lw=0.6)
        if show_avg.get() and xaxis.get() == "mjd":
            which = "postfit" if ctrl.postfit_model is not None else "prefit"
            am, ay, ae, albl = ctrl.averaged_y_data(which)
            ax.errorbar(am, ay, yerr=ae, fmt="s", color="C2", ms=5,
                        label=albl, zorder=5)
        sel = ctrl.selected[~ctrl.deleted]
        if sel.any() and not sel.all():
            ax.plot(x[sel], ydisp[sel], "o", mfc="none", mec="C3", ms=9,
                    label="selected")
        ax.axhline(0.0, color="k", lw=0.5)
        ax.set_xlabel(xlabel)
        ax.set_ylabel(ylabel)
        ax.legend(loc="best", fontsize=8)
        canvas.draw_idle()

    def on_select_box(eclick, erelease):
        if xaxis.get() != "mjd":
            return
        lo, hi = sorted((eclick.xdata, erelease.xdata))
        n = ctrl.select_range(lo, hi)
        status.set(f"selected {n} TOAs")
        redraw()

    selector = RectangleSelector(ax, on_select_box, useblit=True, button=[1],
                                 minspanx=1e-6, spancoords="data")

    # --------------------------------------------------------------- actions
    def do_fit():
        try:
            info = ctrl.fit()
        except Exception as exc:  # surface fit errors in the GUI
            messagebox.showerror("fit failed", str(exc))
            return
        status.set(f"{info['fitter']}: chi2 {info['chi2']:.2f} / "
                   f"dof {info['dof']} — wrms {info['wrms_us']:.3f} us")
        redraw()

    def do_reset():
        ctrl.reset()
        for name, v in flag_vars.items():
            v.set(not ctrl.model.params[name].frozen)
        status.set(ctrl.summary())
        redraw()

    def do_random():
        if ctrl.fitter is None:
            messagebox.showinfo("random models", "fit first")
            return
        ctrl.random_models(30)
        show_random.set(True)
        redraw()

    def do_delete():
        n = ctrl.delete_selected()
        status.set(f"{n} TOAs remain")
        redraw()

    def do_write_par():
        path = filedialog.asksaveasfilename(defaultextension=".par")
        if path:
            ctrl.write_par(path)
            status.set(f"wrote {path}")

    def do_write_tim():
        path = filedialog.asksaveasfilename(defaultextension=".tim")
        if path:
            ctrl.write_tim(path)
            status.set(f"wrote {path}")

    # ------------------------------------------------------- editor panes
    # (reference: pint.pintk.paredit / timedit — a text editor window
    # whose Apply round-trips through the normal par/tim load paths)
    def _editor(title, get_text, apply_text, after_apply):
        win = tk.Toplevel(root)
        win.title(f"{title} — {ctrl.model.name}")
        win.geometry("700x600")
        txt = tk.Text(win, wrap="none", undo=True)
        txt.insert("1.0", get_text())

        def on_apply():
            try:
                apply_text(txt.get("1.0", "end-1c"))
            except Exception as exc:  # invalid edit: model/TOAs untouched
                messagebox.showerror(f"{title}: apply failed", str(exc),
                                     parent=win)
                return
            after_apply()
            status.set(f"{title} applied")
            redraw()

        def on_reload():
            txt.delete("1.0", "end")
            txt.insert("1.0", get_text())

        def on_open():
            path = filedialog.askopenfilename(parent=win)
            if not path:
                return
            try:
                with open(path) as f:
                    content = f.read()
            except (OSError, UnicodeDecodeError) as exc:
                messagebox.showerror(f"{title}: open failed", str(exc),
                                     parent=win)
                return
            txt.delete("1.0", "end")
            txt.insert("1.0", content)

        ebar = ttk.Frame(win)
        for label, cmd in (("Apply", on_apply), ("Reload", on_reload),
                           ("Open...", on_open)):
            ttk.Button(ebar, text=label, command=cmd).pack(side="left",
                                                           padx=2)
        ebar.pack(side="top", fill="x")
        txt.pack(side="top", fill="both", expand=True)

    def do_edit_par():
        _editor("paredit", ctrl.get_par_text, ctrl.apply_par_text,
                _refresh_flags)

    def do_edit_tim():
        _editor("timedit", ctrl.get_tim_text, ctrl.apply_tim_text,
                lambda: None)

    bar = ttk.Frame(root)
    for text, cmd in (("Fit", do_fit), ("Reset", do_reset),
                      ("Random models", do_random),
                      ("Delete selected", do_delete),
                      ("Write par", do_write_par), ("Write tim", do_write_tim),
                      ("Edit par", do_edit_par), ("Edit tim", do_edit_tim)):
        ttk.Button(bar, text=text, command=cmd).pack(side="left", padx=2)
    ttk.Checkbutton(bar, text="Avg", variable=show_avg,
                    command=redraw).pack(side="left", padx=4)
    ttk.Label(bar, text="  x:").pack(side="left")
    opt = ttk.Combobox(bar, textvariable=xaxis, values=list(X_AXES), width=13,
                       state="readonly")
    opt.bind("<<ComboboxSelected>>", lambda e: redraw())
    opt.pack(side="left")

    bar.pack(side="top", fill="x")
    side.pack(side="right", fill="y", padx=4)
    canvas.get_tk_widget().pack(side="top", fill="both", expand=True)
    ttk.Label(root, textvariable=status, anchor="w").pack(side="bottom",
                                                          fill="x")
    redraw()
    root.mainloop()
    # keep the selector alive for the mainloop's duration
    del selector
    return 0
