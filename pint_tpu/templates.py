"""Pulse-profile templates and photon-event likelihood/fitting.

Reference equivalents: ``pint.templates`` (lctemplate.py/lcprimitives.py
/lcfitters.py — Gaussian-component light-curve templates with unbinned
likelihood), the ``photonphase`` phase-assignment + H-test path, and
``pint.scripts.event_optimize`` (MCMC of timing parameters against the
template likelihood). TPU-first differences:

* the template pdf is a pure jittable function of (params, phases);
  template fitting is an ``optax.adam`` loop under ``lax.scan`` in an
  unconstrained parametrization (softmax norms, softplus widths) — one
  XLA program instead of scipy minimize;
* the event-timing MCMC vmaps the Kerr (2011) weighted photon
  likelihood sum(log(w f(phi) + 1 - w)) over walkers through the same
  jitted phase function the fitters use (pint_tpu.sampler).
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array
# numpy (not jnp): module-level device arrays initialize the backend at
# import; converted to a constant at trace time
_WRAPS = np.arange(-3.0, 4.0)  # alias sum covers widths up to ~0.3 cycles


def wrapped_gaussian_pdf(phases: Array, loc: Array, width: Array) -> Array:
    """Periodic (wrapped) normal density on [0, 1).

    Returns shape ``phases.shape + loc.shape`` for 1-D ``loc``/``width``
    (one density column per component), or ``phases.shape`` for scalars.
    """
    scalar = np.ndim(loc) == 0
    loc = jnp.atleast_1d(loc)
    width = jnp.atleast_1d(width)
    # (..., k, wraps): alias sum over the wrap axis, per component
    d = phases[..., None, None] - loc[:, None] - _WRAPS[None, :]
    z = d / width[:, None]
    g = jnp.exp(-0.5 * jnp.square(z)) / (width[:, None]
                                         * jnp.sqrt(2.0 * jnp.pi))
    out = jnp.sum(g, axis=-1)
    return out[..., 0] if scalar else out


def template_pdf(params: dict[str, Array], phases: Array) -> Array:
    """Normalized profile: uniform background + Gaussian peaks.

    params: ``loc`` (k,) peak phases, ``width`` (k,) sigmas [cycles],
    ``norm`` (k,) component weights with sum <= 1 (remainder = DC).
    """
    loc = jnp.atleast_1d(params["loc"])
    width = jnp.atleast_1d(params["width"])
    norm = jnp.atleast_1d(params["norm"])
    peaks = wrapped_gaussian_pdf(phases, loc, width)  # (..., k)
    return (1.0 - jnp.sum(norm)) + jnp.sum(norm * peaks, axis=-1)


def unbinned_log_likelihood(params: dict[str, Array], phases: Array,
                            weights: Array | None = None) -> Array:
    """Kerr (2011) weighted unbinned likelihood of a photon phase set."""
    f = template_pdf(params, phases)
    if weights is None:
        return jnp.sum(jnp.log(jnp.maximum(f, 1e-300)))
    return jnp.sum(jnp.log(jnp.maximum(weights * f + (1.0 - weights), 1e-300)))


@dataclasses.dataclass
class LCTemplate:
    """Host-side template object (reference: pint.templates.LCTemplate)."""

    locs: np.ndarray
    widths: np.ndarray
    norms: np.ndarray

    def __post_init__(self):
        self.locs = np.atleast_1d(np.asarray(self.locs, np.float64)) % 1.0
        self.widths = np.atleast_1d(np.asarray(self.widths, np.float64))
        self.norms = np.atleast_1d(np.asarray(self.norms, np.float64))
        if not (self.locs.shape == self.widths.shape == self.norms.shape):
            raise ValueError("locs/widths/norms must have matching shapes")
        if self.norms.sum() > 1.0 + 1e-9:
            raise ValueError("component norms must sum to <= 1")

    @property
    def params(self) -> dict[str, Array]:
        return {"loc": jnp.asarray(self.locs),
                "width": jnp.asarray(self.widths),
                "norm": jnp.asarray(self.norms)}

    def __call__(self, phases) -> np.ndarray:
        return np.asarray(template_pdf(self.params, jnp.asarray(phases)))

    def log_likelihood(self, phases, weights=None) -> float:
        w = None if weights is None else jnp.asarray(weights)
        return float(unbinned_log_likelihood(self.params,
                                             jnp.asarray(phases), w))


# ---------------------------------------------------------------------------
# template fitting (reference: pint.templates.lcfitters.LCFitter)
# ---------------------------------------------------------------------------

def _unconstrain(t: LCTemplate) -> dict[str, Array]:
    k = t.locs.size
    total = min(float(t.norms.sum()), 1.0 - 1e-6)
    frac = t.norms / max(t.norms.sum(), 1e-12)
    return {
        "loc": jnp.asarray(t.locs),
        "log_width": jnp.log(jnp.asarray(t.widths)),
        "logit_total": jnp.asarray(np.log(total / (1.0 - total))),
        "log_frac": jnp.log(jnp.asarray(frac) + 1e-12) if k > 1
        else jnp.zeros(1),
    }


def _constrain(u: dict[str, Array]) -> dict[str, Array]:
    total = jax.nn.sigmoid(u["logit_total"])
    frac = jax.nn.softmax(u["log_frac"])
    return {"loc": u["loc"] % 1.0,
            "width": jnp.exp(u["log_width"]),
            "norm": total * frac}


def fit_template(phases, template: LCTemplate, *, weights=None,
                 steps: int = 1000, learning_rate: float = 3e-3
                 ) -> tuple[LCTemplate, float]:
    """Maximum-likelihood template fit via Adam under one jitted scan.

    Returns (fitted template, final log-likelihood). The reference
    minimizes with scipy (lcfitters.LCFitter.fit); here the whole
    optimization is a single XLA program.
    """
    import optax

    phases = jnp.asarray(phases)
    w = None if weights is None else jnp.asarray(weights)
    opt = optax.adam(learning_rate)

    def loss(u):
        return -unbinned_log_likelihood(_constrain(u), phases, w)

    u0 = _unconstrain(template)
    state0 = opt.init(u0)

    @jax.jit
    def run(u, state):
        def step(carry, _):
            u, state = carry
            g = jax.grad(loss)(u)
            updates, state = opt.update(g, state)
            return (optax.apply_updates(u, updates), state), None

        (u, state), _ = jax.lax.scan(step, (u, state), None, length=steps)
        return u, -loss(u)

    u, lnl = run(u0, state0)
    p = _constrain(u)
    fitted = LCTemplate(np.asarray(p["loc"]), np.asarray(p["width"]),
                        np.asarray(p["norm"]))
    return fitted, float(lnl)


# ---------------------------------------------------------------------------
# phase assignment + H-test (reference: photonphase / pint.stats hm)
# ---------------------------------------------------------------------------

def photon_phases(model, toas) -> np.ndarray:
    """Absolute model phase of each photon, folded to [0, 1)."""
    ph = model.phase_fn(toas)(model.base_dd(), {})
    frac = np.asarray(ph.frac.hi + ph.frac.lo)
    return frac % 1.0


def h_test(phases, weights=None, max_harmonics: int = 20) -> tuple[float, float]:
    """de Jager et al. (1989) H statistic and its false-alarm probability.

    H = max_m (sum_{k<=m} 2n |a_k|^2 - 4(m-1)); P ~ exp(-0.4 H)
    (de Jager & Busching 2010). Weighted variant per Kerr (2011).
    """
    phases = jnp.asarray(phases)
    w = jnp.ones_like(phases) if weights is None else jnp.asarray(weights)
    k = jnp.arange(1, max_harmonics + 1)
    arg = 2.0 * jnp.pi * k[:, None] * phases[None, :]
    c = jnp.sum(w[None, :] * jnp.cos(arg), axis=1)
    s = jnp.sum(w[None, :] * jnp.sin(arg), axis=1)
    z2 = 2.0 * jnp.cumsum(jnp.square(c) + jnp.square(s)) / jnp.sum(jnp.square(w))
    h = jnp.max(z2 - 4.0 * (k - 1.0))
    hval = float(h)
    return hval, float(np.exp(-0.4 * hval))


# ---------------------------------------------------------------------------
# event-timing MCMC (reference: pint.scripts.event_optimize)
# ---------------------------------------------------------------------------

class EventFitter:
    """Sample timing parameters against the photon-template likelihood.

    The likelihood is sum log(w f(phi_i) + 1 - w) with phi from the
    jitted phase function at offset parameters; the stretch-move
    ensemble (pint_tpu.sampler) explores the posterior. Priors default
    to the same uniform bands pint_tpu.bayesian uses.
    """

    def __init__(self, toas, model, template: LCTemplate, *,
                 priors: dict | None = None, weights=None):
        from pint_tpu.bayesian import default_priors
        from pint_tpu.event_toas import get_photon_weights

        self.toas = toas
        self.model = model
        self.template = template
        self.fit_params = list(model.free_params)
        self.priors = dict(default_priors(model))
        if priors:
            self.priors.update(priors)
        if weights is None:
            weights = get_photon_weights(toas)
        self._w = None if weights is None else jnp.asarray(weights)

        base = model.base_dd()
        hi = {k: float(base[k].hi) for k in self.fit_params}
        lo = {k: float(base[k].lo) for k in self.fit_params}
        phase_fn = model.phase_fn(toas, abs_phase=True)
        tparams = template.params
        prior_fns = [(j, self.priors[k])
                     for j, k in enumerate(self.fit_params)]

        def lnpost(x):
            lp = jnp.zeros(())
            for j, pr in prior_fns:
                lp = lp + pr.log_pdf(x[j])
            deltas = {k: (x[j] - hi[k]) - lo[k]
                      for j, k in enumerate(self.fit_params)}
            ph = phase_fn(base, deltas)
            phi = (ph.frac.hi + ph.frac.lo) % 1.0
            ll = unbinned_log_likelihood(tparams, phi, self._w)
            return jnp.where(jnp.isfinite(lp), lp + ll, -jnp.inf)

        self._lnpost = jax.jit(lnpost)
        self.chain: np.ndarray | None = None

    def fit_toas(self, nsteps: int = 500, *, nwalkers: int | None = None,
                 seed: int = 0, burn_frac: float = 0.25) -> float:
        from pint_tpu.sampler import initialize_walkers, run_ensemble

        nd = len(self.fit_params)
        nw = nwalkers or max(2 * nd + 2, 16)
        nw += nw % 2
        center = np.asarray([self.model.params[k].value_f64
                             for k in self.fit_params])
        scale = np.asarray([
            (self.model.params[k].uncertainty or 0.0)
            or self.priors[k].width() * 0.1 for k in self.fit_params])
        p0 = initialize_walkers(center, scale, nw, seed=seed)
        out = run_ensemble(self._lnpost, p0, nsteps, seed=seed)
        burn = int(nsteps * burn_frac)
        chain = out["chain"][burn:].reshape(-1, nd)
        self.chain = chain
        # report the maximum-posterior sample (event_optimize convention)
        lp = out["log_prob"][burn:].reshape(-1)
        best = chain[np.argmax(lp)]
        for j, k in enumerate(self.fit_params):
            p = self.model.params[k]
            p.add_delta(float(best[j]) - p.value_f64)
            p.uncertainty = float(chain[:, j].std())
        return float(lp.max())
