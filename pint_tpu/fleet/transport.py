"""Transport seam between the fleet router and per-host schedulers.

The router (:mod:`pint_tpu.fleet.router`) never talks to a
:class:`~pint_tpu.serve.scheduler.ThroughputScheduler` directly — it
talks to a *host transport*, a small duck-typed surface
(:class:`LoopbackHost` documents it) with exactly the operations the
routing tier needs:

* ``submit(request) -> token`` — enqueue one fit/read on the host,
  returning an opaque per-host token;
* ``drain() -> [wire results]`` / ``drain_reads() -> [wire reads]`` —
  resolve everything queued since the last drain;
* ``predict(request) -> wire read`` — the synchronous read fast lane
  (never behind the host's fit queue — the worker serves it as its own
  op, not as part of a drain);
* ``report() -> dict`` — the host's health surface
  (:meth:`ThroughputScheduler.report`): queue depth, fail streak,
  degraded flag, program-cache misses. The router's per-host health
  state is fed ONLY from these reports plus transport-level failures.

Two implementations:

:class:`LoopbackHost` wraps an in-process scheduler — N "hosts" in one
process, zero network, zero serialization (results are the scheduler's
own objects; the caller's model is mutated in place exactly as in
single-host serving). Tests, ``bench --smoke`` and the soak fleet axis
run on loopback, so every routing invariant is provable without
silicon or sockets.

:class:`TcpHost` speaks a line-oriented JSONL protocol to a real
worker process (:mod:`pint_tpu.fleet.worker`): one JSON object per
line, ``{"op": ..., "payload": <base64 pickle>}`` requests and
``{"ok": ..., ...}`` responses. Payloads (TOA tables, models, results)
are pickled — the fleet protocol is for a TRUSTED pod-internal
network, like any jax.distributed coordinator traffic, never an
internet-facing surface. Because a remote worker fits a *copy* of the
request, fitted parameter values come back in the wire result
(``params``: name -> (hi, lo, uncertainty) double-double parts, exact)
and the router writes them onto the caller's model — the same
in-place contract the loopback path gets for free.

A dead socket raises :class:`HostDown` — the router's signal to mark
the host dead and re-route its pending work (failover), never an
exception surfaced to a submit caller.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import pickle
import socket
import threading
import time

from pint_tpu import telemetry


class HostDown(ConnectionError):
    """The transport lost the host (refused/reset/closed socket or an
    explicitly killed loopback). The router catches this everywhere a
    transport is touched and fails over; it never reaches a caller."""


class HostSuspect(ConnectionError):
    """A transport operation TIMED OUT — the host may be hung,
    partitioned, or merely slow, but it is not provably dead (ISSUE
    13). Distinct from :class:`HostDown` on purpose: one miss feeds
    the router's suspicion ladder (suspect -> degraded -> dead after
    ``dead_after`` consecutive misses) instead of immediately
    declaring a corpse, and the work routed away from a suspect host
    is *fenced* — if the host comes back, its late replies are
    rejected at the router rather than double-committed."""

    def __init__(self, host_id: str = "", op: str = "",
                 deadline_s: float | None = None, detail: str = ""):
        self.host_id = host_id
        self.op = op
        self.deadline_s = deadline_s
        msg = f"host {host_id} missed the {op or 'op'} deadline"
        if deadline_s is not None:
            msg += f" ({deadline_s:g}s)"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


def _b64(obj) -> str:
    return base64.b64encode(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)).decode()


def _unb64(s: str):
    return pickle.loads(base64.b64decode(s.encode()))


def wire_fit_result(token, res) -> dict:
    """Slim wire form of one FitResult: everything the router needs to
    rebuild the envelope against the CALLER's request object, without
    shipping the TOA table back. ``params`` carries the fitted values
    as exact (hi, lo) double-double parts plus uncertainties — only for
    results whose status writes back (:attr:`FitResult.fitted`)."""
    params = None
    if res.fitted and res.request.model is not None:
        m = res.request.model
        params = {k: (m[k].hi, m[k].lo, m[k].uncertainty)
                  for k in m.free_params}
    return {"token": token, "status": res.status, "chi2": res.chi2,
            "converged": res.converged, "error": res.error,
            "attempts": res.attempts, "retry_after_s": res.retry_after_s,
            "session": res.session, "passthrough": res.passthrough,
            "queue_latency_s": res.queue_latency_s, "group": res.group,
            "batch": res.batch, "n_members": res.n_members,
            "occupancy": res.occupancy, "host": res.host,
            "injected": res.injected, "trace": res.trace,
            "trace_ctx": telemetry.trace.wire(res.trace_ctx),
            "params": params}


def wire_read_result(res) -> dict:
    """Wire form of one PredictResult (arrays ride the pickle)."""
    return {"status": res.status, "phase_int": res.phase_int,
            "phase_frac": res.phase_frac, "freq_hz": res.freq_hz,
            "source": res.source, "cache_hit": res.cache_hit,
            "n_queries": res.n_queries, "latency_s": res.latency_s,
            "error": res.error, "host": res.host,
            "trace_ctx": telemetry.trace.wire(res.trace_ctx)}


# ----------------------------------------------------------------------
# loopback: N hosts in one process (tests / bench / soak)
# ----------------------------------------------------------------------

class LoopbackHost:
    """In-process host: a scheduler behind the transport surface.

    ``kill()`` simulates a host crash for failover tests — every later
    operation raises :class:`HostDown`, exactly what a dead TCP socket
    surfaces, so the router's failover path is transport-agnostic.

    Partition chaos (ISSUE 13 — the soak ``--partition`` axis and the
    fencing tests drive these): ``hang()`` makes every operation raise
    :class:`HostSuspect` (a SIGSTOP-shaped host: alive, unresponsive,
    state intact) until ``resume()``; ``delay_ops(n)`` times out the
    next ``n`` operations then self-heals (a transiently slow peer);
    ``duplicate_delivery(True)`` returns every drained wire result
    twice (an at-least-once network) — the router must dedup, never
    double-commit.
    """

    kind = "loopback"

    def __init__(self, host_id: str, scheduler=None, **sched_kwargs):
        from pint_tpu.serve.scheduler import ThroughputScheduler

        self.host_id = host_id
        self.scheduler = (scheduler if scheduler is not None
                          else ThroughputScheduler(host_id=host_id,
                                                   **sched_kwargs))
        if not self.scheduler.host_id:
            self.scheduler.host_id = host_id
        self._tokens = itertools.count()
        self._pending: list[tuple[int, object]] = []       # (token, handle)
        self._pending_reads: list[tuple[int, object]] = []
        self._dead = False
        self._hung = False
        self._delay_ops = 0
        self._duplicate = False

    def _check(self, op: str = "op", deadline_s=None):
        if self._dead:
            raise HostDown(f"loopback host {self.host_id} was killed")
        if self._hung:
            raise HostSuspect(self.host_id, op, deadline_s,
                              "host is hung (simulated partition)")
        if self._delay_ops > 0:
            self._delay_ops -= 1
            raise HostSuspect(self.host_id, op, deadline_s,
                              "reply delayed past the deadline "
                              "(simulated)")

    def kill(self) -> None:
        """Simulate a crashed host (failover tests / soak host-kill)."""
        self._dead = True

    def hang(self) -> None:
        """Simulate a partitioned/SIGSTOPped host: alive but every op
        times out; queued work and session state stay intact."""
        self._hung = True

    def resume(self) -> None:
        self._hung = False

    def delay_ops(self, n: int) -> None:
        """Time out the next ``n`` operations, then heal."""
        self._delay_ops = max(0, int(n))

    def duplicate_delivery(self, on: bool = True) -> None:
        self._duplicate = bool(on)

    def alive(self) -> bool:
        return not self._dead

    def ping(self, deadline_s=None) -> dict:
        self._check("ping", deadline_s)
        return {"ok": True, "host": self.host_id, "t": time.time()}

    def submit(self, request) -> int:
        from pint_tpu.serve.scheduler import PredictRequest

        self._check("submit", getattr(request, "deadline_s", None))
        token = next(self._tokens)
        handle = self.scheduler.submit(request)
        if isinstance(request, PredictRequest):
            self._pending_reads.append((token, handle))
        else:
            self._pending.append((token, handle))
        return token

    def _dup(self, out: list[dict]) -> list[dict]:
        if self._duplicate and out:
            return out + [dict(w) for w in out]
        return out

    def drain(self, deadline_s=None) -> list[dict]:
        self._check("drain", deadline_s)
        # catalog slices advance through the router's OWN
        # advance_catalog op (slow-path deadline), never inside the
        # fit-drain RPC (see ThroughputScheduler.drain)
        self.scheduler.drain(advance_catalog=False)
        out = [{"token": t, "result": h.result()}
               for t, h in self._pending]
        self._pending = []
        return self._dup(out)

    def drain_reads(self, deadline_s=None) -> list[dict]:
        self._check("drain_reads", deadline_s)
        self.scheduler.drain_reads()
        out = [{"token": t, "result": h.result()}
               for t, h in self._pending_reads]
        self._pending_reads = []
        return self._dup(out)

    def predict(self, request) -> dict:
        self._check("predict", getattr(request, "deadline_s", None))
        return {"result": self.scheduler.predict(request)}

    def report(self) -> dict:
        self._check("report")
        return self.scheduler.report()

    def metrics(self, deadline_s=None) -> dict:
        """The live-plane snapshot op (ISSUE 19)."""
        self._check("metrics", deadline_s)
        return self.scheduler.metrics_snapshot()

    # -- program supply chain (ISSUE 16) -------------------------------
    def pull_programs(self, fp8s, deadline_s=None) -> dict:
        """Export this host's shipment for the given fp8 set (AOT
        blobs + XLA cache entries + warm keys); empty with no store."""
        self._check("pull_programs", deadline_s)
        from pint_tpu.programs.ship import export_for_ship

        return export_for_ship(fp8s)

    def ship_programs(self, shipment, deadline_s=None) -> dict:
        """Install a shipment into this host's store (prewarm/adopt)."""
        self._check("ship_programs", deadline_s)
        from pint_tpu.programs.ship import adopt_shipment

        return adopt_shipment(shipment)

    # -- durable sessions (ISSUE 13) -----------------------------------
    def session_summary(self, skey) -> dict | None:
        self._check("session_summary")
        return self.scheduler.session_summary(skey)

    def stash_replica(self, skey, blob: dict) -> None:
        self._check("stash_replica")
        self.scheduler.stash_replica(skey, blob)

    def adopt_session(self, skey, toas, replica=None,
                      deadline_s=None) -> dict:
        self._check("adopt_session", deadline_s)
        return self.scheduler.adopt_session(skey, toas, replica=replica)

    def drop_session(self, session_id, deadline_s=None) -> None:
        """Forget any entry this host holds for ``session_id`` —
        the router calls it on a restore target before rebuilding:
        an entry there is by definition an orphan of an
        unacknowledged (fenced) commit, and a replayed populate must
        never MERGE into it (the duplicate-populate corruption of the
        at-least-once retry path)."""
        self._check("drop_session", deadline_s)
        self.scheduler.sessions.drop(session_id)

    def replay(self, requests, deadline_s=None) -> list[dict]:
        """Run journal-replay requests to completion in ONE host-side
        step (submit + drain inside the op): the router's restore path
        never touches this host's transport-pending bookkeeping, and
        co-queued work simply resolves early — its wire results still
        deliver at the next ``drain`` op."""
        self._check("replay", deadline_s)
        handles = [self.scheduler.submit(r) for r in requests]
        self.scheduler.drain(advance_catalog=False)
        return [{"status": h.result().status, "chi2": h.result().chi2,
                 "session": h.result().session}
                for h in handles]

    # -- catalog long jobs (ISSUE 14) ----------------------------------
    def submit_catalog(self, request, deadline_s=None) -> str:
        self._check("submit_catalog", deadline_s)
        return self.scheduler.submit_catalog(request).job_id

    def adopt_catalog(self, checkpoint, deadline_s=None) -> str:
        """Resume a checkpointed catalog job on this host (failover)."""
        self._check("adopt_catalog", deadline_s)
        return self.scheduler.adopt_catalog(checkpoint).job_id

    def advance_catalog(self, job_id, budget_s=None,
                        deadline_s=None) -> dict:
        """One slice + the refreshed checkpoint: the router calls this
        per drain and stashes the checkpoint so a later host death
        resumes from the last slice instead of restarting."""
        self._check("advance_catalog", deadline_s)
        job = self.scheduler.catalog_jobs.get(job_id)
        if job is None:
            raise KeyError(f"unknown catalog job {job_id!r}")
        if job.state not in ("done", "failed"):
            job.advance(budget_s)
        return {"progress": job.progress(),
                "checkpoint": self.scheduler.catalog_checkpoint(job_id)}

    def catalog_progress(self, job_id, deadline_s=None) -> dict | None:
        self._check("catalog_progress", deadline_s)
        return self.scheduler.catalog_progress(job_id)

    def close(self) -> None:
        self._dead = True


# ----------------------------------------------------------------------
# TCP/JSONL: a real worker process behind a socket
# ----------------------------------------------------------------------

class TcpHost:
    """JSONL client for one :mod:`pint_tpu.fleet.worker` process.

    Liveness above the socket (ISSUE 13): every RPC runs under a
    per-operation deadline — the request's own ``deadline_s`` when it
    carries one, else ``op_deadline_s`` (default from
    ``PINT_TPU_FLEET_OP_DEADLINE_S``, 60 s) — instead of the old flat
    600 s socket timeout. A deadline miss raises
    :class:`HostSuspect` (the peer accepted the connection but never
    replied: hung/partitioned, not provably dead) and drops the now
    desynchronized connection; a refused/reset/closed socket is still
    :class:`HostDown`. ``timeout_s`` survives as the absolute ceiling
    no deadline may exceed."""

    kind = "tcp"

    def __init__(self, host_id: str, address: tuple[str, int],
                 timeout_s: float = 600.0,
                 op_deadline_s: float | None = None):
        self.host_id = host_id
        self.address = tuple(address)
        self.timeout_s = timeout_s
        self.op_deadline_s = op_deadline_s
        self._sock = None
        self._fh = None
        # at-least-once drain delivery: the highest drain sequence
        # number whose reply this client has SEEN, echoed back as the
        # ``ack`` of the next drain op — the worker redelivers
        # anything newer (a reply lost with a dead connection)
        self._drain_ack = -1

    def _deadline(self, deadline_s=None) -> float:
        from pint_tpu.fleet.durability import op_deadline_s

        d = deadline_s
        if d is None:
            d = (self.op_deadline_s if self.op_deadline_s is not None
                 else op_deadline_s())
        return max(0.05, min(float(d), self.timeout_s))

    def _connect(self, deadline: float):
        if self._sock is not None:
            return
        try:
            self._sock = socket.create_connection(
                self.address, timeout=min(10.0, deadline))
            self._fh = self._sock.makefile("rwb")
        except socket.timeout as e:
            self._sock = self._fh = None
            raise HostSuspect(self.host_id, "connect", deadline,
                              str(e)) from e
        except OSError as e:
            self._sock = self._fh = None
            raise HostDown(
                f"host {self.host_id} at {self.address}: {e}") from e

    def _rpc(self, op: str, payload=None, deadline_s=None,
             **fields) -> dict:
        deadline = self._deadline(deadline_s)
        self._connect(deadline)
        msg = {"op": op, **fields}
        if payload is not None:
            msg["payload"] = _b64(payload)
        try:
            self._sock.settimeout(deadline)
            self._fh.write((json.dumps(msg) + "\n").encode())
            self._fh.flush()
            line = self._fh.readline()
        except socket.timeout as e:
            # the peer holds the connection but missed the deadline: a
            # hung/partitioned host. The stream is desynchronized (a
            # late reply would answer the WRONG request) — drop it; a
            # recovered host gets a fresh connection
            self.close()
            raise HostSuspect(self.host_id, op, deadline, str(e)) from e
        except OSError as e:
            self.close()
            raise HostDown(
                f"host {self.host_id} at {self.address}: {e}") from e
        if not line:
            self.close()
            raise HostDown(f"host {self.host_id} at {self.address}: "
                           "connection closed")
        resp = json.loads(line)
        if not resp.get("ok"):
            # a structured application error (bad request, backpressure)
            # — the host is alive; re-raise the typed error router-side
            et = resp.get("error_type", "RuntimeError")
            if et == "ServeQueueFull":
                from pint_tpu.serve.scheduler import ServeQueueFull

                a = resp.get("attrs", {})
                raise ServeQueueFull(**a)
            raise RuntimeError(f"host {self.host_id}: "
                               f"{et}: {resp.get('error')}")
        return resp

    def ping(self, deadline_s=None) -> dict:
        return self._rpc("ping", deadline_s=deadline_s)

    def alive(self) -> bool:
        try:
            self.ping()
            return True
        except (HostDown, HostSuspect, OSError):
            return False

    def submit(self, request) -> int:
        # the request's own SLA rides the wire as the socket deadline
        return int(self._rpc(
            "submit", payload=request,
            deadline_s=getattr(request, "deadline_s", None))["token"])

    def drain(self, deadline_s=None) -> list[dict]:
        resp = self._rpc("drain", deadline_s=deadline_s,
                         ack=self._drain_ack)
        if resp.get("seq") is not None:
            self._drain_ack = max(self._drain_ack, int(resp["seq"]))
        return _unb64(resp["payload"])

    def drain_reads(self, deadline_s=None) -> list[dict]:
        return _unb64(self._rpc("drain_reads",
                                deadline_s=deadline_s)["payload"])

    def predict(self, request) -> dict:
        return _unb64(self._rpc(
            "predict", payload=request,
            deadline_s=getattr(request, "deadline_s", None))["payload"])

    def report(self) -> dict:
        return self._rpc("report")["report"]

    def metrics(self, deadline_s=None) -> dict:
        return _unb64(self._rpc("metrics",
                                deadline_s=deadline_s)["payload"])

    # -- program supply chain (ISSUE 16) -------------------------------
    def pull_programs(self, fp8s, deadline_s=None) -> dict:
        return _unb64(self._rpc("pull_programs", payload=list(fp8s),
                                deadline_s=deadline_s)["payload"])

    def ship_programs(self, shipment, deadline_s=None) -> dict:
        return _unb64(self._rpc("ship_programs", payload=shipment,
                                deadline_s=deadline_s)["payload"])

    # -- durable sessions (ISSUE 13) -----------------------------------
    def session_summary(self, skey) -> dict | None:
        resp = self._rpc("session_summary", payload=tuple(skey))
        return _unb64(resp["payload"]) if resp.get("payload") else None

    def stash_replica(self, skey, blob: dict) -> None:
        self._rpc("stash", payload={"skey": tuple(skey), "blob": blob})

    def adopt_session(self, skey, toas, replica=None,
                      deadline_s=None) -> dict:
        return _unb64(self._rpc(
            "adopt", payload={"skey": tuple(skey), "toas": toas,
                              "replica": replica},
            deadline_s=deadline_s)["payload"])

    def drop_session(self, session_id, deadline_s=None) -> None:
        self._rpc("drop_session", payload=session_id,
                  deadline_s=deadline_s)

    def replay(self, requests, deadline_s=None) -> list[dict]:
        return _unb64(self._rpc("replay", payload=list(requests),
                                deadline_s=deadline_s)["payload"])

    # -- catalog long jobs (ISSUE 14) ----------------------------------
    def submit_catalog(self, request, deadline_s=None) -> str:
        return self._rpc("submit_catalog", payload=request,
                         deadline_s=deadline_s)["job_id"]

    def adopt_catalog(self, checkpoint, deadline_s=None) -> str:
        return self._rpc("adopt_catalog", payload=checkpoint,
                         deadline_s=deadline_s)["job_id"]

    def advance_catalog(self, job_id, budget_s=None,
                        deadline_s=None) -> dict:
        return _unb64(self._rpc(
            "advance_catalog",
            payload={"job_id": job_id, "budget_s": budget_s},
            deadline_s=deadline_s)["payload"])

    def catalog_progress(self, job_id, deadline_s=None) -> dict | None:
        resp = self._rpc("catalog_progress", payload=job_id,
                         deadline_s=deadline_s)
        return _unb64(resp["payload"]) if resp.get("payload") else None

    def shutdown(self) -> None:
        """Ask the worker to exit cleanly (best-effort)."""
        try:
            self._rpc("shutdown")
        except (HostDown, OSError, RuntimeError):
            pass
        self.close()

    def close(self) -> None:
        for o in (self._fh, self._sock):
            try:
                if o is not None:
                    o.close()
            except OSError:
                pass
        self._sock = self._fh = None


# ----------------------------------------------------------------------
# worker-side server loop
# ----------------------------------------------------------------------

def serve_worker(scheduler, port: int, *, host: str = "127.0.0.1",
                 ready_fh=None, extra_report=None) -> int:
    """Serve one scheduler over the JSONL protocol until ``shutdown``.

    Op execution is SERIALIZED (one lock around every handler — the
    serve layer itself stays thread-free), but connections are
    concurrent (ISSUE 19): the router holds a persistent connection,
    and the live introspection plane (``python -m
    pint_tpu.telemetry.top``) must still be able to attach to a busy
    worker and run its ``metrics`` op between the router's ops — a
    single-connection accept loop would park it in the listen backlog
    for as long as the router stays connected. Sequential reconnects
    are accepted (a router that restarts resumes against the same host
    state). ``ready_fh`` (when given) receives one ``{"ready": ...}``
    JSON line after the socket is listening — the spawn handshake the
    bench/worker entry points wait on. ``extra_report`` is merged into
    every ``report`` response (the worker adds its jax.distributed
    status and pid). Returns the number of requests served.
    """
    from pint_tpu.serve.scheduler import PredictRequest, ServeQueueFull

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(8)  # router + live-plane probes may connect together
    bound_port = srv.getsockname()[1]
    if ready_fh is not None:
        ready_fh.write(json.dumps(
            {"ready": True, "host": scheduler.host_id,
             "port": bound_port, "pid": os.getpid()}) + "\n")
        ready_fh.flush()
    tokens = itertools.count()
    pending: list[tuple[int, object]] = []
    pending_reads: list[tuple[int, object]] = []
    state = {"served": 0, "running": True}
    # at-least-once delivery (ISSUE 13): drain replies are sequenced
    # and kept until the CLIENT acks them (the next drain op echoes
    # the last seq it saw) — a reply lost with a dead/partitioned
    # connection is redelivered on the next drain, whichever
    # connection it arrives on. The router dedups by token and FENCES
    # stale sessionful replies, so redelivery is harmless and late
    # commits become visible instead of silently vanishing.
    unacked: list[tuple[int, list]] = []   # (seq, wire results)
    drain_seq = itertools.count()

    def handle(msg: dict, reply) -> None:
        """Dispatch one protocol op (replies structured app errors via
        the surrounding handlers; only a dead pipe's OSError escapes)."""
        nonlocal pending, pending_reads

        op = msg.get("op")
        state["served"] += 1
        if op == "ping":
            # the heartbeat op (ISSUE 13): cheap liveness + queue
            # depths, never touching device work — what the router's
            # suspicion ladder pings between drains
            reply({"ok": True, "host": scheduler.host_id,
                   "t": time.time(),
                   "queue_depth": scheduler.pending(),
                   "read_depth": scheduler.pending_reads()})
        elif op == "submit":
            req = _unb64(msg["payload"])
            token = next(tokens)
            h = scheduler.submit(req)
            if isinstance(req, PredictRequest):
                pending_reads.append((token, h))
            else:
                pending.append((token, h))
            telemetry.inc("fleet.worker.requests")
            # the accept hop must be DURABLE before the ack (ISSUE 19):
            # the router may SIGKILL this process the instant it holds
            # the token, and the cross-process trace merge still needs
            # the dead worker's accept on disk — the generic post-op
            # flush below runs after the reply and loses that race
            if telemetry.enabled():
                telemetry.flush()
            reply({"ok": True, "token": token})
        elif op == "drain":
            ack = msg.get("ack")
            if ack is not None:
                unacked[:] = [(s, w) for s, w in unacked if s > ack]
            # catalog slices run under the router's advance_catalog op
            # (slow-path deadline), never inside the fit-drain RPC
            scheduler.drain(advance_catalog=False)
            out = [wire_fit_result(t, h.result()) for t, h in pending]
            pending = []
            out_r = [dict(wire_read_result(h.result()), token=t)
                     for t, h in pending_reads]
            pending_reads = []
            fresh = out + out_r
            payload = [w for _s, ws in unacked for w in ws] + fresh
            if fresh:
                unacked.append((next(drain_seq), fresh))
                while sum(len(ws) for _s, ws in unacked) > 512:
                    unacked.pop(0)
            seq = unacked[-1][0] if unacked else (ack if ack is not
                                                  None else -1)
            reply({"ok": True, "seq": seq,
                   "payload": _b64(payload)})
        elif op == "drain_reads":
            scheduler.drain_reads()
            out = [dict(wire_read_result(h.result()), token=t)
                   for t, h in pending_reads]
            pending_reads = []
            reply({"ok": True, "payload": _b64(out)})
        elif op == "predict":
            res = scheduler.predict(_unb64(msg["payload"]))
            reply({"ok": True, "payload": _b64(wire_read_result(res))})
        elif op == "session_summary":
            # durable sessions (ISSUE 13): the router pulls this host's
            # committed summary to replicate it onto the ring successor
            summary = scheduler.session_summary(_unb64(msg["payload"]))
            reply({"ok": True,
                   "payload": _b64(summary) if summary else None})
        elif op == "stash":
            p = _unb64(msg["payload"])
            scheduler.stash_replica(tuple(p["skey"]), p["blob"])
            reply({"ok": True})
        elif op == "adopt":
            p = _unb64(msg["payload"])
            out = scheduler.adopt_session(tuple(p["skey"]), p["toas"],
                                          replica=p.get("replica"))
            reply({"ok": True, "payload": _b64(out)})
        elif op == "drop_session":
            scheduler.sessions.drop(_unb64(msg["payload"]))
            reply({"ok": True})
        elif op == "replay":
            # journal replay: run the requests to completion in ONE op
            # (atomic on this host; co-queued handles resolving early
            # still wire out at the next drain op)
            reqs = _unb64(msg["payload"])
            handles = [scheduler.submit(r) for r in reqs]
            scheduler.drain(advance_catalog=False)
            reply({"ok": True, "payload": _b64(
                [{"status": h.result().status,
                  "chi2": h.result().chi2,
                  "session": h.result().session} for h in handles])})
        elif op == "submit_catalog":
            # catalog long jobs (ISSUE 14): submit returns the job id;
            # the router advances it slice-by-slice via advance_catalog
            h = scheduler.submit_catalog(_unb64(msg["payload"]))
            reply({"ok": True, "job_id": h.job_id})
        elif op == "adopt_catalog":
            h = scheduler.adopt_catalog(_unb64(msg["payload"]))
            reply({"ok": True, "job_id": h.job_id})
        elif op == "advance_catalog":
            p = _unb64(msg["payload"])
            job = scheduler.catalog_jobs.get(p["job_id"])
            if job is None:
                reply({"ok": False, "error_type": "KeyError",
                       "error": f"unknown catalog job {p['job_id']!r}"})
            else:
                if job.state not in ("done", "failed"):
                    job.advance(p.get("budget_s"))
                reply({"ok": True, "payload": _b64(
                    {"progress": job.progress(),
                     "checkpoint": scheduler.catalog_checkpoint(
                         p["job_id"])})})
        elif op == "catalog_progress":
            prog = scheduler.catalog_progress(_unb64(msg["payload"]))
            reply({"ok": True,
                   "payload": _b64(prog) if prog else None})
        elif op == "pull_programs":
            # program supply chain (ISSUE 16): a warm host exports its
            # shipment for a joining worker's adopt set
            from pint_tpu.programs.ship import export_for_ship

            reply({"ok": True, "payload": _b64(
                export_for_ship(_unb64(msg["payload"])))})
        elif op == "ship_programs":
            from pint_tpu.programs.ship import adopt_shipment

            reply({"ok": True, "payload": _b64(
                adopt_shipment(_unb64(msg["payload"])))})
        elif op == "report":
            rep = scheduler.report()
            if extra_report:
                rep.update(extra_report)
            reply({"ok": True, "report": rep})
        elif op == "metrics":
            # the live plane (ISSUE 19): cheap, never touches device
            # work — answerable even mid-backlog
            reply({"ok": True,
                   "payload": _b64(scheduler.metrics_snapshot())})
        elif op == "shutdown":
            reply({"ok": True})
            state["running"] = False
        else:
            reply({"ok": False, "error_type": "ValueError",
                   "error": f"unknown op {op!r}"})

    # ONE lock serializes every op across connections: the handlers
    # mutate shared serve state (scheduler queues, pending/unacked,
    # the token/seq counters), and the pre-ISSUE-19 contract was
    # strictly sequential execution — concurrency lives only at the
    # socket layer
    op_lock = threading.Lock()

    def serve_conn(conn) -> None:
        fh = conn.makefile("rwb")

        def reply(obj: dict) -> None:
            fh.write((json.dumps(obj) + "\n").encode())
            fh.flush()

        while state["running"]:
            try:
                line = fh.readline()
            except OSError:
                break  # reset mid-read: await a reconnect, don't die
            if not line:
                break  # router went away; await a reconnect
            # the inner handlers reply structured app errors; a reply
            # on a DEAD pipe raises OSError through them to the outer
            # except, which drops the connection and awaits a
            # reconnect instead of killing the worker — warm programs
            # and session state must survive a router crash
            try:
                with op_lock:
                    if not state["running"]:
                        break
                    try:
                        handle(json.loads(line), reply)
                    except ServeQueueFull as e:
                        reply({"ok": False,
                               "error_type": "ServeQueueFull",
                               "attrs": {"depth": e.depth,
                                         "max_queue": e.max_queue,
                                         "retry_after_s": e.retry_after_s,
                                         "degraded": e.degraded}})
                    except Exception as e:  # noqa: BLE001 — isolation
                        # boundary: a bad request must never kill the
                        # worker
                        reply({"ok": False,
                               "error_type": type(e).__name__,
                               "error": str(e)})
                    # flush buffered telemetry after EVERY op (ISSUE
                    # 19): a SIGKILLed worker's accept/dispatch hops
                    # must already be on disk for the cross-process
                    # trace merge — the worker RPC path is not hot, so
                    # per-op flush is cheap relative to one socket
                    # round-trip
                    if telemetry.enabled():
                        telemetry.flush()
            except OSError:
                break  # pipe died mid-reply: await a reconnect
        if not state["running"]:
            # this connection carried the shutdown op (or observed
            # it): wake the accept loop — close() alone does NOT
            # unblock a thread parked in accept() on Linux, the
            # listener must be shut down first
            try:
                srv.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                srv.close()
            except OSError:
                pass
        try:
            fh.close()
            conn.close()
        except OSError:
            pass

    while state["running"]:
        try:
            conn, _addr = srv.accept()
        except OSError:
            break
        t = threading.Thread(target=serve_conn, args=(conn,),
                             daemon=True, name="fleet-worker-conn")
        t.start()
    try:
        srv.close()
    except OSError:
        pass
    return state["served"]
