"""Transport seam between the fleet router and per-host schedulers.

The router (:mod:`pint_tpu.fleet.router`) never talks to a
:class:`~pint_tpu.serve.scheduler.ThroughputScheduler` directly — it
talks to a *host transport*, a small duck-typed surface
(:class:`LoopbackHost` documents it) with exactly the operations the
routing tier needs:

* ``submit(request) -> token`` — enqueue one fit/read on the host,
  returning an opaque per-host token;
* ``drain() -> [wire results]`` / ``drain_reads() -> [wire reads]`` —
  resolve everything queued since the last drain;
* ``predict(request) -> wire read`` — the synchronous read fast lane
  (never behind the host's fit queue — the worker serves it as its own
  op, not as part of a drain);
* ``report() -> dict`` — the host's health surface
  (:meth:`ThroughputScheduler.report`): queue depth, fail streak,
  degraded flag, program-cache misses. The router's per-host health
  state is fed ONLY from these reports plus transport-level failures.

Two implementations:

:class:`LoopbackHost` wraps an in-process scheduler — N "hosts" in one
process, zero network, zero serialization (results are the scheduler's
own objects; the caller's model is mutated in place exactly as in
single-host serving). Tests, ``bench --smoke`` and the soak fleet axis
run on loopback, so every routing invariant is provable without
silicon or sockets.

:class:`TcpHost` speaks a line-oriented JSONL protocol to a real
worker process (:mod:`pint_tpu.fleet.worker`): one JSON object per
line, ``{"op": ..., "payload": <base64 pickle>}`` requests and
``{"ok": ..., ...}`` responses. Payloads (TOA tables, models, results)
are pickled — the fleet protocol is for a TRUSTED pod-internal
network, like any jax.distributed coordinator traffic, never an
internet-facing surface. Because a remote worker fits a *copy* of the
request, fitted parameter values come back in the wire result
(``params``: name -> (hi, lo, uncertainty) double-double parts, exact)
and the router writes them onto the caller's model — the same
in-place contract the loopback path gets for free.

A dead socket raises :class:`HostDown` — the router's signal to mark
the host dead and re-route its pending work (failover), never an
exception surfaced to a submit caller.
"""

from __future__ import annotations

import base64
import itertools
import json
import os
import pickle
import socket
import time

from pint_tpu import telemetry


class HostDown(ConnectionError):
    """The transport lost the host (refused/reset/closed socket or an
    explicitly killed loopback). The router catches this everywhere a
    transport is touched and fails over; it never reaches a caller."""


def _b64(obj) -> str:
    return base64.b64encode(
        pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)).decode()


def _unb64(s: str):
    return pickle.loads(base64.b64decode(s.encode()))


def wire_fit_result(token, res) -> dict:
    """Slim wire form of one FitResult: everything the router needs to
    rebuild the envelope against the CALLER's request object, without
    shipping the TOA table back. ``params`` carries the fitted values
    as exact (hi, lo) double-double parts plus uncertainties — only for
    results whose status writes back (:attr:`FitResult.fitted`)."""
    params = None
    if res.fitted and res.request.model is not None:
        m = res.request.model
        params = {k: (m[k].hi, m[k].lo, m[k].uncertainty)
                  for k in m.free_params}
    return {"token": token, "status": res.status, "chi2": res.chi2,
            "converged": res.converged, "error": res.error,
            "attempts": res.attempts, "retry_after_s": res.retry_after_s,
            "session": res.session, "passthrough": res.passthrough,
            "queue_latency_s": res.queue_latency_s, "group": res.group,
            "batch": res.batch, "n_members": res.n_members,
            "occupancy": res.occupancy, "host": res.host,
            "injected": res.injected, "trace": res.trace,
            "params": params}


def wire_read_result(res) -> dict:
    """Wire form of one PredictResult (arrays ride the pickle)."""
    return {"status": res.status, "phase_int": res.phase_int,
            "phase_frac": res.phase_frac, "freq_hz": res.freq_hz,
            "source": res.source, "cache_hit": res.cache_hit,
            "n_queries": res.n_queries, "latency_s": res.latency_s,
            "error": res.error, "host": res.host}


# ----------------------------------------------------------------------
# loopback: N hosts in one process (tests / bench / soak)
# ----------------------------------------------------------------------

class LoopbackHost:
    """In-process host: a scheduler behind the transport surface.

    ``kill()`` simulates a host crash for failover tests — every later
    operation raises :class:`HostDown`, exactly what a dead TCP socket
    surfaces, so the router's failover path is transport-agnostic.
    """

    kind = "loopback"

    def __init__(self, host_id: str, scheduler=None, **sched_kwargs):
        from pint_tpu.serve.scheduler import ThroughputScheduler

        self.host_id = host_id
        self.scheduler = (scheduler if scheduler is not None
                          else ThroughputScheduler(host_id=host_id,
                                                   **sched_kwargs))
        if not self.scheduler.host_id:
            self.scheduler.host_id = host_id
        self._tokens = itertools.count()
        self._pending: list[tuple[int, object]] = []       # (token, handle)
        self._pending_reads: list[tuple[int, object]] = []
        self._dead = False

    def _check(self):
        if self._dead:
            raise HostDown(f"loopback host {self.host_id} was killed")

    def kill(self) -> None:
        """Simulate a crashed host (failover tests / soak host-kill)."""
        self._dead = True

    def alive(self) -> bool:
        return not self._dead

    def submit(self, request) -> int:
        from pint_tpu.serve.scheduler import PredictRequest

        self._check()
        token = next(self._tokens)
        handle = self.scheduler.submit(request)
        if isinstance(request, PredictRequest):
            self._pending_reads.append((token, handle))
        else:
            self._pending.append((token, handle))
        return token

    def drain(self) -> list[dict]:
        self._check()
        self.scheduler.drain()
        out = [{"token": t, "result": h.result()}
               for t, h in self._pending]
        self._pending = []
        return out

    def drain_reads(self) -> list[dict]:
        self._check()
        self.scheduler.drain_reads()
        out = [{"token": t, "result": h.result()}
               for t, h in self._pending_reads]
        self._pending_reads = []
        return out

    def predict(self, request) -> dict:
        self._check()
        return {"result": self.scheduler.predict(request)}

    def report(self) -> dict:
        self._check()
        return self.scheduler.report()

    def close(self) -> None:
        self._dead = True


# ----------------------------------------------------------------------
# TCP/JSONL: a real worker process behind a socket
# ----------------------------------------------------------------------

class TcpHost:
    """JSONL client for one :mod:`pint_tpu.fleet.worker` process."""

    kind = "tcp"

    def __init__(self, host_id: str, address: tuple[str, int],
                 timeout_s: float = 600.0):
        self.host_id = host_id
        self.address = tuple(address)
        self.timeout_s = timeout_s
        self._sock = None
        self._fh = None

    def _connect(self):
        if self._sock is not None:
            return
        try:
            self._sock = socket.create_connection(self.address,
                                                  timeout=self.timeout_s)
            self._fh = self._sock.makefile("rwb")
        except OSError as e:
            self._sock = self._fh = None
            raise HostDown(
                f"host {self.host_id} at {self.address}: {e}") from e

    def _rpc(self, op: str, payload=None, **fields) -> dict:
        self._connect()
        msg = {"op": op, **fields}
        if payload is not None:
            msg["payload"] = _b64(payload)
        try:
            self._fh.write((json.dumps(msg) + "\n").encode())
            self._fh.flush()
            line = self._fh.readline()
        except OSError as e:
            self.close()
            raise HostDown(
                f"host {self.host_id} at {self.address}: {e}") from e
        if not line:
            self.close()
            raise HostDown(f"host {self.host_id} at {self.address}: "
                           "connection closed")
        resp = json.loads(line)
        if not resp.get("ok"):
            # a structured application error (bad request, backpressure)
            # — the host is alive; re-raise the typed error router-side
            et = resp.get("error_type", "RuntimeError")
            if et == "ServeQueueFull":
                from pint_tpu.serve.scheduler import ServeQueueFull

                a = resp.get("attrs", {})
                raise ServeQueueFull(**a)
            raise RuntimeError(f"host {self.host_id}: "
                               f"{et}: {resp.get('error')}")
        return resp

    def ping(self) -> dict:
        return self._rpc("ping")

    def alive(self) -> bool:
        try:
            self.ping()
            return True
        except (HostDown, OSError):
            return False

    def submit(self, request) -> int:
        return int(self._rpc("submit", payload=request)["token"])

    def drain(self) -> list[dict]:
        return _unb64(self._rpc("drain")["payload"])

    def drain_reads(self) -> list[dict]:
        return _unb64(self._rpc("drain_reads")["payload"])

    def predict(self, request) -> dict:
        return _unb64(self._rpc("predict", payload=request)["payload"])

    def report(self) -> dict:
        return self._rpc("report")["report"]

    def shutdown(self) -> None:
        """Ask the worker to exit cleanly (best-effort)."""
        try:
            self._rpc("shutdown")
        except (HostDown, OSError, RuntimeError):
            pass
        self.close()

    def close(self) -> None:
        for o in (self._fh, self._sock):
            try:
                if o is not None:
                    o.close()
            except OSError:
                pass
        self._sock = self._fh = None


# ----------------------------------------------------------------------
# worker-side server loop
# ----------------------------------------------------------------------

def serve_worker(scheduler, port: int, *, host: str = "127.0.0.1",
                 ready_fh=None, extra_report=None) -> int:
    """Serve one scheduler over the JSONL protocol until ``shutdown``.

    Single-threaded by design — the serve layer is thread-free, and the
    fleet has exactly one router per worker. Sequential reconnects are
    accepted (a router that restarts resumes against the same host
    state). ``ready_fh`` (when given) receives one ``{"ready": ...}``
    JSON line after the socket is listening — the spawn handshake the
    bench/worker entry points wait on. ``extra_report`` is merged into
    every ``report`` response (the worker adds its jax.distributed
    status and pid). Returns the number of requests served.
    """
    from pint_tpu.serve.scheduler import PredictRequest, ServeQueueFull

    srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind((host, port))
    srv.listen(1)
    bound_port = srv.getsockname()[1]
    if ready_fh is not None:
        ready_fh.write(json.dumps(
            {"ready": True, "host": scheduler.host_id,
             "port": bound_port, "pid": os.getpid()}) + "\n")
        ready_fh.flush()
    tokens = itertools.count()
    pending: list[tuple[int, object]] = []
    pending_reads: list[tuple[int, object]] = []
    state = {"served": 0, "running": True}

    def handle(msg: dict, reply) -> None:
        """Dispatch one protocol op (replies structured app errors via
        the surrounding handlers; only a dead pipe's OSError escapes)."""
        nonlocal pending, pending_reads

        op = msg.get("op")
        state["served"] += 1
        if op == "ping":
            reply({"ok": True, "host": scheduler.host_id,
                   "t": time.time()})
        elif op == "submit":
            req = _unb64(msg["payload"])
            token = next(tokens)
            h = scheduler.submit(req)
            if isinstance(req, PredictRequest):
                pending_reads.append((token, h))
            else:
                pending.append((token, h))
            telemetry.inc("fleet.worker.requests")
            reply({"ok": True, "token": token})
        elif op == "drain":
            scheduler.drain()
            out = [wire_fit_result(t, h.result()) for t, h in pending]
            pending = []
            out_r = [dict(wire_read_result(h.result()), token=t)
                     for t, h in pending_reads]
            pending_reads = []
            reply({"ok": True, "payload": _b64(out + out_r)})
        elif op == "drain_reads":
            scheduler.drain_reads()
            out = [dict(wire_read_result(h.result()), token=t)
                   for t, h in pending_reads]
            pending_reads = []
            reply({"ok": True, "payload": _b64(out)})
        elif op == "predict":
            res = scheduler.predict(_unb64(msg["payload"]))
            reply({"ok": True, "payload": _b64(wire_read_result(res))})
        elif op == "report":
            rep = scheduler.report()
            if extra_report:
                rep.update(extra_report)
            reply({"ok": True, "report": rep})
        elif op == "shutdown":
            reply({"ok": True})
            state["running"] = False
        else:
            reply({"ok": False, "error_type": "ValueError",
                   "error": f"unknown op {op!r}"})

    while state["running"]:
        try:
            conn, _addr = srv.accept()
        except OSError:
            break
        fh = conn.makefile("rwb")

        def reply(obj: dict) -> None:
            fh.write((json.dumps(obj) + "\n").encode())
            fh.flush()

        while state["running"]:
            try:
                line = fh.readline()
            except OSError:
                break  # reset mid-read: await a reconnect, don't die
            if not line:
                break  # router went away; await a reconnect
            # the inner handlers reply structured app errors; a reply
            # on a DEAD pipe raises OSError through them to the outer
            # except, which drops the connection and awaits a
            # reconnect instead of killing the worker — warm programs
            # and session state must survive a router crash
            try:
                try:
                    handle(json.loads(line), reply)
                except ServeQueueFull as e:
                    reply({"ok": False, "error_type": "ServeQueueFull",
                           "attrs": {"depth": e.depth,
                                     "max_queue": e.max_queue,
                                     "retry_after_s": e.retry_after_s,
                                     "degraded": e.degraded}})
                except Exception as e:  # noqa: BLE001 — isolation
                    # boundary: a bad request must never kill the worker
                    reply({"ok": False, "error_type": type(e).__name__,
                           "error": str(e)})
            except OSError:
                break  # pipe died mid-reply: await a reconnect
        try:
            fh.close()
            conn.close()
        except OSError:
            pass
    srv.close()
    return state["served"]
