"""Durable fleet sessions (ISSUE 13): the router-side append journal.

``SessionCache`` state is per-host device/process memory: when a host
dies, every session pinned to it loses its accumulated TOAs and rank-k
fit state, and PR 12's failover can only re-run *pending* requests —
the committed history was gone. FLEET_r01 measured why durability has
to live HERE, above the runtime: a jax.distributed process group is
one fault domain, so surviving a host means the state (or the recipe
to rebuild it) must be held by the routing tier and the OTHER hosts.

Three cooperating mechanisms (see docs/ARCHITECTURE.md "Durability
contract"):

* **Append journal** (this module): the router records every
  *committed* sessionful request — the populate envelope (model +
  initial table) as the *base*, then each append's TOA rows + fit
  hyperparameters. Replaying base-then-appends onto a fresh host walks
  the exact populate/append code path the original stream took, so the
  rebuilt session converges to the dead host's solution (1e-9-class
  parity, pinned by tests and the FLEET_r02 artifact). The journal is
  bounded by ``PINT_TPU_FLEET_JOURNAL_BYTES``: over budget, the oldest
  appends are *merged into the base table* (snapshot truncation —
  replaying a merged base is mathematically the same stream, one fit
  shorter), and only when bases alone exceed the budget is a whole
  session's log dropped LRU (counted; that session cold-refits from
  the triggering request alone, nothing silently wrong — just slower
  and starting from less history).
* **Snapshot replication** (:func:`build_replica` + the transport
  ``stash``/``adopt`` ops): after a drain commits sessions, the router
  pulls each owning host's small committed summary (model values as
  exact (hi, lo) double-double parts, uncertainties, chi2, append
  count) and ships it to the session's ring successor. A warm failover
  then *adopts* the replica on the successor — no refit at all for the
  covered prefix — and replays only the journal suffix since the last
  replication. Stashing also truncates the journal: covered appends
  merge into the base.
* **Fencing** (:mod:`pint_tpu.fleet.router`): every pin carries a
  monotonic epoch; any re-pin bumps it, and commits/replies arriving
  from a stale epoch are rejected at the router — at-least-once
  re-execution with exactly-once state effect.

Lost only on simultaneous death of a host *and* the router holding its
journal (or the host and its successor between a commit and the next
replication): the appends since the last surviving copy.
"""

from __future__ import annotations

from pint_tpu import config
import pickle
from typing import Any

from pint_tpu import telemetry



def journal_budget() -> int:
    """Journal byte budget (read per call so tests can flip it)."""
    return config.env_int("PINT_TPU_FLEET_JOURNAL_BYTES")


def op_deadline_s() -> float:
    """Default per-operation transport deadline [s] — the sane default
    the ISSUE-13 liveness work replaces the flat 600 s timeout with.
    A request's own ``deadline_s`` extends it per call."""
    return config.env_float("PINT_TPU_FLEET_OP_DEADLINE_S")


def heartbeat_deadline_s() -> float:
    """Heartbeat ping deadline [s] (the suspicion-ladder cadence)."""
    return config.env_float("PINT_TPU_FLEET_HEARTBEAT_S")


def _nbytes(obj) -> int:
    """Journal accounting size of one payload: its pickle length (what
    a replay actually ships over the wire)."""
    try:
        return len(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
    except Exception:  # noqa: BLE001 — unpicklable payloads can't
        return 1 << 20  # journal anyway; charge them heavily


class SessionLog:
    """One session's write-ahead log: a base (model blob + accumulated
    table it covers) plus the append suffix since the base."""

    __slots__ = ("skey", "sid", "fp8", "base_toas", "base_model_blob",
                 "base_bytes", "base_appends", "appends", "next_seq",
                 "replica_host", "chi2")

    def __init__(self, skey, sid, fp8):
        self.skey = skey
        self.sid = sid
        self.fp8 = fp8
        self.base_toas = None
        self.base_model_blob: bytes | None = None
        self.base_bytes = 0
        self.base_appends = 0        # committed appends the base covers
        self.appends: list[dict] = []  # {seq, toas, hyper, nbytes}
        self.next_seq = 0
        self.replica_host: str | None = None  # last stash target
        self.chi2 = float("nan")

    @property
    def bytes(self) -> int:
        return self.base_bytes + sum(a["nbytes"] for a in self.appends)

    def merge_appends_into_base(self, upto_seq: int | None = None) -> int:
        """Snapshot truncation: fold appends (all, or those with seq <=
        ``upto_seq``) into the base table. Replaying the merged base is
        the same TOA history in one fit instead of many — the session
        layer's own full-refit path does exactly this merge."""
        from pint_tpu.toas import merge_TOAs

        take = [a for a in self.appends
                if upto_seq is None or a["seq"] <= upto_seq]
        if not take:
            return 0
        taken = {a["seq"] for a in take}
        self.base_toas = merge_TOAs([self.base_toas]
                                    + [a["toas"] for a in take])
        self.appends = [a for a in self.appends
                        if a["seq"] not in taken]
        self.base_appends += len(take)
        self.base_bytes = _nbytes(self.base_toas) \
            + len(self.base_model_blob or b"")
        return len(take)


class SessionJournal:
    """Per-router WAL of committed sessionful work, LRU over sessions
    and bounded by :func:`journal_budget`."""

    def __init__(self, budget_bytes: int | None = None):
        self._budget = budget_bytes
        self.logs: dict[tuple, SessionLog] = {}
        self.truncations = 0
        self.dropped = 0

    @property
    def budget(self) -> int:
        return self._budget if self._budget is not None \
            else journal_budget()

    def bytes(self) -> int:
        return sum(lg.bytes for lg in self.logs.values())

    def log(self, skey) -> SessionLog | None:
        return self.logs.get(skey)

    def _touch(self, skey) -> None:
        lg = self.logs.pop(skey)
        self.logs[skey] = lg  # dict order = LRU order

    def record_populate(self, skey, sid, model, toas,
                        chi2: float) -> None:
        """A populate (or re-populate) committed: (re)seed the log.
        The model is pickled POST-fit — replaying it warm-starts at the
        committed values and converges immediately."""
        lg = SessionLog(skey, sid, skey[1])
        lg.base_toas = toas
        lg.base_model_blob = pickle.dumps(
            model, protocol=pickle.HIGHEST_PROTOCOL)
        lg.base_bytes = _nbytes(toas) + len(lg.base_model_blob)
        lg.chi2 = float(chi2)
        self.logs.pop(skey, None)
        self.logs[skey] = lg
        telemetry.inc("fleet.journal.populates")
        self._enforce_budget()

    def record_append(self, skey, toas, hyper: dict,
                      chi2: float) -> bool:
        """One committed append; returns False when the session has no
        base (its populate predates journaling or was dropped) — the
        caller counts the miss, nothing else to do."""
        lg = self.logs.get(skey)
        if lg is None or lg.base_toas is None:
            return False
        lg.appends.append({"seq": lg.next_seq, "toas": toas,
                           "hyper": dict(hyper), "nbytes": _nbytes(toas)})
        lg.next_seq += 1
        lg.chi2 = float(chi2)
        self._touch(skey)
        telemetry.inc("fleet.journal.appends")
        self._enforce_budget()
        return True

    def note_replica(self, skey, host: str, model_blob: bytes) -> None:
        """A replica covering the log's full current history was
        stashed on ``host``: every append folds into the base (the
        replica restores the prefix; replay need only cover the suffix
        recorded AFTER this point) and the base model refreshes to the
        replicated values."""
        lg = self.logs.get(skey)
        if lg is None:
            return
        merged = lg.merge_appends_into_base()
        if merged:
            self.truncations += 1
            telemetry.inc("fleet.journal.truncations")
        lg.replica_host = host
        lg.base_model_blob = model_blob
        lg.base_bytes = _nbytes(lg.base_toas) + len(model_blob)

    def forget(self, skey) -> None:
        self.logs.pop(skey, None)

    def _enforce_budget(self) -> None:
        budget = self.budget
        if self.bytes() <= budget:
            return
        # first: snapshot-truncate the fattest append suffixes
        for lg in sorted(self.logs.values(),
                         key=lambda g: g.bytes - g.base_bytes,
                         reverse=True):
            if self.bytes() <= budget:
                return
            if lg.appends and lg.merge_appends_into_base():
                # the stashed replica (if any) now predates the merged
                # base: a warm adopt would install pre-merge values
                # over the larger table and replay nothing for the
                # merged appends — force the next restore COLD (replay
                # re-fits the merged base; the next commit
                # re-replicates)
                lg.replica_host = None
                self.truncations += 1
                telemetry.inc("fleet.journal.truncations")
        # still over: bases alone exceed the budget — drop LRU logs
        # (those sessions lose replay, never correctness: a restore
        # miss cold-refits from the triggering request alone)
        for skey in list(self.logs):
            if self.bytes() <= budget:
                return
            del self.logs[skey]
            self.dropped += 1
            telemetry.inc("fleet.journal.dropped")

    def stats(self) -> dict:
        return {"sessions": len(self.logs), "bytes": self.bytes(),
                "budget": self.budget,
                "appends": sum(len(lg.appends)
                               for lg in self.logs.values()),
                "truncations": self.truncations,
                "dropped": self.dropped}


def build_replica(summary: dict, *, epoch: int) -> dict:
    """The wire replica blob: the owning host's committed summary
    (:meth:`ThroughputScheduler.session_summary`) stamped with the
    router's current pin epoch. Everything a successor needs to adopt
    the session as committed host state — deliberately SMALL (the
    model pickle is ~KBs; the accumulated table stays in the journal
    and rides the adopt op instead)."""
    return {**summary, "epoch": int(epoch)}


def replay_requests(log: SessionLog, *, suffix_only: bool):
    """(populate_request_or_None, [append_requests]) rebuilding the
    journaled history. ``suffix_only`` (warm restore: the target host
    adopted a replica covering the base) skips the populate and
    replays only appends recorded after the last replication."""
    from pint_tpu.serve.scheduler import FitRequest

    populate = None
    if not suffix_only:
        model = pickle.loads(log.base_model_blob)
        populate = FitRequest(log.base_toas, model,
                              tag=("journal", "populate"),
                              session_id=log.sid)
    appends = [
        FitRequest(a["toas"], None, tag=("journal", a["seq"]),
                   session_id=log.sid, **a["hyper"])
        for a in log.appends]
    return populate, appends


__all__ = ["SessionJournal", "SessionLog", "build_replica",
           "replay_requests", "journal_budget", "op_deadline_s",
           "heartbeat_deadline_s"]
