"""Fleet worker/coordinator entry points.

A *worker* is one host process: it (optionally) joins the
``jax.distributed`` process group, builds a
:class:`~pint_tpu.serve.scheduler.ThroughputScheduler` over its
process-LOCAL device pool, and serves the JSONL transport protocol
(:func:`pint_tpu.fleet.transport.serve_worker`) until told to shut
down. ``python -m pint_tpu.fleet worker --port 0 --host-id w0`` is the
CLI; :func:`spawn_local_workers` is the same thing as a library call
for the bench/A-B harness (N real processes on one machine, ports
auto-assigned, ready lines handshaked over stdout).

**jax.distributed.** When ``PINT_TPU_FLEET_PROCESSES > 1`` the worker
attempts ``jax.distributed.initialize(coordinator_address=
$PINT_TPU_FLEET_COORD, num_processes=N, process_id=$PINT_TPU_FLEET_
PROCESS_ID)`` — the pjit multi-process machinery (SNIPPETS.md [1][2]):
on pod-scale platforms this is what makes each process's
``jax.local_devices()`` its slice of the pod. The attempt is guarded
and *honestly recorded*: runtimes without multi-process support (or
with no live coordinator) degrade to single-process local devices and
the worker's ``report`` op carries ``jax_distributed: "unavailable:
..."`` so committed artifacts state which mode actually ran. At
``PINT_TPU_FLEET_PROCESSES`` unset/1 (or under ``PINT_TPU_FLEET=0``)
nothing distributed is touched at all — the worker is bitwise today's
single-host scheduler behind a socket.
"""

from __future__ import annotations

import json
import os
from pint_tpu import config
import subprocess
import sys
import time


def init_distributed() -> str:
    """Join the jax.distributed process group when configured.

    Returns a status token for the worker's report surface:
    ``"off"`` (not configured / N=1 / kill switch),
    ``"initialized(N=...)"`` on success, or ``"unavailable: <err>"``
    when the runtime refused — the caller continues single-process
    either way (the loopback-fallback honesty rule of FLEET_r01).
    """
    from pint_tpu.fleet.router import fleet_enabled

    n = config.env_int("PINT_TPU_FLEET_PROCESSES")
    if n <= 1 or not fleet_enabled():
        return "off"
    coord = config.env_str("PINT_TPU_FLEET_COORD")
    pid = config.env_int("PINT_TPU_FLEET_PROCESS_ID")
    try:
        import jax

        jax.distributed.initialize(coordinator_address=coord,
                                   num_processes=n, process_id=pid)
        return f"initialized(N={n}, process={pid})"
    except Exception as e:  # noqa: BLE001 — recorded, never fatal
        return f"unavailable: {type(e).__name__}: {e}"


def build_host_scheduler(host_id: str, **sched_kwargs):
    """One scheduler over this PROCESS's local devices.

    ``jax.local_devices()`` — not ``jax.devices()`` — is the pool: in
    a jax.distributed fleet the global device list spans processes,
    and a scheduler must only place buffers on devices its own process
    addresses. Single-process, the two lists are identical."""
    import jax

    from pint_tpu.serve.scheduler import ThroughputScheduler

    sched_kwargs.setdefault("devices", list(jax.local_devices()))
    return ThroughputScheduler(host_id=host_id, **sched_kwargs)


def run_worker(port: int, host_id: str, *, max_queue: int = 256,
               window: int = 2, ready_fh=None) -> int:
    """Worker main: distributed init, local scheduler, serve protocol."""
    from pint_tpu.fleet.transport import serve_worker

    # touch the program store FIRST (ISSUE 16): with PINT_TPU_PROGRAM_
    # CACHE_DIR set this wires the persistent XLA compile cache before
    # the process's first compile, and primes the manifest so the
    # worker's first fits count warm after a restart. No-op (None)
    # with the knob unset.
    from pint_tpu.programs.store import store as _store

    _store()
    dist = init_distributed()
    sched = build_host_scheduler(host_id, max_queue=max_queue,
                                 window=window)
    import jax

    extra = {"jax_distributed": dist, "pid": os.getpid(),
             "n_local_devices": len(jax.local_devices()),
             "backend": jax.default_backend()}
    return serve_worker(sched, port,
                        ready_fh=ready_fh if ready_fh is not None
                        else sys.stdout,
                        extra_report=extra)


def spawn_local_workers(n: int, *, env=None, env_per_worker=None,
                        ready_timeout_s: float = 120.0,
                        distributed: bool = False,
                        coord_port: int = 9733, prefix: str = "w"):
    """Spawn N real worker processes on this machine; returns
    ``[(host_id, port, Popen)]`` once every worker's ready line has
    been read (ports are OS-assigned: ``--port 0``; host ids are
    ``<prefix>0..<prefix>N-1``).

    ``env_per_worker`` (optional, length >= n) layers per-worker
    overrides on top of ``env`` — the supply-chain A/B gives each
    worker its own ``PINT_TPU_PROGRAM_CACHE_DIR`` this way (a program
    store is per-host state; sharing one dir would fake the shipping
    protocol's work).

    With ``distributed=True`` the workers are armed to attempt
    ``jax.distributed.initialize`` against a local coordinator
    (process 0); whether that succeeded is read from each worker's
    ``report`` op, not assumed."""
    out = []
    procs = []
    for i in range(n):
        wenv = dict(os.environ, **(env or {}))
        if env_per_worker is not None:
            wenv.update(env_per_worker[i] or {})
        wenv.setdefault("JAX_PLATFORMS", "cpu")
        if distributed:
            wenv["PINT_TPU_FLEET_PROCESSES"] = str(n)
            wenv["PINT_TPU_FLEET_PROCESS_ID"] = str(i)
            wenv["PINT_TPU_FLEET_COORD"] = f"127.0.0.1:{coord_port}"
        p = subprocess.Popen(
            [sys.executable, "-m", "pint_tpu.fleet", "worker",
             "--port", "0", "--host-id", f"{prefix}{i}"],
            env=wenv, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)
        procs.append((f"{prefix}{i}", p))
    deadline = time.time() + ready_timeout_s
    for hid, p in procs:
        line = ""
        while time.time() < deadline:
            line = p.stdout.readline()
            if line.strip().startswith("{"):
                break
            if not line and p.poll() is not None:
                break  # child died before its ready line: fail fast
                # (a closed stdout returns "" instantly — without the
                # poll() check this loop would busy-spin the timeout)
        if not line.strip().startswith("{"):
            for _hid, q in procs:
                q.kill()
            raise TimeoutError(
                f"worker {hid} never reported ready within "
                f"{ready_timeout_s:g}s"
                + (f" (exited rc={p.returncode})"
                   if p.poll() is not None else ""))
        info = json.loads(line)
        out.append((hid, int(info["port"]), p))
    return out
