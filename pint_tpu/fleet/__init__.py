"""pint_tpu.fleet — fingerprint-sticky multi-host routing (ISSUE 12).

The scale-OUT tier over :mod:`pint_tpu.serve`: a
:class:`~pint_tpu.fleet.router.FleetRouter` rendezvous-hashes structure
fingerprints onto N per-host schedulers so each structure's compiled
programs, sessions and read caches stay hot on exactly one host, with
session stickiness, cold-structure work stealing, health-fed failover
(reads before fits) and a transport seam
(:mod:`pint_tpu.fleet.transport`) whose loopback implementation proves
every routing invariant without sockets or silicon. ``python -m
pint_tpu.fleet worker`` runs one real host process
(:mod:`pint_tpu.fleet.worker`; TCP/JSONL, optional jax.distributed).
At N=1 — or under ``PINT_TPU_FLEET=0`` — everything degenerates
bitwise to the single-host path. See docs/ARCHITECTURE.md
"Fleet tier".
"""

from __future__ import annotations

from pint_tpu import config

from pint_tpu.fleet.durability import SessionJournal  # noqa: F401
from pint_tpu.fleet.router import (  # noqa: F401
    FleetHandle, FleetPredictHandle, FleetRouter, fleet_enabled,
    rendezvous_rank)
from pint_tpu.fleet.transport import (  # noqa: F401
    HostDown, HostSuspect, LoopbackHost, TcpHost, serve_worker)


def build_fleet(n_hosts: int | None = None, *,
                host_ids=None, router_kwargs=None,
                **sched_kwargs) -> FleetRouter:
    """An N-host LOOPBACK fleet (one process, N schedulers).

    The zero-network construction tests/bench/soak use; real
    deployments build :class:`~pint_tpu.fleet.transport.TcpHost`
    transports against ``python -m pint_tpu.fleet worker`` processes
    and hand them to :class:`FleetRouter` directly. ``n_hosts``
    defaults to ``PINT_TPU_FLEET_PROCESSES`` (1 when unset); N=1 or
    ``PINT_TPU_FLEET=0`` yields the degenerate single-host router.
    ``sched_kwargs`` pass through to every host's scheduler.
    """
    if n_hosts is None:
        n_hosts = config.env_int("PINT_TPU_FLEET_PROCESSES")
    if not fleet_enabled():
        n_hosts = 1
    n_hosts = max(1, int(n_hosts))
    ids = list(host_ids) if host_ids is not None else [
        f"host{i}" for i in range(n_hosts)]
    hosts = [LoopbackHost(hid, **sched_kwargs) for hid in ids]
    return FleetRouter(hosts, **(router_kwargs or {}))


__all__ = [
    "FleetHandle", "FleetPredictHandle", "FleetRouter", "HostDown",
    "HostSuspect", "LoopbackHost", "SessionJournal", "TcpHost",
    "build_fleet", "fleet_enabled", "rendezvous_rank", "serve_worker",
]
