"""``python -m pint_tpu.fleet`` — fleet worker/selftest CLI.

* ``worker --port P --host-id ID``: run one host process (port 0 =
  OS-assigned; the ready line on stdout carries the bound port).
* ``selftest [--hosts N]``: spin an N-host loopback fleet in-process,
  run a tiny routed fit roundtrip, and print the fleet drain record —
  the zero-silicon smoke an operator runs before pointing real
  traffic at a pod.
"""

from __future__ import annotations

import argparse
import json
import sys


def _selftest(n_hosts: int) -> int:
    import numpy as np

    from pint_tpu.fleet import build_fleet
    from pint_tpu.models import get_model
    from pint_tpu.serve.scheduler import FitRequest
    from pint_tpu.simulation import make_fake_toas_uniform

    par = ("PSRJ FLEET_SELFTEST\nF0 61.485476554 1\nF1 -1.181e-15 1\n"
           "PEPOCH 53750\nRAJ 17:48:52.75\nDECJ -20:21:29.0\n"
           "POSEPOCH 53750\nDM 223.9\nEPHEM DE421\nUNITS TDB\n"
           "TZRMJD 53801.0\nTZRFRQ 1400.0\nTZRSITE @\n")
    router = build_fleet(n_hosts)
    for i in range(4):
        truth = get_model(par)
        toas = make_fake_toas_uniform(
            53000, 56000, 40, truth, obs="@", freq_mhz=1400.0,
            error_us=2.0, add_noise=True, seed=200 + i)
        m = get_model(par)
        m["F0"].add_delta(2e-10)
        router.submit(FitRequest(toas, m, tag=i, maxiter=8,
                                 min_chi2_decrease=1e-5))
    res = router.drain()
    ok = all(r.status == "ok" and np.isfinite(r.chi2) for r in res)
    print(json.dumps({"ok": ok, "hosts": n_hosts,
                      "degenerate": router.degenerate,
                      "results": [{"tag": r.tag, "status": r.status,
                                   "host": r.host} for r in res],
                      "record": router.last_drain}))
    return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="python -m pint_tpu.fleet")
    sub = ap.add_subparsers(dest="cmd", required=True)
    w = sub.add_parser("worker", help="run one fleet host process")
    w.add_argument("--port", type=int, default=0,
                   help="TCP port (0 = OS-assigned, reported on the "
                        "ready line)")
    w.add_argument("--host-id", default="w0")
    w.add_argument("--max-queue", type=int, default=256)
    w.add_argument("--window", type=int, default=2)
    st = sub.add_parser("selftest",
                        help="N-host loopback fleet roundtrip")
    st.add_argument("--hosts", type=int, default=2)
    args = ap.parse_args(argv)
    if args.cmd == "worker":
        from pint_tpu.fleet.worker import run_worker

        run_worker(args.port, args.host_id, max_queue=args.max_queue,
                   window=args.window)
        return 0
    return _selftest(args.hosts)


if __name__ == "__main__":
    sys.exit(main())
