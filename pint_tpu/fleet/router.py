"""Fingerprint-sticky rendezvous routing over N per-host schedulers.

ROADMAP item 1 / ISSUE 12: every tier below this one scales within ONE
process — union batching, mesh placement, fault domains, sessions, the
read path all live inside a single
:class:`~pint_tpu.serve.scheduler.ThroughputScheduler`. The fleet tier
is the scale-OUT seam: a :class:`FleetRouter` in front of N host
transports (:mod:`pint_tpu.fleet.transport`), each owning one
scheduler over its process-local device pool.

**Routing IS the performance feature.** Compiled fit programs, TZR
caches, session rank-k state and read-path segment caches are all
per-host (device memory + process-local jit caches): a request landing
on the wrong host pays a full recompile (~tens of seconds) instead of
a ~ms warm-cache hit. The router therefore concentrates each structure
on exactly one host:

* **Rendezvous (HRW) hashing** on the structure-fingerprint short id:
  every (key, host) pair gets a deterministic score
  (:func:`rendezvous_rank`); the key routes to its highest-scoring
  alive host. Host join/leave moves only the keys whose top choice
  changed — ~1/N of them, measured over 1k fingerprints in
  tests/test_fleet.py — while every other structure stays hot where it
  is. No central ring state: the ranking is a pure function of
  (key, host ids).
* **Session stickiness** keyed ``(session_id, fingerprint)``: the
  first sessionful request pins its session to the routed host; every
  later append and read follows the pin (rank-k device state and
  polycos segment caches are that host's memory), surviving ring
  rebalance — a new host joining NEVER moves an existing session, only
  fresh structures.
* **Work stealing for cold structures**: when the sticky host's queue
  depth reaches ``steal_depth`` and the structure is not yet warm
  there, the request goes to the least-loaded healthy host instead —
  a cold structure recompiles wherever it lands, so stealing costs
  nothing extra and drains the hot spot. Warm structures are NEVER
  stolen (that would trade a queue wait for a recompile).
* **Health + failover**: per-host health is fed only from
  :meth:`~pint_tpu.serve.scheduler.ThroughputScheduler.report`
  envelopes (fail streak, queue depth, degraded flag — the PR-6
  degradation ladder, now visible across hosts) plus transport-level
  :class:`~pint_tpu.fleet.transport.HostDown` failures. A *degraded*
  host sheds fits to its ring successor (the next host in its
  rendezvous ranking); **reads fail over before fits** — a merely
  *suspect* host (fail streak >= 1, below the degrade threshold)
  already loses its model-carrying reads (any host can serve those
  dense) while fits keep flowing until the ladder actually trips.
  A dead host's pending work is re-routed and re-submitted at drain —
  never silently dropped; requests that cannot be re-served elsewhere
  (a session append whose state died with the host and whose request
  carries no model) resolve as structured ``failed`` envelopes.

At N=1 — or under the ``PINT_TPU_FLEET=0`` kill switch — the router is
*degenerate*: every request goes to host 0 with zero routing
bookkeeping (no second fingerprint canonicalization, no health
machinery on the submit path), so the single-host path is bitwise
today's behavior (pinned in tests/test_fleet.py).

Telemetry: ``fleet.*`` counters (route split, failovers, steals,
host-down events), one ``type="fleet"`` record per router drain with
the per-host report block — rendered by ``python -m
pint_tpu.telemetry.report`` under "fleet tier".
"""

from __future__ import annotations

import hashlib
from pint_tpu import config
import time
from typing import Any

from pint_tpu import telemetry
from pint_tpu.fleet import durability as _dur
from pint_tpu.fleet.transport import HostDown, HostSuspect
from pint_tpu.serve import fingerprint as _fp
from pint_tpu.serve.scheduler import (FitResult, PredictRequest,
                                      PredictResult, ServeQueueFull)


def fleet_enabled() -> bool:
    """Kill switch (read per call so tests can flip it):
    ``PINT_TPU_FLEET=0`` forces the degenerate single-host path."""
    return config.env_on("PINT_TPU_FLEET")


def _score(host_id: str, key: str) -> str:
    """The (host, key) rendezvous score: a content digest, never
    ``hash()`` (salted per process — the ranking must agree across
    router restarts and across processes)."""
    return hashlib.sha1(f"{host_id}|{key}".encode()).hexdigest()


def rendezvous_rank(key: str, host_ids) -> list[str]:
    """All hosts ranked for ``key``, best first (highest-random-weight
    hashing). Deterministic in (key, set of hosts): independent of list
    order, stable across processes, and removing a host only promotes
    lower-ranked hosts — keys whose top choice survives never move."""
    return sorted(host_ids, key=lambda h: _score(h, key), reverse=True)


#: Test seam for the elastic join handshake (ISSUE 16): when set, the
#: router calls it as ``hook(stage, host_id)`` at each join stage
#: ("selected", "pulled", "shipped", "ready") — the SIGKILL-mid-adopt
#: test uses it to kill the joining worker at a precise stage. Never
#: set in production.
_JOIN_STAGE_HOOK = None


class FleetHandle:
    """Future-like handle for a routed fit (the router's FitHandle)."""

    __slots__ = ("_result", "host", "route")

    def __init__(self, host: str, route: str):
        self._result: FitResult | None = None
        self.host = host      # host id the request was routed to
        self.route = route    # routing token (sticky/rendezvous/...)

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> FitResult:
        if self._result is None:
            raise RuntimeError("request not drained yet; call "
                               "FleetRouter.drain() first")
        return self._result


class FleetPredictHandle:
    """Future-like handle for a routed queued read."""

    __slots__ = ("_result", "host")

    def __init__(self, host: str):
        self._result: PredictResult | None = None
        self.host = host

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> PredictResult:
        if self._result is None:
            raise RuntimeError("read not drained yet; call "
                               "FleetRouter.drain_reads() first")
        return self._result


class FleetCatalogHandle:
    """Pollable fleet-side handle for a routed catalog long job: the
    router refreshes ``progress`` (and the checkpoint behind it) once
    per drain slice; ``host`` tracks the CURRENT owner across
    failovers."""

    __slots__ = ("_router", "job_id")

    def __init__(self, router: "FleetRouter", job_id: str):
        self._router = router
        self.job_id = job_id

    @property
    def host(self) -> str:
        return self._router._catalog[self.job_id]["host"]

    def done(self) -> bool:
        p = self._router._catalog[self.job_id].get("progress")
        return bool(p and p.get("state") in ("done", "failed"))

    def progress(self) -> dict | None:
        """The last slice's progress dict (None before the first
        slice); includes fleet routing fields."""
        e = self._router._catalog[self.job_id]
        p = e.get("progress")
        if p is None:
            return None
        return dict(p, host=e["host"],
                    fleet_resumes=e["resumes"])

    def result(self) -> dict:
        if not self.done():
            raise RuntimeError(
                f"catalog job {self.job_id} still running; keep "
                "draining the router")
        return self.progress()


class _Pending:
    """One routed, not-yet-resolved request on a host. Sessionful
    requests also carry their session key and the pin EPOCH they were
    submitted under (ISSUE 13): a commit arriving after the session
    re-pinned — the submit epoch no longer current — is fenced."""

    __slots__ = ("seq", "token", "request", "handle", "route", "read",
                 "skey", "epoch")

    def __init__(self, seq, token, request, handle, route, read=False,
                 skey=None, epoch=0):
        self.seq = seq
        self.token = token
        self.request = request
        self.handle = handle
        self.route = route
        self.read = read
        self.skey = skey
        self.epoch = epoch


class FleetRouter:
    """Route fits/reads over host transports; drain and resolve them.

    ``hosts`` is a list of transports (each carries a unique
    ``host_id``). ``steal_depth`` is the queue depth at which a cold
    structure is stolen to the least-loaded host; ``degrade_after``
    the router-side fail-streak threshold above which a host that
    stopped reporting cleanly counts as degraded even without a
    report saying so. ``degenerate`` forces the N=1 fast path
    (implied by a single host or the ``PINT_TPU_FLEET=0`` switch).
    """

    def __init__(self, hosts, *, steal_depth: int = 8,
                 degrade_after: int = 2, dead_after: int = 3,
                 degenerate: bool = False):
        hosts = list(hosts)
        if not hosts:
            raise ValueError("FleetRouter needs at least one host")
        ids = [h.host_id for h in hosts]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids: {ids}")
        self.hosts = {h.host_id: h for h in hosts}
        self._order = ids
        self.steal_depth = max(1, int(steal_depth))
        self.degrade_after = max(1, int(degrade_after))
        # the suspicion ladder's top rung (ISSUE 13): this many
        # CONSECUTIVE transport deadline misses presume the host dead
        # (one miss only suspects it — reads re-route, fencing arms)
        self.dead_after = max(1, int(dead_after))
        self.degenerate = bool(degenerate or len(hosts) == 1
                               or not fleet_enabled())
        self._health: dict[str, dict] = {
            hid: {"alive": True, "ready": True, "fail_streak": 0,
                  "queue_depth": 0, "read_depth": 0, "degraded": False,
                  "latency_s": None, "program_misses": 0, "misses": 0}
            for hid in ids}
        self._warm: dict[str, set] = {hid: set() for hid in ids}
        # per-fp8 request counts (ISSUE 16): the popularity stats that
        # rank a joining host's prewarm adopt set — hottest structures
        # ship first, bounded so a long-lived router cannot grow it
        # unboundedly over one-shot structures
        self._popularity: dict[str, int] = {}
        self._sticky: dict[tuple, str] = {}   # (sid, fp8) -> host id
        self._sid_last: dict[Any, tuple] = {}  # sid -> last sticky key
        self._inflight: dict[str, int] = {hid: 0 for hid in ids}
        self._pending: dict[str, list[_Pending]] = {hid: [] for hid in ids}
        self._seq = 0
        self._route_counts: dict[str, int] = {}
        self._failovers = 0
        # lifetime totals for the live plane (the per-drain counters
        # above zero out in _emit_record; fleet_metrics must not)
        self._failovers_total = 0
        self._fenced_rejects_total = 0
        self._warm_hits = 0   # requests landing on an already-warm host
        self._warm_total = 0  # ... out of all warm-trackable fits
        # durable sessions (ISSUE 13): the append journal, per-session
        # pin epochs, and per-host fence maps of tokens whose work was
        # re-routed away while the host might still reply
        self._journal = _dur.SessionJournal()
        self._epoch: dict[tuple, int] = {}
        self._fence: dict[str, dict] = {}
        # (host, session_id) pairs whose sessionful SUBMIT timed out
        # after the host may have accepted it: the host may hold an
        # orphaned (never-acknowledged) session entry that a later
        # shed/re-route back to it must drop before submitting — an
        # append resolving against the orphan would commit diverged
        # state (at-least-once submits, exactly-once session effect)
        self._maybe_orphaned: set[tuple] = set()
        self._committed: set = set()   # skeys committed this drain
        self._replicated = 0           # per-drain durability counters
        self._replayed = 0
        self._fenced_rejects = 0
        self._duplicates = 0
        self._restores: dict[str, int] = {}
        # catalog long jobs (ISSUE 14): job_id -> routing entry. The
        # router advances each job one slice per drain and stashes the
        # slice's CHECKPOINT here — the long-job analogue of the
        # session journal: a host death costs the slice since the last
        # checkpoint, never the fit
        self._catalog: dict[str, dict] = {}
        self._catalog_resumes = 0
        #: wall seconds this drain spent BLOCKED on unresponsive hosts
        #: (deadline misses + dead sockets) — the quantity the ISSUE-13
        #: liveness work bounds at one op deadline + one heartbeat per
        #: hung host, vs the old flat 600 s; productive failover work
        #: (restores, re-fits on live hosts) is not blocked time
        self._blocked_s = 0.0
        self.last_drain: dict | None = None

    # ------------------------------------------------------------------
    # health
    # ------------------------------------------------------------------
    def alive_hosts(self) -> list[str]:
        """Routable hosts: alive AND ready. A joining host is
        registered but not ready until its adopt set is loaded
        (ISSUE 16) — no traffic routes to it mid-handshake."""
        return [h for h in self._order
                if self._health[h]["alive"]
                and self._health[h].get("ready", True)]

    def _degraded(self, hid: str) -> bool:
        h = self._health[hid]
        return bool(h["degraded"]
                    or h["fail_streak"] >= self.degrade_after)

    def _suspect(self, hid: str) -> bool:
        """Read-level caution: trips BEFORE the fit-shedding threshold
        (reads fail over first — any host serves a model-carrying read
        dense, so there is no reason to send one toward trouble)."""
        h = self._health[hid]
        return bool(self._degraded(hid) or h["fail_streak"] >= 1)

    def _depth(self, hid: str) -> int:
        return self._health[hid]["queue_depth"] + self._inflight[hid]

    @staticmethod
    def _drain_deadline(pend) -> float:
        """The wire deadline for draining these pendings: the largest
        per-request SLA carried by any of them, floored at the fleet
        op default — per-request deadlines propagated over the wire
        (ISSUE 13), replacing the old flat 600 s socket timeout.

        A drain is an AGGREGATE op (the host executes its whole
        queue), so the allowance scales with the pending count — an
        eighth of the base per extra request — or a deep-queued but
        healthy host would be falsely suspected and its entire batch
        re-run elsewhere. Operators size ``PINT_TPU_FLEET_OP_
        DEADLINE_S`` to their per-drain SLA; the TcpHost ``timeout_s``
        ceiling (600 s) still caps everything."""
        base = _dur.op_deadline_s()
        dls = [p.request.deadline_s for p in pend
               if getattr(p.request, "deadline_s", None)]
        return max([base] + dls) + base * max(0, len(pend) - 1) / 8.0

    def add_host(self, transport) -> None:
        """Host JOIN: register a new transport and run the elastic
        join handshake (ISSUE 16). Rendezvous ranking is a pure
        function of (key, host set), so only keys whose top score the
        new host beats move to it (~1/(N+1), measured in
        tests/test_fleet.py) — and existing session pins never move
        (stickiness beats the ring).

        The join is gated on READINESS: the host registers not-ready
        (invisible to routing), the router selects its prewarm adopt
        set from popularity stats, pulls the shipment from a warm
        donor, ships it to the joiner (whose store eager-loads the
        executables), re-stashes the session replicas the new ring
        assigns it, and only then marks it routable. Every stage is
        best-effort; a joiner that dies mid-adopt is abandoned (left
        not-ready — a later heartbeat answer readmits it cold) and
        in-flight traffic never notices. With shipping off
        (``PINT_TPU_PROGRAM_SHIP=0``), no popularity yet, or the
        degenerate fleet, the handshake is a no-op and the join is
        exactly the pre-ISSUE-16 instant join."""
        hid = transport.host_id
        if hid in self.hosts:
            raise ValueError(f"duplicate host id {hid!r}")
        self.hosts[hid] = transport
        self._order.append(hid)
        self._health[hid] = {"alive": True, "ready": False,
                             "fail_streak": 0, "queue_depth": 0,
                             "read_depth": 0, "degraded": False,
                             "latency_s": None, "program_misses": 0,
                             "misses": 0}
        self._warm[hid] = set()
        self._inflight[hid] = 0
        self._pending[hid] = []
        telemetry.inc("fleet.host_join")
        self._join_prewarm(hid, transport)
        self.degenerate = False if len(self._order) > 1 \
            and fleet_enabled() else self.degenerate

    def _join_prewarm(self, hid: str, transport) -> None:
        """The supply-chain half of a join: select/pull/ship/adopt,
        then flip readiness. See :meth:`add_host`."""
        from pint_tpu.programs import ship as _ship

        h = self._health[hid]
        hook = _JOIN_STAGE_HOOK
        try:
            top_k = config.env_int("PINT_TPU_PREWARM_TOP_K")
            if (self.degenerate or top_k <= 0 or not self._popularity
                    or not config.env_on("PINT_TPU_PROGRAM_SHIP")):
                h["ready"] = True
                if hook:
                    hook("ready", hid)
                return
            donors = [d for d in self._order
                      if d != hid and self._health[d]["alive"]
                      and self._health[d].get("ready", True)
                      and not self._suspect(d)]
            adopt = _ship.select_adopt_set(
                self._popularity, [*donors, hid], hid, top_k,
                rendezvous_rank)
            if hook:
                hook("selected", hid)
            # one donor suffices: XLA cache entries + warm keys are
            # host-global, and the blob tier dedups by key anyway.
            # Prefer the donor holding the most of the adopt set warm.
            shipment = None
            for d in sorted(donors,
                            key=lambda d: -len(self._warm[d]
                                               & set(adopt))):
                try:
                    shipment = self.hosts[d].pull_programs(
                        adopt, deadline_s=_dur.op_deadline_s())
                except HostSuspect:
                    self._note_timeout(d)
                    continue
                except (HostDown, OSError):
                    self._note_down(d)
                    continue
                if shipment and any(shipment.get(k)
                                    for k in ("blobs", "xla", "keys")):
                    break
                shipment = None
            if hook:
                hook("pulled", hid)
            if shipment is not None:
                # adopt may deserialize+compile-load: slow-path deadline
                res = transport.ship_programs(
                    shipment,
                    deadline_s=max(_dur.op_deadline_s(), 300.0))
                self._warm[hid].update(adopt)
                telemetry.inc("fleet.join.adopted",
                              int(res.get("adopted", 0)))
                telemetry.add_record({
                    "type": "fleet_join", "host": hid,
                    "adopt_set": list(adopt), **(res or {})})
            if hook:
                hook("shipped", hid)
            self._join_restash(hid)
            h["ready"] = True
            telemetry.inc("fleet.join.ready")
            if hook:
                hook("ready", hid)
        except HostSuspect:
            self._note_timeout(hid)
            self._abandon_join(hid)
        except (HostDown, OSError):
            self._note_down(hid)
            self._abandon_join(hid)

    def _join_restash(self, hid: str) -> None:
        """Re-stash session replicas the NEW ring assigns to ``hid``
        (best-effort, bounded): the joiner becomes ring successor for
        ~1/(N+1) of the journaled sessions, and replicating their
        summaries now — before it takes traffic — means a later
        failover onto it restores WARM instead of replaying the whole
        journal."""
        done = 0
        for skey, lg in list(self._journal.logs.items()):
            if done >= 16:
                break
            pin = self._sticky.get(skey)
            if pin is None or pin == hid \
                    or not self._health[pin]["alive"]:
                continue
            if self._ring_successor(skey, pin) != hid:
                continue
            try:
                summary = self.hosts[pin].session_summary(skey)
                if summary is None:
                    continue
                blob = _dur.build_replica(
                    summary, epoch=self._epoch.get(skey, 0))
                self.hosts[hid].stash_replica(skey, blob)
                self._journal.note_replica(skey, hid,
                                           summary["model_blob"])
                done += 1
                telemetry.inc("fleet.join.restashed")
            except Exception:  # noqa: BLE001 — replica shipping is
                continue       # always best-effort (ISSUE 13 contract)

    def _abandon_join(self, hid: str) -> None:
        """The joiner died/hung mid-handshake: leave it registered but
        NOT ready — zero traffic ever routed to it, so nothing fails
        over and nothing is lost. If it answers a later heartbeat it
        is readmitted (cold: its adopt set never finished loading)."""
        telemetry.inc("fleet.join.abandoned")
        telemetry.add_record({"type": "fleet_join", "host": hid,
                              "abandoned": True})

    def retire_host(self, host_id: str) -> None:
        """Host LEAVE (administrative): mark it dead so routing moves
        its keys to their next-ranked hosts; pending work fails over at
        the next :meth:`drain` exactly like a crash."""
        if host_id not in self.hosts:
            raise KeyError(host_id)
        self._health[host_id]["alive"] = False
        telemetry.inc("fleet.host_leave")

    def mark(self, host_id: str, *, alive: bool | None = None,
             fail_streak: int | None = None,
             degraded: bool | None = None) -> None:
        """Operator/test surface: override one host's health state
        (e.g. administratively drain a host before maintenance). The
        next report from the host refreshes the report-fed fields."""
        h = self._health[host_id]
        if alive is not None:
            h["alive"] = bool(alive)
        if fail_streak is not None:
            h["fail_streak"] = int(fail_streak)
        if degraded is not None:
            h["degraded"] = bool(degraded)

    def _note_down(self, hid: str) -> None:
        h = self._health[hid]
        if h["alive"]:
            telemetry.inc("fleet.host_down")
        h["alive"] = False
        h["fail_streak"] += 1

    def _note_timeout(self, hid: str) -> None:
        """One transport deadline miss: climb the suspicion ladder
        (ISSUE 13). First miss -> suspect (fail streak feeds the
        existing read-failover-first rule); ``dead_after`` consecutive
        misses -> presumed dead (full failover). A later successful
        heartbeat resets the ladder — and fences any late replies the
        host accumulated while partitioned."""
        h = self._health[hid]
        h["misses"] += 1
        h["fail_streak"] += 1
        telemetry.inc("fleet.heartbeat.miss")
        if h["misses"] >= self.dead_after and h["alive"]:
            self._note_down(hid)

    def heartbeat(self) -> dict:
        """One liveness pass over every host: a cheap ``ping`` under
        the heartbeat deadline (``PINT_TPU_FLEET_HEARTBEAT_S``) drives
        the suspicion ladder WITHOUT waiting on a full drain deadline.
        A host that answers after being suspected/presumed dead first
        has its late replies collected and FENCED
        (:meth:`_reconcile`), then rejoins the ring for fresh work —
        its sessions stay wherever failover re-pinned them (the stale
        epoch keeps its old commits harmless). Runs at the top of
        every :meth:`drain`; callable standalone as the operator's
        liveness probe. Returns {host: status token}."""
        if self.degenerate:
            return {}
        out: dict[str, str] = {}
        dl = _dur.heartbeat_deadline_s()
        for hid in list(self._order):
            h = self._health[hid]
            t0 = time.perf_counter()
            try:
                self.hosts[hid].ping(dl)
            except HostSuspect:
                self._blocked_s += time.perf_counter() - t0
                self._note_timeout(hid)
                out[hid] = "suspect" if h["alive"] else "dead"
                continue
            except (HostDown, OSError):
                self._blocked_s += time.perf_counter() - t0
                self._note_down(hid)
                out[hid] = "dead"
                continue
            was_dead = not h["alive"]
            h["misses"] = 0
            if was_dead or self._fence.get(hid):
                # the host is responsive again but may hold replies to
                # work this router already re-routed: drain + fence
                # them BEFORE it serves anything new
                self._reconcile(hid)
            if was_dead:
                h["alive"] = True
                h["fail_streak"] = 0
                telemetry.inc("fleet.host_rejoin")
                out[hid] = "rejoined"
            else:
                out[hid] = "ok"
            if not h.get("ready", True):
                # an ABANDONED join answering again: readmit it cold
                # (its adopt set never finished loading — it simply
                # compiles on demand like a pre-ISSUE-16 joiner)
                h["ready"] = True
                telemetry.inc("fleet.join.readmitted")
        telemetry.set_gauge("fleet.hosts_alive", len(self.alive_hosts()))
        telemetry.set_gauge(
            "fleet.hosts_suspect",
            sum(1 for hid in self._order
                if self._health[hid]["alive"] and self._suspect(hid)))
        return out

    def _reconcile(self, hid: str) -> None:
        """Collect a recovered host's LATE replies and fence them.

        Every token here answers a request the router failed over
        while the host was unresponsive — the duplicate execution of
        the at-least-once retry. The fence map carries the (session
        key, submit epoch) of each; all are rejected (counted,
        recorded with the stale epoch) and none touches the journal or
        a caller's handle. Skipped while the host still holds live
        pendings (a regular drain owns those)."""
        if self._pending[hid]:
            return
        dl = _dur.heartbeat_deadline_s()
        try:
            wires = list(self.hosts[hid].drain(dl))
            wires += list(self.hosts[hid].drain_reads(dl))
        except (HostDown, HostSuspect, OSError):
            return
        fence = self._fence.get(hid) or {}
        for w in wires:
            tok = w.get("token") if isinstance(w, dict) else None
            info = fence.pop(tok, None) if tok is not None else None
            if info is not None:
                self._fence_reject(hid, tok, info)
            elif tok is not None:
                telemetry.inc("fleet.transport.stale_replies")

    def _fence_reject(self, hid: str, token, info: tuple,
                      ctx=None) -> None:
        """Reject one stale-epoch commit/reply (never applied to the
        caller's model, the journal, or replication)."""
        skey, epoch = info
        self._fenced_rejects += 1
        self._fenced_rejects_total += 1
        telemetry.inc("fleet.session.fenced_rejects")
        telemetry.add_record(telemetry.trace.stamp({
            "type": "fleet_fence", "host": hid, "token": token,
            "session": repr(skey[0]) if skey else None,
            "stale_epoch": epoch,
            "epoch": self._epoch.get(skey, 0) if skey else None}, ctx))

    def _fence_arm(self, hid: str, p: _Pending) -> None:
        """The router is about to re-run ``p`` elsewhere while ``hid``
        may still reply: remember the token so the late duplicate is
        recognized and rejected (FIFO-bounded — an overflowing entry
        degrades to the stale-reply counter, never a double-commit:
        unmatched tokens are always dropped)."""
        fm = self._fence.setdefault(hid, {})
        while len(fm) >= 256:
            fm.pop(next(iter(fm)))
        fm[p.token] = (p.skey, p.epoch)

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def _fit_candidates(self, key: str) -> list[str]:
        """Fit routing order for ``key``: rendezvous ranking over alive
        hosts, degraded hosts moved to the back (a degraded host sheds
        to its ring successor — the next alive host in ITS OWN
        ranking — but remains the last resort before failing)."""
        ranked = rendezvous_rank(key, self.alive_hosts())
        return ([h for h in ranked if not self._degraded(h)]
                + [h for h in ranked if self._degraded(h)])

    def _ring_successor(self, skey: tuple,
                        exclude: str | None) -> str | None:
        """THE session ring successor: the first host in the session
        key's own ring order that is not ``exclude``, is alive, and
        has not missed a deadline this cycle (restoring onto or
        stashing at a suspect host would trade the stall we just
        avoided for a new one). One definition shared by replication,
        failover restore and re-pinning — the three must never
        disagree about who the successor is."""
        for h in self._fit_candidates(skey[1] or repr(skey[0])):
            if h != exclude and self._health[h]["alive"] \
                    and not self._health[h]["misses"]:
                return h
        return None

    def _route_fit(self, request) -> tuple[str, str, str | None]:
        """(host id, route token, fp8) for one fit request — fp8 is
        threaded back so the submit path ranks its fallback candidates
        by the request's OWN ring order and never canonicalizes the
        structure twice."""
        sid = getattr(request, "session_id", None)
        fp8 = None
        if request.model is not None:
            fp8 = _fp.short_id(
                _fp.structure_fingerprint(request.model, request.toas))
        if sid is not None:
            skey = (sid, fp8) if fp8 is not None else self._sid_last.get(sid)
            if skey is None:
                raise ValueError(
                    f"session {sid!r} is unknown to the fleet and the "
                    "request carries no model; the first request of a "
                    "session must include one")
            self._sid_last[sid] = skey
            hid = self._sticky.get(skey)
            if hid is not None and self._health[hid]["alive"] \
                    and not self._degraded(hid):
                return hid, "sticky", skey[1]
            if hid is not None:
                # sticky host dead/degraded: fail over to the ring
                # successor. ISSUE 13: the re-pin ADOPTS the session's
                # replicated/journaled state on the successor BEFORE
                # this request dispatches — warm from the replica when
                # the successor holds one, else a journal replay — so
                # the retry appends to the dead host's solution, not
                # to reconstructed-from-nothing state. The epoch bumps
                # either way: any late commit from the old pin is now
                # fenced.
                new = self._ring_successor(skey, hid)
                if new is None:
                    new = next(
                        (h for h in self._fit_candidates(
                            skey[1] or repr(sid)) if h != hid), hid)
                if new != hid and not self.degenerate:
                    self._restore_session(skey, new)
                self._sticky[skey] = new
                return new, "failover", skey[1]
            hid, token = self._route_structure(fp8)
            self._sticky[skey] = hid
            return hid, token, skey[1]
        return (*self._route_structure(fp8), fp8)

    def _route_structure(self, fp8: str | None) -> tuple[str, str]:
        cands = self._fit_candidates(fp8 or "?")
        if not cands:
            raise HostDown("no alive hosts in the fleet")
        primary = cands[0]
        token = "rendezvous"
        if self._degraded(primary):
            token = "failover"  # every host degraded: last resort
        elif fp8 is not None and primary != rendezvous_rank(
                fp8, self.alive_hosts())[0]:
            token = "failover"  # rendezvous winner was degraded: shed
        if (fp8 is not None and token == "rendezvous"
                and self._depth(primary) >= self.steal_depth
                and fp8 not in self._warm[primary]):
            # cold-structure work stealing: recompiles wherever it
            # lands, so send it to the shortest healthy queue
            others = [h for h in cands[1:] if not self._degraded(h)]
            if others:
                target = min(others, key=self._depth)
                if self._depth(target) < self._depth(primary):
                    return target, "stolen"
        return primary, token

    def _route_read(self, request) -> tuple[str, str]:
        """(host id, token) for one read. Session reads follow the
        sticky pin (the segment cache and committed solution live
        there); model-carrying reads avoid suspect hosts entirely."""
        sid = request.session_id
        if sid is not None:
            skey = self._sid_last.get(sid)
            hid = self._sticky.get(skey) if skey is not None else None
            if hid is not None and self._health[hid]["alive"]:
                if not self._suspect(hid) or request.model is None:
                    # the state lives here; a suspect host still beats
                    # a guaranteed "no committed solution" elsewhere
                    return hid, "sticky"
            if request.model is None:
                if hid is not None:
                    raise HostDown(
                        f"session {sid!r} is pinned to dead host "
                        f"{hid}; resubmit with a model to re-fit")
                raise ValueError(
                    f"session {sid!r} is unknown to the fleet; fit "
                    "(populate) it first")
            # fall through: serve dense from the model, away from the
            # suspect/dead sticky host
        fp8 = "?"
        if request.model is not None:
            fp8 = _fp.short_id(
                _fp.structure_fingerprint(request.model, None))
        ranked = rendezvous_rank(fp8, self.alive_hosts())
        if not ranked:
            raise HostDown("no alive hosts in the fleet")
        clean = [h for h in ranked if not self._suspect(h)]
        if clean:
            return clean[0], ("rendezvous" if clean[0] == ranked[0]
                              else "failover")
        return ranked[0], "failover"

    # ------------------------------------------------------------------
    # durable-session restore (ISSUE 13)
    # ------------------------------------------------------------------
    def _restore_session(self, skey: tuple, target_hid: str,
                         ctx=None) -> str:
        """Rebuild a re-pinned session's committed state on
        ``target_hid`` before any retry dispatches.

        Bumps the pin epoch FIRST (fencing arms even when the rebuild
        fails), then restores: **warm** when the target holds the
        session's replica (one ``adopt`` op installs the committed
        solution + device snapshot; only the journal's post-replication
        suffix replays), **cold** otherwise (replay the journal's base
        populate then every retained append — the exact stream the
        dead host served, so the rebuilt solution matches it at the
        1e-9 class). Replays run through the host-side ``replay`` op:
        atomic on the host, invisible to the router's own pending
        bookkeeping. Returns the restore-kind token (``warm`` /
        ``cold`` / ``miss`` / ``failed``); on anything but
        warm/cold the caller proceeds exactly as pre-ISSUE-13 (the
        retry repopulates from its own payload or resolves a
        structured error)."""
        self._epoch[skey] = self._epoch.get(skey, 0) + 1
        host = self.hosts[target_hid]
        # restore ops run FITS (and may compile the structure cold on
        # the successor): the generous slow-path deadline, never the
        # cheap per-op default
        restore_dl = max(_dur.op_deadline_s(), 300.0)
        # the target must start CLEAN: any entry it already holds for
        # this session is the orphan of an unacknowledged (fenced)
        # commit — an at-least-once duplicate populate resolving as an
        # "append" against it would MERGE the same table twice
        try:
            host.drop_session(skey[0], deadline_s=restore_dl)
            self._maybe_orphaned.discard((target_hid, skey[0]))
        except Exception:  # noqa: BLE001 — a failed drop degrades to
            pass           # the restore-failed path below (or "miss")
        lg = self._journal.log(skey)
        if lg is None or lg.base_toas is None:
            telemetry.inc("fleet.session.restore_miss")
            return "miss"
        kind = "cold"
        try:
            if lg.replica_host == target_hid:
                ad = host.adopt_session(skey, lg.base_toas,
                                        deadline_s=restore_dl)
                if ad.get("adopted"):
                    kind = "warm"
            if kind == "cold":
                populate, appends = _dur.replay_requests(
                    lg, suffix_only=False)
                w0 = host.replay([populate],
                                 deadline_s=restore_dl)[0]
                if w0["status"] not in ("ok", "nonconverged"):
                    raise RuntimeError(
                        f"journal populate replay -> {w0['status']}")
            else:
                _populate, appends = _dur.replay_requests(
                    lg, suffix_only=True)
            if appends:
                wires = host.replay(appends, deadline_s=restore_dl)
                bad = [w for w in wires
                       if w["status"] not in ("ok", "nonconverged")]
                if bad:
                    raise RuntimeError(
                        f"journal append replay -> {bad[0]['status']}")
                self._replayed += len(appends)
                telemetry.inc("fleet.session.replayed", len(appends))
        except Exception as e:  # noqa: BLE001 — restore is best-effort:
            # the retry still runs (PR-12 behavior) and the journal
            # keeps the history for the next attempt
            telemetry.inc("fleet.session.restore_failed")
            telemetry.add_record(telemetry.trace.stamp({
                "type": "fault", "status": "session_restore_failed",
                "host": target_hid, "session": repr(skey[0]),
                "error": f"{type(e).__name__}: {e}"},
                ctx if ctx is not None else telemetry.trace.current()))
            return "failed"
        self._sticky[skey] = target_hid
        self._restores[kind] = self._restores.get(kind, 0) + 1
        telemetry.inc(f"fleet.session.restore.{kind}")
        telemetry.trace.hop(
            ctx if ctx is not None else telemetry.trace.current(),
            "replay", host=target_hid, kind=kind,
            epoch=self._epoch.get(skey, 0))
        return kind

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, request):
        """Route + enqueue one request on its host; returns a
        :class:`FleetHandle` (fits) / :class:`FleetPredictHandle`
        (reads). A full primary host sheds to the next candidate
        (backpressure composes); :class:`ServeQueueFull` surfaces only
        when the whole fleet is full. A host dying at submit fails
        over transparently."""
        read = isinstance(request, PredictRequest)
        # the trace is born HERE (ISSUE 19): the root context rides the
        # request object through every transport op; the root hop
        # itself is emitted in _track once the accepting host is known.
        # The use() scope makes submit-time restore work (replay hops,
        # spans) parent under this request's root.
        if request.trace_ctx is None:
            request.trace_ctx = telemetry.trace.root()
        # hold the ROOT here: a loopback scheduler advances the shared
        # request object's ctx to its accept hop, and the root hop must
        # still be emitted with the original ids
        rctx = request.trace_ctx
        with telemetry.trace.use(rctx):
            return self._submit_routed(request, read, rctx)

    def _submit_routed(self, request, read: bool, rctx=None):
        fp8 = None
        if self.degenerate:
            hid = self._order[0]
            cands, token = [hid], "degenerate"
        else:
            if read:
                hid, token = self._route_read(request)
                cands = [hid] + [h for h in self.alive_hosts()
                                 if h != hid]
            else:
                hid, token, fp8 = self._route_fit(request)
                # fallback candidates follow the request's OWN ring
                # order — shed/failover traffic spreads per key, not
                # onto whichever host wins some constant ranking
                cands = [hid] + [h for h in
                                 self._fit_candidates(fp8 or "?")
                                 if h != hid]
        sid = (getattr(request, "session_id", None)
               if not read else None)
        last_exc: BaseException | None = None
        for i, h in enumerate(cands):
            if i > 0:
                token = ("failover" if isinstance(
                    last_exc, (HostDown, HostSuspect)) else "shed")
            if sid is not None and (h, sid) in self._maybe_orphaned:
                # this host may hold an orphan of an earlier timed-out
                # submit for this session: clear it before handing the
                # session back (see _maybe_orphaned)
                try:
                    self.hosts[h].drop_session(sid)
                    self._maybe_orphaned.discard((h, sid))
                except Exception:  # noqa: BLE001 — the submit below
                    pass           # will surface real transport state
            try:
                tok = self.hosts[h].submit(request)
            except HostSuspect as e:
                # missed deadline, not a dead socket: climb the
                # suspicion ladder and try the next candidate — the
                # hung host keeps its state and may rejoin. The host
                # MAY have accepted the sessionful work before the
                # deadline: remember the possible orphan (bounded)
                if sid is not None:
                    if len(self._maybe_orphaned) >= 256:
                        self._maybe_orphaned.pop()
                    self._maybe_orphaned.add((h, sid))
                self._note_timeout(h)
                last_exc = e
                continue
            except HostDown as e:
                self._note_down(h)
                last_exc = e
                continue
            except ServeQueueFull as e:
                if self.degenerate:
                    raise
                telemetry.inc("fleet.shed")
                self._health[h]["queue_depth"] = e.depth
                last_exc = e
                continue
            return self._track(h, tok, request, token, read, fp8,
                               rctx=rctx)
        assert last_exc is not None
        raise last_exc

    def _track(self, hid, tok, request, token, read, fp8=None,
               rctx=None):
        self._seq += 1
        telemetry.trace.emit_root(
            rctx, "submit", host=hid, route=token,
            lane="read" if read else "fit",
            **({"fp8": fp8} if fp8 else {}))
        skey = None
        if read:
            handle = FleetPredictHandle(hid)
            telemetry.inc("fleet.read.requests")
            sid = getattr(request, "session_id", None)
            if sid is not None and not self.degenerate:
                skey = self._sid_last.get(sid)
        else:
            handle = FleetHandle(hid, token)
            telemetry.inc("fleet.requests")
            sid = getattr(request, "session_id", None)
            if sid is not None and not self.degenerate:
                # pin (or RE-pin) the session to the host that actually
                # accepted the work: a shed/failover at submit must
                # move the pin with the state, or later appends would
                # chase a host that never saw this session
                skey = self._sid_last.get(sid)
                if skey is not None:
                    self._sticky[skey] = hid
            if fp8 is not None:
                # the sticky-routing hit rate: did this request land on
                # a host whose caches its structure already warmed?
                self._warm_total += 1
                if fp8 in self._warm[hid]:
                    self._warm_hits += 1
                    telemetry.inc("fleet.route.warm_hit")
                self._warm[hid].add(fp8)
                # popularity stats feed the join prewarm adopt set
                # (ISSUE 16); bounded by halving-prune, hot keys survive
                self._popularity[fp8] = self._popularity.get(fp8, 0) + 1
                if len(self._popularity) > 4096:
                    keep = sorted(self._popularity,
                                  key=self._popularity.get,
                                  reverse=True)[:2048]
                    self._popularity = {k: self._popularity[k]
                                        for k in keep}
        telemetry.inc(f"fleet.route.{token}")
        self._route_counts[token] = self._route_counts.get(token, 0) + 1
        self._inflight[hid] += 1
        self._pending[hid].append(
            _Pending(self._seq, tok, request, handle, token, read,
                     skey=skey,
                     epoch=(self._epoch.get(skey, 0)
                            if skey is not None else 0)))
        return handle

    def pending(self) -> int:
        return sum(len(p) for p in self._pending.values())

    # ------------------------------------------------------------------
    # the read fast lane
    # ------------------------------------------------------------------
    def predict(self, request: PredictRequest) -> PredictResult:
        """Serve one read NOW through its host's synchronous fast lane.

        The worker serves ``predict`` as its own protocol op — it never
        triggers, joins, or waits on a fit drain on the remote host
        (zero fit-loop launches, counter-pinned in tests/test_fleet.py)
        — and session stickiness routes the read to the host whose
        memory holds the session's segment cache."""
        if self.degenerate:
            hid = self._order[0]
            token = "degenerate"
        else:
            hid, token = self._route_read(request)
            telemetry.inc(f"fleet.read.route.{token}")
        if request.trace_ctx is None:
            request.trace_ctx = telemetry.trace.begin(
                "submit", host=hid, route=token, lane="read")
        telemetry.inc("fleet.read.requests")
        try:
            wire = self.hosts[hid].predict(request)
        except (HostDown, HostSuspect) as e:
            if isinstance(e, HostSuspect):
                self._note_timeout(hid)
            else:
                self._note_down(hid)
            if self.degenerate:
                raise
            alive = self.alive_hosts()
            if not alive or request.session_id is not None \
                    and request.model is None:
                return PredictResult(
                    tag=request.tag, request=request, status="failed",
                    error=f"host {hid} unresponsive and the read "
                          "cannot be served elsewhere", host=hid)
            telemetry.inc("fleet.read.route.failover")
            hid = self._route_read(request)[0]
            request.trace_ctx = telemetry.trace.hop(
                request.trace_ctx, "failover",
                host=hid) or request.trace_ctx
            wire = self.hosts[hid].predict(request)
        return self._unwire_read(wire, request)

    @staticmethod
    def _unwire_read(wire: dict, request) -> PredictResult:
        if "result" in wire:           # loopback: the real object
            return wire["result"]
        return PredictResult(
            tag=request.tag, request=request, status=wire["status"],
            phase_int=wire["phase_int"], phase_frac=wire["phase_frac"],
            freq_hz=wire["freq_hz"], source=wire["source"],
            cache_hit=wire["cache_hit"], n_queries=wire["n_queries"],
            latency_s=wire["latency_s"], error=wire["error"],
            host=wire.get("host"),
            trace_ctx=telemetry.trace.unwire(wire.get("trace_ctx")))

    def _unwire_fit(self, wire: dict, pend: _Pending) -> FitResult:
        if "result" in wire:           # loopback: the real object
            return wire["result"]
        req = pend.request
        if wire.get("params") and req.model is not None:
            for name, (hi, lo, unc) in wire["params"].items():
                if name in req.model.params:
                    p = req.model[name]
                    p.set_value_dd(hi, lo)
                    p.uncertainty = unc
        return FitResult(
            tag=req.tag, request=req, chi2=wire["chi2"],
            converged=wire["converged"], batch=wire["batch"],
            group=wire["group"], n_members=wire["n_members"],
            occupancy=wire["occupancy"],
            queue_latency_s=wire["queue_latency_s"],
            passthrough=wire["passthrough"], status=wire["status"],
            error=wire["error"], attempts=wire["attempts"],
            trace=wire["trace"], retry_after_s=wire["retry_after_s"],
            injected=wire["injected"], session=wire["session"],
            host=wire.get("host"),
            trace_ctx=telemetry.trace.unwire(wire.get("trace_ctx")))

    # ------------------------------------------------------------------
    # drain
    # ------------------------------------------------------------------
    def drain_reads(self) -> list[PredictResult]:
        """Drain every host's queued reads (fit queues untouched —
        the two-tier contract holds fleet-wide)."""
        out: list[tuple[int, PredictResult]] = []
        orphans: list[tuple[str, _Pending]] = []
        for hid in self._order:
            pend = [p for p in self._pending[hid] if p.read]
            if not pend:
                continue
            t_host = time.perf_counter()
            try:
                wires = self.hosts[hid].drain_reads(
                    self._drain_deadline(pend))
            except HostSuspect:
                self._blocked_s += time.perf_counter() - t_host
                self._note_timeout(hid)
                wires = []
            except HostDown:
                self._blocked_s += time.perf_counter() - t_host
                self._note_down(hid)
                wires = []
            matched, left = self._match(hid, pend, wires, reads=True)
            out.extend(matched)
            orphans.extend((hid, p) for p in left)
        for hid, p in orphans:
            out.append((p.seq, self._failover_pending(hid, p)))
        return [r for _s, r in sorted(out, key=lambda t: t[0])]

    def _match(self, hid, pend, wires, *, reads: bool):
        """Resolve one host's drained wire results against its pending
        list. Returns ``(matched, leftovers)`` — leftovers are pending
        entries the host died holding; the CALLER fails them over
        AFTER its sweep (a failover drains the target host, which
        mid-sweep would discard that host's own undrained results).

        Durability rules (ISSUE 13) enforced here: duplicate wire
        deliveries dedup by token (counted, never double-committed);
        replies answering already-failed-over tokens fence (or count
        as stale); a sessionful result whose submit EPOCH is no longer
        the session's current pin epoch is rejected — its request
        re-runs on the current pin instead — and a committed
        sessionful result is appended to the journal."""
        by_tok: dict = {}
        dups = 0
        for w in wires:
            if not (isinstance(w, dict) and "token" in w):
                continue
            if w["token"] in by_tok:
                dups += 1  # at-least-once delivery: keep the first
            else:
                by_tok[w["token"]] = w
        if dups:
            self._duplicates += dups
            telemetry.inc("fleet.transport.duplicates", dups)
        known = {p.token for p in pend}
        fence = self._fence.get(hid)
        for tok in list(by_tok):
            if tok in known:
                continue
            info = fence.pop(tok, None) if fence else None
            if info is not None:
                self._fence_reject(hid, tok, info)
            else:
                telemetry.inc("fleet.transport.stale_replies")
        out = []
        leftovers = []
        for p in pend:
            self._pending[hid].remove(p)
            self._inflight[hid] = max(0, self._inflight[hid] - 1)
            w = by_tok.get(p.token)
            if w is None:
                leftovers.append(p)
                continue
            if (p.skey is not None
                    and self._epoch.get(p.skey, 0) != p.epoch):
                # the session re-pinned while this host held the
                # request (partition failover mid-drain): the stale
                # pin's commit must not become the record — reject it
                # and re-run on the current pin
                self._fence_reject(hid, p.token, (p.skey, p.epoch),
                                   ctx=getattr(p.request,
                                               "trace_ctx", None))
                leftovers.append(p)
                continue
            res = (self._unwire_read(w, p.request) if reads
                   else self._unwire_fit(w, p))
            if not reads:
                self._journal_commit(p, res)
            p.handle._result = res
            out.append((p.seq, res))
        return out, leftovers

    def _journal_commit(self, p: _Pending, res: FitResult) -> None:
        """Record one resolved sessionful fit in the append journal
        (committed results only — failures/rejections never journal)
        and mark the session for post-drain replication."""
        if self.degenerate or p.skey is None or not res.fitted:
            return
        route = res.session
        req = p.request
        if route == "populate":
            self._journal.record_populate(
                p.skey, req.session_id, req.model, req.toas, res.chi2)
        elif route in ("incremental", "full_refit"):
            ok = self._journal.record_append(
                p.skey, req.toas,
                {"maxiter": req.maxiter,
                 "min_chi2_decrease": req.min_chi2_decrease,
                 "max_step_halvings": req.max_step_halvings},
                res.chi2)
            if not ok:
                telemetry.inc("fleet.journal.orphan_appends")
        else:
            return
        self._committed.add(p.skey)
        # the durable-commit hop closes the trace's causal chain: its
        # parent is the worker's dispatch hop (carried home on the
        # result envelope), so the merged tree reads submit -> accept
        # -> dispatch -> commit even across a failover re-pin
        ctx = (res.trace_ctx if res.trace_ctx is not None
               else getattr(req, "trace_ctx", None))
        telemetry.trace.hop(ctx, "commit", host=res.host, route=route,
                            epoch=p.epoch)

    def _replicate_committed(self) -> None:
        """Ship each just-committed session's summary to its ring
        successor (the ``stash`` op), then snapshot-truncate the
        journal: the replica now restores the whole prefix, so replay
        need only cover appends recorded after this point.
        Best-effort — a failed stash leaves the journal covering
        everything, losing nothing but the warm path."""
        committed, self._committed = self._committed, set()
        if self.degenerate or not committed:
            return
        for skey in committed:
            hid = self._sticky.get(skey)
            if hid is None or not self._health[hid]["alive"]:
                continue
            # suspect hosts are excluded: stashing at a hung successor
            # would block this drain an extra op deadline — exactly
            # the stall the liveness work bounds
            succ = self._ring_successor(skey, hid)
            if succ is None:
                continue
            t0 = time.perf_counter()
            try:
                summary = self.hosts[hid].session_summary(skey)
                if summary is None:
                    continue
                blob = _dur.build_replica(
                    summary, epoch=self._epoch.get(skey, 0))
                self.hosts[succ].stash_replica(skey, blob)
            except HostSuspect as e:
                # accounted and laddered: a timeout here is real
                # blocked wall, never silently swallowed
                self._blocked_s += time.perf_counter() - t0
                self._note_timeout(getattr(e, "host_id", None) or succ)
                continue
            except (HostDown, OSError, RuntimeError):
                continue
            self._journal.note_replica(skey, succ,
                                       summary["model_blob"])
            self._replicated += 1
            telemetry.inc("fleet.session.replicated")

    def _failover_pending(self, hid: str, p: _Pending):
        """A host died (or went unresponsive) holding ``p``: re-route
        + re-run it on a surviving host (synchronously — failover is
        the slow path), or resolve a structured failure. Nothing is
        silently dropped.

        Sessionful requests get the full ISSUE-13 treatment first: the
        old pin's token is FENCED (the host may be partitioned, not
        dead — its eventual reply must not double-commit), the pin
        epoch bumps, and the session's journaled/replicated state is
        restored onto the new pin BEFORE the retry dispatches, so the
        re-run appends to the dead host's committed solution."""
        self._failovers += 1
        self._failovers_total += 1
        telemetry.inc("fleet.failover.requests")
        # the failover hop re-heads the request's trace chain: the
        # restore replay, the survivor's accept, and the eventual
        # commit all parent under it, so the merged tree shows the
        # request crossing processes instead of fracturing into two
        p.request.trace_ctx = telemetry.trace.hop(
            p.request.trace_ctx, "failover", host=hid,
            lane="read" if p.read else "fit") or p.request.trace_ctx
        # a sessionful request pinned to the dead host must re-pin —
        # with its state restored and the old pin fenced
        sid = getattr(p.request, "session_id", None)
        if sid is not None and not self.degenerate:
            skey = self._sid_last.get(sid)
            if skey is not None:
                self._fence_arm(hid, p)
                if self._sticky.get(skey) == hid:
                    del self._sticky[skey]
                if self._sticky.get(skey) is None:
                    new = self._ring_successor(skey, hid)
                    if new is not None:
                        self._restore_session(
                            skey, new, ctx=p.request.trace_ctx)
        try:
            if p.read:
                res = self.predict(p.request)
                p.handle._result = res
                return res
            alive = self.alive_hosts()
            if not alive:
                raise HostDown("no alive hosts in the fleet")
            new_hid, _token, _fp8 = self._route_fit(p.request)
            tok = self.hosts[new_hid].submit(p.request)
            # failover is the slow path and may compile the structure
            # cold on the survivor: the generous deadline, not the
            # per-op default (the target just accepted the submit —
            # it is alive, merely working)
            wires = self.hosts[new_hid].drain(
                max(self._drain_deadline([p]), 300.0))
            w = next(w for w in wires if w["token"] == tok)
            res = self._unwire_fit(w, p)
            if sid is not None and not self.degenerate:
                # the re-run committed on the NEW pin: journal it
                # there (the fenced original never journals)
                skey = self._sid_last.get(sid)
                if skey is not None:
                    self._journal_commit(
                        _Pending(p.seq, tok, p.request, p.handle,
                                 "failover", skey=skey,
                                 epoch=self._epoch.get(skey, 0)),
                        res)
        except Exception as e:  # noqa: BLE001 — isolation boundary
            if p.read:
                res = PredictResult(
                    tag=p.request.tag, request=p.request,
                    status="failed",
                    error=f"host {hid} died; failover failed: "
                          f"{type(e).__name__}: {e}", host=hid)
            else:
                res = FitResult(
                    tag=p.request.tag, request=p.request,
                    chi2=float("nan"), converged=False, batch=-1,
                    group="", n_members=0, occupancy=0.0,
                    queue_latency_s=0.0, status="failed",
                    error=f"host {hid} died; failover failed: "
                          f"{type(e).__name__}: {e}", host=hid)
        p.handle._result = res
        return res

    # ------------------------------------------------------------------
    # catalog long jobs (ISSUE 14)
    # ------------------------------------------------------------------
    def _catalog_target(self, exclude: set[str] = frozenset()) -> str:
        """Least-loaded healthy host for a catalog job: a long job is
        structure-cold by definition (its programs compile wherever it
        lands), so load — queue depth + in-flight — beats ring
        affinity; degraded/suspect hosts are skipped while any clean
        host exists."""
        alive = [h for h in self.alive_hosts() if h not in exclude]
        if not alive:
            raise RuntimeError("no alive host for catalog job")
        clean = [h for h in alive
                 if not self._degraded(h) and not self._suspect(h)]
        pool = clean or alive
        return min(pool, key=lambda h: (self._depth(h)
                                        + sum(1 for e in
                                              self._catalog.values()
                                              if e["host"] == h
                                              and not e["done"]),
                                        self._order.index(h)))

    def submit_catalog(self, request) -> FleetCatalogHandle:
        """Route one catalog long job to the least-loaded healthy
        host. The job advances one slice per :meth:`drain`; its
        checkpoint is pulled back after every slice, so
        :meth:`_failover_catalog` can resume it on a survivor."""
        hid = self._catalog_target()
        if getattr(request, "trace_ctx", None) is None:
            request.trace_ctx = telemetry.trace.begin(
                "submit", host=hid, lane="longjob")
        job_id = self.hosts[hid].submit_catalog(request)
        # the handle key is the FIRST host's job id, stable for the
        # job's life; "remote_id" tracks the current host-local id (a
        # checkpoint-less fresh re-submit on a survivor mints a new
        # one — the handle must keep resolving)
        self._catalog[job_id] = {
            "host": hid, "remote_id": job_id, "request": request,
            "checkpoint": None, "progress": None, "resumes": 0,
            "done": False}
        self._route_counts["catalog"] = \
            self._route_counts.get("catalog", 0) + 1
        telemetry.inc("fleet.catalog.jobs")
        return FleetCatalogHandle(self, job_id)

    def catalog_progress(self, job_id: str) -> dict | None:
        e = self._catalog.get(job_id)
        return None if e is None else e.get("progress")

    def _advance_catalog(self) -> None:
        """One slice per live job; checkpoint stashed router-side.

        A slice is long DEVICE work (a joint iteration at catalog
        scale), so it runs under the generous slow-path deadline, like
        restores — a working host must never be suspected for doing
        the work it was asked to do. A miss or dead socket fails the
        job over to a survivor via its last checkpoint: resumed, not
        restarted (iteration counters continue — asserted by soak and
        the smoke gate)."""
        slow_dl = max(_dur.op_deadline_s(), 300.0)
        for job_id, e in list(self._catalog.items()):
            if e["done"]:
                continue
            hid = e["host"]
            t0 = time.perf_counter()
            try:
                out = self.hosts[hid].advance_catalog(
                    e.get("remote_id", job_id), deadline_s=slow_dl)
            except HostSuspect:
                self._blocked_s += time.perf_counter() - t0
                self._note_timeout(hid)
                self._failover_catalog(job_id, e, hid)
                continue
            except (HostDown, OSError):
                self._blocked_s += time.perf_counter() - t0
                self._note_down(hid)
                self._failover_catalog(job_id, e, hid)
                continue
            e["progress"] = out["progress"]
            if out.get("checkpoint") is not None:
                e["checkpoint"] = out["checkpoint"]
            if out["progress"]["state"] in ("done", "failed"):
                e["done"] = True

    def _failover_catalog(self, job_id: str, e: dict,
                          dead_hid: str) -> None:
        """Resume the job on a survivor from its stashed checkpoint
        (no checkpoint yet -> fresh re-submit: nothing was lost, the
        job had not started). The adopted job continues the SAME
        iteration count — pre-kill work is accounted, never re-run."""
        try:
            target = self._catalog_target(exclude={dead_hid})
        except RuntimeError:
            e["done"] = True
            e["progress"] = dict(e.get("progress") or {},
                                 state="failed",
                                 error="no surviving host")
            telemetry.inc("fleet.catalog.lost")
            return
        slow_dl = max(_dur.op_deadline_s(), 300.0)
        try:
            if e["checkpoint"] is not None:
                e["remote_id"] = self.hosts[target].adopt_catalog(
                    e["checkpoint"], deadline_s=slow_dl)
                telemetry.inc("fleet.catalog.resumed")
            else:
                # nothing ran yet (no checkpoint): fresh re-submit;
                # the survivor mints its own id — the entry keeps its
                # stable handle key and only the remote id moves
                e["remote_id"] = self.hosts[target].submit_catalog(
                    e["request"], deadline_s=slow_dl)
                telemetry.inc("fleet.catalog.restarted")
            e["host"] = target
            e["resumes"] += 1
            self._catalog_resumes += 1
            self._failovers += 1
            self._failovers_total += 1
        except (HostSuspect, HostDown, OSError):
            # the fallback died too: the next drain's sweep retries
            # against whatever is still alive
            self._note_down(target)

    def drain(self) -> list[FitResult]:
        """Drain every host with pending work; resolve all handles.

        Reads drain first fleet-wide (the two-tier contract), then
        each host's fit queue; a host that died since submit has its
        pending requests re-routed to survivors. Results return in
        fleet submission order. One ``type="fleet"`` record per drain
        carries the per-host health/report block."""
        t0 = time.perf_counter()
        # liveness pass first (ISSUE 13): climb/heal the suspicion
        # ladder under the cheap heartbeat deadline and fence any late
        # replies from recovered hosts — a hung host costs this drain
        # at most one op deadline, never the old 600 s socket stall
        self.heartbeat()
        self.drain_reads()
        out: list[tuple[int, FitResult]] = []
        per_host_n: dict[str, int] = {}
        orphans: list[tuple[str, _Pending]] = []
        for hid in self._order:
            pend = [p for p in self._pending[hid] if not p.read]
            if not pend:
                continue
            per_host_n[hid] = len(pend)
            t_host = time.perf_counter()
            try:
                wires = self.hosts[hid].drain(
                    self._drain_deadline(pend))
            except HostSuspect:
                # missed the drain deadline: suspect (maybe dead) —
                # the pendings fail over NOW (fenced), the drain wall
                # never blocks on an unresponsive host beyond its one
                # deadline
                self._blocked_s += time.perf_counter() - t_host
                self._note_timeout(hid)
                wires = []
            except HostDown:
                self._blocked_s += time.perf_counter() - t_host
                self._note_down(hid)
                wires = []
            matched, left = self._match(hid, pend, wires, reads=False)
            out.extend(matched)
            orphans.extend((hid, p) for p in left)
        # failover AFTER the sweep: every survivor's own pending is
        # resolved by now, so the failover's drain on it cannot
        # swallow co-pending work
        for hid, p in orphans:
            out.append((p.seq, self._failover_pending(hid, p)))
        # replication AFTER failover: re-pinned sessions replicate
        # from their NEW pin
        self._replicate_committed()
        # catalog slice AFTER the whole fit sweep (ISSUE 14): long
        # jobs advance once per drain, checkpoints pulled back — small
        # fits and reads are already resolved, so the slice bounds the
        # drain's long-job cost without starving anything. LIVE jobs
        # only: finished entries stay resolvable through their handles
        # but must not keep sweeping hosts or emitting records forever
        catalog_live = any(not e["done"] for e in self._catalog.values())
        if catalog_live:
            self._advance_catalog()
        self._refresh_reports()
        wall = time.perf_counter() - t0
        results = [r for _s, r in sorted(out, key=lambda t: t[0])]
        if results or per_host_n or catalog_live:
            self._emit_record(results, per_host_n, wall)
        return results

    def _refresh_reports(self) -> None:
        for hid in self._order:
            h = self._health[hid]
            if not h["alive"] or h["misses"]:
                # a host that already missed a deadline this cycle is
                # known-unresponsive: another blocking report would
                # just re-pay the timeout (the stall budget is ONE
                # deadline + heartbeat per drain, never per op)
                continue
            try:
                rep = self.hosts[hid].report()
            except HostSuspect:
                self._note_timeout(hid)
                continue
            except (HostDown, OSError):
                self._note_down(hid)
                continue
            h["misses"] = 0
            h["queue_depth"] = int(rep.get("queue_depth", 0))
            h["read_depth"] = int(rep.get("read_depth", 0))
            h["fail_streak"] = int(rep.get("fail_streak", 0))
            h["degraded"] = bool(rep.get("degraded", False))
            h["latency_s"] = rep.get("last_drain_wall_s")
            h["program_misses"] = int(rep.get("program_misses", 0))

    def _emit_record(self, results, per_host_n, wall) -> None:
        routes, self._route_counts = self._route_counts, {}
        failovers, self._failovers = self._failovers, 0
        warm_hits, self._warm_hits = self._warm_hits, 0
        warm_total, self._warm_total = self._warm_total, 0
        replicated, self._replicated = self._replicated, 0
        replayed, self._replayed = self._replayed, 0
        fenced, self._fenced_rejects = self._fenced_rejects, 0
        duplicates, self._duplicates = self._duplicates, 0
        restores, self._restores = self._restores, {}
        blocked, self._blocked_s = self._blocked_s, 0.0
        sticky = routes.get("sticky", 0)
        routed = sum(routes.values())
        statuses: dict[str, int] = {}
        for r in results:
            statuses[r.status] = statuses.get(r.status, 0) + 1
        alive = self.alive_hosts()
        telemetry.set_gauge("fleet.hosts_alive", len(alive))
        self.last_drain = {
            "type": "fleet",
            "hosts": [
                {"host": hid,
                 "alive": self._health[hid]["alive"],
                 "ready": self._health[hid].get("ready", True),
                 "requests": per_host_n.get(hid, 0),
                 "queue_depth": self._health[hid]["queue_depth"],
                 "fail_streak": self._health[hid]["fail_streak"],
                 "misses": self._health[hid]["misses"],
                 "degraded": self._degraded(hid),
                 "program_misses": self._health[hid]["program_misses"]}
                for hid in self._order],
            "requests": len(results),
            "routes": routes,
            "sticky_hit_rate": (round(sticky / routed, 4)
                                if routed else None),
            # fraction of warm-trackable fits that landed on a host
            # already holding their structure's caches — the sticky-
            # routing effectiveness headline of the FLEET artifacts
            # (raw counts carried too so rollups aggregate exactly:
            # the rate's denominator is warm-trackable fits, NOT the
            # route-count total, which also counts reads/sheds)
            "warm_hits": warm_hits,
            "warm_total": warm_total,
            "warm_hit_rate": (round(warm_hits / warm_total, 4)
                              if warm_total else None),
            "failovers": failovers,
            "statuses": statuses,
            # durable-sessions rollup (ISSUE 13): journal health plus
            # this drain's replication/replay/fencing activity — the
            # report CLI's durability section reads this block; old
            # fleet records simply lack it and degrade gracefully
            "durability": {
                "journal": self._journal.stats(),
                "replicated": replicated,
                "replayed": replayed,
                "fenced_rejects": fenced,
                "duplicates_deduped": duplicates,
                "restores": restores,
                "blocked_wall_s": round(blocked, 6),
                "epochs": {repr(k[0]): v
                           for k, v in list(self._epoch.items())[:32]},
            },
            "degenerate": self.degenerate,
            "wall_s": round(wall, 6),
            "trace_ids": sorted({
                r.trace_ctx.trace_id for r in results
                if getattr(r, "trace_ctx", None) is not None
                and r.trace_ctx.trace_id})[:64],
        }
        if self._catalog:
            cat_resumes, self._catalog_resumes = self._catalog_resumes, 0
            self.last_drain["catalog"] = {
                "jobs": len(self._catalog),
                "running": sum(1 for e in self._catalog.values()
                               if not e["done"]),
                "resumes_this_drain": cat_resumes,
                "by_host": {
                    hid: sum(1 for e in self._catalog.values()
                             if e["host"] == hid and not e["done"])
                    for hid in self._order},
            }
        telemetry.add_record(dict(self.last_drain))

    def fleet_metrics(self, deadline_s: float | None = None) -> dict:
        """The live introspection plane's fleet view: one ``metrics``
        snapshot per host (a host that misses the snapshot deadline
        becomes an ``error`` entry — the plane reports sickness, it
        never hangs on it), folded by :func:`telemetry.top.aggregate`
        and extended with the router's own state: routing/failover
        health and the trace ids the ROUTER still holds pending (a
        request a dead host took with it appears here even when no
        live worker still knows about it)."""
        from pint_tpu.telemetry import top as _top

        if deadline_s is None:
            deadline_s = config.env_float(
                "PINT_TPU_FLEET_METRICS_DEADLINE_S")
        per_host: dict[str, dict] = {}
        for hid in self._order:
            try:
                per_host[hid] = self.hosts[hid].metrics(
                    deadline_s=deadline_s)
            except Exception as e:  # noqa: BLE001 — a dead host is data
                per_host[hid] = {
                    "error": f"{type(e).__name__}: {e}"}
        agg = _top.aggregate(per_host)
        inflight = {
            p.request.trace_ctx.trace_id
            for pend in self._pending.values() for p in pend
            if getattr(p.request, "trace_ctx", None) is not None
            and p.request.trace_ctx.trace_id}
        inflight.update(agg["inflight_traces"])
        agg["inflight_traces"] = sorted(inflight)[:256]
        agg["router"] = {
            "hosts": {hid: {"alive": h["alive"],
                            "fail_streak": h["fail_streak"],
                            "misses": h["misses"],
                            "degraded": self._degraded(hid)}
                      for hid, h in self._health.items()},
            "pending": sum(len(v) for v in self._pending.values()),
            "sessions_pinned": len(self._sticky),
            "catalog_jobs": sum(1 for e in self._catalog.values()
                                if not e["done"]),
            "failovers": self._failovers_total,
            "fenced_rejects": self._fenced_rejects_total,
        }
        return agg

    def close(self) -> None:
        for h in self.hosts.values():
            try:
                h.close()
            except (HostDown, OSError):
                pass
