"""Fingerprint-sticky rendezvous routing over N per-host schedulers.

ROADMAP item 1 / ISSUE 12: every tier below this one scales within ONE
process — union batching, mesh placement, fault domains, sessions, the
read path all live inside a single
:class:`~pint_tpu.serve.scheduler.ThroughputScheduler`. The fleet tier
is the scale-OUT seam: a :class:`FleetRouter` in front of N host
transports (:mod:`pint_tpu.fleet.transport`), each owning one
scheduler over its process-local device pool.

**Routing IS the performance feature.** Compiled fit programs, TZR
caches, session rank-k state and read-path segment caches are all
per-host (device memory + process-local jit caches): a request landing
on the wrong host pays a full recompile (~tens of seconds) instead of
a ~ms warm-cache hit. The router therefore concentrates each structure
on exactly one host:

* **Rendezvous (HRW) hashing** on the structure-fingerprint short id:
  every (key, host) pair gets a deterministic score
  (:func:`rendezvous_rank`); the key routes to its highest-scoring
  alive host. Host join/leave moves only the keys whose top choice
  changed — ~1/N of them, measured over 1k fingerprints in
  tests/test_fleet.py — while every other structure stays hot where it
  is. No central ring state: the ranking is a pure function of
  (key, host ids).
* **Session stickiness** keyed ``(session_id, fingerprint)``: the
  first sessionful request pins its session to the routed host; every
  later append and read follows the pin (rank-k device state and
  polycos segment caches are that host's memory), surviving ring
  rebalance — a new host joining NEVER moves an existing session, only
  fresh structures.
* **Work stealing for cold structures**: when the sticky host's queue
  depth reaches ``steal_depth`` and the structure is not yet warm
  there, the request goes to the least-loaded healthy host instead —
  a cold structure recompiles wherever it lands, so stealing costs
  nothing extra and drains the hot spot. Warm structures are NEVER
  stolen (that would trade a queue wait for a recompile).
* **Health + failover**: per-host health is fed only from
  :meth:`~pint_tpu.serve.scheduler.ThroughputScheduler.report`
  envelopes (fail streak, queue depth, degraded flag — the PR-6
  degradation ladder, now visible across hosts) plus transport-level
  :class:`~pint_tpu.fleet.transport.HostDown` failures. A *degraded*
  host sheds fits to its ring successor (the next host in its
  rendezvous ranking); **reads fail over before fits** — a merely
  *suspect* host (fail streak >= 1, below the degrade threshold)
  already loses its model-carrying reads (any host can serve those
  dense) while fits keep flowing until the ladder actually trips.
  A dead host's pending work is re-routed and re-submitted at drain —
  never silently dropped; requests that cannot be re-served elsewhere
  (a session append whose state died with the host and whose request
  carries no model) resolve as structured ``failed`` envelopes.

At N=1 — or under the ``PINT_TPU_FLEET=0`` kill switch — the router is
*degenerate*: every request goes to host 0 with zero routing
bookkeeping (no second fingerprint canonicalization, no health
machinery on the submit path), so the single-host path is bitwise
today's behavior (pinned in tests/test_fleet.py).

Telemetry: ``fleet.*`` counters (route split, failovers, steals,
host-down events), one ``type="fleet"`` record per router drain with
the per-host report block — rendered by ``python -m
pint_tpu.telemetry.report`` under "fleet tier".
"""

from __future__ import annotations

import hashlib
import os
import time
from typing import Any

from pint_tpu import telemetry
from pint_tpu.fleet.transport import HostDown
from pint_tpu.serve import fingerprint as _fp
from pint_tpu.serve.scheduler import (FitResult, PredictRequest,
                                      PredictResult, ServeQueueFull)


def fleet_enabled() -> bool:
    """Kill switch (read per call so tests can flip it):
    ``PINT_TPU_FLEET=0`` forces the degenerate single-host path."""
    return os.environ.get("PINT_TPU_FLEET", "") != "0"


def _score(host_id: str, key: str) -> str:
    """The (host, key) rendezvous score: a content digest, never
    ``hash()`` (salted per process — the ranking must agree across
    router restarts and across processes)."""
    return hashlib.sha1(f"{host_id}|{key}".encode()).hexdigest()


def rendezvous_rank(key: str, host_ids) -> list[str]:
    """All hosts ranked for ``key``, best first (highest-random-weight
    hashing). Deterministic in (key, set of hosts): independent of list
    order, stable across processes, and removing a host only promotes
    lower-ranked hosts — keys whose top choice survives never move."""
    return sorted(host_ids, key=lambda h: _score(h, key), reverse=True)


class FleetHandle:
    """Future-like handle for a routed fit (the router's FitHandle)."""

    __slots__ = ("_result", "host", "route")

    def __init__(self, host: str, route: str):
        self._result: FitResult | None = None
        self.host = host      # host id the request was routed to
        self.route = route    # routing token (sticky/rendezvous/...)

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> FitResult:
        if self._result is None:
            raise RuntimeError("request not drained yet; call "
                               "FleetRouter.drain() first")
        return self._result


class FleetPredictHandle:
    """Future-like handle for a routed queued read."""

    __slots__ = ("_result", "host")

    def __init__(self, host: str):
        self._result: PredictResult | None = None
        self.host = host

    def done(self) -> bool:
        return self._result is not None

    def result(self) -> PredictResult:
        if self._result is None:
            raise RuntimeError("read not drained yet; call "
                               "FleetRouter.drain_reads() first")
        return self._result


class _Pending:
    """One routed, not-yet-resolved request on a host."""

    __slots__ = ("seq", "token", "request", "handle", "route", "read")

    def __init__(self, seq, token, request, handle, route, read=False):
        self.seq = seq
        self.token = token
        self.request = request
        self.handle = handle
        self.route = route
        self.read = read


class FleetRouter:
    """Route fits/reads over host transports; drain and resolve them.

    ``hosts`` is a list of transports (each carries a unique
    ``host_id``). ``steal_depth`` is the queue depth at which a cold
    structure is stolen to the least-loaded host; ``degrade_after``
    the router-side fail-streak threshold above which a host that
    stopped reporting cleanly counts as degraded even without a
    report saying so. ``degenerate`` forces the N=1 fast path
    (implied by a single host or the ``PINT_TPU_FLEET=0`` switch).
    """

    def __init__(self, hosts, *, steal_depth: int = 8,
                 degrade_after: int = 2, degenerate: bool = False):
        hosts = list(hosts)
        if not hosts:
            raise ValueError("FleetRouter needs at least one host")
        ids = [h.host_id for h in hosts]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate host ids: {ids}")
        self.hosts = {h.host_id: h for h in hosts}
        self._order = ids
        self.steal_depth = max(1, int(steal_depth))
        self.degrade_after = max(1, int(degrade_after))
        self.degenerate = bool(degenerate or len(hosts) == 1
                               or not fleet_enabled())
        self._health: dict[str, dict] = {
            hid: {"alive": True, "fail_streak": 0, "queue_depth": 0,
                  "read_depth": 0, "degraded": False, "latency_s": None,
                  "program_misses": 0}
            for hid in ids}
        self._warm: dict[str, set] = {hid: set() for hid in ids}
        self._sticky: dict[tuple, str] = {}   # (sid, fp8) -> host id
        self._sid_last: dict[Any, tuple] = {}  # sid -> last sticky key
        self._inflight: dict[str, int] = {hid: 0 for hid in ids}
        self._pending: dict[str, list[_Pending]] = {hid: [] for hid in ids}
        self._seq = 0
        self._route_counts: dict[str, int] = {}
        self._failovers = 0
        self._warm_hits = 0   # requests landing on an already-warm host
        self._warm_total = 0  # ... out of all warm-trackable fits
        self.last_drain: dict | None = None

    # ------------------------------------------------------------------
    # health
    # ------------------------------------------------------------------
    def alive_hosts(self) -> list[str]:
        return [h for h in self._order if self._health[h]["alive"]]

    def _degraded(self, hid: str) -> bool:
        h = self._health[hid]
        return bool(h["degraded"]
                    or h["fail_streak"] >= self.degrade_after)

    def _suspect(self, hid: str) -> bool:
        """Read-level caution: trips BEFORE the fit-shedding threshold
        (reads fail over first — any host serves a model-carrying read
        dense, so there is no reason to send one toward trouble)."""
        h = self._health[hid]
        return bool(self._degraded(hid) or h["fail_streak"] >= 1)

    def _depth(self, hid: str) -> int:
        return self._health[hid]["queue_depth"] + self._inflight[hid]

    def add_host(self, transport) -> None:
        """Host JOIN: register a new transport. Rendezvous ranking is a
        pure function of (key, host set), so only keys whose top score
        the new host beats move to it (~1/(N+1), measured in
        tests/test_fleet.py) — and existing session pins never move
        (stickiness beats the ring)."""
        hid = transport.host_id
        if hid in self.hosts:
            raise ValueError(f"duplicate host id {hid!r}")
        self.hosts[hid] = transport
        self._order.append(hid)
        self._health[hid] = {"alive": True, "fail_streak": 0,
                             "queue_depth": 0, "read_depth": 0,
                             "degraded": False, "latency_s": None,
                             "program_misses": 0}
        self._warm[hid] = set()
        self._inflight[hid] = 0
        self._pending[hid] = []
        self.degenerate = False if len(self._order) > 1 \
            and fleet_enabled() else self.degenerate
        telemetry.inc("fleet.host_join")

    def retire_host(self, host_id: str) -> None:
        """Host LEAVE (administrative): mark it dead so routing moves
        its keys to their next-ranked hosts; pending work fails over at
        the next :meth:`drain` exactly like a crash."""
        if host_id not in self.hosts:
            raise KeyError(host_id)
        self._health[host_id]["alive"] = False
        telemetry.inc("fleet.host_leave")

    def mark(self, host_id: str, *, alive: bool | None = None,
             fail_streak: int | None = None,
             degraded: bool | None = None) -> None:
        """Operator/test surface: override one host's health state
        (e.g. administratively drain a host before maintenance). The
        next report from the host refreshes the report-fed fields."""
        h = self._health[host_id]
        if alive is not None:
            h["alive"] = bool(alive)
        if fail_streak is not None:
            h["fail_streak"] = int(fail_streak)
        if degraded is not None:
            h["degraded"] = bool(degraded)

    def _note_down(self, hid: str) -> None:
        h = self._health[hid]
        if h["alive"]:
            telemetry.inc("fleet.host_down")
        h["alive"] = False
        h["fail_streak"] += 1

    # ------------------------------------------------------------------
    # routing
    # ------------------------------------------------------------------
    def _fit_candidates(self, key: str) -> list[str]:
        """Fit routing order for ``key``: rendezvous ranking over alive
        hosts, degraded hosts moved to the back (a degraded host sheds
        to its ring successor — the next alive host in ITS OWN
        ranking — but remains the last resort before failing)."""
        ranked = rendezvous_rank(key, self.alive_hosts())
        return ([h for h in ranked if not self._degraded(h)]
                + [h for h in ranked if self._degraded(h)])

    def _route_fit(self, request) -> tuple[str, str, str | None]:
        """(host id, route token, fp8) for one fit request — fp8 is
        threaded back so the submit path ranks its fallback candidates
        by the request's OWN ring order and never canonicalizes the
        structure twice."""
        sid = getattr(request, "session_id", None)
        fp8 = None
        if request.model is not None:
            fp8 = _fp.short_id(
                _fp.structure_fingerprint(request.model, request.toas))
        if sid is not None:
            skey = (sid, fp8) if fp8 is not None else self._sid_last.get(sid)
            if skey is None:
                raise ValueError(
                    f"session {sid!r} is unknown to the fleet and the "
                    "request carries no model; the first request of a "
                    "session must include one")
            self._sid_last[sid] = skey
            hid = self._sticky.get(skey)
            if hid is not None and self._health[hid]["alive"] \
                    and not self._degraded(hid):
                return hid, "sticky", skey[1]
            if hid is not None:
                # sticky host dead/degraded: fail over to the ring
                # successor; the session re-pins there (its device
                # state is gone — the new host repopulates from the
                # request, or resolves a structured error when it
                # cannot)
                cands = [h for h in self._fit_candidates(skey[1] or
                                                         repr(sid))
                         if h != hid] or [hid]
                new = cands[0]
                self._sticky[skey] = new
                return new, "failover", skey[1]
            hid, token = self._route_structure(fp8)
            self._sticky[skey] = hid
            return hid, token, skey[1]
        return (*self._route_structure(fp8), fp8)

    def _route_structure(self, fp8: str | None) -> tuple[str, str]:
        cands = self._fit_candidates(fp8 or "?")
        if not cands:
            raise HostDown("no alive hosts in the fleet")
        primary = cands[0]
        token = "rendezvous"
        if self._degraded(primary):
            token = "failover"  # every host degraded: last resort
        elif fp8 is not None and primary != rendezvous_rank(
                fp8, self.alive_hosts())[0]:
            token = "failover"  # rendezvous winner was degraded: shed
        if (fp8 is not None and token == "rendezvous"
                and self._depth(primary) >= self.steal_depth
                and fp8 not in self._warm[primary]):
            # cold-structure work stealing: recompiles wherever it
            # lands, so send it to the shortest healthy queue
            others = [h for h in cands[1:] if not self._degraded(h)]
            if others:
                target = min(others, key=self._depth)
                if self._depth(target) < self._depth(primary):
                    return target, "stolen"
        return primary, token

    def _route_read(self, request) -> tuple[str, str]:
        """(host id, token) for one read. Session reads follow the
        sticky pin (the segment cache and committed solution live
        there); model-carrying reads avoid suspect hosts entirely."""
        sid = request.session_id
        if sid is not None:
            skey = self._sid_last.get(sid)
            hid = self._sticky.get(skey) if skey is not None else None
            if hid is not None and self._health[hid]["alive"]:
                if not self._suspect(hid) or request.model is None:
                    # the state lives here; a suspect host still beats
                    # a guaranteed "no committed solution" elsewhere
                    return hid, "sticky"
            if request.model is None:
                if hid is not None:
                    raise HostDown(
                        f"session {sid!r} is pinned to dead host "
                        f"{hid}; resubmit with a model to re-fit")
                raise ValueError(
                    f"session {sid!r} is unknown to the fleet; fit "
                    "(populate) it first")
            # fall through: serve dense from the model, away from the
            # suspect/dead sticky host
        fp8 = "?"
        if request.model is not None:
            fp8 = _fp.short_id(
                _fp.structure_fingerprint(request.model, None))
        ranked = rendezvous_rank(fp8, self.alive_hosts())
        if not ranked:
            raise HostDown("no alive hosts in the fleet")
        clean = [h for h in ranked if not self._suspect(h)]
        if clean:
            return clean[0], ("rendezvous" if clean[0] == ranked[0]
                              else "failover")
        return ranked[0], "failover"

    # ------------------------------------------------------------------
    # intake
    # ------------------------------------------------------------------
    def submit(self, request):
        """Route + enqueue one request on its host; returns a
        :class:`FleetHandle` (fits) / :class:`FleetPredictHandle`
        (reads). A full primary host sheds to the next candidate
        (backpressure composes); :class:`ServeQueueFull` surfaces only
        when the whole fleet is full. A host dying at submit fails
        over transparently."""
        read = isinstance(request, PredictRequest)
        fp8 = None
        if self.degenerate:
            hid = self._order[0]
            cands, token = [hid], "degenerate"
        else:
            if read:
                hid, token = self._route_read(request)
                cands = [hid] + [h for h in self.alive_hosts()
                                 if h != hid]
            else:
                hid, token, fp8 = self._route_fit(request)
                # fallback candidates follow the request's OWN ring
                # order — shed/failover traffic spreads per key, not
                # onto whichever host wins some constant ranking
                cands = [hid] + [h for h in
                                 self._fit_candidates(fp8 or "?")
                                 if h != hid]
        last_exc: BaseException | None = None
        for i, h in enumerate(cands):
            if i > 0:
                token = "failover" if isinstance(last_exc, HostDown) \
                    else "shed"
            try:
                tok = self.hosts[h].submit(request)
            except HostDown as e:
                self._note_down(h)
                last_exc = e
                continue
            except ServeQueueFull as e:
                if self.degenerate:
                    raise
                telemetry.inc("fleet.shed")
                self._health[h]["queue_depth"] = e.depth
                last_exc = e
                continue
            return self._track(h, tok, request, token, read, fp8)
        assert last_exc is not None
        raise last_exc

    def _track(self, hid, tok, request, token, read, fp8=None):
        self._seq += 1
        if read:
            handle = FleetPredictHandle(hid)
            telemetry.inc("fleet.read.requests")
        else:
            handle = FleetHandle(hid, token)
            telemetry.inc("fleet.requests")
            sid = getattr(request, "session_id", None)
            if sid is not None and not self.degenerate:
                # pin (or RE-pin) the session to the host that actually
                # accepted the work: a shed/failover at submit must
                # move the pin with the state, or later appends would
                # chase a host that never saw this session
                skey = self._sid_last.get(sid)
                if skey is not None:
                    self._sticky[skey] = hid
            if fp8 is not None:
                # the sticky-routing hit rate: did this request land on
                # a host whose caches its structure already warmed?
                self._warm_total += 1
                if fp8 in self._warm[hid]:
                    self._warm_hits += 1
                    telemetry.inc("fleet.route.warm_hit")
                self._warm[hid].add(fp8)
        telemetry.inc(f"fleet.route.{token}")
        self._route_counts[token] = self._route_counts.get(token, 0) + 1
        self._inflight[hid] += 1
        self._pending[hid].append(
            _Pending(self._seq, tok, request, handle, token, read))
        return handle

    def pending(self) -> int:
        return sum(len(p) for p in self._pending.values())

    # ------------------------------------------------------------------
    # the read fast lane
    # ------------------------------------------------------------------
    def predict(self, request: PredictRequest) -> PredictResult:
        """Serve one read NOW through its host's synchronous fast lane.

        The worker serves ``predict`` as its own protocol op — it never
        triggers, joins, or waits on a fit drain on the remote host
        (zero fit-loop launches, counter-pinned in tests/test_fleet.py)
        — and session stickiness routes the read to the host whose
        memory holds the session's segment cache."""
        if self.degenerate:
            hid = self._order[0]
        else:
            hid, token = self._route_read(request)
            telemetry.inc(f"fleet.read.route.{token}")
        telemetry.inc("fleet.read.requests")
        try:
            wire = self.hosts[hid].predict(request)
        except HostDown:
            self._note_down(hid)
            if self.degenerate:
                raise
            alive = self.alive_hosts()
            if not alive or request.session_id is not None \
                    and request.model is None:
                return PredictResult(
                    tag=request.tag, request=request, status="failed",
                    error=f"host {hid} down and the read cannot be "
                          "served elsewhere", host=hid)
            telemetry.inc("fleet.read.route.failover")
            hid = self._route_read(request)[0]
            wire = self.hosts[hid].predict(request)
        return self._unwire_read(wire, request)

    @staticmethod
    def _unwire_read(wire: dict, request) -> PredictResult:
        if "result" in wire:           # loopback: the real object
            return wire["result"]
        return PredictResult(
            tag=request.tag, request=request, status=wire["status"],
            phase_int=wire["phase_int"], phase_frac=wire["phase_frac"],
            freq_hz=wire["freq_hz"], source=wire["source"],
            cache_hit=wire["cache_hit"], n_queries=wire["n_queries"],
            latency_s=wire["latency_s"], error=wire["error"],
            host=wire.get("host"))

    def _unwire_fit(self, wire: dict, pend: _Pending) -> FitResult:
        if "result" in wire:           # loopback: the real object
            return wire["result"]
        req = pend.request
        if wire.get("params") and req.model is not None:
            for name, (hi, lo, unc) in wire["params"].items():
                if name in req.model.params:
                    p = req.model[name]
                    p.set_value_dd(hi, lo)
                    p.uncertainty = unc
        return FitResult(
            tag=req.tag, request=req, chi2=wire["chi2"],
            converged=wire["converged"], batch=wire["batch"],
            group=wire["group"], n_members=wire["n_members"],
            occupancy=wire["occupancy"],
            queue_latency_s=wire["queue_latency_s"],
            passthrough=wire["passthrough"], status=wire["status"],
            error=wire["error"], attempts=wire["attempts"],
            trace=wire["trace"], retry_after_s=wire["retry_after_s"],
            injected=wire["injected"], session=wire["session"],
            host=wire.get("host"))

    # ------------------------------------------------------------------
    # drain
    # ------------------------------------------------------------------
    def drain_reads(self) -> list[PredictResult]:
        """Drain every host's queued reads (fit queues untouched —
        the two-tier contract holds fleet-wide)."""
        out: list[tuple[int, PredictResult]] = []
        orphans: list[tuple[str, _Pending]] = []
        for hid in self._order:
            pend = [p for p in self._pending[hid] if p.read]
            if not pend:
                continue
            try:
                wires = self.hosts[hid].drain_reads()
            except HostDown:
                self._note_down(hid)
                wires = []
            matched, left = self._match(hid, pend, wires, reads=True)
            out.extend(matched)
            orphans.extend((hid, p) for p in left)
        for hid, p in orphans:
            out.append((p.seq, self._failover_pending(hid, p)))
        return [r for _s, r in sorted(out, key=lambda t: t[0])]

    def _match(self, hid, pend, wires, *, reads: bool):
        """Resolve one host's drained wire results against its pending
        list. Returns ``(matched, leftovers)`` — leftovers are pending
        entries the host died holding; the CALLER fails them over
        AFTER its sweep (a failover drains the target host, which
        mid-sweep would discard that host's own undrained results)."""
        by_tok = {w["token"]: w for w in wires
                  if isinstance(w, dict) and "token" in w}
        out = []
        leftovers = []
        for p in pend:
            self._pending[hid].remove(p)
            self._inflight[hid] = max(0, self._inflight[hid] - 1)
            w = by_tok.get(p.token)
            if w is None:
                leftovers.append(p)
                continue
            res = (self._unwire_read(w, p.request) if reads
                   else self._unwire_fit(w, p))
            p.handle._result = res
            out.append((p.seq, res))
        return out, leftovers

    def _failover_pending(self, hid: str, p: _Pending):
        """A host died holding ``p``: re-route + re-run it on a
        surviving host (synchronously — failover is the slow path),
        or resolve a structured failure. Nothing is silently dropped."""
        self._failovers += 1
        telemetry.inc("fleet.failover.requests")
        # a sessionful request pinned to the dead host must re-pin
        sid = getattr(p.request, "session_id", None)
        if sid is not None:
            skey = self._sid_last.get(sid)
            if skey is not None and self._sticky.get(skey) == hid:
                del self._sticky[skey]
        try:
            if p.read:
                res = self.predict(p.request)
                p.handle._result = res
                return res
            alive = self.alive_hosts()
            if not alive:
                raise HostDown("no alive hosts in the fleet")
            new_hid, _token, _fp8 = self._route_fit(p.request)
            tok = self.hosts[new_hid].submit(p.request)
            wires = self.hosts[new_hid].drain()
            w = next(w for w in wires if w["token"] == tok)
            res = self._unwire_fit(w, p)
        except Exception as e:  # noqa: BLE001 — isolation boundary
            if p.read:
                res = PredictResult(
                    tag=p.request.tag, request=p.request,
                    status="failed",
                    error=f"host {hid} died; failover failed: "
                          f"{type(e).__name__}: {e}", host=hid)
            else:
                res = FitResult(
                    tag=p.request.tag, request=p.request,
                    chi2=float("nan"), converged=False, batch=-1,
                    group="", n_members=0, occupancy=0.0,
                    queue_latency_s=0.0, status="failed",
                    error=f"host {hid} died; failover failed: "
                          f"{type(e).__name__}: {e}", host=hid)
        p.handle._result = res
        return res

    def drain(self) -> list[FitResult]:
        """Drain every host with pending work; resolve all handles.

        Reads drain first fleet-wide (the two-tier contract), then
        each host's fit queue; a host that died since submit has its
        pending requests re-routed to survivors. Results return in
        fleet submission order. One ``type="fleet"`` record per drain
        carries the per-host health/report block."""
        t0 = time.perf_counter()
        self.drain_reads()
        out: list[tuple[int, FitResult]] = []
        per_host_n: dict[str, int] = {}
        orphans: list[tuple[str, _Pending]] = []
        for hid in self._order:
            pend = [p for p in self._pending[hid] if not p.read]
            if not pend:
                continue
            per_host_n[hid] = len(pend)
            try:
                wires = self.hosts[hid].drain()
            except HostDown:
                self._note_down(hid)
                wires = []
            matched, left = self._match(hid, pend, wires, reads=False)
            out.extend(matched)
            orphans.extend((hid, p) for p in left)
        # failover AFTER the sweep: every survivor's own pending is
        # resolved by now, so the failover's drain on it cannot
        # swallow co-pending work
        for hid, p in orphans:
            out.append((p.seq, self._failover_pending(hid, p)))
        self._refresh_reports()
        wall = time.perf_counter() - t0
        results = [r for _s, r in sorted(out, key=lambda t: t[0])]
        if results or per_host_n:
            self._emit_record(results, per_host_n, wall)
        return results

    def _refresh_reports(self) -> None:
        for hid in self._order:
            h = self._health[hid]
            if not h["alive"]:
                continue
            try:
                rep = self.hosts[hid].report()
            except (HostDown, OSError):
                self._note_down(hid)
                continue
            h["queue_depth"] = int(rep.get("queue_depth", 0))
            h["read_depth"] = int(rep.get("read_depth", 0))
            h["fail_streak"] = int(rep.get("fail_streak", 0))
            h["degraded"] = bool(rep.get("degraded", False))
            h["latency_s"] = rep.get("last_drain_wall_s")
            h["program_misses"] = int(rep.get("program_misses", 0))

    def _emit_record(self, results, per_host_n, wall) -> None:
        routes, self._route_counts = self._route_counts, {}
        failovers, self._failovers = self._failovers, 0
        warm_hits, self._warm_hits = self._warm_hits, 0
        warm_total, self._warm_total = self._warm_total, 0
        sticky = routes.get("sticky", 0)
        routed = sum(routes.values())
        statuses: dict[str, int] = {}
        for r in results:
            statuses[r.status] = statuses.get(r.status, 0) + 1
        alive = self.alive_hosts()
        telemetry.set_gauge("fleet.hosts_alive", len(alive))
        self.last_drain = {
            "type": "fleet",
            "hosts": [
                {"host": hid,
                 "alive": self._health[hid]["alive"],
                 "requests": per_host_n.get(hid, 0),
                 "queue_depth": self._health[hid]["queue_depth"],
                 "fail_streak": self._health[hid]["fail_streak"],
                 "degraded": self._degraded(hid),
                 "program_misses": self._health[hid]["program_misses"]}
                for hid in self._order],
            "requests": len(results),
            "routes": routes,
            "sticky_hit_rate": (round(sticky / routed, 4)
                                if routed else None),
            # fraction of warm-trackable fits that landed on a host
            # already holding their structure's caches — the sticky-
            # routing effectiveness headline of the FLEET artifacts
            # (raw counts carried too so rollups aggregate exactly:
            # the rate's denominator is warm-trackable fits, NOT the
            # route-count total, which also counts reads/sheds)
            "warm_hits": warm_hits,
            "warm_total": warm_total,
            "warm_hit_rate": (round(warm_hits / warm_total, 4)
                              if warm_total else None),
            "failovers": failovers,
            "statuses": statuses,
            "degenerate": self.degenerate,
            "wall_s": round(wall, 6),
        }
        telemetry.add_record(dict(self.last_drain))

    def close(self) -> None:
        for h in self.hosts.values():
            try:
                h.close()
            except (HostDown, OSError):
                pass
