"""Observatory registry: ground stations, special locations, clock chains.

Reference equivalent: ``pint.observatory`` (src/pint/observatory/__init__.py,
topo_obs.py, special_locations.py, observatories.json). An Observatory
resolves a TOA's site code to (a) an ITRF position for geometric delays and
(b) a clock-correction chain to bring local time onto TT.

ITRF coordinates below are transcribed from documented public values of the
standard pulsar observatories (the same constants observatories.json
carries). Offline caveat: values recalled to ~10 m; that shifts the
topocentric Roemer term by tens of ns — absorbed entirely by the
self-consistent simulate->fit test strategy, and each entry is data, not
code: override or extend via :func:`register`.

Clock files (obs->UTC(GPS)->TT(BIPM) chains; reference
src/pint/observatory/clock_file.py + global_clock_corrections.py) are not
shipped offline; the chain evaluates to zero with a warning unless clock
data is registered via :func:`register_clock`.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from pint_tpu.clock import ClockFile

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Observatory:
    """A timing site. itrf_xyz_m is None for special (non-topocentric) sites."""

    name: str
    itrf_xyz_m: Optional[tuple[float, float, float]]
    aliases: tuple[str, ...] = ()
    tempo_code: str = ""
    origin: str = ""
    is_barycenter: bool = False
    is_geocenter: bool = False

    @property
    def is_special(self) -> bool:
        return self.itrf_xyz_m is None


_REGISTRY: dict[str, Observatory] = {}
_ALIAS_MAP: dict[str, str] = {}
_CLOCKS: dict[str, list[ClockFile]] = {}


def register(obs: Observatory) -> None:
    key = obs.name.lower()
    _REGISTRY[key] = obs
    _ALIAS_MAP[key] = key
    for a in obs.aliases:
        _ALIAS_MAP[a.lower()] = key
    if obs.tempo_code:
        _ALIAS_MAP[obs.tempo_code.lower()] = key


def get_observatory(name: str) -> Observatory:
    key = _ALIAS_MAP.get(str(name).lower())
    if key is None:
        raise KeyError(
            f"unknown observatory {name!r}; known: {sorted(_REGISTRY)} "
            "(register custom sites via pint_tpu.observatory.register)"
        )
    return _REGISTRY[key]


def list_observatories() -> list[str]:
    return sorted(_REGISTRY)


def register_clock(obs_name: str, clock_files: list[ClockFile]) -> None:
    """Attach a clock-correction chain (applied in order, seconds added)."""
    _CLOCKS[get_observatory(obs_name).name.lower()] = clock_files


def _discover_clock_chain(name: str):
    """Auto-register a chain from $PINT_TPU_CLOCK_DIR, once per site.

    Conventions searched (the IPTA clock-repo layouts the reference
    downloads into its cache): tempo2 ``<name>2gps.clk`` (+
    ``gps2utc.clk`` if present) or tempo ``time_<name>.dat``. Returns
    the chain, or None if the env var is unset / no file matches.
    """
    import os

    from pint_tpu.config import get_config

    clock_dir = get_config().clock_dir
    if not clock_dir:
        return None
    chain: list[ClockFile] = []
    t2 = os.path.join(clock_dir, f"{name}2gps.clk")
    t1 = os.path.join(clock_dir, f"time_{name}.dat")
    if os.path.isfile(t2):
        chain.append(ClockFile.read_tempo2(t2))
        gps = os.path.join(clock_dir, "gps2utc.clk")
        if os.path.isfile(gps):
            chain.append(ClockFile.read_tempo2(gps))
    elif os.path.isfile(t1):
        chain.append(ClockFile.read_tempo(t1))
    if not chain:
        return None
    log.info("auto-registered clock chain for %s from %s", name, clock_dir)
    _CLOCKS[name] = chain
    return chain


def clock_corrections_s(obs_name: str, mjd_utc: np.ndarray, *, limits: str = "warn") -> np.ndarray:
    """Total clock correction to add to site TOAs [s] at the given MJDs.

    Host-side (numpy): clock files are irregular tables; evaluation happens
    once at load time and is stored on the TOA table, mirroring
    ``TOAs.apply_clock_corrections`` (reference src/pint/toa.py).
    """
    obs = get_observatory(obs_name)
    chain = _CLOCKS.get(obs.name.lower())
    if chain is None and not obs.is_special:
        chain = _discover_clock_chain(obs.name.lower())
    mjd_utc = np.asarray(mjd_utc, np.float64)
    if chain is None:
        if not obs.is_special:
            log.warning(
                "no clock chain registered for %s; assuming perfect site clock "
                "(offline default — register files via register_clock or set "
                "PINT_TPU_CLOCK_DIR)",
                obs.name,
            )
        return np.zeros_like(mjd_utc)
    total = np.zeros_like(mjd_utc)
    for cf in chain:
        total = total + cf.evaluate(mjd_utc + total / 86400.0, limits=limits)
    return total


# ---------------------------------------------------------------------------
# Built-in registry (ITRF XYZ in meters)
# ---------------------------------------------------------------------------

_BUILTIN = [
    Observatory("gbt", (882589.65, -4924872.32, 3943729.348), ("gb", "green_bank"), "1"),
    Observatory("arecibo", (2390490.0, -5564764.0, 1994727.0), ("ao", "aoutc"), "3"),
    Observatory("parkes", (-4554231.5, 2816759.1, -3454036.3), ("pks",), "7"),
    Observatory("jodrell", (3822626.04, -154105.65, 5086486.04), ("jb", "jbdfb", "jbroach", "jbafb"), "8"),
    Observatory("nancay", (4324165.81, 165927.11, 4670132.83), ("ncy", "nuppi"), "f"),
    Observatory("effelsberg", (4033949.5, 486989.4, 4900430.8), ("eff", "effix"), "g"),
    Observatory("wsrt", (3828445.659, 445223.600, 5064921.568), ("we",), "i"),
    Observatory("vla", (-1601192.0, -5041981.4, 3554871.4), ("jvla",), "6"),
    Observatory("meerkat", (5109360.133, 2006852.586, -3238948.127), ("mk",), "m"),
    Observatory("fast", (-1668557.0, 5506838.0, 2744934.0), (), "k"),
    Observatory("chime", (-2059166.313, -3621302.972, 4814304.113), (), "y"),
    Observatory("gmrt", (1656342.30, 5797947.77, 2073243.16), (), "r"),
    Observatory("lofar", (3826577.462, 461022.624, 5064892.526), (), "t"),
    Observatory("srt", (4865182.766, 791922.689, 4035137.174), ("sardinia",), "z"),
    Observatory("hobart", (-3950077.96, 2522377.31, -4311667.52), (), "4"),
    Observatory("hartrao", (5085442.780, 2668263.483, -2768697.034), ("hart",), "a"),
    Observatory("kat7", (5109943.105, 2003650.7359, -3239908.3195), (), ""),
    Observatory("mwa", (-2559454.08, 5095372.14, -2849057.18), (), "u"),
    Observatory("lwa1", (-1602196.60, -5042313.47, 3553971.51), (), "x"),
    Observatory("ncyobs", (4324165.81, 165927.11, 4670132.83), (), "w"),
    # special locations (reference src/pint/observatory/special_locations.py)
    Observatory("barycenter", None, ("@", "ssb", "bary", "bat"), "@", is_barycenter=True),
    Observatory("geocenter", None, ("coe", "0"), "o", is_geocenter=True),
    Observatory("stl_geo", None, ("stl",), "", is_geocenter=True),  # spacecraft placeholder
    # orbiting observatory: GCRS offsets are injected per-TOA from an
    # orbit file (pint_tpu.event_toas.load_orbit_file) instead of an
    # ITRF rotation; neither barycentric nor geocentric, no site clock
    # (reference: pint.observatory.satellite_obs)
    Observatory("spacecraft", None, ("orb", "satellite"), ""),
]

for _obs in _BUILTIN:
    register(_obs)
