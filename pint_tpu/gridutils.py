"""Chi-square over parameter grids, evaluated as one vmapped XLA program.

Reference equivalent: ``pint.gridutils.grid_chisq`` /
``grid_chisq_derived`` (src/pint/gridutils.py) — the reference's only
parallelism, a ``concurrent.futures`` pool refitting at every grid node
with a full Fitter. Here the grid is a ``vmap`` axis: at each node the
gridded parameters are pinned to their offsets and the *remaining* free
parameters are solved in the same linearized WLS step used everywhere
else, so an entire (e.g.) 64x64 grid is one compiled program on device
instead of thousands of Python fits.
"""

from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.fitter import wls_solve_gram

Array = jax.Array


def _chisq_at_points(toas, model, param_names: tuple[str, ...],
                     points: np.ndarray, *, solve_free: bool = True,
                     gls: bool = False) -> np.ndarray:
    """Vmapped chi2 at (npoints, nparams) parameter-offset rows.

    ``gls=True`` evaluates the generalized chi2 r^T C^-1 r with
    C = N + U phi U^T (ECORR/red-noise bases at the model's current
    hyperparameters) via the Woodbury identity — the option the
    round-1 review flagged as missing (grid-chi2 was white-noise only).

    The white-noise metric routes through the structure-fingerprinted
    program cache with the TOA table *traced and bucketed*
    (pint_tpu.bucketing): repeated grids in a session — around
    successive fits, or over different same-structure datasets — reuse
    ONE compiled program per (structure, gridded params, bucket) instead
    of re-jitting a fresh closure every call. The GLS metric keeps the
    per-call closure at exact shapes: its host-built dense noise basis U
    is dataset-content-keyed, which a conservative program cache cannot
    express (documented policy — docs/ARCHITECTURE.md).
    """
    pairs = model._noise_basis_pairs(toas) if gls else []
    if pairs:
        return _chisq_at_points_dense_noise(toas, model, param_names,
                                            points, solve_free, pairs)

    from pint_tpu import bucketing

    def build(owner):
        free_rest = [n for n in owner.free_params if n not in param_names]
        phase_fn = owner.phase_fn_toas()

        def f(base, pts, tt):
            err = owner.scaled_toa_uncertainty(tt)
            w = 1.0 / jnp.square(err)
            sqrtw = jnp.sqrt(w)
            f0 = base["F0"].hi + base["F0"].lo

            def whitened_resid(deltas):
                ph = phase_fn(base, deltas, tt)
                resid = ph.frac.hi + ph.frac.lo
                resid = resid - jnp.sum(resid * w) / jnp.sum(w)
                return resid / f0

            def total_phase(deltas):
                ph = phase_fn(base, deltas, tt)
                return ph.int_part + (ph.frac.hi + ph.frac.lo)

            def chi2_at(point):
                deltas = {n: point[i] for i, n in enumerate(param_names)}
                deltas.update({n: jnp.zeros(()) for n in free_rest})
                r = whitened_resid(deltas)
                if solve_free and free_rest:
                    J = jax.jacfwd(total_phase)(deltas)
                    cols = [jnp.ones_like(r) / f0] \
                        + [-J[n] / f0 for n in free_rest]
                    M = jnp.stack(cols, axis=1)
                    x = wls_solve_gram(M, r, err)["x"]
                    fitted = dict(deltas)
                    for i, n in enumerate(free_rest):
                        fitted[n] = x[i + 1]
                    r = whitened_resid(fitted)
                rw = r * sqrtw
                return rw @ rw

            return jax.vmap(chi2_at)(pts)

        return f

    fn = model._cached_jit(("grid_chisq", tuple(param_names), solve_free),
                           build)
    tt = bucketing.bucket_toas(toas)
    from pint_tpu.models.timing_model import program_fp8

    bucketing.note_program("grid_chisq", (program_fp8(fn) or id(fn),),
                           (len(tt), int(np.shape(points)[0])))
    return np.asarray(fn(model.base_dd(), jnp.asarray(points), tt))


def _chisq_at_points_dense_noise(toas, model, param_names, points,
                                 solve_free, pairs) -> np.ndarray:
    """GLS grid metric with the host-built dense noise basis (exact shapes)."""
    free_rest = [n for n in model.free_params if n not in param_names]
    base = model.base_dd()
    phase_fn = model.phase_fn_toas()
    err = model.scaled_toa_uncertainty(toas)
    w = 1.0 / jnp.square(err)
    f0 = model.f0_f64

    U = jnp.asarray(np.concatenate([u for _, u, _ in pairs], axis=1))
    inv_phi = jnp.asarray(1.0 / np.concatenate([p for _, _, p in pairs]))

    def frac_phase(deltas):
        ph = phase_fn(base, deltas, toas)
        return ph.frac.hi + ph.frac.lo

    def total_phase(deltas):
        ph = phase_fn(base, deltas, toas)
        return ph.int_part + (ph.frac.hi + ph.frac.lo)

    def whitened_resid(deltas):
        resid = frac_phase(deltas)
        resid = resid - jnp.sum(resid * w) / jnp.sum(w)
        return resid / f0

    sqrtw = jnp.sqrt(w)

    Aw = U * sqrtw[:, None]
    S = jnp.diag(inv_phi) + Aw.T @ Aw
    S_fac = jax.scipy.linalg.cho_factor(S, lower=True)

    def cinv_w(X):  # whitened C^-1 via Woodbury: I - Aw S^-1 Aw^T
        return X - Aw @ jax.scipy.linalg.cho_solve(S_fac, Aw.T @ X)

    def gls_solve_free(M, r):
        """Linearized free-parameter solve in the C metric."""
        Mw = M * sqrtw[:, None]
        CiM = cinv_w(Mw)
        G = Mw.T @ CiM
        G = G + jnp.eye(G.shape[0]) * (jnp.finfo(jnp.float64).eps
                                       * jnp.trace(G))
        c = CiM.T @ (r * sqrtw)
        L, low = jax.scipy.linalg.cho_factor(G, lower=True)
        return jax.scipy.linalg.cho_solve((L, low), c)

    def chi2_at(point):
        deltas = {n: point[i] for i, n in enumerate(param_names)}
        deltas.update({n: jnp.zeros(()) for n in free_rest})
        r = whitened_resid(deltas)
        if solve_free and free_rest:
            J = jax.jacfwd(total_phase)(deltas)
            cols = [jnp.ones_like(r) / f0] + [-J[n] / f0 for n in free_rest]
            M = jnp.stack(cols, axis=1)
            x = gls_solve_free(M, r)
            fitted = dict(deltas)
            for i, n in enumerate(free_rest):
                fitted[n] = x[i + 1]
            r = whitened_resid(fitted)
        rw = r * sqrtw
        return rw @ cinv_w(rw)

    return np.asarray(jax.jit(jax.vmap(chi2_at))(jnp.asarray(points)))


def grid_chisq(toas, model, param_names: tuple[str, ...], grids,
               *, solve_free: bool = True, gls: bool = False) -> np.ndarray:
    """chi2 over an outer-product grid of parameter *offsets*.

    param_names: gridded parameters; grids: per-parameter 1D arrays of
    offsets about the current model values (the reference grids around
    the fitted solution the same way). With ``solve_free`` the other
    free parameters are re-solved (linearized) at every node; with
    ``gls`` the chi2 is the generalized r^T C^-1 r including the model's
    correlated-noise bases. Returns chi2 shaped [len(g) for g in grids].
    """
    grids = [np.asarray(g, dtype=np.float64) for g in grids]
    if len(grids) != len(param_names):
        raise ValueError("one grid per parameter required")
    points = np.asarray(list(itertools.product(*grids)))
    chi2 = _chisq_at_points(toas, model, tuple(param_names), points,
                            solve_free=solve_free, gls=gls)
    return chi2.reshape([len(g) for g in grids])


def grid_chisq_derived(toas, model, param_names, funcs, grids,
                       *, solve_free: bool = True, gls: bool = False) -> np.ndarray:
    """Grid over derived coordinates: offsets = funcs applied to grid axes.

    Reference: pint.gridutils.grid_chisq_derived. ``funcs[i](*mesh)``
    maps the grid coordinates to the offset of ``param_names[i]``.
    """
    grids = [np.asarray(g, dtype=np.float64) for g in grids]
    mesh = np.meshgrid(*grids, indexing="ij")
    offsets = [np.asarray(f(*mesh), dtype=np.float64).ravel() for f in funcs]
    points = np.stack(offsets, axis=1)
    chi2 = _chisq_at_points(toas, model, tuple(param_names), points,
                            solve_free=solve_free, gls=gls)
    return chi2.reshape(mesh[0].shape)
