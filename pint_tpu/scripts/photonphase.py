"""``photonphase``: assign pulse phases to photon events + H-test.

Reference: pint.scripts.photonphase (src/pint/scripts/photonphase.py).
Reads a FITS event file (barycentered TDB or geocentered TT — the same
no-orbit-file constraint as the reference), computes model phases with
the jitted phase function, reports the H-test, and can write the
phases back out.
"""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="photonphase",
        description="Compute model pulse phase for FITS photon events")
    parser.add_argument("eventfile")
    parser.add_argument("parfile")
    parser.add_argument("--mission", default="generic",
                        help="fermi / nicer / nustar / rxte / xmm / generic")
    parser.add_argument("--weightcol", default=None,
                        help="photon-weight column name (e.g. Fermi WEIGHT)")
    parser.add_argument("--emin", type=float, default=None, help="keV")
    parser.add_argument("--emax", type=float, default=None, help="keV")
    parser.add_argument("--maxharmonics", type=int, default=20)
    parser.add_argument("--orbfile", default=None,
                        help="spacecraft orbit FITS file (required for "
                             "unbarycentered TIMEREF=LOCAL events)")
    parser.add_argument("--outfile", default=None,
                        help="write 'mjd_tdb phase [weight]' rows here")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    import numpy as np

    from pint_tpu.event_toas import get_photon_weights, load_event_TOAs
    from pint_tpu.models import get_model
    from pint_tpu.templates import h_test, photon_phases

    erange = None
    if args.emin is not None or args.emax is not None:
        erange = (args.emin or 0.0, args.emax or np.inf)
    toas = load_event_TOAs(args.eventfile, args.mission,
                           weight_column=args.weightcol,
                           energy_range_kev=erange, orbfile=args.orbfile)
    model = get_model(args.parfile)
    phases = photon_phases(model, toas)
    weights = get_photon_weights(toas)
    h, prob = h_test(phases, weights, max_harmonics=args.maxharmonics)
    print(f"Photons: {len(toas)}")
    print(f"Htest  : {h:.3f}  (prob {prob:.3e})")

    if args.outfile:
        mjd = np.asarray(toas.tdb.hi) + np.asarray(toas.tdb.lo)
        cols = [mjd, phases] + ([weights] if weights is not None else [])
        np.savetxt(args.outfile, np.column_stack(cols),
                   header="mjd_tdb phase" + (" weight" if weights is not None
                                             else ""))
        print(f"Wrote {args.outfile}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
