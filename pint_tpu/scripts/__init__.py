"""Console entry points (reference: src/pint/scripts/).

Each module exposes ``main(argv=None)`` and is wired as a console script
in ``pyproject.toml``:

* ``pintempo``  — load par+tim, fit, print summary, write post-fit par
* ``zima``      — simulate fake TOAs from a model and write a tim file
* ``tcb2tdb``   — convert a TCB par file to TDB
* ``compare_parfiles`` — parameter-by-parameter model comparison
* ``pintbary``  — barycenter arrival times with a (minimal) model
* ``photonphase`` — phases + H-test for FITS photon events
* ``event_optimize`` — MCMC timing fit against a profile template
* ``pintpublish`` — LaTeX/plain publication parameter table
"""
