"""Console entry points (reference: src/pint/scripts/).

Each module exposes ``main(argv=None)`` and is wired as a console script
in ``pyproject.toml``:

* ``pintempo``  — load par+tim, fit, print summary, write post-fit par
* ``zima``      — simulate fake TOAs from a model and write a tim file
* ``tcb2tdb``   — convert a TCB par file to TDB
* ``compare_parfiles`` — parameter-by-parameter model comparison
* ``pintbary``  — barycenter arrival times with a (minimal) model
* ``photonphase`` — phases + H-test for FITS photon events
* ``event_optimize`` — MCMC timing fit against a profile template
* ``pintpublish`` — LaTeX/plain publication parameter table
"""


def script_init(log_level: str = "INFO") -> None:
    """One-call console-script initialization: logging + f64 safety.

    Every entry point calls this (and ONLY this) after argument
    parsing, so a new tool cannot forget the exact-f64 guard without
    also forgetting its logging setup.
    """
    _pin_platform()
    from pint_tpu import logging as pint_logging

    pint_logging.setup(log_level)
    ensure_exact_f64()
    _touch_program_store()


def _touch_program_store() -> None:
    """Latch the persistent program store before the first compile.

    The store-touch-before-first-compile rule (see
    :mod:`pint_tpu.programs`): with PINT_TPU_PROGRAM_CACHE_DIR set, the
    persistent XLA compile cache only helps if it is wired before the
    process traces anything, so a console tool's repeat invocations pay
    the compile once, not per run. No-op (store() is None) with the
    knob unset; never raises — persistence must not break a CLI.
    """
    try:
        from pint_tpu.programs.store import store as _store

        _store()
    except Exception:  # noqa: BLE001
        pass


def _pin_platform() -> None:
    """Select the JAX platform BEFORE any backend initialization.

    Two measured sandbox facts force this: (1) the axon sitecustomize
    force-selects its TPU platform via ``jax.config``, silently
    overriding a user's ``JAX_PLATFORMS=cpu``; (2) merely *initializing*
    that tunnel backend (which ``dd.self_check`` would trigger) can hang
    for minutes when the tunnel is busy (round-1 bench failure mode).
    Console tools are single-dataset workflows that must run on an
    IEEE-exact-f64 backend anyway, so default them to CPU outright; an
    explicit ``JAX_PLATFORMS`` naming an accelerator still wins. The
    mechanism is the library-level :func:`pint_tpu.setup_platform`
    guard — this wrapper only supplies the console-script default.
    """
    import os

    import pint_tpu

    pint_tpu.setup_platform(os.environ.get("JAX_PLATFORMS") or "cpu")


def ensure_exact_f64() -> None:
    """Pin the default device to the CPU if the current backend's float64
    is not IEEE-exact (``pint_tpu.ops.dd.self_check``).

    The interactive tools are single-dataset workflows whose DD phase
    arithmetic silently produces garbage on a backend with emulated
    f64 (observed on TPU v5e rounds 2 and 4, committed artifact
    pending — see pint_tpu.ops.dd).
    The big-N TPU
    paths go through the hybrid/sharded fitters, which manage device
    placement themselves; everything a console script touches should
    just run on the exact CPU backend.
    """
    import logging
    import os
    import subprocess
    import sys

    import jax

    from pint_tpu import config

    log = logging.getLogger("pint_tpu.scripts")

    platforms = str(jax.config.jax_platforms or "")
    if platforms and platforms.split(",")[0] == "cpu":
        return
    # NOTE: an EMPTY platforms config is NOT safe to skip — on a host
    # with an accelerator plugin installed (libtpu etc.), jax
    # auto-detects it, so the resolved default backend must be probed
    # exactly like an explicitly-requested one.

    # Touching a non-CPU backend (init OR first compile) can hang for
    # minutes inside a C call when the accelerator tunnel is down — and
    # the sandbox exports JAX_PLATFORMS=axon globally, so a console tool
    # must not trust it blindly. A SIGALRM guard cannot interrupt the
    # C-level init (GIL held), so probe in a CHILD process with a
    # wall-clock timeout (the guard pattern bench.py uses), and only
    # initialize the backend here once the child proved it responsive.
    timeout_s = config.env_int("PINT_TPU_SCRIPT_INIT_TIMEOUT")
    code = ("import jax\n"
            "from pint_tpu.ops import dd\n"
            "b = jax.default_backend()\n"
            "ok = b == 'cpu' or dd.self_check()\n"
            "print(b + ':' + ('EXACT' if ok else 'INEXACT'))\n")
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True,
                              timeout=timeout_s)
        out = proc.stdout.strip().splitlines()[-1] if proc.stdout else ""
        backend, _, verdict = out.partition(":")
        if proc.returncode != 0 or verdict not in ("EXACT", "INEXACT"):
            raise RuntimeError(
                f"probe rc={proc.returncode}: {proc.stderr[-300:]}")
    except (subprocess.TimeoutExpired, RuntimeError) as exc:
        jax.config.update("jax_platforms", "cpu")
        log.warning(
            "accelerator backend %s unreachable (%s); running on the "
            "CPU backend", platforms or "<auto>", exc)
        return

    if backend == "cpu":
        return  # auto-detection resolved to CPU: nothing to pin
    if verdict == "INEXACT":
        cpu = jax.devices("cpu")[0]
        jax.config.update("jax_default_device", cpu)
        log.warning(
            "backend %s fails the float64 exactness self-check; pinning "
            "computation to %s (see pint_tpu.ops.dd)", backend, cpu)
