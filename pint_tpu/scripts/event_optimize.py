"""``event_optimize``: MCMC timing fit against a photon-profile template.

Reference: pint.scripts.event_optimize (src/pint/scripts/event_optimize.py)
— emcee sampling of timing parameters with the unbinned template
likelihood. Here the sampler is the in-package pure-JAX ensemble and the
likelihood is one jitted program (pint_tpu.templates.EventFitter).

The template file format matches the reference's gaussian-template text
files: one ``phase width amplitude`` row per component (lines starting
with '#' ignored).
"""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def read_gaussian_template(path: str):
    """Parse 'phase width amplitude' rows into an LCTemplate."""
    import numpy as np

    from pint_tpu.templates import LCTemplate

    rows = []
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            vals = [float(x) for x in line.split()]
            if len(vals) != 3:
                raise ValueError(f"template row needs 3 numbers: {line!r}")
            rows.append(vals)
    if not rows:
        raise ValueError(f"no template components in {path}")
    arr = np.asarray(rows)
    return LCTemplate(locs=arr[:, 0], widths=arr[:, 1], norms=arr[:, 2])


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="event_optimize",
        description="MCMC-fit timing parameters to photon events using a "
                    "pulse-profile template")
    parser.add_argument("eventfile")
    parser.add_argument("parfile")
    parser.add_argument("gaussianfile", help="template: 'phase width amp' rows")
    parser.add_argument("--mission", default="generic")
    parser.add_argument("--weightcol", default=None)
    parser.add_argument("--nwalkers", type=int, default=None)
    parser.add_argument("--nsteps", type=int, default=500)
    parser.add_argument("--burnfrac", type=float, default=0.25)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--outpar", default=None,
                        help="write the max-posterior model here")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    from pint_tpu.event_toas import load_event_TOAs
    from pint_tpu.models import get_model
    from pint_tpu.templates import EventFitter, h_test, photon_phases

    toas = load_event_TOAs(args.eventfile, args.mission,
                           weight_column=args.weightcol)
    model = get_model(args.parfile)
    template = read_gaussian_template(args.gaussianfile)
    if not model.free_params:
        raise SystemExit("no free parameters in the par file")

    from pint_tpu.event_toas import get_photon_weights

    weights = get_photon_weights(toas)
    h0, _ = h_test(photon_phases(model, toas), weights)
    fitter = EventFitter(toas, model, template)
    best = fitter.fit_toas(args.nsteps, nwalkers=args.nwalkers,
                           seed=args.seed, burn_frac=args.burnfrac)
    h1, p1 = h_test(photon_phases(model, toas), weights)
    print(f"Photons: {len(toas)}   walkers x steps: "
          f"{fitter.chain.shape[0] // max(1, args.nsteps - int(args.nsteps * args.burnfrac))} x {args.nsteps}")
    print(f"log-posterior (best): {best:.3f}")
    print(f"Htest pre-fit : {h0:.2f}")
    print(f"Htest post-fit: {h1:.2f}  (prob {p1:.3e})")
    for name in fitter.fit_params:
        p = model.params[name]
        print(f"  {name:<10} {p.value_f64!r} +- {p.uncertainty:.3e}")
    if args.outpar:
        with open(args.outpar, "w") as f:
            f.write(model.as_parfile())
        print(f"Wrote {args.outpar}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
