"""``pintpublish``: publication-quality parameter table from a par file.

Reference: pint.scripts.pintpublish (src/pint/scripts/pintpublish.py) —
renders a fitted timing model as a LaTeX (or plain) table with
value(uncertainty-in-last-digits) notation plus derived quantities.
"""

from __future__ import annotations

import argparse
import math

from pint_tpu.scripts import script_init


def tex_escape(s: str) -> str:
    """Escape LaTeX text-mode specials in parameter names/units."""
    return (s.replace("\\", "\\textbackslash{}").replace("_", "\\_")
            .replace("^", "\\^{}").replace("&", "\\&").replace("%", "\\%")
            .replace("#", "\\#").replace("$", "\\$"))


def value_with_unc(value: float, unc: float) -> str:
    """'1.23456(78)' notation: uncertainty in units of the last digits."""
    if not unc or unc <= 0 or not math.isfinite(unc):
        return f"{value:.12g}"
    exp = int(math.floor(math.log10(unc)))
    u2 = round(unc / 10 ** (exp - 1))  # uncertainty to 2 significant digits
    if u2 >= 100:  # rounding carried (e.g. 9.99 -> 100): shift the decade
        exp += 1
        u2 = round(unc / 10 ** (exp - 1))
    digits = max(0, -(exp - 1))
    if digits == 0:
        return f"{value:.0f}({u2 * 10 ** (exp - 1):.0f})"
    return f"{value:.{digits}f}({u2})"


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pintpublish",
        description="Render a timing model as a publication table")
    parser.add_argument("parfile")
    parser.add_argument("timfile", nargs="?", default=None,
                        help="optionally refit before rendering")
    parser.add_argument("--format", choices=("latex", "text"),
                        default="latex")
    parser.add_argument("--all", action="store_true",
                        help="include frozen parameters too")
    parser.add_argument("--log-level", default="WARNING")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    from pint_tpu.derived_quantities import (pulsar_age_yr, pulsar_B_gauss,
                                             pulsar_period_s)
    from pint_tpu.models import get_model

    model = get_model(args.parfile)
    ntoa = chi2 = None
    if args.timfile:
        from pint_tpu.fitting import Fitter
        from pint_tpu.toas import get_TOAs

        toas = get_TOAs(args.timfile, ephem=model.ephem)
        fitter = Fitter.auto(toas, model)
        chi2 = fitter.fit_toas(maxiter=3)
        ntoa = len(toas)

    rows = []
    for name, p in model.params.items():
        if not p.is_numeric:
            continue
        if p.frozen and not (args.all or p.uncertainty):
            continue
        val = value_with_unc(p.value_f64, p.uncertainty or 0.0)
        rows.append((name, val, p.units or ""))

    f0 = model.f0_f64
    f1 = model["F1"].value_f64 if "F1" in model.params else 0.0
    derived = [("Period (s)", f"{pulsar_period_s(f0):.9f}")]
    if f1:
        derived += [
            ("Characteristic age (yr)", f"{pulsar_age_yr(f0, f1):.3e}"),
            ("Surface B field (G)", f"{pulsar_B_gauss(f0, f1):.3e}"),
        ]

    if args.format == "latex":
        print("\\begin{table}")
        print(f"\\caption{{Timing parameters for {tex_escape(model.name)}}}")
        print("\\begin{tabular}{lll}")
        print("\\hline")
        print("Parameter & Value & Units \\\\")
        print("\\hline")
        for name, val, units in rows:
            print(f"{tex_escape(name)} & {val} & {tex_escape(units)} \\\\")
        print("\\hline")
        for label, val in derived:
            print(f"{tex_escape(label)} & {val} & \\\\")
        if ntoa is not None:
            print(f"Number of TOAs & {ntoa} & \\\\")
            print(f"$\\chi^2$ & {chi2:.2f} & \\\\")
        print("\\hline")
        print("\\end{tabular}")
        print("\\end{table}")
    else:
        width = max(len(r[0]) for r in rows + [(d[0], "", "") for d in derived])
        for name, val, units in rows:
            print(f"{name:<{width}}  {val}  {units}")
        for label, val in derived:
            print(f"{label:<{width}}  {val}")
        if ntoa is not None:
            print(f"{'TOAs':<{width}}  {ntoa}")
            print(f"{'chi2':<{width}}  {chi2:.2f}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    raise SystemExit(main())
