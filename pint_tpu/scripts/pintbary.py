"""``pintbary``: barycenter times on the command line
(reference: pint.scripts.pintbary).

Given an observatory MJD (topocentric UTC) and a sky position — from a
par file or --ra/--dec — prints the barycentric arrival time (TDB MJD at
the SSB) obtained by subtracting the model's total delay (Roemer +
Shapiro + Einstein chain; dispersion at infinite frequency).
"""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init

_MIN_PAR = """PSR BARY
RAJ {ra}
DECJ {dec}
F0 1.0
PEPOCH {epoch}
DM 0.0
UNITS TDB
"""


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pintbary", description="Barycenter one or more MJDs")
    parser.add_argument("mjd", type=float, nargs="+",
                        help="topocentric UTC MJD(s)")
    parser.add_argument("--parfile", default=None)
    parser.add_argument("--ra", default=None, help="e.g. 12:34:56.7")
    parser.add_argument("--dec", default=None, help="e.g. -12:34:56.7")
    parser.add_argument("--obs", default="gbt")
    parser.add_argument("--freq", type=float, default=1e8,
                        help="MHz (default: effectively infinite -> no DM delay)")
    args = parser.parse_args(argv)
    script_init()

    import numpy as np

    import jax.numpy as jnp

    from pint_tpu.models import get_model
    from pint_tpu.ops import dd
    from pint_tpu.toas import build_TOAs_from_arrays

    if args.parfile:
        model = get_model(args.parfile)
    elif args.ra and args.dec:
        model = get_model(_MIN_PAR.format(ra=args.ra, dec=args.dec,
                                          epoch=args.mjd[0]))
    else:
        parser.error("provide --parfile or both --ra and --dec")

    n = len(args.mjd)
    mjds = dd.from_strings([repr(m) for m in args.mjd])
    toas = build_TOAs_from_arrays(
        mjds, freq_mhz=np.full(n, args.freq), error_us=np.ones(n),
        obs_names=(args.obs,), eph=model.ephem)
    delay_s = np.asarray(model.delay(toas))
    tdb_bary = dd.sub(toas.tdb, jnp.asarray(delay_s) / 86400.0)
    for i in range(n):
        print(dd.to_string(tdb_bary[i], ndigits=20))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
