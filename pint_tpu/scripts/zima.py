"""``zima``: simulate fake TOAs (reference: pint.scripts.zima).

Usage: zima [options] PARFILE TIMFILE_OUT
"""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="zima",
        description="Simulate TOAs that a timing model predicts perfectly "
                    "(optionally with noise), and write them as a tim file")
    parser.add_argument("parfile")
    parser.add_argument("timfile", help="output .tim path")
    parser.add_argument("--ntoa", type=int, default=100)
    parser.add_argument("--startMJD", type=float, default=56000.0)
    parser.add_argument("--duration", type=float, default=400.0,
                        help="days of data")
    parser.add_argument("--obs", default="gbt")
    parser.add_argument("--freq", type=float, nargs="+", default=[1400.0],
                        help="observing frequencies, MHz (cycled over TOAs)")
    parser.add_argument("--error", type=float, default=1.0, help="TOA sigma, us")
    parser.add_argument("--addnoise", action="store_true",
                        help="fold a Gaussian error draw into the TOAs")
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--inputtim", default=None,
                        help="take MJDs/errors/flags from this tim file "
                             "instead of a uniform grid")
    parser.add_argument("--log-level", default="INFO")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    import numpy as np

    from pint_tpu.models import get_model
    from pint_tpu.simulation import (make_fake_toas_fromtim,
                                     make_fake_toas_uniform)
    from pint_tpu.toas import write_TOA_file

    model = get_model(args.parfile)
    if args.inputtim:
        toas = make_fake_toas_fromtim(args.inputtim, model,
                                      add_noise=args.addnoise, seed=args.seed)
    else:
        toas = make_fake_toas_uniform(
            args.startMJD, args.startMJD + args.duration, args.ntoa, model,
            obs=args.obs, freq_mhz=np.asarray(args.freq),
            error_us=args.error, add_noise=args.addnoise, seed=args.seed)
    write_TOA_file(toas, args.timfile)
    print(f"Wrote {len(toas)} simulated TOAs to {args.timfile}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
