"""``compare_parfiles``: parameter-level model diff
(reference: pint.scripts.compare_parfiles / TimingModel.compare)."""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def compare_models(m1, m2) -> str:
    """Tabulate parameter differences between two models.

    For parameters with uncertainties the difference is also expressed in
    units of the first model's sigma (the reference's compare() column).
    """
    lines = [f"{'PAR':<12}{'model1':>24}{'model2':>24}{'diff':>14}{'diff/sig1':>11}"]
    names = list(dict.fromkeys(list(m1.params) + list(m2.params)))
    for name in names:
        p1 = m1.params.get(name)
        p2 = m2.params.get(name)
        if p1 is None or p2 is None:
            only = "model1" if p2 is None else "model2"
            p = p1 or p2
            if p.is_numeric or p.kind == "str":
                lines.append(f"{name:<12}{'(only in ' + only + ')':>24}")
            continue
        if not p1.is_numeric or not p2.is_numeric:
            continue
        v1, v2 = p1.value_f64, p2.value_f64
        d = v2 - v1
        sig = ""
        if p1.uncertainty:
            sig = f"{d / p1.uncertainty:10.2f}"
        if d == 0.0 and not p1.uncertainty:
            continue
        lines.append(f"{name:<12}{p1.format_value():>24}{p2.format_value():>24}"
                     f"{d:>14.4e}{sig:>11}")
    return "\n".join(lines)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="compare_parfiles",
        description="Compare two par files parameter by parameter")
    parser.add_argument("parfile1")
    parser.add_argument("parfile2")
    args = parser.parse_args(argv)
    script_init()

    from pint_tpu.models import get_model

    m1 = get_model(args.parfile1)
    m2 = get_model(args.parfile2)
    print(compare_models(m1, m2))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
