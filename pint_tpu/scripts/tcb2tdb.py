"""``tcb2tdb``: convert a TCB par file to TDB (reference: pint.scripts.tcb2tdb)."""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="tcb2tdb", description="Convert a TCB-units par file to TDB")
    parser.add_argument("input_par")
    parser.add_argument("output_par")
    args = parser.parse_args(argv)
    script_init()

    from pint_tpu.models.tcb_conversion import tcb2tdb_file

    tcb2tdb_file(args.input_par, args.output_par)
    print(f"Wrote TDB par file to {args.output_par}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
