"""``pintempo``: command-line fitting (reference: pint.scripts.pintempo).

Usage: pintempo [options] PARFILE TIMFILE
"""

from __future__ import annotations

import argparse

from pint_tpu.scripts import script_init


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="pintempo",
        description="Fit a pulsar timing model to TOAs (PINT pintempo equivalent)")
    parser.add_argument("parfile")
    parser.add_argument("timfile")
    parser.add_argument("--outfile", default=None,
                        help="write the post-fit par file here")
    parser.add_argument("--fitter", default="auto",
                        choices=["auto", "wls", "gls", "downhill", "sharded",
                                 "hybrid"],
                        help="fitter selection (auto follows the model's "
                             "noise; hybrid = CPU DD stage + accelerator "
                             "GLS solve)")
    parser.add_argument("--maxiter", type=int, default=10)
    parser.add_argument("--allow-tcb", action="store_true",
                        help="auto-convert a TCB par file to TDB")
    parser.add_argument("--log-level", default="INFO")
    parser.add_argument("--plotfile", default=None,
                        help="write a pre/post-fit residual plot (requires "
                             "matplotlib)")
    args = parser.parse_args(argv)
    script_init(args.log_level)

    from pint_tpu.fitting import Fitter, GLSFitter, WLSFitter
    from pint_tpu.models import get_model
    from pint_tpu.residuals import Residuals
    from pint_tpu.toas import get_TOAs

    model = get_model(args.parfile, allow_tcb=args.allow_tcb)
    toas = get_TOAs(args.timfile, ephem=model.ephem)
    print(f"Read {len(toas)} TOAs; model {model.name or args.parfile} with "
          f"{len(model.free_params)} free parameters")

    prefit = Residuals(toas, model)
    print(f"Prefit residuals: wrms = {prefit.rms_weighted_s() * 1e6:.4f} us, "
          f"chi2 = {prefit.chi2:.2f}")

    if args.fitter == "auto":
        fitter = Fitter.auto(toas, model)
    elif args.fitter == "wls":
        fitter = WLSFitter(toas, model)
    elif args.fitter == "gls":
        fitter = GLSFitter(toas, model)
    elif args.fitter == "sharded":
        from pint_tpu.parallel import ShardedGLSFitter, ShardedWLSFitter

        cls = (ShardedGLSFitter if model.has_correlated_errors
               else ShardedWLSFitter)
        fitter = cls(toas, model)
    elif args.fitter == "hybrid":
        from pint_tpu.fitting.hybrid import HybridGLSFitter

        fitter = HybridGLSFitter(toas, model)
    else:
        fitter = Fitter.auto(toas, model, downhill=True)
    fitter.fit_toas(maxiter=args.maxiter)
    print(fitter.get_summary())

    if args.plotfile:
        _plot(prefit, fitter, args.plotfile)
    if args.outfile:
        with open(args.outfile, "w") as f:
            f.write(model.as_parfile())
        print(f"Wrote post-fit model to {args.outfile}")
    return 0


def _plot(prefit, fitter, path: str) -> None:
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except ImportError:  # pragma: no cover - matplotlib is optional
        print("matplotlib not available; skipping plot")
        return
    import numpy as np

    post = fitter.resids
    mjds = np.asarray(prefit.toas.get_mjds())
    fig, axes = plt.subplots(2, 1, sharex=True, figsize=(8, 6))
    for ax, r, title in ((axes[0], prefit, "Pre-fit"), (axes[1], post, "Post-fit")):
        ax.errorbar(mjds, np.asarray(r.time_resids) * 1e6,
                    yerr=np.asarray(r.get_errors_s()) * 1e6, fmt=".", ms=3)
        ax.set_ylabel("residual [us]")
        ax.set_title(title)
    axes[1].set_xlabel("MJD")
    fig.tight_layout()
    fig.savefig(path)
    print(f"Wrote residual plot to {path}")


if __name__ == "__main__":
    raise SystemExit(main())
