"""Leap-second (TAI-UTC) step table.

Replaces astropy's bundled IERS leap-second handling used implicitly by the
reference via ``astropy.time`` (reference: src/pint/pulsar_mjd.py uses UTC
MJDs; src/pint/toa.py converts through TT). Values are the canonical IERS
announcements since 1972; TAI-UTC has been 37 s since 2017-01-01 and no
further leap second is scheduled as of mid-2026.

To update after a future leap second: append (MJD of 00:00 UTC on the
effective date, new TAI-UTC seconds).
"""

# (MJD at which the new offset takes effect, TAI-UTC in seconds from then on)
_TABLE = [
    (41317.0, 10.0),  # 1972-01-01
    (41499.0, 11.0),  # 1972-07-01
    (41683.0, 12.0),  # 1973-01-01
    (42048.0, 13.0),  # 1974-01-01
    (42413.0, 14.0),  # 1975-01-01
    (42778.0, 15.0),  # 1976-01-01
    (43144.0, 16.0),  # 1977-01-01
    (43509.0, 17.0),  # 1978-01-01
    (43874.0, 18.0),  # 1979-01-01
    (44239.0, 19.0),  # 1980-01-01
    (44786.0, 20.0),  # 1981-07-01
    (45151.0, 21.0),  # 1982-07-01
    (45516.0, 22.0),  # 1983-07-01
    (46247.0, 23.0),  # 1985-07-01
    (47161.0, 24.0),  # 1988-01-01
    (47892.0, 25.0),  # 1990-01-01
    (48257.0, 26.0),  # 1991-01-01
    (48804.0, 27.0),  # 1992-07-01
    (49169.0, 28.0),  # 1993-07-01
    (49534.0, 29.0),  # 1994-07-01
    (50083.0, 30.0),  # 1996-01-01
    (50630.0, 31.0),  # 1997-07-01
    (51179.0, 32.0),  # 1999-01-01
    (53736.0, 33.0),  # 2006-01-01
    (54832.0, 34.0),  # 2009-01-01
    (56109.0, 35.0),  # 2012-07-01
    (57204.0, 36.0),  # 2015-07-01
    (57754.0, 37.0),  # 2017-01-01
]

LEAP_MJD = [row[0] for row in _TABLE]
LEAP_TAI_MINUS_UTC = [row[1] for row in _TABLE]
