"""Bundled runtime data tables (leap seconds, TDB series, observatories).

Mirrors the role of the reference's ``src/pint/data/runtime/`` directory
(observatories.json, ecliptic.dat, ...) but shipped as Python modules so
they are importable with zero file IO and fully offline.
"""
