"""Solar-system ephemerides: Earth/Sun posvel relative to the SSB.

Reference equivalent: ``pint.solar_system_ephemerides.objPosVel_wrt_SSB``
(src/pint/solar_system_ephemerides.py), which evaluates JPL DE ephemerides
(Chebyshev polynomial kernels) through jplephem. This machine has no
``.bsp`` kernels and no network (SURVEY.md §2.4), so the framework defines
a *provider interface* with two implementations:

``AnalyticEphemeris``
    Fully offline, jittable Keplerian model: Earth-Moon-barycenter orbit
    from J2000 mean elements with secular rates, geocenter offset from the
    EMB via a two-term lunar theory, and the Sun's barycentric wobble from
    Jupiter/Saturn/Uranus/Neptune Kepler orbits. Positional accuracy is at
    the ~1e-4 AU level (tens of arcsec) versus DE440 — *not* suitable for
    absolute sub-us barycentering against real data, but exactly as good
    as a real ephemeris for self-consistent simulate->fit testing, which
    is the offline test strategy (SURVEY.md §4).

``TabulatedEphemeris``
    Cubic-Hermite interpolation over injected (t, pos, vel) samples — the
    hook through which real DE440 Chebyshev evaluations (precomputed
    elsewhere) enter; O(1) gather per TOA, fully jittable and shardable.

Units: positions in light-seconds, velocities in light-seconds/second
(dimensionless v/c), times TDB MJD (float64 — ephemeris interpolation
needs ~ms time resolution at most, far below f64 noise).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Protocol

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

from pint_tpu.constants import AU_LIGHT_S, MJD_J2000, OBLIQUITY_RAD as EPS0_RAD
from pint_tpu.constants import SECS_PER_DAY as DAY_S



def _rot_ecl_to_eq(xyz_ecl: Array) -> Array:
    """Rotate ecliptic-of-J2000 coords to equatorial (ICRS-aligned) frame."""
    ce, se = np.cos(EPS0_RAD), np.sin(EPS0_RAD)
    x, y, z = xyz_ecl[..., 0], xyz_ecl[..., 1], xyz_ecl[..., 2]
    return jnp.stack([x, ce * y - se * z, se * y + ce * z], axis=-1)


# jitted posvel programs, keyed by (include_sun_wobble, body) — shared
# across every AnalyticEphemeris instance (the model is pure constants).
# LRU-bounded: ad-hoc body-set combinations would otherwise accumulate
# executables without limit in long sessions.
from pint_tpu.utils.cache import LRUCache

_POSVEL_JIT_CACHE = LRUCache(64, name="posvel")
_posvel_cache_get = _POSVEL_JIT_CACHE.get_lru
_posvel_cache_put = _POSVEL_JIT_CACHE.put_lru


@dataclass(frozen=True)
class _KeplerOrbit:
    """Mean J2000 heliocentric elements + linear secular rates (per century)."""

    a_au: float  # semi-major axis
    e0: float
    e_dot: float
    i0_deg: float
    i_dot: float
    L0_deg: float  # mean longitude
    L_dot: float  # deg/century
    peri0_deg: float  # longitude of perihelion
    peri_dot: float
    node0_deg: float  # longitude of ascending node
    node_dot: float
    mass_ratio: float = 0.0  # M_planet / M_sun (for the solar wobble)

    def pos_ecl(self, t_cent: Array) -> Array:
        """Heliocentric ecliptic position [au].

        Velocities are everywhere obtained by jax.jvp of position functions
        (exact derivative incl. secular element rates), never hand-derived —
        this keeps pos/vel consistent to machine precision, which Hermite
        resampling in TabulatedEphemeris relies on.
        """
        deg = jnp.pi / 180.0
        e = self.e0 + self.e_dot * t_cent
        inc = (self.i0_deg + self.i_dot * t_cent) * deg
        L = (self.L0_deg + self.L_dot * t_cent) * deg
        peri = (self.peri0_deg + self.peri_dot * t_cent) * deg
        node = (self.node0_deg + self.node_dot * t_cent) * deg
        M = L - peri
        omega = peri - node

        # Kepler solve, fixed-count Newton iterations (jit-friendly; e<0.1
        # converges quadratically: 4 iterations reach ~1e-15)
        E = M + e * jnp.sin(M)
        for _ in range(4):
            E = E - (E - e * jnp.sin(E) - M) / (1.0 - e * jnp.cos(E))

        cosE, sinE = jnp.cos(E), jnp.sin(E)
        a = self.a_au
        b = a * jnp.sqrt(1.0 - e * e)
        xp = a * (cosE - e)
        yp = b * sinE

        co, so = jnp.cos(omega), jnp.sin(omega)
        cn, sn = jnp.cos(node), jnp.sin(node)
        ci, si = jnp.cos(inc), jnp.sin(inc)
        x1 = co * xp - so * yp
        y1 = so * xp + co * yp
        y2 = ci * y1
        z2 = si * y1
        X = cn * x1 - sn * y2
        Y = sn * x1 + cn * y2
        return jnp.stack([X, Y, z2], axis=-1)


# J2000 mean elements (Standish, Explanatory Supplement tables; documented
# public constants). Angles deg, rates per Julian century.
_EMB = _KeplerOrbit(1.00000261, 0.01671123, -0.00004392, -0.00001531, -0.01294668,
                    100.46457166, 35999.37244981, 102.93768193, 0.32327364,
                    0.0, 0.0)
_JUPITER = _KeplerOrbit(5.20288700, 0.04838624, -0.00013253, 1.30439695, -0.00183714,
                        34.39644051, 3034.74612775, 14.72847983, 0.21252668,
                        100.47390909, 0.20469106, mass_ratio=1.0 / 1047.348644)
_SATURN = _KeplerOrbit(9.53667594, 0.05386179, -0.00050991, 2.48599187, 0.00193609,
                       49.95424423, 1222.49362201, 92.59887831, -0.41897216,
                       113.66242448, -0.28867794, mass_ratio=1.0 / 3497.9018)
_URANUS = _KeplerOrbit(19.18916464, 0.04725744, -0.00004397, 0.77263783, -0.00242939,
                       313.23810451, 428.48202785, 170.95427630, 0.40805281,
                       74.01692503, 0.04240589, mass_ratio=1.0 / 22902.98)
_NEPTUNE = _KeplerOrbit(30.06992276, 0.00859048, 0.00005105, 1.77004347, 0.00035372,
                        -55.12002969, 218.45945325, 44.96476227, -0.32241464,
                        131.78422574, -0.00508664, mass_ratio=1.0 / 19412.26)
_VENUS = _KeplerOrbit(0.72333566, 0.00677672, -0.00004107, 3.39467605, -0.00078890,
                      181.97909950, 58517.81538729, 131.60246718, 0.00268329,
                      76.67984255, -0.27769418, mass_ratio=1.0 / 408523.719)
_MARS = _KeplerOrbit(1.52371034, 0.09339410, 0.00007882, 1.84969142, -0.00813131,
                     -4.55343205, 19140.30268499, -23.94362959, 0.44441088,
                     49.55953891, -0.29257343, mass_ratio=1.0 / 3098703.59)
_MERCURY = _KeplerOrbit(0.38709927, 0.20563593, 0.00001906, 7.00497902, -0.00594749,
                        252.25032350, 149472.67411175, 77.45779628, 0.16047689,
                        48.33076593, -0.12534081, mass_ratio=1.0 / 6023600.0)

_WOBBLE_PLANETS = (_JUPITER, _SATURN, _URANUS, _NEPTUNE, _VENUS, _MARS, _MERCURY)

# Earth-Moon mass ratio -> geocenter offset from EMB toward the Moon
_EARTH_MOON_MASS_RATIO = 81.30056907419062
_MOON_DIST_AU = 384400.0 / 149597870.7


class Ephemeris(Protocol):
    """posvel provider: TDB MJD (f64 array) -> dict of body posvels."""

    def earth_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        """Geocenter position [lt-s] and velocity [lt-s/s] wrt SSB."""
        ...

    def sun_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        ...

    def planet_posvel_ssb(self, name: str, t_tdb_mjd: Array) -> tuple[Array, Array]:
        ...


def _moon_geocentric_ecl_au(t_cent: Array) -> Array:
    """Low-order lunar position (geocentric ecliptic, au). ~0.5% accuracy.

    Principal-term Brown theory: longitude terms (6.289 sin M') etc.
    Good to ~0.2 deg — enough for the EMB->geocenter correction (whose
    total effect on the Roemer delay is <16 ms; 0.5% error -> ~80 us,
    absorbed by the self-consistency test strategy).
    """
    deg = jnp.pi / 180.0
    T = t_cent
    Lp = (218.3164477 + 481267.88123421 * T) * deg  # mean longitude
    D = (297.8501921 + 445267.1114034 * T) * deg  # elongation
    M = (357.5291092 + 35999.0502909 * T) * deg  # Sun anomaly
    Mp = (134.9633964 + 477198.8675055 * T) * deg  # Moon anomaly
    F = (93.2720950 + 483202.0175233 * T) * deg  # argument of latitude

    lon = Lp + deg * (
        6.288774 * jnp.sin(Mp)
        + 1.274027 * jnp.sin(2 * D - Mp)
        + 0.658314 * jnp.sin(2 * D)
        + 0.213618 * jnp.sin(2 * Mp)
        - 0.185116 * jnp.sin(M)
        - 0.114332 * jnp.sin(2 * F)
    )
    lat = deg * (
        5.128122 * jnp.sin(F)
        + 0.280602 * jnp.sin(Mp + F)
        + 0.277693 * jnp.sin(Mp - F)
    )
    r = _MOON_DIST_AU * (1.0 - 0.0549 * jnp.cos(Mp))
    cl, sl = jnp.cos(lat), jnp.sin(lat)
    return jnp.stack([r * cl * jnp.cos(lon), r * cl * jnp.sin(lon), r * sl], axis=-1)


@dataclass(frozen=True)
class AnalyticEphemeris:
    """Offline Keplerian ephemeris (see module docstring). Jittable."""

    include_sun_wobble: bool = True
    name: str = "builtin_analytic"

    def _t_cent(self, t_tdb_mjd: Array) -> Array:
        return (jnp.asarray(t_tdb_mjd, jnp.float64) - MJD_J2000) / 36525.0

    # --- position-only models in ecliptic au, as functions of T (centuries);
    # --- velocities come from jax.jvp of these (see _posvel).

    def _sun_pos_ecl(self, T: Array) -> Array:
        pos = jnp.zeros(jnp.shape(T) + (3,))
        if self.include_sun_wobble:
            for body in _WOBBLE_PLANETS:
                f = body.mass_ratio / (1.0 + body.mass_ratio)
                pos = pos - f * body.pos_ecl(T)
        return pos

    def _earth_pos_ecl(self, T: Array) -> Array:
        f = 1.0 / (1.0 + _EARTH_MOON_MASS_RATIO)
        return _EMB.pos_ecl(T) - f * _moon_geocentric_ecl_au(T) + self._sun_pos_ecl(T)

    def _body_pos_ecl(self, name: str, T: Array) -> Array:
        orbits = {
            "mercury": _MERCURY, "venus": _VENUS, "mars": _MARS,
            "jupiter": _JUPITER, "saturn": _SATURN, "uranus": _URANUS,
            "neptune": _NEPTUNE, "emb": _EMB,
        }
        if name == "earth":
            return self._earth_pos_ecl(T)
        if name == "sun":
            return self._sun_pos_ecl(T)
        if name == "moon":
            return self._earth_pos_ecl(T) + _moon_geocentric_ecl_au(T)
        return orbits[name].pos_ecl(T) + self._sun_pos_ecl(T)

    def _posvel(self, posfn, t_tdb_mjd: Array, key: str) -> tuple[Array, Array]:
        """(pos [lt-s], vel [lt-s/s]) via exact jvp of the position model.

        Jitted through a module-level cache keyed by (wobble flag, body):
        the Kepler chains are ~50 eager jax ops per body (the sun wobble
        alone sums four), which made every un-jitted call cost ~0.4 s of
        op dispatch — the dominant cost of building a TOA table.  The
        ephemeris is pure and instance-independent given the cache key,
        so one compiled program serves every instance and dataset.
        """
        cache_key = (self.include_sun_wobble, key)
        fn = _posvel_cache_get(cache_key)
        if fn is None:
            def raw(t):
                T = self._t_cent(t)
                p, dp_dcent = jax.jvp(posfn, (T,), (jnp.ones_like(T),))
                pos = _rot_ecl_to_eq(p) * AU_LIGHT_S
                vel = _rot_ecl_to_eq(dp_dcent) * (AU_LIGHT_S / (36525.0 * DAY_S))
                return pos, vel

            fn = jax.jit(raw)
            _posvel_cache_put(cache_key, fn)
        return fn(t_tdb_mjd)

    def earth_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._posvel(self._earth_pos_ecl, t_tdb_mjd, "earth")

    def sun_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._posvel(self._sun_pos_ecl, t_tdb_mjd, "sun")

    def planet_posvel_ssb(self, name: str, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._posvel(lambda T: self._body_pos_ecl(name.lower(), T),
                            t_tdb_mjd, f"planet:{name.lower()}")

    def bodies_posvel_ssb(self, t_tdb_mjd: Array, names: tuple
                          ) -> dict[str, tuple[Array, Array]]:
        """All requested bodies in ONE jitted program (one jvp).

        The per-body entry points each re-trace the solar-wobble chain
        (every heliocentric position adds the sun's barycentric offset),
        so building a TOA table used to cost ~9 separate traces per
        input shape.  Here the sun/earth/moon subexpressions are traced
        once and every body reuses them — one trace, one executable,
        for the whole (n_bodies, n, 3) stack.
        """
        names = tuple(str(n).lower() for n in names)
        cache_key = (self.include_sun_wobble, "bodies", names)
        fn = _posvel_cache_get(cache_key)
        if fn is None:
            orbits = {
                "mercury": _MERCURY, "venus": _VENUS, "mars": _MARS,
                "jupiter": _JUPITER, "saturn": _SATURN, "uranus": _URANUS,
                "neptune": _NEPTUNE, "emb": _EMB,
            }

            def raw(t):
                T = self._t_cent(t)

                def allpos(Tc):
                    sun = self._sun_pos_ecl(Tc)
                    moon_geo = _moon_geocentric_ecl_au(Tc)
                    f = 1.0 / (1.0 + _EARTH_MOON_MASS_RATIO)
                    earth = _EMB.pos_ecl(Tc) - f * moon_geo + sun
                    out = []
                    for nm in names:
                        if nm == "sun":
                            out.append(sun)
                        elif nm == "earth":
                            out.append(earth)
                        elif nm == "moon":
                            out.append(earth + moon_geo)
                        else:
                            out.append(orbits[nm].pos_ecl(Tc) + sun)
                    return jnp.stack(out)

                p, dp = jax.jvp(allpos, (T,), (jnp.ones_like(T),))
                pos = _rot_ecl_to_eq(p) * AU_LIGHT_S
                vel = _rot_ecl_to_eq(dp) * (AU_LIGHT_S / (36525.0 * DAY_S))
                return pos, vel

            fn = jax.jit(raw)
            _posvel_cache_put(cache_key, fn)
        pos, vel = fn(t_tdb_mjd)
        return {nm: (pos[i], vel[i]) for i, nm in enumerate(names)}


@dataclass(frozen=True)
class TabulatedEphemeris:
    """Cubic-Hermite interpolation over injected posvel samples.

    The injection point for real JPL DE kernels: precompute (t, pos, vel)
    for each body on a uniform grid (e.g. 0.25-day spacing) with any
    external tool, and timing evaluation here is jittable + shardable.
    Hermite interpolation with exact velocities is ~O(h^4): 0.25-day
    spacing on Earth's orbit gives sub-meter (~ns) accuracy.
    """

    t0: float
    dt_days: float
    tables: dict  # name -> (pos[N,3], vel[N,3]) in lt-s, lt-s/s
    name: str = "tabulated"

    def _interp(self, name: str, t: Array) -> tuple[Array, Array]:
        pos, vel = self.tables[name]
        pos = jnp.asarray(pos)
        vel = jnp.asarray(vel)
        x = (jnp.asarray(t, jnp.float64) - self.t0) / self.dt_days
        i = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, pos.shape[0] - 2)
        s = (x - i)[..., None]
        h = self.dt_days * DAY_S  # step in seconds (vel is per second)
        p0, p1 = pos[i], pos[i + 1]
        v0, v1 = vel[i] * h, vel[i + 1] * h
        h00 = (1 + 2 * s) * (1 - s) ** 2
        h10 = s * (1 - s) ** 2
        h01 = s * s * (3 - 2 * s)
        h11 = s * s * (s - 1)
        p = h00 * p0 + h10 * v0 + h01 * p1 + h11 * v1
        dh00 = 6 * s * (s - 1)
        dh10 = (1 - s) * (1 - 3 * s)
        dh01 = -6 * s * (s - 1)
        dh11 = s * (3 * s - 2)
        v = (dh00 * p0 + dh10 * v0 + dh01 * p1 + dh11 * v1) / h
        return p, v

    def earth_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._interp("earth", t_tdb_mjd)

    def sun_posvel_ssb(self, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._interp("sun", t_tdb_mjd)

    def planet_posvel_ssb(self, name: str, t_tdb_mjd: Array) -> tuple[Array, Array]:
        return self._interp(name.lower(), t_tdb_mjd)


# interned AnalyticEphemeris instances: every get_TOAs call resolves an
# ephemeris, and downstream jit caches key on the instance — a fresh
# object per call would recompile the astrometric pipeline every build
_ANALYTIC_INSTANCES: dict = {}
_SPK_INSTANCES: dict = {}


def _analytic(**kwargs) -> "AnalyticEphemeris":
    key = tuple(sorted(kwargs.items()))
    inst = _ANALYTIC_INSTANCES.get(key)
    if inst is None:
        inst = _ANALYTIC_INSTANCES[key] = AnalyticEphemeris(**kwargs)
    return inst


def get_ephemeris(name: str = "builtin_analytic", **kwargs) -> Ephemeris:
    """Ephemeris factory. DE names fall back to the analytic model offline.

    Mirrors the reference's ephemeris-selection-by-name
    (src/pint/solar_system_ephemerides.py), where 'DE421'/'DE440' pick
    .bsp kernels. Without kernels on disk we log-and-fall-back rather
    than fail, so par files naming an ephemeris still load. Analytic
    instances are interned so repeated loads share jitted programs.
    """
    if name.lower() in ("builtin_analytic", "analytic", ""):
        return _analytic(**kwargs)
    if name.lower().startswith("de"):
        import logging
        import os

        from pint_tpu.config import get_config

        cfg = get_config()
        # real kernel if available: <config.ephem_dir>/<name>.bsp or ./<name>.bsp
        for d in (cfg.ephem_dir, "."):
            if not d:
                continue
            path = os.path.join(d, f"{name.lower()}.bsp")
            if os.path.isfile(path):
                from pint_tpu.io.bsp import SPKEphemeris

                # intern per resolved path (like _analytic): repeated
                # loads must share one instance so the TOA-build
                # pipeline cache (keyed by instance) reuses its
                # compiled program instead of recompiling + re-holding
                # a fresh copy of the Chebyshev tables per call
                key = ("spk", os.path.abspath(path))
                inst = _SPK_INSTANCES.get(key)
                if inst is None:
                    inst = SPKEphemeris(path, name=name.upper())
                    _SPK_INSTANCES[key] = inst
                return inst
        if cfg.strict_ephem:
            raise FileNotFoundError(
                f"JPL ephemeris {name} requested but no {name.lower()}.bsp "
                "found (PINT_TPU_EPHEM_DIR) and PINT_TPU_STRICT_EPHEM is set; "
                "refusing the arcsecond-level analytic fallback")
        logging.getLogger(__name__).warning(
            "JPL ephemeris %s not available offline; using builtin analytic "
            "ephemeris (set PINT_TPU_EPHEM_DIR to provide %s.bsp, or "
            "PINT_TPU_STRICT_EPHEM=1 to make this an error)",
            name, name.lower(),
        )
        return _analytic(**kwargs)
    raise ValueError(f"unknown ephemeris {name!r}")
