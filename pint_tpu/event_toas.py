"""Photon-event loading: FITS event files -> TOAs (+ photon weights).

Reference equivalents: ``pint.event_toas`` (load_event_TOAs and the
mission table, src/pint/event_toas.py) and ``pint.fermi_toas``
(load_Fermi_TOAs with photon weights, src/pint/fermi_toas.py). The
astropy.io.fits dependency is replaced by the pure-numpy reader in
:mod:`pint_tpu.io.fits`.

Supported event timestamps:

* **barycentered** (``TIMESYS='TDB'`` / ``TIMEREF='SOLARSYSTEM'``):
  TOAs are built at the solar-system barycenter ("@"),
* **geocentered** (``TIMEREF='GEOCENTRIC'``, TT times): TOAs are built
  at the geocenter after a TT->UTC conversion so the standard pipeline
  reproduces the event TT exactly, or
* **spacecraft-local** (``TIMEREF='LOCAL'``, TT times) with an orbit
  file (``orbfile=`` / photonphase ``--orbfile``): per-event GCRS
  positions interpolated from the orbit data feed the TOA pipeline
  (reference: pint.observatory.satellite_obs).

Mission defaults mirror the reference's table: the FITS time columns,
MJDREF handling (NICER/RXTE split MJDREFI/MJDREFF; Fermi single
MJDREF), and the energy/weight columns.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from pint_tpu.io.fits import read_fits
from pint_tpu.constants import SECS_PER_DAY
from pint_tpu.ops import dd, timescales as ts
from pint_tpu.toas import TOAs, build_TOAs_from_arrays

# mission -> (extension name, energy column, column-unit -> keV multiplier)
MISSIONS = {
    "fermi": ("EVENTS", "ENERGY", 1e3),  # FT1 ENERGY is MeV
    "nicer": ("EVENTS", "PI", 0.01),  # PI channel = 10 eV
    "nustar": ("EVENTS", "PI", 0.04),
    "rxte": ("XTE_SE", "PHA", 1.0),
    "xmm": ("EVENTS", "PI", 1e-3),  # PI channel = 1 eV
    "generic": ("EVENTS", "PI", 1.0),
}


def _mjdref_days(hdr: dict, primary: dict) -> tuple[float, float]:
    """(int days, frac days) of the mission epoch, from either header."""
    for h in (hdr, primary):
        if "MJDREFI" in h:
            return float(h["MJDREFI"]), float(h.get("MJDREFF", 0.0))
        if "MJDREF" in h:
            r = float(h["MJDREF"])
            return float(np.floor(r)), r - np.floor(r)
    raise ValueError("event file has no MJDREF/MJDREFI keyword")


def _tt_to_utc(mjd_tt: dd.DD) -> dd.DD:
    """Invert utc_to_tt (fixed-point on the leap-second lookup)."""
    utc = mjd_tt
    for _ in range(3):
        off = ts.tai_minus_utc(jnp.asarray(utc.hi)) + 32.184
        utc = dd.sub(mjd_tt, off / SECS_PER_DAY)
    return utc


def load_orbit_file(orbfile: str) -> tuple[np.ndarray, np.ndarray]:
    """(met_s, gcrs_pos_m (n,3)) from a spacecraft orbit FITS file.

    Reference: pint.observatory.satellite_obs orbit ingestion. Supported
    shapes: NICER/NuSTAR-style ``ORBIT`` extensions (TIME + POSITION
    vector or X/Y/Z scalars; meters or km via TUNIT/POSUNIT) and
    Fermi FT2 ``SC_DATA`` (START + SC_POSITION, meters). Positions are
    J2000 ECI, treated as GCRS.
    """
    f = read_fits(orbfile)
    tab = None
    for name in ("ORBIT", "SC_DATA", "PREFILTER"):
        try:
            tab = f.table(name)
            break
        except KeyError:
            continue
    if tab is None:
        tab = f.tables[0]
    tcol = "START" if "START" in tab else "TIME"
    met = np.asarray(tab[tcol], dtype=np.float64)
    unit_scale = 1.0
    unit = str(tab.header.get("POSUNIT", "")).strip().lower()
    for j in range(1, int(tab.header.get("TFIELDS", 0)) + 1):
        if str(tab.header.get(f"TTYPE{j}", "")).strip().upper() in (
                "POSITION", "SC_POSITION", "X", "Y", "Z"):
            unit = unit or str(tab.header.get(f"TUNIT{j}", "")).strip().lower()
    if unit in ("km", "kilometers"):
        unit_scale = 1e3
    if "POSITION" in tab:
        pos = np.asarray(tab["POSITION"], dtype=np.float64)
    elif "SC_POSITION" in tab:
        pos = np.asarray(tab["SC_POSITION"], dtype=np.float64)
    elif "X" in tab:
        pos = np.stack([np.asarray(tab[c], dtype=np.float64)
                        for c in ("X", "Y", "Z")], axis=1)
    else:
        raise ValueError(
            f"orbit file has no POSITION/SC_POSITION/X,Y,Z columns "
            f"(columns: {sorted(tab.columns)})")
    order = np.argsort(met)
    pos = pos[order] * unit_scale
    r = np.linalg.norm(pos, axis=1)
    # sanity: geocentric orbit radii live between Earth's surface and
    # ~lunar distance; anything else means wrong units (e.g. km data
    # with no TUNIT read as meters) — fail loudly, not 1000x off
    if np.any(r < 6.2e6) or np.any(r > 5e8):
        raise ValueError(
            f"orbit radii [{r.min():.3g}, {r.max():.3g}] m are outside "
            "the plausible geocentric range [6.2e6, 5e8] m — check the "
            "orbit file's position units (TUNIT/POSUNIT)")
    return met[order], pos


def _interp_orbit(met_s: np.ndarray, orbit: tuple[np.ndarray, np.ndarray]
                  ) -> np.ndarray:
    """Linear per-axis interpolation of orbit positions at event METs."""
    t, pos = orbit
    if np.any(met_s < t[0] - 1.0) or np.any(met_s > t[-1] + 1.0):
        raise ValueError(
            f"event times [{met_s.min():.1f}, {met_s.max():.1f}] extend "
            f"outside the orbit file span [{t[0]:.1f}, {t[-1]:.1f}]")
    return np.stack([np.interp(met_s, t, pos[:, k]) for k in range(3)],
                    axis=1)


def load_event_TOAs(eventfile: str, mission: str = "generic", *,
                    weight_column: str | None = None,
                    energy_range_kev: tuple[float, float] | None = None,
                    orbfile: str | None = None,
                    ephem: str = "builtin_analytic",
                    planets: bool = True, error_us: float = 1.0) -> TOAs:
    """Load a FITS photon event list as a TOAs table.

    Photon weights (``weight_column``, e.g. Fermi's 'WEIGHT' or
    'MODEL_WEIGHT') are carried on ``toas.aux_masks['photon_weight']``
    as a traced (n,) array — the unbinned template likelihood consumes
    them on-device (the reference stashes them in per-TOA flag dicts).

    ``orbfile`` enables unbarycentered spacecraft events
    (``TIMEREF='LOCAL'``): per-event GCRS positions are interpolated
    from the orbit file and injected into the TOA pipeline, so the
    Roemer/Einstein terms see the true orbiting-observatory position
    (reference: photonphase --orbfile / satellite_obs).
    """
    mission = mission.lower()
    if mission not in MISSIONS:
        raise ValueError(f"unknown mission {mission!r}; have {sorted(MISSIONS)}")
    extname, energy_col, _scale = MISSIONS[mission]
    f = read_fits(eventfile)
    try:
        tab = f.table(extname)
    except KeyError:
        tab = f.tables[0]
    hdr = tab.header

    timesys = str(hdr.get("TIMESYS", f.primary_header.get("TIMESYS", ""))
                  ).strip().upper()
    timeref = str(hdr.get("TIMEREF", f.primary_header.get("TIMEREF", ""))
                  ).strip().upper()
    barycentered = timesys == "TDB" or timeref in ("SOLARSYSTEM", "BARYCENTER")
    geocentered = not barycentered and timeref in ("GEOCENTRIC", "GEOCENTER")
    local = not barycentered and not geocentered
    if local and orbfile is None:
        raise ValueError(
            f"events are TIMESYS={timesys!r}/TIMEREF={timeref!r}; "
            "unbarycentered spacecraft events need an orbit file "
            "(orbfile=...), matching the reference's photonphase "
            "--orbfile")
    if orbfile is not None and not local:
        raise ValueError(
            "orbfile given but events are already "
            + ("barycentered" if barycentered else "geocentered"))

    met = np.asarray(tab["TIME"], dtype=np.float64)
    keep = np.ones(met.size, dtype=bool)
    if energy_range_kev is not None:
        if energy_col not in tab:
            raise ValueError(
                f"energy cut requested but the {mission} energy column "
                f"{energy_col!r} is not in the event table "
                f"(columns: {sorted(tab.columns)})")
        e = np.asarray(tab[energy_col], dtype=np.float64) * _scale
        keep &= (e >= energy_range_kev[0]) & (e <= energy_range_kev[1])
    weights = None
    if weight_column is not None:
        weights = np.asarray(tab[weight_column], dtype=np.float64)[keep]
    met = met[keep]

    refi, reff = _mjdref_days(hdr, f.primary_header)
    timezero = float(hdr.get("TIMEZERO", 0.0))
    # exact split: integer epoch days carried in hi; MET seconds divided
    # in DD (the f64 quotient alone would cost ~0.3 ns at MET ~ 3e8 s)
    met_days = dd.div(dd.from_f64(jnp.asarray(met + timezero)), SECS_PER_DAY)
    mjd = dd.add(dd.add(dd.from_f64(jnp.full(met.shape, refi)), reff),
                 met_days)

    gcrs_pos_m = None
    if barycentered:
        obs_names = ("barycenter",)
    elif geocentered:
        obs_names = ("geocenter",)
        mjd = _tt_to_utc(mjd)  # pipeline re-derives the exact TT
    else:
        obs_names = ("spacecraft",)
        gcrs_pos_m = _interp_orbit(met + timezero, load_orbit_file(orbfile))
        mjd = _tt_to_utc(mjd)

    toas = build_TOAs_from_arrays(
        mjd,
        freq_mhz=np.full(met.shape, np.inf),
        error_us=np.full(met.shape, error_us),
        obs_names=obs_names,
        eph=ephem,
        planets=planets,
        include_clock=False,
        gcrs_pos_m=gcrs_pos_m,
    )
    if weights is not None:
        import dataclasses

        toas = dataclasses.replace(
            toas, aux_masks=dict(toas.aux_masks,
                                 photon_weight=jnp.asarray(weights)))
    return toas


def load_fermi_TOAs(ft1file: str, *, weightcolumn: str | None = None,
                    **kw) -> TOAs:
    """Fermi-LAT FT1 loader (reference: pint.fermi_toas.load_Fermi_TOAs)."""
    return load_event_TOAs(ft1file, "fermi", weight_column=weightcolumn, **kw)


def load_nicer_TOAs(eventfile: str, **kw) -> TOAs:
    return load_event_TOAs(eventfile, "nicer", **kw)


def get_photon_weights(toas: TOAs) -> np.ndarray | None:
    w = toas.aux_masks.get("photon_weight")
    return None if w is None else np.asarray(w)
