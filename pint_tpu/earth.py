"""Earth orientation: ITRF observatory coordinates -> GCRS (celestial) frame.

Reference equivalent: astropy's ITRS->GCRS transformation used by
``pint.observatory.topo_obs.TopoObs.posvel`` (src/pint/observatory/topo_obs.py)
via ERFA. Offline reimplementation with documented truncations:

* Earth rotation angle (ERA, IAU 2000) — exact linear-in-UT1 formula.
* Equation of the origins approximated through GAST built from GMST
  (IAU 1982-style polynomial) + principal nutation term.
* Precession: IAU 1976 zeta/z/theta polynomials (arcsec-level).
* Nutation: leading 18.6-yr + semiannual terms (~0.1 arcsec residual).
* Polar motion + UT1-UTC: zero by default (no IERS data offline), both
  injectable through :class:`EOPData`. 0.9 s of neglected UT1-UTC moves
  an equatorial observatory ~420 m -> <=1.4 us of topocentric Roemer
  error; irrelevant for self-consistent simulate->fit testing.

Accuracy of the full chain vs ERFA: ~0.1 arcsec orientation -> tens of ns
in the topocentric delay. All functions are jittable float64.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

Array = jax.Array

from pint_tpu.constants import MJD_J2000  # noqa: E402
ARCSEC = np.pi / (180.0 * 3600.0)


@dataclass(frozen=True)
class EOPData:
    """Earth-orientation parameters; defaults = zero (offline)."""

    ut1_minus_utc_s: float = 0.0
    xp_arcsec: float = 0.0
    yp_arcsec: float = 0.0


def era_rad(mjd_ut1: Array) -> Array:
    """Earth rotation angle (IAU 2000): 2*pi*(0.7790572732640 + 1.00273781191135448*Tu)."""
    tu = jnp.asarray(mjd_ut1, jnp.float64) - MJD_J2000
    frac = 0.7790572732640 + 1.00273781191135448 * tu
    return 2.0 * jnp.pi * (frac - jnp.floor(frac))


def gmst_rad(mjd_ut1: Array) -> Array:
    """Greenwich mean sidereal time (IAU 1982 polynomial, radians)."""
    t = (jnp.asarray(mjd_ut1, jnp.float64) - MJD_J2000) / 36525.0
    gmst_s = (
        67310.54841
        + (876600.0 * 3600.0 + 8640184.812866) * t
        + 0.093104 * t * t
        - 6.2e-6 * t**3
    )
    return (gmst_s % 86400.0) * (2.0 * jnp.pi / 86400.0)


def nutation_angles(t_cent: Array) -> tuple[Array, Array]:
    """Principal nutation terms: (dpsi, deps) in radians (~0.1'' residual)."""
    deg = jnp.pi / 180.0
    om = (125.04452 - 1934.136261 * t_cent) * deg  # lunar node
    ls = (280.4665 + 36000.7698 * t_cent) * deg  # mean sun longitude
    lm = (218.3165 + 481267.8813 * t_cent) * deg  # mean moon longitude
    dpsi = (-17.20 * jnp.sin(om) - 1.32 * jnp.sin(2 * ls)
            - 0.23 * jnp.sin(2 * lm) + 0.21 * jnp.sin(2 * om)) * ARCSEC
    deps = (9.20 * jnp.cos(om) + 0.57 * jnp.cos(2 * ls)
            + 0.10 * jnp.cos(2 * lm) - 0.09 * jnp.cos(2 * om)) * ARCSEC
    return dpsi, deps


def mean_obliquity(t_cent: Array) -> Array:
    return (84381.448 - 46.8150 * t_cent - 5.9e-4 * t_cent**2) * ARCSEC


def _rx(angle: Array) -> Array:
    c, s = jnp.cos(angle), jnp.sin(angle)
    z, o = jnp.zeros_like(c), jnp.ones_like(c)
    return jnp.stack([
        jnp.stack([o, z, z], -1),
        jnp.stack([z, c, s], -1),
        jnp.stack([z, -s, c], -1),
    ], -2)


def _rz(angle: Array) -> Array:
    c, s = jnp.cos(angle), jnp.sin(angle)
    z, o = jnp.zeros_like(c), jnp.ones_like(c)
    return jnp.stack([
        jnp.stack([c, s, z], -1),
        jnp.stack([-s, c, z], -1),
        jnp.stack([z, z, o], -1),
    ], -2)


def precession_matrix(t_cent: Array) -> Array:
    """IAU 1976 precession: mean-of-date <- J2000 rotation."""
    zeta = (2306.2181 * t_cent + 0.30188 * t_cent**2 + 0.017998 * t_cent**3) * ARCSEC
    z = (2306.2181 * t_cent + 1.09468 * t_cent**2 + 0.018203 * t_cent**3) * ARCSEC
    theta = (2004.3109 * t_cent - 0.42665 * t_cent**2 - 0.041833 * t_cent**3) * ARCSEC
    # P = Rz(-z) Ry(theta) Rz(-zeta); build Ry inline
    c, s = jnp.cos(theta), jnp.sin(theta)
    zz, o = jnp.zeros_like(c), jnp.ones_like(c)
    ry = jnp.stack([
        jnp.stack([c, zz, -s], -1),
        jnp.stack([zz, o, zz], -1),
        jnp.stack([s, zz, c], -1),
    ], -2)
    return _rz(-z) @ ry @ _rz(-zeta)


def nutation_matrix(t_cent: Array) -> Array:
    dpsi, deps = nutation_angles(t_cent)
    eps = mean_obliquity(t_cent)
    return _rx(-(eps + deps)) @ _rz(-dpsi) @ _rx(eps)


def itrf_to_gcrs_posvel(
    itrf_xyz_m: Array,
    mjd_utc: Array,
    eop: Optional[EOPData] = None,
) -> tuple[Array, Array]:
    """Observatory ITRF position -> GCRS position [m] and velocity [m/s].

    mjd_utc: (...,) float64; itrf_xyz_m broadcastable (..., 3).
    """
    eop = eop or EOPData()
    mjd_ut1 = jnp.asarray(mjd_utc, jnp.float64) + eop.ut1_minus_utc_s / 86400.0
    t = (mjd_ut1 - MJD_J2000) / 36525.0

    dpsi, _ = nutation_angles(t)
    eps = mean_obliquity(t)
    gast = gmst_rad(mjd_ut1) + dpsi * jnp.cos(eps)

    # polar motion (tiny): W = Rx(-yp) Ry(-xp)
    xp = eop.xp_arcsec * ARCSEC
    yp = eop.yp_arcsec * ARCSEC
    r = jnp.broadcast_to(jnp.asarray(itrf_xyz_m, jnp.float64), jnp.shape(t) + (3,))
    if xp != 0.0 or yp != 0.0:
        cy, sy = np.cos(yp), np.sin(yp)
        cx, sx = np.cos(xp), np.sin(xp)
        wm = jnp.asarray(
            [[cx, 0.0, sx], [sx * sy, cy, -cx * sy], [-sx * cy, sy, cx * cy]]
        )
        r = jnp.einsum("ij,...j->...i", wm, r)

    # spin: TIRS -> true-of-date via Rz(-GAST)
    cg, sg = jnp.cos(gast), jnp.sin(gast)
    x, y, z = r[..., 0], r[..., 1], r[..., 2]
    x_tod = cg * x - sg * y
    y_tod = sg * x + cg * y
    r_tod = jnp.stack([x_tod, y_tod, z], -1)
    # velocity = omega x r (Earth spin rate in rad/s of UT1)
    omega = 2.0 * jnp.pi * 1.00273781191135448 / 86400.0
    v_tod = jnp.stack([-omega * y_tod, omega * x_tod, jnp.zeros_like(z)], -1)

    # true-of-date -> J2000/GCRS: transpose(N P)
    np_mat = nutation_matrix(t) @ precession_matrix(t)
    np_t = jnp.swapaxes(np_mat, -1, -2)
    r_gcrs = jnp.einsum("...ij,...j->...i", np_t, r_tod)
    v_gcrs = jnp.einsum("...ij,...j->...i", np_t, v_tod)
    return r_gcrs, v_gcrs
