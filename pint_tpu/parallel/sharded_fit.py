"""TOA-axis-sharded WLS fitting: one XLA program over a device mesh.

The "long-context" path (SURVEY.md §5): the TOA table is the sequence.
Every (n,)-shaped leaf is sharded over the mesh's "toa" axis; the fit
step (residuals -> jacfwd design matrix -> Gram solve,
pint_tpu.fitting.step) then partitions automatically — per-device
design-matrix blocks, a psum for the (p, p) Gram matrix over ICI, and a
replicated Cholesky. No hand-written collectives.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.step import make_wls_step
from pint_tpu.parallel.mesh import (make_mesh, pad_to_multiple, replicate,
                                    shard_toas)
from pint_tpu.toas import Flags, TOAs

# padded TOAs carry this uncertainty -> weight ~1e-24 of a real TOA
PAD_ERROR_US = 1e12


def pad_toas(toas: TOAs, n_target: int) -> TOAs:
    """Extend a TOA table to `n_target` rows with zero-weight padding.

    Padding rows replicate the last TOA but with enormous uncertainty, so
    every weighted reduction (mean phase, Gram matrix, chi2) is unchanged
    to machine precision while shapes stay static for XLA.
    """
    n = len(toas)
    if n_target < n:
        raise ValueError(f"n_target {n_target} < ntoas {n}")
    if n_target == n:
        return toas
    k = n_target - n

    def pad_leaf(x):
        x = jnp.asarray(x)
        reps = jnp.repeat(x[-1:], k, axis=0)
        return jnp.concatenate([x, reps], axis=0)

    err = pad_leaf(toas.error_us).at[n:].set(PAD_ERROR_US)
    padded = jax.tree.map(pad_leaf, toas)
    return dataclasses.replace(
        padded,
        error_us=err,
        flags=Flags(tuple(toas.flags) + tuple(dict(toas.flags[-1]) for _ in range(k))),
    )


def sharded_fit(toas, model, *, mesh=None, maxiter: int = 2):
    """Run `maxiter` sharded WLS iterations; returns (deltas, info).

    Host-side wrapper: pads the table to the mesh's TOA-shard multiple,
    places shardings, jits the step once, and iterates.
    """
    mesh = mesh or make_mesh()
    n_shards = mesh.shape["toa"]
    padded = pad_toas(toas, pad_to_multiple(len(toas), n_shards))
    toas_sh = shard_toas(padded, mesh)
    step = jax.jit(make_wls_step(model))
    base = replicate(model.base_dd(), mesh)
    deltas = replicate(model.zero_deltas(), mesh)
    info = None
    with mesh:
        for _ in range(max(1, maxiter)):
            deltas, info = step(base, deltas, toas_sh)
    return deltas, info


class ShardedWLSFitter:
    """Fitter-API wrapper around :func:`sharded_fit`.

    Mirrors ``WLSFitter`` results (updated params, uncertainties, chi2)
    while the compute runs TOA-sharded over the mesh.
    """

    def __init__(self, toas, model, mesh=None):
        self.toas = toas
        self.model = model
        self.mesh = mesh or make_mesh()
        self.converged = False

    def fit_toas(self, maxiter: int = 2) -> float:
        deltas, info = sharded_fit(self.toas, self.model, mesh=self.mesh,
                                   maxiter=maxiter)
        errors = info["errors"]
        for name, d in deltas.items():
            p = self.model[name]
            p.add_delta(float(np.asarray(d)))
            p.uncertainty = float(np.asarray(errors[name]))
        self.converged = True
        return float(np.asarray(info["chi2"]))
