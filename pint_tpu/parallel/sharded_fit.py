"""TOA-axis-sharded WLS/GLS fitting: one XLA program over a device mesh.

The "long-context" path (SURVEY.md §5): the TOA table is the sequence.
Every (n,)-shaped leaf is sharded over the mesh's "toa" axis; the fit
step (residuals -> jacfwd design matrix -> Gram solve,
pint_tpu.fitting.step / gls_step) then partitions automatically —
per-device design-matrix and Fourier-basis blocks, psums for the small
Gram matrices over ICI, segment-sum scatter-adds for the ECORR epoch
blocks, and a replicated Cholesky. No hand-written collectives.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from pint_tpu import telemetry
# pad_toas/PAD_ERROR_US moved to pint_tpu.bucketing (the shared shape
# policy home); re-exported here for the existing import sites
from pint_tpu.bucketing import (PAD_ERROR_US, bucket_size,  # noqa: F401
                                pad_toas, toa_shape)
from pint_tpu.fitting import device_loop
from pint_tpu.fitting.damped import downhill_iterate
from pint_tpu.fitting.fitter import Fitter
from pint_tpu.fitting.gls_step import (NoiseStatics, build_noise_statics,
                                       jitted_gls_probe, jitted_gls_step,
                                       pad_noise_statics)
from pint_tpu.fitting.step import jitted_wls_probe, jitted_wls_step
from pint_tpu.parallel.mesh import make_mesh, replicate, shard_toas


def sharded_fit(toas, model, *, mesh=None, maxiter: int = 2,
                min_chi2_decrease: float = 1e-3):
    """Damped sharded WLS; returns (deltas, info, chi2, converged).

    Host-side wrapper: pads the table to the mesh's TOA-shard multiple,
    places shardings, jits the step once, and runs the same
    accept / halve / converge loop as the dense Downhill fitters
    (:func:`pint_tpu.fitting.damped.downhill_iterate`) — each trial
    evaluation is one sharded XLA program.
    """
    mesh = mesh or make_mesh()
    n_shards = mesh.shape["toa"]
    telemetry.set_gauge("mesh.devices", mesh.size)
    telemetry.set_gauge("fit.ntoas", len(toas))
    # bucketed (not just shard-rounded) padding: same-structure fits of
    # different TOA counts execute one compiled step program
    padded = pad_toas(toas, bucket_size(len(toas), multiple=n_shards))
    toas_sh = shard_toas(padded, mesh)
    del padded  # drop the unsharded copy before the fit's peak
    base = replicate(model.base_dd(), mesh)
    deltas0 = replicate(model.zero_deltas(), mesh)
    if device_loop.enabled():
        # the whole accept/halve/converge loop fused on-device: one
        # program launch, one host fetch (fitting.device_loop)
        step = jitted_wls_step(model, counted=False)
        probe = jitted_wls_probe(model)
        with mesh, telemetry.profile_span("fit.sharded_wls", ntoas=len(toas)):
            out = device_loop.run_damped(
                lambda d, ops: step(ops[0], d, *ops[1:]), deltas0,
                (base, toas_sh),
                probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
                key=("sharded_wls", id(step), id(probe)),
                maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
                kind="device_loop_wls",
                fingerprint=(device_loop.fingerprint_id(model),),
                shape=toa_shape(toas_sh))
        return out[:4]
    step = jitted_wls_step(model)
    with mesh, telemetry.profile_span("fit.sharded_wls", ntoas=len(toas)):
        return downhill_iterate(
            lambda d: step(base, d, toas_sh), deltas0, maxiter=maxiter,
            min_chi2_decrease=min_chi2_decrease)


class ShardedWLSFitter(Fitter):
    """Fitter-API wrapper around :func:`sharded_fit`.

    Mirrors ``WLSFitter`` results (updated params, uncertainties, chi2,
    summary) while the compute runs TOA-sharded over the mesh.
    """

    def __init__(self, toas, model, mesh=None):
        super().__init__(toas, model)
        self.mesh = mesh or make_mesh()

    def fit_toas(self, maxiter: int = 20,
                 min_chi2_decrease: float = 1e-3) -> float:
        deltas, info, chi2, converged = sharded_fit(
            self.toas, self.model, mesh=self.mesh, maxiter=maxiter,
            min_chi2_decrease=min_chi2_decrease)
        # a diverged fit (non-finite chi2 — loop's in-carry flag) must
        # be FLAGGED and must not write NaN params/uncertainties back
        self.diverged = bool(np.asarray(info.get("diverged", False)))
        if self.diverged:
            self.diverged_reason = f"non-finite chi2 ({chi2})"
            self.converged = False
            return chi2
        errors = info["errors"]
        for name, d in deltas.items():
            p = self.model[name]
            p.add_delta(float(np.asarray(d)))
            p.uncertainty = float(np.asarray(errors[name]))
        self.fit_params = list(deltas)
        self.resids = self._new_resids()
        self.converged = converged
        return chi2


def sharded_gls_fit(toas, model, *, mesh=None, maxiter: int = 2,
                    min_chi2_decrease: float = 1e-3):
    """Damped TOA-sharded GLS; returns (deltas, info, chi2, converged).

    The north-star configuration (SURVEY.md §5): correlated noise
    (ECORR + power-law Fourier) with every O(n) array — TOA table,
    design-matrix rows, Fourier blocks, epoch indices — sharded over the
    mesh's "toa" axis. Noise bases are built inside the jitted step
    (pint_tpu.fitting.gls_step); the host only precomputes the O(n)
    epoch-index vector. The outer loop has the dense Downhill fitters'
    accept / halve / converge semantics (``chi2_at_input`` is computed
    in-step via the Schur-restricted noise subsystem, so a trial point
    costs one program).
    """
    mesh = mesh or make_mesh()
    n_shards = mesh.shape["toa"]
    telemetry.set_gauge("mesh.devices", mesh.size)
    telemetry.set_gauge("fit.ntoas", len(toas))
    # bucketed padding (see sharded_fit): cross-size program reuse
    n_target = bucket_size(len(toas), multiple=n_shards)

    noise, pl_specs = build_noise_statics(model, toas)
    noise = pad_noise_statics(noise, n_target)
    padded = pad_toas(toas, n_target)

    toas_sh = shard_toas(padded, mesh)
    del padded  # drop the unsharded copy before the fit's peak
    rep = NamedSharding(mesh, P())
    noise_sh = NoiseStatics(
        epoch_idx=jax.device_put(noise.epoch_idx,
                                 NamedSharding(mesh, P("toa"))),
        ecorr_phi=jax.device_put(noise.ecorr_phi, rep),
        pl_params=jax.device_put(noise.pl_params, rep),
    )
    base = replicate(model.base_dd(), mesh)
    deltas0 = replicate(model.zero_deltas(), mesh)
    if device_loop.enabled():
        # fused damped loop: one program launch + one fetch per fit,
        # with the existing psum reductions inside the while body
        step = jitted_gls_step(model, pl_specs=pl_specs, counted=False)
        probe = jitted_gls_probe(model, pl_specs=pl_specs)
        with mesh, telemetry.profile_span("fit.sharded_gls", ntoas=len(toas)):
            out = device_loop.run_damped(
                lambda d, ops: step(ops[0], d, *ops[1:]), deltas0,
                (base, toas_sh, noise_sh),
                probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
                key=("sharded_gls", id(step), id(probe)),
                maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
                kind="device_loop_gls",
                fingerprint=(device_loop.fingerprint_id(model), pl_specs),
                shape=toa_shape(toas_sh))
        return out[:4]
    step = jitted_gls_step(model, pl_specs=pl_specs)
    with mesh, telemetry.profile_span("fit.sharded_gls", ntoas=len(toas)):
        return downhill_iterate(
            lambda d: step(base, d, toas_sh, noise_sh), deltas0,
            maxiter=maxiter, min_chi2_decrease=min_chi2_decrease)


class ShardedServeFitter:
    """TOA-axis-sharded singleton fit with the batched dispatch surface.

    The throughput scheduler's big-fit route (ISSUE 7): a batchable
    request whose TOA bucket crosses the shard planner's threshold is
    not worth batching on the member axis (one such fit saturates the
    mesh by itself) — it runs as ONE fused loop program with every O(n)
    leaf sharded over the mesh's "toa" axis instead, exactly
    :func:`sharded_fit`'s placement. The surface mirrors
    ``BatchedPulsarFitter``'s dispatch split so the scheduler's
    pipeline treats both uniformly: construction is the host prep stage
    (pad + shard + replicate — device placement happens HERE, which is
    why the pipeline drains the target slots before prep),
    :meth:`dispatch_fit` enqueues without blocking, and the returned
    handle's ``finish()`` performs the fit's single device->host fetch,
    writes fitted values back into the request's model, and exposes
    per-member (length-1) ``converged`` / ``diverged`` arrays.
    """

    def __init__(self, toas, model, mesh):
        self.model = model
        self.mesh = mesh
        self.n_real = 1
        self.converged = np.zeros(1, dtype=bool)
        self.diverged = np.zeros(1, dtype=bool)
        n_shards = mesh.shape["toa"]
        telemetry.set_gauge("fit.ntoas", len(toas))
        padded = pad_toas(toas, bucket_size(len(toas), multiple=n_shards))
        self.toas = shard_toas(padded, mesh)
        del padded  # drop the unsharded copy before the fit's peak
        self.base = replicate(model.base_dd(), mesh)
        self.deltas0 = replicate(model.zero_deltas(), mesh)

    def device_bytes(self) -> dict[int, int]:
        """Per-device bytes of the placed table (serve accounting)."""
        from pint_tpu.parallel.mesh import per_device_bytes

        return per_device_bytes(self.toas)

    def dispatch_fit(self, maxiter: int = 20,
                     min_chi2_decrease: float = 1e-3,
                     max_step_halvings: int = 8):
        """Enqueue the fused sharded loop; returns the in-flight handle.

        With the device loop disabled (``PINT_TPU_DEVICE_LOOP=0``) the
        host driver cannot be suspended mid-loop, so the fit runs
        synchronously here and the handle is already resolved.
        """
        from pint_tpu.bucketing import toa_shape

        step = jitted_wls_step(self.model, counted=False)
        if device_loop.enabled():
            probe = jitted_wls_probe(self.model)
            with self.mesh, telemetry.span("fit.sharded_serve.dispatch",
                                           mesh=self.mesh.size):
                handle = device_loop.dispatch_damped(
                    lambda d, ops: step(ops[0], d, *ops[1:]),
                    self.deltas0, (self.base, self.toas),
                    probe=lambda d, ops: probe(ops[0], d, *ops[1:]),
                    key=("sharded_wls", id(step), id(probe)),
                    maxiter=maxiter,
                    min_chi2_decrease=min_chi2_decrease,
                    max_step_halvings=max_step_halvings,
                    kind="device_loop_wls",
                    fingerprint=(device_loop.fingerprint_id(self.model),),
                    shape=toa_shape(self.toas))
            return _InFlightShardedServeFit(self, handle)
        with self.mesh, telemetry.span("fit.sharded_serve.host_loop"):
            out = downhill_iterate(
                lambda d: step(self.base, d, self.toas), self.deltas0,
                maxiter=maxiter, min_chi2_decrease=min_chi2_decrease,
                max_step_halvings=max_step_halvings)
        return _InFlightShardedServeFit(self, _HostLoopResult(out))

    def _finish(self, deltas, info, chi2, converged) -> np.ndarray:
        """Write-back half of the fetch (ShardedWLSFitter's contract:
        a diverged fit is flagged and never writes NaN params back)."""
        diverged = bool(np.asarray(info.get("diverged", False))) \
            or not np.isfinite(float(np.asarray(chi2)))
        self.diverged[0] = diverged
        self.converged[0] = bool(converged) and not diverged
        if not diverged:
            errors = info["errors"]
            for name, d in deltas.items():
                p = self.model[name]
                p.add_delta(float(np.asarray(d)))
                p.uncertainty = float(np.asarray(errors[name]))
        return np.asarray([float(np.asarray(chi2))])


class _HostLoopResult:
    """Already-resolved pseudo-handle (host-driver fallback path)."""

    __slots__ = ("_out",)

    def __init__(self, out):
        self._out = out

    def ready(self) -> bool:
        return True

    def fetch(self):
        deltas, info, chi2, converged = self._out
        return deltas, info, chi2, converged, {}


class _InFlightShardedServeFit:
    """A dispatched sharded fit: ``finish()`` = fetch + write-back."""

    __slots__ = ("fitter", "_handle", "_chi2")

    def __init__(self, fitter: ShardedServeFitter, handle):
        self.fitter = fitter
        self._handle = handle
        self._chi2 = None

    def ready(self) -> bool:
        return self._chi2 is not None or self._handle.ready()

    def finish(self) -> np.ndarray:
        """The fit's one device->host sync; idempotent."""
        if self._chi2 is None:
            deltas, info, chi2, converged, _cnt = self._handle.fetch()
            self._chi2 = self.fitter._finish(deltas, info, chi2,
                                             converged)
        return self._chi2


class ShardedGLSFitter(Fitter):
    """TOA-sharded GLS fitter (north star; matches ``GLSFitter`` results).

    Mirrors ``pint_tpu.fitting.gls.GLSFitter`` — correlated-noise GLS
    with ECORR + power-law components — but runs as one sharded XLA
    program per iteration with device-side noise bases, so it scales to
    the 6e5-TOA regime where the dense host basis would need ~20 GB.
    """

    def __init__(self, toas, model, mesh=None):
        super().__init__(toas, model)
        self.mesh = mesh or make_mesh()
        self.noise_coeffs: np.ndarray | None = None

    def fit_toas(self, maxiter: int = 20,
                 min_chi2_decrease: float = 1e-3) -> float:
        deltas, info, chi2, converged = sharded_gls_fit(
            self.toas, self.model, mesh=self.mesh, maxiter=maxiter,
            min_chi2_decrease=min_chi2_decrease)
        # flagged, never silent NaN write-back (see ShardedWLSFitter)
        self.diverged = bool(np.asarray(info.get("diverged", False)))
        if self.diverged:
            self.diverged_reason = f"non-finite chi2 ({chi2})"
            self.converged = False
            return chi2
        errors = info["errors"]
        for name, d in deltas.items():
            p = self.model[name]
            p.add_delta(float(np.asarray(d)))
            p.uncertainty = float(np.asarray(errors[name]))
        self.fit_params = list(deltas)
        self.noise_coeffs = np.concatenate([
            np.asarray(info["fourier_coeffs"]),
            np.asarray(info["ecorr_coeffs"]),
        ])
        self.resids = self._new_resids()
        self.converged = converged
        return chi2
