"""Batched multi-pulsar fitting: vmap over stacked per-pulsar problems.

The "expert-parallel" analogue (SURVEY.md §2.6): each pulsar is an
independent fit problem; problems with a common model structure are
padded to one TOA count, stacked leaf-wise, ``vmap``-ed through the
single-pulsar fit step, and sharded over the mesh's "psr" axis (with the
TOA axis optionally sharded too). One compiled program fits the whole
array — the reference's equivalent is a Python loop over pintempo runs.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.step import make_wls_step
from pint_tpu.ops.dd import DD
from pint_tpu.parallel.mesh import (make_mesh, pad_to_multiple, replicate,
                                    shard_toas)
from pint_tpu.parallel.sharded_fit import pad_toas
from pint_tpu.toas import Flags, TOAs


def _strip_static(toas: TOAs) -> TOAs:
    """Erase per-pulsar static metadata so stacked treedefs match.

    The batched path requires selector-free models (no JUMP/EFAC flags),
    so flags and site names are not consulted during tracing.
    """
    n = len(toas)
    return dataclasses.replace(
        toas, flags=Flags({} for _ in range(n)), obs_names=("batched",),
        ephem_name="batched")


def stack_toas(toas_list: list[TOAs], n_pad: int | None = None) -> TOAs:
    """Pad to a common length and stack along a new leading pulsar axis."""
    n_max = n_pad or max(len(t) for t in toas_list)
    stripped = [_strip_static(pad_toas(t, n_max)) for t in toas_list]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *stripped)


class BatchedPulsarFitter:
    """Fit many pulsars with one vmapped, mesh-sharded XLA program.

    All models must share the same component structure and free-parameter
    list (the template is the first model). Per-pulsar parameter values
    are stacked into (B,)-shaped DD leaves.
    """

    def __init__(self, problems: list[tuple[TOAs, object]], mesh=None,
                 psr_axis: int | None = None):
        if not problems:
            raise ValueError("no problems given")
        self.toas_list = [t for t, _ in problems]
        self.models = [m for _, m in problems]
        template = self.models[0]
        names = template.free_params
        for m in self.models[1:]:
            if m.free_params != names:
                raise ValueError(
                    "batched fitting requires identical free-parameter lists: "
                    f"{m.free_params} != {names}")
        self.free_params = names
        for m in self.models:
            selector_params = [p.name for p in m.params.values() if p.selector]
            if selector_params:
                raise ValueError(
                    "batched fitting strips per-TOA flags, which would "
                    f"silently zero selector parameters {selector_params}; "
                    "fit this pulsar with WLSFitter/ShardedWLSFitter instead")
        if mesh is None:
            ndev = len(jax.devices())
            b = len(problems)
            axis = psr_axis if psr_axis is not None else int(np.gcd(b, ndev))
            mesh = make_mesh(psr_axis=axis)
        self.mesh = mesh
        # batched parameter state
        bases = [m.base_dd() for m in self.models]
        self.base = {
            k: DD(jnp.asarray([b[k].hi for b in bases]),
                  jnp.asarray([b[k].lo for b in bases]))
            for k in bases[0]
        }
        n_shards = self.mesh.shape["toa"]
        n_max = pad_to_multiple(max(len(t) for t in self.toas_list), n_shards)
        self.toas = shard_toas(stack_toas(self.toas_list, n_max), self.mesh,
                               batched=True)
        # abs_phase off: the weighted-mean subtraction absorbs TZR anchors
        self.step = jax.jit(jax.vmap(make_wls_step(template, abs_phase=False)))

    def fit_toas(self, maxiter: int = 2) -> np.ndarray:
        """Run the batched fit; updates every model. Returns per-pulsar chi2."""
        deltas = {k: jnp.zeros(len(self.models)) for k in self.free_params}
        base = replicate(self.base, self.mesh)
        info = None
        with self.mesh:
            for _ in range(max(1, maxiter)):
                deltas, info = self.step(base, deltas, self.toas)
        for i, m in enumerate(self.models):
            for k in self.free_params:
                p = m[k]
                p.add_delta(float(np.asarray(deltas[k][i])))
                p.uncertainty = float(np.asarray(info["errors"][k][i]))
        return np.asarray(info["chi2"])
