"""Batched multi-pulsar fitting: vmap over stacked per-pulsar problems.

The "expert-parallel" analogue (SURVEY.md §2.6): each pulsar is an
independent fit problem; problems are padded to one TOA count, stacked
leaf-wise, ``vmap``-ed through the single-pulsar fit step, and sharded
over the mesh's "psr" axis (with the TOA axis optionally sharded too).
One compiled program fits the whole array — the reference's equivalent
is a Python loop over pintempo runs.

Heterogeneous models (VERDICT round-1 task 4) are batched through a
**union model** + parameter-superset mask:

* the union's components are the set union of every pulsar's components
  (merged by class; EFAC/EQUAD/JUMP mask-parameters merged per entry
  with per-owner selector tags);
* a pulsar lacking a component runs it with *neutral* parameter values
  (zero amplitudes; see ``NEUTRAL_VALUES`` for the few non-zero ones
  needed to avoid 0/0), so its delay/phase contribution vanishes;
* each pulsar's free-parameter set is imposed by a traced 0/1 mask that
  zeroes design-matrix columns of parameters it does not fit;
* flag-based selectors are materialized as data arrays
  (``materialize_selector_masks``) before the static flags are stripped
  for stacking, and zeroed on non-owner pulsars.

Limitations (documented, checked): one binary class per batch (two
binary models would collide on PB/A1/... names — batch per binary family
instead).

**Batchable frontier (ISSUE 8).** Correlated-noise bases and wideband
tables are first-class batch members:

* noise-basis components (ECORR / PLRedNoise / PLDMNoise / PLChromNoise)
  merge by class into the union with their value-bearing
  hyperparameters NORMALIZED to canonical constants — the batched GLS
  step never reads them from the model (per-member values ride the
  traced ``NoiseStatics``: stacked (B, n) epoch indices, (B, ne) ECORR
  priors padded to the pow-2 basis bucket, (B, n_pl, 2) power-law
  params), so the union's compiled program — and its fingerprint — is
  independent of the members' noise values;
* wideband members additionally carry a traced DM block
  ({"vals", "errs"}, (B, n) each — the flag-borne measurements
  materialized as data before static stripping) through the fused
  wideband step (pint_tpu.fitting.wideband.make_wb_step);
* the per-member damped state machines of the fused batched loop are
  UNCHANGED — only the step/probe pair and the operand tail differ per
  family ("wls" | "gls" | "wb").
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from pint_tpu.fitting.step import jitted_wls_step
from pint_tpu.models.jump import PhaseJump
from pint_tpu.models.noise import ScaleToaError
from pint_tpu.models.timing_model import TimingModel
from pint_tpu.ops.dd import DD
from pint_tpu.bucketing import bucket_size
from pint_tpu.parallel.mesh import make_mesh, replicate, shard_toas
from pint_tpu.toas import Flags, TOAs

# neutral values that make an absent component a no-op without 0/0: a
# zero-amplitude binary still runs its Kepler solve (needs PB/FB0 > 0),
# DDK divides by sin(KIN). Everything not listed neutralizes at 0.0
# (amplitudes) or 1.0 (EFAC-like multipliers).
NEUTRAL_VALUES = {
    "PB": 365.25, "FB0": 1.0 / (365.25 * 86400.0), "KIN": 60.0,
    "TZRFRQ": 1400.0,
}
_MULTIPLICATIVE = ("EFAC", "DMEFAC")


def neutral_value(name: str) -> float:
    base = name.rstrip("0123456789").rstrip("_")
    if base in _MULTIPLICATIVE:
        return 1.0
    if name in NEUTRAL_VALUES:
        return NEUTRAL_VALUES[name]
    if base in NEUTRAL_VALUES:
        return NEUTRAL_VALUES[base]
    return 0.0


def _structural_state(c) -> tuple:
    """Non-parameter component state that must match across a batch.

    Components merged by class share ONE instance in the union, so any
    state living outside the Param dict (DMX MJD windows, IFunc node
    epochs) must be identical for every pulsar contributing it.
    """
    out = []
    for attr in ("ranges", "node_mjds", "nodes", "indices"):
        v = getattr(c, attr, None)
        if isinstance(v, dict):
            out.append(tuple(sorted((k, tuple(np.atleast_1d(x)))
                                    for k, x in v.items())))
        elif v is not None:
            out.append(tuple(np.ravel(np.asarray(v, dtype=np.float64))))
    return tuple(out)


def _normalized_noise_basis(c):
    """Deepcopy of a noise-basis component with value-bearing
    hyperparameters pinned to canonical constants.

    The union's compiled GLS/wideband step reads noise VALUES from the
    traced ``NoiseStatics`` operand, never from the union model — but
    the union's ``_fn_fingerprint`` (the program-cache key) pins frozen
    parameter values. Normalizing them here makes two batches that
    differ only in noise values share one union fingerprint, hence one
    compiled loop program. The harmonic-count parameter (``_c_name``:
    TNREDC/TNDMC/TNCHROMC) is shape-static and KEPT — a different
    nharm is a different program.
    """
    import copy as _copy

    cc = _copy.deepcopy(c)
    keep = getattr(cc, "_c_name", None)
    for p in cc.params:
        if p.is_numeric and p.name != keep:
            p.value = (1.0, 0.0)
        p.frozen = True
    return cc


def _check_noise_merge(prev, c, name: str) -> None:
    """Noise-basis components merged by class must agree on everything
    shape-static: parameter sets, structural state, harmonic count and
    chromatic index (per-member VALUES ride the traced statics)."""
    if [p.name for p in prev.params] != [p.name for p in c.params]:
        raise ValueError(
            f"noise component {name} has different parameter sets "
            "across the batch; split the batch")
    if _structural_state(prev) != _structural_state(c):
        raise ValueError(
            f"noise component {name} has different non-parameter state "
            "across the batch; split the batch")
    if hasattr(prev, "nharm") and prev.nharm() != c.nharm():
        raise ValueError(
            f"noise component {name} has different harmonic counts "
            f"({prev.nharm()} vs {c.nharm()}) across the batch — the "
            "Fourier block shape is static; split the batch")
    if (hasattr(prev, "basis_alpha")
            and prev.basis_alpha() != c.basis_alpha()):
        raise ValueError(
            f"noise component {name} has different chromatic indices "
            "across the batch; split the batch")


def build_union_model(models, drop_noise_scale: bool = False,
                      drop_dm_scale: bool = False
                      ) -> tuple[TimingModel, dict[str, dict[int, tuple]]]:
    """Union of the models' components for batched fitting.

    ``drop_noise_scale=True`` (the traced-EFAC frontier, ISSUE 10
    satellite) omits every ``ScaleToaError`` from the union entirely:
    the batched GLS/wideband steps then read the per-member scaled
    sigmas from the traced ``NoiseStatics.sigma`` operand, so the union
    model — and its fingerprint, the compiled-program key — carries no
    white-noise values at all. Only valid for noise/wideband batches
    whose step consumes statics (the WLS union step has no statics
    operand and keeps the merged-scale machinery below).
    ``drop_dm_scale=True`` (ISSUE 14 satellite) is the wideband
    analogue: every ``ScaleDmError`` is omitted and per-member DM-error
    scaling rides the traced ``NoiseStatics.dm_sigma``, so mixed-DMEFAC
    wideband members share one union fingerprint — only valid for
    wideband batches (narrowband steps never read DM errors).

    Returns (union_model, owners) where ``owners`` maps each merged
    mask-parameter's synthetic selector key to a per-member dict
    ``{member index: (original selector, original name, original
    frozen)}`` — non-owner members get a zero mask at materialization,
    and fit results are written back to each owner's own parameter (the
    union name is synthetic).

    Structurally identical entries are DEDUPED into one shared union
    parameter instead of one per member: a scheduler batch of B
    same-structure pulsars used to carry B synthetic JUMP columns (B-1
    masked to zero per member), tripling the per-iteration jacfwd cost
    of the fused batched loop. A JUMP dedupes on its selector alone —
    per-member values ride the traced ``base`` as (B,) leaves like any
    plain parameter. EFAC/EQUAD values are host-side trace constants
    (``scale_sigma`` reads ``value_f64``), so scale entries dedup only
    when frozen with an identical (kind, selector, value) triple.
    """
    plain: dict[str, object] = {}
    scale = ScaleToaError()
    jump = PhaseJump()
    owners: dict[str, dict[int, tuple]] = {}
    shared: dict[tuple, str] = {}  # dedup key -> synthetic owners key
    by_key: dict[str, object] = {}  # synthetic owners key -> union Param
    binary_classes: set[str] = set()
    tag = 0

    def _join(dk, i, p) -> bool:
        """Attach member ``i``'s param to an existing shared entry."""
        key = shared.get(dk)
        if key is None or i in owners[key]:
            return False
        owners[key][i] = (p.selector, p.name, p.frozen)
        if not p.frozen:
            by_key[key].frozen = False
        return True

    noise_basis: dict[str, tuple] = {}  # class -> (normalized, exemplar)
    for i, m in enumerate(models):
        for c in m.components:
            if getattr(c, "is_noise_basis", False):
                name = type(c).__name__
                # a FREE hyperparameter would be silently frozen by the
                # union normalization (its masked design column has an
                # identically-zero phase derivative -> zero delta,
                # bogus uncertainty) — reject, mirroring the serve
                # layer's free_noise_param passthrough routing
                free = [p.name for p in c.params
                        if p.is_numeric and not p.frozen]
                if free:
                    raise ValueError(
                        f"noise component {name} has free "
                        f"hyperparameters {free}; batched fitting "
                        "treats noise values as fixed per-member "
                        "statics — freeze them or fit standalone")
                prev = noise_basis.get(name)
                if prev is None:
                    noise_basis[name] = (_normalized_noise_basis(c), c)
                else:
                    _check_noise_merge(prev[1], c, name)
                continue
            if hasattr(c, "scale_dm_sigma") and drop_dm_scale:
                continue  # DM-error scaling rides NoiseStatics.dm_sigma
            if isinstance(c, ScaleToaError):
                if drop_noise_scale:
                    continue  # scaling rides NoiseStatics.sigma
                for p in c.params:
                    kind = p.name.rstrip("0123456789")
                    dk = (("scale", kind, p.selector, p.value_f64)
                          if p.frozen else None)
                    if dk is not None and _join(dk, i, p):
                        continue
                    sel = ("batched", str(tag))
                    np_ = scale._add(kind, sel, value=p.value_f64)
                    np_.value = p.value
                    np_.frozen = p.frozen
                    key = " ".join(sel)
                    owners[key] = {i: (p.selector, p.name, p.frozen)}
                    by_key[key] = np_
                    if dk is not None:
                        shared[dk] = key
                    tag += 1
                continue
            # exact type: DelayJump subclasses PhaseJump but applies in
            # the delay chain — absorbing it here would silently turn it
            # into a phase term, and the generic union path would share
            # one pulsar's jump windows with the whole batch
            if isinstance(c, PhaseJump) and type(c) is not PhaseJump:
                raise ValueError(
                    f"batched fitting does not support {type(c).__name__}; "
                    "use per-pulsar fitters or PhaseJump")
            if type(c) is PhaseJump:
                for p in c.params:
                    # jump values are traced (phase reads the resolved
                    # base), so same-selector jumps share one column
                    # with per-member (B,) values
                    dk = ("jump", p.selector)
                    if _join(dk, i, p):
                        continue
                    sel = ("batched", str(tag))
                    np_ = jump.add_jump(sel, frozen=p.frozen)
                    np_.value = p.value
                    key = " ".join(sel)
                    owners[key] = {i: (p.selector, p.name, p.frozen)}
                    by_key[key] = np_
                    shared[dk] = key
                    tag += 1
                continue
            name = type(c).__name__
            if getattr(c, "binary_model_name", None):
                binary_classes.add(name)
                if len(binary_classes) > 1:
                    raise ValueError(
                        f"one binary class per batch (got {binary_classes}); "
                        "group pulsars by binary model family")
            if name in plain:
                prev = plain[name]
                if [p.name for p in prev.params] != [p.name for p in c.params]:
                    raise ValueError(
                        f"component {name} has different parameter sets "
                        "across the batch; split the batch")
                if _structural_state(prev) != _structural_state(c):
                    raise ValueError(
                        f"component {name} has different non-parameter state "
                        "(DMX windows / IFunc nodes) across the batch; the "
                        "union would apply one pulsar's windows to all — "
                        "split the batch")
            else:
                plain[name] = c
    comps = list(plain.values())
    comps.extend(norm for norm, _ in noise_basis.values())
    if scale.params:
        comps.append(scale)
    if jump.params:
        comps.append(jump)
    union = TimingModel(comps, name="batched_union",
                        header=dict(models[0].header))
    return union, owners


def _materialize_for_pulsar(toas, i, models, union, owners):
    """All selector masks as data, with non-owner mask params zeroed.

    Only the UNION's selectors are materialized — they are the complete
    set the stacked table is ever consulted for (the union is the model
    every traced evaluation runs), and the synthetic merged keys are
    skipped entirely because the ``owners`` loop overwrites each one
    (owner's original selector, zeros elsewhere). Materializing every
    member model's own selectors too — the previous behavior — made
    batch prep O(B^2) in dead keys.
    """
    from pint_tpu.models.parameter import toa_mask

    masks = dict(toas.aux_masks)
    n = len(toas)
    for p in union.params.values():
        if not p.selector:
            continue
        key = " ".join(p.selector)
        if key in masks or key in owners:
            continue
        masks[key] = np.asarray(toa_mask(p.selector, toas),
                                dtype=np.float64)
    zeros = np.zeros(n)
    for key, ent in owners.items():
        info = ent.get(i)
        if info is not None:
            masks[key] = np.asarray(toa_mask(info[0], toas),
                                    dtype=np.float64)
        else:
            masks[key] = zeros
    return dataclasses.replace(toas, aux_masks=masks)


def _strip_static(toas: TOAs, n: int | None = None) -> TOAs:
    """Erase per-pulsar static metadata so stacked treedefs match.

    Safe because every flag-based selector has been materialized into
    ``aux_masks`` (data) first; site names are not consulted during
    tracing (obs-dependent quantities were precomputed into the table).
    ``n`` is the post-padding row count the static flags must claim
    (static aux is part of pytree equality, so every member must agree
    BEFORE the leaves are stacked).
    """
    n = len(toas) if n is None else n
    return dataclasses.replace(
        toas, flags=Flags({} for _ in range(n)), obs_names=("batched",),
        ephem_name="batched")


def stack_toas(toas_list: list[TOAs], n_pad: int | None = None) -> TOAs:
    """Pad to a common length and stack along a new leading pulsar axis.

    Pure-numpy pad + stack: the previous per-member ``pad_toas`` +
    ``jnp.stack`` dispatched ~20 eager device ops per member per leaf,
    which dominated throughput-scheduler host prep (0.22 s of a 0.27 s
    warm 16-member batch build). Leaves stay NUMPY — both callers shard
    the stacked table immediately (``shard_toas`` / ``_shard_psr_only``
    device_put every leaf), so materializing jnp arrays here transferred
    each leaf twice (measured: ~40% of a warm 16-member ctor). Padding
    policy is ``bucketing.pad_toas``'s exactly: pad rows replicate the
    member's last TOA with ``PAD_ERROR_US`` uncertainty (zero-weight
    rows).
    """
    from pint_tpu.bucketing import PAD_ERROR_US

    n_max = n_pad or max(len(t) for t in toas_list)
    k_pads = [n_max - len(t) for t in toas_list]
    if any(k < 0 for k in k_pads):
        raise ValueError(f"n_pad {n_max} < a member's TOA count")

    def pad_np(x, k):
        x = np.asarray(x)
        if k == 0:
            return x
        return np.concatenate([x, np.repeat(x[-1:], k, axis=0)], axis=0)

    def stack_leaf(*xs):
        return np.stack([pad_np(x, k) for x, k in zip(xs, k_pads)])

    stripped = [_strip_static(t, n_max) for t in toas_list]
    stacked = jax.tree.map(stack_leaf, *stripped)
    if any(k_pads):
        err = np.array(stacked.error_us)
        for i, k in enumerate(k_pads):
            if k:
                err[i, n_max - k:] = PAD_ERROR_US
        stacked = dataclasses.replace(stacked, error_us=err)
    return stacked


def _shard_psr_only(toas: TOAs, mesh):
    """Mesh-place a stacked (B, 1) table with ONLY "psr" sharded.

    The stacked TZR anchor tables are one row per member — a length-1
    TOA axis cannot shard over a >1 "toa" mesh axis, and sharding it
    buys nothing (one row), so every data axis but the member axis is
    replicated."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    def put(x):
        spec = P("psr", *([None] * (jnp.ndim(x) - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, toas)


class BatchedPulsarFitter:
    """Fit many pulsars with one vmapped, mesh-sharded XLA program.

    Models may differ in components and free parameters (union model +
    superset mask; see module docstring). Per-pulsar parameter values are
    stacked into (B,)-shaped DD leaves; neutral values stand in for
    parameters a pulsar does not have.

    ``pad_members`` (the throughput scheduler's member-count bucket,
    pint_tpu.bucketing.member_bucket_size) extends the batch with dummy
    members replicating the LAST real problem — deepcopied models, so
    write-back never aliases a real parameter. Dummies are bit-inert on
    real members: vmapped evaluation is member-independent, and a dummy
    converges in lockstep with the member it clones, so it adds no loop
    iterations either. Results (``fit_toas`` return, ``converged``) are
    sliced to the real members.
    """

    def __init__(self, problems: list[tuple[TOAs, object]], mesh=None,
                 psr_axis: int | None = None,
                 pad_members: int | None = None,
                 basis_bucket: int | None = None):
        if not problems:
            raise ValueError("no problems given")
        self.n_real = len(problems)
        if pad_members is not None and pad_members > len(problems):
            import copy as _copy

            last_t, last_m = problems[-1]
            problems = list(problems) + [
                (last_t, _copy.deepcopy(last_m))
                for _ in range(pad_members - len(problems))]
        self.toas_list = [t for t, _ in problems]
        self.models = [m for _, m in problems]
        # batch family (ISSUE 8): wideband tables run the fused joint
        # TOA+DM step, noise-basis models the fused GLS step, everything
        # else the original WLS union path — the per-member damped state
        # machines are identical across families
        wb_flags = [bool(getattr(t, "is_wideband", lambda: False)())
                    for t in self.toas_list]
        if any(wb_flags) and not all(wb_flags):
            raise ValueError("cannot batch wideband and narrowband "
                             "tables together; split the batch")
        has_noise = any(getattr(c, "is_noise_basis", False)
                        for m in self.models for c in m.components)
        self.family = ("wb" if wb_flags and all(wb_flags)
                       else "gls" if has_noise else "wls")
        # per-real-member flags; fit_toas / finish() overwrite
        self.converged = np.zeros(self.n_real, dtype=bool)
        self.diverged = np.zeros(self.n_real, dtype=bool)
        from pint_tpu.bucketing import note_batch_occupancy

        note_batch_occupancy(self.n_real, len(self.models))
        # traced-EFAC frontier (ISSUE 10 satellite): noise/wideband
        # batches whose every scaled member's white-noise chain is
        # expressible as one per-TOA sigma vector ride it as a traced
        # statics leaf — the union then needs (and gets) no scale
        # component, so one compiled program serves every EFAC/EQUAD
        # value mix. PINT_TPU_TRACE_EFAC=0 restores the PR-8 path.
        from pint_tpu.fitting.gls_step import (sigma_traceable,
                                               trace_efac_enabled)

        def _has_scale(m):
            return any(getattr(c, "is_noise_scale", False)
                       for c in m.components)

        self._trace_sigma = (
            self.family != "wls" and trace_efac_enabled()
            and any(_has_scale(m) for m in self.models)
            and all(sigma_traceable(m) for m in self.models
                    if _has_scale(m)))
        # traced-DMEFAC frontier (ISSUE 14 satellite, the PR-10
        # residue): wideband batches whose DM-error scaling is
        # expressible as one per-TOA dm_sigma vector ride it as a
        # traced statics leaf; the union then carries no ScaleDmError,
        # so mixed-DMEFAC members share one compiled program.
        # PINT_TPU_TRACE_DMEFAC=0 restores the pinned-constant path.
        from pint_tpu.fitting.gls_step import (dm_sigma_traceable,
                                               trace_dmefac_enabled)

        def _has_dm_scale(m):
            return any(hasattr(c, "scale_dm_sigma")
                       for c in m.components)

        self._trace_dm_sigma = (
            self.family == "wb" and trace_dmefac_enabled()
            and any(_has_dm_scale(m) for m in self.models)
            and all(dm_sigma_traceable(m) for m in self.models
                    if _has_dm_scale(m)))
        self.union, owners = build_union_model(
            self.models, drop_noise_scale=self._trace_sigma,
            drop_dm_scale=self._trace_dm_sigma)

        # free-parameter union + per-pulsar 0/1 masks. Mask params that
        # were merged (JUMP/EFAC family) are fitted under their synthetic
        # union names; the owner's own per-model name is skipped and the
        # result written back through ``_merged_owner``.
        merged = {(i, info[1])
                  for ent in owners.values() for i, info in ent.items()}
        # union name -> {member: (orig name, orig frozen)}
        self._merged_owner: dict[str, dict[int, tuple[str, bool]]] = {}
        for p in self.union.params.values():
            key = " ".join(p.selector) if p.selector else ""
            if key in owners:
                self._merged_owner[p.name] = {
                    i: (info[1], info[2])
                    for i, info in owners[key].items()}
        names: list[str] = []
        for i, m in enumerate(self.models):
            for k in m.free_params:
                if (i, k) in merged:
                    continue  # fitted via its synthetic union name
                if k not in names:
                    names.append(k)
        for p in self.union.params.values():
            if not p.frozen and p.fittable and p.name not in names:
                names.append(p.name)
        self.free_params = names
        B = len(self.models)
        mask_rows = []
        for i, m in enumerate(self.models):
            row = []
            for k in names:
                if k in self._merged_owner:
                    # a member fits a shared merged column iff it owns
                    # an entry AND its own parameter is free
                    info = self._merged_owner[k].get(i)
                    row.append(1.0 if info is not None and not info[1]
                               else 0.0)
                else:
                    row.append(1.0 if k in m.params and k in m.free_params
                               else 0.0)
            mask_rows.append(row)
        # numpy until dispatch: ``replicate`` device_puts the leaves at
        # fit time, and host-side consumers (_write_back's owner check)
        # index these per member — eager jnp scalars there cost an XLA
        # dispatch each (~900 per 64-fit drain; measured at 40% of the
        # throughput scheduler's fetch stage)
        self.param_mask = {
            k: np.asarray([mask_rows[i][j] for i in range(B)])
            for j, k in enumerate(names)}

        if mesh is None:
            ndev = len(jax.devices())
            axis = psr_axis if psr_axis is not None else int(np.gcd(B, ndev))
            mesh = make_mesh(psr_axis=axis)
        self.mesh = mesh

        # batched parameter state: model value, else neutral
        self.base = {}
        for pname, up in self.union.params.items():
            if not up.is_numeric:
                continue
            key = " ".join(up.selector) if up.selector else ""
            ent = owners.get(key)
            his, los = [], []
            for i, m in enumerate(self.models):
                if ent is not None:
                    # merged mask param: each owner member's OWN value
                    # (a shared JUMP column fits per-member amplitudes
                    # through the traced base); neutral elsewhere
                    info = ent.get(i)
                    p = m[info[1]] if info is not None else None
                    his.append(p.hi if p is not None
                               else neutral_value(pname))
                    los.append(p.lo if p is not None else 0.0)
                elif pname in m.params:
                    his.append(m[pname].hi)
                    los.append(m[pname].lo)
                else:
                    his.append(neutral_value(pname))
                    los.append(0.0)
            self.base[pname] = DD(np.asarray(his, dtype=np.float64),
                                  np.asarray(los, dtype=np.float64))

        n_shards = self.mesh.shape["toa"]
        # bucketed common length: batches over similar TOA counts (and
        # re-built batches as datasets grow) reuse one vmapped program
        n_max = bucket_size(max(len(t) for t in self.toas_list),
                            multiple=n_shards)
        prepped = [
            _materialize_for_pulsar(t, i, self.models, self.union, owners)
            for i, t in enumerate(self.toas_list)
        ]
        self.toas = shard_toas(stack_toas(prepped, n_max), self.mesh,
                               batched=True)
        # noise statics + wideband DM block (the batchable frontier):
        # per-member values as TRACED stacked operands. Statics are
        # built on each member's RAW table — padding rows therefore
        # cannot form phantom ECORR epochs by construction (the PR-2
        # bug class; regression-pinned through this path in
        # tests/test_serve_frontier.py) — then padded to the TOA bucket
        # and the pow-2 basis bucket (inert columns; bucketing
        # .pad_basis_cols) and stacked (B, ...).
        self.noise = None
        self.dm = None
        self.pl_specs = ()
        self.basis_bucket = 0
        if self.family != "wls":
            from pint_tpu.bucketing import basis_bucket_size
            from pint_tpu.fitting.gls_step import (build_noise_statics,
                                                   stack_noise_statics)

            statics, specs_list = [], []
            for t, m in zip(self.toas_list, self.models):
                # numpy leaves: the stacked statics are device-placed
                # ONCE below (jnp here would transfer every member's
                # epoch vector twice — the stack_toas lesson)
                s, specs = build_noise_statics(m, t, as_numpy=True)
                if self._trace_sigma:
                    from pint_tpu.fitting.gls_step import scaled_sigma_np

                    # per-member scaled sigmas over the PADDED length
                    # (pad rows replicate the last row's selector masks
                    # at PAD_ERROR weight — elementwise what the pinned
                    # path computes on the padded stacked table)
                    s = s._replace(sigma=scaled_sigma_np(m, t, n_max))
                if self._trace_dm_sigma:
                    from pint_tpu.fitting.gls_step import \
                        scaled_dm_sigma_np

                    s = s._replace(
                        dm_sigma=scaled_dm_sigma_np(m, t, n_max))
                statics.append(s)
                specs_list.append(specs)
            if any(sp != specs_list[0] for sp in specs_list[1:]):
                raise ValueError(
                    "noise-basis specs differ across the batch "
                    "(component set / harmonic counts / chromatic "
                    "index); split the batch")
            self.pl_specs = specs_list[0]
            ne_max = max(int(np.shape(s.ecorr_phi)[0]) for s in statics)
            ne_target = (basis_bucket if basis_bucket is not None
                         else basis_bucket_size(ne_max))
            if ne_target < ne_max:
                raise ValueError(
                    f"basis_bucket {ne_target} < largest member epoch "
                    f"count {ne_max}")
            self.basis_bucket = ne_target
            self.noise = _shard_psr_only(
                stack_noise_statics(statics, n_max, ne_target), self.mesh)
            if self.family == "wb":
                from pint_tpu.fitting.wideband import build_wb_data

                blocks = [build_wb_data(t, n_max) for t in self.toas_list]
                self.dm = _shard_psr_only(
                    {"vals": np.stack([b["vals"] for b in blocks]),
                     "errs": np.stack([b["errs"] for b in blocks])},
                    self.mesh)
        # TZR anchoring: when every member carries an AbsPhase (TZRMJD),
        # the one-row TZR tables are stacked and traced through the step
        # so each member computes the exact DENSE anchored convention —
        # the anchorless (abs_phase=False) wrapped-phase path is offset-
        # fragile: a member whose constant phase offset lands near ±0.5
        # turns wraps incoherently and fits to garbage (found by the
        # ISSUE-5 throughput A/B; regression-pinned in tests/test_serve
        # .py). Members without TZRMJD fall back to the anchorless path,
        # now guarded by the circular re-centering in fitting.step.
        tzr_list = [m.get_tzr_toas() for m in self.models]
        if all(t is not None for t in tzr_list):
            prepped_tzr = [
                _materialize_for_pulsar(t, i, self.models, self.union,
                                        owners)
                for i, t in enumerate(tzr_list)
            ]
            self.tzr = _shard_psr_only(stack_toas(prepped_tzr), self.mesh)
        else:
            self.tzr = None
        # params= is the fitter's free-param union — a parameter frozen in
        # the model that contributed the union component may still be free
        # in another pulsar (its column is masked per pulsar).
        anchored = self.tzr is not None
        if self.family == "wls":
            self.step = jitted_wls_step(self.union, abs_phase=anchored,
                                        traced_tzr=anchored, masked=True,
                                        params=self.free_params,
                                        vmapped=True)
        elif self.family == "gls":
            from pint_tpu.fitting.gls_step import jitted_gls_step

            self.step = jitted_gls_step(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, masked=True,
                params=self.free_params, vmapped=True)
        else:
            from pint_tpu.fitting.wideband import jitted_wb_step

            self.step = jitted_wb_step(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, masked=True,
                params=self.free_params, vmapped=True)
        # the union is never mutated after construction (fit results
        # write back to the MEMBER models), so its fingerprint id is
        # stable — dispatch_fit reuses it instead of re-digesting the
        # whole component stack per launch. A content digest, not
        # hash(): the persistent program store keys on it across
        # processes (pint_tpu.programs).
        from pint_tpu.fitting.device_loop import fingerprint_id
        self._union_fp_hash = fingerprint_id(self.union)

    def _family_args(self) -> tuple:
        """Per-family operand tail between the TOA table and the mask:
        ``()`` (wls) / ``(noise,)`` (gls) / ``(noise, dm)`` (wb)."""
        if self.family == "gls":
            return (self.noise,)
        if self.family == "wb":
            return (self.noise, self.dm)
        return ()

    def _probe_step(self):
        """The family's vmapped residual-only probe (shared program
        cache; traced into the fused loop)."""
        from pint_tpu.fitting.step import jitted_wls_probe

        anchored = self.tzr is not None
        if self.family == "gls":
            from pint_tpu.fitting.gls_step import jitted_gls_probe

            return jitted_gls_probe(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, vmapped=True)
        if self.family == "wb":
            from pint_tpu.fitting.wideband import jitted_wb_probe

            return jitted_wb_probe(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, vmapped=True)
        return jitted_wls_probe(self.union, abs_phase=anchored,
                                traced_tzr=anchored, vmapped=True)

    def _step_uncounted(self):
        """The family's vmapped full step WITHOUT the execution-counter
        wrapper (device-loop callers trace it into the loop program)."""
        anchored = self.tzr is not None
        if self.family == "gls":
            from pint_tpu.fitting.gls_step import jitted_gls_step

            return jitted_gls_step(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, masked=True,
                params=self.free_params, vmapped=True, counted=False)
        if self.family == "wb":
            from pint_tpu.fitting.wideband import jitted_wb_step

            return jitted_wb_step(
                self.union, pl_specs=self.pl_specs, abs_phase=anchored,
                traced_tzr=anchored, masked=True,
                params=self.free_params, vmapped=True, counted=False)
        from pint_tpu.fitting.step import jitted_wls_step as _wls

        return _wls(self.union, abs_phase=anchored, traced_tzr=anchored,
                    masked=True, params=self.free_params, vmapped=True,
                    counted=False)

    def fit_toas(self, maxiter: int = 20,
                 min_chi2_decrease: float = 1e-3,
                 max_step_halvings: int = 8) -> np.ndarray:
        """Run the damped batched fit; updates every model.

        The dense fitters' accept/halve/converge loop, vectorized over
        the pulsar axis: each pulsar carries its own step damping
        ``lam`` and convergence flag, and every trial evaluation is the
        ONE vmapped XLA program (a halving for one pulsar re-evaluates
        all — the batch is a single program, so partial evaluation
        would not be cheaper). Returns per-pulsar chi2;
        ``self.converged`` is the per-pulsar (B,) truth array.

        Default path (``fitting.device_loop``): the whole loop runs
        inside ONE fused XLA program with a per-member lam carry —
        members halve independently on-device and the host sees one
        launch + one fetch per fit instead of a masking round trip per
        trial. ``PINT_TPU_DEVICE_LOOP=0`` restores this host loop (the
        reference oracle; parity pinned by tests/test_device_loop.py).
        """
        B = len(self.models)

        from pint_tpu import telemetry
        from pint_tpu.fitting import device_loop

        if device_loop.enabled():
            with telemetry.profile_span("fit.batched", n_pulsars=B):
                return self.dispatch_fit(
                    maxiter=maxiter,
                    min_chi2_decrease=min_chi2_decrease,
                    max_step_halvings=max_step_halvings).finish()

        deltas = {k: jnp.zeros(B) for k in self.free_params}
        base = replicate(self.base, self.mesh)
        mask = replicate(self.param_mask, self.mesh)

        anchored = self.tzr is not None
        probe_step = self._probe_step()
        extra = self._family_args()

        def run(d):
            if anchored:
                return self.step(base, d, self.toas, *extra, mask,
                                 self.tzr)
            return self.step(base, d, self.toas, *extra, mask)

        def run_probe(d):
            if anchored:
                return np.asarray(probe_step(base, d, self.toas, *extra,
                                             self.tzr))
            return np.asarray(probe_step(base, d, self.toas, *extra))

        # the reference transcription of the fused batched loop (see
        # device_loop._build_batched_probe_loop): full evaluations judge
        # fresh (lam=1) trials and re-check probe-found candidates; the
        # member-wise residual-only probe walks the halving ladder
        with self.mesh:
            new_deltas, info = run(deltas)
            chi2 = np.asarray(info["chi2_at_input"]).copy()
            converged = np.zeros(B, dtype=bool)
            for _ in range(max(1, maxiter)):
                dx = {k: new_deltas[k] - deltas[k] for k in deltas}
                lam = np.ones(B)
                h = np.zeros(B, dtype=int)
                active = ~converged
                accepted = np.zeros(B, dtype=bool)
                pending = active.copy()
                rej = np.zeros(B, dtype=bool)
                trial_info = None
                while pending.any():
                    act = active & ~accepted & pending
                    lam_j = jnp.asarray(np.where(act, lam, 0.0))
                    trial = {k: deltas[k] + lam_j * dx[k]
                             for k in deltas}
                    trial_new, trial_info = run(trial)
                    trial_chi2 = np.asarray(trial_info["chi2_at_input"])
                    better = trial_chi2 <= chi2 + 1e-12
                    newly = act & better
                    rej = act & ~better
                    # keep the accepted pulsars' trial state
                    keep = jnp.asarray(newly)
                    deltas = {k: jnp.where(keep, trial[k], deltas[k])
                              for k in deltas}
                    new_deltas = {k: jnp.where(keep, trial_new[k],
                                               new_deltas[k])
                                  for k in deltas}
                    decrease = chi2 - trial_chi2
                    chi2 = np.where(newly, trial_chi2, chi2)
                    converged |= newly & (decrease < min_chi2_decrease)
                    accepted |= newly
                    # rejected members probe halved candidates
                    seek = rej.copy()
                    found = np.zeros(B, dtype=bool)
                    hp = h + 1
                    lam_p = lam * 0.5
                    while (seek & (hp < max_step_halvings)).any():
                        sk = seek & (hp < max_step_halvings)
                        lam_pj = jnp.asarray(np.where(sk, lam_p, 0.0))
                        cand = {k: deltas[k] + lam_pj * dx[k]
                                for k in deltas}
                        pc = run_probe(cand)
                        fnd = sk & (pc <= chi2 + 1e-12)
                        found |= fnd
                        seek &= ~fnd
                        cont = sk & ~fnd
                        hp = np.where(cont, hp + 1, hp)
                        lam_p = np.where(cont, lam_p * 0.5, lam_p)
                    # no downhill step left: at the numerical optimum
                    converged |= rej & ~found & active
                    pending = rej & found
                    lam = np.where(pending, lam_p, lam)
                    h = np.where(pending, hp, h)
                # the last full evaluation was at every member's kept
                # point unless it rejected some member's candidate
                last_eval_at_kept = not bool(rej.any())
                if converged.all():
                    break
            if last_eval_at_kept and trial_info is not None:
                info = trial_info
            else:
                _, info = run(deltas)
            info = dict(info, chi2=info["chi2_at_input"])
        # host-loop divergence flag (the device loop carries this in the
        # while-loop state): a member whose chi2 is non-finite never
        # converged and must not write NaN back into its model
        div = ~np.isfinite(np.asarray(info["chi2"]))
        info = dict(info, diverged=div)
        self.converged = (converged & ~div)[:self.n_real]
        self.diverged = div[:self.n_real]
        self._write_back(deltas, info)
        return np.asarray(info["chi2"])[:self.n_real]

    def dispatch_fit(self, maxiter: int = 20,
                     min_chi2_decrease: float = 1e-3,
                     max_step_halvings: int = 8):
        """Launch the fused batched fit WITHOUT blocking on the result.

        The throughput scheduler's device stage (pint_tpu.serve): the
        whole damped loop is enqueued as one XLA program and this call
        returns a handle immediately, so the host can pack the next
        batch while the device executes this one. ``handle.finish()``
        performs the fit's single device->host fetch, writes fitted
        parameters back into the (real) models, sets ``self.converged``
        and returns the per-real-member chi2 array — exactly
        ``fit_toas``'s contract, split at the sync point.

        With the device loop disabled (``PINT_TPU_DEVICE_LOOP=0``) the
        host driver cannot be suspended mid-loop, so the fit runs
        synchronously here and the handle is already resolved.
        """
        from pint_tpu import telemetry
        from pint_tpu.fitting import device_loop

        if not device_loop.enabled():
            chi2 = self.fit_toas(maxiter=maxiter,
                                 min_chi2_decrease=min_chi2_decrease,
                                 max_step_halvings=max_step_halvings)
            return _ResolvedBatchFit(self, chi2)

        from pint_tpu.bucketing import toa_shape

        B = len(self.models)
        anchored = self.tzr is not None
        deltas = {k: np.zeros(B) for k in self.free_params}
        base = replicate(self.base, self.mesh)
        mask = replicate(self.param_mask, self.mesh)
        step_raw = self._step_uncounted()
        # halved trials are judged by the residual-only probe — the
        # chi2 doesn't read the design matrix, so the probe takes no
        # mask — and re-checked by the authoritative full step. The
        # operand layout is (base, toas, family-extra tuple, mask
        # [, tzr]) — the extra tuple is empty for WLS, (noise,) for
        # GLS, (noise, dm) for wideband.
        probe_raw = self._probe_step()
        extra = self._family_args()
        if anchored:
            operands = (base, self.toas, extra, mask, self.tzr)

            def run_ops(d, ops):
                return step_raw(ops[0], d, ops[1], *ops[2], ops[3],
                                ops[4])

            def probe_ops(d, ops):
                return probe_raw(ops[0], d, ops[1], *ops[2], ops[4])
        else:
            operands = (base, self.toas, extra, mask)

            def run_ops(d, ops):
                return step_raw(ops[0], d, ops[1], *ops[2], ops[3])

            def probe_ops(d, ops):
                return probe_raw(ops[0], d, ops[1], *ops[2])
        with self.mesh, telemetry.span("fit.batched.dispatch",
                                       n_pulsars=B):
            handle = device_loop.dispatch_damped_batched(
                run_ops, deltas, operands, probe=probe_ops,
                key=("batched", id(step_raw), id(probe_raw)),
                maxiter=maxiter,
                min_chi2_decrease=min_chi2_decrease,
                max_step_halvings=max_step_halvings,
                kind="device_loop_batched",
                fingerprint=(self._union_fp_hash,
                             tuple(self.free_params), anchored,
                             self.family, self.pl_specs,
                             self.basis_bucket),
                shape=toa_shape(self.toas))
        return _InFlightBatchPulsarFit(self, handle)

    def device_bytes(self) -> dict[int, int]:
        """Per-device bytes of the batch's placed tables, by device id
        (pure sharding metadata — the serve layer's per-device
        accounting; see parallel.mesh.per_device_bytes)."""
        from pint_tpu.parallel.mesh import per_device_bytes

        return per_device_bytes((self.toas, self.tzr, self.noise,
                                 self.dm))

    def _write_back(self, deltas, info) -> None:
        """Apply fitted deltas + uncertainties to every REAL (owner)
        model; padded dummy members' rows are discarded.

        Whole (B,) arrays convert to numpy ONCE before the member loop:
        per-element jnp indexing here cost one eager XLA dispatch per
        (member, param) pair — ~900 of them per 64-fit scheduler drain,
        the single largest host cost of the throughput fetch stage."""
        deltas = {k: np.asarray(deltas[k]) for k in self.free_params}
        errors = {k: np.asarray(info["errors"][k])
                  for k in self.free_params}
        # a diverged member's deltas/errors are not trustworthy (NaN or
        # at an arbitrary last-kept point of a poisoned objective):
        # leave its model untouched — the serve layer quarantines it
        div = np.asarray(info.get("diverged",
                                  np.zeros(len(self.models), bool)))
        for i, m in enumerate(self.models[:self.n_real]):
            if div[i]:
                continue
            for k in self.free_params:
                if self.param_mask[k][i] == 0.0:
                    continue
                if k in self._merged_owner:
                    own = self._merged_owner[k].get(i)
                    if own is None:
                        continue  # unreachable: the mask row is 0
                    p = m[own[0]]
                elif k in m.params:
                    p = m[k]
                else:
                    continue
                p.add_delta(float(deltas[k][i]))
                p.uncertainty = float(errors[k][i])


class _ResolvedBatchFit:
    """Already-finished dispatch handle (host-loop fallback path)."""

    __slots__ = ("fitter", "_chi2")

    def __init__(self, fitter, chi2):
        self.fitter = fitter
        self._chi2 = chi2

    def ready(self) -> bool:
        return True

    def finish(self) -> np.ndarray:
        return self._chi2


class _InFlightBatchPulsarFit:
    """A dispatched batched fit: ``finish()`` = fetch + write-back."""

    __slots__ = ("fitter", "_handle", "_chi2")

    def __init__(self, fitter: BatchedPulsarFitter, handle):
        self.fitter = fitter
        self._handle = handle
        self._chi2 = None

    def ready(self) -> bool:
        """Result complete without blocking (work-stealing drain peek)."""
        return self._chi2 is not None or self._handle.ready()

    def finish(self) -> np.ndarray:
        """The fit's one device->host sync; idempotent."""
        if self._chi2 is None:
            f = self.fitter
            d_fit, info, _chi2, converged, _cnt = self._handle.fetch()
            info = dict(info, chi2=info["chi2_at_input"])
            f.converged = np.asarray(converged)[:f.n_real]
            f.diverged = np.asarray(
                info.get("diverged",
                         np.zeros(len(f.models), bool)))[:f.n_real]
            f._write_back(d_fit, info)
            self._chi2 = np.asarray(info["chi2"])[:f.n_real]
        return self._chi2
